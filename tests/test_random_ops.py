"""Statistical tests for the random/sample operator family (reference
src/operator/random/sample_op.cc — tested upstream in test_operator.py's
test_*_generator cases via moment checks). Moments at n=20k with loose
tolerances; determinism via mx.random.seed."""
import numpy as np
import pytest

import mxnet_tpu as mx

N = (200, 100)          # 20k draws


def _draw(name, **params):
    return mx.nd.invoke(name, [], dict(params, shape=N)).asnumpy()


def test_uniform_moments_and_range():
    x = _draw("_random_uniform", low=-2.0, high=3.0)
    assert x.min() >= -2.0 and x.max() < 3.0
    assert abs(x.mean() - 0.5) < 0.1           # (low+high)/2
    assert abs(x.var() - 25 / 12.0) < 0.15     # (high-low)^2/12


def test_normal_moments():
    x = _draw("_random_normal", loc=1.5, scale=2.0)
    assert abs(x.mean() - 1.5) < 0.1
    assert abs(x.std() - 2.0) < 0.1


def test_gaussian_alias_matches_normal_api():
    mx.random.seed(3)
    a = _draw("_random_gaussian", loc=0.0, scale=1.0)
    assert abs(a.mean()) < 0.05


def test_gamma_moments():
    x = _draw("_random_gamma", alpha=3.0, beta=2.0)
    # mxnet convention: mean = alpha*beta, var = alpha*beta^2
    assert abs(x.mean() - 6.0) < 0.3
    assert abs(x.var() - 12.0) < 1.5
    assert x.min() > 0


def test_exponential_moments():
    x = _draw("_random_exponential", lam=2.0)
    assert abs(x.mean() - 0.5) < 0.05          # 1/lam
    assert x.min() >= 0


def test_poisson_moments():
    x = _draw("_random_poisson", lam=4.0)
    assert abs(x.mean() - 4.0) < 0.2
    assert abs(x.var() - 4.0) < 0.5
    np.testing.assert_allclose(x, np.round(x))  # integral support


def test_negative_binomial_moments():
    k, p = 5, 0.4
    x = _draw("_random_negative_binomial", k=k, p=p)
    mean = k * (1 - p) / p
    var = mean / p
    assert abs(x.mean() - mean) < 0.4
    assert abs(x.var() - var) < 2.5
    assert x.min() >= 0


def test_generalized_negative_binomial_moments():
    mu, alpha = 3.0, 0.5
    x = _draw("_random_generalized_negative_binomial", mu=mu, alpha=alpha)
    assert abs(x.mean() - mu) < 0.3
    assert abs(x.var() - (mu + alpha * mu * mu)) < 1.5


def test_randint_bounds_and_coverage():
    x = _draw("_random_randint", low=2, high=7, dtype="int32")
    assert x.min() >= 2 and x.max() < 7
    assert set(np.unique(x)) == {2, 3, 4, 5, 6}


def test_seed_determinism_across_ops():
    mx.random.seed(42)
    a = _draw("_random_normal", loc=0.0, scale=1.0)
    b = _draw("_random_gamma", alpha=2.0, beta=1.0)
    mx.random.seed(42)
    a2 = _draw("_random_normal", loc=0.0, scale=1.0)
    b2 = _draw("_random_gamma", alpha=2.0, beta=1.0)
    np.testing.assert_array_equal(a, a2)
    np.testing.assert_array_equal(b, b2)
    # and a different seed gives a different stream
    mx.random.seed(43)
    a3 = _draw("_random_normal", loc=0.0, scale=1.0)
    assert not np.array_equal(a, a3)


# ---------------------------------------------------------------------------
# _sample_*: per-row distribution parameters
# ---------------------------------------------------------------------------

def test_sample_uniform_per_row_params():
    low = mx.nd.array([0.0, 10.0])
    high = mx.nd.array([1.0, 20.0])
    x = mx.nd.invoke("_sample_uniform", [low, high],
                     {"shape": (5000,)}).asnumpy()
    assert x.shape == (2, 5000)
    assert 0 <= x[0].min() and x[0].max() < 1
    assert 10 <= x[1].min() and x[1].max() < 20


def test_sample_normal_per_row_params():
    mu = mx.nd.array([0.0, 50.0])
    sigma = mx.nd.array([1.0, 5.0])
    x = mx.nd.invoke("_sample_normal", [mu, sigma],
                     {"shape": (8000,)}).asnumpy()
    assert abs(x[0].mean()) < 0.1 and abs(x[0].std() - 1) < 0.1
    assert abs(x[1].mean() - 50) < 0.5 and abs(x[1].std() - 5) < 0.4


def test_sample_gamma_per_row_params():
    alpha = mx.nd.array([2.0, 9.0])
    beta = mx.nd.array([1.0, 0.5])
    x = mx.nd.invoke("_sample_gamma", [alpha, beta],
                     {"shape": (8000,)}).asnumpy()
    assert abs(x[0].mean() - 2.0) < 0.25
    assert abs(x[1].mean() - 4.5) < 0.4


def test_sample_multinomial_frequencies_and_probs():
    p = mx.nd.array([[0.1, 0.6, 0.3]])
    draws = mx.nd.invoke("_sample_multinomial", [p],
                         {"shape": (8000,)}).asnumpy()[0]
    freq = np.bincount(draws.astype("i8"), minlength=3) / draws.size
    np.testing.assert_allclose(freq, [0.1, 0.6, 0.3], atol=0.03)
    out = mx.nd.invoke("_sample_multinomial", [p],
                       {"shape": (10,), "get_prob": True})
    sample, logp = out[0].asnumpy()[0], out[1].asnumpy()[0]
    np.testing.assert_allclose(
        np.exp(logp), np.array([0.1, 0.6, 0.3])[sample.astype("i8")],
        rtol=1e-4)


def test_shuffle_is_permutation():
    x = np.arange(512, dtype="f4")
    y = mx.nd.invoke("_shuffle", [mx.nd.array(x)], {}).asnumpy()
    assert not np.array_equal(y, x)
    np.testing.assert_array_equal(np.sort(y), x)


def test_sample_unique_zipfian_properties():
    out = mx.nd.invoke("_sample_unique_zipfian", [],
                       {"range_max": 1000, "shape": (1, 64)})
    samples, num_tries = out[0].asnumpy(), out[1].asnumpy()
    # rejection sampling needs >= num_sampled draws
    assert num_tries.shape == (1,) and num_tries[0] >= 64
    row = samples[0]
    assert row.shape == (64,)
    assert len(np.unique(row)) == 64            # unique within a row
    assert row.min() >= 0 and row.max() < 1000
    # zipfian skew: small ids must dominate a large-id band of equal width
    lo = (row < 100).sum()
    hi = ((row >= 800) & (row < 900)).sum()
    assert lo > hi


def test_mx_random_module_reexports_samplers():
    """mx.random.* exposes the sampler surface positionally (reference
    random.py:26 star-import of ndarray.random; randn at :155)."""
    mx.random.seed(11)
    u = mx.random.uniform(-1, 1, (500,)).asnumpy()
    assert u.min() >= -1 and u.max() < 1
    n = mx.random.normal(5, 0.5, (2000,)).asnumpy()
    assert abs(n.mean() - 5) < 0.1
    r = mx.random.randn(3, 4)
    assert r.shape == (3, 4)
    s = mx.random.shuffle(mx.nd.array(np.arange(16, dtype="f4"))).asnumpy()
    np.testing.assert_array_equal(np.sort(s), np.arange(16))
    # seed reproducibility through the re-exported surface
    mx.random.seed(7)
    a = mx.random.uniform(0, 1, (8,)).asnumpy()
    mx.random.seed(7)
    np.testing.assert_array_equal(a, mx.random.uniform(0, 1, (8,)).asnumpy())
