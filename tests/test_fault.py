"""Failure-detection tests (reference surface: kvstore.h:353
num_dead_node via ps-lite heartbeats; here parallel/fault.py)."""
import os
import subprocess
import sys
import time

import pytest

from mxnet_tpu.parallel import fault

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(ROOT, "tools", "launch.py")
WORKER = os.path.join(ROOT, "tests", "fault_worker.py")


def test_heartbeat_tracker_unit(tmp_path, monkeypatch):
    d = str(tmp_path)
    monkeypatch.setenv("MXNET_HEARTBEAT_DIR", d)
    assert fault.start(0, interval=0.05)
    try:
        time.sleep(0.2)
        # rank 1: stale heartbeat; rank 2: never wrote one (still in grace)
        p1 = os.path.join(d, "hb_1")
        with open(p1, "w") as f:
            f.write("0 0")
        os.utime(p1, (time.time() - 100, time.time() - 100))
        dead = fault.dead_nodes(3, timeout=5.0)
        assert dead == [1], dead
        # our own heartbeat is fresh
        assert 0 not in fault.dead_nodes(3, timeout=1.0)
    finally:
        fault.stop()


def test_dead_nodes_survive_wall_clock_step(tmp_path, monkeypatch):
    d = str(tmp_path)
    monkeypatch.setenv("MXNET_HEARTBEAT_DIR", d)
    p = os.path.join(d, "hb_0")
    with open(p, "w") as f:
        f.write("0 0")
    try:
        # first sighting: the wall/mtime delta is trusted once — a
        # fresh file is alive
        assert fault.dead_nodes(1, timeout=5.0) == []
        # a 1000s wall-clock step (NTP slew, operator `date`) between
        # polls must NOT mass-kill: liveness is monotonic time since
        # the last OBSERVED change, not wall-vs-mtime
        real = time.time
        monkeypatch.setattr(time, "time", lambda: real() + 1000.0)
        assert fault.dead_nodes(1, timeout=5.0) == []
        # a genuinely unchanged heartbeat still ages out on the
        # monotonic clock (rewind the cached observation stamp)
        fault._obs[(d, 0)][1] -= 6.0
        assert fault.dead_nodes(1, timeout=5.0) == [0]
    finally:
        fault._obs.pop((d, 0), None)


def test_heartbeat_no_dir_is_noop(monkeypatch):
    monkeypatch.delenv("MXNET_HEARTBEAT_DIR", raising=False)
    assert not fault.start(0)
    assert fault.dead_nodes(4, timeout=1.0) == []


def test_dead_node_detected_across_processes():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, LAUNCH, "-n", "3",
         "--env", "MXNET_HEARTBEAT_INTERVAL=0.2",
         sys.executable, WORKER],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-2000:]
    for rank in range(3):
        assert "rank %d/3: fault detection OK" % rank in r.stdout, \
            r.stdout[-4000:]


def test_launcher_reports_dead_workers():
    # --max-restarts 0: this failure is deterministic; retrying it would
    # only slow the test down
    r = subprocess.run(
        [sys.executable, LAUNCH, "-n", "2", "--max-restarts", "0",
         sys.executable, "-c",
         "import sys, os; sys.exit(5 if os.environ['MXNET_WORKER_RANK'] "
         "== '0' else 0)"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 5
    assert "worker(s) [0] died" in r.stderr, r.stderr[-1000:]


def test_launcher_supervised_restart_retries_and_summarizes(tmp_path):
    """A worker that fails on its first incarnation and succeeds on the
    restart: the launcher must retry (rc 0) and emit the structured JSON
    summary naming the dead rank."""
    import json
    marker = tmp_path / "first_attempt_done"
    prog = (
        "import os, sys\n"
        "m = %r\n"
        "if os.environ['MXNET_WORKER_RANK'] == '0' and "
        "not os.path.exists(m):\n"
        "    open(m, 'w').close()\n"
        "    sys.exit(7)\n"
        "assert os.environ.get('MXNET_RESUME_DIR') or "
        "os.environ['MXNET_WORKER_RANK'] != '0'\n"
        "sys.exit(0)\n" % str(marker)
    )
    r = subprocess.run(
        [sys.executable, LAUNCH, "-n", "2", "--max-restarts", "2",
         "--restart-backoff", "0.1", sys.executable, "-c", prog],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-2000:]
    summary_lines = [ln for ln in r.stderr.splitlines()
                     if ln.startswith("launch.py: summary ")]
    assert summary_lines, r.stderr[-2000:]
    summary = json.loads(summary_lines[-1].split("summary ", 1)[1])
    assert summary["rc"] == 0
    assert summary["restarts"] == 1
    assert summary["attempts"][0]["rc"] == 7
    assert summary["attempts"][0]["dead_ranks"] == [0]
    assert summary["attempts"][1]["resumed"] is True


def test_fault_inject_kill_fires_only_on_matching_rank(tmp_path):
    """kill@step with rank filter: rank 0 dies with the injected rc,
    rank 1 is untouched (exits 0 on its own)."""
    prog = (
        "from mxnet_tpu.parallel import faultinject\n"
        "for s in range(5):\n"
        "    faultinject.fire('step', step=s)\n"
        "print('survived rank', __import__('os')"
        ".environ['MXNET_WORKER_RANK'])\n"
    )
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, LAUNCH, "-n", "2", "--max-restarts", "0",
         "--env", "MXNET_FAULT_INJECT=kill@step=3:rank=0:rc=9",
         sys.executable, "-c", prog],
        capture_output=True, text=True, timeout=120, cwd=ROOT, env=env)
    assert r.returncode == 9, r.stdout[-4000:] + r.stderr[-2000:]
    import json
    summary = json.loads(
        [ln for ln in r.stderr.splitlines()
         if ln.startswith("launch.py: summary ")][-1].split("summary ", 1)[1])
    # rank 0 is the root cause; rank 1 may appear too (it aborts when the
    # coordinator it lost was hosted by the killed rank 0)
    assert 0 in summary["attempts"][0]["dead_ranks"], r.stderr[-2000:]
    assert "survived rank 0" not in r.stdout
    # rank 1 either finished (printed) or died on the lost coordinator —
    # both are fine; rank 0 must NOT have survived the injection


def test_fault_inject_kill_dumps_flight_recorder_postmortem(tmp_path):
    """An injected kill must leave a flight-recorder postmortem under
    MXNET_TELEMETRY_DIR — written on the kill path BEFORE the signal,
    so it works even for uncatchable SIGKILL specs."""
    import glob
    import json
    telem = str(tmp_path / "telemetry")
    prog = (
        "from mxnet_tpu import telemetry\n"
        "from mxnet_tpu.parallel import faultinject\n"
        "for s in range(5):\n"
        "    telemetry.publish_window(steps=1, window_s=0.01, examples=4,\n"
        "                             engine_depth=1, global_step=s)\n"
        "    faultinject.fire('step', step=s)\n"
    )
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["MXNET_FAULT_INJECT"] = "kill@step=3:rc=7"
    env["MXNET_TELEMETRY_DIR"] = telem
    env["MXNET_WORKER_RANK"] = "0"
    r = subprocess.run([sys.executable, "-c", prog], capture_output=True,
                       text=True, timeout=120, cwd=ROOT, env=env)
    assert r.returncode == 7, r.stdout[-2000:] + r.stderr[-2000:]
    pm = glob.glob(os.path.join(telem, "postmortem_rank0_pid*.json"))
    assert len(pm) == 1, pm
    with open(pm[0]) as f:
        post = json.load(f)
    assert post["reason"] == "faultinject: kill@step=3:rc=7"
    assert post["rank"] == 0
    # the ring holds the windows published up to the kill; the fault
    # itself is on the event log and the registry snapshot rode along
    assert [s["global_step"] for s in post["steps"]] == [0, 1, 2, 3]
    assert any(ev["kind"] == "fault" for ev in post["events"])
    assert "train/step_time_ms" in post["registry"]


def test_no_telemetry_dir_no_postmortem(tmp_path):
    """Opt-in contract: without MXNET_TELEMETRY_DIR the kill path writes
    nothing anywhere (and still kills)."""
    prog = (
        "from mxnet_tpu.parallel import faultinject\n"
        "for s in range(5):\n"
        "    faultinject.fire('step', step=s)\n"
    )
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("MXNET_TELEMETRY_DIR", None)
    env["MXNET_FAULT_INJECT"] = "kill@step=2:rc=3"
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", prog], capture_output=True,
                       text=True, timeout=120, cwd=str(tmp_path), env=env)
    assert r.returncode == 3
    assert list(tmp_path.iterdir()) == []


@pytest.mark.slow
def test_kill_resume_bitwise_matches_uninterrupted(tmp_path):
    """THE elastic-training acceptance test: an injected kill of rank 0
    mid 2-process dist_sync training is survived by supervised restart,
    and the resumed run's final params match the uninterrupted run's
    BITWISE (same RNG stream, same optimizer/momentum state, same number
    of updates)."""
    import numpy as np
    resume_worker = os.path.join(ROOT, "tests", "fault_resume_worker.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("MXNET_FAULT_INJECT", None)

    def run(dump, extra_args, extra_env):
        e = dict(env)
        e["FAULT_TRAIN_DUMP"] = dump
        return subprocess.run(
            [sys.executable, LAUNCH, "-n", "2", "--restart-backoff",
             "0.2"] + extra_args + [sys.executable, resume_worker],
            capture_output=True, text=True, timeout=600, env=e, cwd=ROOT)

    base_dump = str(tmp_path / "baseline.npz")
    r = run(base_dump, ["--max-restarts", "0"], {})
    assert r.returncode == 0, r.stdout[-6000:] + r.stderr[-3000:]

    kill_dump = str(tmp_path / "killed.npz")
    r = run(kill_dump,
            ["--max-restarts", "3", "--checkpoint-dir",
             str(tmp_path / "ckpt"),
             "--env", "MXNET_FAULT_INJECT=kill@step=3:rank=0"], {})
    assert r.returncode == 0, r.stdout[-6000:] + r.stderr[-3000:]
    # the kill really happened and the group really restarted+resumed
    assert "launch.py: restarting the group" in r.stderr, r.stderr[-3000:]
    assert "resumed from checkpoint step" in r.stdout, r.stdout[-6000:]

    with np.load(base_dump) as base, np.load(kill_dump) as killed:
        assert sorted(base.files) == sorted(killed.files)
        for k in base.files:
            np.testing.assert_array_equal(
                base[k], killed[k],
                err_msg="param %r diverged after kill+resume" % k)
