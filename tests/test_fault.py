"""Failure-detection tests (reference surface: kvstore.h:353
num_dead_node via ps-lite heartbeats; here parallel/fault.py)."""
import os
import subprocess
import sys
import time

import pytest

from mxnet_tpu.parallel import fault

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(ROOT, "tools", "launch.py")
WORKER = os.path.join(ROOT, "tests", "fault_worker.py")


def test_heartbeat_tracker_unit(tmp_path, monkeypatch):
    d = str(tmp_path)
    monkeypatch.setenv("MXNET_HEARTBEAT_DIR", d)
    assert fault.start(0, interval=0.05)
    try:
        time.sleep(0.2)
        # rank 1: stale heartbeat; rank 2: never wrote one (still in grace)
        p1 = os.path.join(d, "hb_1")
        with open(p1, "w") as f:
            f.write("0 0")
        os.utime(p1, (time.time() - 100, time.time() - 100))
        dead = fault.dead_nodes(3, timeout=5.0)
        assert dead == [1], dead
        # our own heartbeat is fresh
        assert 0 not in fault.dead_nodes(3, timeout=1.0)
    finally:
        fault.stop()


def test_heartbeat_no_dir_is_noop(monkeypatch):
    monkeypatch.delenv("MXNET_HEARTBEAT_DIR", raising=False)
    assert not fault.start(0)
    assert fault.dead_nodes(4, timeout=1.0) == []


def test_dead_node_detected_across_processes():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, LAUNCH, "-n", "3",
         "--env", "MXNET_HEARTBEAT_INTERVAL=0.2",
         sys.executable, WORKER],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-2000:]
    for rank in range(3):
        assert "rank %d/3: fault detection OK" % rank in r.stdout, \
            r.stdout[-4000:]


def test_launcher_reports_dead_workers():
    r = subprocess.run(
        [sys.executable, LAUNCH, "-n", "2", sys.executable, "-c",
         "import sys, os; sys.exit(5 if os.environ['MXNET_WORKER_RANK'] "
         "== '0' else 0)"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 5
    assert "worker(s) [0] died" in r.stderr, r.stderr[-1000:]
