"""Reference-format symbol JSON loading (VERDICT r3 #3).

The fixtures below are verbatim reference-MXNet on-disk layouts: attr
values are repr-strings ("(2, 2)", "True", "64"), variables carry dtype
ENUM codes in __dtype__, hidden keys ride as `weight_lr_mult` on the op
node in pre-0.9 files, and the top level has node_row_ptr + mxnet_version
(format written by reference python/mxnet/symbol save; upgraders:
src/nnvm/legacy_json_util.cc:49-155). A real `prefix-symbol.json` +
`prefix-0000.params` pair must load and run inference.
"""
import json

import numpy as np

import mxnet_tpu as mx

REFERENCE_LENET_JSON = json.dumps({
    "nodes": [
        {"op": "null", "name": "data", "inputs": []},
        {"op": "null", "name": "conv1_weight", "inputs": [],
         "attrs": {"__dtype__": "0", "__lr_mult__": "2.0"}},
        {"op": "null", "name": "conv1_bias", "inputs": []},
        {"op": "Convolution", "name": "conv1",
         "attrs": {"kernel": "(3, 3)", "num_filter": "8",
                   "stride": "(1, 1)", "pad": "(1, 1)", "no_bias": "False",
                   "workspace": "1024", "cudnn_tune": "off"},
         "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
        {"op": "Activation", "name": "relu1",
         "attrs": {"act_type": "relu"}, "inputs": [[3, 0, 0]]},
        {"op": "Pooling", "name": "pool1",
         "attrs": {"kernel": "(2, 2)", "pool_type": "max",
                   "stride": "(2, 2)"},
         "inputs": [[4, 0, 0]]},
        {"op": "Flatten", "name": "flat", "inputs": [[5, 0, 0]]},
        {"op": "null", "name": "fc1_weight", "inputs": []},
        {"op": "null", "name": "fc1_bias", "inputs": []},
        {"op": "FullyConnected", "name": "fc1",
         "attrs": {"num_hidden": "10", "no_bias": "False"},
         "inputs": [[6, 0, 0], [7, 0, 0], [8, 0, 0]]},
        {"op": "null", "name": "softmax_label", "inputs": []},
        {"op": "SoftmaxOutput", "name": "softmax",
         "inputs": [[9, 0, 0], [10, 0, 0]]},
    ],
    "arg_nodes": [0, 1, 2, 7, 8, 10],
    "node_row_ptr": list(range(13)),
    "heads": [[11, 0, 0]],
    "attrs": {"mxnet_version": ["int", 10400]},
})


def test_reference_json_loads():
    sym = mx.sym.load_json(REFERENCE_LENET_JSON)
    args = sym.list_arguments()
    assert args == ["data", "conv1_weight", "conv1_bias", "fc1_weight",
                    "fc1_bias", "softmax_label"]
    # repr-string attrs parsed into real types
    conv = [n for n in sym._topo() if n.name == "conv1"][0]
    assert conv.params["kernel"] == (3, 3)
    assert conv.params["no_bias"] is False
    assert conv.params["num_filter"] == 8
    assert "workspace" not in conv.params  # backend knob dropped
    # dtype enum code + lr_mult hidden key land on the variable
    w = [n for n in sym._topo() if n.name == "conv1_weight"][0]
    assert w.attrs["__dtype__"] == "float32"
    assert float(w.attrs["__lr_mult__"]) == 2.0


def test_reference_pair_runs_inference(tmp_path):
    """The point of byte-exact .params: a reference checkpoint PAIR loads
    and predicts."""
    prefix = str(tmp_path / "refmodel")
    with open(prefix + "-symbol.json", "w") as f:
        f.write(REFERENCE_LENET_JSON)
    rng = np.random.RandomState(0)
    shapes = {"conv1_weight": (8, 1, 3, 3), "conv1_bias": (8,),
              "fc1_weight": (10, 8 * 14 * 14), "fc1_bias": (10,)}
    arg_params = {k: mx.nd.array(rng.randn(*v).astype("f4") * 0.1)
                  for k, v in shapes.items()}
    mx.model.save_checkpoint(prefix, 0, mx.sym.load_json(
        REFERENCE_LENET_JSON), arg_params, {})

    sym, args, aux = mx.model.load_checkpoint(prefix, 0)
    mod = mx.mod.Module(sym, context=mx.cpu())
    it_shape = [("data", (2, 1, 28, 28))]
    mod.bind(it_shape, [("softmax_label", (2,))], for_training=False)
    mod.set_params(args, aux)
    x = rng.randn(2, 1, 28, 28).astype("f4")
    from mxnet_tpu.io import DataBatch
    mod.forward(DataBatch(data=[mx.nd.array(x)]), is_train=False)
    out = mod.get_outputs()[0].asnumpy()
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.sum(axis=1), np.ones(2), rtol=1e-5)


def test_legacy_suffixed_hidden_keys_rehome():
    """Pre-0.9 layout: `weight_lr_mult` rides on the op node and must move
    to the weight variable (UpgradeJSON_FixParsing)."""
    j = json.dumps({
        "nodes": [
            {"op": "null", "name": "data", "inputs": []},
            {"op": "null", "name": "fc_weight", "inputs": []},
            {"op": "null", "name": "fc_bias", "inputs": []},
            {"op": "FullyConnected", "name": "fc",
             "param": {"num_hidden": "4", "weight_lr_mult": "3.0",
                       "lr_mult": "0.5"},
             "inputs": [[0, 0], [1, 0], [2, 0]]},
        ],
        "arg_nodes": [0, 1, 2],
        "heads": [[3, 0]],
    })
    sym = mx.sym.load_json(j)
    nodes = {n.name: n for n in sym._topo()}
    assert float(nodes["fc_weight"].attrs["__lr_mult__"]) == 3.0
    assert float(nodes["fc"].attrs["__lr_mult__"]) == 0.5
    assert nodes["fc"].params == {"num_hidden": 4}


def test_own_roundtrip_is_reference_format(tmp_path):
    """tojson now EMITS the reference layout (repr-strings, node_row_ptr,
    mxnet_version) and still round-trips."""
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, name="c")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    j = json.loads(net.tojson())
    assert j["attrs"]["mxnet_version"] == ["int", 10400]
    assert "node_row_ptr" in j
    conv = [n for n in j["nodes"] if n["name"] == "c"][0]
    assert conv["attrs"]["kernel"] == "(3, 3)"      # repr-string, not json
    sym2 = mx.sym.load_json(net.tojson())
    c2 = [n for n in sym2._topo() if n.name == "c"][0]
    assert c2.params["kernel"] == (3, 3)
    assert c2.params["num_filter"] == 4


def test_variadic_num_args_attr_accepted():
    """Reference JSON stores num_args on every variadic op (Concat etc.);
    the count is implied by the inputs list here and must not reject."""
    j = json.dumps({
        "nodes": [
            {"op": "null", "name": "a", "inputs": []},
            {"op": "null", "name": "b", "inputs": []},
            {"op": "Concat", "name": "cat",
             "attrs": {"num_args": "2", "dim": "1"},
             "inputs": [[0, 0, 0], [1, 0, 0]]},
        ],
        "arg_nodes": [0, 1], "heads": [[2, 0, 0]],
    })
    sym = mx.sym.load_json(j)
    cat = [n for n in sym._topo() if n.name == "cat"][0]
    assert cat.params == {"dim": 1}


def test_unknown_semantic_param_raises():
    j = json.dumps({
        "nodes": [
            {"op": "null", "name": "x", "inputs": []},
            {"op": "Activation", "name": "a",
             "attrs": {"act_type": "relu", "not_a_real_param": "7"},
             "inputs": [[0, 0, 0]]},
        ],
        "arg_nodes": [0], "heads": [[1, 0, 0]],
    })
    import pytest
    with pytest.raises(mx.base.MXNetError):
        mx.sym.load_json(j)
