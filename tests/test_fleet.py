"""Fleet tier (mxnet_tpu.fleet): router, registry, supervisor backoff,
metrics federation — chip-free.

The acceptance properties: (1) a router over CPU replica subprocesses
spreads predict traffic least-loaded, honors blue/green splits, and
auto-rolls-back a canary on an over-budget accuracy delta with zero
dropped in-flight requests; (2) a decode session whose owner replica is
killed mid-hop is resumed on a survivor via its cursor and the stitched
token tail is BITWISE identical to an uninterrupted single-replica run;
(3) the federated /metrics exposition round-trips through the strict
``prom.parse_exposition`` with per-replica labels.
"""
import glob
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

import mxnet_tpu as mx
import numpy as np
from mxnet_tpu.base import MXNetError
from mxnet_tpu.fleet import (NoReplica, ReplicaRegistry, Router,
                             backoff_delay, route_http)
from mxnet_tpu.serve import decode_model as dm
from mxnet_tpu import serving
from mxnet_tpu.telemetry import federate, prom

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GEN_SPEC = dm.DecoderSpec(vocab=61, dim=32, num_heads=4, num_layers=2,
                          max_prompt_len=8, page_size=4,
                          max_pages_per_slot=8, max_slots=4, num_pages=33)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _get(url, timeout=10.0, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _get_json(url, timeout=10.0):
    code, body = _get(url, timeout=timeout)
    return code, json.loads(body or "{}")


def _post(url, payload, timeout=60.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}")


def _register(registry, rid, *, model="m", version="0", mode="predict",
              ready=True, load=None, spec=None, static=False):
    return registry.register({
        "id": rid, "url": "http://%s.invalid" % rid, "model": model,
        "version": version, "mode": mode, "ready": ready,
        "load": load or {}, "spec": spec, "static": static})


# ---------------------------------------------------------------------------
# backoff_delay: the one restart schedule (launcher + supervisor)
# ---------------------------------------------------------------------------

class _FixedRng:
    def __init__(self, frac):
        self.frac = frac

    def uniform(self, lo, hi):
        return lo + (hi - lo) * self.frac


def test_backoff_delay_exponential_cap_and_jitter():
    assert backoff_delay(0, base=0.5, cap=30.0, jitter=0.0) == 0.5
    assert backoff_delay(3, base=0.5, cap=30.0, jitter=0.0) == 4.0
    # capped: 2**10 * 1.0 >> 30
    assert backoff_delay(10, base=1.0, cap=30.0, jitter=0.0) == 30.0
    # jitter spans [1-j, 1+j] around the raw delay
    lo = backoff_delay(2, base=1.0, cap=30.0, jitter=0.5, rng=_FixedRng(0.0))
    hi = backoff_delay(2, base=1.0, cap=30.0, jitter=0.5, rng=_FixedRng(1.0))
    assert lo == pytest.approx(2.0)
    assert hi == pytest.approx(6.0)
    for _ in range(20):
        d = backoff_delay(2, base=1.0, cap=30.0, jitter=0.5)
        assert 2.0 <= d <= 6.0


def test_launcher_shares_supervisor_backoff():
    # tools/launch.py loads backoff_delay from fleet/supervisor.py by
    # file path (no package import); same schedule, not a private copy
    import tools.launch as launch
    assert (launch._backoff_delay(4, base=0.25, cap=30.0, jitter=0.0)
            == backoff_delay(4, base=0.25, cap=30.0, jitter=0.0))


# ---------------------------------------------------------------------------
# registry: heartbeat liveness, sweep, static seeds, draining
# ---------------------------------------------------------------------------

def test_registry_sweep_marks_stale_dead_and_heartbeat_revives():
    reg = ReplicaRegistry(heartbeat_timeout_s=0.2)
    _register(reg, "a")
    assert reg.is_routable("a")
    time.sleep(0.3)
    assert reg.sweep() == ["a"]
    rep = reg.get("a")
    assert rep.dead and not rep.ready
    assert "no heartbeat" in rep.dead_reason
    # a heartbeat from the "dead" is a liveness correction
    assert reg.heartbeat("a", ready=True) is True
    assert not reg.get("a").dead
    assert reg.is_routable("a")
    # unknown id: announcer re-registers on False
    assert reg.heartbeat("ghost") is False


def test_registry_static_seed_exempt_from_sweep():
    reg = ReplicaRegistry(heartbeat_timeout_s=0.1)
    _register(reg, "s", static=True)
    time.sleep(0.25)
    assert reg.sweep() == []
    assert reg.is_routable("s")
    # but a proxy failure still kills it
    reg.mark_dead("s", "proxy failed")
    assert not reg.is_routable("s")


def test_registry_draining_and_reregistration_reset():
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    _register(reg, "a")
    reg.set_draining("a")
    assert not reg.is_routable("a")
    assert reg.snapshot()["counts"]["draining"] == 1
    reg.mark_dead("a", "boom")
    # supervised restart reuses the id: registration resets death state
    _register(reg, "a")
    rep = reg.get("a")
    assert not rep.dead and not rep.draining and rep.ready
    assert reg.is_routable("a")


def test_registry_routable_filters_and_score():
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    _register(reg, "a", load={"load_s": 0.5, "unit_s": 0.1})
    _register(reg, "b", version="1", mode="generate")
    _register(reg, "c", ready=False)
    assert {r.id for r in reg.routable()} == {"a", "b"}
    assert [r.id for r in reg.routable(mode="generate")] == ["b"]
    assert [r.id for r in reg.routable(version="1")] == ["b"]
    rep = reg.get("a")
    reg.note_inflight("a", +1)
    reg.note_inflight("a", +1)
    assert rep.score() == pytest.approx(0.5 + 2 * 0.1)
    assert rep.served == 2
    reg.note_inflight("a", -1)
    assert rep.inflight == 1 and rep.served == 2


# ---------------------------------------------------------------------------
# router core (stubbed transport): least-loaded, retry, hops, migration
# ---------------------------------------------------------------------------

def test_route_predict_least_loaded_then_retries_on_death(monkeypatch):
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    router = Router(registry=reg, retry_limit=2)
    _register(reg, "a", load={"load_s": 0.0, "unit_s": 0.01})
    _register(reg, "b", load={"load_s": 1.0, "unit_s": 0.01})
    calls = []

    def fake_call(url, payload, timeout_s):
        calls.append(url)
        if "//a" in url:
            raise ConnectionError("injected death")
        return 200, {"outputs": [[1.0]]}, {}

    monkeypatch.setattr(router, "_call", fake_call)
    code, out, _ = router.route_predict({"inputs": {"data": [[0.0]]}})
    assert code == 200
    assert out["replica"] == "b" and out["version"] == "0"
    # least-loaded went to a first, then the retry excluded the corpse
    assert ["//a" in u for u in calls] == [True, False]
    assert reg.get("a").dead
    assert "proxy failed" in reg.get("a").dead_reason


def test_route_predict_no_replica_is_503():
    router = Router(registry=ReplicaRegistry(heartbeat_timeout_s=60.0))
    code, out, _ = router.route_predict({"inputs": {"data": [[0.0]]}})
    assert code == 503
    assert "no ready" in out["error"]


def test_route_generate_hop_chunking_caps_at_prefill_window(monkeypatch):
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    router = Router(registry=reg, hop_tokens=4)
    _register(reg, "g", mode="generate",
              spec={"vocab": 61, "max_prompt_len": 8, "max_context": 32})
    bodies = []

    def fake_call(url, payload, timeout_s):
        bodies.append(payload)
        n = payload["max_new_tokens"]
        base = len(payload["prompt"])
        return 200, {"tokens": list(range(base, base + n)),
                     "finish_reason": "length", "ttft_ms": 1.0}, {}

    monkeypatch.setattr(router, "_call", fake_call)
    code, out, _ = router.route_generate(
        {"prompt": [5, 9, 13], "max_new_tokens": 17})
    assert code == 200
    # hop 1 forwards 4 tokens (3+4 <= max_prompt_len=8); after it the
    # resume prompt is 7 tokens, so another 4-token hop would leave an
    # inadmissible 11-token resume point — the rest goes in ONE
    # unsplittable final hop
    assert [b["max_new_tokens"] for b in bodies] == [4, 13]
    assert [len(b["prompt"]) for b in bodies] == [3, 7]
    assert len(out["tokens"]) == 17
    assert out["hops"] == 2 and out["migrations"] == 0
    assert out["replicas"] == ["g"]


def test_route_generate_hop_cap_lifted_for_chunked_prefill(monkeypatch):
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    router = Router(registry=reg, hop_tokens=4)
    _register(reg, "g", mode="generate",
              spec={"vocab": 61, "max_prompt_len": 8, "max_context": 32,
                    "chunked_prefill": True})
    bodies = []

    def fake_call(url, payload, timeout_s):
        bodies.append(payload)
        n = payload["max_new_tokens"]
        base = len(payload["prompt"])
        return 200, {"tokens": list(range(base, base + n)),
                     "finish_reason": "length", "ttft_ms": 1.0}, {}

    monkeypatch.setattr(router, "_call", fake_call)
    code, out, _ = router.route_generate(
        {"prompt": [5, 9, 13], "max_new_tokens": 17})
    assert code == 200
    # the replica streams long resume prompts through chunked prefill,
    # so the unsplittable-final-hop fallback never triggers: pure
    # 4/4/4/4/1 chunking with resume prompts growing past max_prompt_len
    assert [b["max_new_tokens"] for b in bodies] == [4, 4, 4, 4, 1]
    assert [len(b["prompt"]) for b in bodies] == [3, 7, 11, 15, 19]
    assert len(out["tokens"]) == 17
    assert out["hops"] == 5


def test_route_generate_400_when_budget_exceeds_max_context():
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    router = Router(registry=reg, hop_tokens=4)
    _register(reg, "g", mode="generate",
              spec={"vocab": 61, "max_prompt_len": 8, "max_context": 32,
                    "chunked_prefill": True})
    code, out, _ = router.route_generate(
        {"prompt": list(range(2, 22)), "max_new_tokens": 20})
    assert code == 400
    assert "max_context" in out["error"]


def test_route_generate_aggregates_speculation_fields(monkeypatch):
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    router = Router(registry=reg, hop_tokens=4)
    _register(reg, "g", mode="generate",
              spec={"vocab": 61, "max_prompt_len": 8, "max_context": 32,
                    "chunked_prefill": True, "speculative": True})
    rates = iter([(3.0, 0.9), (2.0, 0.5), (1.0, 0.1)])

    def fake_call(url, payload, timeout_s):
        n = payload["max_new_tokens"]
        base = len(payload["prompt"])
        atps, rate = next(rates)
        return 200, {"tokens": list(range(base, base + n)),
                     "finish_reason": "length", "ttft_ms": 1.0,
                     "accepted_tokens_per_step": atps,
                     "draft_acceptance_rate": rate}, {}

    monkeypatch.setattr(router, "_call", fake_call)
    code, out, _ = router.route_generate(
        {"prompt": [1, 2], "max_new_tokens": 10})
    assert code == 200 and out["hops"] == 3
    # token-weighted across 4/4/2-token hops
    assert out["accepted_tokens_per_step"] == round(
        (3.0 * 4 + 2.0 * 4 + 1.0 * 2) / 10, 4)
    assert out["draft_acceptance_rate"] == round(
        (0.9 * 4 + 0.5 * 4 + 0.1 * 2) / 10, 4)


def test_route_generate_migrates_on_owner_death(monkeypatch):
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    router = Router(registry=reg, hop_tokens=4)
    _register(reg, "a", mode="generate",
              load={"load_s": 0.0, "unit_s": 0.0})
    _register(reg, "b", mode="generate",
              load={"load_s": 9.0, "unit_s": 0.0})

    def fake_call(url, payload, timeout_s):
        if "//a" in url and len(payload["prompt"]) > 3:
            raise ConnectionError("injected mid-session death")
        n = payload["max_new_tokens"]
        base = len(payload["prompt"])
        return 200, {"tokens": list(range(base, base + n)),
                     "finish_reason": "length", "ttft_ms": 1.0}, {}

    monkeypatch.setattr(router, "_call", fake_call)
    code, out, _ = router.route_generate(
        {"prompt": [1, 2, 3], "max_new_tokens": 10})
    assert code == 200
    # no spec registered -> no prefill cap -> pure 4/4/2 chunking; the
    # owner dies before hop 2 and the session moves to the survivor
    assert len(out["tokens"]) == 10
    assert out["hops"] == 3
    assert out["migrations"] == 1
    assert out["replicas"] == ["a", "b"]
    assert reg.get("a").dead
    # the fake regenerates deterministically from the resume prompt, so
    # the stitched stream equals what "b" alone would have produced
    assert out["tokens"] == list(range(3, 13))


def test_route_generate_banks_eviction_cursor(monkeypatch):
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    router = Router(registry=reg, hop_tokens=0)
    _register(reg, "g", mode="generate")
    state = {"evicted": False}

    def fake_call(url, payload, timeout_s):
        base = len(payload["prompt"])
        if not state["evicted"]:
            state["evicted"] = True
            got = [base, base + 1]
            return 429, {"tokens": got, "retry_after_s": 0.0,
                         "cursor": {"prompt": payload["prompt"],
                                    "generated": got,
                                    "resume_prompt":
                                        payload["prompt"] + got,
                                    "remaining_tokens": 4}}, {}
        n = payload["max_new_tokens"]
        return 200, {"tokens": list(range(base, base + n)),
                     "finish_reason": "length", "ttft_ms": 1.0}, {}

    monkeypatch.setattr(router, "_call", fake_call)
    code, out, _ = router.route_generate(
        {"prompt": [1, 2], "max_new_tokens": 6})
    assert code == 200
    assert len(out["tokens"]) == 6
    assert out["tokens"][:2] == [2, 3]          # banked eviction partial
    assert out["tokens"][2:] == [4, 5, 6, 7]    # resumed from the cursor
    assert out["migrations"] == 0               # same replica resumed it


def test_route_generate_no_replica_returns_resumable_partial():
    router = Router(registry=ReplicaRegistry(heartbeat_timeout_s=60.0))
    code, out, headers = router.route_generate(
        {"prompt": [1, 2, 3], "max_new_tokens": 5})
    assert code == 429
    # the partial carries a PR-9-shaped cursor so the client can resubmit
    assert out["cursor"]["resume_prompt"] == [1, 2, 3]
    assert out["cursor"]["remaining_tokens"] == 5
    assert "Retry-After" in headers


# ---------------------------------------------------------------------------
# blue/green splits + canary auto-rollback
# ---------------------------------------------------------------------------

def test_split_pins_version_and_promote_flips(monkeypatch):
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    router = Router(registry=reg)
    _register(reg, "r1", version="v1")
    _register(reg, "r2", version="v2")
    hit = []
    monkeypatch.setattr(
        router, "_call",
        lambda url, payload, t: (hit.append(url) or
                                 (200, {"outputs": []}, {})))
    router.set_split("m", {"v2": 1.0})
    for _ in range(5):
        code, out, _ = router.route_predict({"inputs": {"data": [[0.0]]}})
        assert code == 200 and out["version"] == "v2"
    assert all("//r2" in u for u in hit)
    out = router.promote("m", "v1")
    assert out["split"] == {"v1": 1.0}
    hit.clear()
    code, out, _ = router.route_predict({"inputs": {"data": [[0.0]]}})
    assert out["version"] == "v1"
    with pytest.raises(MXNetError):
        router.set_split("m", {"v1": -0.5})
    with pytest.raises(MXNetError):
        router.set_split("m", {"v1": 0.0})


def test_canary_rollback_on_over_budget_delta_drains_canary():
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    router = Router(registry=reg)
    _register(reg, "blue", version="f32")
    _register(reg, "cn", version="int8")
    router.set_split("m", {"f32": 1.0})
    c = router.start_canary("m", "int8", split=0.25, budget=0.01)
    assert c["state"] == "active" and c["baseline"] == {"f32": 1.0}
    assert router.splits["m"] == pytest.approx(
        {"f32": 0.75, "int8": 0.25})
    # within budget: nothing happens
    out = router.report_canary("m", 0.004)
    assert out == {"state": "active", "action": "none",
                   "delta": 0.004, "budget": 0.01}
    # the PR-10 accuracy-probe delta blows the budget: auto-rollback
    out = router.report_canary("m", 0.05)
    assert out["state"] == "rolled_back" and out["action"] == "rollback"
    assert out["drained_replicas"] == ["cn"]
    assert router.splits["m"] == {"f32": 1.0}
    assert reg.get("cn").draining          # in-flight finish; no new traffic
    assert not reg.get("blue").draining
    snap = router.fleet_snapshot()
    assert snap["canaries"]["m"]["state"] == "rolled_back"
    assert "exceeds budget" in snap["canaries"]["m"]["reason"]
    # a dead canary can't take more reports
    with pytest.raises(MXNetError):
        router.report_canary("m", 0.0)


def test_canary_requires_baseline_and_sane_split():
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    router = Router(registry=reg)
    with pytest.raises(MXNetError):
        router.start_canary("m", "int8", split=1.5)
    with pytest.raises(MXNetError):
        # no other version registered to canary against
        router.start_canary("m", "int8", split=0.1)


def test_canary_journals_first_outside_the_routing_lock(monkeypatch):
    """WAL discipline on both canary paths: the (fsyncing) journal
    append runs with the routing lock RELEASED and before any split or
    canary state mutates, and every control append is required=True."""
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    router = Router(registry=reg)
    _register(reg, "blue", version="f32")
    _register(reg, "cn", version="int8")
    router.set_split("m", {"f32": 1.0})
    seen = []
    orig = router._journal_append

    def spy(kind, data, sync=False, required=False):
        seen.append({"kind": kind, "required": required,
                     "locked": router._lock.locked(),
                     "split": dict(router.splits.get("m") or {}),
                     "canary": (router.canaries.get("m") or {}).get(
                         "state")})
        return orig(kind, data, sync=sync, required=required)

    monkeypatch.setattr(router, "_journal_append", spy)
    router.start_canary("m", "int8", split=0.25, budget=0.01)
    start = [s for s in seen if s["kind"] in ("split", "canary")]
    assert len(start) == 2
    for s in start:
        assert s["required"] and not s["locked"]
        # journal-first: live state untouched at append time
        assert s["split"] == {"f32": 1.0} and s["canary"] is None

    seen.clear()
    out = router.report_canary("m", 0.05)     # over budget: rollback
    assert out["state"] == "rolled_back"
    rb = [s for s in seen if s["kind"] in ("split", "canary")]
    assert len(rb) == 2
    for s in rb:
        assert s["required"] and not s["locked"]
        assert s["split"] == pytest.approx({"f32": 0.75, "int8": 0.25})
        assert s["canary"] == "active"
    assert router.splits["m"] == {"f32": 1.0}


def test_epoch_fence_rejects_stale_control_writes():
    """A control POST naming a stale fleet_epoch gets a 409 (with the
    current epoch in the body); the matching epoch and fence-less
    legacy payloads go through; data-plane-free GETs are unaffected."""
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    router = Router(registry=reg, epoch=3)
    front = route_http(router, "127.0.0.1", 0)
    url = front.address
    try:
        code, out = _post(url + "/fleet/register",
                          {"id": "a", "url": "http://a.invalid",
                           "model": "m", "version": "0",
                           "mode": "predict", "ready": True,
                           "fleet_epoch": 2})
        assert code == 409 and out["epoch"] == 3
        assert "stale" in out["error"]
        code, out = _get_json(url + "/readyz")
        assert code == 503                    # the stale write never landed
        code, out = _post(url + "/fleet/register",
                          {"id": "a", "url": "http://a.invalid",
                           "model": "m", "version": "0",
                           "mode": "predict", "ready": True,
                           "fleet_epoch": 3})
        assert code == 200 and out["registered"] == "a"
        code, out = _post(url + "/admin/split",
                          {"model": "m", "weights": {"0": 1.0},
                           "fleet_epoch": 1})
        assert code == 409 and out["epoch"] == 3
        # pre-fence client (no field): accepted, backward compatible
        code, out = _post(url + "/admin/split",
                          {"model": "m", "weights": {"0": 1.0}})
        assert code == 200 and out["split"] == {"0": 1.0}
    finally:
        front.stop()


def test_supervisor_snapshots_children_under_lock():
    """kill/stop/alive_count/statuses must touch _children only under
    the supervisor lock: the background poller mutates the dict while
    restarting children, and iterating it mid-mutation raises."""
    from mxnet_tpu.fleet import ReplicaSpec, ReplicaSupervisor
    sup = ReplicaSupervisor(backoff_base=0.1)
    sup.add(ReplicaSpec("a", ["true"]), start=False)

    class Guarded(dict):
        def __getitem__(self, k):
            assert sup._lock.locked(), "unlocked _children[...] access"
            return dict.__getitem__(self, k)

        def values(self):
            assert sup._lock.locked(), "unlocked _children.values()"
            return dict.values(self)

        def items(self):
            assert sup._lock.locked(), "unlocked _children.items()"
            return dict.items(self)

    sup._children = Guarded(sup._children)
    assert sup.kill("a") is None          # never spawned: no pid
    sup.stop("a")
    assert sup.alive_count() == 0
    assert sup.statuses()["a"]["state"] == "stopped"
    sup.stop()


def test_split_is_intent_fallback_only_when_nothing_else_ready():
    # a rolled-back canary (weight 0 via absence) must not come back
    # just because the preferred version died — unless NOTHING else is
    # ready (availability beats policy)
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    router = Router(registry=reg)
    _register(reg, "r1", version="v1")
    _register(reg, "r2", version="v2")
    router.set_split("m", {"v1": 1.0})
    reg.mark_dead("r1", "boom")
    rep = router._pick(model="m", mode="predict")
    assert rep.id == "r2"


# ---------------------------------------------------------------------------
# metrics federation
# ---------------------------------------------------------------------------

_EXPO_A = (
    "# HELP serve_requests Requests.\n"
    "# TYPE serve_requests counter\n"
    'serve_requests{outcome="ok"} 3\n'
    "# TYPE serve_latency_ms histogram\n"
    'serve_latency_ms_bucket{le="1"} 1\n'
    'serve_latency_ms_bucket{le="+Inf"} 2\n'
    "serve_latency_ms_sum 3.5\n"
    "serve_latency_ms_count 2\n")

_EXPO_B = (
    "# TYPE serve_requests counter\n"
    "serve_requests 5\n")


def test_federate_merge_round_trips_through_strict_parse():
    text, skipped = federate.merge_expositions(
        [("r1", _EXPO_A), ("r2", _EXPO_B),
         ("sick", "not { a valid exposition\n")])
    # a sick replica is skipped whole, never merged half-way
    assert [sid for sid, _ in skipped] == ["sick"]
    parsed = prom.parse_exposition(text)
    req = parsed["serve_requests"]
    assert req["type"] == "counter"
    assert {lab["replica"] for lab, _ in req["samples"]} == {"r1", "r2"}
    # r1's own label survived next to the injected replica label
    assert ({"replica": "r1", "outcome": "ok"}, 3.0) in req["samples"]
    assert ({"replica": "r2"}, 5.0) in req["samples"]
    # histogram children grouped under the parent family, labels intact
    hist = parsed["serve_latency_ms"]
    assert hist["type"] == "histogram"
    assert ({"replica": "r1", "le": "+Inf"}, 2.0) in hist["samples"]
    # one TYPE line per family after the merge
    assert text.count("# TYPE serve_requests counter") == 1


def test_federate_escapes_label_values():
    text, skipped = federate.merge_expositions(
        [('r"1\\x', "# TYPE c counter\nc 1\n")])
    assert not skipped
    parsed = prom.parse_exposition(text)
    assert parsed["c"]["samples"] == [({"replica": 'r"1\\x'}, 1.0)]


# ---------------------------------------------------------------------------
# fault injection plumbing the fleet drill leans on
# ---------------------------------------------------------------------------

def test_faultinject_skip_counts_matching_events(monkeypatch):
    from mxnet_tpu.parallel import faultinject
    monkeypatch.setenv("MXNET_FAULT_INJECT",
                       "raise@call=fleet_unit:skip=2")
    faultinject.reset()
    try:
        faultinject.fire("call", op="fleet_unit")    # skip 2 -> 1
        faultinject.fire("call", op="other")         # no match: untouched
        faultinject.fire("call", op="fleet_unit")    # skip 1 -> 0
        with pytest.raises(faultinject.InjectedFault):
            faultinject.fire("call", op="fleet_unit")
    finally:
        faultinject.reset()


# ---------------------------------------------------------------------------
# router HTTP surface (no replicas needed)
# ---------------------------------------------------------------------------

def test_router_http_probes_and_admin():
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    router = Router(registry=reg)
    front = route_http(router, "127.0.0.1", 0)
    url = front.address
    try:
        code, out = _get_json(url + "/livez")
        assert code == 200 and out == {"alive": True}
        code, out = _get_json(url + "/readyz")
        assert code == 503 and out["ready"] is False
        code, out = _get_json(url + "/healthz")
        assert code == 503 and out["status"] == "no_ready_replicas"
        code, out = _post(url + "/fleet/register",
                          {"id": "a", "url": "http://a.invalid",
                           "model": "m", "version": "0",
                           "mode": "predict", "ready": True})
        assert code == 200 and out == {"registered": "a"}
        code, out = _get_json(url + "/readyz")
        assert code == 200 and out["ready"] is True
        code, out = _post(url + "/fleet/heartbeat",
                          {"id": "a", "ready": True,
                           "load": {"load_s": 0.25, "unit_s": 0.1}})
        assert code == 200 and out == {"known": True}
        code, out = _post(url + "/fleet/heartbeat", {"id": "nope"})
        assert code == 200 and out == {"known": False}
        code, out = _get_json(url + "/fleet")
        assert code == 200
        assert out["counts"] == {"total": 1, "ready": 1, "dead": 0,
                                 "draining": 0}
        assert out["replicas"][0]["load"] == {"load_s": 0.25,
                                              "unit_s": 0.1}
        code, out = _post(url + "/admin/split",
                          {"model": "m", "weights": {"0": 3.0}})
        assert code == 200 and out["split"] == {"0": 1.0}
        code, out = _post(url + "/admin/split",
                          {"model": "m", "weights": {"0": -1.0}})
        assert code == 400
        code, out = _post(url + "/admin/drain", {"id": "a"})
        assert code == 200 and out["draining"] is True
        code, out = _get_json(url + "/readyz")
        assert code == 503
        code, out = _post(url + "/fleet/deregister", {"id": "a"})
        assert code == 200
        code, out = _get_json(url + "/fleet")
        assert out["counts"]["total"] == 0
        code, _ = _get_json(url + "/no/such")
        assert code == 404
    finally:
        front.stop()


# ---------------------------------------------------------------------------
# tier-1 fleet smoke: router + 2 CPU replica subprocesses
# ---------------------------------------------------------------------------

def _replica_env(**extra):
    env = os.environ.copy()
    # replicas are plain single-device CPU processes: drop the test
    # harness's 8-virtual-device XLA_FLAGS and any inherited injection
    for k in ("XLA_FLAGS", "MXNET_FAULT_INJECT", "MXNET_TELEMETRY_DIR"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["MXNET_FLEET_HEARTBEAT_S"] = "0.2"
    env.update(extra)
    return env


def _spawn_replica(tmp_path, art_path, router_url, rid, version,
                   extra_args=(), extra_env=None):
    argv = [sys.executable, os.path.join(ROOT, "tools", "serve.py"),
            "--artifact", art_path, "--port", "0",
            "--register", router_url, "--replica-id", rid,
            "--model-name", "m", "--model-version", version]
    argv += list(extra_args)
    log = open(os.path.join(str(tmp_path), "%s.log" % rid), "w")
    proc = subprocess.Popen(argv, cwd=ROOT,
                            env=_replica_env(**(extra_env or {})),
                            stdout=log, stderr=subprocess.STDOUT)
    proc._mx_log = log
    return proc


def _stop_all(front, procs):
    front.stop()
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=20)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait(timeout=10)
        p._mx_log.close()


def _wait_routable(registry, want, tmp_path, timeout_s=240.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if len(registry.routable()) >= want:
            return
        time.sleep(0.1)
    logs = {os.path.basename(p): open(p).read()[-2000:]
            for p in glob.glob(os.path.join(str(tmp_path), "*.log"))}
    raise AssertionError("replicas never became routable: %r\nlogs: %r"
                         % (registry.snapshot(), logs))


@pytest.fixture(scope="module")
def predict_art(tmp_path_factory):
    """A tiny dynamic-batch FC artifact for predict replicas."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(7)
    shapes, _, _ = net.infer_shape(data=(2, 6))
    args = {n: mx.nd.array(rng.uniform(-0.3, 0.3, s).astype("f4"))
            for n, s in zip(net.list_arguments(), shapes)
            if n not in ("data", "softmax_label")}
    path = str(tmp_path_factory.mktemp("fleet_art") / "m.mxtpu")
    meta = mx.serving.export_compiled(net, args, {}, {"data": (None, 6)},
                                      path)
    assert meta["dynamic_batch"] is True
    return path


@pytest.fixture(scope="module")
def gen_art(tmp_path_factory):
    params = dm.init_params(GEN_SPEC, seed=0)
    path = str(tmp_path_factory.mktemp("fleet_gen") / "m.gen.mxtpu")
    meta = serving.export_generate(params, GEN_SPEC, path)
    assert meta["format_version"] == 3
    return {"path": path, "params": params}


def test_fleet_smoke_router_two_replicas(predict_art, tmp_path):
    registry = ReplicaRegistry(heartbeat_timeout_s=3.0)
    router = Router(registry=registry)
    front = route_http(router, "127.0.0.1", 0)
    url = front.address
    procs = []
    try:
        procs.append(_spawn_replica(tmp_path, predict_art, url, "r1", "v1",
                                    extra_args=("--buckets", "1,4")))
        procs.append(_spawn_replica(tmp_path, predict_art, url, "r2", "v2",
                                    extra_args=("--buckets", "1,4")))
        _wait_routable(registry, 2, tmp_path)

        # the replica side of satellite (a): split probes live alongside
        # the legacy combined /healthz
        rep_url = registry.get("r1").url
        code, out = _get_json(rep_url + "/livez")
        assert code == 200 and out == {"alive": True}
        code, out = _get_json(rep_url + "/readyz")
        assert code == 200 and out["ready"] is True
        code, out = _get_json(rep_url + "/healthz")
        assert code == 200
        assert out["status"] == "ok" and out["ready"] is True
        code, out = _get_json(rep_url + "/info")
        assert out["model"] == "m" and out["version"] == "v1"
        assert out["identity"]

        # least-loaded routing spreads a cold fleet over both replicas
        from tools.serve_loadgen import measure
        res = measure(url, concurrency=4, requests=24, shape=(1, 6),
                      retries=2)
        assert res["completed"] == 24
        assert set(res["per_replica"]) == {"r1", "r2"}

        # federated /metrics parses strictly, with per-replica labels
        code, text = _get(url + "/metrics?format=prometheus",
                          headers={"Accept": "text/plain"})
        assert code == 200
        parsed = prom.parse_exposition(text)
        labels = {lab.get("replica")
                  for fam in parsed.values()
                  for lab, _ in fam["samples"]}
        assert {"router", "r1", "r2"} <= labels
        assert "mxtpu_fleet_requests_total" in parsed

        # blue/green: pin v2, then canary v1 and roll it back
        code, out = _post(url + "/admin/split",
                          {"model": "m", "weights": {"v2": 1.0}})
        assert code == 200
        for _ in range(4):
            code, out = _post(url + "/v1/predict",
                              {"inputs": {"data": [[0.0] * 6]}})
            assert code == 200 and out["version"] == "v2"

        code, out = _post(url + "/admin/canary",
                          {"model": "m", "version": "v1",
                           "split": 0.5, "budget": 0.01})
        assert code == 200 and out["state"] == "active"

        # keep load running THROUGH the rollback: zero dropped in-flight
        bg = {}

        def _bg():
            bg["res"] = measure(url, concurrency=4, requests=40,
                                shape=(1, 6), retries=4)

        t = threading.Thread(target=_bg)
        t.start()
        time.sleep(0.2)
        code, out = _post(url + "/admin/canary/report",
                          {"model": "m", "delta": 0.25})
        assert code == 200 and out["state"] == "rolled_back"
        assert out["drained_replicas"] == ["r1"]
        t.join(timeout=120)
        assert not t.is_alive()
        assert bg["res"]["completed"] == 40
        assert bg["res"]["errors"] == 0

        # post-rollback traffic is v2-only; the drained canary finished
        # its in-flight work but takes no new requests
        for _ in range(4):
            code, out = _post(url + "/v1/predict",
                              {"inputs": {"data": [[0.0] * 6]}})
            assert code == 200 and out["version"] == "v2"
        assert registry.get("r1").draining
        snap = router.fleet_snapshot()
        assert snap["canaries"]["m"]["state"] == "rolled_back"
    finally:
        _stop_all(front, procs)


# ---------------------------------------------------------------------------
# tier-1 cursor migration: kill the owner mid-hop, stitch bitwise
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def spec_gen_art(tmp_path_factory):
    params = dm.init_params(GEN_SPEC, seed=0)
    path = str(tmp_path_factory.mktemp("fleet_spec") / "m.spec.mxtpu")
    meta = serving.export_generate(
        params, GEN_SPEC, path,
        draft_params=dm.quantize_decoder_params(params), speculate_k=3)
    assert meta["format_version"] == 5
    return {"path": path, "params": params}


def test_speculative_cursor_migration_stitches_bitwise_tail(spec_gen_art,
                                                            tmp_path):
    """Kill-mid-hop against SPECULATIVE replicas, with the hop-chunk
    cap lifted: resume prompts grow past max_prompt_len and stream
    through chunked prefill on the survivor, the kill lands between
    fused draft+verify windows (same decode_step op the drill targets
    on a plain server), and the stitched stream is BITWISE the
    uninterrupted single-process reference."""
    prompt, max_new, temp, seed = [5, 9, 13], 17, 0.7, 11
    ref = [int(t) for t in dm.reference_generate(
        spec_gen_art["params"], GEN_SPEC, prompt, max_new,
        temperature=temp, seed=seed)]

    registry = ReplicaRegistry(heartbeat_timeout_s=3.0)
    router = Router(registry=registry, hop_tokens=4)
    front = route_http(router, "127.0.0.1", 0)
    url = front.address
    procs = []
    try:
        # skip=3: hop 1 takes at most 3 fused dispatches (prefill emits
        # the first token, each window >= 1 more), so gA survives it and
        # dies on a later hop — mid-session, KV pages, draft cache and
        # all
        procs.append(_spawn_replica(
            tmp_path, spec_gen_art["path"], url, "gA", "vA",
            extra_env={
                "MXNET_FAULT_INJECT": "kill@serve=decode_step:skip=3"}))
        procs.append(_spawn_replica(tmp_path, spec_gen_art["path"], url,
                                    "gB", "vB"))
        _wait_routable(registry, 2, tmp_path)
        # both replicas registered the lifted-cap capabilities
        for rid in ("gA", "gB"):
            sp = registry.get(rid).spec
            assert sp["chunked_prefill"] and sp["speculative"]
        router.set_split("m", {"vA": 1.0})

        code, out = _post(url + "/v1/generate",
                          {"model": "m", "prompt": prompt,
                           "max_new_tokens": max_new,
                           "temperature": temp, "seed": seed},
                          timeout=300)
        assert code == 200, out
        assert out["tokens"] == ref
        assert out["finish_reason"] == "length"
        assert out["migrations"] >= 1
        assert out["replicas"] == ["gA", "gB"]
        # the lifted cap kept chunking instead of one unsplittable
        # final hop: at least the 4/4/4/4/1 schedule (+ death retries)
        assert out["hops"] >= 5
        # speculation stats aggregated across the surviving hops
        assert out["accepted_tokens_per_step"] >= 1.0
        assert registry.get("gA").dead
    finally:
        _stop_all(front, procs)


def test_cursor_migration_stitches_bitwise_tail(gen_art, tmp_path):
    prompt, max_new, temp, seed = [5, 9, 13], 17, 0.7, 11
    ref = [int(t) for t in dm.reference_generate(
        gen_art["params"], GEN_SPEC, prompt, max_new,
        temperature=temp, seed=seed)]

    registry = ReplicaRegistry(heartbeat_timeout_s=3.0)
    router = Router(registry=registry, hop_tokens=4)
    front = route_http(router, "127.0.0.1", 0)
    url = front.address
    tele = str(tmp_path / "tele")
    os.makedirs(tele)
    procs = []
    try:
        # gA owns the session and is armed to die mid-generation: hop 1
        # (4 tokens) consumes 3 decode steps of the skip budget; the
        # unsplittable final hop burns the remaining 3 and the 7th
        # decode-step event SIGKILLs the process with its KV pages
        procs.append(_spawn_replica(
            tmp_path, gen_art["path"], url, "gA", "vA",
            extra_env={
                "MXNET_FAULT_INJECT": "kill@serve=decode_step:skip=6",
                "MXNET_TELEMETRY_DIR": tele}))
        procs.append(_spawn_replica(tmp_path, gen_art["path"], url,
                                    "gB", "vB"))
        _wait_routable(registry, 2, tmp_path)
        # pin the session's first hops onto the victim
        router.set_split("m", {"vA": 1.0})

        code, out = _post(url + "/v1/generate",
                          {"model": "m", "prompt": prompt,
                           "max_new_tokens": max_new,
                           "temperature": temp, "seed": seed},
                          timeout=300)
        assert code == 200, out
        # position-keyed sampling: the tail regenerated on the survivor
        # stitches BITWISE onto the banked hop-1 tokens
        assert out["tokens"] == ref
        assert out["finish_reason"] == "length"
        assert out["migrations"] >= 1
        assert out["replicas"] == ["gA", "gB"]
        assert registry.get("gA").dead
        assert "proxy failed" in registry.get("gA").dead_reason

        # the kill left a flight-recorder postmortem naming the injection
        pms = glob.glob(os.path.join(tele, "postmortem_rank*_*.json"))
        assert pms, os.listdir(tele)
        rec = json.loads(open(pms[0]).read())
        assert rec["reason"].startswith("faultinject:")

        # the fleet keeps serving: a fresh session runs wholly on the
        # survivor (the vA-only split is intent, not a suicide pact) and
        # still matches the single-process reference
        code, out = _post(url + "/v1/generate",
                          {"model": "m", "prompt": [2, 3],
                           "max_new_tokens": 6, "temperature": 0.0,
                           "seed": 0},
                          timeout=300)
        assert code == 200, out
        assert out["replicas"] == ["gB"] and out["migrations"] == 0
        assert out["tokens"] == [int(t) for t in dm.reference_generate(
            gen_art["params"], GEN_SPEC, [2, 3], 6)]
    finally:
        _stop_all(front, procs)
