"""KVStore behavior contract (model: reference
tests/python/unittest/test_kvstore.py + python/mxnet/kvstore.py docstring
examples)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import kvstore as kv_mod

SHAPE = (4, 3)


def test_init_and_pull():
    kv = kv_mod.create("local")
    kv.init("3", mx.nd.ones(SHAPE) * 2)
    a = mx.nd.zeros(SHAPE)
    kv.pull("3", out=a)
    np.testing.assert_array_equal(a.asnumpy(), 2 * np.ones(SHAPE))


def test_push_replaces_without_updater():
    # reference kvstore_local.h PushImpl: no updater => local = merged
    kv = kv_mod.create("local")
    kv.init("3", mx.nd.ones(SHAPE) * 2)
    kv.push("3", mx.nd.ones(SHAPE) * 8)
    a = mx.nd.zeros(SHAPE)
    kv.pull("3", out=a)
    np.testing.assert_array_equal(a.asnumpy(), 8 * np.ones(SHAPE))


def test_push_multi_value_sums():
    # "aggregate the value and then push" example: 4 device grads sum to 4
    kv = kv_mod.create("local")
    kv.init("3", mx.nd.zeros(SHAPE))
    kv.push("3", [mx.nd.ones(SHAPE) for _ in range(4)])
    a = mx.nd.zeros(SHAPE)
    kv.pull("3", out=a)
    np.testing.assert_array_equal(a.asnumpy(), 4 * np.ones(SHAPE))


def test_updater_aggregation():
    # custom updater: stored += merged (the classic kvstore test updater)
    kv = kv_mod.create("local")
    kv.init("9", mx.nd.ones(SHAPE))

    def update(key, input_, stored):
        stored += input_ * 2
    kv._set_updater(update)
    kv.push("9", [mx.nd.ones(SHAPE)] * 4)
    a = mx.nd.zeros(SHAPE)
    kv.pull("9", out=a)
    # 1 + 2*sum(4 ones) = 9
    np.testing.assert_array_equal(a.asnumpy(), 9 * np.ones(SHAPE))


def test_push_uninitialized_key_with_updater_raises():
    kv = kv_mod.create("local")
    kv._set_updater(lambda k, g, w: None)
    with pytest.raises(mx.MXNetError):
        kv.push("nope", mx.nd.ones(SHAPE))


def test_list_key_push_pull():
    kv = kv_mod.create("local")
    keys = ["4", "5", "6"]
    for k in keys:
        kv.init(k, mx.nd.zeros(SHAPE))
    kv.push(keys, [mx.nd.ones(SHAPE)] * len(keys))
    outs = [mx.nd.zeros(SHAPE) for _ in keys]
    kv.pull(keys, out=outs)
    for o in outs:
        np.testing.assert_array_equal(o.asnumpy(), np.ones(SHAPE))


def test_row_sparse_pull():
    kv = kv_mod.create("local")
    dense = np.arange(12, dtype=np.float32).reshape(4, 3)
    kv.init("rs", mx.nd.array(dense))
    out = mx.nd.zeros((4, 3))
    kv.row_sparse_pull("rs", out=out, row_ids=mx.nd.array([0, 2]))
    expect = np.zeros((4, 3), np.float32)
    expect[[0, 2]] = dense[[0, 2]]
    np.testing.assert_array_equal(out.asnumpy(), expect)


def test_gradient_compression_roundtrip():
    kv = kv_mod.create("local")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("g", mx.nd.zeros((8,)))
    g = mx.nd.array(np.array([1.0, -1.0, 0.1, -0.1, 0.6, -0.6, 0.0, 2.0],
                             np.float32))
    kv.push("g", g)
    a = mx.nd.zeros((8,))
    kv.pull("g", out=a)
    got = a.asnumpy()
    # quantized to {-thr, 0, +thr}
    assert set(np.unique(got)).issubset({-0.5, 0.0, 0.5})
    # error feedback: residual carries the difference to the next push
    kv.push("g", mx.nd.zeros((8,)))
    b = mx.nd.zeros((8,))
    kv.pull("g", out=b)
    assert set(np.unique(b.asnumpy())).issubset({-0.5, 0.0, 0.5})


def test_invalid_type_rejected():
    with pytest.raises(ValueError):
        kv_mod.create("bogus")


def test_rank_and_num_workers_single_process():
    kv = kv_mod.create("dist_sync")
    assert kv.rank == 0
    assert kv.num_workers == 1
    assert kv.get_num_dead_node() == 0
