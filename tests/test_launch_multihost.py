"""Multi-host launch recipe (reference tools/launch.py ssh mode).

No ssh daemon exists in CI, so the recipe is proven through --dry-run:
the launcher must emit one correct, complete command per host — exactly
what an operator (or a k8s/slurm wrapper) runs on each machine.
"""
import os
import re
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(ROOT, "tools", "launch.py")


def _run(args):
    env = dict(os.environ)
    env.pop("MXNET_KVSTORE_SECRET", None)
    r = subprocess.run([sys.executable, LAUNCH] + args,
                       capture_output=True, text=True, timeout=60, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    return r.stdout.strip().splitlines()


def test_multihost_dry_run_emits_one_ssh_command_per_host():
    lines = _run(["-H", "hostA,hostB", "--heartbeat-dir", "/shared/hb",
                  "--dry-run", "python", "train.py", "--kv-store",
                  "dist_sync"])
    assert len(lines) == 2
    # runnable as printed: operator env supplies the secret via stdin
    assert lines[0].startswith(
        "[rank 0 @ hostA] printf '%s\\n' \"$MXNET_KVSTORE_SECRET\" | ssh ")
    assert lines[1].startswith(
        "[rank 1 @ hostB] printf '%s\\n' \"$MXNET_KVSTORE_SECRET\" | ssh ")
    for rank_, line in enumerate(lines):
        # every worker points at host 0's coordinator
        assert "MXNET_COORDINATOR_ADDRESS=hostA:9091" in line
        assert "MXNET_WORKER_RANK=%d" % rank_ in line
        assert "MXNET_NUM_WORKERS=2" in line
        assert "MXNET_HEARTBEAT_DIR=/shared/hb" in line
        # reference-era aliases for v1.x scripts
        assert "DMLC_PS_ROOT_URI=hostA" in line
        assert "DMLC_PS_ROOT_PORT=9091" in line
        assert "DMLC_ROLE=worker" in line
        assert "python train.py --kv-store dist_sync" in line
        # the job secret value must NOT travel in argv (world-readable
        # via /proc/<pid>/cmdline) — it ships on ssh stdin
        assert 'MXNET_KVSTORE_SECRET="' not in line
        assert re.search(r"MXNET_KVSTORE_SECRET=\w", line) is None
        assert "IFS= read -r MXNET_KVSTORE_SECRET" in line


def test_multihost_user_at_host_coordinator_is_dialable():
    lines = _run(["-H", "ubuntu@10.0.0.1,ubuntu@10.0.0.2",
                  "--heartbeat-dir", "/hb", "--dry-run", "cmd"])
    for line in lines:
        # ssh keeps the user@ prefix; the coordinator address must not
        assert "MXNET_COORDINATOR_ADDRESS=10.0.0.1:9091" in line
        assert "DMLC_PS_ROOT_URI=10.0.0.1" in line
        assert "ssh" in line and "ubuntu@10.0.0." in line


def test_multihost_round_robin_when_n_exceeds_hosts():
    lines = _run(["-H", "h0,h1", "-n", "4", "--heartbeat-dir", "/hb",
                  "--dry-run", "cmd"])
    hosts = [li.split("@ ")[1].split("]")[0] for li in lines]
    assert hosts == ["h0", "h1", "h0", "h1"]


def test_multihost_custom_port():
    (line,) = _run(["-H", "tpu-vm-0", "--coordinator-port", "7777",
                    "--heartbeat-dir", "/hb", "--dry-run", "cmd"])
    assert "MXNET_COORDINATOR_ADDRESS=tpu-vm-0:7777" in line


def test_singlehost_dry_run_contract():
    lines = _run(["-n", "2", "--dry-run", "python", "train.py"])
    assert len(lines) == 2
    for rank_, line in enumerate(lines):
        assert "MXNET_WORKER_RANK=%d" % rank_ in line
        assert re.search(r"MXNET_COORDINATOR_ADDRESS=127\.0\.0\.1:\d+",
                         line)
        assert "MXNET_KVSTORE_SECRET" not in line  # never in argv


def test_missing_heartbeat_dir_warns():
    env = dict(os.environ)
    r = subprocess.run(
        [sys.executable, LAUNCH, "-H", "a,b", "--dry-run", "cmd"],
        capture_output=True, text=True, timeout=60, env=env)
    assert r.returncode == 0
    assert "failure detection" in r.stderr
