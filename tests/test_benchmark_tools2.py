"""Smoke tests for the round-4 measurement tools: the gluon
imperative-vs-hybrid benchmark (reference benchmark/python/gluon/
benchmark_gluon.py) and the sparse end-to-end benchmark (reference
benchmark/python/sparse/sparse_end2end.py). Tiny shapes; the tools'
real-shape numbers run on the chip."""
import json
import os
import subprocess
import sys

TOP = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, TOP)


def test_benchmark_gluon_inference_both_variants():
    out = subprocess.run(
        [sys.executable, os.path.join(TOP, "tools", "benchmark_gluon.py"),
         "--model", "squeezenet1.0", "--batch-size", "1",
         "--num-batches", "2", "--type", "inference"],
        capture_output=True, text=True, timeout=560,
        env=dict(os.environ, JAX_PLATFORMS="cpu")).stdout
    lines = [json.loads(ln) for ln in out.splitlines()
             if ln.startswith("{")]
    metrics = {(l["metric"], l.get("hybrid")) for l in lines}
    assert ("gluon_img_per_sec", True) in metrics
    assert ("gluon_img_per_sec", False) in metrics
    assert ("gluon_hybridize_speedup", None) in metrics
    for l in lines:
        assert l["value"] > 0


def test_sparse_end2end_phases():
    out = subprocess.run(
        [sys.executable, os.path.join(TOP, "tools", "sparse_end2end.py"),
         "--num-features", "500", "--nnz", "5", "--batch-size", "32",
         "--num-batch", "3"],
        capture_output=True, text=True, timeout=560,
        env=dict(os.environ, JAX_PLATFORMS="cpu")).stdout
    line = json.loads([ln for ln in out.splitlines()
                       if ln.startswith("{")][-1])
    assert line["metric"] == "sparse_linear_samples_per_sec"
    assert line["value"] > 0
    for phase in ("io_ms", "comm_ms", "compute_ms"):
        assert line[phase] >= 0
