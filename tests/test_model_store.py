"""Pretrained model-zoo store (parity: python/mxnet/gluon/model_zoo/
model_store.py get_model_file/purge + the zoo factories' pretrained=
path, reference vision/resnet.py:388-390).

No network exists here, so fixtures are generated: a zoo net's params
are saved in reference ``.params`` format and resolved back through the
public ``pretrained=True`` surface.
"""
import os
import zipfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo import model_store, vision


def _save_fixture(name, root, fname=None, ctor=None):
    """Initialize zoo model `name` and save its params as a fixture."""
    net = (ctor or (lambda: vision.get_model(name)))()
    net.initialize(mx.initializer.Xavier())
    # materialize params (deferred init) with one tiny forward
    net(mx.nd.zeros((1, 3, 224, 224)))
    os.makedirs(root, exist_ok=True)
    path = os.path.join(root, fname or ("%s.params" % name))
    net.save_parameters(path)
    return net, path


def test_get_model_file_resolves_plain_params(tmp_path):
    root = str(tmp_path / "models")
    _save_fixture("squeezenet1.0", root)
    path = model_store.get_model_file("squeezenet1.0", root=root)
    assert path.endswith("squeezenet1.0.params")


def test_pretrained_true_loads_weights(tmp_path):
    root = str(tmp_path / "models")
    src, _ = _save_fixture("squeezenet1.0", root)
    net = vision.get_model("squeezenet1.0", pretrained=True, root=root)
    x = mx.nd.array(np.random.RandomState(0).randn(1, 3, 224, 224)
                    .astype(np.float32))
    np.testing.assert_allclose(net(x).asnumpy(), src(x).asnumpy(),
                               rtol=1e-5, atol=1e-5)


def test_pretrained_via_local_repo_dir(tmp_path, monkeypatch):
    repo = str(tmp_path / "repo")
    root = str(tmp_path / "cache")
    _save_fixture("mobilenet0.25", repo)
    monkeypatch.setenv("MXNET_GLUON_REPO", repo)
    net = vision.get_model("mobilenet0.25", pretrained=True, root=root)
    assert os.path.exists(os.path.join(root, "mobilenet0.25.params"))
    assert any(p.shape for p in net.collect_params().values())


def test_pretrained_via_repo_zip(tmp_path, monkeypatch):
    repo = str(tmp_path / "repo")
    root = str(tmp_path / "cache")
    _, params_path = _save_fixture("squeezenet1.1", str(tmp_path / "stage"))
    os.makedirs(repo, exist_ok=True)
    short = model_store.short_hash("squeezenet1.1")
    with zipfile.ZipFile(os.path.join(
            repo, "squeezenet1.1-%s.zip" % short), "w") as zf:
        zf.write(params_path, "squeezenet1.1.params")
    monkeypatch.setenv("MXNET_GLUON_REPO", repo)
    path = model_store.get_model_file("squeezenet1.1", root=root)
    assert path.endswith("squeezenet1.1.params")


def test_hash_named_file_with_wrong_content_is_rejected(tmp_path):
    """A reference-hash-named file must byte-verify; junk is refused
    loudly rather than loaded."""
    root = str(tmp_path / "models")
    os.makedirs(root)
    short = model_store.short_hash("resnet18_v1")
    with open(os.path.join(root, "resnet18_v1-%s.params" % short),
              "wb") as f:
        f.write(b"junk")
    with pytest.raises(RuntimeError, match="resnet18_v1"):
        model_store.get_model_file("resnet18_v1", root=root)


def test_missing_model_error_names_locations(tmp_path):
    with pytest.raises(RuntimeError) as e:
        model_store.get_model_file("resnet50_v2", root=str(tmp_path))
    assert "resnet50_v2" in str(e.value)
    assert str(tmp_path) in str(e.value)


def test_unknown_model_short_hash_raises():
    with pytest.raises(ValueError, match="not available"):
        model_store.short_hash("not_a_model")


def test_purge(tmp_path):
    root = str(tmp_path / "models")
    os.makedirs(root)
    for n in ("a.params", "b.params"):
        open(os.path.join(root, n), "wb").close()
    open(os.path.join(root, "keep.txt"), "wb").close()
    model_store.purge(root=root)
    assert os.listdir(root) == ["keep.txt"]


def test_factory_name_mapping():
    """Every get_model zoo name maps to a known store entry, so
    pretrained= resolution agrees with the reference's table."""
    from mxnet_tpu.gluon.model_zoo.vision.mobilenet import \
        _multiplier_suffix
    assert _multiplier_suffix(1.0) == "1.0"
    assert _multiplier_suffix(0.75) == "0.75"
    assert _multiplier_suffix(0.5) == "0.5"
    assert _multiplier_suffix(0.25) == "0.25"
    for name in ("resnet18_v1", "resnet152_v2", "vgg16", "vgg19_bn",
                 "alexnet", "densenet201", "squeezenet1.0", "inceptionv3",
                 "mobilenet0.5", "mobilenetv2_1.0"):
        assert name in model_store._model_sha1
