"""dist_async server semantics (in-process) + 2-bit wire packing
(VERDICT r3 #4/#5).

The cross-process versions live in tests/dist_async_worker.py (launched by
test_dist_kvstore-style subprocess runs below); here the server thread and
the pack/unpack codec are exercised directly.
"""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.kvstore import _pack_2bit, _dequantize_2bit
from mxnet_tpu.parallel.async_server import Server, Client

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_pack_2bit_roundtrip_and_size():
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    g = rng.randn(13, 7).astype("f4")  # deliberately not %4
    thr = 0.5
    packed, res = _pack_2bit(jnp.asarray(g), jnp.zeros_like(jnp.asarray(g)),
                             thr)
    # ~16x wire reduction: 4 codes per byte vs 4 bytes per f32
    assert packed.nbytes == int(np.ceil(g.size / 4))
    assert g.nbytes / packed.nbytes > 15.0  # (16x minus pad rounding)
    deq = _dequantize_2bit(np.asarray(packed), g.shape, thr)
    exp = np.where(g >= thr, thr, np.where(g <= -thr, -thr, 0.0))
    np.testing.assert_allclose(deq, exp, rtol=1e-6)
    # error feedback: residual carries exactly what quantization dropped
    np.testing.assert_allclose(np.asarray(res), g - exp, rtol=1e-5,
                               atol=1e-7)


def test_pack_2bit_error_feedback_converges():
    """Accumulated residuals eventually push small gradients across the
    threshold — the property that makes 2-bit training converge."""
    import jax.numpy as jnp
    g = jnp.full((4,), 0.2, jnp.float32)
    res = jnp.zeros((4,), jnp.float32)
    sent = np.zeros((4,), "f4")
    for _ in range(10):
        packed, res = _pack_2bit(g, res, 0.5)
        sent += _dequantize_2bit(np.asarray(packed), (4,), 0.5)
    # 10 steps of 0.2 = 2.0 total; quantized stream must track it
    np.testing.assert_allclose(sent, np.full((4,), 2.0), atol=0.5)


def test_async_server_apply_on_push():
    srv = Server()
    cli = Client("127.0.0.1", srv.port)
    try:
        cli.call("init", "w", np.zeros((2, 2), "f4"))
        import pickle
        cli.call("set_optimizer",
                 pickle.dumps(mx.optimizer.create("sgd", learning_rate=1.0)))
        for _ in range(3):
            cli.call("push", "w", np.ones((2, 2), "f4"))
        out = cli.call("pull", "w")
        np.testing.assert_allclose(out, np.full((2, 2), -3.0))
        # push of packed 2-bit codes dequantizes server-side
        import jax.numpy as jnp
        g = jnp.asarray(np.full((2, 2), 0.7, "f4"))
        packed, _ = _pack_2bit(g, jnp.zeros_like(g), 0.5)
        cli.call("pushq", "w", np.asarray(packed), (2, 2), 0.5)
        out = cli.call("pull", "w")
        np.testing.assert_allclose(out, np.full((2, 2), -3.5))
        stats = cli.call("stats")
        assert len(stats["pushes"]) == 4
    finally:
        cli.call("shutdown")
        cli.close()


def test_async_server_uninitialized_key_errors():
    srv = Server()
    cli = Client("127.0.0.1", srv.port)
    try:
        with pytest.raises(mx.base.MXNetError):
            cli.call("push", "nope", np.zeros((1,), "f4"))
    finally:
        cli.call("shutdown")
        cli.close()


def test_async_server_rejects_unauthenticated_frames():
    """A peer without the shared secret cannot get anything parsed —
    frames are HMAC-verified before any deserialization."""
    import socket
    import struct
    from mxnet_tpu.parallel.async_server import _recv_frame
    srv = Server()
    try:
        sock = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        # the server greets every connection with a hello frame carrying
        # the anti-replay challenge; drain it first
        hello, _ = _recv_frame(sock)
        assert hello["op"] == "hello"
        # well-formed frame, wrong tag: header {"op": "stats"}
        payload = struct.pack("<I", 15) + b'{"op": "stats"}'
        sock.sendall(struct.pack("<Q", 32 + len(payload)) + b"\x00" * 32
                     + payload)
        # server must drop the connection without replying
        sock.settimeout(5)
        assert sock.recv(1) == b""  # EOF
        sock.close()
        # an authenticated client still works afterwards
        cli = Client("127.0.0.1", srv.port)
        cli.call("init", "k", np.ones((2,), "f4"))
        np.testing.assert_array_equal(cli.call("pull", "k"), [1, 1])
    finally:
        Client("127.0.0.1", srv.port).call("shutdown")


def test_async_server_rejects_replayed_frames():
    """A frame captured off the wire fails authentication when resent:
    every frame MACs over the per-connection challenge plus its position
    in the lock-step stream, so replays land on a stale counter."""
    import hashlib
    import hmac
    import json
    import socket
    import struct
    from mxnet_tpu.parallel.async_server import (_Channel, _recv_frame,
                                                 _secret)
    srv = Server()
    try:
        sock = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
        hello, _ = _recv_frame(sock)
        chan = _Channel(bytes.fromhex(hello["challenge"]))
        hdr = json.dumps({"op": "stats"}).encode()
        payload = struct.pack("<I", len(hdr)) + hdr
        tag = hmac.new(_secret(), chan._mac_prefix() + payload,
                       hashlib.sha256).digest()
        frame = struct.pack("<Q", 32 + len(payload)) + tag + payload
        sock.sendall(frame)
        reply, _ = _recv_frame(sock, chan=chan)
        assert reply["status"] == "ok"  # the frame was valid the 1st time
        sock.sendall(frame)  # verbatim replay: counter is now stale
        sock.settimeout(5)
        assert sock.recv(1) == b""  # EOF — dropped like a forgery
        sock.close()
    finally:
        Client("127.0.0.1", srv.port).call("shutdown")


def test_async_server_refuses_public_bind_without_secret(monkeypatch):
    monkeypatch.delenv("MXNET_KVSTORE_SECRET", raising=False)
    with pytest.raises(RuntimeError, match="MXNET_KVSTORE_SECRET"):
        Server(bind="0.0.0.0")


def test_async_client_threads_use_independent_sockets():
    """Push and pull from different threads ride separate connections, so
    they can overlap (single-socket head-of-line block fixed)."""
    import threading
    srv = Server()
    cli = Client("127.0.0.1", srv.port)
    try:
        cli.call("init", "w", np.zeros((4,), "f4"))
        socks = {}

        def worker(name):
            cli.call("pull", "w")
            socks[name] = id(cli._tls.sock)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(set(socks.values())) == 3  # one socket per thread
    finally:
        cli.call("shutdown")
        cli.close()


def test_async_server_shutdown_drains_inflight_push():
    """Server.close() is a bounded DRAIN, not a kill: a push already being
    applied when shutdown lands must finish and get its "ok" reply (no
    half-applied weights, no worker wedged on a lost reply)."""
    import threading

    srv = Server()
    cli = Client("127.0.0.1", srv.port)
    cli.call("init", "w", np.zeros((4,), "f4"))
    real_dispatch = srv._dispatch
    entered = threading.Event()

    def slow_dispatch(header, blob):
        if header.get("op") == "push":
            entered.set()
            time.sleep(0.4)     # push caught mid-apply by the shutdown
        return real_dispatch(header, blob)

    srv._dispatch = slow_dispatch
    result = {}

    def pusher():
        try:
            result["reply"] = cli.call("push", "w", np.ones((4,), "f4"))
            result["ok"] = True
        except Exception as e:   # noqa: BLE001 — recorded for the assert
            result["ok"] = False
            result["err"] = e

    t = threading.Thread(target=pusher)
    t.start()
    assert entered.wait(5.0)
    srv.close(drain_s=5.0)       # idempotent; second call is a no-op
    srv.close(drain_s=5.0)
    t.join(10.0)
    assert not t.is_alive()
    assert result.get("ok"), result.get("err")
    np.testing.assert_allclose(np.asarray(srv._store["w"]),
                               np.ones((4,), "f4"))
    assert not srv._thread.is_alive()
    # the listener is really gone: a fresh client cannot connect
    with pytest.raises(OSError):
        Client("127.0.0.1", srv.port, timeout=1.0)
    cli.close()


def test_async_client_close_blocks_reconnect():
    """close() must win the race against a retrying call() in another
    thread: once closed, the client never dials the (draining) server."""
    srv = Server()
    cli = Client("127.0.0.1", srv.port)
    cli.call("init", "w", np.zeros((2,), "f4"))
    cli.close()
    with pytest.raises(ConnectionError):
        cli.call("pull", "w")
    srv.close()


def test_send_command_refuses_without_server():
    kv = mx.kv.create("local")
    with pytest.raises(mx.base.MXNetError):
        kv._send_command_to_servers(0, "x")


@pytest.mark.parametrize("n", [2])
def test_gluon_trainer_dist_async(n):
    """Gluon Trainer end to end over the async server: optimizer runs
    server-side (update_on_kvstore), every rank converges."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", str(n), sys.executable,
         os.path.join(ROOT, "tests", "dist_gluon_async_worker.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-2000:]
    for rank in range(n):
        assert "rank %d/%d: gluon dist_async invariants OK" % (rank, n) \
            in r.stdout, r.stdout[-4000:]


@pytest.mark.parametrize("n", [2])
def test_dist_async_multiprocess(n):
    """Full N-process dist_async: apply-on-push, no barrier, slow worker
    does not stall the fast one — observably different from dist_sync."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", str(n), sys.executable,
         os.path.join(ROOT, "tests", "dist_async_worker.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-2000:]
    for rank in range(n):
        assert "rank %d/%d: all dist_async invariants OK" % (rank, n) \
            in r.stdout, r.stdout[-4000:]
    assert "async pushes applied" in r.stdout
