"""Table-driven numeric sweep over the elemwise/broadcast/scalar/reduction
operator families vs numpy — the reference test_operator.py's per-op
checks (tests/python/unittest/test_operator.py) compressed into tables.
Every op is invoked through the public generic `mx.nd.invoke` path (the
registry name a symbol/NNVM-JSON would carry), so this also guards the
registered-name surface itself."""
import math

import numpy as np
import pytest

import mxnet_tpu as mx

RNG = np.random.RandomState(7)


def _inv(name, arrs, **kw):
    out = mx.nd.invoke(name, [mx.nd.array(a) for a in arrs], kw)
    if isinstance(out, (list, tuple)):
        out = out[0]
    return out.asnumpy()


# ---------------------------------------------------------------------------
# unary math
# ---------------------------------------------------------------------------

UNARY = [
    # (registry name, numpy fn, domain_lo, domain_hi)
    ("sin", np.sin, -3, 3), ("cos", np.cos, -3, 3), ("tan", np.tan, -1, 1),
    ("sinh", np.sinh, -2, 2), ("cosh", np.cosh, -2, 2),
    ("tanh", np.tanh, -2, 2),
    ("arcsin", np.arcsin, -0.9, 0.9), ("arccos", np.arccos, -0.9, 0.9),
    ("arctan", np.arctan, -3, 3),
    ("arcsinh", np.arcsinh, -3, 3), ("arccosh", np.arccosh, 1.1, 4),
    ("arctanh", np.arctanh, -0.9, 0.9),
    ("exp", np.exp, -2, 2), ("expm1", np.expm1, -2, 2),
    ("log", np.log, 0.1, 5), ("log1p", np.log1p, -0.5, 5),
    ("log2", np.log2, 0.1, 5), ("log10", np.log10, 0.1, 5),
    ("sqrt", np.sqrt, 0.0, 9), ("rsqrt", lambda x: 1 / np.sqrt(x), 0.1, 9),
    ("cbrt", np.cbrt, -8, 8),
    ("rcbrt", lambda x: 1 / np.cbrt(x), 0.1, 8),
    ("reciprocal", lambda x: 1 / x, 0.2, 4),
    ("square", np.square, -4, 4), ("abs", np.abs, -4, 4),
    ("sign", np.sign, -4, 4), ("negative", np.negative, -4, 4),
    ("_np_negative", np.negative, -4, 4),
    ("floor", np.floor, -4, 4), ("ceil", np.ceil, -4, 4),
    ("trunc", np.trunc, -4, 4), ("rint", np.rint, -4, 4),
    ("fix", np.fix, -4, 4),
    ("degrees", np.degrees, -3, 3), ("radians", np.radians, -180, 180),
    ("erf", np.vectorize(math.erf), -2, 2),
    ("gammaln", np.vectorize(math.lgamma), 0.2, 5),
    ("gamma", np.vectorize(math.gamma), 0.2, 5),
    ("softsign", lambda x: x / (1 + np.abs(x)), -4, 4),
    ("logical_not", lambda x: (x == 0).astype("f4"), -1, 1),
]


@pytest.mark.parametrize("name,ref,lo,hi", UNARY,
                         ids=[u[0] for u in UNARY])
def test_unary_vs_numpy(name, ref, lo, hi):
    x = RNG.uniform(lo, hi, (3, 4)).astype("f4")
    np.testing.assert_allclose(_inv(name, [x]), ref(x).astype("f4"),
                               rtol=2e-5, atol=2e-6)


def test_erfinv_roundtrip():
    x = RNG.uniform(-0.9, 0.9, (8,)).astype("f4")
    y = _inv("erfinv", [x])
    np.testing.assert_allclose(np.vectorize(math.erf)(y), x, rtol=1e-4,
                               atol=1e-5)


# ---------------------------------------------------------------------------
# binary: elemwise_*, broadcast_*, legacy _-names, CamelCase legacy
# ---------------------------------------------------------------------------

BINARY = [
    ("add", np.add), ("sub", np.subtract), ("mul", np.multiply),
    ("div", np.divide), ("mod", np.mod), ("power", np.power),
    ("maximum", np.maximum), ("minimum", np.minimum), ("hypot", np.hypot),
    ("equal", lambda a, b: (a == b).astype("f4")),
    ("not_equal", lambda a, b: (a != b).astype("f4")),
    ("greater", lambda a, b: (a > b).astype("f4")),
    ("greater_equal", lambda a, b: (a >= b).astype("f4")),
    ("lesser", lambda a, b: (a < b).astype("f4")),
    ("lesser_equal", lambda a, b: (a <= b).astype("f4")),
    ("logical_and", lambda a, b: ((a != 0) & (b != 0)).astype("f4")),
    ("logical_or", lambda a, b: ((a != 0) | (b != 0)).astype("f4")),
    ("logical_xor", lambda a, b: ((a != 0) ^ (b != 0)).astype("f4")),
]

_BCAST_NAME = {"add": "broadcast_plus", "sub": "broadcast_minus"}


@pytest.mark.parametrize("stem,ref", BINARY, ids=[b[0] for b in BINARY])
def test_binary_families_vs_numpy(stem, ref):
    # positive operands keep power/mod/div well-conditioned
    a = RNG.uniform(0.5, 3, (2, 4)).astype("f4")
    b = RNG.uniform(0.5, 3, (2, 4)).astype("f4")
    b[0, 0] = a[0, 0]  # give the comparison ops one equal element
    want = ref(a, b).astype("f4")

    names = ["elemwise_" + stem]
    if stem in ("add", "sub", "mul", "div", "mod", "power", "hypot",
                "equal", "not_equal", "greater", "lesser"):
        legacy = {"add": "_plus", "sub": "_minus"}.get(stem, "_" + stem)
        names.append(legacy)
    for name in names:
        np.testing.assert_allclose(_inv(name, [a, b]), want, rtol=1e-5,
                                   err_msg=name)

    # broadcast variant over (2,1,3) x (1,4,3)
    a3 = RNG.uniform(0.5, 3, (2, 1, 3)).astype("f4")
    b3 = RNG.uniform(0.5, 3, (1, 4, 3)).astype("f4")
    bname = _BCAST_NAME.get(stem, "broadcast_" + stem)
    np.testing.assert_allclose(_inv(bname, [a3, b3]),
                               ref(a3, b3).astype("f4"), rtol=1e-5,
                               err_msg=bname)


def test_broadcast_aliases():
    a = RNG.uniform(0.5, 3, (2, 3)).astype("f4")
    b = RNG.uniform(0.5, 3, (2, 3)).astype("f4")
    np.testing.assert_allclose(_inv("broadcast_add", [a, b]),
                               _inv("broadcast_plus", [a, b]))
    np.testing.assert_allclose(_inv("broadcast_sub", [a, b]),
                               _inv("broadcast_minus", [a, b]))
    np.testing.assert_allclose(_inv("broadcast_div", [a, b]), a / b,
                               rtol=1e-6)


SCALAR = [
    ("_plus_scalar", lambda x, s: x + s),
    ("_minus_scalar", lambda x, s: x - s),
    ("_rminus_scalar", lambda x, s: s - x),
    ("_mul_scalar", lambda x, s: x * s),
    ("_div_scalar", lambda x, s: x / s),
    ("_rdiv_scalar", lambda x, s: s / x),
    ("_mod_scalar", lambda x, s: np.mod(x, s)),
    ("_rmod_scalar", lambda x, s: np.mod(s, x)),
    ("_power_scalar", lambda x, s: np.power(x, s)),
    ("_rpower_scalar", lambda x, s: np.power(s, x)),
    ("_maximum_scalar", np.maximum), ("_minimum_scalar", np.minimum),
    ("_equal_scalar", lambda x, s: (x == s).astype("f4")),
    ("_not_equal_scalar", lambda x, s: (x != s).astype("f4")),
    ("_greater_scalar", lambda x, s: (x > s).astype("f4")),
    ("_greater_equal_scalar", lambda x, s: (x >= s).astype("f4")),
    ("_lesser_scalar", lambda x, s: (x < s).astype("f4")),
    ("_lesser_equal_scalar", lambda x, s: (x <= s).astype("f4")),
    ("_logical_and_scalar", lambda x, s: ((x != 0) & (s != 0)).astype("f4")),
    ("_logical_or_scalar", lambda x, s: ((x != 0) | (s != 0)).astype("f4")),
]


@pytest.mark.parametrize("name,ref", SCALAR, ids=[s[0] for s in SCALAR])
def test_scalar_ops_vs_numpy(name, ref):
    x = RNG.uniform(0.5, 3, (2, 3)).astype("f4")
    x[0, 0] = 1.5  # equality hit
    np.testing.assert_allclose(_inv(name, [x], scalar=1.5),
                               ref(x, np.float32(1.5)).astype("f4"),
                               rtol=1e-5)


def test_camelcase_legacy_binary_names():
    a = RNG.uniform(0.5, 2, (2, 2)).astype("f4")
    b = RNG.uniform(0.5, 2, (2, 2)).astype("f4")
    np.testing.assert_allclose(_inv("_Mul", [a, b]), a * b, rtol=1e-6)
    np.testing.assert_allclose(_inv("_Div", [a, b]), a / b, rtol=1e-6)
    np.testing.assert_allclose(_inv("_Minus", [a, b]), a - b, rtol=1e-6)
    np.testing.assert_allclose(_inv("_Power", [a, b]), np.power(a, b),
                               rtol=1e-5)
    np.testing.assert_allclose(_inv("_Hypot", [a, b]), np.hypot(a, b),
                               rtol=1e-5)
    np.testing.assert_allclose(_inv("_MulScalar", [a], scalar=2.0), a * 2)
    np.testing.assert_allclose(_inv("_RDivScalar", [a], scalar=2.0), 2 / a,
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

def test_reductions_vs_numpy():
    x = RNG.randn(3, 4, 5).astype("f4")
    xn = x.copy()
    xn[0, 0, 0] = np.nan
    np.testing.assert_allclose(_inv("nansum", [xn], axis=1),
                               np.nansum(xn, axis=1), rtol=1e-5)
    np.testing.assert_allclose(_inv("nanprod", [xn], axis=2),
                               np.nanprod(xn, axis=2), rtol=1e-5)
    np.testing.assert_allclose(_inv("max_axis", [x], axis=1),
                               x.max(axis=1))
    np.testing.assert_allclose(_inv("min_axis", [x], axis=0),
                               x.min(axis=0))
    np.testing.assert_allclose(_inv("sum_axis", [x], axis=2),
                               x.sum(axis=2), rtol=1e-5)
    np.testing.assert_allclose(_inv("square_sum", [x], axis=1),
                               (x ** 2).sum(axis=1), rtol=1e-5)
    np.testing.assert_allclose(_inv("argmin", [x], axis=1),
                               x.argmin(axis=1).astype("f4"))
    # argmax_channel: argmax over the trailing axis of a 2-D input
    x2 = RNG.randn(4, 6).astype("f4")
    np.testing.assert_allclose(_inv("argmax_channel", [x2]),
                               x2.argmax(axis=-1).astype("f4"))


# ---------------------------------------------------------------------------
# shape / indexing
# ---------------------------------------------------------------------------

def test_shape_index_ops_vs_numpy():
    x = RNG.randn(2, 3, 4).astype("f4")
    np.testing.assert_allclose(_inv("repeat", [x], repeats=2, axis=1),
                               np.repeat(x, 2, axis=1))
    np.testing.assert_allclose(_inv("reverse", [x], axis=1),
                               x[:, ::-1, :])
    np.testing.assert_allclose(_inv("shape_array", [x]),
                               np.array([2, 3, 4]))
    assert _inv("size_array", [x]).item() == 24
    np.testing.assert_allclose(
        _inv("broadcast_like", [x[:, :1, :], x]),
        np.broadcast_to(x[:, :1, :], x.shape))
    np.testing.assert_allclose(
        _inv("slice_like", [RNG.randn(4, 6).astype("f4")[:2, :3],
                            np.zeros((2, 3), "f4")]).shape, (2, 3))
    # gather_nd / scatter_nd round trip
    data = RNG.randn(4, 5).astype("f4")
    idx = np.array([[0, 2, 3], [1, 4, 0]], dtype="f4")  # (2, n)
    picked = _inv("gather_nd", [data, idx])
    np.testing.assert_allclose(picked, data[[0, 2, 3], [1, 4, 0]])
    scat = _inv("scatter_nd", [mx.nd.array(picked).asnumpy(), idx],
                shape=(4, 5))
    np.testing.assert_allclose(scat[[0, 2, 3], [1, 4, 0]], picked)
    # ravel/unravel
    mi = np.array([[1, 2], [3, 1]], dtype="f4")  # (ndim, n)
    flat = _inv("ravel_multi_index", [mi], shape=(5, 4))
    np.testing.assert_allclose(flat, np.ravel_multi_index(
        mi.astype("i8"), (5, 4)).astype("f4"))
    back = _inv("unravel_index", [flat], shape=(5, 4))
    np.testing.assert_allclose(back, mi)
    # space_to_depth
    sd = RNG.randn(1, 2, 4, 6).astype("f4")
    out = _inv("space_to_depth", [sd], block_size=2)
    assert out.shape == (1, 8, 2, 3)
    rt = _inv("depth_to_space", [out], block_size=2)
    np.testing.assert_allclose(rt, sd)


def test_stop_gradient_blocks_grad():
    x = mx.nd.array(np.ones((2, 2), "f4"))
    x.attach_grad()
    with mx.autograd.record():
        y = (mx.nd.invoke("stop_gradient", [x], {}) * x).sum()
    y.backward()
    # d/dx [sg(x) * x] = sg(x) = 1 (not 2x = 2)
    np.testing.assert_allclose(x.grad.asnumpy(), np.ones((2, 2)))


def test_grad_add_matches_add():
    a = RNG.randn(3, 3).astype("f4")
    b = RNG.randn(3, 3).astype("f4")
    np.testing.assert_allclose(_inv("_grad_add", [a, b]), a + b, rtol=1e-6)


# ---------------------------------------------------------------------------
# optimizer update ops invoked directly by registry name
# ---------------------------------------------------------------------------

def test_sgd_update_op_direct():
    w = RNG.randn(4).astype("f4")
    g = RNG.randn(4).astype("f4")
    out = _inv("sgd_update", [w, g], lr=0.1, wd=0.0, rescale_grad=1.0)
    np.testing.assert_allclose(out, w - 0.1 * g, rtol=1e-6)


def test_mp_sgd_update_keeps_master_precision():
    w16 = np.array([1.0, 2.0], dtype=np.float16)
    g16 = np.array([0.5, 0.5], dtype=np.float16)
    w32 = w16.astype("f4")
    outs = mx.nd.invoke("mp_sgd_update",
                        [mx.nd.array(w16, dtype="float16"),
                         mx.nd.array(g16, dtype="float16"),
                         mx.nd.array(w32)],
                        {"lr": 0.1, "wd": 0.0, "rescale_grad": 1.0})
    out = outs[0] if isinstance(outs, (list, tuple)) else outs
    np.testing.assert_allclose(out.asnumpy().astype("f4"),
                               w32 - 0.1 * g16.astype("f4"), atol=1e-3)


def test_histogram_and_diag():
    x = np.array([0.5, 1.5, 2.5, 0.1, 1.1, 2.9], "f4")
    cnt, edges = mx.nd.invoke("histogram", [mx.nd.array(x)],
                              {"bin_cnt": 3, "range": (0.0, 3.0)})
    np.testing.assert_allclose(cnt.asnumpy(), [2, 2, 2])
    np.testing.assert_allclose(edges.asnumpy(), [0, 1, 2, 3])
    m = RNG.randn(4, 4).astype("f4")
    np.testing.assert_allclose(_inv("diag", [m]), np.diag(m))
    np.testing.assert_allclose(_inv("diag", [m], k=1), np.diag(m, 1))
    v = np.array([1.0, 2.0, 3.0], "f4")
    np.testing.assert_allclose(_inv("diag", [v]), np.diag(v))


def test_one_hot_pick_take():
    idx = np.array([0, 2, 1], "f4")
    got = _inv("one_hot", [idx], depth=4, on_value=2.0, off_value=-1.0)
    want = np.full((3, 4), -1.0, "f4")
    for i, j in enumerate(idx.astype(int)):
        want[i, j] = 2.0
    np.testing.assert_allclose(got, want)

    data = RNG.randn(3, 5).astype("f4")
    picked = _inv("pick", [data, idx], axis=1)
    np.testing.assert_allclose(picked,
                               data[np.arange(3), idx.astype(int)])

    t = _inv("take", [data, np.array([2, 0], "f4")], axis=1)
    np.testing.assert_allclose(t, data[:, [2, 0]])


def test_sort_argsort_topk():
    x = RNG.randn(3, 6).astype("f4")
    np.testing.assert_allclose(_inv("sort", [x], axis=1),
                               np.sort(x, axis=1))
    np.testing.assert_allclose(_inv("argsort", [x], axis=1),
                               np.argsort(x, axis=1).astype("f4"))
    top = _inv("topk", [x], axis=1, k=2, ret_typ="value")
    want = np.sort(x, axis=1)[:, ::-1][:, :2]
    np.testing.assert_allclose(top, want)


def test_khatri_rao():
    a = RNG.randn(2, 3).astype("f4")
    b = RNG.randn(4, 3).astype("f4")
    got = _inv("khatri_rao", [a, b])
    want = np.vstack([np.kron(a[:, j], b[:, j]) for j in range(3)]).T
    assert got.shape == (8, 3)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_symbolic_broadcast_backward_reduces_over_broadcast_axes():
    """Gradient of a broadcast op must SUM over the broadcast axes
    (reference test_operator.py test_broadcast_binary_op backward)."""
    from mxnet_tpu import test_utils
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = mx.sym.broadcast_mul(a, b)
    av = RNG.randn(2, 1, 3).astype("f4")
    bv = RNG.randn(1, 4, 3).astype("f4")
    og = RNG.randn(2, 4, 3).astype("f4")
    test_utils.check_symbolic_forward(out, [av, bv], [av * bv], rtol=1e-5)
    test_utils.check_symbolic_backward(
        out, [av, bv], [og],
        {"a": (og * bv).sum(axis=1, keepdims=True),
         "b": (og * av).sum(axis=0, keepdims=True)}, rtol=1e-5)

    out = mx.sym.broadcast_add(a, b)
    test_utils.check_symbolic_backward(
        out, [av, bv], [og],
        {"a": og.sum(axis=1, keepdims=True),
         "b": og.sum(axis=0, keepdims=True)}, rtol=1e-5)

    # scalar-ish broadcast: (1,1,1) against full shape
    sv = RNG.randn(1, 1, 1).astype("f4")
    out = mx.sym.broadcast_div(a, b)
    test_utils.check_symbolic_backward(
        out, [og, sv], [og],
        {"a": og / sv, "b": (-og * og / (sv * sv)).sum(keepdims=True)
         .reshape(1, 1, 1)}, rtol=1e-4)


def test_symbolic_grad_req_add_accumulates():
    """grad_req='add' must accumulate into the provided grad buffer
    instead of overwriting (reference executor semantics)."""
    a = mx.sym.Variable("a")
    out = 2.0 * a
    av = np.ones((2, 2), "f4")
    seed = np.full((2, 2), 5.0, "f4")
    ex = out.bind(mx.cpu(), {"a": mx.nd.array(av)},
                  args_grad={"a": mx.nd.array(seed)}, grad_req="add")
    ex.forward(is_train=True)
    ex.backward([mx.nd.ones((2, 2))])
    np.testing.assert_allclose(ex.grad_dict["a"].asnumpy(), seed + 2.0)
    ex.forward(is_train=True)
    ex.backward([mx.nd.ones((2, 2))])
    np.testing.assert_allclose(ex.grad_dict["a"].asnumpy(), seed + 4.0)


RESHAPE_CASES = [
    # (src_shape, spec, reverse, want) — the reference test_reshape table
    # (tests/python/unittest/test_operator.py:2128-2148) verbatim
    ((2, 3, 5, 5), (0, -1), False, (2, 75)),
    ((2, 3, 5, 5), (0, 0, -1), False, (2, 3, 25)),
    ((5, 3, 4, 5), (0, -1, 0), False, (5, 15, 4)),
    ((2, 3, 5, 4), (-1, 0, 0), False, (8, 3, 5)),
    ((2, 3, 5, 5), (0, 0, 0, 0), False, (2, 3, 5, 5)),
    ((2, 4, 5, 3), (-1, 2, 2, 1), False, (30, 2, 2, 1)),
    ((2, 3, 5, 6), (-2,), False, (2, 3, 5, 6)),
    ((2, 3, 5, 6), (6, 1, -2), False, (6, 1, 5, 6)),
    ((2, 3, 5, 6), (-3, -3), False, (6, 30)),
    ((2, 3, 5, 6), (-3, -1), False, (6, 30)),
    ((64,), (-4, 16, 4), False, (16, 4)),
    ((64,), (-4, 16, -1), False, (16, 4)),
    ((64, 1, 2, 3), (-4, 16, -1, -2), False, (16, 4, 1, 2, 3)),
    ((2, 3, 5, 5), (0, -1), True, (5, 30)),
    ((2, 3, 5, 5), (0, 0, -1), True, (3, 5, 10)),
    ((5, 3, 4, 5), (0, -1, 0), True, (3, 20, 5)),
    ((2, 3, 5, 4), (-1, 0, 0), True, (6, 5, 4)),
    ((2, 3, 4, 5), (3, -1, 0), True, (3, 8, 5)),
    ((2, 3, 5, 5), (5, 3, 0, -1), True, (5, 3, 5, 2)),
    ((2, 3, 5, 5), (0, 0, 0, 0), True, (2, 3, 5, 5)),
]


@pytest.mark.parametrize("src,spec,rev,want", RESHAPE_CASES,
                         ids=["%s%s%s" % (s, p, "R" if r else "")
                              for s, p, r, _ in RESHAPE_CASES])
def test_reshape_special_codes(src, spec, rev, want):
    x = np.arange(int(np.prod(src)), dtype="f4").reshape(src)
    out = mx.nd.reshape(mx.nd.array(x), shape=spec, reverse=rev)
    assert out.shape == want
    np.testing.assert_allclose(out.asnumpy(), x.reshape(want))
    # values survive (same memory order contract as numpy reshape) and
    # the symbolic path infers the identical shape
    sym = mx.sym.Reshape(mx.sym.Variable("data"), shape=spec, reverse=rev)
    _, out_shapes, _ = sym.infer_shape(data=src)
    assert out_shapes[0] == want


def test_topk_mask_and_where_rows_and_positional_clip():
    a = np.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]], "f4")
    mask = mx.nd.topk(mx.nd.array(a), k=1, ret_typ="mask").asnumpy()
    np.testing.assert_allclose(mask, [[1, 0, 0], [0, 1, 0]])
    mask2 = mx.nd.topk(mx.nd.array(a), k=2, is_ascend=True,
                       ret_typ="mask").asnumpy()
    np.testing.assert_allclose(mask2, [[0, 1, 1], [1, 0, 1]])
    # row-selecting 1-D condition (reference where with csr/1-D cond)
    got = mx.nd.where(mx.nd.array([1.0, 0.0]),
                      mx.nd.array([[1.0, 2.0], [3.0, 4.0]]),
                      mx.nd.array([[9.0, 9.0], [8.0, 8.0]])).asnumpy()
    np.testing.assert_allclose(got, [[1, 2], [8, 8]])
    # elementwise condition unchanged
    got = mx.nd.where(mx.nd.array([[1.0, 0.0], [0.0, 1.0]]),
                      mx.nd.array([[1.0, 2.0], [3.0, 4.0]]),
                      mx.nd.array([[9.0, 9.0], [8.0, 8.0]])).asnumpy()
    np.testing.assert_allclose(got, [[1, 9], [8, 4]])
    # positional clip (reference generated signature)
    np.testing.assert_allclose(
        mx.nd.clip(mx.nd.array(a), 1.0, 3.0).asnumpy(),
        np.clip(a, 1, 3))


def test_positional_parameter_binding():
    """Generated op functions accept params positionally after the tensor
    inputs (the reference codegen contract: mx.nd.reshape(x, (3,2)),
    mx.nd.sum(x, 1), Convolution(..., kernel) etc.)."""
    x = mx.nd.array(np.arange(6, dtype="f4").reshape(2, 3))
    assert mx.nd.reshape(x, (3, 2)).shape == (3, 2)
    assert mx.nd.expand_dims(x, 1).shape == (2, 1, 3)
    assert mx.nd.transpose(x, (1, 0)).shape == (3, 2)
    np.testing.assert_allclose(mx.nd.sum(x, 1).asnumpy(),
                               x.asnumpy().sum(1))
    assert len(mx.nd.split(x, 3, axis=1)) == 3
    out = mx.nd.FullyConnected(x, mx.nd.zeros((4, 3)), mx.nd.zeros((4,)), 4)
    assert out.shape == (2, 4)
    # symbol surface follows the same contract
    s = mx.sym.reshape(mx.sym.Variable("data"), (3, 2))
    assert s.infer_shape(data=(2, 3))[1][0] == (3, 2)
    # duplicate positional+keyword must raise
    with pytest.raises(TypeError, match="positionally and by keyword"):
        mx.nd.reshape(x, (3, 2), shape=(6,))
    with pytest.raises(TypeError, match="too many positional"):
        mx.nd.zeros_like(x, 1, 2, 3, 4, 5, 6, 7)
