"""ImageDetIter + detection augmenters (parity model:
tests/python/unittest/test_image.py test_det_augmenters/test_image_detiter)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as img

cv2 = pytest.importorskip("cv2")


def _det_label(boxes):
    """Pack [cls, xmin, ymin, xmax, ymax] rows the reference way."""
    out = [2, 5]
    for b in boxes:
        out.extend(b)
    return np.array(out, np.float32)


def _make_det_rec(tmp_path, n=8):
    from mxnet_tpu import recordio
    rng = np.random.RandomState(0)
    rec = str(tmp_path / "det.rec")
    idx = str(tmp_path / "det.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        im = rng.randint(0, 255, (32, 40, 3), np.uint8)
        ok, buf = cv2.imencode(".jpg", im)
        label = _det_label([[i % 3, 0.1, 0.2, 0.6, 0.7],
                            [1, 0.3, 0.3, 0.9, 0.8]])
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(2 + 2 * 5, label, i, 0), buf.tobytes()))
    w.close()
    return rec


def test_det_iter_batches(tmp_path):
    rec = _make_det_rec(tmp_path)
    it = img.ImageDetIter(batch_size=4, data_shape=(3, 24, 24),
                          path_imgrec=rec)
    assert it.provide_label[0][1] == (4, 2, 5)
    b = next(iter(it))
    assert b.data[0].shape == (4, 3, 24, 24)
    lab = b.label[0].asnumpy()
    assert lab.shape == (4, 2, 5)
    # boxes stay normalized and ordered, padding is -1
    valid = lab[lab[:, :, 0] >= 0]
    assert (valid[:, 1:] >= 0).all() and (valid[:, 1:] <= 1).all()
    assert (valid[:, 3] > valid[:, 1]).all()


def test_det_hflip_flips_boxes():
    label = np.array([[0, 0.1, 0.2, 0.4, 0.6]], np.float32)
    im = mx.nd.array(np.arange(2 * 4 * 3, dtype=np.uint8).reshape(2, 4, 3))
    aug = img.DetHorizontalFlipAug(p=1.0)
    out, new = aug(im, label)
    np.testing.assert_allclose(new[0, 1], 0.6, rtol=1e-6)  # 1 - 0.4
    np.testing.assert_allclose(new[0, 3], 0.9, rtol=1e-6)  # 1 - 0.1
    np.testing.assert_array_equal(out.asnumpy(), im.asnumpy()[:, ::-1])


def test_det_random_crop_keeps_coverage():
    rng = np.random.RandomState(1)
    im = mx.nd.array(rng.randint(0, 255, (64, 64, 3), np.uint8))
    label = np.array([[0, 0.4, 0.4, 0.6, 0.6]], np.float32)
    aug = img.DetRandomCropAug(min_object_covered=0.5,
                               min_eject_coverage=0.5, max_attempts=200)
    out, new = aug(im, label)
    assert new.shape[0] >= 1
    assert (new[:, 1:] >= 0).all() and (new[:, 1:] <= 1).all()
    assert (new[:, 3] > new[:, 1]).all() and (new[:, 4] > new[:, 2]).all()


def test_det_random_pad_shrinks_boxes():
    rng = np.random.RandomState(2)
    im = mx.nd.array(rng.randint(0, 255, (32, 32, 3), np.uint8))
    label = np.array([[0, 0.0, 0.0, 1.0, 1.0]], np.float32)
    aug = img.DetRandomPadAug(area_range=(2.0, 3.0), max_attempts=200)
    out, new = aug(im, label)
    arr = out.asnumpy()
    assert arr.shape[0] >= 32 and arr.shape[1] >= 32
    # padded canvas -> the box no longer spans the whole image
    assert (new[0, 3] - new[0, 1]) < 1.0 or arr.shape[1] == 32


def test_det_iter_with_augmenters_trains_shapes(tmp_path):
    rec = _make_det_rec(tmp_path)
    it = img.ImageDetIter(batch_size=4, data_shape=(3, 24, 24),
                          path_imgrec=rec, rand_crop=0.5, rand_pad=0.5,
                          rand_mirror=True,
                          mean=[123.0, 117.0, 104.0],
                          std=[58.0, 57.0, 57.0])
    for b in it:
        lab = b.label[0].asnumpy()
        valid = lab[lab[:, :, 0] >= 0]
        assert valid.shape[0] >= 1  # every image keeps >= 1 box
        assert (valid[:, 1:5] >= 0).all() and (valid[:, 1:5] <= 1).all()


def test_multibox_target_matching():
    """SSD target assignment: every gt claims its best anchor; encoded
    offsets invert back to the gt box (reference multibox_target.cc)."""
    anchors = mx.nd.array(np.array(
        [[[0.2, 0.2, 0.6, 0.6],    # ~gt1
          [0.0, 0.0, 0.3, 0.3],
          [0.5, 0.5, 0.95, 0.95]]], np.float32))  # ~gt2
    label = mx.nd.array(np.array(
        [[[1, 0.25, 0.25, 0.55, 0.55],
          [0, 0.55, 0.55, 0.9, 0.9]]], np.float32))
    cls_pred = mx.nd.array(np.zeros((1, 3, 3), np.float32))
    loc_t, loc_m, cls_t = mx.nd._contrib_MultiBoxTarget(
        anchors, label, cls_pred, overlap_threshold=0.5)
    ct = cls_t.asnumpy()[0]
    assert ct[0] == 2.0 and ct[2] == 1.0 and ct[1] == 0.0, ct
    # decode anchor 0's offsets -> must reproduce gt1
    t = loc_t.asnumpy()[0].reshape(3, 4)[0]
    a = np.array([0.2, 0.2, 0.6, 0.6])
    aw, ah = a[2] - a[0], a[3] - a[1]
    acx, acy = (a[0] + a[2]) / 2, (a[1] + a[3]) / 2
    cx = t[0] * 0.1 * aw + acx
    cy = t[1] * 0.1 * ah + acy
    w = np.exp(t[2] * 0.2) * aw
    h = np.exp(t[3] * 0.2) * ah
    np.testing.assert_allclose(
        [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
        [0.25, 0.25, 0.55, 0.55], atol=1e-5)
    # mask on positives only
    np.testing.assert_array_equal(
        loc_m.asnumpy()[0].reshape(3, 4).sum(axis=1), [4.0, 0.0, 4.0])


def test_multibox_target_negative_mining():
    a = np.random.RandomState(0).rand(1, 40, 4).astype(np.float32)
    a[..., 2:] = a[..., :2] + 0.2  # valid corners
    anchors = mx.nd.array(a)
    label = mx.nd.array(np.array([[[0, 0.1, 0.1, 0.35, 0.35]]], np.float32))
    conf = np.zeros((1, 3, 40), np.float32)
    conf[0, 1:, :] = 0.9  # every negative looks confidently wrong
    loc_t, loc_m, cls_t = mx.nd._contrib_MultiBoxTarget(
        anchors, label, mx.nd.array(conf), overlap_threshold=0.5,
        negative_mining_ratio=3.0, negative_mining_thresh=0.5)
    ct = cls_t.asnumpy()[0]
    n_pos = (ct > 0).sum()
    n_neg = (ct == 0).sum()
    n_ign = (ct == -1).sum()
    assert n_pos >= 1
    assert n_neg <= 3 * n_pos  # mined down to the ratio
    assert n_ign > 0           # the rest ignored


def test_multibox_target_bipartite_guarantees_every_gt():
    """A dominant gt must not starve others of their bipartite match
    (regression: claimed gts were not excluded from later iterations)."""
    anchors = mx.nd.array(np.array(
        [[[0.0, 0.0, 0.5, 0.5],     # IoU: g0 high, g1 low
          [0.05, 0.05, 0.55, 0.55]]], np.float32))  # g0 second-best
    label = mx.nd.array(np.array(
        [[[0, 0.0, 0.0, 0.5, 0.5],        # g0: IoU 1.0 with a0
          [1, 0.05, 0.05, 0.55, 0.55]]],  # g1: IoU 1.0 with a1
        np.float32))
    cls_pred = mx.nd.array(np.zeros((1, 3, 2), np.float32))
    _, _, cls_t = mx.nd._contrib_MultiBoxTarget(
        anchors, label, cls_pred, overlap_threshold=0.95)
    ct = cls_t.asnumpy()[0]
    assert ct[0] == 1.0 and ct[1] == 2.0, ct  # both gts matched


def test_multibox_target_mining_reference_semantics():
    """Exact reference mining (multibox_target.cc:180-239): candidates
    are unmatched anchors with best-IoU < thresh, the HARDEST (lowest
    background softmax prob) ratio*num_pos train as background, the rest
    are ignored — and mining works at fresh init (all-zero logits)."""
    a = np.random.RandomState(3).rand(1, 30, 4).astype(np.float32)
    a[..., 2:] = a[..., :2] + 0.2
    label = mx.nd.array(np.array([[[0, 0.1, 0.1, 0.35, 0.35]]], np.float32))
    conf = np.zeros((1, 3, 30), np.float32)
    conf[0, 1, :5] = 4.0            # 5 anchors confidently non-background
    _, _, cls_t = mx.nd._contrib_MultiBoxTarget(
        mx.nd.array(a), label, mx.nd.array(conf), overlap_threshold=0.5,
        negative_mining_ratio=3.0, negative_mining_thresh=0.5)
    ct = cls_t.asnumpy()[0]
    n_pos = int((ct > 0).sum())
    assert n_pos >= 1
    neg_idx = np.where(ct == 0)[0]
    assert len(neg_idx) == min(3 * n_pos, 30 - n_pos)
    # every selected negative comes from the hard pool (lowest bg prob =
    # the 5 boosted anchors); quota < pool means a strict subset
    hard = set(range(5))
    assert set(neg_idx.tolist()) <= hard
    assert (ct == -1).sum() > 0

    # fresh init: all-zero logits must still mine background gradient
    conf0 = np.zeros((1, 3, 30), np.float32)
    _, _, ct0 = mx.nd._contrib_MultiBoxTarget(
        mx.nd.array(a), label, mx.nd.array(conf0), overlap_threshold=0.5,
        negative_mining_ratio=3.0, negative_mining_thresh=0.5)
    assert (ct0.asnumpy()[0] == 0).sum() >= 1


def test_det_iter_reshape_validates_label_rows(tmp_path):
    rec = _make_det_rec(tmp_path)
    it = img.ImageDetIter(batch_size=4, data_shape=(3, 24, 24),
                          path_imgrec=rec)
    with pytest.raises(mx.base.MXNetError):
        it.reshape(label_shape=(1, 5))  # dataset has 2 objects per image
    it.reshape(label_shape=(4, 5))      # growing is fine
    assert it.label_shape == (4, 5)
