"""Regression tests for code-review findings (round 1 review)."""
import numpy as np

import mxnet_tpu as mx
import mxnet_tpu.test_utils as tu


def test_softmax_output_loss_gradient():
    data = mx.nd.array([[1., 2., 3.], [1., 0., 0.]])
    data.attach_grad()
    label = mx.nd.array([2., 0.])
    with mx.autograd.record():
        out = mx.nd.SoftmaxOutput(data, label)
    out.backward()
    sm = np.exp(data.asnumpy())
    sm /= sm.sum(1, keepdims=True)
    oh = np.eye(3)[[2, 0]]
    np.testing.assert_allclose(data.grad.asnumpy(), sm - oh, atol=1e-5)


def test_out_kwarg_carries_autograd():
    a = mx.nd.array([1., 2.])
    a.attach_grad()
    c = mx.nd.zeros((2,))
    with mx.autograd.record():
        mx.nd.broadcast_mul(a, a, out=c)
        d = c * 2
    d.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), 4 * a.asnumpy())


def test_ndarray_key_setitem():
    x = mx.nd.array([[1., 2.], [3., 4.]])
    idx = mx.nd.array(np.array([0], dtype=np.int32))
    x[idx] = 9.0
    assert x.asnumpy()[0, 0] == 9.0
    assert x.asnumpy()[1, 0] == 3.0


def test_sparse_inherited_dense_fallback():
    s = tu.rand_ndarray((4, 3), "csr", density=0.5)
    assert s.size == 12
    assert s.ndim == 2
    (s + 1).asnumpy()
    s.copy()
    s.astype("float64")
    r = tu.rand_ndarray((6, 2), "row_sparse", density=0.5)
    assert r.size == 12
    (r * 2).asnumpy()


def test_deep_backward_no_recursion_limit():
    x = mx.nd.array([1.0])
    x.attach_grad()
    with mx.autograd.record():
        y = x
        for _ in range(1500):
            y = y + 1.0
    y.backward()
    assert x.grad.asnumpy()[0] == 1.0


def test_random_ctx_placement():
    r = mx.nd.random.uniform(shape=(2, 2), ctx=mx.cpu(1))
    assert r.context.device_type == "cpu"
    assert r.context.device_id == 1


def test_make_loss_grad_scale():
    x = mx.nd.array([1., 2.])
    x.attach_grad()
    with mx.autograd.record():
        l = mx.nd.make_loss(x, grad_scale=0.1)
    l.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [0.1, 0.1], rtol=1e-6)


def test_dropout_mode_always():
    y = mx.nd.Dropout(mx.nd.ones((1000,)), p=0.5, mode="always")
    frac = (y.asnumpy() != 0).mean()
    assert 0.3 < frac < 0.7


def test_int_inputs_are_autograd_constants():
    xi = mx.nd.array(np.array([1, 2], dtype=np.int32))
    xi.attach_grad()
    with mx.autograd.record():
        z = (xi * xi).sum()
    z.backward()
    assert (xi.grad.asnumpy() == 0).all()
    # embedding: int indices + float weight
    w = mx.nd.random.normal(shape=(5, 3))
    w.attach_grad()
    idx = mx.nd.array(np.array([0, 2], dtype=np.int32))
    with mx.autograd.record():
        e = mx.nd.Embedding(idx, w, input_dim=5, output_dim=3).sum()
    e.backward()
    rowsums = w.grad.asnumpy().sum(axis=1)
    np.testing.assert_allclose(rowsums, [3., 0., 3., 0., 0.])


def test_module_fit_feed_from_other_device():
    """Module.fit feed data must be placed on the executor's device (round-3
    verify found CPU NDArrayIter + tpu() executor crashing with mixed
    platforms). Reproduced here with two virtual CPU devices."""
    import mxnet_tpu as mx_
    ctx1 = mx_.Context("cpu", 1)
    data = mx_.sym.Variable("data")
    net = mx_.sym.FullyConnected(data, num_hidden=4, name="fc1")
    net = mx_.sym.SoftmaxOutput(net, name="softmax")
    X = np.random.RandomState(0).randn(32, 8).astype("float32")
    Y = (X[:, 0] > 0).astype("float32")
    it = mx_.io.NDArrayIter(X, Y, batch_size=8, label_name="softmax_label")
    mod = mx_.mod.Module(net, context=ctx1)
    mod.fit(it, num_epoch=1, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    mod.score(it, mx_.metric.Accuracy())
