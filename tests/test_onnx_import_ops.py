"""ONNX importer breadth tests — the reference's full 92-entry import
table (reference onnx2mx/_import_helper.py:28-117).

These graphs are built directly as protobuf, NOT round-tripped through
our own exporter, so they model third-party ONNX files (the reference
imports its model-zoo exports the same way). Each test compares the
imported graph's forward against a numpy reference.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import onnx as onnx_mxnet
from mxnet_tpu.contrib.onnx import _proto as P


def _tensor(name, arr):
    arr = np.asarray(arr)
    dt = {np.dtype("float32"): P.TensorProto.FLOAT,
          np.dtype("int64"): P.TensorProto.INT64,
          np.dtype("int32"): P.TensorProto.INT32}[arr.dtype]
    return P.TensorProto(name=name, dims=list(arr.shape), data_type=dt,
                         raw_data=arr.tobytes())


def _attr(name, v):
    if isinstance(v, float):
        return P.AttributeProto(name=name, f=v, type=P.AttributeProto.FLOAT)
    if isinstance(v, int):
        return P.AttributeProto(name=name, i=v, type=P.AttributeProto.INT)
    if isinstance(v, str):
        return P.AttributeProto(name=name, s=v.encode(),
                                type=P.AttributeProto.STRING)
    if isinstance(v, (tuple, list)):
        return P.AttributeProto(name=name, ints=list(v),
                                type=P.AttributeProto.INTS)
    raise TypeError(v)


def _node(op, inputs, outputs, **attrs):
    return P.NodeProto(op_type=op, input=list(inputs), output=list(outputs),
                       attribute=[_attr(k, v) for k, v in attrs.items()])


def _import(nodes, feeds, initializers=(), n_out=1, tmp_path=None,
            for_training=False):
    """Build a ModelProto around `nodes`, write it, import it, run it.

    feeds: {input_name: np array}; outputs are y0..y{n_out-1}."""
    outs = ["y%d" % i for i in range(n_out)]
    g = P.GraphProto(
        node=list(nodes), name="g",
        input=[P.ValueInfoProto(name=n) for n in feeds],
        output=[P.ValueInfoProto(name=o) for o in outs],
        initializer=list(initializers))
    m = P.ModelProto(ir_version=4, producer_name="test", graph=g,
                     opset_import=[P.OperatorSetIdProto(version=12)])
    path = str(tmp_path / "m.onnx")
    with open(path, "wb") as f:
        f.write(m.encode())
    sym, arg, aux = onnx_mxnet.import_model(path, for_training=for_training)
    mod = mx.mod.Module(sym, data_names=list(feeds), label_names=[])
    mod.bind([(k, v.shape) for k, v in feeds.items()], for_training=False)
    mod.init_params(arg_params=arg, aux_params=aux, allow_missing=True,
                    initializer=mx.initializer.Zero())
    mod.forward(mx.io.DataBatch(data=[mx.nd.array(v)
                                      for v in feeds.values()]),
                is_train=False)
    return [o.asnumpy() for o in mod.get_outputs()]


RNG = np.random.RandomState(7)


# ---- unary math ----------------------------------------------------------

@pytest.mark.parametrize("op,ref", [
    ("Ceil", np.ceil),
    ("Floor", np.floor),
    ("Reciprocal", lambda x: 1.0 / x),
    ("Softsign", lambda x: x / (1 + np.abs(x))),
    ("Cos", np.cos), ("Sin", np.sin), ("Tan", np.tan),
])
def test_unary(op, ref, tmp_path):
    x = RNG.randn(3, 4).astype(np.float32) + 2.0
    (y,) = _import([_node(op, ["x"], ["y0"])], {"x": x}, tmp_path=tmp_path)
    np.testing.assert_allclose(y, ref(x), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("op,ref", [
    ("Acos", np.arccos), ("Asin", np.arcsin), ("Atan", np.arctan),
])
def test_inverse_trig(op, ref, tmp_path):
    x = (RNG.rand(3, 4).astype(np.float32) - 0.5) * 1.8
    (y,) = _import([_node(op, ["x"], ["y0"])], {"x": x}, tmp_path=tmp_path)
    np.testing.assert_allclose(y, ref(x), rtol=1e-5, atol=1e-6)


def test_selu(tmp_path):
    x = RNG.randn(4, 5).astype(np.float32)
    (y,) = _import([_node("Selu", ["x"], ["y0"])], {"x": x},
                   tmp_path=tmp_path)
    a, s = 1.6732632423543772, 1.0507009873554805
    ref = s * np.where(x > 0, x, a * (np.exp(x) - 1))
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)


def test_hard_sigmoid(tmp_path):
    x = RNG.randn(4, 5).astype(np.float32) * 4
    (y,) = _import([_node("HardSigmoid", ["x"], ["y0"],
                          alpha=0.25, beta=0.4)],
                   {"x": x}, tmp_path=tmp_path)
    np.testing.assert_allclose(y, np.clip(0.25 * x + 0.4, 0, 1),
                               rtol=1e-5, atol=1e-6)


def test_log_softmax(tmp_path):
    x = RNG.randn(3, 6).astype(np.float32)
    (y,) = _import([_node("LogSoftmax", ["x"], ["y0"], axis=-1)],
                   {"x": x}, tmp_path=tmp_path)
    e = x - x.max(-1, keepdims=True)
    ref = e - np.log(np.exp(e).sum(-1, keepdims=True))
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)


# ---- comparison / logical ------------------------------------------------

@pytest.mark.parametrize("op,ref", [
    ("Less", lambda a, b: a < b),
    ("Greater", lambda a, b: a > b),
    ("Equal", lambda a, b: a == b),
])
def test_compare(op, ref, tmp_path):
    a = RNG.randint(0, 3, (3, 4)).astype(np.float32)
    b = RNG.randint(0, 3, (1, 4)).astype(np.float32)
    (y,) = _import([_node(op, ["a", "b"], ["y0"])], {"a": a, "b": b},
                   tmp_path=tmp_path)
    np.testing.assert_array_equal(y.astype(bool), ref(a, b))


@pytest.mark.parametrize("op,ref", [
    ("And", np.logical_and), ("Or", np.logical_or),
    ("Xor", np.logical_xor),
])
def test_logical_binary(op, ref, tmp_path):
    a = RNG.randint(0, 2, (3, 4)).astype(np.float32)
    b = RNG.randint(0, 2, (3, 4)).astype(np.float32)
    (y,) = _import([_node(op, ["a", "b"], ["y0"])], {"a": a, "b": b},
                   tmp_path=tmp_path)
    np.testing.assert_array_equal(y.astype(bool), ref(a > 0, b > 0))


def test_logical_not(tmp_path):
    a = RNG.randint(0, 2, (3, 4)).astype(np.float32)
    (y,) = _import([_node("Not", ["a"], ["y0"])], {"a": a},
                   tmp_path=tmp_path)
    np.testing.assert_array_equal(y.astype(bool), a == 0)


# ---- variadic elementwise ------------------------------------------------

def test_sum_mean_max_min_variadic(tmp_path):
    xs = [RNG.randn(2, 3).astype(np.float32) for _ in range(3)]
    feeds = {"x%d" % i: v for i, v in enumerate(xs)}
    for op, ref in [("Sum", np.sum), ("Mean", np.mean),
                    ("Max", np.max), ("Min", np.min)]:
        (y,) = _import([_node(op, list(feeds), ["y0"])], feeds,
                       tmp_path=tmp_path)
        np.testing.assert_allclose(y, ref(np.stack(xs), axis=0),
                                   rtol=1e-5, atol=1e-6)


# ---- reductions ----------------------------------------------------------

@pytest.mark.parametrize("op,ref", [
    ("ReduceProd", lambda x, ax, kd: np.prod(x, axis=ax, keepdims=kd)),
    ("ReduceSumSquare",
     lambda x, ax, kd: np.sum(x * x, axis=ax, keepdims=kd)),
    ("ReduceLogSum",
     lambda x, ax, kd: np.log(np.sum(x, axis=ax, keepdims=kd))),
    ("ReduceLogSumExp",
     lambda x, ax, kd: np.log(np.sum(np.exp(x), axis=ax, keepdims=kd))),
    ("ReduceL1",
     lambda x, ax, kd: np.sum(np.abs(x), axis=ax, keepdims=kd)),
    ("ReduceL2",
     lambda x, ax, kd: np.sqrt(np.sum(x * x, axis=ax, keepdims=kd))),
])
@pytest.mark.parametrize("keepdims", [0, 1])
def test_reductions(op, ref, keepdims, tmp_path):
    x = (RNG.rand(2, 3, 4).astype(np.float32) + 0.5)
    (y,) = _import([_node(op, ["x"], ["y0"], axes=(1,), keepdims=keepdims)],
                   {"x": x}, tmp_path=tmp_path)
    np.testing.assert_allclose(y, ref(x, 1, bool(keepdims)),
                               rtol=1e-4, atol=1e-5)


def test_argmax_argmin(tmp_path):
    x = RNG.randn(3, 5).astype(np.float32)
    (y,) = _import([_node("ArgMax", ["x"], ["y0"], axis=1, keepdims=0)],
                   {"x": x}, tmp_path=tmp_path)
    np.testing.assert_array_equal(y.astype(np.int64), x.argmax(1))
    (y,) = _import([_node("ArgMin", ["x"], ["y0"], axis=0, keepdims=1)],
                   {"x": x}, tmp_path=tmp_path)
    np.testing.assert_array_equal(y.astype(np.int64),
                                  x.argmin(0, keepdims=True))


# ---- structure / indexing ------------------------------------------------

def test_shape(tmp_path):
    x = RNG.randn(2, 3, 5).astype(np.float32)
    (y,) = _import([_node("Shape", ["x"], ["y0"])], {"x": x},
                   tmp_path=tmp_path)
    np.testing.assert_array_equal(y.astype(np.int64), (2, 3, 5))


def test_gather(tmp_path):
    x = RNG.randn(5, 4).astype(np.float32)
    idx = np.array([[0, 2], [4, 1]], np.float32)
    (y,) = _import([_node("Gather", ["x", "i"], ["y0"], axis=0)],
                   {"x": x, "i": idx}, tmp_path=tmp_path)
    np.testing.assert_allclose(y, x[idx.astype(int)], rtol=1e-6)


def test_depth_space_roundtrip(tmp_path):
    x = RNG.randn(1, 8, 2, 3).astype(np.float32)
    (y,) = _import([_node("DepthToSpace", ["x"], ["t"], blocksize=2),
                    _node("SpaceToDepth", ["t"], ["y0"], blocksize=2)],
                   {"x": x}, tmp_path=tmp_path)
    np.testing.assert_allclose(y, x, rtol=1e-6)
    (y,) = _import([_node("DepthToSpace", ["x"], ["y0"], blocksize=2)],
                   {"x": x}, tmp_path=tmp_path)
    assert y.shape == (1, 2, 4, 6)


def test_split_equal_and_unequal(tmp_path):
    x = RNG.randn(2, 7).astype(np.float32)
    y = _import([_node("Split", ["x"], ["y0", "y1"], axis=1,
                       split=(3, 4))], {"x": x}, n_out=2,
                tmp_path=tmp_path)
    np.testing.assert_allclose(y[0], x[:, :3], rtol=1e-6)
    np.testing.assert_allclose(y[1], x[:, 3:], rtol=1e-6)
    x2 = RNG.randn(2, 6).astype(np.float32)
    y = _import([_node("Split", ["x"], ["y0", "y1", "y2"], axis=1)],
                {"x": x2}, n_out=3, tmp_path=tmp_path)
    for i in range(3):
        np.testing.assert_allclose(y[i], x2[:, 2 * i:2 * i + 2], rtol=1e-6)


def test_slice_attr_and_input_forms(tmp_path):
    x = RNG.randn(4, 6, 5).astype(np.float32)
    # opset<10 attribute form, INT_MAX end on axis 2
    (y,) = _import([_node("Slice", ["x"], ["y0"], axes=(1, 2),
                          starts=(1, 0), ends=(4, 2 ** 31 - 1))],
                   {"x": x}, tmp_path=tmp_path)
    np.testing.assert_allclose(y, x[:, 1:4, :], rtol=1e-6)
    # opset>=10 constant-input form
    inits = [_tensor("st", np.array([0], np.int64)),
             _tensor("en", np.array([2], np.int64)),
             _tensor("ax", np.array([0], np.int64))]
    (y,) = _import([_node("Slice", ["x", "st", "en", "ax"], ["y0"])],
                   {"x": x}, initializers=inits, tmp_path=tmp_path)
    np.testing.assert_allclose(y, x[:2], rtol=1e-6)


@pytest.mark.parametrize("mode,np_mode", [("constant", "constant"),
                                          ("reflect", "reflect"),
                                          ("edge", "edge")])
def test_pad_modes(mode, np_mode, tmp_path):
    x = RNG.randn(2, 3, 4, 4).astype(np.float32)
    pads = (0, 0, 1, 1, 0, 0, 1, 1)  # ONNX begin*, end* order
    kw = {"mode": mode, "pads": pads}
    if mode == "constant":
        kw["value"] = 2.5
    (y,) = _import([_node("Pad", ["x"], ["y0"], **kw)], {"x": x},
                   tmp_path=tmp_path)
    pw = ((0, 0), (0, 0), (1, 1), (1, 1))
    if np_mode == "constant":
        ref = np.pad(x, pw, constant_values=2.5)
    else:
        ref = np.pad(x, pw, mode=np_mode)
    np.testing.assert_allclose(y, ref, rtol=1e-6)


def test_pad_opset11_input_form(tmp_path):
    x = RNG.randn(2, 5).astype(np.float32)
    inits = [_tensor("p", np.array([0, 1, 0, 2], np.int64)),
             _tensor("v", np.array(3.0, np.float32))]
    (y,) = _import([_node("Pad", ["x", "p", "v"], ["y0"],
                          mode="constant")],
                   {"x": x}, initializers=inits, tmp_path=tmp_path)
    np.testing.assert_allclose(
        y, np.pad(x, ((0, 0), (1, 2)), constant_values=3.0), rtol=1e-6)


# ---- NN layers -----------------------------------------------------------

def test_conv_transpose_matches_deconvolution(tmp_path):
    x = RNG.randn(2, 3, 5, 5).astype(np.float32)
    w = (RNG.randn(3, 4, 3, 3) * 0.1).astype(np.float32)
    (y,) = _import([_node("ConvTranspose", ["x", "w"], ["y0"],
                          kernel_shape=(3, 3), strides=(2, 2),
                          pads=(1, 1, 1, 1), output_padding=(1, 1))],
                   {"x": x}, initializers=[_tensor("w", w)],
                   tmp_path=tmp_path)
    sym = mx.sym.Deconvolution(mx.sym.Variable("x"), kernel=(3, 3),
                               num_filter=4, stride=(2, 2), pad=(1, 1),
                               adj=(1, 1), no_bias=True, name="d")
    ex = sym._bind_exec({"x": mx.nd.array(x), "d_weight": mx.nd.array(w)}) \
        if hasattr(sym, "_bind_exec") else None
    mod = mx.mod.Module(sym, data_names=["x"], label_names=[])
    mod.bind([("x", x.shape)], for_training=False)
    mod.init_params(arg_params={"d_weight": mx.nd.array(w)}, aux_params={})
    mod.forward(mx.io.DataBatch(data=[mx.nd.array(x)]), is_train=False)
    ref = mod.get_outputs()[0].asnumpy()
    assert y.shape == ref.shape == (2, 4, 10, 10)
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)


def test_fc_legacy(tmp_path):
    x = RNG.randn(3, 6).astype(np.float32)
    w = RNG.randn(4, 6).astype(np.float32)
    b = RNG.randn(4).astype(np.float32)
    (y,) = _import([_node("FC", ["x", "w", "b"], ["y0"])], {"x": x},
                   initializers=[_tensor("w", w), _tensor("b", b)],
                   tmp_path=tmp_path)
    np.testing.assert_allclose(y, x @ w.T + b, rtol=1e-4, atol=1e-5)


def test_lrn(tmp_path):
    x = RNG.rand(2, 6, 3, 3).astype(np.float32)
    (y,) = _import([_node("LRN", ["x"], ["y0"], size=5, alpha=1e-3,
                          beta=0.75, bias=2.0)],
                   {"x": x}, tmp_path=tmp_path)
    # numpy LRN: cross-channel window of size 5
    sq = x * x
    pad = np.pad(sq, ((0, 0), (2, 2), (0, 0), (0, 0)))
    win = sum(pad[:, i:i + 6] for i in range(5))
    ref = x / (2.0 + 1e-3 * win / 5) ** 0.75
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)


def test_instance_normalization(tmp_path):
    x = RNG.randn(2, 3, 4, 4).astype(np.float32)
    g = RNG.rand(3).astype(np.float32) + 0.5
    b = RNG.randn(3).astype(np.float32)
    (y,) = _import([_node("InstanceNormalization", ["x", "g", "b"], ["y0"],
                          epsilon=1e-5)],
                   {"x": x}, initializers=[_tensor("g", g), _tensor("b", b)],
                   tmp_path=tmp_path)
    mu = x.mean((2, 3), keepdims=True)
    var = x.var((2, 3), keepdims=True)
    ref = (x - mu) / np.sqrt(var + 1e-5) * g[None, :, None, None] \
        + b[None, :, None, None]
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-4)


def test_max_roi_pool(tmp_path):
    x = RNG.randn(1, 2, 8, 8).astype(np.float32)
    rois = np.array([[0, 0, 0, 4, 4]], np.float32)
    (y,) = _import([_node("MaxRoiPool", ["x", "r"], ["y0"],
                          pooled_shape=(2, 2), spatial_scale=1.0)],
                   {"x": x, "r": rois}, tmp_path=tmp_path)
    assert y.shape == (1, 2, 2, 2)
    mod = mx.mod.Module(mx.sym.ROIPooling(
        mx.sym.Variable("x"), mx.sym.Variable("r"), pooled_size=(2, 2),
        spatial_scale=1.0), data_names=["x", "r"], label_names=[])
    mod.bind([("x", x.shape), ("r", rois.shape)], for_training=False)
    mod.init_params()
    mod.forward(mx.io.DataBatch(data=[mx.nd.array(x), mx.nd.array(rois)]),
                is_train=False)
    np.testing.assert_allclose(y, mod.get_outputs()[0].asnumpy(), rtol=1e-5)


def test_lp_pool_and_global(tmp_path):
    x = RNG.randn(1, 2, 4, 4).astype(np.float32)
    (y,) = _import([_node("LpPool", ["x"], ["y0"], kernel_shape=(2, 2),
                          strides=(2, 2), p=2)],
                   {"x": x}, tmp_path=tmp_path)
    ref = np.sqrt(sum(
        x[:, :, i::2, :][:, :, :, j::2][:, :, :2, :2] ** 2
        for i in range(2) for j in range(2)))
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)
    (y,) = _import([_node("GlobalLpPool", ["x"], ["y0"], p=2)],
                   {"x": x}, tmp_path=tmp_path)
    np.testing.assert_allclose(
        y, np.sqrt((x ** 2).sum((2, 3), keepdims=True)),
        rtol=1e-4, atol=1e-5)


# ---- random --------------------------------------------------------------

def test_random_ops(tmp_path):
    (y,) = _import([_node("RandomUniform", [], ["y0"], shape=(50, 4),
                          low=2.0, high=3.0)], {"x": np.zeros((1,), "f4")},
                   tmp_path=tmp_path)
    assert y.shape == (50, 4) and y.min() >= 2.0 and y.max() <= 3.0
    x = np.zeros((20, 5), np.float32)
    (y,) = _import([_node("RandomUniformLike", ["x"], ["y0"], low=-1.0,
                          high=1.0)], {"x": x}, tmp_path=tmp_path)
    assert y.shape == x.shape and y.min() >= -1.0 and y.max() <= 1.0
    (y,) = _import([_node("RandomNormalLike", ["x"], ["y0"], mean=10.0,
                          scale=0.1)], {"x": x}, tmp_path=tmp_path)
    assert y.shape == x.shape and 9.0 < y.mean() < 11.0
    (y,) = _import([_node("RandomNormal", [], ["y0"], shape=(30, 3),
                          mean=5.0, scale=0.5)],
                   {"x": np.zeros((1,), "f4")}, tmp_path=tmp_path)
    assert y.shape == (30, 3) and 4.0 < y.mean() < 6.0


# ---- table completeness + real-model import ------------------------------

def test_import_table_covers_reference_92(tmp_path):
    """Name-by-name diff against the reference's _import_helper table."""
    from mxnet_tpu.contrib.onnx import onnx2mx as m
    reference_table = [
        "Constant", "RandomUniform", "RandomNormal", "RandomUniformLike",
        "RandomNormalLike", "Add", "Sub", "Mul", "Div", "Abs", "Neg",
        "Sum", "Tanh", "Ceil", "Floor", "Concat", "Sigmoid", "Relu",
        "Pad", "MatMul", "Conv", "ConvTranspose", "BatchNormalization",
        "SpatialBN", "LeakyRelu", "Elu", "PRelu", "Selu", "Softmax",
        "FC", "GlobalAveragePool", "GlobalMaxPool", "GlobalLpPool",
        "Gemm", "LRN", "Dropout", "Reshape", "Cast", "Split", "Slice",
        "Transpose", "Squeeze", "Unsqueeze", "Flatten", "Identity",
        "Reciprocal", "Sqrt", "Pow", "Exp", "Log", "ReduceMax",
        "ReduceMean", "ReduceMin", "ReduceSum", "ReduceProd",
        "AveragePool", "MaxPool", "ArgMax", "ArgMin", "Max", "Min",
        "Clip", "ReduceLogSum", "ReduceLogSumExp", "ReduceSumSquare",
        "ReduceL1", "ReduceL2", "MaxRoiPool", "InstanceNormalization",
        "LogSoftmax", "Softsign", "Less", "Greater", "Equal", "And",
        "Xor", "Not", "Or", "Mean", "Acos", "Asin", "Atan", "Cos",
        "Sin", "Softplus", "Tan", "Shape", "Gather", "HardSigmoid",
        "LpPool", "DepthToSpace", "SpaceToDepth",
    ]
    missing = [op for op in reference_table
               if not hasattr(m._Importer, "_cv_" + op)]
    assert not missing, "importer lacks reference table ops: %r" % missing
    assert len(reference_table) >= 91


def _resnet_block_onnx():
    """A hand-built ONNX residual block + head, the op diet of the
    reference zoo's exported ResNets (Conv/BN/Relu/MaxPool/Add/GAP/
    Flatten/Gemm/Softmax)."""
    rng = np.random.RandomState(0)
    inits, nodes = [], []

    def conv(name, x_in, cin, cout, k, stride, pad):
        w = (rng.randn(cout, cin, k, k) * (1.0 / np.sqrt(cin * k * k))) \
            .astype(np.float32)
        inits.append(_tensor(name + "_w", w))
        nodes.append(_node("Conv", [x_in, name + "_w"], [name],
                           kernel_shape=(k, k), strides=(stride, stride),
                           pads=(pad, pad, pad, pad)))
        return name

    def bn(name, x_in, c):
        for suffix, v in [("_g", np.ones(c)), ("_b", np.zeros(c)),
                          ("_m", rng.randn(c) * 0.01), ("_v", np.ones(c))]:
            inits.append(_tensor(name + suffix, v.astype(np.float32)))
        nodes.append(_node("BatchNormalization",
                           [x_in, name + "_g", name + "_b", name + "_m",
                            name + "_v"], [name], epsilon=1e-5))
        return name

    def relu(name, x_in):
        nodes.append(_node("Relu", [x_in], [name]))
        return name

    x = conv("c0", "data", 3, 8, 3, 1, 1)
    x = bn("bn0", x, 8)
    x = relu("r0", x)
    nodes.append(_node("MaxPool", [x], ["mp"], kernel_shape=(2, 2),
                       strides=(2, 2)))
    # residual block
    y = conv("c1", "mp", 8, 8, 3, 1, 1)
    y = bn("bn1", y, 8)
    y = relu("r1", y)
    y = conv("c2", y, 8, 8, 3, 1, 1)
    y = bn("bn2", y, 8)
    nodes.append(_node("Add", ["mp", y], ["res"]))
    x = relu("r2", "res")
    nodes.append(_node("GlobalAveragePool", [x], ["gap"]))
    nodes.append(_node("Flatten", ["gap"], ["flat"], axis=1))
    fw = (rng.randn(10, 8) * 0.3).astype(np.float32)
    fb = np.zeros(10, np.float32)
    inits += [_tensor("fc_w", fw), _tensor("fc_b", fb)]
    nodes.append(_node("Gemm", ["flat", "fc_w", "fc_b"], ["gemm"],
                       transB=1))
    nodes.append(_node("Softmax", ["gemm"], ["y0"], axis=1))
    return nodes, inits


def test_conv_auto_pad_same(tmp_path):
    """auto_pad=SAME_UPPER (stride 1, odd kernel) pads to same-size
    output instead of being silently ignored as zero padding."""
    x = RNG.randn(1, 2, 6, 6).astype(np.float32)
    w = (RNG.randn(3, 2, 3, 3) * 0.2).astype(np.float32)
    nodes = [P.NodeProto(op_type="Conv", input=["x", "w"], output=["y0"],
                         attribute=[_attr("kernel_shape", (3, 3)),
                                    _attr("auto_pad", "SAME_UPPER")])]
    (y,) = _import(nodes, {"x": x}, initializers=[_tensor("w", w)],
                   tmp_path=tmp_path)
    assert y.shape == (1, 3, 6, 6)


def test_conv_auto_pad_same_with_stride_refuses(tmp_path):
    x = RNG.randn(1, 2, 6, 6).astype(np.float32)
    w = (RNG.randn(3, 2, 3, 3) * 0.2).astype(np.float32)
    nodes = [P.NodeProto(op_type="Conv", input=["x", "w"], output=["y0"],
                         attribute=[_attr("kernel_shape", (3, 3)),
                                    _attr("strides", (2, 2)),
                                    _attr("auto_pad", "SAME_UPPER")])]
    with pytest.raises(Exception, match="auto_pad"):
        _import(nodes, {"x": x}, initializers=[_tensor("w", w)],
                tmp_path=tmp_path)


def test_pool_ceil_mode(tmp_path):
    """ceil_mode=1 maps to the reference 'full' pooling convention."""
    x = RNG.randn(1, 1, 5, 5).astype(np.float32)
    (y,) = _import([_node("MaxPool", ["x"], ["y0"], kernel_shape=(2, 2),
                          strides=(2, 2), ceil_mode=1)],
                   {"x": x}, tmp_path=tmp_path)
    assert y.shape == (1, 1, 3, 3)   # ceil(5/2) = 3
    (y,) = _import([_node("MaxPool", ["x"], ["y0"], kernel_shape=(2, 2),
                          strides=(2, 2))],
                   {"x": x}, tmp_path=tmp_path)
    assert y.shape == (1, 1, 2, 2)   # floor


def test_gather_negative_indices_wrap(tmp_path):
    x = RNG.randn(5, 4).astype(np.float32)
    idx = np.array([-1, 0], np.float32)  # ONNX: -1 == last element
    (y,) = _import([_node("Gather", ["x", "i"], ["y0"], axis=0)],
                   {"x": x, "i": idx}, tmp_path=tmp_path)
    np.testing.assert_allclose(y, x[[-1, 0]], rtol=1e-6)


def test_logsoftmax_opset_default_axis(tmp_path):
    """opset<13 LogSoftmax/Softmax default to axis=1 (not -1)."""
    x = RNG.randn(3, 4, 5).astype(np.float32)
    (y,) = _import([_node("LogSoftmax", ["x"], ["y0"])], {"x": x},
                   tmp_path=tmp_path)  # _import writes opset 12
    e = x - x.max(1, keepdims=True)
    ref = e - np.log(np.exp(e).sum(1, keepdims=True))
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)


def test_random_like_dtype_attr(tmp_path):
    """RandomNormalLike(dtype=FLOAT) over an int tensor draws float noise."""
    x = np.zeros((6, 3), np.int32)
    nodes = [P.NodeProto(op_type="RandomNormalLike", input=["x"],
                         output=["y0"],
                         attribute=[_attr("dtype", P.TensorProto.FLOAT)])]
    (y,) = _import(nodes, {"x": x}, tmp_path=tmp_path)
    assert y.shape == x.shape and y.dtype == np.float32
    assert y.std() > 0.1  # actually random, not zeros


def test_conv_transpose_output_shape_attr(tmp_path):
    """output_shape maps to Deconvolution target_shape (reference
    InferPad) instead of being silently dropped."""
    x = RNG.randn(1, 2, 5, 5).astype(np.float32)
    w = (RNG.randn(2, 3, 3, 3) * 0.1).astype(np.float32)
    (y,) = _import([_node("ConvTranspose", ["x", "w"], ["y0"],
                          kernel_shape=(3, 3), strides=(2, 2),
                          output_shape=(10, 10))],
                   {"x": x}, initializers=[_tensor("w", w)],
                   tmp_path=tmp_path)
    assert y.shape == (1, 3, 10, 10)


def test_resnet_style_onnx_imports_and_infers(tmp_path):
    nodes, inits = _resnet_block_onnx()
    x = np.random.RandomState(3).randn(2, 3, 16, 16).astype(np.float32)
    (y,) = _import(nodes, {"data": x}, initializers=inits,
                   tmp_path=tmp_path)
    assert y.shape == (2, 10)
    np.testing.assert_allclose(y.sum(1), np.ones(2), rtol=1e-5)
    assert (y > 0).all()
