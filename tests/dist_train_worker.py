"""N-process data-parallel Module.fit over dist_sync (launched by
tools/launch.py). Each worker trains on its contiguous shard; gradients
aggregate across processes through the kvstore (update_on_kvstore, the
reference's server-side update — python/mxnet/model.py:123-170). Verifies:

* final params identical on every rank (broadcast compare);
* rank 0 dumps params for the driver test to compare against an
  equivalent single-process full-batch run.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.parallel import dist  # noqa: E402
from tests.dist_train_common import (  # noqa: E402
    make_net, full_data, fixed_params, PER_WORKER_BATCH,
    N_SAMPLES_PER_WORKER, EPOCHS)


def main():
    kv = mx.kv.create("dist_sync")
    rank, n = kv.rank, kv.num_workers
    X, Y = full_data(n)
    # contiguous shard per worker (reference SplitSampler semantics)
    lo, hi = rank * N_SAMPLES_PER_WORKER, (rank + 1) * N_SAMPLES_PER_WORKER
    it = mx.io.NDArrayIter(X[lo:hi], Y[lo:hi],
                           batch_size=PER_WORKER_BATCH,
                           label_name="softmax_label")
    sym = make_net()
    mod = mx.mod.Module(sym)
    mod.fit(it, num_epoch=EPOCHS, kvstore=kv, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1.0 / (PER_WORKER_BATCH * n)},
            arg_params=fixed_params(sym), initializer=None)
    args, _ = mod.get_params()
    # every rank must hold identical params
    for name in sorted(args):
        mine = np.asarray(args[name].asnumpy())
        theirs = np.asarray(dist.broadcast(mine, root=0))
        np.testing.assert_allclose(mine, theirs, rtol=0, atol=0,
                                   err_msg="rank %d diverged on %s"
                                           % (rank, name))
    if rank == 0 and os.environ.get("DIST_TRAIN_DUMP"):
        np.savez(os.environ["DIST_TRAIN_DUMP"],
                 **{k: v.asnumpy() for k, v in args.items()})
    print("rank %d/%d: dist training converged identically" % (rank, n))
    return 0


if __name__ == "__main__":
    sys.exit(main())
