"""Symbol.infer_type dtype propagation + mixed-precision symbolic training
(reference src/executor/infer_graph_attr_pass.cc:41-72, simple_bind
type_dict path graph_executor.cc:1594, multi-precision SGD
python/mxnet/optimizer/optimizer.py:452)."""
import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx


def _mlp(with_bn=False):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    if with_bn:
        net = mx.sym.BatchNorm(net, name="bn")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_infer_type_default_f32():
    sym = _mlp()
    arg_t, out_t, aux_t = sym.infer_type()
    assert all(t == np.float32 for t in arg_t)
    assert out_t[0] == np.float32


def test_infer_type_fp16_propagates_to_params():
    sym = _mlp()
    arg_t, out_t, _ = sym.infer_type(data=np.float16)
    types = dict(zip(sym.list_arguments(), arg_t))
    assert types["data"] == np.float16
    assert types["fc1_weight"] == np.float16  # same-dtype constraint
    assert types["fc2_bias"] == np.float16
    assert out_t[0] == np.float16


def test_infer_type_bn_pins_f32_stats():
    sym = _mlp(with_bn=True)
    arg_t, out_t, aux_t = sym.infer_type(data=np.float16)
    types = dict(zip(sym.list_arguments(), arg_t))
    assert types["fc1_weight"] == np.float16
    assert types["bn_gamma"] == np.float32  # BN FInferType pins f32
    assert types["bn_beta"] == np.float32
    assert all(t == np.float32 for t in aux_t)  # moving stats f32
    assert out_t[0] == np.float16  # BN output follows data dtype


def test_infer_type_through_cast():
    data = mx.sym.Variable("data")
    net = mx.sym.Cast(data, dtype="float16")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc")
    arg_t, out_t, _ = net.infer_type(data=np.float32)
    types = dict(zip(net.list_arguments(), arg_t))
    assert types["data"] == np.float32
    assert types["fc_weight"] == np.float16  # downstream of the cast
    assert out_t[0] == np.float16


def test_infer_type_bfloat16():
    sym = _mlp()
    arg_t, out_t, _ = sym.infer_type(data=jnp.bfloat16)
    types = dict(zip(sym.list_arguments(), arg_t))
    assert types["fc1_weight"] == jnp.bfloat16
    assert out_t[0] == jnp.bfloat16


@pytest.mark.parametrize("dt", [np.float16, jnp.bfloat16])
def test_mixed_precision_symbolic_training(dt):
    """simple_bind(type_dict) trains in reduced precision with f32 master
    weights via the multi-precision updater (reference mp_sgd path)."""
    from mxnet_tpu import optimizer as opt
    sym = _mlp(with_bn=True)
    rng = np.random.RandomState(0)
    ex = sym.simple_bind(mx.cpu(), type_dict={"data": dt},
                         data=(16, 8))
    assert ex.arg_dict["fc1_weight"].dtype == dt
    assert ex.aux_dict["bn_moving_mean"].dtype == np.float32
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr[:] = rng.uniform(-0.1, 0.1, arr.shape).astype(arr.dtype)
    X = rng.randn(16, 8).astype(np.float32)
    Y = rng.randint(0, 4, (16,)).astype(np.float32)
    optimizer = opt.create("sgd", learning_rate=0.1, momentum=0.9,
                           rescale_grad=1.0 / 16,
                           multi_precision=(dt == np.float16))
    updater = opt.get_updater(optimizer)

    def loss_of(probs, y):
        p = probs.asnumpy().astype(np.float64)
        return -np.log(np.maximum(p[np.arange(16), y.astype(int)], 1e-9)).mean()

    losses = []
    for step in range(12):
        ex.forward(is_train=True, data=X, softmax_label=Y)
        losses.append(loss_of(ex.outputs[0], Y))
        ex.backward()
        for i, name in enumerate(ex.arg_names):
            g = ex.grad_dict.get(name)
            if g is not None:
                updater(i, g, ex.arg_dict[name])
    assert ex.arg_dict["fc1_weight"].dtype == dt  # stayed reduced precision
    assert losses[-1] < losses[0] * 0.9, losses
    assert np.isfinite(losses).all()


def test_grads_match_param_dtype():
    sym = _mlp()
    ex = sym.simple_bind(mx.cpu(), type_dict={"data": np.float16},
                         data=(8, 8))
    ex.forward(is_train=True,
               data=np.random.RandomState(0).randn(8, 8).astype(np.float16),
               softmax_label=np.zeros(8, np.float16))
    ex.backward()
    assert ex.grad_dict["fc1_weight"].dtype == np.float16


def test_variable_dtype_object_accepted():
    """Variable(dtype=np.float16) — numpy type OBJECT, the standard MXNet
    spelling — must parse (round-3 review: str(np.float16) was stored
    unparseably)."""
    v = mx.sym.Variable("data", dtype=np.float16)
    net = mx.sym.FullyConnected(v, num_hidden=4, name="fc")
    arg_t, out_t, _ = net.infer_type()
    types = dict(zip(net.list_arguments(), arg_t))
    assert types["data"] == np.float16
    assert types["fc_weight"] == np.float16
    net.simple_bind(mx.cpu(), data=(4, 8))  # must not raise


def test_index_ops_report_actual_dtype():
    a = mx.sym.Variable("a")
    for sym in (mx.sym.argmax(a, axis=1), mx.sym.argsort(a, axis=1)):
        _, out_t, _ = sym.infer_type(a=np.float16)
        assert out_t[0] == np.float32, sym  # matches op execution
    _, out_t, _ = mx.sym.topk(a, k=2, ret_typ="both").infer_type(
        a=np.float16)
    assert out_t[0] == np.float16 and out_t[1] == np.float32
