"""Linear-algebra op tests (parity model:
tests/python/unittest/test_operator.py test_laop* — reference
src/operator/tensor/la_op.cc)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _spd(n=4, batch=(), seed=0):
    rng = np.random.RandomState(seed)
    a = rng.randn(*batch, n, n).astype(np.float32)
    return a @ np.swapaxes(a, -1, -2) + n * np.eye(n, dtype=np.float32)


def test_gemm_and_gemm2():
    rng = np.random.RandomState(1)
    A = rng.randn(2, 3, 4).astype(np.float32)
    B = rng.randn(2, 4, 5).astype(np.float32)
    C = rng.randn(2, 3, 5).astype(np.float32)
    out = mx.nd.linalg.gemm(mx.nd.array(A), mx.nd.array(B), mx.nd.array(C),
                            alpha=2.0, beta=0.5)
    np.testing.assert_allclose(out.asnumpy(), 2.0 * A @ B + 0.5 * C,
                               rtol=1e-5)
    out2 = mx.nd.linalg.gemm2(mx.nd.array(A), mx.nd.array(B))
    np.testing.assert_allclose(out2.asnumpy(), A @ B, rtol=1e-5)
    # transposes
    out3 = mx.nd.linalg.gemm2(mx.nd.array(np.swapaxes(A, -1, -2)),
                              mx.nd.array(B), transpose_a=True)
    np.testing.assert_allclose(out3.asnumpy(), A @ B, rtol=1e-5)


def test_potrf_potri_sumlogdiag():
    S = _spd(5, batch=(3,))
    L = mx.nd.linalg.potrf(mx.nd.array(S))
    np.testing.assert_allclose(
        (L.asnumpy() @ np.swapaxes(L.asnumpy(), -1, -2)), S, rtol=1e-4,
        atol=1e-4)
    Sinv = mx.nd.linalg.potri(L)
    np.testing.assert_allclose(Sinv.asnumpy() @ S,
                               np.broadcast_to(np.eye(5), (3, 5, 5)),
                               rtol=1e-3, atol=1e-3)
    # log det via sumlogdiag of the Cholesky factor
    sld = mx.nd.linalg.sumlogdiag(L).asnumpy()
    _, logdet = np.linalg.slogdet(S)
    np.testing.assert_allclose(2.0 * sld, logdet, rtol=1e-4)


def test_trmm_trsm_roundtrip():
    rng = np.random.RandomState(2)
    A = np.tril(rng.randn(4, 4).astype(np.float32)) + 4 * np.eye(
        4, dtype=np.float32)
    B = rng.randn(4, 3).astype(np.float32)
    prod = mx.nd.linalg.trmm(mx.nd.array(A), mx.nd.array(B), alpha=1.0)
    np.testing.assert_allclose(prod.asnumpy(), np.tril(A) @ B, rtol=1e-5)
    back = mx.nd.linalg.trsm(mx.nd.array(A), prod)
    np.testing.assert_allclose(back.asnumpy(), B, rtol=1e-3, atol=1e-4)
    # rightside
    Bt = rng.randn(3, 4).astype(np.float32)
    pr = mx.nd.linalg.trmm(mx.nd.array(A), mx.nd.array(Bt), rightside=True)
    np.testing.assert_allclose(pr.asnumpy(), Bt @ np.tril(A), rtol=1e-5)


def test_syrk_gelqf_syevd():
    rng = np.random.RandomState(3)
    A = rng.randn(3, 5).astype(np.float32)
    np.testing.assert_allclose(
        mx.nd.linalg.syrk(mx.nd.array(A)).asnumpy(), A @ A.T, rtol=1e-5)
    np.testing.assert_allclose(
        mx.nd.linalg.syrk(mx.nd.array(A), transpose=True).asnumpy(),
        A.T @ A, rtol=1e-5)

    L, Q = mx.nd.linalg.gelqf(mx.nd.array(A))
    np.testing.assert_allclose(L.asnumpy() @ Q.asnumpy(), A, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(Q.asnumpy() @ Q.asnumpy().T, np.eye(3),
                               rtol=1e-4, atol=1e-5)

    S = _spd(4)
    U, lam = mx.nd.linalg.syevd(mx.nd.array(S))
    U, lam = U.asnumpy(), lam.asnumpy()
    np.testing.assert_allclose(U.T @ np.diag(lam) @ U, S, rtol=1e-3,
                               atol=1e-3)


def test_extractdiag_makediag():
    rng = np.random.RandomState(4)
    A = rng.randn(2, 4, 4).astype(np.float32)
    d = mx.nd.linalg.extractdiag(mx.nd.array(A))
    np.testing.assert_allclose(d.asnumpy(),
                               np.diagonal(A, axis1=-2, axis2=-1))
    v = rng.randn(3).astype(np.float32)
    m = mx.nd.linalg.makediag(mx.nd.array(v), offset=1)
    np.testing.assert_allclose(m.asnumpy(), np.diag(v, k=1))
    m2 = mx.nd.linalg.makediag(mx.nd.array(v), offset=-2)
    np.testing.assert_allclose(m2.asnumpy(), np.diag(v, k=-2))


def test_linalg_gradients_flow():
    """potrf/sumlogdiag autodiff: d logdet(S)/dS = S^-1 (symmetrized)."""
    S = _spd(4)
    x = mx.nd.array(S)
    x.attach_grad()
    with mx.autograd.record():
        L = mx.nd.linalg.potrf(x)
        y = 2.0 * mx.nd.linalg.sumlogdiag(L)  # = logdet(S)
    y.backward()
    g = x.grad.asnumpy()
    expect = np.linalg.inv(S)
    np.testing.assert_allclose(g + g.T, expect + expect.T, rtol=1e-3,
                               atol=1e-3)


def test_linalg_symbolic():
    A = mx.sym.Variable("A")
    B = mx.sym.Variable("B")
    out = mx.sym.linalg.gemm2(A, B, name="g2")
    rng = np.random.RandomState(5)
    a = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(4, 2).astype(np.float32)
    ex = out.bind(mx.cpu(), {"A": mx.nd.array(a), "B": mx.nd.array(b)})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), a @ b, rtol=1e-5)


def test_gemm_axis_rejected_loudly():
    A = mx.nd.array(np.zeros((2, 3, 4), np.float32))
    B = mx.nd.array(np.zeros((2, 4, 5), np.float32))
    C = mx.nd.array(np.zeros((2, 3, 5), np.float32))
    with pytest.raises(NotImplementedError, match="axis"):
        mx.nd.linalg.gemm(A, B, C, axis=0)


def test_linalg_namespace_uses_generated_wrappers():
    # raw numpy coercion + out= support come from the shared codegen
    a = np.eye(3, dtype=np.float32)
    out = mx.nd.linalg.potrf(a)  # numpy accepted
    np.testing.assert_allclose(out.asnumpy(), np.eye(3))
