"""N-process bucketed-DDP Module.fit worker (launched by
``tools/launch.py --ddp``). Trains the shared little net TWICE — once
with sub-KiB buckets (several fused all-reduces) and once with one huge
bucket — and asserts the two runs are BITWISE identical: bucketing is a
scheduling choice, never a numerics choice. Also asserts:

* every rank holds identical params after each run (broadcast compare);
* the optimizer (momentum) state files are byte-identical across bucket
  sizes — the whole update chain matches, not just the weights;
* the DDP path really engaged (``mod._ddp``) and the bucket counts
  differ the way the override says they must.

Rank 0 dumps the tiny-bucket run's params for the driver to compare
against the kvstore dist_sync path.
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import config  # noqa: E402
from mxnet_tpu.parallel import dist  # noqa: E402
from tests.dist_train_common import (  # noqa: E402
    make_net, full_data, fixed_params, PER_WORKER_BATCH,
    N_SAMPLES_PER_WORKER, EPOCHS)


def train_once(kv, bucket_mb, states_path):
    # identical RNG chain for every run: bucketing must not touch it
    mx.random.seed(7)
    rank, n = kv.rank, kv.num_workers
    X, Y = full_data(n)
    lo, hi = rank * N_SAMPLES_PER_WORKER, (rank + 1) * N_SAMPLES_PER_WORKER
    it = mx.io.NDArrayIter(X[lo:hi], Y[lo:hi],
                           batch_size=PER_WORKER_BATCH,
                           label_name="softmax_label")
    sym = make_net()
    mod = mx.mod.Module(sym)
    with config.override(ddp_bucket_mb=bucket_mb):
        mod.fit(it, num_epoch=EPOCHS, kvstore=kv, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                                  "rescale_grad":
                                      1.0 / (PER_WORKER_BATCH * n)},
                arg_params=fixed_params(sym), initializer=None)
    assert mod._ddp, "bucketed DDP did not engage (MXNET_DDP unset?)"
    mod.save_optimizer_states(states_path)
    stats = mod._ddp_stats(1)
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}, stats


def main():
    kv = mx.kv.create("dist_sync")
    rank, n = kv.rank, kv.num_workers
    tmp = tempfile.mkdtemp(prefix="ddp_states_")
    tiny_states = os.path.join(tmp, "tiny.states")
    huge_states = os.path.join(tmp, "huge.states")

    # ~300 bytes per bucket: the little net's grads split across several
    # fused all-reduces (fc1_weight alone overflows one bucket)
    tiny, tiny_stats = train_once(kv, 0.0003, tiny_states)
    huge, huge_stats = train_once(kv, 64.0, huge_states)

    assert tiny_stats and tiny_stats["buckets"] >= 2, tiny_stats
    assert huge_stats and huge_stats["buckets"] == 1, huge_stats
    assert tiny_stats["comm_bytes"] > 0

    # bucketing is numerics-neutral: BITWISE equal params + momentum
    for name in sorted(tiny):
        np.testing.assert_array_equal(
            tiny[name], huge[name],
            err_msg="rank %d: bucket size changed the math on %s"
                    % (rank, name))
    with open(tiny_states, "rb") as f:
        tb = f.read()
    with open(huge_states, "rb") as f:
        hb = f.read()
    assert tb == hb, \
        "rank %d: optimizer state diverged across bucket sizes" % rank

    # every rank holds identical params (replication by construction)
    for name in sorted(tiny):
        theirs = np.asarray(dist.broadcast(tiny[name], root=0))
        np.testing.assert_array_equal(
            tiny[name], theirs,
            err_msg="rank %d diverged from rank 0 on %s" % (rank, name))

    if rank == 0 and os.environ.get("DDP_TRAIN_DUMP"):
        np.savez(os.environ["DDP_TRAIN_DUMP"], **tiny)
    print("rank %d/%d: ddp bucketed training bitwise-stable "
          "(buckets %d vs %d)" % (rank, n, tiny_stats["buckets"],
                                  huge_stats["buckets"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
