"""Flash-attention kernel family (kernels/attention.py) — chip-free.

The acceptance property mirrors the PR-6 tier contract: the fused
kernels may change WALL TIME, never NUMBERS. Forward parity against the
dense pure-JAX reference (f32-widened tolerance), backward grads
bitwise-identical to the reference under the same cotangent, served
decode token streams bitwise-equal with the tier auto vs off — greedy
and sampled, speculation on and off, across an eviction/resume stitch —
and the TPU-platform export census proving the kernels actually lower
(mxk_flash_attn / mxk_flash_attn_paged custom calls) in the fused train
step, the decode module, and the v5 draft/verify module.
"""
import math
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import config, hlo_stats, serving, sym
from mxnet_tpu.kernels import attention as attn
from mxnet_tpu.kernels import tier
from mxnet_tpu.serve import Evicted, GenerateSession
from mxnet_tpu.serve import decode_model as dm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _qkv(b=2, h=3, t=64, d=16, dtype=jnp.float32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(
        (rng.randn(b, h, t, d) / np.sqrt(d)).astype(np.float32), dtype)
    return mk(), mk(), mk()


# ---------------------------------------------------------------------------
# dense training kernel: forward parity, backward bitwise
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("cfg", [{"block_q": 16, "block_k": 16},
                                 {"block_q": 32, "block_k": 16},
                                 {"block_q": 128, "block_k": 128}])
def test_dense_forward_matches_reference_f32(cfg):
    q, k, v = _qkv()
    out = attn.flash_attention(q, k, v, causal=True, config=cfg)
    ref = attn.reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)


def test_dense_forward_uneven_tail():
    # T=56 not a multiple of the 16-row blocks: the padding path, and
    # the tail-mask convention (padded KV rows contribute exact zeros)
    q, k, v = _qkv(t=56)
    cfg = {"block_q": 16, "block_k": 16}
    for causal in (True, False):
        out = attn.flash_attention(q, k, v, causal=causal, config=cfg)
        ref = attn.reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-6, atol=2e-6)


def test_dense_forward_bf16_accumulates_f32():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = attn.flash_attention(q, k, v, causal=True,
                               config={"block_q": 16, "block_k": 16})
    assert out.dtype == jnp.bfloat16
    ref = attn.reference_attention(q.astype(jnp.float32),
                                   k.astype(jnp.float32),
                                   v.astype(jnp.float32), causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("causal", [True, False])
def test_dense_backward_bitwise_equals_reference(causal):
    """The custom_vjp differentiates reference_attention itself, so under
    the SAME cotangent the grads are bit-identical, not merely close."""
    q, k, v = _qkv(t=48, d=8, seed=3)
    _, vjp_k = jax.vjp(
        lambda a, b, c: attn.flash_attention(
            a, b, c, causal=causal, config={"block_q": 16, "block_k": 16}),
        q, k, v)
    _, vjp_r = jax.vjp(
        lambda a, b, c: attn.reference_attention(a, b, c, causal=causal),
        q, k, v)
    g = jnp.asarray(np.random.RandomState(9).randn(*q.shape), jnp.float32)
    for gk, gr in zip(vjp_k(g), vjp_r(g)):
        assert jnp.array_equal(gk, gr), "grad not bitwise"


def test_dense_guard_reasons():
    f32, i32 = jnp.float32, jnp.int32
    ok = ((2, 3, 64, 16),) * 3
    assert attn.eligible(*ok, f32) is None
    assert "4-D" in attn.eligible((2, 64, 16), ok[1], ok[2], f32)
    assert "dtype" in attn.eligible(*ok, i32)
    assert "cross-length" in attn.eligible(
        (2, 3, 32, 16), (2, 3, 64, 16), (2, 3, 64, 16), f32, causal=True)
    # non-causal cross-length IS eligible (prefill-style windows)
    assert attn.eligible((2, 3, 32, 16), (2, 3, 64, 16), (2, 3, 64, 16),
                         f32, causal=False) is None
    assert "head_dim" in attn.eligible(
        (2, 3, 64, 1024), (2, 3, 64, 1024), (2, 3, 64, 1024), f32)
    assert "disagree" in attn.eligible(
        (2, 3, 64, 16), (2, 4, 64, 16), (2, 4, 64, 16), f32, causal=False)


def test_attend_or_none_tier_policy_and_fallback_census():
    q, k, v = _qkv(t=32, d=8)
    with config.override(kernel_tier="off"):
        tier.reset_stats()
        assert attn.attend_or_none(q, k, v) is None
    with config.override(kernel_tier="auto"):
        tier.reset_stats()
        out = attn.attend_or_none(q, k, v)
        assert out is not None
        # an ineligible call on the same tier records its reason per site
        assert attn.attend_or_none(q.astype(jnp.int32), k.astype(jnp.int32),
                                   v.astype(jnp.int32)) is None
        st = tier.stats()
    assert st["dispatch"].get("flash_attn") == 1
    assert any(k_.startswith("flash_attn:") and "dtype" in k_
               for k_ in st["fallback"]), st["fallback"]
    ref = attn.reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-6, atol=2e-6)


# ---------------------------------------------------------------------------
# paged serving kernel: parity vs the naive gather+softmax reference
# ---------------------------------------------------------------------------

def _softmax(x):
    m = x.max(axis=-1, keepdims=True)
    e = np.exp(x - m)
    return e / e.sum(axis=-1, keepdims=True)


def _paged_ref(q, kp, vp, bt, pos, heads, page):
    """Naive paged attention: gather every page a slot may see, dense
    softmax with the positional mask (-1e30 before the max)."""
    S, W, C = q.shape
    Dh = C // heads
    MP = bt.shape[1]
    ctx = MP * page
    out = np.zeros((S, W, C), np.float32)
    for s in range(S):
        rows = (np.asarray(bt)[s][:, None] * page
                + np.arange(page)[None, :]).reshape(-1)
        k_ctx = np.asarray(kp)[rows].reshape(ctx, heads, Dh)
        v_ctx = np.asarray(vp)[rows].reshape(ctx, heads, Dh)
        qs = np.asarray(q)[s].reshape(W, heads, Dh)
        t_pos = np.arange(ctx)[None, :]
        q_pos = int(pos[s]) + np.arange(W)[:, None]
        for h in range(heads):
            s_mat = (qs[:, h] @ k_ctx[:, h].T) / math.sqrt(Dh)
            s_mat = np.where(t_pos <= q_pos, s_mat, -1e30)
            out[s, :, h * Dh:(h + 1) * Dh] = _softmax(s_mat) @ v_ctx[:, h]
    return out


def _paged_setup(S=3, W=5, heads=4, Dh=8, page=8, MP=4, seed=0):
    rng = np.random.RandomState(seed)
    C = heads * Dh
    n_pages = S * MP + 1          # page 0 reserved like the real cache
    kp = jnp.asarray(rng.randn(n_pages * page, C).astype(np.float32))
    vp = jnp.asarray(rng.randn(n_pages * page, C).astype(np.float32))
    q = jnp.asarray((rng.randn(S, W, C) / np.sqrt(Dh)).astype(np.float32))
    bt = jnp.asarray(1 + np.arange(S * MP).reshape(S, MP), jnp.int32)
    # ragged positions: slot 0 mid-page, others deeper into the table
    pos = jnp.asarray([3 + (MP * page - W) * s // max(1, S - 1)
                       for s in range(S)], jnp.int32)
    return q, kp, vp, bt, pos


@pytest.mark.parametrize("heads,Dh,block_h", [
    (4, 8, 4),        # lanes == C: the always-valid full-width block
    (2, 128, 1),      # 128-aligned lane dim, grid over head pairs
    (2, 128, 2),
])
def test_paged_forward_matches_naive_reference(heads, Dh, block_h):
    q, kp, vp, bt, pos = _paged_setup(heads=heads, Dh=Dh)
    out = attn.paged_attention(q, kp, vp, bt, pos, heads=heads,
                               page_size=8, config={"block_h": block_h})
    ref = _paged_ref(q, kp, vp, bt, pos, heads, 8)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_paged_decode_window_one(edge_pos=0):
    # the decode step shape: W=1, and a slot sitting at position 0 only
    # sees its first token (everything else masked to an exact 0 weight)
    q, kp, vp, bt, _ = _paged_setup(W=1)
    pos = jnp.asarray([edge_pos, 7, 24], jnp.int32)
    out = attn.paged_attention(q, kp, vp, bt, pos, heads=4, page_size=8)
    ref = _paged_ref(q, kp, vp, bt, pos, 4, 8)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_paged_invalid_block_h_self_heals():
    # heads=4, Dh=8: lanes for block_h=2 is 16 — Mosaic-invalid, so the
    # call must fall back to the full-width head block, not crash
    q, kp, vp, bt, pos = _paged_setup()
    out = attn.paged_attention(q, kp, vp, bt, pos, heads=4, page_size=8,
                               config={"block_h": 2})
    ref = _paged_ref(q, kp, vp, bt, pos, 4, 8)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_paged_guard_reasons():
    f32 = jnp.float32
    q, pages, bt, pos = (3, 5, 32), (264, 32), (3, 4), (3,)
    assert attn.paged_eligible(q, pages, bt, pos, f32, 4, 8) is None
    assert "sublane" in attn.paged_eligible(q, pages, bt, pos, f32, 4, 4)
    assert "3-D" in attn.paged_eligible((3, 5, 4, 8), pages, bt, pos,
                                        f32, 4, 8)
    assert "divisible by heads" in attn.paged_eligible(
        q, pages, bt, pos, f32, 5, 8)
    assert "whole number" in attn.paged_eligible(
        q, (260, 32), bt, pos, f32, 4, 8)
    assert "block table" in attn.paged_eligible(
        q, pages, (2, 4), pos, f32, 4, 8)
    assert "dtype" in attn.paged_eligible(q, pages, bt, pos,
                                          jnp.int32, 4, 8)


def test_paged_attend_or_none_records_page_size_fallback():
    q, kp, vp, bt, pos = _paged_setup()
    with config.override(kernel_tier="auto"):
        tier.reset_stats()
        assert attn.paged_attend_or_none(
            q, kp, vp, bt, pos, heads=4, page_size=4) is None
        out = attn.paged_attend_or_none(
            q, kp, vp, bt, pos, heads=4, page_size=8)
        st = tier.stats()
    assert out is not None
    assert st["dispatch"].get("flash_attn_paged") == 1
    assert any(k.startswith("flash_attn_paged:") and "sublane" in k
               for k in st["fallback"]), st["fallback"]


# ---------------------------------------------------------------------------
# served decode: tokens bitwise tier=auto vs tier=off
# ---------------------------------------------------------------------------

SPEC8 = dm.DecoderSpec(vocab=61, dim=32, num_heads=4, num_layers=2,
                       max_prompt_len=8, page_size=8, max_pages_per_slot=6,
                       max_slots=4, num_pages=25)

WORK8 = [  # (prompt, max_new, temperature, seed) — greedy AND sampled
    ([5, 9, 13], 12, 0.0, 0),
    ([2, 3], 3, 0.0, 0),
    ([4, 4, 4, 4, 6, 7], 8, 0.9, 11),
    ([7], 2, 0.0, 0),
    ([11, 60, 1, 2, 3], 16, 0.7, 5),
    ([8, 8, 9], 5, 0.0, 0),
]


@pytest.fixture(scope="module")
def params8():
    return dm.init_params(SPEC8, seed=0)


@pytest.fixture(scope="module")
def tier_arts(tmp_path_factory, params8):
    """One artifact per tier setting (the tier is resolved at export/
    lowering time), plain and speculative."""
    d = tmp_path_factory.mktemp("attn_arts")
    draft = dm.quantize_decoder_params(params8)
    arts = {}
    for t in ("auto", "off"):
        with config.override(kernel_tier=t):
            plain = str(d / ("m_%s.gen.mxtpu" % t))
            spec = str(d / ("m_%s.spec.mxtpu" % t))
            serving.export_generate(params8, SPEC8, plain)
            serving.export_generate(params8, SPEC8, spec,
                                    draft_params=draft, speculate_k=3)
            arts[t] = (plain, spec)
    return arts


def _drive(sess, reqs, cap=400):
    rounds = 0
    while not all(r.done() for r in reqs) and rounds < cap:
        sess.run_round()
        rounds += 1
    assert all(r.done() for r in reqs), "scheduler stalled"
    return [r.result(timeout=1.0) for r in reqs]


def _serve_all(path, work, **kw):
    with config.override(kernel_tier=kw.pop("tier")):
        sess = GenerateSession(path, auto_start=False, timeout_ms=0, **kw)
        reqs = [sess.submit(p, max_new_tokens=n, temperature=t, seed=s)
                for p, n, t, s in work]
        outs = _drive(sess, reqs)
        sess.close(drain=True)
    return [o["tokens"] for o in outs]


def test_decode_tokens_bitwise_auto_vs_off(tier_arts):
    on = _serve_all(tier_arts["auto"][0], WORK8, tier="auto")
    off = _serve_all(tier_arts["off"][0], WORK8, tier="off")
    assert on == off


def test_decode_tokens_bitwise_speculative_auto_vs_off(tier_arts):
    on = _serve_all(tier_arts["auto"][1], WORK8, tier="auto",
                    speculative=True)
    off = _serve_all(tier_arts["off"][1], WORK8, tier="off",
                     speculative=True)
    no_spec = _serve_all(tier_arts["off"][1], WORK8, tier="off",
                         speculative=False)
    assert on == off == no_spec


def test_eviction_resume_stitches_bitwise_across_tiers(tier_arts):
    """Cursor migration across the tier boundary: a request evicted from
    a kernel-tier server resumes on a naive-path server (and vice versa)
    with the stitched stream equal to the uninterrupted one."""
    prompt, n = [5, 9, 13], 14
    full = _serve_all(tier_arts["off"][0], [(prompt, n, 0.0, 0)],
                      tier="off")[0]
    for first, then in (("auto", "off"), ("off", "auto")):
        with config.override(kernel_tier=first):
            sess = GenerateSession(tier_arts[first][0], auto_start=False,
                                   timeout_ms=0, drain_tokens=2)
            req = sess.submit(prompt, max_new_tokens=n, temperature=0.0,
                              seed=0)
            for _ in range(2):   # few tokens: the resume prompt must
                sess.run_round()  # still fit the v3 max_prompt_len
            sess.close(drain=True)     # bounded drain -> evict + cursor
        with pytest.raises(Evicted) as ei:
            req.result(timeout=1.0)
        exc = ei.value
        assert exc.cursor["resume_prompt"] == prompt + exc.tokens
        assert 0 < len(exc.tokens) < n
        with config.override(kernel_tier=then):
            sess2 = GenerateSession(tier_arts[then][0], auto_start=False,
                                    timeout_ms=0)
            tail = _drive(sess2, [sess2.submit(
                exc.cursor["resume_prompt"],
                max_new_tokens=n - len(exc.tokens), temperature=0.0,
                seed=0)])[0]["tokens"]
            sess2.close(drain=True)
        assert exc.tokens + tail == full, (first, then)


def test_decode_sync_budget_one_d2h_per_step_with_kernel(tier_arts):
    """The kernel path must not add device syncs: still exactly one d2h
    fetch per decode step plus one per prefill batch."""
    from mxnet_tpu import profiler
    with config.override(kernel_tier="auto"):
        sess = GenerateSession(tier_arts["auto"][0], auto_start=False,
                               timeout_ms=0)
        reqs = [sess.submit(p, max_new_tokens=n, temperature=t, seed=s)
                for p, n, t, s in WORK8[:4]]
        before = profiler.sync_counters()["d2h"]
        _drive(sess, reqs)
        prefills = sess.metrics_.prefill_batches
        sess._publish_window(force=True)
        snap = sess.metrics_.snapshot()
        after = profiler.sync_counters()["d2h"]
        sess.close(drain=True)
    steps = snap["decode_steps"]
    assert prefills >= 1 and steps >= 1
    assert after - before == steps + prefills, (after - before, steps,
                                                prefills)


def test_mxl512_clean_at_auto_fires_at_off(tier_arts):
    for t, clean in (("auto", True), ("off", False)):
        with config.override(kernel_tier=t):
            sess = GenerateSession(tier_arts[t][0], auto_start=False,
                                   timeout_ms=0)
            diags = sess.check_attention_discipline()
            # the cache-discipline and spec gates stay clean either way
            assert sess.check_discipline() == []
            sess.close(drain=True)
        if clean:
            assert diags == [], [str(d) for d in diags]
        else:
            assert diags and all(d.rule == "MXL512" for d in diags)
            assert "softmax exponential" in str(diags[0])


# ---------------------------------------------------------------------------
# TPU-platform export census: the kernels actually lower via Mosaic
# ---------------------------------------------------------------------------

def _tpu_census(fn, *args):
    from jax import export
    with tier.force_compiled():
        exp = export.export(jax.jit(fn), platforms=["tpu"])(*args)
    return hlo_stats.pallas_kernel_names(exp.mlir_module())


@pytest.mark.skipif(jax.devices()[0].platform != "cpu",
                    reason="chip-free export census is CPU-host-defined")
def test_export_census_decode_and_draft_verify_modules(params8):
    SDS = jax.ShapeDtypeStruct
    i32, f32 = jnp.int32, jnp.float32
    S, MP = SPEC8.max_slots, SPEC8.max_pages_per_slot
    L, C, R = SPEC8.num_layers, SPEC8.dim, SPEC8.cache_rows
    pages = SDS((L, R, C), f32)
    draft = dm.quantize_decoder_params(params8)
    with config.override(kernel_tier="auto"):
        tier.reset_stats()
        dec = _tpu_census(
            dm.make_decode(params8, SPEC8),
            SDS((S, 1), i32), SDS((S,), i32), SDS((S, MP), i32),
            SDS((S,), f32), SDS((S,), i32), pages, pages)
        ver = _tpu_census(
            dm.make_draft_verify(params8, draft, SPEC8, 3),
            SDS((S, 1), i32), SDS((S,), i32), SDS((S, MP), i32),
            SDS((S,), f32), SDS((S,), i32), pages, pages, pages, pages)
        st = tier.stats()
    # one paged kernel per layer in the decode step; the verifier runs
    # target AND draft stacks (draft token-steps + (k+1)-window verify)
    assert dec.get("mxk_flash_attn_paged", 0) == SPEC8.num_layers, dec
    assert ver.get("mxk_flash_attn_paged", 0) > SPEC8.num_layers, ver
    assert st["dispatch"].get("flash_attn_paged", 0) >= 2 * SPEC8.num_layers


@pytest.mark.skipif(jax.devices()[0].platform != "cpu",
                    reason="chip-free export census is CPU-host-defined")
def test_export_census_artifact_meta_carries_kernels(tmp_path, params8):
    with config.override(kernel_tier="auto"):
        with tier.force_compiled():
            meta = serving.export_generate(
                params8, SPEC8, str(tmp_path / "m.gen.mxtpu"),
                platforms=["tpu"])
    kt = meta["kernel_tier"]
    assert kt["tier"] == "auto" and "tuning_fingerprint" in kt
    assert kt["pallas_kernels"].get("mxk_flash_attn_paged", 0) \
        >= SPEC8.num_layers, kt


# ---------------------------------------------------------------------------
# graph fusion + fused train step: the GPT path picks the kernel up
# ---------------------------------------------------------------------------

def _naive_attn_bind(b=2, h=2, t=32, d=8, scale=None):
    """The naive spelling graph_fuse matches: batch_dot(softmax(scale *
    batch_dot(q, k, transpose_b=True)), v) over (B*H, T, D)."""
    rng = np.random.RandomState(11)
    q = sym.Variable("q")
    k = sym.Variable("k")
    v = sym.Variable("v")
    s = sym.batch_dot(q, k, transpose_b=True) \
        * (1.0 / math.sqrt(d) if scale is None else scale)
    out = sym.batch_dot(sym.softmax(s, axis=-1), v)
    args = {n: mx.nd.array(rng.randn(b * h, t, d).astype(np.float32))
            for n in ("q", "k", "v")}
    grads = {n: mx.nd.zeros(a.shape) for n, a in args.items()}
    return out.bind(mx.cpu(), args, args_grad=grads)


def test_graph_fuse_naive_attention_parity_and_dispatch():
    def run(tier_val):
        with config.override(kernel_tier=tier_val):
            tier.reset_stats()
            ex = _naive_attn_bind()
            out = ex.forward(is_train=True)[0]
            ex.backward(mx.nd.ones(out.shape))
            st = dict(tier.stats()["dispatch"])
        return ([out.asnumpy()] + [g.asnumpy() for g in ex.grad_arrays],
                st)

    off, _ = run("off")
    auto, st = run("auto")
    assert st.get("flash_attn", 0) >= 1, st
    for a, b in zip(off, auto):
        assert float(np.max(np.abs(a - b))) < 2e-5


def test_graph_fuse_wrong_scale_falls_back():
    with config.override(kernel_tier="auto"):
        tier.reset_stats()
        ex = _naive_attn_bind(scale=0.5)     # not 1/sqrt(d)
        ex.forward(is_train=True)
        st = tier.stats()
    assert st["dispatch"].get("flash_attn", 0) == 0
    assert any("1/sqrt(d)" in k for k in st["fallback"]), st["fallback"]


def _gpt_attn_module(batch=4, seq=16, embed=32, heads=4):
    """A miniature of the example GPT's attention block through the
    Module fused train step (examples/train_transformer_lm.py spelling:
    F.contrib.FlashAttention over head-split projections)."""
    from mxnet_tpu.io import DataDesc
    data = mx.sym.Variable("data")               # (B, T, C)
    qkv = mx.sym.FullyConnected(data, num_hidden=3 * embed, flatten=False,
                                name="attn_qkv")
    qkv = mx.sym.reshape(qkv, shape=(0, 0, heads, 3, embed // heads))
    qkv = mx.sym.transpose(qkv, axes=(3, 0, 2, 1, 4))  # (3, B, H, T, D)
    q = mx.sym.squeeze(mx.sym.slice_axis(qkv, axis=0, begin=0, end=1),
                       axis=0)
    k = mx.sym.squeeze(mx.sym.slice_axis(qkv, axis=0, begin=1, end=2),
                       axis=0)
    v = mx.sym.squeeze(mx.sym.slice_axis(qkv, axis=0, begin=2, end=3),
                       axis=0)
    o = mx.sym.contrib.FlashAttention(q, k, v, causal=True)
    o = mx.sym.transpose(o, axes=(0, 2, 1, 3))
    o = mx.sym.reshape(o, shape=(0, 0, -3))
    o = mx.sym.mean(o, axis=1)
    o = mx.sym.FullyConnected(o, num_hidden=8, name="head")
    net = mx.sym.SoftmaxOutput(o, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind([DataDesc("data", (batch, seq, embed))],
             [DataDesc("softmax_label", (batch,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore="tpu_sync", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    assert mod._fused is not None, "fused step did not engage"
    return mod


@pytest.mark.skipif(jax.devices()[0].platform != "cpu",
                    reason="chip-free export census is CPU-host-defined")
def test_export_census_fused_train_step_has_flash_attn():
    from jax import export
    with config.override(kernel_tier="auto"):
        tier.reset_stats()
        mod = _gpt_attn_module()
        fused = mod._fused
        ex = mod._exec
        npar = len(fused.param_names)
        params, rest = fused.split_args(ex._arg_vals())
        args = (params, rest, ex._aux_vals(), mod._fused_opt_state, None,
                jnp.zeros((npar,), jnp.float32),
                jnp.zeros((npar,), jnp.float32),
                np.float32(1.0), np.int32(1), jax.random.PRNGKey(0))
        with tier.force_compiled():
            exp = export.export(fused._jitted, platforms=["tpu"])(*args)
        st = tier.stats()
    kernels = hlo_stats.pallas_kernel_names(exp.mlir_module())
    assert kernels.get("mxk_flash_attn", 0) >= 1, kernels
    assert st["dispatch"].get("flash_attn", 0) >= 1


# ---------------------------------------------------------------------------
# satellite: speculation-depth policy, property-tested chip-free
# ---------------------------------------------------------------------------

def test_speculation_depth_monotone_in_cost_ratio():
    from mxnet_tpu import perfmodel
    t_verify = 1.0
    last = None
    for t_draft in (2.0, 1.0, 0.5, 0.2, 0.1, 0.02, 0.005):
        k = perfmodel.speculation_depth(t_draft, t_verify, max_k=8)
        if last is not None:
            assert k >= last, "k must not shrink as drafts get cheaper"
        last = k
    assert perfmodel.speculation_depth(1e-6, 1.0, max_k=8) == 8
    assert perfmodel.speculation_depth(10.0, 1.0, max_k=8) == 1


def test_speculation_depth_clamps_to_window():
    from mxnet_tpu import perfmodel
    for cap in (1, 2, 3, 5):
        assert 1 <= perfmodel.speculation_depth(0.01, 1.0,
                                                max_k=cap) <= cap


def test_suggest_speculation_depth_respects_spec_window():
    k = dm.suggest_speculation_depth(SPEC8)
    assert 1 <= k <= min(8, SPEC8.max_prompt_len)
    # the spec window is the binding cap: a tiny prompt window clamps it
    tight = SPEC8._replace(max_prompt_len=2)
    assert dm.suggest_speculation_depth(tight) <= 2


def test_suggest_speculation_depth_monotone_in_draft_ratio():
    last = None
    for ratio in (1.0, 0.5, 0.25, 0.1, 0.02):
        k = dm.suggest_speculation_depth(SPEC8, draft_bytes_ratio=ratio)
        if last is not None:
            assert k >= last, "cheaper draft must not shrink k"
        last = k
