"""Int8 post-training quantization (mxnet_tpu.quant) — all chip-free.

The acceptance properties of the quantization pipeline
(docs/quantization.md):

* calibration is DETERMINISTIC — same data, same checkpoint -> the same
  bit-exact scale fingerprint, regardless of engine depth — and performs
  exactly ONE device->host transfer regardless of batch count (the PR-3
  device-carry discipline, witnessed by the profiler sync counters);
* the rewrite quantizes every eligible site and reports every refusal
  with its reason; the int8 weight payload is <= 0.3x the f32 one;
* the ``format_version`` 4 artifact round-trips bitwise (save -> load ->
  serve twice == same bits) and its lowered StableHLO passes the MXL509
  all-int8 gate (every quantizable matmul/conv accumulates in i32, no
  dequantize-before-matmul);
* quantized outputs track f32 (argmax agreement on the probe batch).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import config as _config
from mxnet_tpu import profiler, quant, serving
from mxnet_tpu.analysis import hlo_passes

BATCH = 4


def _net():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4,
                             pad=(1, 1), name="c1")
    net = mx.sym.BatchNorm(net, name="bn1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


@pytest.fixture(scope="module")
def model():
    sym = _net()
    rng = np.random.RandomState(0)
    shapes, _, aux_shapes = sym.infer_shape(data=(2, 1, 8, 8))
    args = {n: mx.nd.array(rng.uniform(-0.3, 0.3, s).astype("f4"))
            for n, s in zip(sym.list_arguments(), shapes)
            if n not in ("data", "softmax_label")}
    aux = {n: mx.nd.array(np.ones(s, "f4") if "var" in n
                          else np.zeros(s, "f4"))
           for n, s in zip(sym.list_auxiliary_states(), aux_shapes)}
    return {"sym": sym, "args": args, "aux": aux}


def _calib(seed=5, n=3):
    rng = np.random.RandomState(seed)
    return [{"data": rng.randn(BATCH, 1, 8, 8).astype("f4")}
            for _ in range(n)]


@pytest.fixture(scope="module")
def qart(model, tmp_path_factory):
    path = str(tmp_path_factory.mktemp("quant") / "m.int8.mxtpu")
    meta = quant.export_quantized(model["sym"], model["args"],
                                  model["aux"], _calib(),
                                  {"data": (None, 1, 8, 8)}, path)
    return {"path": path, "meta": meta}


# ---------------------------------------------------------------------------
# calibration: determinism + the one-d2h budget
# ---------------------------------------------------------------------------

def test_calibration_is_deterministic_and_syncs_exactly_once(model):
    profiler.reset_sync_counters()
    c1 = quant.calibrate(model["sym"], model["args"], model["aux"],
                         _calib(n=4))
    counters = profiler.sync_counters()
    # the whole pass — 4 batches, conv + fc sites — moves device data to
    # host exactly ONCE: the batched fetch of the folded amax carry
    assert counters["d2h"] == 1, counters

    # same data -> bit-exact fingerprint, and engine depth must not
    # change WHAT was computed (it only changes when the host waits)
    c2 = quant.calibrate(model["sym"], model["args"], model["aux"],
                         _calib(n=4))
    with _config.override(engine_depth=1):
        c3 = quant.calibrate(model["sym"], model["args"], model["aux"],
                             _calib(n=4))
    assert c1.fingerprint() == c2.fingerprint() == c3.fingerprint()
    assert set(c1.act_scale) == {"c1", "fc"}

    # more data widens (or keeps) the observed range — never invents one
    c_less = quant.calibrate(model["sym"], model["args"], model["aux"],
                             _calib(n=1))
    for name in c1.act_amax:
        assert c1.act_amax[name] >= c_less.act_amax[name]


def test_find_sites_reports_every_refusal_with_reason(model):
    sites, skipped = quant.find_sites(model["sym"], model["args"],
                                      excluded=("fc",))
    assert [s.name for s in sites] == ["c1"]
    assert "fc" in skipped and "excluded" in skipped["fc"]


# ---------------------------------------------------------------------------
# the v4 artifact: payload, round trip, MXL509
# ---------------------------------------------------------------------------

def test_quantized_artifact_weight_payload_and_sites(qart):
    rep = qart["meta"]["quant"]
    assert qart["meta"]["format_version"] == 4
    assert sorted(rep["sites"]) == ["c1", "fc"]
    assert rep["skipped"] == {}
    wb = rep["weight_bytes"]
    assert wb["int8"] <= 0.3 * wb["f32"], wb
    assert rep["calibration"]["fingerprint"]


def test_round_trip_is_bitwise_stable(qart, model, tmp_path):
    m1 = serving.load_artifact(qart["path"])
    assert m1.quantized is True
    rng = np.random.RandomState(9)
    x = rng.randn(BATCH, 1, 8, 8).astype("f4")
    out_a = np.asarray(m1.predict(data=x)[0])
    out_b = np.asarray(m1.predict(data=x)[0])
    assert (out_a == out_b).all()                # static scales: no drift
    m2 = serving.load_artifact(qart["path"])     # fresh load, same bits
    assert (np.asarray(m2.predict(data=x)[0]) == out_a).all()

    # ...and tracks f32: same argmax on the probe batch
    f32_path = str(tmp_path / "rt_f32.mxtpu")
    serving.export_compiled(model["sym"], model["args"], model["aux"],
                            {"data": (BATCH, 1, 8, 8)}, f32_path)
    ref = np.asarray(
        serving.load_artifact(f32_path).predict(data=x)[0])
    assert (np.argmax(out_a, -1) == np.argmax(ref, -1)).all()
    np.testing.assert_allclose(out_a, ref, atol=0.06)


def test_every_eligible_site_is_int8_in_the_lowering(qart, model,
                                                     tmp_path):
    text = serving.load_artifact(qart["path"])._exp.mlir_module()
    # MXL509: both MXU ops accumulate in i32, and no int8 tensor is
    # upcast back to f32 ahead of a matmul (dequantize-before-matmul)
    diags = hlo_passes.quant_dequant_budget_pass(text, "int8 artifact",
                                                 min_int8_ops=2)
    assert diags == [], [str(d) for d in diags]

    # the same gate flags the UNQUANTIZED artifact: zero int8 MXU ops
    f32_path = str(tmp_path / "f32.mxtpu")
    serving.export_compiled(model["sym"], model["args"], model["aux"],
                            {"data": (BATCH, 1, 8, 8)}, f32_path)
    text = serving.load_artifact(f32_path)._exp.mlir_module()
    diags = hlo_passes.quant_dequant_budget_pass(text, "f32 artifact",
                                                 min_int8_ops=2)
    assert diags and all(d.rule == "MXL509" for d in diags)


def test_quantize_model_cli_round_trip(model, tmp_path):
    """tools/quantize_model.py: checkpoint in, v4 artifact + one JSON
    report line out — the deployment path users actually run."""
    prefix = str(tmp_path / "ckpt")
    mx.model.save_checkpoint(prefix, 0, model["sym"], model["args"],
                             model["aux"])
    out = str(tmp_path / "cli.int8.mxtpu")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.dirname(
             os.path.abspath(__file__))), "tools", "quantize_model.py"),
         "--prefix", prefix, "--epoch", "0",
         "--data-shape", "4,1,8,8", "--out", out,
         "--calib-batches", "3", "--platform", "cpu"],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    rep = json.loads(r.stdout.strip().splitlines()[-1])
    assert rep["format_version"] == 4
    assert sorted(rep["sites"]) == ["c1", "fc"]
    m = serving.load_artifact(out)
    assert m.quantized is True
    x = np.zeros((4, 1, 8, 8), "f4")
    assert np.asarray(m.predict(data=x)[0]).shape == (4, 3)
