"""AOT inference export (VERDICT r3 #7 missing item — the TensorRT-analog
slot, reference src/executor/trt_graph_executor.cc): freeze, serialize,
reload WITHOUT the symbol machinery, predict, match the live executor.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _trained_pair(tmp_path, with_bn=True):
    rng = np.random.RandomState(0)
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, pad=(1, 1),
                             name="c1")
    if with_bn:
        net = mx.sym.BatchNorm(net, name="bn1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    X = rng.randn(32, 1, 8, 8).astype("f4")
    Y = rng.randint(0, 3, (32,)).astype("f4")
    it = mx.io.NDArrayIter(X, Y, batch_size=8, label_name="softmax_label")
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=1, kvstore="tpu_sync", optimizer="sgd",
            optimizer_params={"learning_rate": 0.05},
            initializer=mx.initializer.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 1)
    return prefix, net, mod


def test_export_and_reload_matches_live(tmp_path):
    prefix, net, mod = _trained_pair(tmp_path)
    sym, args, aux = mx.model.load_checkpoint(prefix, 1)
    art = str(tmp_path / "m.mxtpu")
    meta = mx.serving.export_compiled(sym, args, aux,
                                      {"data": (4, 1, 8, 8)}, art)
    assert meta["inputs"][0]["shape"] == [4, 1, 8, 8]
    assert os.path.getsize(art) > 100

    rng = np.random.RandomState(1)
    x = rng.randn(4, 1, 8, 8).astype("f4")

    cm = mx.serving.CompiledModel.load(art)
    out = cm.predict(data=x)[0]
    assert out.shape == (4, 3)
    np.testing.assert_allclose(np.asarray(out).sum(axis=1), np.ones(4),
                               rtol=1e-5)

    # parity with the live executor on the same params
    m2 = mx.mod.Module(sym)
    m2.bind([("data", (4, 1, 8, 8))], [("softmax_label", (4,))],
            for_training=False)
    m2.set_params(args, aux)
    from mxnet_tpu.io import DataBatch
    m2.forward(DataBatch(data=[mx.nd.array(x)]), is_train=False)
    live = m2.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(np.asarray(out), live, rtol=1e-5, atol=1e-6)


def test_export_rejects_unbound_args(tmp_path):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    with pytest.raises(mx.base.MXNetError):
        mx.serving.export_compiled(net, {}, {}, {"data": (1, 4)},
                                   str(tmp_path / "x.mxtpu"))


def test_load_rejects_garbage(tmp_path):
    p = str(tmp_path / "junk.mxtpu")
    with open(p, "wb") as f:
        f.write(b"NOTMAGIC" + b"\0" * 32)
    with pytest.raises(mx.base.MXNetError):
        mx.serving.CompiledModel.load(p)


def test_compile_model_cli(tmp_path):
    prefix, _, _ = _trained_pair(tmp_path, with_bn=False)
    art = str(tmp_path / "cli.mxtpu")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "compile_model.py"),
         "--prefix", prefix, "--epoch", "1", "--data-shape", "2,1,8,8",
         "--out", art, "--platform", "cpu"],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    cm = mx.serving.CompiledModel.load(art)
    out = cm(np.random.rand(2, 1, 8, 8).astype("f4"))[0]
    assert out.shape == (2, 3)


def test_cross_platform_tpu_export_from_cpu_host(tmp_path):
    """The artifact can target TPU from a CPU build host (the
    cross-compile the reference's TensorRT path cannot do). Loading it
    on a mismatched backend fails FAST with an actionable message, not a
    deep XLA crash at call time; allow_platform_mismatch=True keeps the
    inspect/relay path open."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    args = {"fc_weight": mx.nd.array(np.ones((4, 8), "f4")),
            "fc_bias": mx.nd.zeros((4,))}
    art = str(tmp_path / "tpu.mxtpu")
    meta = mx.serving.export_compiled(net, args, {}, {"data": (2, 8)},
                                      art, platforms=["tpu"])
    assert meta["platforms"] == ["tpu"]
    with pytest.raises(mx.base.MXNetError) as ei:
        mx.serving.CompiledModel.load(art)    # cpu backend, tpu artifact
    msg = str(ei.value)
    assert "tpu" in msg and "cpu" in msg and "re-export" in msg
    cm = mx.serving.CompiledModel.load(art, allow_platform_mismatch=True)
    assert cm.meta["platforms"] == ["tpu"]    # runs only on a tpu backend


def test_predict_validates_shape_dtype_naming_input(tmp_path):
    """VERDICT-style satellite: a shape/dtype mismatch must be a clear
    MXNetError naming the offending input, not an opaque XLA error out
    of exp.call."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    args = {"fc_weight": mx.nd.array(np.ones((3, 6), "f4")),
            "fc_bias": mx.nd.zeros((3,))}
    art = str(tmp_path / "v.mxtpu")
    mx.serving.export_compiled(net, args, {}, {"data": (2, 6)}, art)
    cm = mx.serving.CompiledModel.load(art)

    # wrong trailing dim
    with pytest.raises(mx.base.MXNetError) as ei:
        cm.predict(data=np.zeros((2, 7), "f4"))
    assert "'data'" in str(ei.value) and "(2, 7)" in str(ei.value)
    # wrong rank
    with pytest.raises(mx.base.MXNetError) as ei:
        cm.predict(data=np.zeros((2, 6, 1), "f4"))
    assert "'data'" in str(ei.value) and "rank" in str(ei.value)
    # fixed artifact: wrong batch is named too
    with pytest.raises(mx.base.MXNetError) as ei:
        cm.predict(data=np.zeros((3, 6), "f4"))
    assert "'data'" in str(ei.value)
    # unsafe dtype refuses; same-kind dtype casts
    with pytest.raises(mx.base.MXNetError) as ei:
        cm.predict(data=np.zeros((2, 6), "complex64"))
    assert "dtype" in str(ei.value) and "'data'" in str(ei.value)
    out = cm.predict(data=np.zeros((2, 6), "f8"))   # f8 -> f4 same-kind
    assert np.asarray(out[0]).shape == (2, 3)
    # wrong input NAME
    with pytest.raises(mx.base.MXNetError) as ei:
        cm.predict(input=np.zeros((2, 6), "f4"))
    assert "missing" in str(ei.value) and "unexpected" in str(ei.value)


def test_dynamic_batch_export_serves_any_batch(tmp_path):
    """dynamic_batch=True: ONE artifact, any concrete batch size, and
    bucketed CompiledModel calls chunk past the largest bucket."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(0)
    args = {"fc_weight": mx.nd.array(rng.randn(3, 6).astype("f4")),
            "fc_bias": mx.nd.zeros((3,))}
    art = str(tmp_path / "dyn.mxtpu")
    meta = mx.serving.export_compiled(net, args, {}, {"data": (None, 6)},
                                      art)
    assert meta["dynamic_batch"] is True
    assert meta["inputs"][0]["shape"] == [None, 6]
    cm = mx.serving.CompiledModel.load(art)
    for bs in (1, 3, 8):
        out = cm.predict(data=rng.randn(bs, 6).astype("f4"))
        assert np.asarray(out[0]).shape == (bs, 3)
    # bucketed: batch 11 > max bucket 4 chunks through the 4-engine
    cmb = mx.serving.CompiledModel.load(art, buckets=(1, 4))
    x = rng.randn(11, 6).astype("f4")
    got = np.asarray(cmb.predict(data=x)[0])
    ref = np.asarray(cm.predict(data=x)[0])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    # fixed artifact refuses a multi-bucket set with a clear message
    fixed = str(tmp_path / "fix.mxtpu")
    mx.serving.export_compiled(net, args, {}, {"data": (2, 6)}, fixed)
    with pytest.raises(mx.base.MXNetError) as ei:
        mx.serving.CompiledModel.load(fixed, buckets=(1, 4))
    assert "dynamic_batch" in str(ei.value)


def test_int8_model_exports_and_serves(tmp_path):
    """Quantized graphs are ordinary structure: the whole int8 pipeline
    stages out to one AOT artifact (docs/serving.md workflow)."""
    from mxnet_tpu.contrib import quantization as Q
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, name="c1")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(0)
    shapes, _, _ = sym.infer_shape(data=(2, 3, 8, 8))
    args = {n: mx.nd.array(rng.uniform(-0.2, 0.2, s).astype("f4"))
            for n, s in zip(sym.list_arguments(), shapes)
            if n not in ("data", "softmax_label")}
    X = rng.rand(32, 3, 8, 8).astype("f4")
    it = mx.io.NDArrayIter(X, np.zeros(32, "f4"), batch_size=16,
                           label_name="softmax_label")
    qsym, qargs, qaux = Q.quantize_model(sym, args, {}, calib_data=it,
                                         calib_mode="naive",
                                         num_calib_examples=16)
    art = str(tmp_path / "q.mxtpu")
    mx.serving.export_compiled(qsym, qargs, qaux, {"data": (2, 3, 8, 8)},
                               art)
    out = np.asarray(mx.serving.CompiledModel.load(art)(X[:2])[0])
    assert out.shape == (2, 3)
    # the artifact must match the LIVE quantized executor bit-for-bit-ish
    ex = qsym.bind(mx.cpu(), {**qargs, "data": mx.nd.array(X[:2]),
                              "softmax_label": mx.nd.zeros((2,))})
    ex.forward()
    np.testing.assert_allclose(out, ex.outputs[0].asnumpy(),
                               rtol=1e-5, atol=1e-6)
    # and stay in the fp32 model's neighborhood (quantization error only)
    fex = sym.bind(mx.cpu(), {**args, "data": mx.nd.array(X[:2]),
                              "softmax_label": mx.nd.zeros((2,))})
    fex.forward()
    assert np.abs(out - fex.outputs[0].asnumpy()).max() < 0.1
