"""Threaded ImageRecordIter (io/image_record_iter.py): decode/augment
workers over the native dependency engine + device prefetch queue —
the reference's ImageRecordIOParser2 + PrefetcherIter path
(src/io/iter_image_recordio_2.cc:677, iter_prefetcher.h:47)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.io import ImageRecordIter

N_IMAGES = 37
SIDE = 40


@pytest.fixture(scope="module")
def rec_file(tmp_path_factory):
    """Small .rec of solid-color JPEGs; label i encodes the color level."""
    import cv2
    d = tmp_path_factory.mktemp("rec")
    rec_path = str(d / "data.rec")
    idx_path = str(d / "data.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(N_IMAGES):
        img = np.full((SIDE, SIDE, 3), i * 5 % 250, np.uint8)
        ok, buf = cv2.imencode(".png", img)  # lossless: values must survive
        assert ok
        header = recordio.IRHeader(0, float(i), i, 0)
        w.write_idx(i, recordio.pack(header, buf.tobytes()))
    w.close()
    return rec_path


def test_basic_iteration(rec_file):
    it = ImageRecordIter(rec_file, data_shape=(3, 32, 32), batch_size=8,
                         preprocess_threads=3, round_batch=True)
    seen_labels = []
    nb = 0
    for batch in it:
        assert batch.data[0].shape == (8, 3, 32, 32)
        assert batch.label[0].shape == (8,)
        labels = batch.label[0].asnumpy()
        data = batch.data[0].asnumpy()
        # each image is solid-color: every pixel equals label*5 % 250
        for j in range(8):
            expected = (labels[j] * 5) % 250
            assert np.all(data[j] == expected), (labels[j], data[j][0, 0, 0])
        seen_labels.extend(labels.tolist())
        nb += 1
    # round_batch wraps the tail: ceil(37/8)=5 batches, 40 samples
    assert nb == 5 and len(seen_labels) == 40
    assert set(int(x) for x in seen_labels) == set(range(N_IMAGES))
    it.close()


def test_epochs_and_shuffle(rec_file):
    it = ImageRecordIter(rec_file, data_shape=(3, 32, 32), batch_size=8,
                         shuffle=True, preprocess_threads=2, seed=11)
    def epoch_labels():
        out = []
        for b in it:
            out.extend(b.label[0].asnumpy().tolist())
        it.reset()
        return out
    e0, e1 = epoch_labels(), epoch_labels()
    assert e0 != e1, "shuffle must reorder between epochs"
    assert set(int(x) for x in e0) == set(range(N_IMAGES))
    it.close()


def test_augment_mean_std_mirror(rec_file):
    it = ImageRecordIter(rec_file, data_shape=(3, 32, 32), batch_size=4,
                         mean_r=10.0, mean_g=10.0, mean_b=10.0,
                         std_r=2.0, std_g=2.0, std_b=2.0,
                         preprocess_threads=2)
    b = next(iter(it))
    labels = b.label[0].asnumpy()
    data = b.data[0].asnumpy()
    for j in range(4):
        expected = ((labels[j] * 5) % 250 - 10.0) / 2.0
        np.testing.assert_allclose(data[j], expected, rtol=1e-6)
    it.close()


def test_sharding(rec_file):
    seen = []
    for part in range(2):
        it = ImageRecordIter(rec_file, data_shape=(3, 32, 32), batch_size=4,
                             num_parts=2, part_index=part, round_batch=False,
                             preprocess_threads=2)
        for b in it:
            seen.extend(b.label[0].asnumpy().tolist())
        it.close()
    # parts are disjoint and cover all full batches of each shard
    assert len(seen) == len(set(seen))


def test_uses_native_engine_when_available(rec_file):
    from mxnet_tpu import runtime
    it = ImageRecordIter(rec_file, data_shape=(3, 32, 32), batch_size=8,
                         preprocess_threads=2)
    if runtime.available():
        assert it._engine is not None, \
            "native engine must schedule the pipeline when libmxtpu exists"
    next(iter(it))
    it.close()


def test_fit_from_record_iter(rec_file):
    """End-to-end: Module.fit consumes the threaded iterator."""
    data = mx.sym.Variable("data")
    net = mx.sym.Flatten(data)
    net = mx.sym.FullyConnected(net, num_hidden=8, name="fc1")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    it = ImageRecordIter(rec_file, data_shape=(3, 32, 32), batch_size=8,
                         scale=1.0 / 255, preprocess_threads=2)
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.01})
    it.close()


def test_corrupt_record_raises_not_hangs(tmp_path):
    """A corrupt image must surface as an error from next(), not hang the
    consumer or stage garbage (round-3 review finding)."""
    rec_path = str(tmp_path / "bad.rec")
    idx_path = str(tmp_path / "bad.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    header = recordio.IRHeader(0, 0.0, 0, 0)
    for i in range(8):
        w.write_idx(i, recordio.pack(header, b"not-a-jpeg-at-all"))
    w.close()
    it = ImageRecordIter(rec_path, data_shape=(3, 16, 16), batch_size=4,
                         preprocess_threads=2)
    with pytest.raises(Exception) as ei:
        for _ in it:
            pass
    assert "pipeline failed" in str(ei.value) or "corrupt" in str(ei.value)
    it.close()


def test_round_batch_wraps_small_dataset(rec_file):
    """batch_size > dataset: round_batch must wrap repeatedly (review
    finding: single wrap yielded zero batches)."""
    it = ImageRecordIter(rec_file, data_shape=(3, 32, 32), batch_size=100,
                         preprocess_threads=2, round_batch=True)
    batches = list(it)
    assert len(batches) == 1 and batches[0].data[0].shape[0] == 100
    it.close()


def test_host_arena_batches_match_plain_alloc(rec_file):
    """The pooled staging arena (src/storage.cc buffers, recycled
    round-robin) must be invisible to correctness: identical batches to
    the per-batch-malloc path across multiple epochs."""
    from mxnet_tpu.io import ImageRecordIter

    from mxnet_tpu.io import image_record_iter as iri

    def collect(force_plain):
        if force_plain:
            # disable the arena BEFORE the feeder starts (releasing it
            # after construction would race the running pipeline)
            import unittest.mock as mock
            with mock.patch.object(iri, "_HostArena",
                                   side_effect=MemoryError):
                it = ImageRecordIter(rec_file, data_shape=(3, 16, 16),
                                     batch_size=4, preprocess_threads=2,
                                     prefetch_buffer=2, seed=7)
            assert it._arena is None
        else:
            it = ImageRecordIter(rec_file, data_shape=(3, 16, 16),
                                 batch_size=4, preprocess_threads=2,
                                 prefetch_buffer=2, seed=7)
        out = []
        for _ in range(2):
            for b in it:
                out.append(b.data[0].asnumpy().copy())
            it.reset()
        arena = it._arena
        it.close()
        return out, arena

    pooled, arena = collect(force_plain=False)
    if arena is not None:   # native runtime present: pool really backed it
        from mxnet_tpu.io import image_record_iter as iri
        # close() returned the slots to the per-shape cache for reuse
        assert len(iri._SLOT_CACHE.get((4, 3, 16, 16), [])) >= 6
    plain, _ = collect(force_plain=True)
    assert len(pooled) == len(plain) and len(pooled) > 0
    for a, b in zip(pooled, plain):
        np.testing.assert_array_equal(a, b)


def test_num_batches_attribute(rec_file):
    it = ImageRecordIter(rec_file, data_shape=(3, 32, 32), batch_size=8,
                         round_batch=True)
    assert it.num_batches == 5          # ceil(37/8)
    assert sum(1 for _ in it) == 5
    it.close()
    it = ImageRecordIter(rec_file, data_shape=(3, 32, 32), batch_size=8,
                         round_batch=False)
    assert it.num_batches == 4          # floor(37/8)
    assert sum(1 for _ in it) == 4
    it.close()


def test_pad_then_crop_augmentation(rec_file):
    # pad=4 then CENTER crop back to 32 recovers the original exactly
    # (the reference pad/crop recipe is identity without rand_crop)
    it = ImageRecordIter(rec_file, data_shape=(3, 32, 32), batch_size=8,
                         pad=4, fill_value=7, rand_crop=False)
    batch = next(iter(it))
    img = batch.data[0].asnumpy()[0]
    lab = batch.label[0].asnumpy()[0]
    color = (lab * 5) % 250
    assert np.all(img == color)
    it.close()
    # RANDOM crop inside the padded canvas: pixels are only ever the
    # color or the fill, and across a batch some crops hit the border
    it = ImageRecordIter(rec_file, data_shape=(3, 32, 32), batch_size=8,
                         pad=4, fill_value=7, rand_crop=True, seed=3)
    batch = next(iter(it))
    data = batch.data[0].asnumpy()
    labels = batch.label[0].asnumpy()
    fill_seen = False
    for j in range(8):
        c = float((labels[j] * 5) % 250)
        vals = set(np.unique(data[j]))
        assert vals.issubset({7.0, c})
        fill_seen = fill_seen or 7.0 in vals
    assert fill_seen        # at least one off-center crop hit the border
    it.close()
