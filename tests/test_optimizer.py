"""Optimizer tests (parity model: tests/python/unittest/test_optimizer.py —
compare update ops against numpy reference math)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt


def _setup(seed=0, shape=(4, 3)):
    rng = np.random.RandomState(seed)
    w = rng.randn(*shape).astype(np.float32)
    g = rng.randn(*shape).astype(np.float32)
    return mx.nd.array(w), mx.nd.array(g), w, g


def test_sgd_matches_numpy():
    weight, grad, w, g = _setup()
    o = opt.SGD(learning_rate=0.1, wd=0.01, momentum=0.9)
    state = o.create_state(0, weight)
    o.update(0, weight, grad, state)
    mom = -0.1 * (g + 0.01 * w)
    np.testing.assert_allclose(weight.asnumpy(), w + mom, rtol=1e-5)
    w2 = w + mom
    o.update(0, weight, grad, state)
    mom2 = 0.9 * mom - 0.1 * (g + 0.01 * w2)
    np.testing.assert_allclose(weight.asnumpy(), w2 + mom2, rtol=1e-5)


def test_adam_matches_numpy():
    weight, grad, w, g = _setup()
    o = opt.Adam(learning_rate=0.01, wd=0.0)
    state = o.create_state(0, weight)
    o.update(0, weight, grad, state)
    m = 0.1 * g
    v = 0.001 * g * g
    lr_t = 0.01 * np.sqrt(1 - 0.999) / (1 - 0.9)
    expect = w - lr_t * m / (np.sqrt(v) + 1e-8)
    np.testing.assert_allclose(weight.asnumpy(), expect, rtol=1e-4)


def test_adagrad():
    weight, grad, w, g = _setup()
    o = opt.AdaGrad(learning_rate=0.1)
    state = o.create_state(0, weight)
    o.update(0, weight, grad, state)
    h = g * g
    np.testing.assert_allclose(weight.asnumpy(),
                               w - 0.1 * g / np.sqrt(h + 1e-7), rtol=1e-5)


def test_rmsprop():
    weight, grad, w, g = _setup()
    o = opt.RMSProp(learning_rate=0.1, gamma1=0.9)
    state = o.create_state(0, weight)
    o.update(0, weight, grad, state)
    n = 0.1 * g * g
    np.testing.assert_allclose(weight.asnumpy(),
                               w - 0.1 * g / np.sqrt(n + 1e-8), rtol=1e-5)


def test_clip_and_rescale():
    weight, grad, w, g = _setup()
    o = opt.SGD(learning_rate=1.0, rescale_grad=0.5, clip_gradient=0.1)
    o.update(0, weight, grad, None)
    eff = np.clip(g * 0.5, -0.1, 0.1)
    np.testing.assert_allclose(weight.asnumpy(), w - eff, rtol=1e-5)


def test_lr_scheduler_integration():
    from mxnet_tpu.lr_scheduler import FactorScheduler
    sched = FactorScheduler(step=2, factor=0.5)
    o = opt.SGD(learning_rate=1.0, lr_scheduler=sched)
    weight, grad, w, g = _setup()
    for _ in range(5):
        o.update(0, weight, grad, None)
    assert sched.base_lr < 1.0


def test_create_registry():
    assert isinstance(opt.create("sgd"), opt.SGD)
    assert isinstance(opt.create("adam", learning_rate=0.1), opt.Adam)
    with pytest.raises(ValueError):
        opt.create("nosuchopt")


def test_updater_state_dict():
    weight, grad, w, g = _setup()
    o = opt.SGD(learning_rate=0.1, momentum=0.9)
    upd = opt.get_updater(o)
    upd(0, grad, weight)
    blob = upd.get_states()
    upd2 = opt.get_updater(opt.SGD(learning_rate=0.1, momentum=0.9))
    upd2.set_states(blob)
    assert 0 in upd2.states


def test_lr_wd_mult():
    weight, grad, w, g = _setup()
    o = opt.SGD(learning_rate=0.1, param_idx2name={0: "w"})
    o.set_lr_mult({"w": 0.0})
    o.update(0, weight, grad, None)
    np.testing.assert_allclose(weight.asnumpy(), w)


def test_multi_precision():
    rng = np.random.RandomState(0)
    w16 = rng.randn(4).astype(np.float16)
    weight = mx.nd.array(w16, dtype="float16")
    grad = mx.nd.array(rng.randn(4).astype(np.float16), dtype="float16")
    o = opt.SGD(learning_rate=0.1, multi_precision=True)
    state = o.create_state_multi_precision(0, weight)
    o.update_multi_precision(0, weight, grad, state)
    assert weight.dtype == np.float16


def test_schedulers():
    from mxnet_tpu import lr_scheduler as lrs
    s = lrs.MultiFactorScheduler([3, 6], factor=0.1, base_lr=1.0)
    assert s(1) == 1.0
    assert s(4) == pytest.approx(0.1)
    assert s(7) == pytest.approx(0.01)
    p = lrs.PolyScheduler(max_update=10, base_lr=1.0, pwr=1)
    assert p(0) == pytest.approx(1.0)
    assert p(10) == pytest.approx(0.0, abs=1e-6)
    c = lrs.CosineScheduler(max_update=10, base_lr=1.0)
    assert c(0) == pytest.approx(1.0)
    assert c(10) == pytest.approx(0.0, abs=1e-6)
    w = lrs.FactorScheduler(step=100, base_lr=1.0, warmup_steps=5,
                            warmup_begin_lr=0.1)
    assert w(1) < 1.0


def test_lr_mult_from_symbol_attrs():
    """Variable(lr_mult=...) / AttrScope __lr_mult__ reach the update
    rule through sym_info (reference optimizer.py set_lr_mult)."""
    import mxnet_tpu as mx
    w = mx.sym.Variable("w", lr_mult=0.0)
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), weight=w,
                                num_hidden=4, name="fc", no_bias=True)
    out = mx.sym.SoftmaxOutput(net, name="softmax")
    opt = mx.optimizer.create("sgd", learning_rate=1.0, sym=out,
                              param_idx2name={0: "w"})
    assert opt._get_lr(0) == 0.0

    with mx.AttrScope(**{"__lr_mult__": "0.25"}):
        v2 = mx.sym.Variable("v2")
    net2 = mx.sym.FullyConnected(mx.sym.Variable("data"), weight=v2,
                                 num_hidden=4, no_bias=True)
    opt2 = mx.optimizer.create("sgd", learning_rate=1.0, sym=net2,
                               param_idx2name={0: "v2"})
    assert opt2._get_lr(0) == 0.25


def test_wd_mult_bias_default_zero():
    """Reference default: names not ending _weight/_gamma get wd 0."""
    import mxnet_tpu as mx
    opt = mx.optimizer.create(
        "sgd", learning_rate=0.1, wd=0.1,
        param_idx2name={0: "fc_weight", 1: "fc_bias", 2: "bn_gamma",
                        3: "bn_beta"})
    assert opt._get_wd(0) == pytest.approx(0.1)
    assert opt._get_wd(1) == 0.0
    assert opt._get_wd(2) == pytest.approx(0.1)
    assert opt._get_wd(3) == 0.0


def test_frozen_params_through_module_fused():
    """lr_mult=0 params stay frozen through BOTH Module paths (eager
    updater and the fused tpu_sync step)."""
    import mxnet_tpu as mx
    rng = np.random.RandomState(0)
    X = rng.randn(64, 4).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    for kv in ("local", "tpu_sync"):
        w = mx.sym.Variable("frozen_weight", lr_mult=0.0)
        net = mx.sym.FullyConnected(mx.sym.Variable("data"), weight=w,
                                    num_hidden=8, name="fc0", no_bias=True)
        net = mx.sym.Activation(net, act_type="relu")
        out = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(net, num_hidden=2, name="head"),
            name="softmax")
        it = mx.io.NDArrayIter(X, y, batch_size=16)
        mod = mx.mod.Module(out)
        mod.bind(it.provide_data, it.provide_label)
        mod.init_params(mx.initializer.Xavier())
        before = mod.get_params()[0]["frozen_weight"].asnumpy().copy()
        mod.init_optimizer(kvstore=kv, optimizer="sgd",
                           optimizer_params={"learning_rate": 0.5})
        for b in it:
            mod.forward_backward(b)
            mod.update()
        after = mod.get_params()[0]["frozen_weight"].asnumpy()
        np.testing.assert_allclose(before, after, err_msg=kv)
