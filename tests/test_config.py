"""Typed config/flag registry (SURVEY §5: unify env_var.md sprawl +
DMLC_DECLARE_PARAMETER into one introspectable registry)."""
import os
import subprocess
import sys

import pytest

from mxnet_tpu import config


def test_defaults_and_describe():
    rows = {r["name"]: r for r in config.describe()}
    assert rows["enable_x64"]["env"] == "MXNET_ENABLE_X64"
    assert rows["engine_type"]["value"] in ("ThreadedEngine", "NaiveEngine")
    for r in rows.values():
        assert r["doc"]  # every flag is documented


def test_env_parsing_and_reload():
    os.environ["MXNET_CPU_WORKER_NTHREADS"] = "7"
    try:
        config.flags.reload("cpu_worker_nthreads")
        assert config.flags.cpu_worker_nthreads == 7
    finally:
        del os.environ["MXNET_CPU_WORKER_NTHREADS"]
        config.flags.reload("cpu_worker_nthreads")
    assert config.flags.cpu_worker_nthreads == 4


def test_override_context():
    assert config.flags.enforce_determinism is False
    with config.override(enforce_determinism=True):
        assert config.flags.enforce_determinism is True
    assert config.flags.enforce_determinism is False
    with pytest.raises(KeyError):
        with config.override(not_a_flag=1):
            pass


def test_unknown_flag_raises():
    with pytest.raises(AttributeError):
        config.flags.nope


def test_enforce_determinism_blocks_autoseed():
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import mxnet_tpu as mx\n"
        "try:\n"
        "    mx.random.next_key()\n"
        "except RuntimeError as e:\n"
        "    assert 'MXNET_ENFORCE_DETERMINISM' in str(e)\n"
        "    mx.random.seed(7)\n"
        "    mx.random.next_key()\n"  # seeded: fine
        "    print('BLOCKED_THEN_OK')\n")
    env = dict(os.environ, MXNET_ENFORCE_DETERMINISM="1")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stderr
    assert "BLOCKED_THEN_OK" in r.stdout


def test_compile_cache_persists_programs(tmp_path):
    """MXNET_COMPILE_CACHE_DIR: compiled XLA programs persist on disk and
    are reused by later processes (the operator_tune-replacement flag)."""
    cache = str(tmp_path / "xla_cache")
    code = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import mxnet_tpu as mx\n"
        "net = mx.gluon.nn.Dense(8)\n"
        "net.initialize()\n"
        "net.hybridize()\n"
        "y = net(mx.nd.ones((4, 16)))\n"
        "y.asnumpy()\n"
        "print('RAN_OK')\n")
    env = dict(os.environ, MXNET_COMPILE_CACHE_DIR=cache,
               MXNET_COMPILE_CACHE_MIN_COMPILE_SECS="0.0")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, timeout=180)
    assert r.returncode == 0, r.stderr
    assert "RAN_OK" in r.stdout
    entries = os.listdir(cache)
    assert entries, "no programs persisted to the compilation cache"
    # a second process must HIT the cache (jax logs a cache read at debug;
    # cheaper check: the entry set does not grow for the same program)
    r2 = subprocess.run([sys.executable, "-c", code], capture_output=True,
                        text=True, env=env, timeout=180)
    assert r2.returncode == 0, r2.stderr
    assert set(os.listdir(cache)) == set(entries)


def test_misc_parity_modules():
    """util/log/libinfo/rtc parity slots (reference python/mxnet/)."""
    import mxnet_tpu as mx
    import tempfile, os
    d = os.path.join(tempfile.mkdtemp(), "a", "b")
    mx.util.makedirs(d)
    assert os.path.isdir(d)
    lg = mx.log.get_logger("parity_test", level=mx.log.INFO)
    assert lg.level == mx.log.INFO
    assert mx.libinfo.find_lib_path()[0].endswith("libmxtpu.so")
    assert mx.libinfo.find_include_path().endswith("src")
    import pytest as _pytest
    with _pytest.raises(mx.MXNetError, match="pallas"):
        mx.rtc.CudaModule("foo")


def test_generic_registry():
    """mx.registry factory trio (reference registry.py:49-175)."""
    import mxnet_tpu as mx

    class Base:
        def __init__(self, x=1):
            self.x = x

    register = mx.registry.get_register_func(Base, "thing")
    alias = mx.registry.get_alias_func(Base, "thing")
    create = mx.registry.get_create_func(Base, "thing")

    @alias("alt")
    @register
    class MyThing(Base):
        pass

    assert isinstance(create("mything"), MyThing)
    assert isinstance(create("alt", 5), MyThing)
    inst = MyThing(2)
    assert create(inst) is inst
    made = create('["mything", {"x": 7}]')  # JSON form
    assert made.x == 7
    made2 = create({"thing": "mything", "x": 3})
    assert made2.x == 3
    import pytest as _pytest
    with _pytest.raises(AssertionError, match="not registered"):
        create("nope")


def test_log_file_handler_has_no_ansi(tmp_path):
    import mxnet_tpu as mx
    path = str(tmp_path / "run.log")
    lg = mx.log.get_logger("ansi_test", filename=path, level=mx.log.INFO)
    lg.warning("hello")
    for h in lg.handlers:
        h.flush()
    content = open(path).read()
    assert "hello" in content and "\x1b[" not in content


def test_server_role_shims():
    """A server/scheduler-role process exits 0 AT IMPORT (reference
    kvstore_server.py:85 contract) instead of running the training
    script; legacy executor-manager imports point at the SPMD
    replacement."""
    import subprocess, sys
    code = ("import jax; jax.config.update('jax_platforms','cpu');"
            "import mxnet_tpu;"
            "print('MUST NOT REACH: training script ran on a server')")
    env = dict(os.environ, DMLC_ROLE="server")
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 0
    assert "MUST NOT REACH" not in r.stdout
    assert "no parameter servers" in r.stderr

    import mxnet_tpu as mx
    import pytest as _pytest
    with _pytest.raises(mx.MXNetError, match="SPMD"):
        mx.executor_manager.DataParallelExecutorManager()
