"""SPMD data-parallel training through the user APIs.

VERDICT round-1 item 3: `Module(context=[...])` / `fit(kvstore='tpu_sync')`
must actually shard — proven here on the 8-virtual-CPU-device mesh by
(a) numeric parity with single-device training and (b) evidence the
cross-device gradient reduction really happened (per-shard grads differ;
the mesh grad equals their sum).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import test_utils


def _mlp():
    d = mx.sym.Variable("data")
    f1 = mx.sym.FullyConnected(d, num_hidden=16, name="fc1")
    a1 = mx.sym.Activation(f1, act_type="relu")
    f2 = mx.sym.FullyConnected(a1, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(f2, name="softmax")


def _synthetic(batch=32, nfeat=8, nclass=4, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(batch, nfeat).astype(np.float32)
    Y = rng.randint(0, nclass, (batch,)).astype(np.float32)
    return X, Y


def _train(ctx, kvstore, n_steps=4):
    X, Y = _synthetic()
    sym = _mlp()
    mod = mx.mod.Module(sym, context=ctx)
    mod.bind(data_shapes=[("data", X.shape)],
             label_shapes=[("softmax_label", Y.shape)])
    mod.init_params(initializer=mx.init.Xavier(rnd_type="gaussian",
                                               factor_type="in",
                                               magnitude=2.0))
    # deterministic init for parity across runs
    rng = np.random.RandomState(0)
    for name in mod._param_names:
        arr = mod._exec.arg_dict[name]
        arr[:] = mx.nd.array(
            rng.normal(0, 0.1, arr.shape).astype(np.float32))
    mod.init_optimizer(kvstore=kvstore, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5,
                                         "momentum": 0.9})
    from mxnet_tpu.io import DataBatch
    batch = DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(Y)])
    for _ in range(n_steps):
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    return {n: mod._exec.arg_dict[n].asnumpy() for n in mod._param_names}


def test_module_multi_context_parity():
    """4-device dp training must match single-device training bit-for-bit
    (same global batch, same init, deterministic graph)."""
    single = _train(mx.cpu(0), kvstore="local")
    multi = _train([mx.cpu(i) for i in range(4)], kvstore="tpu_sync")
    for name in single:
        np.testing.assert_allclose(single[name], multi[name],
                                   rtol=2e-5, atol=2e-6,
                                   err_msg="param %s diverged" % name)


def test_module_multi_context_actually_shards():
    """The bound executor must hold data sharded across 4 devices and
    replicated parameters."""
    X, Y = _synthetic()
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(4)])
    mod.bind(data_shapes=[("data", X.shape)],
             label_shapes=[("softmax_label", Y.shape)])
    mod.init_params(initializer=mx.init.One())
    exe = mod._exec
    assert exe._mesh is not None and exe._mesh.devices.size == 4
    # writes adopt the written value's placement; the executor re-commits
    # inputs on the next step — run one forward so placement is current
    from mxnet_tpu.io import DataBatch
    mod.forward(DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(Y)]),
                is_train=True)
    data_sh = exe.arg_dict["data"]._data.sharding
    assert len(data_sh.device_set) == 4
    # batch axis actually split: each addressable shard holds batch/4 rows
    shard_shapes = {s.data.shape for s in
                    exe.arg_dict["data"]._data.addressable_shards}
    assert shard_shapes == {(8, 8)}
    w_sh = exe.arg_dict["fc1_weight"]._data
    assert len(w_sh.sharding.device_set) == 4
    assert {s.data.shape for s in w_sh.addressable_shards} == \
        {w_sh.shape}  # replicated: every device holds the full tensor


def test_mesh_grad_is_sum_of_shard_grads():
    """Psum evidence: per-shard grads differ from each other, and the mesh
    gradient equals their sum (SoftmaxOutput's backward seeds sum-style
    cotangents, so the global grad is the sum over shards)."""
    X, Y = _synthetic(batch=16)
    sym = _mlp()
    rng = np.random.RandomState(1)
    init = {}

    def build(ctx, bx, by):
        mod = mx.mod.Module(sym, context=ctx)
        mod.bind(data_shapes=[("data", bx.shape)],
                 label_shapes=[("softmax_label", by.shape)])
        mod.init_params(initializer=mx.init.Zero())
        for name in mod._param_names:
            if name not in init:
                init[name] = rng.normal(
                    0, 0.2, mod._exec.arg_dict[name].shape).astype(np.float32)
            mod._exec.arg_dict[name][:] = mx.nd.array(init[name])
        from mxnet_tpu.io import DataBatch
        mod.forward(DataBatch(data=[mx.nd.array(bx)],
                              label=[mx.nd.array(by)]), is_train=True)
        mod.backward()
        return {n: mod._exec.grad_dict[n].asnumpy()
                for n in mod._param_names}

    mesh_grads = build([mx.cpu(i) for i in range(4)], X, Y)
    shard_grads = [build(mx.cpu(0), X[i * 4:(i + 1) * 4], Y[i * 4:(i + 1) * 4])
                   for i in range(4)]
    for name in mesh_grads:
        # shards see different data, so their grads differ...
        assert not np.allclose(shard_grads[0][name], shard_grads[1][name]), \
            "shard grads identical for %s — test not discriminating" % name
        # ...and the mesh grad is their sum => the all-reduce happened
        total = sum(g[name] for g in shard_grads)
        np.testing.assert_allclose(mesh_grads[name], total,
                                   rtol=1e-4, atol=1e-5,
                                   err_msg="grad %s != sum of shard grads"
                                           % name)


def test_module_fit_multi_context():
    """End to end: Module.fit over a context list converges on a toy
    problem (the reference's multi_lenet.py pattern, shrunk)."""
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype(np.float32)
    W = rng.randn(8, 4).astype(np.float32)
    Y = np.argmax(X @ W, axis=1).astype(np.float32)
    from mxnet_tpu.io import NDArrayIter
    it = NDArrayIter(X, Y, batch_size=16, shuffle=False,
                     label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(4)])
    mod.fit(it, num_epoch=12, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5}, kvstore="tpu_sync",
            initializer=mx.init.Xavier())
    it.reset()
    score = mod.score(it, mx.metric.Accuracy())
    acc = dict(score)["accuracy"] if isinstance(score, list) else score
    assert acc > 0.8, "fit on 4-device mesh failed to learn: acc=%s" % acc


def test_gluon_spmd_training_parity():
    """Gluon: split_and_load over 4 contexts shards the batch over a dp
    mesh; parameters initialized with the ctx list are replicated; training
    matches single-device training."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn

    def run(ctx_list):
        mx.random.seed(7)
        net = nn.Sequential()
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(4))
        net.initialize(mx.init.Xavier(), ctx=ctx_list)
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.3}, kvstore="tpu_sync")
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        rng = np.random.RandomState(0)
        X = rng.randn(32, 8).astype(np.float32)
        Y = rng.randint(0, 4, (32,)).astype(np.float32)
        for _ in range(3):
            losses = []
            for xs, ys in zip(gluon.utils.split_and_load(X, ctx_list),
                              gluon.utils.split_and_load(Y, ctx_list)):
                with mx.autograd.record():
                    out = net(xs)
                    losses.append(loss_fn(out, ys))
            for l in losses:
                l.backward()
            trainer.step(X.shape[0])
        return {name: p.data().asnumpy()
                for name, p in net.collect_params().items()}

    ctx4 = [mx.cpu(i) for i in range(4)]
    single = run([mx.cpu(0)])
    multi = run(ctx4)
    # block name counters differ between runs; compare by position
    for (n1, v1), (n2, v2) in zip(sorted(single.items()),
                                  sorted(multi.items())):
        np.testing.assert_allclose(v1, v2, rtol=2e-5, atol=2e-6,
                                   err_msg="gluon param %s/%s diverged"
                                           % (n1, n2))


def test_gluon_split_and_load_shards():
    from mxnet_tpu import gluon
    ctx4 = [mx.cpu(i) for i in range(4)]
    X = np.arange(64, dtype=np.float32).reshape(16, 4)
    parts = gluon.utils.split_and_load(X, ctx4)
    assert len(parts) == 1  # one global sharded array, not 4 slices
    arr = parts[0]._data
    assert len(arr.sharding.device_set) == 4
    assert {s.data.shape for s in arr.addressable_shards} == {(4, 4)}
    np.testing.assert_array_equal(np.asarray(arr), X)
    # parameters initialized on the same ctx list are replicated
    from mxnet_tpu.gluon import nn
    net = nn.Dense(3)
    net.initialize(ctx=ctx4)
    net(parts[0])  # deferred init completes on first forward
    w = net.weight.data()._data
    assert len(w.sharding.device_set) == 4
    assert {s.data.shape for s in w.addressable_shards} == {w.shape}
    assert net.weight.list_ctx() == ctx4
