"""Symbolic control flow — mx.sym.contrib.foreach / while_loop / cond
(reference python/mxnet/symbol/contrib.py:95-740 over
src/operator/control_flow.cc subgraph ops; here the body subgraph is
interpreted by the executor's evaluator inside lax.scan/while/cond, so
gradients come from jax.vjp through native XLA control flow)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _bind_run(sym, feeds, grad=None):
    args = {k: mx.nd.array(v) for k, v in feeds.items()}
    ex = sym.bind(mx.cpu(), args,
                  args_grad={k: mx.nd.zeros(v.shape)
                             for k, v in feeds.items()} if grad else None)
    ex.forward(is_train=bool(grad))
    outs = [o.asnumpy() for o in ex.outputs]
    if grad:
        ex.backward([mx.nd.array(g) for g in grad])
        return outs, {k: v.asnumpy() for k, v in ex.grad_dict.items()}
    return outs


def test_sym_foreach_cumsum():
    data = mx.sym.Variable("data")
    init = mx.sym.Variable("init")

    def body(x, s):
        new_s = s + x
        return new_s, new_s

    outs, fin = mx.sym.contrib.foreach(body, data, init)
    net = mx.sym.Group([outs, fin])
    x = np.arange(1, 7, dtype=np.float32).reshape(6, 1)
    (o, f) = _bind_run(net, {"data": x, "init": np.zeros((1,), "f4")})
    np.testing.assert_allclose(o.ravel(), np.cumsum(x.ravel()), rtol=1e-6)
    np.testing.assert_allclose(f, [21.0], rtol=1e-6)


def test_sym_foreach_closes_over_outer_weight():
    """Free variables of the body become inputs of the loop node —
    an outer weight used inside the body is trained through the scan."""
    data = mx.sym.Variable("data")
    init = mx.sym.Variable("init")
    w = mx.sym.Variable("w")

    def body(x, s):
        new_s = mx.sym.broadcast_add(mx.sym.broadcast_mul(x, w), s)
        return new_s, new_s

    outs, fin = mx.sym.contrib.foreach(body, data, init)
    loss = mx.sym.sum(fin)
    assert "w" in loss.list_arguments()
    T = 4
    x = np.ones((T, 3), np.float32) * 2.0
    feeds = {"data": x, "init": np.zeros((3,), "f4"),
             "w": np.ones((3,), "f4")}
    (out,), grads = _bind_run(loss, feeds, grad=[np.ones((), "f4")])
    # fin = sum_t x_t * w  -> d/dw = sum_t x_t = 8 per element
    np.testing.assert_allclose(out, 24.0, rtol=1e-6)
    np.testing.assert_allclose(grads["w"], np.full(3, 8.0), rtol=1e-6)


def test_sym_foreach_multi_data_multi_state():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    s1 = mx.sym.Variable("s1")
    s2 = mx.sym.Variable("s2")

    def body(xs, states):
        xa, xb = xs
        p, q = states
        return [xa + p, xb * q], [p + xa, q * xb]

    outs, fins = mx.sym.contrib.foreach(body, [a, b], [s1, s2])
    net = mx.sym.Group(list(outs) + list(fins))
    A = np.ones((3, 2), np.float32)
    B = np.full((3, 2), 2.0, np.float32)
    res = _bind_run(net, {"a": A, "b": B,
                          "s1": np.zeros(2, "f4"), "s2": np.ones(2, "f4")})
    np.testing.assert_allclose(res[0][:, 0], [1, 2, 3])       # cumsum-ish
    np.testing.assert_allclose(res[1][:, 0], [2, 4, 8])       # geometric
    np.testing.assert_allclose(res[2], [3, 3])                # final s1
    np.testing.assert_allclose(res[3], [8, 8])                # final s2


def test_sym_while_loop_counts_and_pads():
    def cond_fn(i, s):
        return i < 3

    def func(i, s):
        return s + i, (i + 1, s + i)

    outs, fin = mx.sym.contrib.while_loop(
        cond_fn, func, [mx.sym.Variable("i"), mx.sym.Variable("s")],
        max_iterations=5)
    net = mx.sym.Group([outs, fin[0], fin[1]])
    res = _bind_run(net, {"i": np.zeros((1,), "f4"),
                          "s": np.zeros((1,), "f4")})
    # steps: s+i = 0, 1, 3; padded with zeros to 5
    np.testing.assert_allclose(res[0].ravel(), [0, 1, 3, 0, 0])
    np.testing.assert_allclose(res[1], [3.0])
    np.testing.assert_allclose(res[2], [3.0])


def test_sym_cond_branches():
    x = mx.sym.Variable("x")
    pred = mx.sym.sum(x) > 0

    out = mx.sym.contrib.cond(pred, lambda: x * 2.0, lambda: x - 10.0)
    for sign, expect in [(1.0, 2.0), (-1.0, -11.0)]:
        (res,) = _bind_run(out, {"x": np.full((2,), sign, "f4")})
        np.testing.assert_allclose(res, np.full(2, expect), rtol=1e-6)


def test_sym_foreach_rnn_cell_shapes_back_infer():
    """An RNN-style cell inside the body: the loop node's shape hook runs
    the subgraph's own inference, so the cell's FC weights back-infer
    from the data slice shape — no explicit weight shapes needed (the
    reference subgraph FInferShape behavior)."""
    data = mx.sym.Variable("data")     # (N, T, F) from the iterator
    init = mx.sym.Variable("init")     # (N, H)
    data_t = mx.sym.transpose(data, axes=(1, 0, 2))  # scan over T

    def body(x, s):
        h = mx.sym.FullyConnected(x, num_hidden=4, name="i2h") \
            + mx.sym.FullyConnected(s, num_hidden=4, no_bias=True,
                                    name="h2h")
        h = mx.sym.Activation(h, act_type="tanh")
        return h, h

    outs, fin = mx.sym.contrib.foreach(body, data_t, init)
    net = mx.sym.FullyConnected(fin, num_hidden=2, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    args = net.list_arguments()
    assert "i2h_weight" in args and "h2h_weight" in args
    arg_shapes, out_shapes, _ = net.infer_shape(data=(4, 5, 3),
                                                init=(4, 4))
    shp = dict(zip(args, arg_shapes))
    assert shp["i2h_weight"] == (4, 3)     # back-inferred through the scan
    assert shp["h2h_weight"] == (4, 4)
    assert shp["fc_weight"] == (2, 4)
    assert out_shapes[0] == (4, 2)

    # and it trains through the standard Module path
    rng = np.random.RandomState(0)
    X = rng.randn(4, 5, 3).astype("f4")   # iter feeds (N, T, F)
    Y = (rng.rand(4) > 0.5).astype("f4")
    mod = mx.mod.Module(net, data_names=["data", "init"],
                        label_names=["softmax_label"])
    it = mx.io.NDArrayIter({"data": X, "init": np.zeros((4, 4), "f4")},
                           Y, batch_size=4, label_name="softmax_label")
    mod.fit(it, num_epoch=2, eval_metric="acc",
            optimizer_params={"learning_rate": 0.1})
    assert mod.get_params()[0]["i2h_weight"].shape == (4, 3)


def test_sym_while_loop_is_differentiable():
    """The loop lowers to a masked lax.scan, not lax.while_loop, so
    jax.vjp (the executor backward) differentiates through it."""
    w = mx.sym.Variable("w")

    def cond_fn(i, s):
        return i < 3

    def func(i, s):
        return s, (i + 1, s * w)

    outs, fin = mx.sym.contrib.while_loop(
        cond_fn, func, [mx.sym.Variable("i"), mx.sym.Variable("s")],
        max_iterations=4)
    loss = mx.sym.sum(fin[1])
    feeds = {"i": np.zeros((1,), "f4"), "s": np.full((1,), 2.0, "f4"),
             "w": np.full((1,), 3.0, "f4")}
    (out,), grads = _bind_run(loss, feeds, grad=[np.ones((), "f4")])
    # 3 iterations: s_final = 2 * w^3 = 54;  d/dw = 6 w^2 = 54
    np.testing.assert_allclose(out, 54.0, rtol=1e-6)
    np.testing.assert_allclose(grads["w"], [54.0], rtol=1e-6)


def test_sym_foreach_batchnorm_aux_stays_aux():
    """Moving stats used inside a body remain AUXILIARY states in the
    outer graph (read-only in the loop) — not trainable arguments."""
    data = mx.sym.Variable("data")
    init = mx.sym.Variable("init")

    def body(x, s):
        h = mx.sym.BatchNorm(x, name="bn", use_global_stats=True)
        return h + s, s

    outs, fin = mx.sym.contrib.foreach(body, data, init)
    net = mx.sym.Group([outs, fin])
    assert "bn_moving_mean" in net.list_auxiliary_states()
    assert "bn_moving_var" in net.list_auxiliary_states()
    assert "bn_moving_mean" not in net.list_arguments()


def test_sym_foreach_multi_output_body_refused():
    data = mx.sym.Variable("data")
    init = mx.sym.Variable("init")

    def body(x, s):
        return mx.sym.SliceChannel(x, num_outputs=2, axis=0), s

    with pytest.raises(mx.base.MXNetError, match="single-output"):
        mx.sym.contrib.foreach(body, data, init)


def test_hybridized_f_contrib_foreach_matches_eager():
    """F.contrib.foreach inside a HybridBlock: same numerics eager and
    under the jit trace (the functional control flow dispatches to the
    lax lowering on raw jax values)."""
    from mxnet_tpu import gluon, autograd

    class ScanCell(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.dense = gluon.nn.Dense(8, flatten=False)

        def hybrid_forward(self, F, x, init):
            xt = F.transpose(x, axes=(1, 0, 2))

            def body(xs, s):
                h = F.tanh(self.dense(xs) + s)
                return h, h

            outs, fin = F.contrib.foreach(body, xt, init)
            return fin

    net = ScanCell()
    net.initialize()
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(4, 5, 3).astype("f4"))
    z = mx.nd.zeros((4, 8))
    y_eager = net(x, z).asnumpy()
    net.hybridize()
    with autograd.record():
        y_hyb = net(x, z)
        loss = y_hyb.sum()
    loss.backward()  # gradient flows through the scan
    np.testing.assert_allclose(y_eager, y_hyb.asnumpy(), atol=1e-5)
    g = net.dense.weight.grad()
    assert np.abs(g.asnumpy()).sum() > 0


def test_hybridized_f_contrib_float_predicates():
    from mxnet_tpu import gluon

    class Pred(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.contrib.isfinite(x) + F.contrib.isnan(x) * 2 \
                + F.contrib.isinf(x) * 4

    net = Pred()
    x = mx.nd.array(np.array([1.0, float("inf"), float("nan")], "f4"))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_hyb = net(x).asnumpy()
    np.testing.assert_allclose(y_eager, [1.0, 4.0, 2.0])
    np.testing.assert_allclose(y_hyb, y_eager)


def test_hybridized_control_flow_refuses_nd_constants():
    """Mixing an NDArray constant into control flow inside a hybridized
    forward fails with a clear message, not a leaked-tracer crash."""
    from mxnet_tpu import gluon

    const = mx.nd.zeros((4, 8))

    class Bad(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            xt = F.transpose(x, axes=(1, 0, 2))
            outs, fin = F.contrib.foreach(
                lambda xs, s: (xs + s, s), xt, const)  # captured NDArray
            return fin

    net = Bad()
    net.initialize()
    net.hybridize()
    x = mx.nd.zeros((4, 5, 8))
    with pytest.raises(mx.base.MXNetError, match="hybridized"):
        net(x)


def test_sym_foreach_json_roundtrip():
    """Control-flow nodes serialize with embedded subgraphs (the
    reference's nnvm subgraph wire layout) and rebuild on load — a
    checkpointed control-flow model round-trips like any other."""
    data = mx.sym.Variable("data")
    init = mx.sym.Variable("init")

    def body(x, s):
        h = mx.sym.Activation(mx.sym.FullyConnected(x, num_hidden=4,
                                                    name="i2h") + s,
                              act_type="tanh")
        return h, h

    outs, fin = mx.sym.contrib.foreach(body, data, init)
    net = mx.sym.Group([outs, fin])
    js = net.tojson()
    assert "subgraphs" in js and "_foreach" in js
    net2 = mx.sym.load_json(js)
    assert sorted(net2.list_arguments()) == sorted(net.list_arguments())
    x = np.random.RandomState(0).randn(5, 2, 3).astype("f4")
    feeds = {"data": x, "init": np.zeros((2, 4), "f4"),
             "i2h_weight": np.ones((4, 3), "f4") * 0.1,
             "i2h_bias": np.zeros((4,), "f4")}
    y1 = _bind_run(net, feeds)
    y2 = _bind_run(net2, feeds)
    for a, b in zip(y1, y2):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_sym_while_and_cond_json_roundtrip():
    w = mx.sym.Variable("w")
    outs, fin = mx.sym.contrib.while_loop(
        lambda i, s: i < 3, lambda i, s: (s, (i + 1, s * w)),
        [mx.sym.Variable("i"), mx.sym.Variable("s")], max_iterations=4)
    x = mx.sym.Variable("x")
    branch = mx.sym.contrib.cond(mx.sym.sum(x) > 0,
                                 lambda: x * 2.0, lambda: x - 1.0)
    net = mx.sym.Group([outs, fin[1], branch])
    net2 = mx.sym.load_json(net.tojson())
    feeds = {"i": np.zeros((1,), "f4"), "s": np.full((1,), 2.0, "f4"),
             "w": np.full((1,), 3.0, "f4"),
             "x": np.full((2,), 1.5, "f4")}
    y1 = _bind_run(net, feeds)
    y2 = _bind_run(net2, feeds)
    for a, b in zip(y1, y2):
        np.testing.assert_allclose(a, b, rtol=1e-6)
    np.testing.assert_allclose(y2[-1], [3.0, 3.0])  # then-branch taken
