"""Worker for the N-process dist_sync kvstore test.

Ports the invariants of the reference's nightly dist test
(tests/nightly/dist_sync_kvstore.py:66-429) onto the jax.distributed
backend: init broadcast, sync push/pull with a server-side ('test')
optimizer, aggregate-replace pushes, row_sparse keys, gradient compression
across the wire, rank/num_workers/barrier.

Run via the launcher (each invariant is collective — all ranks execute in
lockstep):

    python tools/launch.py -n 3 python tests/dist_worker.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")  # CPU fleet; Gloo collectives

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402

SHAPE = (2, 3)
BIG_SHAPE = (120, 120)
RATE = 2


def check_diff(nd, expected, rank):
    a = nd.asnumpy()
    assert np.abs(a - expected).sum() == 0, (rank, a, expected)


def main():
    kv = mx.kv.create("dist_sync")
    my_rank = kv.rank
    nworker = kv.num_workers
    expected_n = int(os.environ["MXNET_NUM_WORKERS"])
    assert nworker == expected_n, (nworker, expected_n)
    assert my_rank == int(os.environ["MXNET_WORKER_RANK"])

    # --- init is a broadcast: rank 0's (random) value wins everywhere -----
    rng = np.random.RandomState(100 + my_rank)
    kv.init("b0", mx.nd.array(rng.randn(*SHAPE).astype(np.float32)))
    rank0_val = np.random.RandomState(100).randn(*SHAPE).astype(np.float32)
    got = mx.nd.zeros(SHAPE)
    kv.pull("b0", out=got)
    np.testing.assert_allclose(got.asnumpy(), rank0_val, rtol=1e-6)

    # --- sync push/pull with server-side optimizer (reference
    # check_default_keys): each rank pushes ones*(rank+1); the 'test'
    # optimizer does w += rescale * sum(grads); after i+1 rounds
    # w = (n+1)*n*rate/2*(i+1) + 1 ----------------------------------------
    for keys, shape in ((["3", "5", "7"], SHAPE), (["99"], BIG_SHAPE)):
        kv2 = mx.kv.create("dist_sync")
        kv2.set_optimizer(mx.optimizer.create("test", rescale_grad=RATE))
        for k in keys:
            kv2.init(k, mx.nd.ones(shape))
        for i in range(3):
            for k in keys:
                kv2.push(k, mx.nd.ones(shape) * (my_rank + 1))
                expected = (nworker + 1) * nworker * RATE / 2 * (i + 1) + 1
                val = mx.nd.zeros(shape)
                kv2.pull(k, out=val)
                check_diff(val, expected, my_rank)

    # --- no-updater push: merged+all-reduced value REPLACES the store ----
    kv.init("r0", mx.nd.zeros(SHAPE))
    kv.push("r0", mx.nd.ones(SHAPE) * (my_rank + 1))
    val = mx.nd.zeros(SHAPE)
    kv.pull("r0", out=val)
    check_diff(val, nworker * (nworker + 1) / 2, my_rank)

    # --- row_sparse keys (reference check_row_sparse_keys) ----------------
    kv.init("rsp", mx.nd.zeros(SHAPE).tostype("row_sparse"))
    v = np.zeros(SHAPE, np.float32)
    v[my_rank % SHAPE[0]] = my_rank + 1
    kv.push("rsp", mx.nd.array(v).tostype("row_sparse"))
    out = mx.nd.zeros(SHAPE)
    kv.pull("rsp", out=out, ignore_sparse=False)
    expected = np.zeros(SHAPE, np.float32)
    for r in range(nworker):
        expected[r % SHAPE[0]] += r + 1
    np.testing.assert_allclose(out.asnumpy(), expected, rtol=1e-6)

    # --- gradient compression crosses the wire (reference
    # test_sync_2bit_compression): each worker quantizes to {-t, 0, +t}
    # before the reduce, so the aggregate is sum of the quantized grads ---
    kvc = mx.kv.create("dist_sync")
    kvc.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kvc.init("c0", mx.nd.zeros(SHAPE))
    kvc.push("c0", mx.nd.ones(SHAPE))  # 1.0 >= 0.5 -> quantized to +0.5
    out = mx.nd.zeros(SHAPE)
    kvc.pull("c0", out=out)
    check_diff(out, 0.5 * nworker, my_rank)
    # error feedback: residual 0.5 carried into the next push
    kvc.push("c0", mx.nd.zeros(SHAPE))  # 0 + residual 0.5 -> +0.5 again
    kvc.pull("c0", out=out)
    check_diff(out, 0.5 * nworker, my_rank)
    # and the WIRE carried packed 2-bit codes, not f32 (~16x smaller)
    n = int(np.prod(SHAPE))
    assert kvc._last_wire_bytes == (n + 3) // 4, kvc._last_wire_bytes

    # --- barrier ----------------------------------------------------------
    kv._barrier()
    assert kv.get_num_dead_node() == 0
    print("rank %d/%d: all dist_sync invariants OK" % (my_rank, nworker))
    return 0


if __name__ == "__main__":
    sys.exit(main())
