"""Import-time hygiene: `import mxnet_tpu` must do NO device work.

Round-1 regression: a module-level `jnp.array` constant
(ops/image_ops.py) forced full JAX backend initialization the moment the
package was imported — on the driver machine that meant initializing the
TPU plugin before bench.py/dryrun_multichip could pin a platform, killing
both runs. These tests run in a subprocess (the parent test process has
long since initialized a backend) and assert that importing the framework
initializes no XLA backend and flips no global JAX config.
"""
import subprocess
import sys

import pytest


def _run(code, timeout=120, env_extra=None):
    import os
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    return subprocess.run([sys.executable, "-c", code], timeout=timeout,
                          capture_output=True, text=True, env=env)


def test_import_initializes_no_backend():
    code = (
        "import jax\n"
        "import jax._src.xla_bridge as xb\n"
        "import mxnet_tpu\n"
        "import mxnet_tpu.ops.image_ops\n"
        "assert not xb._backends, "
        "'backends initialized at import: %r' % list(xb._backends)\n"
        "print('CLEAN')\n")
    r = _run(code)
    assert r.returncode == 0, r.stderr
    assert "CLEAN" in r.stdout


def test_import_does_not_enable_x64_by_default():
    code = (
        "import jax\n"
        "import mxnet_tpu\n"
        "assert not jax.config.jax_enable_x64\n"
        "print('F32DEFAULT')\n")
    r = _run(code, env_extra={"MXNET_ENABLE_X64": ""})
    assert r.returncode == 0, r.stderr
    assert "F32DEFAULT" in r.stdout


def test_x64_opt_in_via_env():
    code = (
        "import jax\n"
        "import mxnet_tpu\n"
        "assert jax.config.jax_enable_x64\n"
        "print('X64ON')\n")
    r = _run(code, env_extra={"MXNET_ENABLE_X64": "1"})
    assert r.returncode == 0, r.stderr
    assert "X64ON" in r.stdout
