"""Generated namespace modules (reference ndarray/{op,_internal,image}
.py, symbol/{op,_internal,image,random,sparse}.py, misc.py, torch.py):
every name a reference script can import resolves here too."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_nd_op_and_internal():
    y = mx.nd.op.relu(mx.nd.array([-1.0, 2.0]))
    np.testing.assert_allclose(y.asnumpy(), [0.0, 2.0])
    z = mx.nd._internal._plus_scalar(mx.nd.array([1.0]), scalar=2.0)
    np.testing.assert_allclose(z.asnumpy(), [3.0])
    with pytest.raises(AttributeError):
        mx.nd.op._plus_scalar  # underscore ops live in _internal only
    with pytest.raises(AttributeError):
        mx.nd._internal.relu


def test_nd_image_namespace():
    x = mx.nd.array(np.random.RandomState(0).rand(8, 8, 3)
                    .astype("f4"))
    t = mx.nd.image.to_tensor(x)
    assert t.shape == (3, 8, 8)
    r = mx.nd.image.resize(x, size=(4, 4))
    assert r.shape == (4, 4, 3)
    assert "resize" in dir(mx.nd.image)


def test_sym_random_namespace():
    s = mx.sym.random.normal(loc=2.0, scale=0.1, shape=(64,))
    ex = s.bind(mx.cpu(), {})
    ex.forward()
    v = ex.outputs[0].asnumpy()
    assert v.shape == (64,) and 1.5 < v.mean() < 2.5
    # symbolic sample op with Symbol params
    mu = mx.sym.Variable("mu")
    s2 = mx.sym.random.uniform(mu, mu + 1.0, shape=())
    assert "mu" in s2.list_arguments()


def test_sym_image_op_internal_sparse():
    img = mx.sym.Variable("img")
    t = mx.sym.image.to_tensor(img)
    ex = t.bind(mx.cpu(), {"img": mx.nd.ones((4, 4, 3))})
    ex.forward()
    assert ex.outputs[0].shape == (3, 4, 4)
    assert callable(mx.sym.op.softmax)
    assert callable(mx.sym._internal._mul_scalar)
    d = mx.sym.sparse.retain(mx.sym.Variable("a"), mx.sym.Variable("i")) \
        if hasattr(mx.sym.sparse, "retain") else None
    assert callable(mx.sym.sparse.dot)


def test_misc_legacy_scheduler():
    from mxnet_tpu.misc import FactorScheduler
    sch = FactorScheduler(step=2, factor=0.1)
    assert sch(0) == pytest.approx(0.01)
    assert sch(4) == pytest.approx(0.01 * 0.01)
    with pytest.raises(ValueError):
        FactorScheduler(step=0)


def test_torch_shim_fails_loudly():
    from mxnet_tpu import torch as mxth
    with pytest.raises(mx.base.MXNetError, match="TPU analog"):
        mxth.zeros((2, 2))
