"""Control-flow op tests
(model: reference tests/python/unittest/test_contrib_control_flow.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.ndarray import contrib
from mxnet_tpu.test_utils import assert_almost_equal


def test_foreach_cumsum():
    def body(x, state):
        new = state + x
        return new, new
    data = mx.nd.array(np.arange(5, dtype="float32"))
    init = mx.nd.array(np.array([0.0], dtype="float32"))
    outs, final = contrib.foreach(body, data, init)
    assert_almost_equal(outs.asnumpy().reshape(-1),
                        np.cumsum(np.arange(5)).astype("float32"))
    assert float(final.asscalar()) == 10.0


def test_foreach_multi_state():
    def body(x, states):
        s0, s1 = states
        return x + s0, [s0 + 1, s1 * 2]
    data = mx.nd.array(np.ones((3, 2), dtype="float32"))
    outs, (f0, f1) = contrib.foreach(
        body, data, [mx.nd.zeros((2,)), mx.nd.ones((2,))])
    assert outs.shape == (3, 2)
    assert float(f0[0].asscalar()) == 3.0
    assert float(f1[0].asscalar()) == 8.0


def test_foreach_grad():
    w = mx.nd.array(np.array([2.0], dtype="float32"))
    w.attach_grad()
    data = mx.nd.array(np.arange(1, 4, dtype="float32"))
    with autograd.record():
        def body(x, state):
            out = x * w
            return out, state + out
        outs, final = contrib.foreach(body, data,
                                      mx.nd.zeros((1,)))
        loss = final.sum()
    loss.backward()
    # d(sum w*x)/dw = sum x = 6
    assert float(w.grad.asscalar()) == 6.0


def test_while_loop_eager():
    def cond(i, s):
        return i < 4
    def func(i, s):
        return i * 2, [i + 1, s + i]
    outs, (i_fin, s_fin) = contrib.while_loop(
        cond, func,
        [mx.nd.array([0.0]), mx.nd.array([0.0])], max_iterations=6)
    assert outs.shape == (6, 1)  # padded to max_iterations
    assert_almost_equal(outs.asnumpy()[:4, 0],
                        np.array([0, 2, 4, 6], dtype="float32"))
    assert float(i_fin.asscalar()) == 4.0
    assert float(s_fin.asscalar()) == 6.0


def test_cond_eager():
    x = mx.nd.array([3.0])
    out = contrib.cond(x.sum() > 2,
                       lambda: x * 2,
                       lambda: x - 1)
    assert float(out.asscalar()) == 6.0
    out = contrib.cond(x.sum() > 5,
                       lambda: x * 2,
                       lambda: x - 1)
    assert float(out.asscalar()) == 2.0


def test_foreach_lax_inside_jit():
    """Traced path lowers to lax.scan inside a compiled function."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(data):
        def body(x, s):
            n = s + x
            return n, n
        outs, fin = contrib.foreach(body, data, jnp.zeros((1,)))
        return outs, fin
    outs, fin = run(jnp.arange(4, dtype=jnp.float32).reshape(4, 1))
    assert np.allclose(np.asarray(outs).reshape(-1), [0, 1, 3, 6])
    assert float(np.asarray(fin)[0]) == 6.0


def test_while_loop_lax_inside_jit():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(start):
        def cond(i, s):
            return i < 3
        def func(i, s):
            return s * 1.0, [i + 1, s + 2.0]
        return contrib.while_loop(cond, func, [start, jnp.zeros(())],
                                  max_iterations=5)
    outs, (i_fin, s_fin) = run(jnp.zeros((), jnp.int32))
    assert np.asarray(outs).shape == (5,)
    assert np.allclose(np.asarray(outs)[:3], [0, 2, 4])
    assert int(i_fin) == 3


def test_cond_lax_inside_jit():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(x):
        return contrib.cond(x.sum() > 0, lambda: x * 2, lambda: x - 1)
    assert np.allclose(np.asarray(run(jnp.ones(3))), [2, 2, 2])
    assert np.allclose(np.asarray(run(-jnp.ones(3))), [-2, -2, -2])


def test_isnan_isinf():
    x = mx.nd.array(np.array([1.0, np.inf, np.nan], dtype="float32"))
    assert list(contrib.isnan(x).asnumpy()) == [0, 0, 1]
    assert list(contrib.isinf(x).asnumpy()) == [0, 1, 0]
    assert list(contrib.isfinite(x).asnumpy()) == [1, 0, 0]


def test_while_loop_zero_iterations():
    """Review regression: initially-false condition returns padded zeros
    (matching the lax path) instead of raising."""
    outs, fin = contrib.while_loop(
        lambda i: i > 100, lambda i: (i * 2, [i + 1]),
        [mx.nd.array([5.0])], max_iterations=3)
    assert outs.shape == (3, 1)
    assert float(outs.asnumpy().sum()) == 0.0
    assert float(fin[0].asscalar()) == 5.0


def test_foreach_lax_single_element_list_output():
    """Review regression: a body returning a 1-element list keeps list
    structure under the lax path, matching eager."""
    import jax
    import jax.numpy as jnp

    def body(x, s):
        return [x + s], s + x

    eager_out, _ = contrib.foreach(body, mx.nd.ones((3, 2)),
                                   mx.nd.zeros((2,)))
    assert isinstance(eager_out, list) and len(eager_out) == 1

    @jax.jit
    def run(d):
        return contrib.foreach(body, d, jnp.zeros((2,)))
    lax_out, _ = run(jnp.ones((3, 2)))
    assert isinstance(lax_out, list) and len(lax_out) == 1


# ------------------------------------------------------------------ dgl ops
def _toy_graph():
    """5-vertex graph; CSR values are edge ids 0..nnz-1."""
    import numpy as np
    dense = np.array([
        [0, 1, 0, 1, 0],
        [1, 0, 1, 0, 0],
        [0, 1, 0, 1, 1],
        [1, 0, 1, 0, 0],
        [0, 0, 1, 0, 0]], np.float32)
    rows, cols = np.nonzero(dense)
    eids = np.arange(len(rows), dtype=np.float32)
    indptr = np.zeros(6, np.int64)
    for r in rows:
        indptr[r + 1:] += 1
    return mx.nd.sparse.csr_matrix(
        (eids, cols.astype(np.int64), indptr), shape=(5, 5))


def test_dgl_edge_id_and_adjacency():
    import numpy as np
    g = _toy_graph()
    ids = mx.nd.contrib.edge_id(g, mx.nd.array([0, 0, 2]),
                                mx.nd.array([1, 2, 4]))
    out = ids.asnumpy()
    assert out[0] >= 0       # edge 0->1 exists
    assert out[1] == -1      # edge 0->2 absent
    assert out[2] >= 0       # edge 2->4 exists
    adj = mx.nd.contrib.dgl_adjacency(g)
    assert adj.stype == "csr"
    np.testing.assert_allclose(adj.data.asnumpy(),
                               np.ones_like(adj.data.asnumpy()))


def test_dgl_subgraph_induced():
    import numpy as np
    g = _toy_graph()
    subs = mx.nd.contrib.dgl_subgraph(g, mx.nd.array([0, 1, 3]),
                                      return_mapping=True)
    sub, mapping = subs
    assert sub.shape == (3, 3)
    # edges among {0,1,3} (positions 0,1,2): 0->1, 0->3, 1->0, 3->0
    expect = np.array([[0, 1, 1], [1, 0, 0], [1, 0, 0]], bool)
    got = np.zeros((3, 3), bool)
    indptr = mapping.indptr.asnumpy()
    idx = mapping.indices.asnumpy()
    for r in range(3):
        got[r, idx[indptr[r]:indptr[r + 1]]] = True
    np.testing.assert_array_equal(got, expect)
    # mapping values are parent edge ids present in the parent graph
    parent_ids = set(g.data.asnumpy().tolist())
    assert set(mapping.data.asnumpy().tolist()) <= parent_ids


def test_dgl_neighbor_sampling():
    g = _toy_graph()
    out = mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
        g, mx.nd.array([0]), num_hops=1, num_neighbor=2,
        max_num_vertices=5)
    verts, sub, layer = out
    v = verts.asnumpy()
    n = int(v[-1])
    assert 1 <= n <= 5
    assert 0 in v[:n]                      # seed kept
    lay = layer.asnumpy()
    assert lay[list(v[:n]).index(0)] == 0  # seed at hop 0
    assert sub.shape == (5, 5)
    # non-uniform variant runs and keeps the seed
    prob = mx.nd.array([0.2, 0.2, 0.2, 0.2, 0.2])
    out2 = mx.nd.contrib.dgl_csr_neighbor_non_uniform_sample(
        g, prob, mx.nd.array([0]), num_hops=1, num_neighbor=2,
        max_num_vertices=5)
    assert int(out2[0].asnumpy()[-1]) >= 1


def test_dgl_non_uniform_zero_prob_neighbors():
    import numpy as np
    g = _toy_graph()
    # vertex 0's neighbors are {1, 3}; zero out 3 -> only 1 ever sampled
    prob = mx.nd.array([1.0, 1.0, 0.0, 0.0, 0.0])
    for _ in range(5):
        out = mx.nd.contrib.dgl_csr_neighbor_non_uniform_sample(
            g, prob, mx.nd.array([0]), num_hops=1, num_neighbor=2,
            max_num_vertices=5)
        v = out[0].asnumpy()
        n = int(v[-1])
        sampled = set(int(x) for x in v[:n])
        assert 3 not in sampled and 0 in sampled
    # all-zero neighborhood: seed expands to nothing, no crash
    prob0 = mx.nd.array([0.0, 0.0, 0.0, 0.0, 0.0])
    out = mx.nd.contrib.dgl_csr_neighbor_non_uniform_sample(
        g, prob0, mx.nd.array([0]), num_hops=1, num_neighbor=2,
        max_num_vertices=5)
    assert int(out[0].asnumpy()[-1]) == 1  # just the seed


def test_dgl_type_errors_are_loud():
    import pytest as _pytest
    dense = mx.nd.array(np.eye(3, dtype=np.float32))
    with _pytest.raises(TypeError, match="CSRNDArray"):
        mx.nd.contrib.dgl_subgraph(dense, mx.nd.array([0]))
    with _pytest.raises(TypeError, match="CSRNDArray"):
        mx.nd.contrib.dgl_csr_neighbor_uniform_sample(
            dense, mx.nd.array([0]), num_hops=1, num_neighbor=1,
            max_num_vertices=3)
