"""Worker for the fault-detection test: rank (n-1) stops heartbeating;
the survivors must observe it through kv.get_num_dead_node() (reference
kvstore.h:353 surface). The "dead" rank stays alive so the final barrier
still completes — heartbeat staleness, not process exit, is what the
surface reports."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.parallel import dist, fault  # noqa: E402

RANK = dist.rank()
N = dist.num_workers()
HB = os.environ["MXNET_HEARTBEAT_DIR"]
assert fault.active(), "dist.init should have started the heartbeat"

kv = mx.kv.create("dist_sync")
assert kv.get_num_dead_node(timeout=30) == 0

dist.barrier("fault_test_start")

if RANK == N - 1:
    fault.stop()
    os.remove(os.path.join(HB, "hb_%d" % RANK))
    # stay alive until every survivor has flagged detection
    deadline = time.time() + 60
    while time.time() < deadline:
        if all(os.path.exists(os.path.join(HB, "done_%d" % r))
               for r in range(N - 1)):
            break
        time.sleep(0.2)
    else:
        sys.exit("survivors never detected the dead heartbeat")
else:
    deadline = time.time() + 60
    while time.time() < deadline:
        if kv.get_num_dead_node(timeout=2.0) >= 1:
            break
        time.sleep(0.2)
    else:
        sys.exit("get_num_dead_node stayed 0")
    with open(os.path.join(HB, "done_%d" % RANK), "w") as f:
        f.write("1")

dist.barrier("fault_test_end")
print("rank %d/%d: fault detection OK" % (RANK, N))
