"""Async fit loop: depth parity, checkpoint quiesce, metric residency.

Companion to tests/test_step_sync_budget.py. That file bounds the host
syncs of the benched ResNet-50 loop; this one pins the OBSERVABLE
semantics of going async on small models:

* engine depth is invisible — callbacks/Speedometer/early-stop logic see
  bitwise-identical metric values and the trained params are bitwise
  equal at any depth (depth changes when the host waits, never what the
  device computes);
* a checkpoint taken mid-flight (depth > 1, dispatches outstanding)
  equals one taken in lockstep — the save path quiesces first;
* device-resident metric accumulation agrees with the reference host
  path, and the proxy publishes through the user's own metric object;
* CompositeEvalMetric moves a whole batch's labels+preds in ONE host
  fetch (satellite of the same PR);
* PrefetchingIter.reset() no longer races its worker thread.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler
from mxnet_tpu import config as _config
from mxnet_tpu.io import DataBatch, DataDesc, NDArrayIter

N, DIM, CLASSES, BATCH = 128, 16, 5, 16


def _data():
    rng = np.random.RandomState(3)
    x = rng.randn(N, DIM).astype(np.float32)
    y = (rng.rand(N) * CLASSES).astype(np.float32)
    return x, y


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _fit_mlp(num_epoch=2, metric="acc", depth=None, **kw):
    x, y = _data()
    it = NDArrayIter(x, y, batch_size=BATCH, label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), data_names=["data"],
                        label_names=["softmax_label"], context=mx.cpu())
    import logging
    logger = logging.getLogger("async_loop_test")
    logger.addHandler(logging.NullHandler())
    logger.propagate = False
    mod.logger = logger
    np.random.seed(5)  # Initializer draws from the global numpy RNG
    mx.random.seed(7)  # pin the device key chain (checkpointed state)
    over = {} if depth is None else {"engine_depth": depth}
    with _config.override(**over):
        mod.fit(it, num_epoch=num_epoch, eval_metric=metric,
                kvstore="tpu_sync",
                optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
                **kw)
    return mod


def _params_np(mod):
    import jax
    ex = mod._exec
    return {k: np.asarray(jax.device_get(ex.arg_dict[k]._data))
            for k in mod._param_names}


# --------------------------------------------------------- depth parity
def test_depth_bitwise_parity_with_callbacks():
    """The same training run at in-flight depth 4 vs lockstep depth 1:
    a per-batch callback (which forces the per-step dispatch path, like
    Speedometer/early-stop users) must read bitwise-identical metric
    values, and the final params must match bit for bit."""
    runs = {}
    for depth in (4, 1):
        seen = []

        def cb(param):
            seen.append(tuple(param.eval_metric.get_name_value()))

        speedo = mx.callback.Speedometer(BATCH, frequent=3)
        mod = _fit_mlp(depth=depth,
                       batch_end_callback=[speedo, cb])
        runs[depth] = (list(seen), _params_np(mod))

    vals4, params4 = runs[4]
    vals1, params1 = runs[1]
    # bitwise: same floats at every read point (NaN-aware — Speedometer's
    # auto_reset legitimately yields a 0/0 reading right after a reset)
    assert len(vals4) == len(vals1)
    for a, b in zip(vals4, vals1):
        assert [n for n, _ in a] == [n for n, _ in b]
        for (_, v1), (_, v2) in zip(a, b):
            assert v1 == v2 or (np.isnan(v1) and np.isnan(v2)), (a, b)
    assert params4.keys() == params1.keys()
    for k in params4:
        assert np.array_equal(params4[k], params1[k]), k


def test_unbounded_depth_still_correct():
    mod0 = _fit_mlp(depth=0)   # unbounded in-flight queue
    mod1 = _fit_mlp(depth=1)
    p0, p1 = _params_np(mod0), _params_np(mod1)
    for k in p0:
        assert np.array_equal(p0[k], p1[k]), k


# --------------------------------------------------- checkpoint quiesce
def test_checkpoint_midflight_equals_lockstep(tmp_path):
    """A snapshot taken while dispatches are in flight (depth 4) must be
    byte-equal to one taken in lockstep: fit's state_fn quiesces the
    depth controller before materialising buffers."""
    from mxnet_tpu.checkpoint import CheckpointManager

    states = {}
    for depth in (4, 1):
        ck = CheckpointManager(str(tmp_path / ("d%d" % depth)),
                               save_every=5, async_save=False)
        _fit_mlp(depth=depth, checkpoint=ck)
        state, manifest = ck.restore_latest()
        assert manifest is not None
        states[depth] = (state, manifest["step"])

    s4, step4 = states[4]
    s1, step1 = states[1]
    assert step4 == step1
    assert set(s4.keys()) == set(s1.keys())
    for k in s4:
        if k == "__rng__":
            assert bytes(s4[k]) == bytes(s1[k])
        else:
            assert np.array_equal(np.asarray(s4[k]), np.asarray(s1[k])), k


def test_gluon_trainer_quiesce_before_save(tmp_path):
    """Trainer.step() dispatches without waiting; save_checkpoint must
    settle the in-flight steps first and snapshot the post-update
    params."""
    from mxnet_tpu import gluon
    from mxnet_tpu.checkpoint import CheckpointManager

    def run(depth):
        np.random.seed(9)
        net = gluon.nn.Dense(4, in_units=8)
        net.initialize()
        ck = CheckpointManager(str(tmp_path / ("g%d" % depth)),
                               save_every=100, async_save=False)
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1}, kvstore=None,
                                checkpoint=ck)
        x = mx.nd.array(np.random.RandomState(2).randn(4, 8)
                        .astype(np.float32))
        with _config.override(engine_depth=depth):
            from mxnet_tpu import autograd
            for _ in range(6):
                with autograd.record():
                    out = net(x)
                    loss = out.sum()
                loss.backward()
                trainer.step(4)
            trainer.save_checkpoint()
        state, _ = ck.restore_latest()
        # gluon auto-naming renumbers prefixes across runs in one
        # process (dense0_ -> dense1_) — key by position only
        return {k.split(":")[1]: np.asarray(v) for k, v in state.items()
                if k.startswith("param:")}

    a, b = run(3), run(1)
    assert a.keys() == b.keys() and len(a) > 0
    for k in a:
        assert np.array_equal(a[k], b[k]), k


# ------------------------------------------------- metric residency
def test_device_metric_matches_host_path():
    """flags.device_metrics=False replays the reference per-batch host
    accumulation; the device carry must agree on acc exactly and on ce
    to f32-accumulation tolerance (host sums in float64)."""
    m_dev = mx.metric.CompositeEvalMetric(
        [mx.metric.Accuracy(), mx.metric.CrossEntropy()])
    mod = _fit_mlp(metric=m_dev)
    assert mod._device_plan is not None, "composite acc+ce must fold"
    dev_vals = dict(m_dev.get_name_value())

    m_host = mx.metric.CompositeEvalMetric(
        [mx.metric.Accuracy(), mx.metric.CrossEntropy()])
    with _config.override(device_metrics=False):
        mod2 = _fit_mlp(metric=m_host)
    assert mod2._device_plan is None
    host_vals = dict(m_host.get_name_value())

    assert dev_vals["accuracy"] == host_vals["accuracy"]
    assert np.isclose(dev_vals["cross-entropy"],
                      host_vals["cross-entropy"], rtol=1e-5)


def test_unsupported_metric_falls_back_to_host():
    """F1 keeps per-batch state (confusion counts with argmax on host
    thresholds) — not fusable; fit must quietly keep the host path."""
    x, y = _data()
    y = (y > 2).astype(np.float32)  # F1 wants binary labels
    it = NDArrayIter(x, y, batch_size=BATCH, label_name="softmax_label")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, data_names=["data"],
                        label_names=["softmax_label"], context=mx.cpu())
    m = mx.metric.F1()
    mod.fit(it, num_epoch=1, eval_metric=m, kvstore="tpu_sync",
            optimizer_params={"learning_rate": 0.05})
    assert mod._device_plan is None
    assert m.num_inst > 0  # host path accumulated normally


def test_composite_update_is_one_fetch():
    """CompositeEvalMetric.update moves labels+preds to host as ONE
    device_get of the whole pytree, not one per leaf metric per array."""
    m = mx.metric.CompositeEvalMetric(
        [mx.metric.Accuracy(), mx.metric.CrossEntropy(),
         mx.metric.TopKAccuracy(top_k=3)])
    pred = mx.nd.array(np.random.RandomState(0)
                       .rand(8, CLASSES).astype(np.float32))
    label = mx.nd.array(np.arange(8, dtype=np.float32) % CLASSES)
    profiler.reset_sync_counters()
    m.update([label], [pred])
    assert profiler.sync_counters()["d2h"] == 1
    assert m.get_name_value()


# ------------------------------------------------- PrefetchingIter race
class _SlowIter:
    """Inner iterator with a latency hump, so reset() reliably lands
    while the worker is mid-next()/mid-put()."""

    def __init__(self, n=8, delay=0.002):
        self.n = n
        self.delay = delay
        self.i = 0
        self.batch_size = 2
        self.provide_data = [DataDesc("data", (2, 3))]
        self.provide_label = [DataDesc("softmax_label", (2,))]

    def next(self):
        if self.i >= self.n:
            raise StopIteration
        time.sleep(self.delay)
        i = self.i
        self.i += 1
        return DataBatch(
            data=[mx.nd.array(np.full((2, 3), i, np.float32))],
            label=[mx.nd.array(np.zeros((2,), np.float32))], pad=0)

    def reset(self):
        self.i = 0


def test_prefetch_reset_not_racy():
    """Hammer reset() mid-stream: the first batch after every reset must
    be batch 0 (a zombie worker feeding the NEW queue from the OLD
    iterator position would surface here as a stale batch), and the
    full epoch must arrive in order afterwards."""
    from mxnet_tpu.io import PrefetchingIter
    pf = PrefetchingIter(_SlowIter())
    try:
        for _ in range(15):
            b = pf.next()
            assert float(b.data[0].asnumpy()[0, 0]) == 0.0
            pf.next()  # leave the worker busy mid-epoch
            pf.reset()
        seq = []
        while True:
            try:
                seq.append(float(pf.next().data[0].asnumpy()[0, 0]))
            except StopIteration:
                break
        assert seq == [float(i) for i in range(8)], seq
    finally:
        pf.close()


def test_prefetch_reset_while_queue_full():
    """The old worker blocked on a FULL queue must still die at reset:
    its put() observes the stop event instead of blocking forever."""
    from mxnet_tpu.io import PrefetchingIter
    pf = PrefetchingIter(_SlowIter(n=50, delay=0.0), prefetch_depth=1)
    time.sleep(0.05)  # queue certainly full, worker blocked in put
    old_worker = pf._thread
    pf.reset()
    old_worker.join(timeout=2)
    assert not old_worker.is_alive()
    b = pf.next()
    assert float(b.data[0].asnumpy()[0, 0]) == 0.0
    pf.close()
