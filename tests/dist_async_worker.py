"""Worker for the N-process dist_async kvstore test.

Demonstrates what the reference's async server arm guarantees
(src/kvstore/kvstore_dist_server.h:348-358): every push applies to the
global weights IMMEDIATELY, with no cross-worker barrier — so a fast
worker completes all its pushes while a slow worker is still sleeping,
which is impossible under dist_sync (where push is collective).

Run: python tools/launch.py -n 2 python tests/dist_async_worker.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402

SHAPE = (4, 3)
FAST_PUSHES = 5


def main():
    kv = mx.kv.create("dist_async")
    rank, nworker = kv.rank, kv.num_workers
    assert nworker >= 2

    kv.init("w", mx.nd.zeros(SHAPE))
    # server-side optimizer: plain SGD lr=1 => weight -= grad per push
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=1.0))
    assert kv._updater is None, "async worker must not update locally"
    kv._barrier()  # line up the start, then NO further barriers

    t0 = time.monotonic()
    if rank == 0:
        # fast worker: burst of pushes, each applied on arrival
        for _ in range(FAST_PUSHES):
            kv.push("w", mx.nd.ones(SHAPE))
        t_done = time.monotonic()
        # server already reflects OUR pushes even though rank 1 is asleep
        out = mx.nd.zeros(SHAPE)
        kv.pull("w", out=out)
        seen = -out.asnumpy()[0, 0]
        assert FAST_PUSHES <= seen < FAST_PUSHES + 1, seen
        assert t_done - t0 < 2.0, (
            "fast worker stalled %.1fs: pushes are barriered, not async"
            % (t_done - t0))
        print("rank 0: %d async pushes applied in %.2fs without waiting"
              % (FAST_PUSHES, t_done - t0))
    else:
        time.sleep(3.0)
        kv.push("w", mx.nd.ones(SHAPE))

    kv._barrier()  # drain: everyone finished pushing
    out = mx.nd.zeros(SHAPE)
    kv.pull("w", out=out)
    total = -out.asnumpy()[0, 0]
    expected = FAST_PUSHES + (nworker - 1)
    assert total == expected, (total, expected)

    # server-side push log proves ordering: all of rank 0's pushes landed
    # before the slow worker's single one
    if rank == 0:
        stats = kv._async_client.call("stats")
        times = [t for t, _ in stats["pushes"]]
        assert len(times) == expected
        assert times[FAST_PUSHES - 1] < times[-1] - 2.0, (
            "slow worker's push should arrive seconds after the burst")
        kv._send_command_to_servers(0, "profile_on")
        stats = kv._async_client.call("stats")
        assert stats["commands"] == [(0, "profile_on")]
    print("rank %d/%d: all dist_async invariants OK" % (rank, nworker))


if __name__ == "__main__":
    main()
