"""Shared, side-effect-free helpers for the dist training worker and its
pytest driver (importing this must not touch jax config — the pytest
session's platform would be contaminated)."""
import numpy as np

PER_WORKER_BATCH = 16
N_SAMPLES_PER_WORKER = 32
EPOCHS = 2


def make_net():
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def full_data(nworker):
    rng = np.random.RandomState(42)
    n = N_SAMPLES_PER_WORKER * nworker
    X = rng.randn(n, 8).astype(np.float32)
    Y = rng.randint(0, 4, (n,)).astype(np.float32)
    return X, Y


def fixed_params(sym):
    import mxnet_tpu as mx
    rng = np.random.RandomState(3)
    shapes, _, _ = sym.infer_shape(data=(PER_WORKER_BATCH, 8))
    return {name: mx.nd.array(
        rng.uniform(-0.1, 0.1, shp).astype(np.float32))
        for name, shp in zip(sym.list_arguments(), shapes)
        if name not in ("data", "softmax_label")}
