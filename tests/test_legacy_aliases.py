"""Legacy op-name surface + remaining tail (ops/legacy_aliases.py):
every name is a reference-registered operator; numerics checked against
the obvious ground truth."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.ops import registry


def _inv(name, inputs, params=None):
    return mx.nd.invoke(name, inputs, params or {})


def test_legacy_capitalized_elemwise():
    a = mx.nd.array(np.array([1., 5., 3.], "f4"))
    b = mx.nd.array(np.array([4., 2., 3.], "f4"))
    np.testing.assert_allclose(_inv("_Plus", [a, b]).asnumpy(), [5, 7, 6])
    np.testing.assert_allclose(_inv("_Maximum", [a, b]).asnumpy(),
                               [4, 5, 3])
    np.testing.assert_allclose(_inv("_Greater", [a, b]).asnumpy(),
                               [0, 1, 0])
    np.testing.assert_allclose(
        _inv("_RMinusScalar", [a], {"scalar": 10.0}).asnumpy(), [9, 5, 7])
    np.testing.assert_allclose(
        _inv("_logical_xor_scalar", [a], {"scalar": 1.0}).asnumpy(),
        [0, 0, 0])
    np.testing.assert_allclose(
        _inv("_hypot_scalar", [mx.nd.array([3.0])],
             {"scalar": 4.0}).asnumpy(), [5.0])


def test_deprecated_layer_names_resolve():
    for legacy, modern in [("BatchNorm_v1", "BatchNorm"),
                           ("Convolution_v1", "Convolution"),
                           ("Pooling_v1", "Pooling"),
                           ("Softmax", "SoftmaxOutput"),
                           ("crop", "Crop"),
                           ("_contrib_ctc_loss", "CTCLoss")]:
        assert registry.get(legacy) is registry.get(modern), legacy


def test_random_surface_names():
    out = _inv("random_uniform", [], {"low": 0.0, "high": 1.0,
                                      "shape": (100,)})
    x = out.asnumpy()
    assert x.shape == (100,) and (x >= 0).all() and (x <= 1).all()
    s = _inv("shuffle", [mx.nd.array(np.arange(16.))], {}).asnumpy()
    assert sorted(s) == list(range(16))


def test_hard_sigmoid_and_grad():
    x = mx.nd.array(np.array([-5., 0., 1., 5.], "f4"))
    x.attach_grad()
    from mxnet_tpu import autograd
    with autograd.record():
        y = mx.nd.hard_sigmoid(x)
    y.backward()
    np.testing.assert_allclose(y.asnumpy(), [0, 0.5, 0.7, 1], rtol=1e-6)
    np.testing.assert_allclose(x.grad.asnumpy(), [0, 0.2, 0.2, 0],
                               rtol=1e-6)


def test_histogram():
    cnt, edges = _inv("_histogram", [mx.nd.array([0.1, 0.5, 0.9, 0.5])],
                      {"bin_cnt": 2, "range": (0.0, 1.0)})
    # half-open bins [a, b) except the last (numpy == reference
    # histogram.cc): both 0.5s land in the second bin
    np.testing.assert_array_equal(cnt.asnumpy(), [1, 3])
    np.testing.assert_allclose(edges.asnumpy(), [0, 0.5, 1.0])


def test_ravel_unravel_roundtrip():
    flat = mx.nd.array([0., 4., 5.])
    coords = _inv("_unravel_index", [flat], {"shape": (2, 3)})
    back = _inv("_ravel_multi_index", [coords], {"shape": (2, 3)})
    np.testing.assert_array_equal(back.asnumpy(), flat.asnumpy())


def test_sparse_retain_dense_lowering():
    d = mx.nd.array(np.arange(12.).reshape(4, 3))
    out = _inv("_sparse_retain", [d, mx.nd.array([0, 2])])
    exp = np.zeros((4, 3))
    exp[[0, 2]] = d.asnumpy()[[0, 2]]
    np.testing.assert_array_equal(out.asnumpy(), exp)


def test_scatter_set_nd():
    lhs = mx.nd.zeros((2, 3))
    idx = mx.nd.array([[0, 1], [1, 2]])   # rows: dim0 coords, dim1 coords
    out = _inv("_scatter_set_nd", [lhs, mx.nd.array([7., 8.]), idx],
               {"shape": (2, 3)})
    exp = np.zeros((2, 3))
    exp[0, 1] = 7.0
    exp[1, 2] = 8.0
    np.testing.assert_array_equal(out.asnumpy(), exp)


def test_square_sum_matches_dense():
    d = np.random.RandomState(0).randn(4, 5).astype("f4")
    out = _inv("_square_sum", [mx.nd.array(d)], {"axis": 1})
    np.testing.assert_allclose(out.asnumpy(), (d * d).sum(1), rtol=1e-6)


def test_sample_family_moments():
    mx.random.seed(7)
    lam = mx.nd.array([4.0, 100.0])
    p = _inv("_sample_poisson", [lam], {"shape": (4000,)}).asnumpy()
    np.testing.assert_allclose(p.mean(axis=1), [4.0, 100.0], rtol=0.1)
    e = _inv("_sample_exponential", [lam], {"shape": (4000,)}).asnumpy()
    np.testing.assert_allclose(e.mean(axis=1), [0.25, 0.01], rtol=0.15)
    k = mx.nd.array([8.0])
    pr = mx.nd.array([0.5])
    nb = _inv("_sample_negative_binomial", [k, pr],
              {"shape": (4000,)}).asnumpy()
    np.testing.assert_allclose(nb.mean(), 8.0, rtol=0.15)  # k(1-p)/p
    mu = mx.nd.array([6.0])
    al = mx.nd.array([0.3])
    g = _inv("_sample_generalized_negative_binomial", [mu, al],
             {"shape": (4000,)}).asnumpy()
    np.testing.assert_allclose(g.mean(), 6.0, rtol=0.15)


def test_rnn_param_concat_and_identity_attr():
    a, b = mx.nd.ones((2, 2)), mx.nd.zeros((1, 2))
    out = _inv("_rnn_param_concat", [a, b], {"dim": 0})
    assert out.shape == (3, 2)
    same = _inv("_identity_with_attr_like_rhs", [a, b])
    np.testing.assert_array_equal(same.asnumpy(), a.asnumpy())


def test_registry_count_meets_target():
    """VERDICT r3 #6: >= 380 reference-registered names."""
    assert len(registry.list_ops()) >= 380
