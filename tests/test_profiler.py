"""Profiler / monitor / visualization tests
(model: reference tests/python/unittest/test_profiler.py)."""
import json

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, monitor, profiler, visualization
from mxnet_tpu.gluon import nn


def test_profiler_chrome_trace(tmp_path):
    fname = str(tmp_path / "prof.json")
    profiler.set_config(filename=fname, profile_all=True,
                        aggregate_stats=True)
    profiler.set_state("run")
    a = mx.nd.ones((16, 16))
    mx.nd.invoke("dot", [a, a], {})
    (a * 3).sum()
    profiler.set_state("stop")
    out = profiler.dump()
    trace = json.load(open(out))
    names = {e.get("name") for e in trace["traceEvents"]}
    assert "dot" in names
    assert "_mul_scalar" in names
    assert all("ts" in e for e in trace["traceEvents"] if e.get("ph") == "X")


def test_profiler_aggregate_stats(tmp_path):
    profiler.set_config(filename=str(tmp_path / "p.json"),
                        aggregate_stats=True)
    profiler.set_state("run")
    a = mx.nd.ones((8,))
    for _ in range(3):
        a + a
    profiler.set_state("stop")
    table = profiler.dumps(reset=True)
    assert "broadcast_add" in table
    line = [ln for ln in table.splitlines() if "broadcast_add" in ln][0]
    assert int(line.split()[1]) >= 3  # call count


def test_profiler_cached_op_events(tmp_path):
    fname = str(tmp_path / "c.json")
    profiler.set_config(filename=fname)
    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.hybridize()
    profiler.set_state("run")
    net(mx.nd.ones((2, 3)))
    profiler.set_state("stop")
    trace = json.load(open(profiler.dump()))
    assert any("CachedOp" in str(e.get("name"))
               for e in trace["traceEvents"])


def test_profiler_pause_resume(tmp_path):
    profiler.set_config(filename=str(tmp_path / "pr.json"),
                        aggregate_stats=True)
    profiler.dumps(reset=True)
    profiler.set_state("run")
    profiler.pause()
    mx.nd.ones((4,)) + 1
    profiler.resume()
    mx.nd.ones((4,)) * 2
    profiler.set_state("stop")
    table = profiler.dumps(reset=True)
    assert "_plus_scalar" not in table
    assert "_mul_scalar" in table


def test_profiler_custom_objects(tmp_path):
    fname = str(tmp_path / "obj.json")
    profiler.set_config(filename=fname)
    profiler.set_state("run")
    with profiler.Task(name="mytask"):
        pass
    c = profiler.Counter(name="ctr")
    c += 2
    profiler.Marker(name="mk").mark()
    profiler.set_state("stop")
    trace = json.load(open(profiler.dump()))
    names = {e.get("name") for e in trace["traceEvents"]}
    assert {"mytask", "ctr", "mk"} <= names


def test_monitor_block():
    mon = monitor.Monitor(1, pattern=".*weight")
    net = nn.Dense(4, in_units=3)
    net.initialize()
    mon.install_block(net)
    mon.tic()
    net(mx.nd.ones((2, 3)))
    res = mon.toc()
    assert len(res) == 1 and "weight" in res[0][1]
    # interval: every other step inactive
    mon2 = monitor.Monitor(2, pattern=".*")
    mon2.install_block(net)
    mon2.tic(); net(mx.nd.ones((2, 3))); r0 = mon2.toc()
    mon2.tic(); net(mx.nd.ones((2, 3))); r1 = mon2.toc()
    assert len(r0) > 0 and len(r1) == 0


def test_monitor_executor():
    from mxnet_tpu import symbol as sym
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=4, name="fc")
    exe = net.bind(ctx=mx.cpu(), args={
        "data": mx.nd.ones((2, 3)),
        "fc_weight": mx.nd.ones((4, 3)),
        "fc_bias": mx.nd.zeros((4,))})
    mon = monitor.Monitor(1, pattern=".*")
    mon.install(exe)
    mon.tic()
    exe.forward()
    res = mon.toc()
    assert any("fc" in name for _, name, _ in res)


def test_print_summary_and_plot():
    from mxnet_tpu import symbol as sym
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=10, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    text = visualization.print_summary(net, shape={"data": (1, 20)})
    assert "fc1" in text and "Total params: 210" in text
    g = visualization.plot_network(net)
    assert g is not None


def test_executor_events_profiled(tmp_path):
    """Executor fwd/bwd emit profiler events (round-2 weak #6: profiling
    was CachedOp-only)."""
    import json
    import mxnet_tpu as mx
    from mxnet_tpu import profiler
    f = str(tmp_path / "exec_profile.json")
    profiler.set_config(profile_symbolic=True, filename=f)
    profiler.set_state("run")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    ex = net.simple_bind(mx.cpu(), data=(8, 3))
    ex.forward(is_train=True, data=np.zeros((8, 3), np.float32),
               softmax_label=np.zeros((8,), np.float32))
    ex.backward()
    profiler.set_state("stop")
    profiler.dump()
    events = json.load(open(f))["traceEvents"]
    names = {e.get("name") for e in events}
    assert "Executor::forward_train" in names
    assert "Executor::backward" in names


def test_group2ctx_raises_loudly():
    import mxnet_tpu as mx
    import pytest as _pytest
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    with _pytest.raises(mx.MXNetError):
        net.simple_bind(mx.cpu(), data=(4, 3),
                        group2ctx={"dev1": mx.cpu(1)})
    with _pytest.raises(mx.MXNetError):
        mx.mod.Module(net, group2ctxs={"dev1": mx.cpu(1)})


def test_fused_fit_step_is_profiled():
    """The atomic donating fit step must appear in the profile like the
    eager Executor::forward does (observability parity for the path the
    bench measures)."""
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4),
        name="softmax")
    X = np.random.rand(32, 8).astype("f4")
    it = mx.io.NDArrayIter(X, np.zeros(32, "f4"), batch_size=16,
                           label_name="softmax_label")
    profiler.set_config(profile_all=True, aggregate_stats=True)
    profiler.set_state("run")
    try:
        mod = mx.mod.Module(sym)
        mod.fit(it, num_epoch=1, kvstore="tpu_sync",
                initializer=mx.initializer.Xavier())
        assert mod._fused is not None
    finally:
        profiler.set_state("stop")
    d = profiler.dumps(reset=True)
    assert "Module::fused_fit_step" in d
