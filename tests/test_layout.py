"""Layout manifests (mxnet_tpu.parallel.layout): the versioned
param -> shard map behind elastic resume and artifact resharding.

Acceptance properties: (1) `partition` tiles any axis near-evenly and
exactly; (2) a manifest round-trips through dict form with a stable
fingerprint, and the fingerprint moves when world/mesh/entries move;
(3) shard -> gather is the identity at any world; (4) `reshard_states`
re-slices a sharded axis 4 -> 3 and 4 -> 6 bitwise, carries the
replicated optimizer/RNG blobs, and drops the world-fingerprinted data
cursors; (5) malformed manifests are refused with a clear error.
"""
import numpy as np
import pytest

from mxnet_tpu.parallel.layout import (LayoutManifest, gather_state,
                                       infer_manifest, partition,
                                       reshard_states, shard_state)


def _state(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "embed.weight": rng.randn(13, 4).astype(np.float32),
        "dense.weight": rng.randn(4, 4).astype(np.float32),
        "dense.bias": rng.randn(4).astype(np.float32),
    }


def _manifest(state, world, sharded=("embed.weight",)):
    shapes = {k: list(v.shape) for k, v in state.items()}
    return LayoutManifest.build(
        shapes, world, sharded_axes={k: 0 for k in sharded})


# ---------------------------------------------------------------------------
# partition + manifest basics
# ---------------------------------------------------------------------------

def test_partition_covers_exactly():
    for n in (1, 3, 7, 13, 64):
        for world in (1, 2, 3, 5, 8):
            parts = partition(n, world)
            assert len(parts) == world
            # contiguous, ordered, exact cover
            cursor = 0
            for start, stop in parts:
                assert start == cursor
                assert stop >= start
                cursor = stop
            assert cursor == n
            sizes = [stop - start for start, stop in parts]
            assert max(sizes) - min(sizes) <= 1


def test_partition_refuses_world_zero():
    with pytest.raises(ValueError):
        partition(4, 0)


def test_manifest_round_trip_and_fingerprint_stability():
    st = _state()
    m = _manifest(st, 4)
    d = m.to_dict()
    back = LayoutManifest.from_dict(d)
    assert back.world == 4
    assert back.fingerprint() == m.fingerprint()
    assert back.to_dict() == d
    # fingerprints are content-addressed: same inputs, same id
    assert _manifest(_state(), 4).fingerprint() == m.fingerprint()


def test_fingerprint_moves_with_world_mesh_and_entries():
    st = _state()
    base = _manifest(st, 4).fingerprint()
    assert _manifest(st, 3).fingerprint() != base
    shapes = {k: list(v.shape) for k, v in st.items()}
    meshed = LayoutManifest.build(shapes, 4,
                                  sharded_axes={"embed.weight": 0},
                                  mesh={"max_slots": 8})
    assert meshed.fingerprint() != base
    fewer = {k: v for k, v in st.items() if k != "dense.bias"}
    assert _manifest(fewer, 4).fingerprint() != base


def test_infer_manifest_defaults_to_replicated():
    st = _state()
    st["__opt__"] = b"opaque"
    m = infer_manifest(st, 3)
    assert m.world == 3
    assert "__opt__" not in m.entries        # blobs are not layout
    for key in ("embed.weight", "dense.weight", "dense.bias"):
        assert m.entries[key]["kind"] == "replicated"


# ---------------------------------------------------------------------------
# shard -> gather identity, resharding
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("world", [1, 2, 3, 5])
def test_shard_then_gather_is_identity(world):
    st = _state()
    m = _manifest(st, world)
    shards = {rank: shard_state(st, m, rank) for rank in range(world)}
    back = gather_state(shards, m)
    for k, v in st.items():
        assert np.array_equal(back[k], v), k


@pytest.mark.parametrize("new_world", [1, 3, 6])
def test_reshard_states_bitwise(new_world):
    st = _state()
    old = _manifest(st, 4)
    per_rank = {r: shard_state(st, old, r) for r in range(4)}
    new_states, new_m = reshard_states(per_rank, old, new_world)
    assert new_m.world == new_world
    assert sorted(new_states) == list(range(new_world))
    if new_world != 4:
        assert new_m.fingerprint() != old.fingerprint()
    back = gather_state(new_states, new_m)
    for k, v in st.items():
        assert np.array_equal(back[k], v), k


def test_reshard_carries_blobs_and_drops_cursors():
    st = _state()
    old = _manifest(st, 2)
    per_rank = {}
    for r in range(2):
        s = shard_state(st, old, r)
        s["__opt__"] = b"\x07optstate"
        s["__rng__"] = b"\x01\x02"
        s["__data_cursor__"] = b"rank-fingerprinted"
        per_rank[r] = s
    new_states, _ = reshard_states(per_rank, old, 3)
    for s in new_states.values():
        # optimizer/RNG are world-invariant under DDP: carried to all
        assert s["__opt__"] == b"\x07optstate"
        assert s["__rng__"] == b"\x01\x02"
        # cursors are (rank, world)-fingerprinted: a resharded run must
        # rebuild them, never inherit a stale one
        assert "__data_cursor__" not in s


def test_gather_missing_rank_raises():
    st = _state()
    m = _manifest(st, 3)
    shards = {r: shard_state(st, m, r) for r in (0, 2)}   # rank 1 gone
    with pytest.raises(KeyError):
        gather_state(shards, m)


def test_part_for_and_shard_array():
    st = _state()
    m = _manifest(st, 3)
    whole = st["embed.weight"]
    rows = 0
    for rank in range(3):
        start, stop = m.part_for("embed.weight", rank)
        piece = m.shard_array("embed.weight", rank, whole)
        assert np.array_equal(piece, whole[start:stop])
        rows += stop - start
    assert rows == whole.shape[0]
    # replicated keys span the whole leading axis
    assert m.part_for("dense.bias", 2) == (0, 4)


# ---------------------------------------------------------------------------
# validation + telemetry
# ---------------------------------------------------------------------------

def test_validate_refuses_malformed_manifests():
    st = _state()
    m = _manifest(st, 2)
    d = m.to_dict()
    d["entries"]["embed.weight"]["kind"] = "diagonal"
    with pytest.raises(ValueError):
        LayoutManifest.from_dict(d)
    d2 = m.to_dict()
    # parts that no longer tile the axis
    d2["entries"]["embed.weight"]["parts"][-1][2] -= 1
    with pytest.raises(ValueError):
        LayoutManifest.from_dict(d2)
    with pytest.raises(ValueError):
        LayoutManifest.from_dict({"format": "something-else"})


def test_reshard_publishes_telemetry():
    from mxnet_tpu import telemetry
    st = _state()
    m = _manifest(st, 2)
    per_rank = {r: shard_state(st, m, r) for r in range(2)}
    c = telemetry.counter("layout/reshards_total",
                          "State resharding operations "
                          "(checkpoint or artifact)")
    before = c.value()
    reshard_states(per_rank, m, 3)
    assert c.value() == before + 1
    g = telemetry.gauge("layout/last_world",
                        "World size the last reshard targeted")
    assert g.value() == 3
