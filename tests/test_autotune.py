"""Auto-tuner: cache round-trip, deterministic chip-free ranking,
version invalidation, the growth guard, and the CLI end-to-end.

Everything here is chip-free: the ranking path under test is the static
cost model (the on-chip measuring path shares all the code above the
scoring function), and the CLI smoke runs one real tuning in a
subprocess against a temp cache file.
"""
import json
import os
import subprocess
import sys

import pytest

from mxnet_tpu import config
from mxnet_tpu.tune import cache as tcache
from mxnet_tpu.tune import cost_model, space, tuner

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ shape keys

def test_shape_bucket_key_rounds_up_pow2():
    key = tcache.shape_bucket_key("bn_act", ((8192, 3136),), "bfloat16")
    assert key == "bn_act|8192x4096|bfloat16"
    key = tcache.shape_bucket_key("take_rows", ((50000, 512), (1000,)),
                                  "float32")
    assert key == "take_rows|65536x512,1024|float32"


def test_bucket_key_is_stable_within_bucket():
    a = tcache.shape_bucket_key("bn_act", ((4097, 100),), "float32")
    b = tcache.shape_bucket_key("bn_act", ((8192, 128),), "float32")
    assert a == b


# ------------------------------------------------------- cache round-trip

def test_cache_round_trip_and_fingerprint(tmp_path):
    path = str(tmp_path / "tuning.json")
    cache = tcache.TuningCache(path=path)
    cache.update_entries({"bn_act|64x64|float32": {
        "op": "bn_act", "config": {"block_r": 64, "block_s": 64},
        "score_us": 1.25, "source": "model", "dtype": "float32"}})
    fp = cache.fingerprint()
    cache.save(path)

    loaded = tcache.TuningCache.load(path)
    assert loaded.version_ok
    assert loaded.lookup("bn_act|64x64|float32") == {"block_r": 64,
                                                     "block_s": 64}
    assert loaded.lookup("missing|1|f32") is None
    assert loaded.fingerprint() == fp
    # saved file is schema-tagged
    raw = json.load(open(path))
    assert raw["format"] == tcache.FORMAT
    assert raw["version"] == tcache.SCHEMA_VERSION


def test_version_mismatch_invalidates_wholesale(tmp_path):
    path = str(tmp_path / "tuning.json")
    payload = {"format": tcache.FORMAT,
               "version": tcache.SCHEMA_VERSION + 999,
               "entries": {"bn_act|64x64|float32": {
                   "config": {"block_r": 8, "block_s": 128}}}}
    with open(path, "w") as f:
        json.dump(payload, f)
    loaded = tcache.TuningCache.load(path)
    assert not loaded.version_ok
    assert loaded.entries == {}         # stale winners are NOT trusted
    # and dispatch-level lookups through the flag-configured path miss
    with config.override(kernel_tuning_cache=path):
        tcache.invalidate_default()
        cfg, _key = tcache.lookup_config("bn_act", ((64, 64),), "float32")
        assert cfg is None
    tcache.invalidate_default()


def test_corrupt_cache_file_is_empty_not_fatal(tmp_path):
    path = str(tmp_path / "tuning.json")
    with open(path, "w") as f:
        f.write("{ not json")
    loaded = tcache.TuningCache.load(path)
    assert loaded.entries == {} and loaded.version_ok


def test_growth_guard_blocks_silent_rewrites(tmp_path):
    cache = tcache.TuningCache()
    cache.update_entries({"k": {"config": {"block_r": 64}}})
    # same config: fine (idempotent re-tune)
    cache.update_entries({"k": {"config": {"block_r": 64}}})
    with pytest.raises(tcache.CacheRewriteError):
        cache.update_entries({"k": {"config": {"block_r": 128}}})
    cache.update_entries({"k": {"config": {"block_r": 128}}},
                         allow_rewrite=True)
    assert cache.lookup("k") == {"block_r": 128}


# ------------------------------------------------------ chip-free ranking

def test_chip_free_ranking_is_deterministic():
    """Acceptance criterion: two chip-free runs produce identical
    rankings (the cost model is pure arithmetic; ties break on the
    config key)."""
    shapes = ((8192, 4096),)
    r1 = tuner.tune("bn_act", shapes, "bfloat16", chip_free=True)
    r2 = tuner.tune("bn_act", shapes, "bfloat16", chip_free=True)
    assert r1["ranking"] == r2["ranking"]
    assert r1["best"]["config"] == r2["best"]["config"]
    assert r1["source"] == "model"


def test_space_is_bounded_and_vmem_feasible():
    for op, shapes in [("bn_act", ((8192, 4096),)),
                       ("scale_bias_act", ((2048, 4096),)),
                       ("take_rows", ((65536, 512), (8192,)))]:
        cands = space.space_for(op, shapes, "bfloat16")
        assert 0 < len(cands) <= 64
        for cfg in cands:
            feat = cost_model.features(op, shapes, "bfloat16", cfg, "v5e")
            assert feat["vmem_frac"] <= 1.0, (op, cfg, feat)


def test_cost_model_fit_recovers_linear_weights():
    rows = []
    times = []
    for cfg in space.space_for("bn_act", ((8192, 4096),), "bfloat16"):
        feat = cost_model.features("bn_act", ((8192, 4096),), "bfloat16",
                                   cfg, "v5e")
        rows.append(feat)
        # synthetic ground truth: 2x HBM time + 3us per grid step
        times.append(2.0 * feat["hbm_time_us"]
                     + 3.0 * feat["grid_overhead_us"])
    m = cost_model.default_model().fit(rows, times)
    pred = [m.predict(r) for r in rows]
    for p, t in zip(pred, times):
        assert abs(p - t) <= 0.05 * max(t, 1.0)


def test_default_config_matches_kernel_modules():
    from mxnet_tpu.kernels import bn_act, mlp, take
    assert space.default_config(
        "bn_act", ((64, 64),), "float32") == bn_act.DEFAULT_CONFIG
    assert space.default_config(
        "scale_bias_act", ((64, 64),), "float32") == mlp.DEFAULT_CONFIG
    assert space.default_config(
        "take_rows", ((64, 128), (4,)), "float32") == take.DEFAULT_CONFIG


# -------------------------------------------------------------- CLI smoke

def test_autotune_cli_end_to_end_chip_free(tmp_path):
    """Tier-1 smoke: tune one op end-to-end through the CLI (interpreter
    host, chip-free ranking), commit to a temp cache, and confirm the
    dispatch layer consumes the winner."""
    path = str(tmp_path / "tuning.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_KERNEL_TUNING_CACHE=path)
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "autotune.py"),
         "--op", "bn_act", "--shape", "256x256", "--dtype", "float32",
         "--chip-free", "--update-cache"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "wrote 1 entry" in proc.stdout, proc.stdout

    loaded = tcache.TuningCache.load(path)
    assert loaded.version_ok
    (key,) = loaded.entries
    assert key == "bn_act|256x256|float32"
    # dispatch consults it (tuned hit, not heuristic default)
    from mxnet_tpu.kernels import tier
    with config.override(kernel_tier="safe", kernel_tuning_cache=path):
        tcache.invalidate_default()
        tier.reset_stats()
        go, cfg = tier.should_dispatch("bn_act", ((200, 200),), "float32")
        assert go and cfg == loaded.lookup(key)
        assert tier.stats()["tuner_hits"] == 1
    tcache.invalidate_default()


def test_committed_cache_matches_a_fresh_chip_free_retune():
    """The committed winners are reproducible: re-ranking any committed
    bn_act bucket chip-free yields the same best config (determinism
    across processes and sessions, not just within one run)."""
    cache = tcache.TuningCache.load(
        os.path.join(REPO, "tools", "kernel_tuning.json"))
    assert cache.version_ok and cache.entries
    checked = 0
    for key, entry in sorted(cache.entries.items()):
        if entry.get("source") != "model" or entry["op"] != "bn_act":
            continue
        shapes = tuple(tuple(s) for s in entry["shapes"])
        result = tuner.tune(entry["op"], shapes, entry["dtype"],
                            chip_free=True)
        assert result["best"]["config"] == entry["config"], key
        checked += 1
        if checked >= 3:                # bound tier-1 time
            break
    assert checked >= 1
