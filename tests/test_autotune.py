"""Auto-tuner: cache round-trip, deterministic chip-free ranking,
version invalidation, the growth guard, and the CLI end-to-end.

Everything here is chip-free: the ranking path under test is the static
cost model (the on-chip measuring path shares all the code above the
scoring function), and the CLI smoke runs one real tuning in a
subprocess against a temp cache file.
"""
import json
import os
import subprocess
import sys

import pytest

from mxnet_tpu import config
from mxnet_tpu.tune import cache as tcache
from mxnet_tpu.tune import cost_model, space, tuner

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ shape keys

def test_shape_bucket_key_rounds_up_pow2():
    key = tcache.shape_bucket_key("bn_act", ((8192, 3136),), "bfloat16")
    assert key == "bn_act|8192x4096|bfloat16"
    key = tcache.shape_bucket_key("take_rows", ((50000, 512), (1000,)),
                                  "float32")
    assert key == "take_rows|65536x512,1024|float32"


def test_bucket_key_is_stable_within_bucket():
    a = tcache.shape_bucket_key("bn_act", ((4097, 100),), "float32")
    b = tcache.shape_bucket_key("bn_act", ((8192, 128),), "float32")
    assert a == b


# ------------------------------------------------------- cache round-trip

def test_cache_round_trip_and_fingerprint(tmp_path):
    path = str(tmp_path / "tuning.json")
    cache = tcache.TuningCache(path=path)
    cache.update_entries({"bn_act|64x64|float32": {
        "op": "bn_act", "config": {"block_r": 64, "block_s": 64},
        "score_us": 1.25, "source": "model", "dtype": "float32"}})
    fp = cache.fingerprint()
    cache.save(path)

    loaded = tcache.TuningCache.load(path)
    assert loaded.version_ok
    assert loaded.lookup("bn_act|64x64|float32") == {"block_r": 64,
                                                     "block_s": 64}
    assert loaded.lookup("missing|1|f32") is None
    assert loaded.fingerprint() == fp
    # saved file is schema-tagged
    raw = json.load(open(path))
    assert raw["format"] == tcache.FORMAT
    assert raw["version"] == tcache.SCHEMA_VERSION


def test_version_mismatch_invalidates_wholesale(tmp_path):
    path = str(tmp_path / "tuning.json")
    payload = {"format": tcache.FORMAT,
               "version": tcache.SCHEMA_VERSION + 999,
               "entries": {"bn_act|64x64|float32": {
                   "config": {"block_r": 8, "block_s": 128}}}}
    with open(path, "w") as f:
        json.dump(payload, f)
    loaded = tcache.TuningCache.load(path)
    assert not loaded.version_ok
    assert loaded.entries == {}         # stale winners are NOT trusted
    # and dispatch-level lookups through the flag-configured path miss
    with config.override(kernel_tuning_cache=path):
        tcache.invalidate_default()
        cfg, _key = tcache.lookup_config("bn_act", ((64, 64),), "float32")
        assert cfg is None
    tcache.invalidate_default()


def test_corrupt_cache_file_is_empty_not_fatal(tmp_path):
    path = str(tmp_path / "tuning.json")
    with open(path, "w") as f:
        f.write("{ not json")
    loaded = tcache.TuningCache.load(path)
    assert loaded.entries == {} and loaded.version_ok


def test_growth_guard_blocks_silent_rewrites(tmp_path):
    cache = tcache.TuningCache()
    cache.update_entries({"k": {"config": {"block_r": 64}}})
    # same config: fine (idempotent re-tune)
    cache.update_entries({"k": {"config": {"block_r": 64}}})
    with pytest.raises(tcache.CacheRewriteError):
        cache.update_entries({"k": {"config": {"block_r": 128}}})
    cache.update_entries({"k": {"config": {"block_r": 128}}},
                         allow_rewrite=True)
    assert cache.lookup("k") == {"block_r": 128}


# ------------------------------------------------------ chip-free ranking

def test_chip_free_ranking_is_deterministic():
    """Acceptance criterion: two chip-free runs produce identical
    rankings (the cost model is pure arithmetic; ties break on the
    config key)."""
    shapes = ((8192, 4096),)
    r1 = tuner.tune("bn_act", shapes, "bfloat16", chip_free=True)
    r2 = tuner.tune("bn_act", shapes, "bfloat16", chip_free=True)
    assert r1["ranking"] == r2["ranking"]
    assert r1["best"]["config"] == r2["best"]["config"]
    assert r1["source"] == "model"


def test_space_is_bounded_and_vmem_feasible():
    for op, shapes in [("bn_act", ((8192, 4096),)),
                       ("scale_bias_act", ((2048, 4096),)),
                       ("take_rows", ((65536, 512), (8192,)))]:
        cands = space.space_for(op, shapes, "bfloat16")
        assert 0 < len(cands) <= 64
        for cfg in cands:
            feat = cost_model.features(op, shapes, "bfloat16", cfg, "v5e")
            assert feat["vmem_frac"] <= 1.0, (op, cfg, feat)


def test_cost_model_fit_recovers_linear_weights():
    rows = []
    times = []
    for cfg in space.space_for("bn_act", ((8192, 4096),), "bfloat16"):
        feat = cost_model.features("bn_act", ((8192, 4096),), "bfloat16",
                                   cfg, "v5e")
        rows.append(feat)
        # synthetic ground truth: 2x HBM time + 3us per grid step
        times.append(2.0 * feat["hbm_time_us"]
                     + 3.0 * feat["grid_overhead_us"])
    m = cost_model.default_model().fit(rows, times)
    pred = [m.predict(r) for r in rows]
    for p, t in zip(pred, times):
        assert abs(p - t) <= 0.05 * max(t, 1.0)


def test_default_config_matches_kernel_modules():
    from mxnet_tpu.kernels import bn_act, mlp, take
    assert space.default_config(
        "bn_act", ((64, 64),), "float32") == bn_act.DEFAULT_CONFIG
    assert space.default_config(
        "scale_bias_act", ((64, 64),), "float32") == mlp.DEFAULT_CONFIG
    assert space.default_config(
        "take_rows", ((64, 128), (4,)), "float32") == take.DEFAULT_CONFIG


# -------------------------------------------------------------- CLI smoke

def test_autotune_cli_end_to_end_chip_free(tmp_path):
    """Tier-1 smoke: tune one op end-to-end through the CLI (interpreter
    host, chip-free ranking), commit to a temp cache, and confirm the
    dispatch layer consumes the winner."""
    path = str(tmp_path / "tuning.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_KERNEL_TUNING_CACHE=path)
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "autotune.py"),
         "--op", "bn_act", "--shape", "256x256", "--dtype", "float32",
         "--chip-free", "--update-cache"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "wrote 1 entry" in proc.stdout, proc.stdout

    loaded = tcache.TuningCache.load(path)
    assert loaded.version_ok
    (key,) = loaded.entries
    assert key == "bn_act|256x256|float32"
    # dispatch consults it (tuned hit, not heuristic default)
    from mxnet_tpu.kernels import tier
    with config.override(kernel_tier="safe", kernel_tuning_cache=path):
        tcache.invalidate_default()
        tier.reset_stats()
        go, cfg = tier.should_dispatch("bn_act", ((200, 200),), "float32")
        assert go and cfg == loaded.lookup(key)
        assert tier.stats()["tuner_hits"] == 1
    tcache.invalidate_default()


def test_committed_cache_matches_a_fresh_chip_free_retune():
    """The committed winners are reproducible: re-ranking any committed
    bn_act bucket chip-free yields the same best config (determinism
    across processes and sessions, not just within one run)."""
    cache = tcache.TuningCache.load(
        os.path.join(REPO, "tools", "kernel_tuning.json"))
    assert cache.version_ok and cache.entries
    checked = 0
    for key, entry in sorted(cache.entries.items()):
        if entry.get("source") != "model" or entry["op"] != "bn_act":
            continue
        shapes = tuple(tuple(s) for s in entry["shapes"])
        result = tuner.tune(entry["op"], shapes, entry["dtype"],
                            chip_free=True)
        assert result["best"]["config"] == entry["config"], key
        checked += 1
        if checked >= 3:                # bound tier-1 time
            break
    assert checked >= 1


# ------------------------------------------------------- attention buckets

def test_attention_space_is_bounded_and_vmem_feasible():
    for op, shapes in [("flash_attn", ((128, 64, 16), (128, 64, 16))),
                       ("flash_attn", ((32, 1024, 64), (32, 1024, 64))),
                       ("flash_attn_paged", ((16, 1, 8, 32), (8, 16))),
                       ("flash_attn_paged", ((8, 4, 8, 64), (8, 16)))]:
        cands = space.space_for(op, shapes, "float32")
        assert 0 < len(cands) <= 64, (op, len(cands))
        for cfg in cands:
            feat = cost_model.features(op, shapes, "float32", cfg, "v5e")
            assert feat["vmem_frac"] <= 1.0, (op, cfg, feat)


def test_paged_space_candidates_are_mosaic_valid():
    """Every enumerated block_h must divide the head count AND give a
    Mosaic-valid lane dim (128-aligned or the full feature width)."""
    for (S, W, H, Dh) in [(16, 1, 8, 32), (8, 4, 8, 64), (4, 5, 2, 128),
                          (3, 1, 4, 8)]:
        for cfg in space.space_for("flash_attn_paged",
                                   ((S, W, H, Dh), (8, 16)), "float32"):
            bh = cfg["block_h"]
            assert H % bh == 0, (H, Dh, cfg)
            assert (bh * Dh) % 128 == 0 or bh == H, (H, Dh, cfg)


def test_attention_default_config_consults_module_hook():
    from mxnet_tpu.kernels import attention
    assert space.default_config(
        "flash_attn", ((128, 64, 16), (128, 64, 16)),
        "float32") == attention.DEFAULT_CONFIG
    # the paged default self-adapts block_h to a Mosaic-valid width
    cfg = space.default_config("flash_attn_paged",
                               ((16, 1, 8, 32), (8, 16)), "float32")
    assert cfg["block_h"] == 8          # widest 128-aligned: 8*32 lanes
    cfg = space.default_config("flash_attn_paged",
                               ((3, 1, 4, 8), (4, 8)), "float32")
    assert cfg["block_h"] == 4          # no 128-aligned divisor: full H


def test_committed_attention_buckets_reproduce():
    """The committed flash_attn / flash_attn_paged winners re-derive
    chip-free — same determinism bar the bn_act buckets carry."""
    cache = tcache.TuningCache.load(
        os.path.join(REPO, "tools", "kernel_tuning.json"))
    checked = {"flash_attn": 0, "flash_attn_paged": 0}
    for key, entry in sorted(cache.entries.items()):
        op = entry["op"]
        if entry.get("source") != "model" or op not in checked \
                or checked[op] >= 2:
            continue
        shapes = tuple(tuple(s) for s in entry["shapes"])
        result = tuner.tune(op, shapes, entry["dtype"], chip_free=True)
        assert result["best"]["config"] == entry["config"], key
        checked[op] += 1
    assert checked["flash_attn"] >= 1, "no committed flash_attn bucket"
    assert checked["flash_attn_paged"] >= 1, \
        "no committed flash_attn_paged bucket"


# ----------------------------------------- recalibration fidelity (v2 model)

def _attention_timing_rows():
    """Synthetic measured rows whose ground truth is carried by the
    fusion-structure features (vpu/dma/tile terms), with only a weak
    bytes term — the regime static bytes/flops cannot rank."""
    rows = []
    for op, shapes in [("flash_attn", ((128, 64, 16), (128, 64, 16))),
                       ("flash_attn", ((32, 1024, 64), (32, 1024, 64))),
                       ("flash_attn_paged", ((16, 1, 8, 32), (8, 16)))]:
        for cfg in space.space_for(op, shapes, "float32"):
            feat = cost_model.features(op, shapes, "float32", cfg, "v5e")
            t = (1.0 * feat["vpu_time_us"] + 0.05 * feat["dma_steps"]
                 + 20.0 * feat["tile_waste"] + 0.2 * feat["hbm_time_us"])
            rows.append({"op": op, "shapes": shapes, "dtype": "float32",
                         "config": cfg, "features": feat, "time_us": t})
    return rows


def test_recalibrate_improves_concordance_beyond_bytes_flops():
    """Satellite acceptance: when measured times carry signal the
    bytes/flops terms cannot see, recalibration must IMPROVE pairwise
    ranking concordance — the new fusion-structure columns are doing
    real work, not just riding along."""
    from mxnet_tpu.tune import timings
    rows = _attention_timing_rows()
    bytes_flops_only = cost_model.LinearCostModel(
        {"vpu_time_us": 0.0, "dma_steps": 0.0, "tile_waste": 0.0})
    _fitted, report = timings.recalibrate(rows,
                                          base_model=bytes_flops_only)
    before = report["before"]["pairwise"]
    after = report["after"]["pairwise"]
    assert before < 1.0, "construction must defeat the bytes/flops model"
    assert after > before, (before, after)
    assert after >= 0.99, after


def test_new_features_are_zero_for_preexisting_ops():
    """The v2 feature columns must not move the committed bn_act /
    scale_bias_act / take_rows rankings: exactly 0.0 there."""
    for op, shapes, cfg in [
            ("bn_act", ((8192, 4096),), {"block_r": 64, "block_s": 512}),
            ("scale_bias_act", ((2048, 4096),),
             {"block_r": 64, "block_f": 512}),
            ("take_rows", ((65536, 512), (8192,)), {"block_d": 512})]:
        feat = cost_model.features(op, shapes, "float32", cfg, "v5e")
        assert feat["vpu_time_us"] == 0.0, op
        assert feat["dma_steps"] == 0.0, op
        assert feat["tile_waste"] == 0.0, op


def test_weights_round_trip_and_v1_rejection(tmp_path):
    """save_weights -> default_model round-trips the v2 file; a v1-era
    file (missing the fusion-structure columns) is cleanly rejected and
    the ship weights win."""
    path = str(tmp_path / "weights.json")
    m = cost_model.LinearCostModel({"vpu_time_us": 7.5, "dma_steps": 0.5})
    cost_model.save_weights(m, path)
    raw = json.load(open(path))
    assert raw["version"] == cost_model.WEIGHTS_VERSION
    assert set(raw["weights"]) == set(cost_model.FEATURE_NAMES)
    with config.override(kernel_cost_model=path):
        loaded = cost_model.default_model()
        assert loaded.weights == m.weights

    stale = dict(raw, version=1)
    del stale["weights"]["vpu_time_us"]
    with open(path, "w") as f:
        json.dump(stale, f)
    with config.override(kernel_cost_model=path):
        assert cost_model.default_model().weights == \
            cost_model.LinearCostModel().weights
