"""Independent numerical validation of the heavy ops against torch (CPU).

The in-repo tests mostly compare against hand-rolled numpy; torch is an
independent reference implementation of the same operator contracts the
reference framework uses (cuDNN-style conv/BN/pooling/CTC semantics), so
agreement here is strong evidence the TPU lowerings compute the right
function."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import mxnet_tpu as mx


def _t(x):
    return torch.from_numpy(np.asarray(x))


def test_conv2d_parity_strides_pad_dilation_groups():
    rng = np.random.RandomState(0)
    for stride, pad, dilate, groups in [
            ((1, 1), (0, 0), (1, 1), 1),
            ((2, 2), (1, 1), (1, 1), 1),
            ((1, 2), (2, 1), (2, 2), 1),
            ((1, 1), (1, 1), (1, 1), 4)]:
        x = rng.randn(2, 8, 14, 14).astype(np.float32)
        w = rng.randn(12, 8 // groups, 3, 3).astype(np.float32)
        b = rng.randn(12).astype(np.float32)
        out = mx.nd.Convolution(
            mx.nd.array(x), mx.nd.array(w), mx.nd.array(b), kernel=(3, 3),
            num_filter=12, stride=stride, pad=pad, dilate=dilate,
            num_group=groups)
        ref = torch.nn.functional.conv2d(
            _t(x), _t(w), _t(b), stride=stride, padding=pad,
            dilation=dilate, groups=groups)
        np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-4,
                                   atol=1e-4)


def test_deconv2d_parity():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 6, 7, 7).astype(np.float32)
    w = rng.randn(6, 4, 4, 4).astype(np.float32)
    out = mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w), kernel=(4, 4),
                              num_filter=4, stride=(2, 2), pad=(1, 1),
                              no_bias=True)
    ref = torch.nn.functional.conv_transpose2d(_t(x), _t(w), stride=2,
                                               padding=1)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_batchnorm_parity_train_and_eval():
    rng = np.random.RandomState(2)
    x = rng.randn(4, 5, 6, 6).astype(np.float32)
    gamma = rng.rand(5).astype(np.float32) + 0.5
    beta = rng.randn(5).astype(np.float32)
    rmean = rng.randn(5).astype(np.float32) * 0.1
    rvar = rng.rand(5).astype(np.float32) + 0.5
    eps, momentum = 1e-5, 0.9

    # training mode: normalize by batch stats
    with mx.autograd.record():  # train-mode flag
        out = mx.nd.BatchNorm(mx.nd.array(x), mx.nd.array(gamma),
                              mx.nd.array(beta), mx.nd.array(rmean.copy()),
                              mx.nd.array(rvar.copy()), eps=eps,
                              momentum=momentum, fix_gamma=False)
    ref = torch.nn.functional.batch_norm(
        _t(x), _t(rmean.copy()), _t(rvar.copy()), _t(gamma), _t(beta),
        training=True, momentum=0.1, eps=eps)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)

    # eval mode: normalize by running stats
    out_e = mx.nd.BatchNorm(mx.nd.array(x), mx.nd.array(gamma),
                            mx.nd.array(beta), mx.nd.array(rmean.copy()),
                            mx.nd.array(rvar.copy()), eps=eps,
                            use_global_stats=True, fix_gamma=False)
    ref_e = torch.nn.functional.batch_norm(
        _t(x), _t(rmean.copy()), _t(rvar.copy()), _t(gamma), _t(beta),
        training=False, eps=eps)
    np.testing.assert_allclose(out_e.asnumpy(), ref_e.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_pooling_parity():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 3, 9, 9).astype(np.float32)
    out = mx.nd.Pooling(mx.nd.array(x), kernel=(3, 3), stride=(2, 2),
                        pad=(1, 1), pool_type="max")
    ref = torch.nn.functional.max_pool2d(_t(x), 3, stride=2, padding=1)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-5)

    out = mx.nd.Pooling(mx.nd.array(x), kernel=(2, 2), stride=(2, 2),
                        pool_type="avg")
    ref = torch.nn.functional.avg_pool2d(_t(x), 2, stride=2)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-5)

    out = mx.nd.Pooling(mx.nd.array(x), kernel=(1, 1), pool_type="avg",
                        global_pool=True)
    ref = _t(x).mean(dim=(2, 3), keepdim=True)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-5)


def test_softmax_and_logsoftmax_parity():
    rng = np.random.RandomState(4)
    x = rng.randn(5, 7).astype(np.float32) * 3
    np.testing.assert_allclose(
        mx.nd.softmax(mx.nd.array(x), axis=1).asnumpy(),
        torch.softmax(_t(x), dim=1).numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        mx.nd.log_softmax(mx.nd.array(x), axis=1).asnumpy(),
        torch.log_softmax(_t(x), dim=1).numpy(), rtol=1e-5, atol=1e-6)


def test_ctc_loss_parity():
    rng = np.random.RandomState(5)
    T, N, C = 12, 3, 6  # time, batch, classes incl. blank
    # mx CTCLoss: data (T, N, C) activations (sequence-major, reference
    # layout), label (N, L) 0-padded with blank at index 0
    acts = rng.randn(T, N, C).astype(np.float32)
    labels = np.array([[1, 2, 3, 0], [2, 3, 0, 0], [4, 5, 1, 2]],
                      np.float32)
    out = mx.nd.CTCLoss(mx.nd.array(acts), mx.nd.array(labels))

    log_probs = torch.log_softmax(_t(acts), dim=2)
    target_lengths = torch.tensor([3, 2, 4])
    targets = torch.tensor([[1, 2, 3, 0], [2, 3, 0, 0], [4, 5, 1, 2]])
    ref = torch.nn.functional.ctc_loss(
        log_probs, targets,
        input_lengths=torch.full((N,), T, dtype=torch.long),
        target_lengths=target_lengths, blank=0, reduction="none")
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_roi_align_parity():
    pytest.importorskip("torchvision")
    rng = np.random.RandomState(6)
    x = rng.randn(1, 4, 16, 16).astype(np.float32)
    rois = np.array([[0, 2.0, 2.0, 10.0, 12.0],
                     [0, 0.0, 0.0, 15.0, 15.0]], np.float32)
    out = mx.nd._contrib_ROIAlign(
        mx.nd.array(x), mx.nd.array(rois), pooled_size=(4, 4),
        spatial_scale=1.0, sample_ratio=2)
    import torchvision
    ref = torchvision.ops.roi_align(_t(x), _t(rois[:, :]), output_size=4,
                                    spatial_scale=1.0, sampling_ratio=2,
                                    aligned=False)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-3,
                               atol=1e-3)


def test_lstm_fused_parity():
    rng = np.random.RandomState(7)
    T, N, I, H = 5, 2, 4, 3
    x = rng.randn(T, N, I).astype(np.float32)

    tl = torch.nn.LSTM(I, H, num_layers=1)
    with torch.no_grad():
        ref_out, (ref_h, ref_c) = tl(_t(x))

    # pack torch weights into the fused RNN parameter layout:
    # [w_ih (4H*I), w_hh (4H*H), b_ih (4H), b_hh (4H)] with mxnet gate
    # order i, f, c, o == torch order i, f, g, o
    w_ih = tl.weight_ih_l0.detach().numpy()
    w_hh = tl.weight_hh_l0.detach().numpy()
    b_ih = tl.bias_ih_l0.detach().numpy()
    b_hh = tl.bias_hh_l0.detach().numpy()
    params = np.concatenate([w_ih.ravel(), w_hh.ravel(), b_ih, b_hh])
    init_h = np.zeros((1, N, H), np.float32)
    init_c = np.zeros((1, N, H), np.float32)
    out = mx.nd.RNN(mx.nd.array(x), mx.nd.array(params),
                    mx.nd.array(init_h), mx.nd.array(init_c),
                    state_size=H, num_layers=1, mode="lstm")
    np.testing.assert_allclose(out.asnumpy(), ref_out.numpy(), rtol=1e-4,
                               atol=1e-4)
