"""Independent numerical validation of the heavy ops against torch (CPU).

The in-repo tests mostly compare against hand-rolled numpy; torch is an
independent reference implementation of the same operator contracts the
reference framework uses (cuDNN-style conv/BN/pooling/CTC semantics), so
agreement here is strong evidence the TPU lowerings compute the right
function."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import mxnet_tpu as mx


def _t(x):
    return torch.from_numpy(np.asarray(x))


def test_conv2d_parity_strides_pad_dilation_groups():
    rng = np.random.RandomState(0)
    for stride, pad, dilate, groups in [
            ((1, 1), (0, 0), (1, 1), 1),
            ((2, 2), (1, 1), (1, 1), 1),
            ((1, 2), (2, 1), (2, 2), 1),
            ((1, 1), (1, 1), (1, 1), 4)]:
        x = rng.randn(2, 8, 14, 14).astype(np.float32)
        w = rng.randn(12, 8 // groups, 3, 3).astype(np.float32)
        b = rng.randn(12).astype(np.float32)
        out = mx.nd.Convolution(
            mx.nd.array(x), mx.nd.array(w), mx.nd.array(b), kernel=(3, 3),
            num_filter=12, stride=stride, pad=pad, dilate=dilate,
            num_group=groups)
        ref = torch.nn.functional.conv2d(
            _t(x), _t(w), _t(b), stride=stride, padding=pad,
            dilation=dilate, groups=groups)
        np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-4,
                                   atol=1e-4)


def test_deconv2d_parity():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 6, 7, 7).astype(np.float32)
    w = rng.randn(6, 4, 4, 4).astype(np.float32)
    out = mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w), kernel=(4, 4),
                              num_filter=4, stride=(2, 2), pad=(1, 1),
                              no_bias=True)
    ref = torch.nn.functional.conv_transpose2d(_t(x), _t(w), stride=2,
                                               padding=1)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_batchnorm_parity_train_and_eval():
    rng = np.random.RandomState(2)
    x = rng.randn(4, 5, 6, 6).astype(np.float32)
    gamma = rng.rand(5).astype(np.float32) + 0.5
    beta = rng.randn(5).astype(np.float32)
    rmean = rng.randn(5).astype(np.float32) * 0.1
    rvar = rng.rand(5).astype(np.float32) + 0.5
    eps, momentum = 1e-5, 0.9

    # training mode: normalize by batch stats
    with mx.autograd.record():  # train-mode flag
        out = mx.nd.BatchNorm(mx.nd.array(x), mx.nd.array(gamma),
                              mx.nd.array(beta), mx.nd.array(rmean.copy()),
                              mx.nd.array(rvar.copy()), eps=eps,
                              momentum=momentum, fix_gamma=False)
    ref = torch.nn.functional.batch_norm(
        _t(x), _t(rmean.copy()), _t(rvar.copy()), _t(gamma), _t(beta),
        training=True, momentum=0.1, eps=eps)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)

    # eval mode: normalize by running stats
    out_e = mx.nd.BatchNorm(mx.nd.array(x), mx.nd.array(gamma),
                            mx.nd.array(beta), mx.nd.array(rmean.copy()),
                            mx.nd.array(rvar.copy()), eps=eps,
                            use_global_stats=True, fix_gamma=False)
    ref_e = torch.nn.functional.batch_norm(
        _t(x), _t(rmean.copy()), _t(rvar.copy()), _t(gamma), _t(beta),
        training=False, eps=eps)
    np.testing.assert_allclose(out_e.asnumpy(), ref_e.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_pooling_parity():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 3, 9, 9).astype(np.float32)
    out = mx.nd.Pooling(mx.nd.array(x), kernel=(3, 3), stride=(2, 2),
                        pad=(1, 1), pool_type="max")
    ref = torch.nn.functional.max_pool2d(_t(x), 3, stride=2, padding=1)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-5)

    out = mx.nd.Pooling(mx.nd.array(x), kernel=(2, 2), stride=(2, 2),
                        pool_type="avg")
    ref = torch.nn.functional.avg_pool2d(_t(x), 2, stride=2)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-5)

    out = mx.nd.Pooling(mx.nd.array(x), kernel=(1, 1), pool_type="avg",
                        global_pool=True)
    ref = _t(x).mean(dim=(2, 3), keepdim=True)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-5)


def test_softmax_and_logsoftmax_parity():
    rng = np.random.RandomState(4)
    x = rng.randn(5, 7).astype(np.float32) * 3
    np.testing.assert_allclose(
        mx.nd.softmax(mx.nd.array(x), axis=1).asnumpy(),
        torch.softmax(_t(x), dim=1).numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        mx.nd.log_softmax(mx.nd.array(x), axis=1).asnumpy(),
        torch.log_softmax(_t(x), dim=1).numpy(), rtol=1e-5, atol=1e-6)


def test_ctc_loss_parity():
    rng = np.random.RandomState(5)
    T, N, C = 12, 3, 6  # time, batch, classes incl. blank
    # mx CTCLoss: data (T, N, C) activations (sequence-major, reference
    # layout), label (N, L) 0-padded with blank at index 0
    acts = rng.randn(T, N, C).astype(np.float32)
    labels = np.array([[1, 2, 3, 0], [2, 3, 0, 0], [4, 5, 1, 2]],
                      np.float32)
    out = mx.nd.CTCLoss(mx.nd.array(acts), mx.nd.array(labels))

    log_probs = torch.log_softmax(_t(acts), dim=2)
    target_lengths = torch.tensor([3, 2, 4])
    targets = torch.tensor([[1, 2, 3, 0], [2, 3, 0, 0], [4, 5, 1, 2]])
    ref = torch.nn.functional.ctc_loss(
        log_probs, targets,
        input_lengths=torch.full((N,), T, dtype=torch.long),
        target_lengths=target_lengths, blank=0, reduction="none")
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_roi_align_parity():
    pytest.importorskip("torchvision")
    rng = np.random.RandomState(6)
    x = rng.randn(1, 4, 16, 16).astype(np.float32)
    rois = np.array([[0, 2.0, 2.0, 10.0, 12.0],
                     [0, 0.0, 0.0, 15.0, 15.0]], np.float32)
    out = mx.nd._contrib_ROIAlign(
        mx.nd.array(x), mx.nd.array(rois), pooled_size=(4, 4),
        spatial_scale=1.0, sample_ratio=2)
    import torchvision
    ref = torchvision.ops.roi_align(_t(x), _t(rois[:, :]), output_size=4,
                                    spatial_scale=1.0, sampling_ratio=2,
                                    aligned=False)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-3,
                               atol=1e-3)


def test_lstm_fused_parity():
    rng = np.random.RandomState(7)
    T, N, I, H = 5, 2, 4, 3
    x = rng.randn(T, N, I).astype(np.float32)

    tl = torch.nn.LSTM(I, H, num_layers=1)
    with torch.no_grad():
        ref_out, (ref_h, ref_c) = tl(_t(x))

    # pack torch weights into the fused RNN parameter layout:
    # [w_ih (4H*I), w_hh (4H*H), b_ih (4H), b_hh (4H)] with mxnet gate
    # order i, f, c, o == torch order i, f, g, o
    w_ih = tl.weight_ih_l0.detach().numpy()
    w_hh = tl.weight_hh_l0.detach().numpy()
    b_ih = tl.bias_ih_l0.detach().numpy()
    b_hh = tl.bias_hh_l0.detach().numpy()
    params = np.concatenate([w_ih.ravel(), w_hh.ravel(), b_ih, b_hh])
    init_h = np.zeros((1, N, H), np.float32)
    init_c = np.zeros((1, N, H), np.float32)
    out = mx.nd.RNN(mx.nd.array(x), mx.nd.array(params),
                    mx.nd.array(init_h), mx.nd.array(init_c),
                    state_size=H, num_layers=1, mode="lstm")
    np.testing.assert_allclose(out.asnumpy(), ref_out.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_conv1d_conv3d_parity():
    rng = np.random.RandomState(8)
    x1 = rng.randn(2, 4, 20).astype(np.float32)
    w1 = rng.randn(6, 4, 5).astype(np.float32)
    out = mx.nd.Convolution(mx.nd.array(x1), mx.nd.array(w1), kernel=(5,),
                            num_filter=6, stride=(2,), pad=(2,),
                            no_bias=True)
    ref = torch.nn.functional.conv1d(_t(x1), _t(w1), stride=2, padding=2)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)

    x3 = rng.randn(1, 2, 6, 6, 6).astype(np.float32)
    w3 = rng.randn(3, 2, 3, 3, 3).astype(np.float32)
    out = mx.nd.Convolution(mx.nd.array(x3), mx.nd.array(w3),
                            kernel=(3, 3, 3), num_filter=3, pad=(1, 1, 1),
                            no_bias=True)
    ref = torch.nn.functional.conv3d(_t(x3), _t(w3), padding=1)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_gru_fused_parity():
    rng = np.random.RandomState(9)
    T, N, I, H = 5, 2, 4, 3
    x = rng.randn(T, N, I).astype(np.float32)
    tg = torch.nn.GRU(I, H, num_layers=1)
    with torch.no_grad():
        ref_out, _ = tg(_t(x))
    params = np.concatenate([
        tg.weight_ih_l0.detach().numpy().ravel(),
        tg.weight_hh_l0.detach().numpy().ravel(),
        tg.bias_ih_l0.detach().numpy(),
        tg.bias_hh_l0.detach().numpy()])
    init_h = np.zeros((1, N, H), np.float32)
    out = mx.nd.RNN(mx.nd.array(x), mx.nd.array(params),
                    mx.nd.array(init_h), state_size=H, num_layers=1,
                    mode="gru")
    np.testing.assert_allclose(out.asnumpy(), ref_out.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_bilinear_sampler_parity_with_grid_sample():
    rng = np.random.RandomState(10)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    # normalized grid in [-1, 1], shape (N, 2, Ho, Wo) with (x, y) rows
    gx = rng.uniform(-1, 1, (2, 6, 6)).astype(np.float32)
    gy = rng.uniform(-1, 1, (2, 6, 6)).astype(np.float32)
    grid = np.stack([gx, gy], axis=1)
    out = mx.nd.BilinearSampler(mx.nd.array(x), mx.nd.array(grid))
    tgrid = torch.from_numpy(np.stack([gx, gy], axis=-1))  # (N,Ho,Wo,2)
    ref = torch.nn.functional.grid_sample(
        _t(x), tgrid, mode="bilinear", padding_mode="zeros",
        align_corners=True)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_instance_and_layer_norm_parity():
    rng = np.random.RandomState(11)
    x = rng.randn(3, 4, 5, 5).astype(np.float32)
    g = rng.rand(4).astype(np.float32) + 0.5
    b = rng.randn(4).astype(np.float32)
    out = mx.nd.InstanceNorm(mx.nd.array(x), mx.nd.array(g), mx.nd.array(b),
                             eps=1e-5)
    ref = torch.nn.functional.instance_norm(_t(x), weight=_t(g), bias=_t(b),
                                            eps=1e-5)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)

    x2 = rng.randn(6, 10).astype(np.float32)
    g2 = rng.rand(10).astype(np.float32) + 0.5
    b2 = rng.randn(10).astype(np.float32)
    out = mx.nd.LayerNorm(mx.nd.array(x2), mx.nd.array(g2), mx.nd.array(b2),
                          axis=-1, eps=1e-5)
    ref = torch.nn.functional.layer_norm(_t(x2), (10,), _t(g2), _t(b2),
                                         eps=1e-5)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_activations_parity():
    rng = np.random.RandomState(12)
    x = (rng.randn(4, 7) * 2).astype(np.float32)
    pairs = [
        (mx.nd.Activation(mx.nd.array(x), act_type="relu"),
         torch.relu(_t(x))),
        (mx.nd.Activation(mx.nd.array(x), act_type="sigmoid"),
         torch.sigmoid(_t(x))),
        (mx.nd.Activation(mx.nd.array(x), act_type="tanh"),
         torch.tanh(_t(x))),
        (mx.nd.Activation(mx.nd.array(x), act_type="softrelu"),
         torch.nn.functional.softplus(_t(x))),
        (mx.nd.LeakyReLU(mx.nd.array(x), act_type="leaky", slope=0.1),
         torch.nn.functional.leaky_relu(_t(x), 0.1)),
        (mx.nd.LeakyReLU(mx.nd.array(x), act_type="elu", slope=1.0),
         torch.nn.functional.elu(_t(x))),
    ]
    for out, ref in pairs:
        np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-5,
                                   atol=1e-6)


def test_embedding_and_take_parity():
    rng = np.random.RandomState(13)
    table = rng.randn(20, 6).astype(np.float32)
    idx = rng.randint(0, 20, (4, 7)).astype(np.float32)
    out = mx.nd.Embedding(mx.nd.array(idx), mx.nd.array(table),
                          input_dim=20, output_dim=6)
    ref = torch.nn.functional.embedding(
        torch.from_numpy(idx.astype(np.int64)), _t(table))
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-6)


def test_deconv_target_shape():
    rng = np.random.RandomState(14)
    x = rng.randn(1, 3, 5, 5).astype(np.float32)
    w = rng.randn(3, 2, 3, 3).astype(np.float32)
    # target == zero-pad natural output (total=0 -> pad=0, adj=0)
    out = mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                              num_filter=2, stride=(2, 2), no_bias=True,
                              target_shape=(11, 11))
    assert out.shape == (1, 2, 11, 11)
    ref = torch.nn.functional.conv_transpose2d(_t(x), _t(w), stride=2)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)
    # targets past the zero-pad natural size are rejected, like the
    # reference InferPad CHECK ("too big target shape")
    with pytest.raises(ValueError, match="too big target shape"):
        mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                            num_filter=2, stride=(2, 2), no_bias=True,
                            target_shape=(12, 12))


def test_deconv_target_shape_smaller_than_natural():
    """Reference InferPad: target_shape REPLACES user pad — pad/adj are
    computed so targets below the zero-pad natural size work too."""
    rng = np.random.RandomState(15)
    x = rng.randn(1, 3, 5, 5).astype(np.float32)
    w = rng.randn(3, 2, 3, 3).astype(np.float32)
    out = mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                              num_filter=2, stride=(2, 2), no_bias=True,
                              target_shape=(9, 9))
    assert out.shape == (1, 2, 9, 9)
    # total=2 -> pad=1, adj=0: equals torch conv_transpose2d(padding=1)
    ref = torch.nn.functional.conv_transpose2d(_t(x), _t(w), stride=2,
                                               padding=1)
    np.testing.assert_allclose(out.asnumpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)
    # user pad is ignored when target_shape is given (reference semantics)
    out2 = mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w),
                               kernel=(3, 3), num_filter=2, stride=(2, 2),
                               pad=(2, 2), no_bias=True,
                               target_shape=(9, 9))
    np.testing.assert_allclose(out2.asnumpy(), out.asnumpy(), rtol=1e-6)


def test_deconv_zero_target_shape_means_unset():
    rng = np.random.RandomState(16)
    x = rng.randn(1, 3, 5, 5).astype(np.float32)
    w = rng.randn(3, 2, 3, 3).astype(np.float32)
    a = mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                            num_filter=2, stride=(2, 2), no_bias=True,
                            target_shape=(0, 0))
    b = mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                            num_filter=2, stride=(2, 2), no_bias=True)
    np.testing.assert_allclose(a.asnumpy(), b.asnumpy(), rtol=1e-6)


def test_lstmp_cell_vs_torch_proj_lstm():
    """gluon.contrib LSTMPCell vs torch.nn.LSTM(proj_size=): identical
    weights -> identical per-step outputs and states."""
    from mxnet_tpu import gluon
    rng = np.random.RandomState(9)
    T, N, I, H, R = 4, 2, 5, 6, 3
    x = rng.randn(N, T, I).astype(np.float32)

    tl = torch.nn.LSTM(I, H, num_layers=1, proj_size=R, batch_first=True)
    with torch.no_grad():
        ref_out, (ref_h, ref_c) = tl(_t(x))

    cell = gluon.contrib.rnn.LSTMPCell(H, projection_size=R, input_size=I)
    cell.initialize()
    p = {k.split("_", 1)[1]: v for k, v in cell.params._params.items()}
    p["i2h_weight"].set_data(mx.nd.array(
        tl.weight_ih_l0.detach().numpy()))
    p["h2h_weight"].set_data(mx.nd.array(
        tl.weight_hh_l0.detach().numpy()))
    p["h2r_weight"].set_data(mx.nd.array(
        tl.weight_hr_l0.detach().numpy()))
    p["i2h_bias"].set_data(mx.nd.array(tl.bias_ih_l0.detach().numpy()))
    p["h2h_bias"].set_data(mx.nd.array(tl.bias_hh_l0.detach().numpy()))

    out, states = cell.unroll(T, mx.nd.array(x), merge_outputs=True)
    np.testing.assert_allclose(out.asnumpy(), ref_out.detach().numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(states[0].asnumpy(),
                               ref_h.detach().numpy()[0], rtol=1e-5,
                               atol=1e-5)
    np.testing.assert_allclose(states[1].asnumpy(),
                               ref_c.detach().numpy()[0], rtol=1e-5,
                               atol=1e-5)
