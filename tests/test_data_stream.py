"""Streaming ingestion tier (mxnet_tpu/data/ — docs/data.md), chip-free.

The contracts under test:

* **exactly-once** — across all dp ranks, one epoch of
  ``ShardedRecordStream`` covers every record of the shard set exactly
  once (no overlap, no gap), for even and uneven world sizes;
* **determinism** — the per-epoch shuffle is a pure function of
  ``(paths, seed, epoch)``: same seed → same order, next epoch →
  same set, different order;
* **parity** — a ``StreamingDataIter`` over raw-tensor records delivers
  the packed rows bit-for-bit (the property that makes streaming-fed
  ``fit`` bitwise-equal to an in-memory feed, pinned end-to-end in
  test_step_sync_budget.py);
* **resume** — a checkpointed cursor ``seek`` replays the remaining
  batches bitwise, and a cursor from a different fleet shape fails
  loudly;
* **shutdown** — closing mid-epoch, with the feeder blocked on the
  bounded queue's backpressure put, unblocks and joins the feeder (the
  unified PrefetchQueue race ImageRecordIter and PrefetchingIter share);
* **packer** — tools/make_recordio.py round-trips through the stream.
"""
import os
import sys

import numpy as np
import pytest

from mxnet_tpu import recordio as rio
from mxnet_tpu.base import MXNetError
from mxnet_tpu.data import (PrefetchQueue, RawTensorDecoder,
                            ShardedRecordStream, StreamingDataIter)

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
from make_recordio import (iter_synth_images, iter_twotower,  # noqa: E402
                           shard_paths, write_shards)

DIM = 5


def _pack(tmp_path, n, shards, name="set"):
    """n raw-tensor records; row i = [i, i+0.5, ...] so the payload
    identifies the sample."""
    rows = np.arange(n, dtype=np.float32)[:, None] \
        + np.arange(DIM, dtype=np.float32)[None, :] / 2.0
    samples = ((float(i), rows[i].tobytes()) for i in range(n))
    recs = write_shards(samples, str(tmp_path / name), shards)
    return recs, rows


def _rec_id(rec):
    _, payload = rio.unpack(rec)
    return int(np.frombuffer(payload, np.float32)[0])


# ------------------------------------------------------------- sharded reads

@pytest.mark.parametrize("world", [2, 3])
def test_exactly_once_across_ranks(tmp_path, world):
    # 53 is prime and not a multiple of anything in sight: uneven shard
    # sizes AND uneven strides, the case where naive splits gap/overlap
    recs, _ = _pack(tmp_path, 53, shards=3)
    seen = []
    for rank in range(world):
        s = ShardedRecordStream(recs, rank=rank, world=world, seed=7)
        ids = [_rec_id(r) for r in s]
        assert len(ids) == s.records_per_epoch()
        seen.append(ids)
    flat = [i for ids in seen for i in ids]
    assert len(flat) == 53                       # no record read twice
    assert sorted(flat) == list(range(53))       # no record missed


def test_shuffle_deterministic_and_epoch_reshuffle(tmp_path):
    recs, _ = _pack(tmp_path, 40, shards=2)
    a = ShardedRecordStream(recs, seed=3)
    b = ShardedRecordStream(recs, seed=3)
    order0 = [_rec_id(r) for r in a]
    assert order0 == [_rec_id(r) for r in b]     # same seed, same order
    a.next_epoch()
    order1 = [_rec_id(r) for r in a]
    assert sorted(order1) == sorted(order0)      # same set...
    assert order1 != order0                      # ...new order
    c = ShardedRecordStream(recs, seed=4)
    assert [_rec_id(r) for r in c] != order0     # seed matters


# ---------------------------------------------------------- streaming iter

def test_streaming_iter_delivers_packed_rows(tmp_path):
    recs, rows = _pack(tmp_path, 24, shards=2)
    it = StreamingDataIter(ShardedRecordStream(recs, seed=1),
                           RawTensorDecoder((DIM,)), batch_size=4)
    try:
        n = 0
        for batch in it:
            data = batch.data[0].asnumpy()
            label = batch.label[0].asnumpy()
            for j in range(4):
                i = int(label[j])
                assert data[j].tobytes() == rows[i].tobytes()  # bitwise
            n += 1
        assert n == it.num_batches == 24 // 4
    finally:
        it.close()


def test_cursor_seek_resumes_bitwise(tmp_path):
    recs, _ = _pack(tmp_path, 32, shards=2)

    def run(it, count=None):
        out = []
        for batch in it:
            out.append((batch.data[0].asnumpy().copy(),
                        batch.label[0].asnumpy().copy()))
            if count is not None and len(out) == count:
                break
        return out

    ref = StreamingDataIter(ShardedRecordStream(recs, seed=2),
                            RawTensorDecoder((DIM,)), batch_size=4)
    try:
        full = run(ref)
    finally:
        ref.close()

    it = StreamingDataIter(ShardedRecordStream(recs, seed=2),
                           RawTensorDecoder((DIM,)), batch_size=4)
    try:
        head = run(it, count=3)
        import json
        cur = json.loads(json.dumps(it.get_cursor()))  # survives a ckpt
        # a FRESH iterator (new process after a kill) seeks to the cursor
        it2 = StreamingDataIter(ShardedRecordStream(recs, seed=2),
                                RawTensorDecoder((DIM,)), batch_size=4)
        try:
            it2.seek(cur)
            assert it2.seeks == 1
            tail = run(it2)
        finally:
            it2.close()
    finally:
        it.close()
    assert len(head) + len(tail) == len(full)
    for (d, l), (rd, rl) in zip(head + tail, full):
        assert d.tobytes() == rd.tobytes()
        assert l.tobytes() == rl.tobytes()


def test_cursor_reflects_consumed_not_read_ahead(tmp_path):
    recs, _ = _pack(tmp_path, 40, shards=2)
    it = StreamingDataIter(ShardedRecordStream(recs, seed=0),
                           RawTensorDecoder((DIM,)), batch_size=4,
                           prefetch_depth=8)
    try:
        start = it.get_cursor()
        next(it)
        # give the feeder time to read far ahead of the consumer
        import time
        time.sleep(0.2)
        cur = it.get_cursor()
        consumed = ShardedRecordStream(recs, seed=0)
        consumed.seek(cur)
        assert consumed.records_consumed() == 4  # one batch, not depth*4
        assert cur != start
    finally:
        it.close()


def test_seek_rejects_foreign_fingerprint(tmp_path):
    recs, _ = _pack(tmp_path, 20, shards=2)
    s = ShardedRecordStream(recs, rank=0, world=2, seed=5)
    cur = s.cursor()
    other = ShardedRecordStream(recs, rank=1, world=2, seed=5)
    with pytest.raises(MXNetError, match="fresh epoch"):
        other.seek(cur)
    reseeded = ShardedRecordStream(recs, rank=0, world=2, seed=6)
    with pytest.raises(MXNetError, match="fresh epoch"):
        reseeded.seek(cur)


def test_mid_epoch_reset_loses_no_records(tmp_path):
    recs, _ = _pack(tmp_path, 40, shards=2)
    it = StreamingDataIter(ShardedRecordStream(recs, seed=1),
                           RawTensorDecoder((DIM,)), batch_size=4,
                           prefetch_depth=6)
    try:
        labels = [next(it).label[0].asnumpy().copy() for _ in range(2)]
        it.reset()  # feeder has read ahead; those records must re-appear
        replay = [b.label[0].asnumpy().copy() for b in it]
        assert len(replay) == 10 - 2  # everything but the consumed two
        got = sorted(int(v) for arr in replay for v in arr)
        want = sorted(set(range(40))
                      - {int(v) for arr in labels for v in arr})
        assert got == want
    finally:
        it.close()


# ------------------------------------------------------- shutdown semantics

def test_mid_epoch_close_unblocks_blocked_feeder(tmp_path):
    """The unified-queue race (io.PrefetchingIter, ImageRecordIter and
    StreamingDataIter all ride PrefetchQueue): close while the feeder is
    parked in the bounded put must stop it, not deadlock or leak."""
    recs, _ = _pack(tmp_path, 80, shards=2)
    for _ in range(5):
        it = StreamingDataIter(ShardedRecordStream(recs, seed=1),
                               RawTensorDecoder((DIM,)), batch_size=4,
                               prefetch_depth=2)
        next(it)  # consume one so the feeder is deep in the epoch
        feeder = it._feeder
        it.close()
        assert not feeder.is_alive()
        assert it._pq.stopped


def test_prefetch_queue_shutdown_races_producer():
    """Direct PrefetchQueue contract: a producer blocked on a FULL queue
    is released by shutdown() and the thread joins."""
    import threading
    pq = PrefetchQueue(1)

    def producer():
        i = 0
        while pq.put(i):
            i += 1
        pq.put_sentinel()

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    assert pq.get() == 0          # let it fill the queue again
    assert pq.shutdown(t, timeout=5.0)
    assert not t.is_alive()


def test_prefetch_shutdown_budget_survives_wall_clock_step(monkeypatch):
    """The shutdown join budget is monotonic: an NTP step (or operator
    `date`) mid-shutdown must neither zero the budget nor stretch it to
    hours. A wall clock that jumps a billion seconds forward on every
    read must not break the join."""
    import threading
    import time as real_time
    from mxnet_tpu.data import pipeline as pipeline_mod

    class JumpyClock:
        def time(self):
            return real_time.time() + 1e9   # NTP stepped, hard

        def __getattr__(self, name):        # monotonic et al: real
            return getattr(real_time, name)

    monkeypatch.setattr(pipeline_mod, "time", JumpyClock())
    pq = PrefetchQueue(1)

    def producer():
        i = 0
        while pq.put(i):
            i += 1
        pq.put_sentinel()

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    assert pq.get() == 0
    assert pq.shutdown(t, timeout=5.0)
    assert not t.is_alive()


# ----------------------------------------------------------------- packer

def test_make_recordio_synth_images_roundtrip(tmp_path):
    from make_recordio import main as mkrec_main
    cv2 = pytest.importorskip("cv2")
    prefix = str(tmp_path / "synth")
    recs = mkrec_main(["synth-images", prefix, "--num-samples", "10",
                       "--side", "8", "--num-shards", "3"])
    assert recs == shard_paths(prefix, 3)
    total = 0
    for s in [ShardedRecordStream(recs, rank=r, world=2, shuffle=False)
              for r in range(2)]:
        for rec in s:
            header, payload = rio.unpack(rec)
            img = cv2.imdecode(np.frombuffer(payload, np.uint8),
                               cv2.IMREAD_COLOR)
            assert img.shape == (8, 8, 3)
            assert 0 <= float(np.asarray(header.label).reshape(-1)[0]) < 10
            total += 1
    assert total == 10


def test_make_recordio_twotower_decodes(tmp_path):
    prefix = str(tmp_path / "inter")
    recs = write_shards(
        iter_twotower(30, users=6, items=4, seed=1), prefix, 2)
    it = StreamingDataIter(ShardedRecordStream(recs, seed=0),
                           RawTensorDecoder((3,)), batch_size=5)
    try:
        rows = np.concatenate([b.data[0].asnumpy() for b in it])
    finally:
        it.close()
    assert rows.shape == (30, 3)
    assert rows[:, 0].max() < 6 and rows[:, 1].max() < 4
    # rating column mirrors the header label the packer wrote
    assert np.isfinite(rows[:, 2]).all()


def test_write_shards_multilabel(tmp_path):
    samples = [(np.array([i, i + 1.0], np.float32),
                np.float32([i]).tobytes()) for i in range(6)]
    recs = write_shards(iter(samples), str(tmp_path / "ml"), 2)
    seen = {}
    for s in [ShardedRecordStream([p], shuffle=False) for p in recs]:
        for rec in s:
            header, payload = rio.unpack(rec)
            i = int(np.frombuffer(payload, np.float32)[0])
            seen[i] = np.asarray(header.label).reshape(-1)
    assert sorted(seen) == list(range(6))
    for i, lab in seen.items():
        assert lab.tolist() == [i, i + 1.0]
