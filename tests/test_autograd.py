"""Autograd tape tests (parity model: tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd as ag
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def test_simple_grad():
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_chain():
    x = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with ag.record():
        y = mx.nd.exp(mx.nd.log(x) * 2.0)  # = x^2
        z = y.sum()
    z.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy(), rtol=1e-5)


def test_multi_input():
    a = mx.nd.array([1.0, 2.0])
    b = mx.nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with ag.record():
        c = (a * b).sum()
    c.backward()
    assert_almost_equal(a.grad, b.asnumpy())
    assert_almost_equal(b.grad, a.asnumpy())


def test_grad_req_add():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with ag.record():
            y = (x * x).sum()
        y.backward()
    assert_almost_equal(x.grad, 3 * 2 * x.asnumpy())


def test_grad_req_write_overwrites():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()  # write
    for _ in range(3):
        with ag.record():
            y = (x * x).sum()
        y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_head_gradient():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with ag.record():
        y = x * 3.0
    y.backward(mx.nd.array([10.0, 100.0]))
    assert_almost_equal(x.grad, np.array([30.0, 300.0]))


def test_fanout_accumulation():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * x + x * 3.0
    y.backward()
    assert_almost_equal(x.grad, np.array([2 * 2.0 + 3.0]))


def test_detach_blocks_grad():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * 2.0
        z = y.detach() * x
    z.backward()
    assert_almost_equal(x.grad, np.array([4.0]))  # only d(z)/dx via second factor


def test_block_grad_op():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = mx.nd.BlockGrad(x * 2.0) * x
    y.backward()
    assert_almost_equal(x.grad, np.array([4.0]))


def test_pause():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * 2.0
        with ag.pause():
            c = x * 5.0  # not recorded
        z = y * c.detach()
    z.backward()
    assert_almost_equal(x.grad, np.array([20.0]))


def test_is_recording_training():
    assert not ag.is_recording()
    with ag.record():
        assert ag.is_recording()
        assert ag.is_training()
    with ag.record(train_mode=False):
        assert not ag.is_training()
    with ag.pause():
        assert not ag.is_recording()


def test_softmax_grad():
    np.random.seed(7)
    check_numeric_gradient(lambda x: mx.nd.softmax(x, axis=-1).square().sum(),
                           [np.random.uniform(-1, 1, (3, 4)).astype(np.float32)])


def test_fc_grad():
    np.random.seed(11)
    x = np.random.uniform(-1, 1, (2, 3)).astype(np.float32)
    w = np.random.uniform(-1, 1, (4, 3)).astype(np.float32)
    b = np.zeros(4, np.float32)
    check_numeric_gradient(
        lambda x_, w_, b_: mx.nd.FullyConnected(x_, w_, b_, num_hidden=4).square().sum(),
        [x, w, b])


def test_conv_grad():
    np.random.seed(13)
    x = np.random.uniform(-1, 1, (1, 2, 5, 5)).astype(np.float32)
    w = np.random.uniform(-1, 1, (3, 2, 3, 3)).astype(np.float32)
    check_numeric_gradient(
        lambda x_, w_: mx.nd.Convolution(x_, w_, no_bias=True, kernel=(3, 3),
                                         num_filter=3).square().sum(),
        [x, w], rtol=5e-2, atol=2e-2)


def test_grad_function_api():
    x = mx.nd.array([1.0, 2.0, 3.0])
    with ag.record():
        y = (x * x).sum()
        g = ag.grad(y, [x])[0] if x._ag else None
    # grad() requires marked vars; mark then redo
    x2 = mx.nd.array([1.0, 2.0, 3.0])
    x2.attach_grad()
    with ag.record():
        y2 = (x2 * x2).sum()
    g2 = ag.grad(y2, x2)
    assert_almost_equal(g2, 2 * x2.asnumpy())


def test_custom_function():
    class Sigmoid(ag.Function):
        def forward(self, x):
            y = 1.0 / (1.0 + mx.nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self._saved
            return dy * y * (1 - y)

    x = mx.nd.random.uniform(-2, 2, shape=(5,))
    x.attach_grad()
    f = Sigmoid()
    with ag.record():
        y = f(x)
    y.backward()
    xn = x.asnumpy()
    sig = 1 / (1 + np.exp(-xn))
    assert_almost_equal(x.grad, sig * (1 - sig), rtol=1e-5)


def test_dropout_grad_and_mode():
    x = mx.nd.ones((1000,))
    x.attach_grad()
    with ag.record():
        y = mx.nd.Dropout(x, p=0.5)
    y.backward()
    yn = y.asnumpy()
    keep = yn != 0
    assert 0.3 < keep.mean() < 0.7
    assert_almost_equal(yn[keep], np.full(keep.sum(), 2.0))
    # grad is mask-scaled
    assert_almost_equal(x.grad.asnumpy()[keep], np.full(keep.sum(), 2.0))
    # not training: identity
    y2 = mx.nd.Dropout(x, p=0.5)
    assert_almost_equal(y2, x)


def test_getitem_grad():
    x = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with ag.record():
        y = x[0].sum()
    y.backward()
    assert_almost_equal(x.grad, np.array([[1.0, 1.0], [0.0, 0.0]]))


def test_mark_variables():
    x = mx.nd.array([1.0, 2.0])
    g = mx.nd.zeros((2,))
    ag.mark_variables([x], [g])
    with ag.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(g, 2 * x.asnumpy())
