"""bench.py's probe/stale machinery (VERDICT r3 weak #1): a TPU-less
round must re-emit the last real-chip result flagged stale — never
headline a CPU number when a TPU measurement exists — and a
deterministic no-TPU host must fail fast instead of burning the
deadline."""
import importlib.util
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(ROOT, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "_LAST_TPU_PATH",
                        str(tmp_path / "BENCH_LAST_TPU.json"))
    return mod


def test_stale_reemit_when_last_tpu_exists(tmp_path, monkeypatch, capsys):
    bench = _load_bench(tmp_path, monkeypatch)
    last = {"metric": "resnet50_module_fit_img_per_sec_b128_bf16",
            "value": 7000.0, "mfu": 0.72, "device": "TPU v5 lite"}
    with open(bench._LAST_TPU_PATH, "w") as f:
        json.dump(last, f)
    assert bench._emit_stale_or_smoke() is True
    out = json.loads(capsys.readouterr().out.strip())
    assert out["value"] == 7000.0
    assert out["stale"] is True and "stale_reason" in out
    assert out["device"] == "TPU v5 lite"   # NOT a CPU line


def test_no_stale_without_history(tmp_path, monkeypatch):
    bench = _load_bench(tmp_path, monkeypatch)
    assert bench._emit_stale_or_smoke() is False


def test_probe_fails_fast_on_deterministic_cpu(tmp_path, monkeypatch):
    """A host where jax resolves straight to CPU (AssertionError, not a
    tunnel timeout) must return after ONE attempt, not retry for the
    whole deadline."""
    import subprocess
    import time as _time
    bench = _load_bench(tmp_path, monkeypatch)
    calls = []

    class R:
        returncode = 1
        stderr = "AssertionError\n"

    def fake_run(*a, **k):
        calls.append(_time.monotonic())
        return R()

    monkeypatch.setattr(subprocess, "run", fake_run)
    t0 = _time.monotonic()
    assert bench.probe_tpu(deadline_s=300, attempt_timeout=60) is False
    assert len(calls) == 1
    assert _time.monotonic() - t0 < 5


def test_probe_retries_on_timeout(tmp_path, monkeypatch):
    import subprocess
    bench = _load_bench(tmp_path, monkeypatch)
    calls = []

    def fake_run(*a, **k):
        calls.append(1)
        if len(calls) < 3:
            raise subprocess.TimeoutExpired(cmd="x", timeout=1)

        class R:
            returncode = 0
            stderr = ""
        return R()

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench.probe_tpu(deadline_s=600, attempt_timeout=60) is True
    assert len(calls) == 3
