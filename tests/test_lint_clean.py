"""Tier-1 gate: the repo is mxlint-clean against its committed baseline.

This is the CI teeth of PR 5 — a new TPU-discipline violation anywhere in
mxnet_tpu/, tools/, or examples/ fails the suite with the exact file:line
and fix hint, while the committed debt (tools/mxlint_baseline.json) is
tolerated but ratcheted: it may only shrink. Chip-free and fast (pure AST
— Layer 2 passes have their own lowering-based tests in test_mxlint.py),
so it is deliberately NOT marked slow.
"""
import os
import subprocess
import sys

from mxnet_tpu import profiler
from mxnet_tpu.analysis import baseline as baseline_mod
from mxnet_tpu.analysis.runner import run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "tools", "mxlint_baseline.json")
SCOPE = [os.path.join(REPO, d) for d in ("mxnet_tpu", "tools", "examples")]

MAX_BASELINE_ENTRIES = 25


def test_repo_is_lint_clean():
    result = run(SCOPE, baseline_path=BASELINE, root=REPO)
    # chrome traces chart lint debt over time (satellite: profiler hook)
    profiler.record_counter("lint/violations",
                            len(result.new) + len(result.baselined))
    assert not result.new, (
        "new mxlint violations (see docs/lint.md; run `python "
        "tools/mxlint.py` locally):\n"
        + "\n".join(d.format() for d in result.new))
    assert not result.stale, (
        "baseline entries no longer fire — pay the ratchet forward with "
        "`python tools/mxlint.py --baseline-update`:\n  "
        + "\n  ".join(result.stale))


def test_baseline_is_bounded():
    entries = baseline_mod.load(BASELINE)
    assert len(entries) <= MAX_BASELINE_ENTRIES, (
        "mxlint baseline grew to %d entries (cap %d): fix violations "
        "instead of baselining them" % (len(entries),
                                        MAX_BASELINE_ENTRIES))


def test_analysis_package_is_import_light():
    """Importing (and running Layer 1 of) the analyzer must initialize
    no XLA backend — the same hygiene `import mxnet_tpu` promises — so
    the CLI and the pre-commit --changed mode stay chip-free and fast."""
    code = (
        "import jax\n"
        "import jax._src.xla_bridge as xb\n"
        "import mxnet_tpu.analysis\n"
        "import mxnet_tpu.analysis.rules_ast\n"
        "import mxnet_tpu.analysis.hlo_passes\n"
        "from mxnet_tpu.analysis import lint_sources\n"
        "lint_sources({'x.py': 'import jax\\n'\n"
        "              'def f(x):\\n    return float(x)\\n'\n"
        "              'g = jax.jit(f)\\n'})\n"
        "assert not xb._backends, "
        "'backends initialized: %r' % list(xb._backends)\n")
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr


FUSION_BYTES_BUDGET_GIB = 42.0   # measured 40.56 at time of writing


def test_step_fusion_bytes_budget(resnet_step_text):
    """MXL505 ratchet: nominal elementwise/layout bytes in the benched
    ResNet-50 fused step (session-scoped lowering from conftest). Like
    the MXL501 convert budget this may only come DOWN — an unfused
    epilogue or f32 widening adds hundreds of MiB and fails here before
    any chip time is spent. The Pallas kernel tier (docs/tuning.md)
    exists to push it lower."""
    from mxnet_tpu.analysis import hlo_passes
    diags = hlo_passes.fusion_bytes_pass(
        resnet_step_text, "resnet50/fused-step", FUSION_BYTES_BUDGET_GIB)
    assert not diags, "\n".join(d.format() for d in diags)


def test_cli_exits_zero_on_repo():
    """The acceptance-criteria invocation, exactly as documented."""
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "mxlint.py"),
         "mxnet_tpu", "tools", "examples"],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
