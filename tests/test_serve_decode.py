"""Continuous-batching decode engine (mxnet_tpu.serve.decode) —
chip-free.

The acceptance property: CONTINUOUS batching changes THROUGHPUT, never
TOKENS. A ragged mix of generations scheduled together (slots refilled
between decode steps, evictions mid-flight) must produce, per request,
the bitwise-identical token sequence the same artifact produces serving
that request alone — greedy and temperature>0 alike — while taking
materially fewer decode steps than static batching, holding the decode
loop to one d2h per step, and passing the MXL508 cache-discipline gate
over the exact lowering being served.
"""
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from mxnet_tpu import profiler, serving
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serve import (DeadlineExceeded, Evicted, GenerateSession,
                             Server, ServerBusy, serve_http)
from mxnet_tpu.serve import decode_model as dm

SPEC = dm.DecoderSpec(vocab=61, dim=32, num_heads=4, num_layers=2,
                      max_prompt_len=8, page_size=4, max_pages_per_slot=8,
                      max_slots=4, num_pages=33)


@pytest.fixture(scope="module")
def params():
    return dm.init_params(SPEC, seed=0)


@pytest.fixture(scope="module")
def art(tmp_path_factory, params):
    path = str(tmp_path_factory.mktemp("decode") / "m.gen.mxtpu")
    meta = serving.export_generate(params, SPEC, path)
    assert meta["format_version"] == 3
    return path


@pytest.fixture(scope="module")
def gm(art):
    # ONE loaded GenerateModel shared by every session in this file:
    # sessions share the model-cached compiled prefill/decode/commit, so
    # the suite pays the compile bill once
    return serving.load_artifact(art)


def _ref(params, prompt, n, temperature=0.0, seed=0):
    return list(dm.reference_generate(params, SPEC, prompt, n,
                                      temperature=temperature, seed=seed))


def _drive(sess, reqs, cap=400):
    rounds = 0
    while not all(r.done() for r in reqs) and rounds < cap:
        sess.run_round()
        rounds += 1
    assert all(r.done() for r in reqs), "scheduler stalled"
    return [r.result(timeout=1.0) for r in reqs]


def _session(model, **kw):
    kw.setdefault("auto_start", False)
    kw.setdefault("timeout_ms", 0)
    return GenerateSession(model, **kw)


# ---------------------------------------------------------------------------
# acceptance: bitwise parity, continuous vs sequential vs dense reference
# ---------------------------------------------------------------------------

WORK = [  # (prompt, max_new, temperature, seed) — ragged on purpose
    ([5, 9, 13], 12, 0.0, 0),
    ([2, 3], 3, 0.0, 0),
    ([4, 4, 4, 4, 6, 7], 8, 0.0, 0),
    ([7], 2, 0.0, 0),
    ([11, 60, 1, 2, 3], 16, 0.0, 0),
    ([8, 8, 9], 5, 0.0, 0),
]


def test_continuous_equals_sequential_bitwise_greedy(gm):
    seq = _session(gm)
    sequential = []
    for p, n, t, s in WORK:
        req = seq.submit(p, max_new_tokens=n, temperature=t, seed=s)
        sequential.append(_drive(seq, [req])[0]["tokens"])
    seq.close(drain=True)

    cont = _session(gm)
    reqs = [cont.submit(p, max_new_tokens=n, temperature=t, seed=s)
            for p, n, t, s in WORK]
    batched = [o["tokens"] for o in _drive(cont, reqs)]
    cont.close(drain=True)
    assert batched == sequential


def test_continuous_equals_sequential_bitwise_temperature(gm):
    work = [(p, n, 0.8, 40 + i) for i, (p, n, _, _) in enumerate(WORK)]
    seq = _session(gm)
    sequential = []
    for p, n, t, s in work:
        req = seq.submit(p, max_new_tokens=n, temperature=t, seed=s)
        sequential.append(_drive(seq, [req])[0]["tokens"])
    seq.close(drain=True)

    cont = _session(gm)
    reqs = [cont.submit(p, max_new_tokens=n, temperature=t, seed=s)
            for p, n, t, s in work]
    batched = [o["tokens"] for o in _drive(cont, reqs)]
    cont.close(drain=True)
    assert batched == sequential


def test_paged_decode_matches_dense_reference(gm, params):
    """KV-correctness oracle: the paged gather/scatter decode must equal
    a dense full-recompute of the same weights token-for-token (greedy:
    fp reduction-order differences cannot flip an argmax here without a
    real indexing bug)."""
    sess = _session(gm)
    reqs = [sess.submit(p, max_new_tokens=n) for p, n, _, _ in WORK]
    outs = _drive(sess, reqs)
    sess.close(drain=True)
    for (p, n, _, _), o in zip(WORK, outs):
        assert o["tokens"] == _ref(params, p, n)


def test_result_reports_latency_metrics(gm):
    sess = _session(gm)
    out = _drive(sess, [sess.submit([5, 9, 13], max_new_tokens=4)])[0]
    sess.close(drain=True)
    assert out["finish_reason"] == "length"
    assert out["ttft_ms"] is not None and out["ttft_ms"] >= 0
    assert out["tpot_ms"] is not None and out["tpot_ms"] >= 0
    assert out["latency_ms"] >= out["ttft_ms"]


# ---------------------------------------------------------------------------
# scheduler: eviction, backpressure, bounded drain
# ---------------------------------------------------------------------------

def test_mid_decode_eviction_frees_pages_admits_queued_and_leaves_survivors_bitwise(gm, params):
    sess = _session(gm)
    free0 = sess.cache.free_pages
    prompts = [[5, 9, 13], [2, 3], [4, 4, 4], [7, 8]]
    reqs = [sess.submit(p, max_new_tokens=12) for p in prompts]
    queued = sess.submit([11, 60, 1], max_new_tokens=12)
    sess.run_round()          # admit 4, queued waits on a slot
    sess.run_round()
    assert sum(s is not None for s in sess._slots) == 4
    victim_pages = next(s.pages for s in sess._slots
                        if s is not None and s.req is reqs[0])
    held = sess.cache.free_pages
    # force a deadline expiry on the first request, mid-decode
    reqs[0].deadline = time.monotonic() - 1.0
    sess.run_round()          # evict victim, admit `queued` SAME round

    with pytest.raises(Evicted) as ei:
        reqs[0].result(timeout=0.1)
    exc = ei.value
    assert exc.tokens and exc.tokens == _ref(params, prompts[0],
                                             12)[:len(exc.tokens)]
    assert exc.cursor["resume_prompt"] == prompts[0] + exc.tokens
    assert exc.retry_after > 0
    # the victim's pages cycled straight into the admitted request
    assert queued in [s.req for s in sess._slots if s is not None]
    newly_held = [s.pages for s in sess._slots
                  if s is not None and s.req is queued][0]
    assert set(victim_pages) & set(newly_held)
    assert sess.cache.free_pages >= held  # nothing leaked
    outs = _drive(sess, reqs[1:] + [queued])
    sess.close(drain=True)
    # survivors and the late admission: bitwise equal to solo runs
    for p, o in zip(prompts[1:] + [[11, 60, 1]], outs):
        assert o["tokens"] == _ref(params, p, 12)
    assert sess.cache.free_pages == free0
    snap = sess.metrics_.snapshot()
    assert snap["requests"]["evicted"] == 1
    assert snap["requests"]["expired"] == 1


def test_evict_computes_retry_after_under_the_queue_lock(gm, monkeypatch):
    """Regression: _evict's retry-after must use the LOCKING
    _retry_after. submit() appends to _pending under _cond, and
    iterating a deque mid-append raises RuntimeError — the unlocked
    variant is only safe from code already holding _cond."""
    sess = _session(gm)
    calls = []
    orig = sess._retry_after
    monkeypatch.setattr(sess, "_retry_after",
                        lambda: (calls.append("locked"), orig())[1])

    def boom():
        raise AssertionError(
            "_evict must not use _retry_after_unlocked: it scans "
            "_pending without _cond while submit() appends under it")

    monkeypatch.setattr(sess, "_retry_after_unlocked", boom)
    req = sess.submit([5, 9, 13], max_new_tokens=8)
    sess.run_round()
    req.deadline = time.monotonic() - 1.0     # force a deadline evict
    sess.run_round()
    with pytest.raises(Evicted) as ei:
        req.result(timeout=0.1)
    assert calls and ei.value.retry_after > 0
    sess.close(drain=True)


def test_page_backpressure_holds_admission_until_pages_free(tmp_path,
                                                            params):
    # same geometry, starved page pool: 6 allocatable pages, so two
    # 3-page requests exhaust it with slots to spare
    tight = SPEC._replace(num_pages=7, max_pages_per_slot=3)
    path = str(tmp_path / "tight.gen.mxtpu")
    serving.export_generate(params, tight, path)
    sess = _session(path)
    reqs = [sess.submit([5, 9], max_new_tokens=10) for _ in range(3)]
    sess.run_round()
    # only two fit page-wise, despite 4 slots
    assert sum(s is not None for s in sess._slots) == 2
    assert sess.cache.free_pages == 0
    outs = _drive(sess, reqs)
    sess.close(drain=True)
    ref = list(dm.reference_generate(params, tight, [5, 9], 10))
    assert [o["tokens"] for o in outs] == [ref] * 3


def test_bounded_drain_evicts_past_budget_with_resumable_cursor(gm,
                                                                params):
    sess = _session(gm, drain_tokens=2)
    prompt = [5, 9, 13]
    full = _ref(params, prompt, 10)
    req = sess.submit(prompt, max_new_tokens=10)
    sess.run_round()          # prefill (token 1) + decode step (token 2)
    sess.run_round()          # decode: token 3
    sess.close(drain=True)    # inline bounded drain: at most 2 more
    with pytest.raises(Evicted) as ei:
        req.result(timeout=0.1)
    exc = ei.value
    assert exc.tokens == full[:5]          # 3 pre-drain + 2 budget
    cursor = exc.cursor
    assert cursor["resume_prompt"] == prompt + exc.tokens
    assert cursor["remaining_tokens"] == 5
    # the cursor actually resumes: greedy continuation equals the tail
    # of the uninterrupted generation (position-keyed sampling)
    sess2 = _session(gm)
    out = _drive(sess2, [sess2.submit(cursor["resume_prompt"],
                                      max_new_tokens=5)])[0]
    sess2.close(drain=True)
    assert exc.tokens + out["tokens"] == full


def test_drain_evicts_queued_requests_with_empty_cursor(gm):
    sess = _session(gm)
    active = sess.submit([5, 9], max_new_tokens=4)
    sess.run_round()
    queued = sess.submit([2, 3], max_new_tokens=4)   # never prefilled
    sess.close(drain=True)
    assert active.result(timeout=0.1)["finish_reason"] == "length"
    with pytest.raises(Evicted) as ei:
        queued.result(timeout=0.1)
    assert ei.value.tokens == []
    assert ei.value.cursor["resume_prompt"] == [2, 3]


def test_queue_depth_rejects_with_cost_model_retry_after(gm):
    sess = _session(gm, queue_depth=2)
    for _ in range(2):
        sess.submit([5], max_new_tokens=4)
    with pytest.raises(ServerBusy) as ei:
        sess.submit([5], max_new_tokens=4)
    assert ei.value.retry_after > 0
    sess.close(drain=False)


def test_eos_stops_generation_early(tmp_path, params):
    base = _ref(params, [5, 9, 13], 6)
    eos_spec = SPEC._replace(eos_id=int(base[2]))
    path = str(tmp_path / "eos.gen.mxtpu")
    serving.export_generate(params, eos_spec, path)
    sess = _session(path)
    out = _drive(sess, [sess.submit([5, 9, 13], max_new_tokens=6)])[0]
    sess.close(drain=True)
    assert out["finish_reason"] == "stop"
    assert out["tokens"] == base[:3]


def test_prompt_and_budget_validation(gm):
    sess = _session(gm)
    with pytest.raises(MXNetError):
        sess.submit([], max_new_tokens=2)
    with pytest.raises(MXNetError):
        sess.submit(list(range(SPEC.max_prompt_len + 1)), max_new_tokens=2)
    with pytest.raises(MXNetError):
        sess.submit([5], max_new_tokens=SPEC.max_context)
    sess.close(drain=False)


# ---------------------------------------------------------------------------
# throughput: continuous must beat static on ragged work (deterministic)
# ---------------------------------------------------------------------------

def test_continuous_takes_at_least_2x_fewer_decode_steps_than_static(gm):
    """The deterministic, load-independent form of the >=2x goodput
    claim: on a mostly-short/one-long ragged workload, static batching
    (a group runs to its last straggler) dispatches >= 2x the compiled
    decode steps continuous batching does for the SAME tokens."""
    rng = np.random.RandomState(0)
    work = []
    for _ in range(3):                      # 3 groups of max_slots
        for j in range(SPEC.max_slots):
            plen = int(rng.randint(2, SPEC.max_prompt_len + 1))
            prompt = rng.randint(2, SPEC.vocab, size=plen).tolist()
            work.append((prompt, 24 if j == SPEC.max_slots - 1 else 2))

    def steps(continuous):
        sess = _session(gm, continuous=continuous, queue_depth=64)
        reqs = [sess.submit(p, max_new_tokens=n) for p, n in work]
        outs = _drive(sess, reqs)
        sess._publish_window(force=True)
        n_steps = sess.metrics_.snapshot()["decode_steps"]
        sess.close(drain=True)
        return n_steps, [o["tokens"] for o in outs]

    s_static, toks_static = steps(False)
    s_cont, toks_cont = steps(True)
    assert toks_cont == toks_static          # scheduling never changes tokens
    assert s_static >= 2 * s_cont, (s_static, s_cont)


# ---------------------------------------------------------------------------
# discipline: sync budget + MXL508 chip-free gate
# ---------------------------------------------------------------------------

def test_decode_loop_sync_budget_one_d2h_per_step_and_prefill(gm):
    sess = _session(gm)                     # warmup happens in init
    profiler.reset_sync_counters()
    reqs = [sess.submit(p, max_new_tokens=n) for p, n, _, _ in WORK[:4]]
    _drive(sess, reqs)
    d2h = profiler.sync_counters()["d2h"]
    prefills = sess.metrics_.prefill_batches
    sess._publish_window(force=True)
    steps = sess.metrics_.snapshot()["decode_steps"]
    assert prefills >= 1 and steps >= 1
    # exactly one fetch per decode step (the sampled tokens) plus one
    # per prefill group (the first tokens) — nothing else syncs
    assert d2h == steps + prefills, (d2h, steps, prefills)
    # the telemetry window publish adds ZERO device transfers
    profiler.reset_sync_counters()
    sess._win_steps = 1
    sess._publish_window(force=True)
    assert profiler.sync_counters()["d2h"] == 0
    sess.close(drain=True)


def test_mxl508_gate_clean_on_served_decode_step(gm):
    sess = _session(gm)
    assert sess.check_discipline() == []
    text = sess.decode_lowered_text()
    sess.close(drain=False)
    # donated cache params are visible in the exact served lowering
    from mxnet_tpu import hlo_stats
    entry = hlo_stats.entry_params(text)
    assert entry[5]["donated"] and entry[6]["donated"]


def test_mxl508_flags_undonated_cache_and_host_transfers(gm):
    import jax
    from mxnet_tpu.analysis import hlo_passes
    sess = _session(gm)
    spec = sess.spec
    S, MP = spec.max_slots, spec.max_pages_per_slot
    pages = jax.ShapeDtypeStruct(
        (spec.num_layers, spec.cache_rows, spec.dim), np.float32)
    args = (jax.ShapeDtypeStruct((S, 1), np.int32),
            jax.ShapeDtypeStruct((S,), np.int32),
            jax.ShapeDtypeStruct((S, MP), np.int32),
            jax.ShapeDtypeStruct((S,), np.float32),
            jax.ShapeDtypeStruct((S,), np.int32), pages, pages)
    undonated = jax.jit(sess.model.decode_exp.call).lower(
        *args).as_text()
    sess.close(drain=False)
    diags = hlo_passes.decode_cache_discipline_pass(
        undonated, "decode_step", cache_params=(5, 6))
    assert len(diags) == 1 and diags[0].rule == "MXL508"
    assert "not donated" in diags[0].message

    def leaky(w):
        jax.debug.callback(lambda v: None, w.sum())
        return w * 2
    text = jax.jit(leaky).lower(np.ones(4, np.float32)).as_text()
    diags = hlo_passes.decode_cache_discipline_pass(
        text, "leaky", cache_params=())
    assert len(diags) == 1 and "host-transfer" in diags[0].message


# ---------------------------------------------------------------------------
# artifact format + loading
# ---------------------------------------------------------------------------

def test_artifact_round_trip_and_version_dispatch(art, tmp_path):
    m = serving.load_artifact(art)
    assert isinstance(m, serving.GenerateModel)
    assert m.meta["format_version"] == 3
    assert sorted(mod["name"] for mod in m.meta["modules"]) == \
        ["commit", "decode", "prefill"]
    assert m.spec == SPEC
    # v3 through the v2 loader: a pointed error, not garbage
    with pytest.raises(MXNetError, match="Generate"):
        serving.CompiledModel.load(art)
    # corrupted magic
    bad = tmp_path / "bad.mxtpu"
    bad.write_bytes(b"NOTMAGIC" + b"\x00" * 64)
    with pytest.raises(MXNetError):
        serving.load_artifact(str(bad))


def test_telemetry_registry_carries_decode_series(gm):
    from mxnet_tpu import telemetry
    sess = _session(gm)
    _drive(sess, [sess.submit([5, 9], max_new_tokens=4)])
    sess._publish_window(force=True)
    sess.close(drain=True)
    snap = telemetry.snapshot()
    for name in ("decode/tokens_per_s", "decode/kv_page_occupancy",
                 "decode/active_slots", "decode/evictions"):
        assert name in snap, name


# ---------------------------------------------------------------------------
# server + HTTP + loadgen integration
# ---------------------------------------------------------------------------

def test_server_autodetects_generate_artifact(gm, params):
    srv = Server(gm)
    try:
        assert srv.mode == "generate"
        out = srv.generate([5, 9, 13], max_new_tokens=6)
        assert out["tokens"] == _ref(params, [5, 9, 13], 6)
        with pytest.raises(MXNetError, match="generate artifact"):
            srv.submit(data=np.zeros((1, 4), np.float32))
        m = srv.metrics()
        assert m["mode"] == "generate"
        assert m["slots"]["max"] == SPEC.max_slots
        assert m["kv_pages"]["total"] == SPEC.num_pages - 1
    finally:
        srv.close(drain=True)
    assert srv.closed


def test_http_generate_round_trip_and_errors(gm, params):
    srv = Server(gm)
    front = serve_http(srv, port=0)
    url = front.address
    try:
        body = json.dumps({"prompt": [5, 9, 13],
                           "max_new_tokens": 6}).encode()
        req = urllib.request.Request(
            url + "/v1/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            out = json.loads(r.read().decode())
        assert out["tokens"] == _ref(params, [5, 9, 13], 6)
        assert out["finish_reason"] == "length"
        assert out["ttft_ms"] >= 0
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                url + "/v1/generate", data=b"{}",
                headers={"Content-Type": "application/json"}),
                timeout=10)
        assert ei.value.code == 400
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            snap = json.loads(r.read().decode())
        assert snap["mode"] == "generate"
    finally:
        front.stop(drain=True)


def test_loadgen_generate_mode_accounting(gm):
    import importlib
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    loadgen = importlib.import_module("serve_loadgen")
    srv = Server(gm)
    try:
        res = loadgen.measure_generate(srv, users=3, requests=9,
                                       prompt_len=3, max_new=5, seed=2)
    finally:
        srv.close(drain=True)
    assert res["completed"] == 9
    assert res["evicted"] == res["rejected"] == res["errors"] == 0
    assert res["tokens_completed"] > 0
    assert res["tokens_per_s_goodput"] > 0
    assert res["ttft_ms"]["p50"] is not None
    assert res["server_metrics"]["requests"]["completed"] >= 9


# ---------------------------------------------------------------------------
# speculative decoding + chunked prefill (format_version 5)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def spec_art(tmp_path_factory, params):
    path = str(tmp_path_factory.mktemp("spec") / "m.spec.mxtpu")
    meta = serving.export_generate(
        params, SPEC, path,
        draft_params=dm.quantize_decoder_params(params), speculate_k=3)
    assert meta["format_version"] == 5
    return path


@pytest.fixture(scope="module")
def sgm(spec_art):
    m = serving.load_artifact(spec_art)
    assert isinstance(m, serving.GenerateModel)
    assert m.speculative and m.has_chunk_prefill
    assert m.speculate_k == 3
    return m


def test_speculative_greedy_and_sampled_bitwise_equal_reference(sgm,
                                                                params):
    """The speculative acceptance property: the draft only sets the
    PACE. Every emitted token is the verifier's position-keyed sample,
    so greedy output is bitwise the target-only stream and sampled
    output IS the target distribution's draw for that (seed, position)
    — asserted as bitwise equality against the dense reference, which
    is strictly stronger than a distributional test."""
    sess = _session(sgm)
    assert sess.speculative and sess.speculate_k == 3
    work = WORK + [(p, n, 0.8, 40 + i)
                   for i, (p, n, _, _) in enumerate(WORK)]
    reqs = [sess.submit(p, max_new_tokens=n, temperature=t, seed=s)
            for p, n, t, s in work]
    outs = _drive(sess, reqs)
    sess.close(drain=True)
    for (p, n, t, s), o in zip(work, outs):
        assert o["tokens"] == _ref(params, p, n, temperature=t, seed=s)
        # per-request draft stats ride the result dict
        assert o["accepted_tokens_per_step"] >= 1.0
        assert 0.0 <= o["draft_acceptance_rate"] <= 1.0


def test_speculative_off_is_graceful_fallback(sgm, gm, params):
    # a v5 artifact serves as a plain engine on request...
    sess = _session(sgm, speculative=False)
    assert not sess.speculative and sess.chunked
    out = _drive(sess, [sess.submit([5, 9, 13], max_new_tokens=8)])[0]
    sess.close(drain=True)
    assert out["tokens"] == _ref(params, [5, 9, 13], 8)
    assert "accepted_tokens_per_step" not in out
    # ...but a v3 artifact cannot be forced speculative
    with pytest.raises(MXNetError, match="draft"):
        _session(gm, speculative=True)


def test_chunked_prefill_long_prompt_bitwise_direct(sgm, params):
    """Prompts past max_prompt_len stream through fixed-shape chunks
    instead of being rejected — and the continuation is bitwise the
    dense reference's, speculating or not."""
    rng = np.random.RandomState(7)
    prompts = [rng.randint(2, SPEC.vocab, size=n).tolist()
               for n in (9, 14, 20)]     # all > max_prompt_len == 8
    for speculative in (None, False):
        sess = _session(sgm, speculative=speculative)
        reqs = [sess.submit(p, max_new_tokens=6, temperature=0.7,
                            seed=3) for p in prompts]
        outs = _drive(sess, reqs)
        sess.close(drain=True)
        for p, o in zip(prompts, outs):
            assert o["tokens"] == _ref(params, p, 6, temperature=0.7,
                                       seed=3), speculative


def test_chunked_prompt_validation_keeps_max_context_cap(sgm):
    sess = _session(sgm)
    # admissible now: longer than max_prompt_len, inside max_context
    sess.submit(list(range(2, 2 + SPEC.max_prompt_len + 2)),
                max_new_tokens=2)
    with pytest.raises(MXNetError, match="max_context"):
        sess.submit([5] * (SPEC.max_context + 1), max_new_tokens=1)
    with pytest.raises(MXNetError, match="max_context"):
        sess.submit([5] * (SPEC.max_context - 2), max_new_tokens=8)
    sess.close(drain=False)


def test_speculative_sync_budget_one_d2h_per_fused_step(sgm):
    """PR-9's sync discipline survives speculation AND chunked prefill:
    ONE packed d2h per fused draft+verify dispatch, ONE per prefill
    batch, ONE per long prompt (its final chunk) — pinned by the
    profiler's transfer counters, not by reading the code."""
    sess = _session(sgm)
    rng = np.random.RandomState(3)
    long_prompt = rng.randint(2, SPEC.vocab, size=13).tolist()
    profiler.reset_sync_counters()
    reqs = [sess.submit(p, max_new_tokens=n) for p, n, _, _ in WORK[:3]]
    reqs.append(sess.submit(long_prompt, max_new_tokens=9))
    _drive(sess, reqs)
    d2h = profiler.sync_counters()["d2h"]
    prefills = sess.metrics_.prefill_batches
    sess._publish_window(force=True)
    snap = sess.metrics_.snapshot()
    steps = snap["decode_steps"]
    assert prefills >= 2 and steps >= 1   # batched group + chunked admit
    assert d2h == steps + prefills, (d2h, steps, prefills)
    # speculation actually engaged, and the gauges were host-computed
    # (speculative steps are per-SLOT consumptions: >= the dispatch
    # count whenever more than one sequence rides a fused window)
    sp = snap["speculative"]
    assert sp["steps"] >= steps and sp["accepted_tokens_per_step"] >= 1.0
    sess.close(drain=True)


def test_eviction_mid_speculation_resumes_bitwise(sgm, gm, params):
    """Cursor semantics under speculation: an eviction lands between
    fused windows, gen[] holds only committed verifier tokens, so the
    cursor resumes bitwise — on a speculative server or a plain one."""
    prompt = [5, 9, 13]
    full = _ref(params, prompt, 24)
    sess = _session(sgm, drain_tokens=2)
    req = sess.submit(prompt, max_new_tokens=24)
    sess.run_round()          # prefill + first fused window
    sess.run_round()
    sess.close(drain=True)    # bounded drain, then evict with cursor
    with pytest.raises(Evicted) as ei:
        req.result(timeout=0.1)
    exc = ei.value
    n_got = len(exc.tokens)
    assert 0 < n_got < 24
    assert exc.tokens == full[:n_got]
    assert exc.cursor["resume_prompt"] == prompt + exc.tokens
    remaining = exc.cursor["remaining_tokens"]
    assert remaining == 24 - n_got
    # resume on a fresh SPECULATIVE session and on a PLAIN v3 session:
    # both stitch to the uninterrupted stream (position-keyed sampling)
    for model in (sgm, gm):
        if len(exc.cursor["resume_prompt"]) > SPEC.max_prompt_len \
                and model is gm:
            continue          # v3 has no chunked prefill for long resumes
        sess2 = _session(model)
        out = _drive(sess2, [sess2.submit(exc.cursor["resume_prompt"],
                                          max_new_tokens=remaining)])[0]
        sess2.close(drain=True)
        assert exc.tokens + out["tokens"] == full


def test_mxl510_gate_clean_on_served_speculative_step(sgm, gm):
    sess = _session(sgm)
    assert sess.check_speculative_discipline() == []
    text = sess.draft_verify_lowered_text()
    sess.close(drain=False)
    from mxnet_tpu import hlo_stats
    entry = hlo_stats.entry_params(text)
    # all FOUR page stores — verifier and draft K/V — donated
    for p in (5, 6, 7, 8):
        assert entry[p]["donated"], p
    # a non-speculative session has nothing to gate
    plain = _session(gm)
    assert plain.check_speculative_discipline() == []
    with pytest.raises(MXNetError, match="not speculative"):
        plain.draft_verify_lowered_text()
    plain.close(drain=False)


def test_v5_artifact_round_trip_and_version_dispatch(spec_art):
    m = serving.load_artifact(spec_art)
    assert m.meta["format_version"] == 5
    assert sorted(mod["name"] for mod in m.meta["modules"]) == \
        ["chunk_prefill", "commit", "decode", "draft_chunk_prefill",
         "draft_verify", "prefill"]
    assert m.meta["generate"]["speculate_k"] == 3
    assert m.spec == SPEC


def test_gluon_converter_matches_decode_model_structure(params):
    """params_from_gluon pulls weights off the example GPT; the family
    contract is that the extracted dict drops into make_prefill/decode.
    Structure check only (example import is heavyweight)."""
    names = set(dm._param_names(SPEC))
    assert set(params) == names
    for k, v in params.items():
        assert v.dtype == np.float32, k


# ---------------------------------------------------------------------------
# artifact resharding: re-target the inference mesh, tokens stay bitwise
# ---------------------------------------------------------------------------

def test_reshard_artifact_serves_bitwise_equal_tokens(art, tmp_path,
                                                      params):
    """`serving.reshard_artifact` re-targets a generate export to a
    different decode mesh (slots / KV page pool) without touching any
    checkpoint. Position-keyed sampling means the served tokens must be
    bitwise-identical on the old and new mesh — cache geometry is a
    throughput knob, never a numerics knob."""
    dst = str(tmp_path / "resharded.gen.mxtpu")
    old_layout = serving.artifact_layout(art)
    assert old_layout is not None
    report = serving.reshard_artifact(art, dst, max_slots=8,
                                      num_pages=65)
    assert report["new_mesh"]["max_slots"] == 8
    assert report["new_mesh"]["num_pages"] == 65
    new_layout = serving.artifact_layout(dst)
    assert new_layout["fingerprint"] != old_layout["fingerprint"]

    work = [([5, 9, 13], 12, 0.8, 100), ([2, 3], 6, 0.8, 101),
            ([11, 60, 1, 2, 3], 10, 0.0, 0)]
    src_srv, dst_srv = Server(art), Server(dst)
    try:
        for prompt, n, temp, seed in work:
            a = src_srv.generate(prompt, max_new_tokens=n,
                                 temperature=temp, seed=seed)
            b = dst_srv.generate(prompt, max_new_tokens=n,
                                 temperature=temp, seed=seed)
            assert list(a["tokens"]) == list(b["tokens"]), \
                "tokens diverged across the mesh reshard"
    finally:
        src_srv.close()
        dst_srv.close()


def test_reshard_artifact_refuses_context_growth(art, tmp_path):
    """The positional sampling table has exactly the old max_context
    rows; a mesh whose page budget would GROW max_context cannot be
    served bitwise and must be refused."""
    dst = str(tmp_path / "grown.gen.mxtpu")
    with pytest.raises(MXNetError, match="max_context"):
        serving.reshard_artifact(art, dst, page_size=16,
                                 max_pages_per_slot=64)


def test_reshard_artifact_needs_bundled_params(tmp_path, params):
    path = str(tmp_path / "lean.gen.mxtpu")
    serving.export_generate(params, SPEC, path, bundle_params=False)
    # the layout record is still there (the mesh exists either way)…
    assert serving.artifact_layout(path) is not None
    # …but without bundled weights the artifact is welded to it
    with pytest.raises(MXNetError, match="bundle"):
        serving.reshard_artifact(path, str(tmp_path / "out.mxtpu"),
                                 max_slots=8)
