"""Elastic-training worker for the kill/resume integration tests
(launched by tools/launch.py, 2 processes, dist_sync).

Trains the shared little net with checkpointing enabled (the launcher
exports MXNET_CHECKPOINT_DIR). The driver test injects
``MXNET_FAULT_INJECT=kill@step=N:rank=0`` into the FIRST incarnation
only; the launcher's supervised restart relaunches the group with
MXNET_RESUME_DIR set, fit() restores the newest snapshot common to both
ranks, and training finishes. Rank 0 dumps the final params so the
driver can compare them BITWISE against an uninterrupted run.
"""
import logging
import os
import sys

logging.basicConfig(level=logging.INFO)  # surface the resume log line

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from tests.dist_train_common import (  # noqa: E402
    make_net, full_data, fixed_params, PER_WORKER_BATCH,
    N_SAMPLES_PER_WORKER, EPOCHS)


def main():
    kv = mx.kv.create("dist_sync")
    rank, n = kv.rank, kv.num_workers
    # deterministic RNG chain: the snapshot carries it, so the resumed
    # incarnation continues the chain this seed starts
    mx.random.seed(7)
    X, Y = full_data(n)
    lo, hi = rank * N_SAMPLES_PER_WORKER, (rank + 1) * N_SAMPLES_PER_WORKER
    it = mx.io.NDArrayIter(X[lo:hi], Y[lo:hi],
                           batch_size=PER_WORKER_BATCH,
                           label_name="softmax_label")
    sym = make_net()
    mod = mx.mod.Module(sym)
    mod.fit(it, num_epoch=EPOCHS, kvstore=kv, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1.0 / (PER_WORKER_BATCH * n)},
            arg_params=fixed_params(sym), initializer=None)
    args, _ = mod.get_params()
    if rank == 0 and os.environ.get("FAULT_TRAIN_DUMP"):
        np.savez(os.environ["FAULT_TRAIN_DUMP"],
                 **{k: v.asnumpy() for k, v in args.items()})
    print("rank %d/%d: elastic training run complete" % (rank, n))
    return 0


if __name__ == "__main__":
    sys.exit(main())
