"""NDArray eager tests (parity model: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import (assert_almost_equal, default_context,
                                  rand_ndarray, with_seed)


def test_creation():
    x = mx.nd.zeros((2, 3))
    assert x.shape == (2, 3)
    assert x.dtype == np.float32
    assert x.asnumpy().sum() == 0
    y = mx.nd.ones((4,), dtype="int32")
    assert y.dtype == np.int32
    z = mx.nd.full((2, 2), 7.0)
    assert (z.asnumpy() == 7).all()
    a = mx.nd.array([[1, 2], [3, 4]])
    assert a.dtype == np.float32
    # int64 narrows to int32 unless x64 is opted in (MXNET_ENABLE_X64=1);
    # the default matches the reference's f32/i32 compute types.
    import jax
    b = mx.nd.array(np.array([1, 2], dtype=np.int64))
    assert b.dtype == (np.int64 if jax.config.jax_enable_x64 else np.int32)
    r = mx.nd.arange(0, 10, 2)
    assert_almost_equal(r, np.arange(0, 10, 2, dtype=np.float32))


def test_elementwise():
    a = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = mx.nd.array([[5.0, 6.0], [7.0, 8.0]])
    assert_almost_equal(a + b, np.array([[6, 8], [10, 12]]))
    assert_almost_equal(a - b, np.array([[-4, -4], [-4, -4]]))
    assert_almost_equal(a * b, np.array([[5, 12], [21, 32]]))
    assert_almost_equal(b / a, np.array([[5, 3], [7 / 3, 2]]), rtol=1e-6)
    assert_almost_equal(a + 1, np.array([[2, 3], [4, 5]]))
    assert_almost_equal(2 * a, np.array([[2, 4], [6, 8]]))
    assert_almost_equal(1 / a, 1 / a.asnumpy())
    assert_almost_equal(a ** 2, a.asnumpy() ** 2)
    assert_almost_equal(2 - a, 2 - a.asnumpy())
    assert_almost_equal(mx.nd.sqrt(a), np.sqrt(a.asnumpy()), rtol=1e-6)
    assert_almost_equal(mx.nd.exp(a), np.exp(a.asnumpy()), rtol=1e-6)
    assert_almost_equal(mx.nd.log(a), np.log(a.asnumpy()), rtol=1e-6)
    assert_almost_equal(mx.nd.negative(a), -a.asnumpy())
    assert_almost_equal(mx.nd.maximum(a, b), np.maximum(a.asnumpy(), b.asnumpy()))


def test_comparison():
    a = mx.nd.array([1.0, 2.0, 3.0])
    b = mx.nd.array([3.0, 2.0, 1.0])
    assert_almost_equal(a == b, np.array([0.0, 1.0, 0.0]))
    assert_almost_equal(a > b, np.array([0.0, 0.0, 1.0]))
    assert_almost_equal(a <= 2, np.array([1.0, 1.0, 0.0]))


def test_inplace():
    a = mx.nd.ones((2, 2))
    aid = id(a)
    a += 1
    assert id(a) == aid
    assert (a.asnumpy() == 2).all()
    a *= 3
    assert (a.asnumpy() == 6).all()


def test_indexing():
    a = mx.nd.array(np.arange(24).reshape(2, 3, 4).astype(np.float32))
    assert_almost_equal(a[0], np.arange(12).reshape(3, 4))
    assert_almost_equal(a[1, 2], np.arange(20, 24))
    assert_almost_equal(a[:, 1], a.asnumpy()[:, 1])
    assert_almost_equal(a[0:1], a.asnumpy()[0:1])
    a[0] = 0
    assert a.asnumpy()[0].sum() == 0
    a[:] = 5
    assert (a.asnumpy() == 5).all()


def test_setitem_array():
    a = mx.nd.zeros((3, 3))
    a[1] = mx.nd.ones((3,))
    assert a.asnumpy()[1].sum() == 3


def test_reshape_transpose():
    a = mx.nd.array(np.arange(12).astype(np.float32))
    b = a.reshape((3, 4))
    assert b.shape == (3, 4)
    c = b.reshape((-1, 2))
    assert c.shape == (6, 2)
    d = b.reshape((0, -1))  # mxnet special code 0: keep dim
    assert d.shape == (3, 4)
    t = b.T
    assert t.shape == (4, 3)
    assert_almost_equal(t, b.asnumpy().T)
    e = b.reshape((-3,))
    assert e.shape == (12,)
    f = a.reshape((-4, 3, 4))
    assert f.shape == (3, 4)


def test_reduce():
    a = mx.nd.array(np.arange(12).reshape(3, 4).astype(np.float32))
    assert a.sum().asscalar() == 66
    assert_almost_equal(a.sum(axis=0), a.asnumpy().sum(axis=0))
    assert_almost_equal(a.mean(axis=1, keepdims=True), a.asnumpy().mean(axis=1, keepdims=True))
    assert a.max().asscalar() == 11
    assert a.min().asscalar() == 0
    assert_almost_equal(mx.nd.sum(a, axis=(0, 1)), 66)
    assert_almost_equal(a.norm(), np.sqrt((a.asnumpy() ** 2).sum()), rtol=1e-6)
    assert_almost_equal(mx.nd.sum(a, axis=0, exclude=True), a.asnumpy().sum(axis=1))


def test_dot():
    a = rand_ndarray((4, 5))
    b = rand_ndarray((5, 6))
    assert_almost_equal(mx.nd.dot(a, b), a.asnumpy() @ b.asnumpy(), rtol=1e-4, atol=1e-5)
    assert_almost_equal(mx.nd.dot(a, b.T, transpose_b=True),
                        a.asnumpy() @ b.asnumpy(), rtol=1e-4, atol=1e-5)
    x = rand_ndarray((2, 3, 4))
    y = rand_ndarray((2, 4, 5))
    assert_almost_equal(mx.nd.batch_dot(x, y),
                        np.matmul(x.asnumpy(), y.asnumpy()), rtol=1e-4, atol=1e-5)


def test_concat_split_stack():
    a = mx.nd.ones((2, 3))
    b = mx.nd.zeros((2, 3))
    c = mx.nd.concat(a, b, dim=0)
    assert c.shape == (4, 3)
    c2 = mx.nd.concat(a, b, dim=1)
    assert c2.shape == (2, 6)
    parts = mx.nd.split(c2, num_outputs=2, axis=1)
    assert parts[0].shape == (2, 3)
    assert_almost_equal(parts[0], a)
    s = mx.nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)


def test_slice_ops():
    a = mx.nd.array(np.arange(24).reshape(4, 6).astype(np.float32))
    s = mx.nd.slice(a, begin=(1, 2), end=(3, 5))
    assert_almost_equal(s, a.asnumpy()[1:3, 2:5])
    s2 = mx.nd.slice_axis(a, axis=1, begin=1, end=4)
    assert_almost_equal(s2, a.asnumpy()[:, 1:4])


def test_take_pick_onehot():
    a = mx.nd.array(np.arange(12).reshape(3, 4).astype(np.float32))
    idx = mx.nd.array(np.array([0, 2], dtype=np.int32), dtype="int32")
    t = mx.nd.take(a, idx)
    assert_almost_equal(t, a.asnumpy()[[0, 2]])
    p = mx.nd.pick(a, mx.nd.array([1, 0, 3]), axis=1)
    assert_almost_equal(p, np.array([1.0, 4.0, 11.0]))
    oh = mx.nd.one_hot(mx.nd.array([0, 2]), depth=3)
    assert_almost_equal(oh, np.array([[1, 0, 0], [0, 0, 1]], dtype=np.float32))


def test_ordering():
    a = mx.nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    assert_almost_equal(mx.nd.sort(a), np.sort(a.asnumpy()))
    assert_almost_equal(mx.nd.argsort(a), np.argsort(a.asnumpy()).astype(np.float32))
    v, i = mx.nd.topk(a, k=2, ret_typ="both")
    assert_almost_equal(v, np.array([[3.0, 2.0], [5.0, 4.0]]))


def test_astype_copy():
    a = mx.nd.ones((2, 2))
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = a.copy()
    c[:] = 9
    assert (a.asnumpy() == 1).all()


def test_context_placement():
    a = mx.nd.ones((2, 2), ctx=mx.cpu())
    assert a.context.device_type == "cpu"
    b = a.as_in_context(default_context())
    assert_almost_equal(a, b)


def test_broadcast():
    a = mx.nd.ones((1, 3))
    b = mx.nd.broadcast_to(a, shape=(4, 3))
    assert b.shape == (4, 3)
    c = mx.nd.broadcast_axis(mx.nd.ones((1, 1)), axis=(0, 1), size=(2, 5))
    assert c.shape == (2, 5)


def test_expand_squeeze_flip():
    a = mx.nd.ones((2, 3))
    assert a.expand_dims(0).shape == (1, 2, 3)
    assert a.expand_dims(0).squeeze(0).shape == (2, 3)
    x = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    assert_almost_equal(x.flip(axis=1), np.array([[2, 1], [4, 3]]))


def test_where_clip():
    cond = mx.nd.array([1.0, 0.0, 1.0])
    x = mx.nd.array([1.0, 2.0, 3.0])
    y = mx.nd.array([4.0, 5.0, 6.0])
    assert_almost_equal(mx.nd.where(cond, x, y), np.array([1.0, 5.0, 3.0]))
    assert_almost_equal(x.clip(1.5, 2.5), np.array([1.5, 2.0, 2.5]))


@with_seed(42)
def test_random_reproducible():
    mx.random.seed(7)
    a = mx.nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(7)
    b = mx.nd.random.uniform(shape=(5,)).asnumpy()
    assert (a == b).all()
    c = mx.nd.random.uniform(shape=(5,)).asnumpy()
    assert not (b == c).all()


@with_seed()
def test_random_moments():
    u = mx.nd.random.uniform(0, 1, shape=(10000,))
    assert abs(u.asnumpy().mean() - 0.5) < 0.02
    n = mx.nd.random.normal(0, 1, shape=(10000,))
    assert abs(n.asnumpy().mean()) < 0.05
    assert abs(n.asnumpy().std() - 1.0) < 0.05
    r = mx.nd.random.randint(0, 10, shape=(1000,))
    assert r.asnumpy().min() >= 0 and r.asnumpy().max() < 10


def test_save_load(tmp_path):
    fname = str(tmp_path / "nd.bin")
    d = {"w": mx.nd.ones((2, 3)), "b": mx.nd.zeros((4,))}
    mx.nd.save(fname, d)
    loaded = mx.nd.load(fname)
    assert set(loaded.keys()) == {"w", "b"}
    assert_almost_equal(loaded["w"], d["w"])
    lst = [mx.nd.ones((2,)), mx.nd.zeros((3,))]
    mx.nd.save(fname, lst)
    l2 = mx.nd.load(fname)
    assert len(l2) == 2 and l2[0].shape == (2,)


def test_waitall_sync():
    a = mx.nd.ones((100, 100))
    for _ in range(5):
        a = a * 1.00001
    mx.nd.waitall()
    a.wait_to_read()
    assert a.asnumpy().shape == (100, 100)


def test_iter_len():
    a = mx.nd.array(np.arange(6).reshape(3, 2).astype(np.float32))
    assert len(a) == 3
    rows = [r.asnumpy() for r in a]
    assert len(rows) == 3
    assert_almost_equal(rows[1], np.array([2.0, 3.0]))
