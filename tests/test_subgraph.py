"""Subgraph partitioning framework tests (parity model:
tests/python/unittest/test_subgraph_op.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import subgraph as sg


def _conv_bn_relu_net():
    net = mx.sym.Convolution(mx.sym.Variable("data"), kernel=(3, 3),
                             num_filter=8, pad=(1, 1), name="conv1")
    net = mx.sym.BatchNorm(net, name="bn1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg",
                         kernel=(1, 1), name="gap")
    net = mx.sym.Flatten(net, name="flat")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _op_names(sym):
    return [n.op.name for n in sym._topo() if not n.is_variable]


def test_partition_reduces_nodes_and_matches_numerics():
    net = _conv_bn_relu_net()
    part = net.get_backend_symbol("default")
    base_ops = _op_names(net)
    part_ops = _op_names(part)
    assert "_sg_conv_bn_act" in "".join(part_ops)
    assert len(part_ops) == len(base_ops) - 2  # conv+bn+relu -> 1 node
    # same arguments surface (weights reachable through the fused node)
    assert set(part.list_arguments()) == set(net.list_arguments())
    assert set(part.list_auxiliary_states()) == set(net.list_auxiliary_states())

    data = np.random.RandomState(0).randn(2, 3, 8, 8).astype(np.float32)
    m1 = mx.mod.Module(net)
    m1.bind([("data", data.shape)], for_training=False)
    mx.random.seed(5)
    m1.init_params(mx.initializer.Xavier())
    arg, aux = m1.get_params()

    m2 = mx.mod.Module(part)
    m2.bind([("data", data.shape)], for_training=False)
    m2.init_params(arg_params=arg, aux_params=aux, force_init=True)

    batch = mx.io.DataBatch(data=[mx.nd.array(data)])
    m1.forward(batch, is_train=False)
    m2.forward(batch, is_train=False)
    np.testing.assert_allclose(m1.get_outputs()[0].asnumpy(),
                               m2.get_outputs()[0].asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_partitioned_training_matches_eager():
    """Training through the fused node: gradients AND BatchNorm moving
    stats must match the unpartitioned graph."""
    rng = np.random.RandomState(1)
    X = rng.randn(64, 3, 8, 8).astype(np.float32)
    y = rng.randint(0, 4, 64).astype(np.float32)
    net = _conv_bn_relu_net()
    part = net.get_backend_symbol("default")

    mods = []
    for s in (net, part):
        it = mx.io.NDArrayIter(X, y, batch_size=16)
        mod = mx.mod.Module(s)
        mod.bind(it.provide_data, it.provide_label)
        mx.random.seed(9)
        mod.init_params(mx.initializer.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        for _ in range(2):
            it.reset()
            for b in it:
                mod.forward_backward(b)
                mod.update()
        mods.append(mod)

    a1, x1 = mods[0].get_params()
    a2, x2 = mods[1].get_params()
    for k in a1:
        np.testing.assert_allclose(a1[k].asnumpy(), a2[k].asnumpy(),
                                   rtol=2e-4, atol=2e-5, err_msg=k)
    for k in x1:  # BN moving stats routed through fused aux slots
        np.testing.assert_allclose(x1[k].asnumpy(), x2[k].asnumpy(),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_env_flag_partitions_at_bind():
    net = _conv_bn_relu_net()
    with mx.config.override(subgraph_backend="default"):
        mod = mx.mod.Module(net)
        mod.bind([("data", (2, 3, 8, 8))], [("softmax_label", (2,))])
        fused = [n for n in mod._exec._symbol._topo()
                 if not n.is_variable and n.op.name.startswith("_sg_")]
        assert fused, "bind should have partitioned via MXNET_SUBGRAPH_BACKEND"


class ExpLogSelector(sg.SubgraphSelector):
    def select(self, node):
        return node.op.name == "exp"

    def select_output(self, node, output_node):
        return output_node.op.name == "log"


class ExpLogProperty(sg.SubgraphProperty):
    op_name = "_sg_exp_log"

    def create_subgraph_selector(self):
        return ExpLogSelector()


# module level: several tests below use this backend, in any order
sg.register_backend("explog_test", [ExpLogProperty()])


def test_custom_property_and_selector():
    """User-defined backend: fuse exp -> log chains."""
    net = mx.sym.log(mx.sym.exp(mx.sym.Variable("data") * 2.0))
    part = net.get_backend_symbol("explog_test")
    names = _op_names(part)
    assert any(n.startswith("_sg_exp_log") for n in names), names

    ex = part.bind(mx.cpu(), {"data": mx.nd.array([[1.0, 2.0]])})
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, [[2.0, 4.0]], rtol=1e-6)


def test_no_fuse_when_interior_output_escapes():
    """A chain whose interior value is also consumed elsewhere must not
    collapse (the escape would lose that output)."""
    d = mx.sym.Variable("data")
    e = mx.sym.exp(d)
    net = mx.sym.log(e) + e  # e escapes the would-be exp->log chain
    part = net.get_backend_symbol("explog_test")
    assert not any(n.op.name.startswith("_sg_exp_log")
                   for n in part._topo() if not n.is_variable)


def test_unknown_backend_raises():
    with pytest.raises(KeyError, match="nonexistent"):
        mx.sym.Variable("x").get_backend_symbol("nonexistent")


def test_partition_deep_graph_no_recursion_error():
    d = mx.sym.Variable("data")
    net = mx.sym.log(mx.sym.exp(d))
    for _ in range(1500):
        net = net + 0.0
    part = net.get_backend_symbol("explog_test")  # must not RecursionError
    assert any(n.op.name.startswith("_sg_exp_log")
               for n in part._topo() if not n.is_variable)


def test_config_flag_available_without_subgraph_import():
    import subprocess, sys
    code = ("import jax; jax.config.update('jax_platforms','cpu');"
            "import mxnet_tpu as mx;"
            "cm = mx.config.override(subgraph_backend='default');"
            "cm.__enter__(); print('flag-ok')")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=240)
    assert "flag-ok" in r.stdout, r.stderr[-500:]


def test_partitioned_symbol_tojson_refuses_loudly():
    net = _conv_bn_relu_net()
    part = net.get_backend_symbol("default")
    with pytest.raises(Exception, match="re-apply get_backend_symbol"):
        part.tojson()
    net.tojson()  # the original still serializes


def test_raw_bind_honors_backend_flag():
    """Symbol.bind must partition under MXNET_SUBGRAPH_BACKEND too."""
    net = mx.sym.log(mx.sym.exp(mx.sym.Variable("data")))
    with mx.config.override(subgraph_backend="explog_test"):
        ex = net.bind(mx.cpu(), {"data": mx.nd.array([1.0, 2.0])})
    fused = [n.op.name for n in ex._symbol._topo()
             if not n.is_variable and n.op.name.startswith("_sg_")]
    assert fused, "raw bind ignored the subgraph backend flag"
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), [1.0, 2.0],
                               rtol=1e-6)
