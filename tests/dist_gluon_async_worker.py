"""N-process Gluon Trainer over kvstore='dist_async' (launched by
tests/test_kvstore_async_compression.py::test_gluon_trainer_dist_async).

Each rank trains independently against the rank-0 apply-on-push server
(update_on_kvstore: the optimizer runs server-side, reference
python/mxnet/gluon/trainer.py _init_kvstore dist default). Invariants:
loss decreases on every rank, no barrier stalls a fast worker, and the
final weights came from the server (both ranks pull the same values
after a settle pass)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd
from mxnet_tpu.parallel import dist

dist.init()
jax.devices()  # collective distributed-backend init, main thread, all ranks


def main():
    rank = dist.rank()
    n = dist.num_workers()
    rng = np.random.RandomState(100 + rank)

    net = gluon.nn.Dense(1, use_bias=True)
    net.initialize(mx.initializer.Constant(0.0))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05},
                            kvstore="dist_async")

    # shared linear target y = 2x + 1 — every rank's pushes help
    losses = []
    t0 = time.time()
    for step in range(40):
        x = mx.nd.array(rng.randn(16, 1).astype("f4"))
        y = x * 2.0 + 1.0
        with autograd.record():
            out = net(x)
            loss = ((out - y) ** 2).mean()
        loss.backward()
        trainer.step(16)
        losses.append(float(loss.asnumpy()))
    wall = time.time() - t0

    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])
    # async: a rank never waits for the group, so per-step wall time stays
    # bounded even if another rank lags
    assert wall < 60, wall

    # settle: pull the server's current weights; all ranks see the server's
    # single source of truth
    kv = trainer._kvstore
    w = mx.nd.zeros((1, 1))
    kv.pull(0, out=w, ignore_sparse=False)
    print("rank %d/%d: dist_async gluon trained, loss %.4f -> %.4f, "
          "server w=%.3f" % (rank, n, losses[0], losses[-1],
                             float(w.asnumpy().ravel()[0])))
    assert 1.0 < float(w.asnumpy().ravel()[0]) < 3.0  # near the true 2.0
    # final sync: rank 0 hosts the server THREAD, so it must outlive the
    # other ranks' pushes (the one legitimate barrier in an async job —
    # the reference's server processes likewise stop only at shutdown)
    kv._barrier()
    print("rank %d/%d: gluon dist_async invariants OK" % (rank, n))


if __name__ == "__main__":
    main()
