"""Pallas kernel tier: interpreter-mode parity, dispatch guards, graph
fusion, and the chip-free acceptance export.

Every kernel runs here in interpreter mode (CPU backend auto-selects it),
so fwd AND bwd parity against the pure-JAX reference is tested on every
tier-1 run with no accelerator. Gradients are bitwise-equal by
construction — each kernel's custom_vjp bwd is the vjp of the reference —
so grad tolerances are exact; bf16 FORWARD tolerances allow a couple of
ulp because the kernel applies its per-channel coefficients in f32 (more
precise than the reference's bf16 apply).
"""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import config, hlo_stats
from mxnet_tpu import symbol as sym
from mxnet_tpu.kernels import bn_act, mlp, take, tier
from mxnet_tpu.tune import cache as tcache

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tol(dt):
    return 8e-2 if dt == jnp.bfloat16 else 1e-5


def _maxerr(a, b):
    return float(jnp.max(jnp.abs(jnp.asarray(a, jnp.float32)
                                 - jnp.asarray(b, jnp.float32))))


# ---------------------------------------------------------------- bn_act

@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("residual", [False, True])
def test_bn_act_forward_parity(dt, residual):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 16, 5, 7), dt)
    res = jnp.asarray(rng.randn(2, 16, 5, 7), dt) if residual else None
    g = jnp.asarray(rng.rand(16) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(16), jnp.float32)
    mm, mv = jnp.zeros(16), jnp.ones(16)
    cfg = bn_act._Cfg(1e-3, 0.9, False, False, True, "relu",
                      256, 512, True)
    out = bn_act.fused_bn_act(x, g, b, mm, mv, res, fix_gamma=False,
                              training=True)
    ref = bn_act._reference(x, g, b, mm, mv, res, cfg)
    for o, r in zip(out, ref):     # y, mean, var, new_mm, new_mv
        assert _maxerr(o, r) < _tol(dt)


@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16])
def test_bn_act_grads_bitwise_equal(dt):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 8, 4, 4), dt)
    res = jnp.asarray(rng.randn(2, 8, 4, 4), dt)
    g = jnp.asarray(rng.rand(8) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(8), jnp.float32)
    mm, mv = jnp.zeros(8), jnp.ones(8)
    cfg = bn_act._Cfg(1e-3, 0.9, False, False, True, "relu",
                      256, 512, True)

    def f_fused(x_, g_, b_, r_):
        return jnp.sum(bn_act.fused_bn_act(
            x_, g_, b_, mm, mv, r_, fix_gamma=False)[0]
            .astype(jnp.float32))

    def f_ref(x_, g_, b_, r_):
        return jnp.sum(bn_act._reference(
            x_, g_, b_, mm, mv, r_, cfg)[0].astype(jnp.float32))

    g1 = jax.grad(f_fused, argnums=(0, 1, 2, 3))(x, g, b, res)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2, 3))(x, g, b, res)
    for a, r in zip(g1, g2):
        assert jnp.array_equal(a, r)   # bwd IS the reference vjp


def test_bn_act_eval_mode_uses_global_stats():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 8, 4, 4), jnp.float32)
    g = jnp.asarray(rng.rand(8) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(8), jnp.float32)
    mm = jnp.asarray(rng.randn(8), jnp.float32)
    mv = jnp.asarray(rng.rand(8) + 0.5, jnp.float32)
    cfg = bn_act._Cfg(1e-3, 0.9, False, False, False, "relu",
                      256, 512, True)
    out = bn_act.fused_bn_act(x, g, b, mm, mv, fix_gamma=False,
                              training=False)
    ref = bn_act._reference(x, g, b, mm, mv, None, cfg)
    assert _maxerr(out[0], ref[0]) < 1e-5
    assert jnp.array_equal(out[3], mm) and jnp.array_equal(out[4], mv)


def test_bn_act_eligibility_guards():
    assert bn_act.eligible((2, 8, 4, 4), jnp.float32, act="relu") is None
    assert bn_act.eligible((2, 8), jnp.float32, act="relu") is not None
    assert bn_act.eligible((2, 8, 4, 4), jnp.int32, act="relu") is not None
    assert bn_act.eligible((2, 8, 4, 4), jnp.float32,
                           act="tanh") is not None
    assert bn_act.eligible((2, 8, 4, 4), jnp.float32, act="relu",
                           residual_shape=(2, 8, 4, 5)) is not None


# ------------------------------------------------------- scale_bias_act

@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("act", ["gelu", "relu", "identity"])
def test_scale_bias_act_parity(dt, act):
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(6, 200), dt)
    sc = jnp.asarray(rng.rand(200) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(200), jnp.float32)
    out = mlp.fused_scale_bias_act(x, sc, b, act=act)
    ref = mlp._reference(x, sc, b, act)
    assert _maxerr(out, ref) < _tol(dt)

    g1 = jax.grad(lambda a, s, bb: jnp.sum(
        mlp.fused_scale_bias_act(a, s, bb, act=act)
        .astype(jnp.float32)), argnums=(0, 1, 2))(x, sc, b)
    g2 = jax.grad(lambda a, s, bb: jnp.sum(
        mlp._reference(a, s, bb, act).astype(jnp.float32)),
        argnums=(0, 1, 2))(x, sc, b)
    for a, r in zip(g1, g2):
        assert jnp.array_equal(a, r)


def test_scale_bias_act_bias_only():
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(4, 96), jnp.float32)
    b = jnp.asarray(rng.randn(96), jnp.float32)
    out = mlp.fused_scale_bias_act(x, None, b, act="gelu")
    ref = mlp._reference(x, None, b, "gelu")
    assert _maxerr(out, ref) < 1e-5
    g1 = jax.grad(lambda a, bb: jnp.sum(
        mlp.fused_scale_bias_act(a, None, bb, act="gelu")))(x, b)
    g2 = jax.grad(lambda a, bb: jnp.sum(
        mlp._reference(a, None, bb, "gelu")))(x, b)
    assert jnp.array_equal(g1, g2)


# ------------------------------------------------------------ take_rows

@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16])
def test_take_rows_parity(dt):
    rng = np.random.RandomState(5)
    w = jnp.asarray(rng.randn(50, 128), dt)
    idx = jnp.asarray(rng.randint(0, 50, size=(4, 7)), jnp.int32)
    out = take.take_rows(w, idx)
    assert jnp.array_equal(out, jnp.take(w, idx, axis=0))
    g1 = jax.grad(lambda w_: jnp.sum(
        (take.take_rows(w_, idx).astype(jnp.float32)) ** 2))(w)
    g2 = jax.grad(lambda w_: jnp.sum(
        (jnp.take(w_, idx, axis=0).astype(jnp.float32)) ** 2))(w)
    assert jnp.array_equal(g1, g2)


def test_take_rows_clips_out_of_range():
    """Reference take/Embedding semantics: out-of-range rows clamp, and
    ops/nn.py's pure-JAX fallback uses mode='clip' to match."""
    w = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
    w = jnp.tile(w, (1, 32))                      # D=128
    idx = jnp.asarray([-5, 0, 2, 99], jnp.int32)
    out = take.take_rows(w, idx)
    ref = jnp.take(w, idx, axis=0, mode="clip")
    assert jnp.array_equal(out, ref)


def test_take_rows_guard_rejects_ragged_width():
    assert take.eligible((50, 100), jnp.float32, (4,),
                         jnp.int32) is not None
    assert take.eligible((50, 128), jnp.float32, (4,), jnp.int32) is None


# ------------------------------------------------- dispatch + tier policy

def test_tier_off_by_default_and_dispatch_modes():
    assert tier.tier() == "off"
    ok, _ = tier.should_dispatch("bn_act", ((64, 64),), "float32")
    assert not ok
    with config.override(kernel_tier="auto"):
        tier.reset_stats()
        ok, cfg = tier.should_dispatch("bn_act", ((64, 64),), "float32")
        assert ok and cfg == bn_act.DEFAULT_CONFIG
        # guard reason forces fallback and records it
        ok, _ = tier.should_dispatch("bn_act", ((64, 64),), "float32",
                                     guard_reason="not 4-D")
        assert not ok
        assert tier.stats()["fallback"] == {"bn_act: not 4-D": 1}
    with config.override(kernel_tier="safe"):
        # safe tier: no tuned entry for this made-up bucket -> fall back
        tier.reset_stats()
        ok, _ = tier.should_dispatch("bn_act", ((3, 3),), "float64")
        assert not ok
        assert tier.stats()["tuner_misses"] == 1


def test_embedding_dispatches_and_falls_back(tmp_path):
    from mxnet_tpu.ops import nn as ops_nn
    rng = np.random.RandomState(6)
    idx = jnp.asarray(rng.randint(0, 40, size=(9,)), jnp.int32)
    w128 = jnp.asarray(rng.randn(40, 128), jnp.float32)   # eligible
    w100 = jnp.asarray(rng.randn(40, 100), jnp.float32)   # ragged width
    with config.override(kernel_tier="auto"):
        tier.reset_stats()
        out1 = ops_nn.embedding(idx, w128)
        out2 = ops_nn.embedding(idx, w100)
        st = tier.stats()
    assert st["dispatch"].get("take_rows") == 1
    assert any(k.startswith("take_rows:") for k in st["fallback"])
    assert jnp.array_equal(out1, jnp.take(w128, idx, axis=0))
    assert jnp.array_equal(out2, jnp.take(w100, idx, axis=0))


# ------------------------------------------------------------ graph fusion

def _small_net_bind():
    rng = np.random.RandomState(7)
    x = sym.Variable("data")
    c = sym.Convolution(x, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        name="c")
    bn = sym.BatchNorm(c, name="bn", fix_gamma=False)
    res = sym.Activation(bn + c, act_type="relu")
    fc = sym.FullyConnected(res, num_hidden=16, name="fc")
    out = sym.LeakyReLU(fc, act_type="gelu")
    args = {"data": mx.nd.array(rng.randn(2, 4, 8, 8).astype(np.float32)),
            "c_weight": mx.nd.array(
                rng.randn(8, 4, 3, 3).astype(np.float32) * 0.1),
            "c_bias": mx.nd.array(np.zeros(8, np.float32)),
            "bn_gamma": mx.nd.array(rng.rand(8).astype(np.float32) + 0.5),
            "bn_beta": mx.nd.array(rng.randn(8).astype(np.float32)),
            "fc_weight": mx.nd.array(
                rng.randn(16, 512).astype(np.float32) * 0.05),
            "fc_bias": mx.nd.array(rng.randn(16).astype(np.float32))}
    aux = {"bn_moving_mean": mx.nd.array(np.zeros(8, np.float32)),
           "bn_moving_var": mx.nd.array(np.ones(8, np.float32))}
    grads = {k: mx.nd.zeros(v.shape) for k, v in args.items()}
    return out.bind(mx.cpu(), args, args_grad=grads, aux_states=aux)


def test_graph_fusion_executor_parity():
    """conv->BN(+residual)->relu->FC->gelu through the executor: tier=auto
    must produce the same outputs, gradients, AND moving-stat updates as
    tier=off, while actually dispatching both fused kernels."""
    def run(tier_val):
        with config.override(kernel_tier=tier_val):
            tier.reset_stats()
            ex = _small_net_bind()
            out = ex.forward(is_train=True)[0]
            ex.backward(mx.nd.ones(out.shape))
            st = dict(tier.stats()["dispatch"])
        vals = ([out.asnumpy()]
                + [g.asnumpy() for g in ex.grad_arrays]
                + [a.asnumpy() for a in ex.aux_arrays])
        return vals, st

    off, _ = run("off")
    auto, st = run("auto")
    assert st.get("bn_act", 0) >= 1 and st.get("scale_bias_act", 0) >= 1
    for a, b in zip(off, auto):
        assert float(np.max(np.abs(a - b))) < 2e-5


def test_graph_fusion_off_tier_is_inert():
    with config.override(kernel_tier="off"):
        tier.reset_stats()
        ex = _small_net_bind()
        ex.forward(is_train=True)
        assert tier.stats()["dispatch"] == {}


# --------------------------------------------- chip-free acceptance export

def test_resnet50_step_exports_pallas_epilogue(resnet_tier_export):
    """THE acceptance criterion: the benched ResNet-50 fused step, traced
    with MXNET_KERNEL_TIER=auto and the committed tuning cache, lowered
    chip-free for the TPU platform, contains the fused BN+ReLU epilogue
    as a tpu_custom_call — provable from the MLIR text alone."""
    text, stats = resnet_tier_export
    targets = hlo_stats.custom_call_targets(text)
    assert targets.get("tpu_custom_call", 0) >= 49, dict(targets)
    kernels = hlo_stats.pallas_kernel_names(text)
    assert kernels.get("mxk_bn_act", 0) == 33, dict(kernels)
    assert kernels.get("mxk_bn_act_res", 0) == 16, dict(kernels)


def test_resnet50_step_tier_consults_seeded_cache(resnet_tier_export):
    """Every dispatch in the benched step hits the committed tuning cache
    (tools/kernel_tuning.json) — the hot path is a dict lookup, and the
    configs are the tuned winners, not heuristic defaults."""
    _text, stats = resnet_tier_export
    assert stats["dispatch"].get("bn_act") == 49
    assert stats["fallback"] == {}
    assert stats["tuner_hits"] == 49 and stats["tuner_misses"] == 0


@pytest.fixture(scope="module")
def resnet_tier_export():
    if jax.devices()[0].platform != "cpu":
        pytest.skip("chip-free export test is defined for the CPU host")
    from jax import export
    import sys
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        from diagnose_step_hlo import build_fused
    finally:
        sys.path.pop(0)
    tcache.invalidate_default()
    with config.override(kernel_tier="auto"):
        tier.reset_stats()
        mod = build_fused(128)          # the benched batch: seeded buckets
        fused = mod._fused
        ex = mod._exec
        npar = len(fused.param_names)
        params, rest = fused.split_args(ex._arg_vals())
        args = (params, rest, ex._aux_vals(), mod._fused_opt_state, None,
                jnp.zeros((npar,), jnp.float32),
                jnp.zeros((npar,), jnp.float32),
                np.float32(1.0), np.int32(1), jax.random.PRNGKey(0))
        with tier.force_compiled():     # Mosaic lowering, not interpreter
            exp = export.export(fused._jitted, platforms=["tpu"])(*args)
        stats = tier.stats()
    return exp.mlir_module(), stats


# ------------------------------------------------- committed cache sanity

def test_committed_tuning_cache_is_valid():
    path = os.path.join(REPO, "tools", "kernel_tuning.json")
    cache = tcache.TuningCache.load(path)
    assert cache.version_ok and cache.entries, path
    for key, entry in cache.entries.items():
        op = key.split("|")[0]
        assert entry["op"] == op
        assert isinstance(entry["config"], dict) and entry["config"]
