"""Unit tests for the elastic-training subsystem: CheckpointManager
atomicity/CRC/retention, the MXNET_FAULT_INJECT grammar, RNG state
round-trip, atomic model saves, and single-process fit() resume
(all chip-free; the multi-process kill drills live in test_fault.py)."""
import logging
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.checkpoint import (CheckpointManager, atomic_replace,
                                  atomic_write_bytes)
from mxnet_tpu.parallel import faultinject


def _mgr(tmp_path, **kw):
    kw.setdefault("async_save", False)
    kw.setdefault("per_rank", False)
    return CheckpointManager(str(tmp_path), **kw)


@pytest.fixture(autouse=True)
def _clean_inject(monkeypatch):
    monkeypatch.delenv("MXNET_FAULT_INJECT", raising=False)
    faultinject.reset()
    yield
    faultinject.reset()


# --------------------------------------------------------------- manager

def test_roundtrip_arrays_and_bytes(tmp_path):
    m = _mgr(tmp_path)
    state = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
             "idx": np.array([1, 2, 3], dtype=np.int64),
             "__opt__": b"\x00\x01binary blob\xff"}
    m.save(state, step=3, epoch=1, nbatch=2, meta={"kvstore": "dist_sync"})
    got, manifest = m.restore_latest()
    assert manifest["step"] == 3
    assert manifest["epoch"] == 1
    assert manifest["nbatch"] == 2
    assert manifest["meta"]["kvstore"] == "dist_sync"
    np.testing.assert_array_equal(got["w"], state["w"])
    np.testing.assert_array_equal(got["idx"], state["idx"])
    assert got["__opt__"] == state["__opt__"]


def test_truncated_snapshot_skipped_with_warning(tmp_path, caplog):
    m = _mgr(tmp_path)
    m.save({"w": np.ones(4, np.float32)}, step=1)
    m.save({"w": np.full(4, 2.0, np.float32)}, step=2)
    data2 = m._data_path(2)
    size = os.path.getsize(data2)
    with open(data2, "r+b") as f:
        f.truncate(size - 16)
    with caplog.at_level(logging.WARNING, "mxnet_tpu.checkpoint"):
        got, manifest = m.restore_latest()
    assert manifest["step"] == 1  # fell back to the intact snapshot
    np.testing.assert_array_equal(got["w"], np.ones(4, np.float32))
    assert any("mismatch" in r.message for r in caplog.records)


def test_crc_mismatch_skipped(tmp_path, caplog):
    m = _mgr(tmp_path)
    m.save({"w": np.ones(4, np.float32)}, step=1)
    m.save({"w": np.full(4, 2.0, np.float32)}, step=2)
    data2 = m._data_path(2)
    blob = bytearray(open(data2, "rb").read())
    blob[len(blob) // 2] ^= 0xFF  # same size, flipped byte
    with open(data2, "wb") as f:
        f.write(blob)
    with caplog.at_level(logging.WARNING, "mxnet_tpu.checkpoint"):
        got, manifest = m.restore_latest()
    assert manifest["step"] == 1


def test_data_without_manifest_is_invisible(tmp_path):
    """A kill between the data rename and the manifest rename leaves a
    data file with no manifest — it must not exist as far as restore is
    concerned."""
    m = _mgr(tmp_path)
    m.save({"w": np.ones(2, np.float32)}, step=1)
    m.save({"w": np.full(2, 9.0, np.float32)}, step=2)
    os.unlink(m._manifest_path(2))
    got, manifest = m.restore_latest()
    assert manifest["step"] == 1
    # no valid snapshot at all -> (None, None), not a crash
    os.unlink(m._manifest_path(1))
    assert m.restore_latest() == (None, None)


def test_retention_keeps_newest(tmp_path):
    m = _mgr(tmp_path, keep_n=2)
    for s in range(1, 6):
        m.save({"w": np.full(2, float(s), np.float32)}, step=s)
    assert m.steps() == [5, 4]
    assert not os.path.exists(m._data_path(1))
    got, manifest = m.restore_latest()
    assert manifest["step"] == 5


def test_restore_at_step_rolls_back(tmp_path):
    m = _mgr(tmp_path)
    for s in (1, 2, 3):
        m.save({"w": np.full(2, float(s), np.float32)}, step=s)
    got, manifest = m.restore(step=2)
    assert manifest["step"] == 2
    np.testing.assert_array_equal(got["w"], np.full(2, 2.0, np.float32))


def test_async_save(tmp_path):
    m = _mgr(tmp_path, async_save=True)
    m.save({"w": np.arange(3, dtype=np.float32)}, step=1, blocking=False)
    m.wait()
    got, manifest = m.restore_latest()
    assert manifest["step"] == 1
    np.testing.assert_array_equal(got["w"], np.arange(3, dtype=np.float32))


def test_maybe_save_honors_grid(tmp_path):
    m = _mgr(tmp_path, save_every=2)
    calls = []

    def state_fn():
        calls.append(1)
        return {"w": np.zeros(1, np.float32)}

    for s in (1, 2, 3, 4):
        m.maybe_save(state_fn, s)
    # state_fn only invoked (device->host only paid) on the grid
    assert len(calls) == 2
    assert m.steps() == [4, 2]


def test_atomic_replace_failure_keeps_old_file(tmp_path):
    p = str(tmp_path / "f.bin")
    atomic_write_bytes(p, b"v1")
    with pytest.raises(RuntimeError):
        with atomic_replace(p) as tmp:
            with open(tmp, "wb") as f:
                f.write(b"v2-partial")
            raise RuntimeError("crash mid-save")
    assert open(p, "rb").read() == b"v1"
    assert [n for n in os.listdir(str(tmp_path)) if ".tmp." in n] == []


def test_per_rank_subdirectories(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_WORKER_RANK", "1")
    m = CheckpointManager(str(tmp_path), async_save=False)
    assert m.directory.endswith("rank_1")
    m.save({"w": np.zeros(1, np.float32)}, step=1)
    assert (tmp_path / "rank_1" / "ckpt-1.json").exists()


# ----------------------------------------------------------- faultinject

def test_inject_grammar_parse(monkeypatch):
    monkeypatch.setenv(
        "MXNET_FAULT_INJECT",
        "kill@step=7:rank=0,delay@step=2:secs=0.5,conn_drop@call=pull:"
        "count=2,truncate@ckpt=3:bytes=128,bogus,nope@@")
    faultinject.reset()
    sps = faultinject.specs()
    assert [s.action for s in sps] == ["kill", "delay", "conn_drop",
                                       "truncate"]
    kill = sps[0]
    assert kill.point == "step" and kill.match == "7"
    assert kill.kwargs["rank"] == "0" and kill.budget == 1
    assert sps[2].budget == 2


def test_inject_conn_drop_budget(monkeypatch):
    monkeypatch.setenv("MXNET_FAULT_INJECT", "conn_drop@call=pull")
    faultinject.reset()
    with pytest.raises(faultinject.InjectedConnDrop):
        faultinject.fire("call", op="pull")
    # budget exhausted (default count=1): next fire is a no-op
    faultinject.fire("call", op="pull")
    # different op never matched
    faultinject.fire("call", op="push")


def test_inject_rank_filter(monkeypatch):
    monkeypatch.setenv("MXNET_FAULT_INJECT", "raise@step=1:rank=3")
    monkeypatch.setenv("MXNET_WORKER_RANK", "0")
    faultinject.reset()
    faultinject.fire("step", step=1)  # wrong rank: no-op
    monkeypatch.setenv("MXNET_WORKER_RANK", "3")
    with pytest.raises(faultinject.InjectedFault):
        faultinject.fire("step", step=1)


def test_inject_ckpt_truncation_end_to_end(tmp_path, monkeypatch):
    """truncate@ckpt corrupts the committed snapshot; restore must fall
    back to the previous step."""
    m = _mgr(tmp_path)
    m.save({"w": np.ones(64, np.float32)}, step=1)
    monkeypatch.setenv("MXNET_FAULT_INJECT", "truncate@ckpt=2:count=1")
    faultinject.reset()
    m.save({"w": np.full(64, 2.0, np.float32)}, step=2)
    monkeypatch.delenv("MXNET_FAULT_INJECT")
    faultinject.reset()
    got, manifest = m.restore_latest()
    assert manifest["step"] == 1


def test_kvstore_client_retry_and_push_fail_fast(monkeypatch):
    """Injected connection drops against a live in-process async server:
    idempotent ops (pull) retry through reconnects; push fails fast with
    an MXNetError (a lost push may already be applied server-side)."""
    from mxnet_tpu.parallel.async_server import Server, Client
    monkeypatch.setenv("MXNET_KVSTORE_RETRY_BACKOFF", "0.01")
    srv = Server()
    cli = Client("127.0.0.1", srv.port)
    try:
        cli.call("init", "w", np.ones((2, 2), "f4"))
        # client-side: drop the connection twice mid-pull; retries win
        monkeypatch.setenv("MXNET_FAULT_INJECT",
                           "conn_drop@call=pull:count=2")
        faultinject.reset()
        np.testing.assert_array_equal(cli.call("pull", "w"),
                                      np.ones((2, 2), "f4"))
        # server-side: the handler severs the connection dispatching pull
        monkeypatch.setenv("MXNET_FAULT_INJECT", "conn_drop@serve=pull")
        faultinject.reset()
        np.testing.assert_array_equal(cli.call("pull", "w"),
                                      np.ones((2, 2), "f4"))
        # push: never retried — fails fast naming the policy
        monkeypatch.setenv("MXNET_FAULT_INJECT", "conn_drop@call=push")
        faultinject.reset()
        with pytest.raises(mx.base.MXNetError, match="not retried"):
            cli.call("push", "w", np.ones((2, 2), "f4"))
    finally:
        monkeypatch.delenv("MXNET_FAULT_INJECT", raising=False)
        faultinject.reset()
        cli.call("shutdown")
        cli.close()


# ------------------------------------------------------------- RNG state

def test_rng_state_roundtrip():
    mx.random.seed(1234)
    mx.nd.random.uniform(shape=(2,))  # advance the chain
    snap = mx.random.get_state()
    a = mx.nd.random.uniform(shape=(4,)).asnumpy()
    b = mx.nd.random.uniform(shape=(4,)).asnumpy()
    mx.random.set_state(snap)
    a2 = mx.nd.random.uniform(shape=(4,)).asnumpy()
    b2 = mx.nd.random.uniform(shape=(4,)).asnumpy()
    np.testing.assert_array_equal(a, a2)
    np.testing.assert_array_equal(b, b2)


# --------------------------------------------------- atomic model saves

def test_model_save_checkpoint_atomic(tmp_path):
    from tests.dist_train_common import make_net, fixed_params
    sym = make_net()
    prefix = str(tmp_path / "model")
    params = fixed_params(sym)
    mx.model.save_checkpoint(prefix, 1, sym, params, {})
    sym2, args2, _ = mx.model.load_checkpoint(prefix, 1)
    for k in params:
        np.testing.assert_array_equal(params[k].asnumpy(),
                                      args2[k].asnumpy())
    assert [n for n in os.listdir(str(tmp_path)) if ".tmp." in n] == []


def test_heartbeat_files_atomic_and_stop_joins(tmp_path, monkeypatch):
    import time
    from mxnet_tpu.parallel import fault
    d = str(tmp_path)
    monkeypatch.setenv("MXNET_HEARTBEAT_DIR", d)
    assert fault.start(0, interval=0.02)
    time.sleep(0.1)
    fault.stop()
    assert not fault.active()
    # joined: no straggler beat can race us; and no partial temp records
    files = os.listdir(d)
    assert "hb_0" in files
    assert [n for n in files if ".tmp." in n] == []
    pid, ts = open(os.path.join(d, "hb_0")).read().split()
    assert int(pid) == os.getpid() and float(ts) > 0


# --------------------------------------------- gluon Trainer resume

def test_trainer_checkpoint_roundtrip_bitwise(tmp_path):
    """Save a Trainer mid-run, restore into a FRESH net+Trainer, finish:
    final params must match an uninterrupted run bitwise (params,
    momentum, and update counters all restored). The fresh net gets a
    renumbered gluon name prefix, so this also covers restore's
    positional fallback."""
    from mxnet_tpu import gluon, autograd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.checkpoint import CheckpointManager

    def make(ckpt=None):
        mx.random.seed(5)
        net = nn.Dense(3, in_units=4)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9},
                           checkpoint=ckpt)
        return net, tr

    def step(net, tr, k):
        x = mx.nd.array(np.full((2, 4), 0.1 * (k + 1), np.float32))
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        tr.step(2)

    net_a, tr_a = make()
    for k in range(4):
        step(net_a, tr_a, k)

    mgr = CheckpointManager(str(tmp_path), async_save=False,
                            per_rank=False)
    net_b, tr_b = make(mgr)
    for k in range(2):
        step(net_b, tr_b, k)
    assert tr_b.save_checkpoint()
    assert tr_b._global_step == 2

    net_c, tr_c = make(CheckpointManager(str(tmp_path), async_save=False,
                                         per_rank=False))
    assert tr_c.restore_checkpoint() == 2
    for k in range(2, 4):
        step(net_c, tr_c, k)

    for (na, a), (nc, c) in zip(sorted(net_a.collect_params().items()),
                                sorted(net_c.collect_params().items())):
        np.testing.assert_array_equal(
            a.data().asnumpy(), c.data().asnumpy(),
            err_msg="%s vs %s diverged across trainer resume" % (na, nc))


# ------------------------------------------------ SPMDTrainStep resume

def test_spmd_checkpoint_roundtrip_bitwise(tmp_path):
    """save_checkpoint/restore_latest on SPMDTrainStep: restore into a
    FRESHLY compiled step (new program, same mesh) and finish — params
    must match the uninterrupted trajectory bitwise."""
    import jax
    from jax.sharding import Mesh
    from mxnet_tpu.parallel.spmd import SPMDTrainStep
    from mxnet_tpu.checkpoint import CheckpointManager

    def make_step():
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
        sym = mx.sym.SoftmaxOutput(net, name="softmax")
        mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
        st = SPMDTrainStep(sym, mesh, lr=0.1, momentum=0.9)
        pshapes = {"fc1_weight": (8, 6), "fc1_bias": (8,),
                   "fc2_weight": (4, 8), "fc2_bias": (4,)}
        st.compile(pshapes, {}, {"data": (16, 6)},
                   {"softmax_label": (16,)})
        return st, pshapes

    rng = np.random.RandomState(0)
    X = {"data": rng.randn(16, 6).astype(np.float32)}
    Y = {"softmax_label": rng.randint(0, 4, (16,)).astype(np.float32)}
    key = jax.random.PRNGKey(0)

    st, pshapes = make_step()
    params, aux, opt = st.init(pshapes, {}, seed=1)
    for _ in range(2):
        params, aux, opt, _ = st(params, aux, opt, X, Y, key=key)

    mgr = CheckpointManager(str(tmp_path), async_save=False,
                            per_rank=False)
    st.save_checkpoint(mgr, params, aux, opt, step=2)
    for _ in range(2):
        params, aux, opt, _ = st(params, aux, opt, X, Y, key=key)

    st2, _ = make_step()
    got = st2.restore_latest(
        CheckpointManager(str(tmp_path), async_save=False, per_rank=False))
    assert got is not None
    p2, a2, o2, manifest = got
    assert manifest["step"] == 2
    assert manifest["meta"]["kvstore"] == "spmd"
    for _ in range(2):
        p2, a2, o2, _ = st2(p2, a2, o2, X, Y, key=key)

    for k in params:
        np.testing.assert_array_equal(
            np.asarray(params[k]), np.asarray(p2[k]),
            err_msg="param %r diverged across SPMD resume" % k)


# ------------------------------------------- single-process fit() resume

def _fit_once(tmp_path, num_epoch, ckpt_env, tag):
    """Train the shared little net for `num_epoch` epochs in-process."""
    from tests.dist_train_common import (make_net, full_data, fixed_params,
                                         PER_WORKER_BATCH)
    mx.random.seed(99)
    X, Y = full_data(1)
    it = mx.io.NDArrayIter(X, Y, batch_size=PER_WORKER_BATCH,
                           label_name="softmax_label")
    sym = make_net()
    mod = mx.mod.Module(sym)
    mod.fit(it, num_epoch=num_epoch, kvstore="local", optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1.0 / PER_WORKER_BATCH},
            arg_params=fixed_params(sym), initializer=None,
            eval_metric=None)
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}


def test_fit_resume_bitwise_single_process(tmp_path, monkeypatch):
    """Interrupt-at-epoch-boundary resume: a run checkpointed through
    epoch 0 and resumed for epoch 1 must finish with BITWISE the same
    params as an uninterrupted 2-epoch run (same momentum, same update
    counts, same RNG chain)."""
    monkeypatch.delenv("MXNET_CHECKPOINT_DIR", raising=False)
    monkeypatch.delenv("MXNET_RESUME_DIR", raising=False)

    baseline = _fit_once(tmp_path, 2, None, "base")

    ckpt_dir = str(tmp_path / "ckpt")
    monkeypatch.setenv("MXNET_CHECKPOINT_DIR", ckpt_dir)
    _fit_once(tmp_path, 1, ckpt_dir, "partial")  # "crashes" after epoch 0

    monkeypatch.setenv("MXNET_RESUME_DIR", ckpt_dir)
    resumed = _fit_once(tmp_path, 2, ckpt_dir, "resumed")

    assert sorted(baseline) == sorted(resumed)
    for k in baseline:
        np.testing.assert_array_equal(
            baseline[k], resumed[k],
            err_msg="param %r diverged across resume" % k)

# ----------------------------------------------- mid-epoch cursor resume

def _pack_stream_set(tmp_path):
    """full_data's 32 (x, y) rows as a 2-shard raw-tensor RecordIO set."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        from make_recordio import write_shards
    finally:
        sys.path.pop(0)
    from tests.dist_train_common import full_data
    X, Y = full_data(1)
    return write_shards(((float(Y[i]), X[i].tobytes())
                         for i in range(len(X))),
                        str(tmp_path / "stream" / "set"), 2)


class _Boom(RuntimeError):
    pass


def _stream_fit(recs, num_epoch, crash_at_nbatch=None, ckpt_dir=None):
    """Fit the shared little net from a StreamingDataIter; optionally
    "crash" (raise) mid-epoch-0 after ``crash_at_nbatch`` batches.
    ``ckpt_dir`` checkpoints SYNCHRONOUSLY so the crash can't race an
    in-flight async save (determinism for the manifest assertions).
    Returns (params, delivered_batches, seeks)."""
    from tests.dist_train_common import make_net, fixed_params
    from mxnet_tpu.data import (RawTensorDecoder, ShardedRecordStream,
                                StreamingDataIter)
    mx.random.seed(99)
    it = StreamingDataIter(ShardedRecordStream(recs, seed=11),
                           RawTensorDecoder((8,)), batch_size=8)
    delivered = [0]
    orig_next = it.next

    def counting_next():
        b = orig_next()
        delivered[0] += 1
        return b
    it.next = counting_next

    cb = None
    if crash_at_nbatch is not None:
        def cb(param):
            if param.epoch == 0 and param.nbatch == crash_at_nbatch:
                raise _Boom()
    sym = make_net()
    mod = mx.mod.Module(sym)
    ckpt = (CheckpointManager(ckpt_dir, async_save=False)
            if ckpt_dir else None)
    try:
        mod.fit(it, num_epoch=num_epoch, kvstore="local", optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                                  "rescale_grad": 1.0 / 8},
                arg_params=fixed_params(sym), initializer=None,
                eval_metric=None, batch_end_callback=cb, checkpoint=ckpt)
    finally:
        it.close()
    args, _ = mod.get_params()
    return ({k: v.asnumpy() for k, v in args.items()}, delivered[0],
            it.seeks)


def test_fit_resume_cursor_seek_mid_epoch_bitwise(tmp_path, monkeypatch):
    """Kill/resume THROUGH the data cursor: a streaming-fed fit killed
    mid-epoch resumes by an O(1) ``seek`` to the checkpointed
    (epoch, shard, offset) — no batch-skip replay — and still finishes
    bitwise-identical to the uninterrupted run."""
    monkeypatch.delenv("MXNET_CHECKPOINT_DIR", raising=False)
    monkeypatch.delenv("MXNET_RESUME_DIR", raising=False)
    recs = _pack_stream_set(tmp_path)

    baseline, n_base, _ = _stream_fit(recs, 2)
    assert n_base == 8  # 32 rows / batch 8 * 2 epochs

    ckpt_dir = str(tmp_path / "ckpt")
    # crash after 3 batches; the newest snapshot is step 2 — MID epoch 0
    with pytest.raises(_Boom):
        _stream_fit(recs, 2, crash_at_nbatch=2, ckpt_dir=ckpt_dir)

    # the snapshot really carries the cursor of the CONSUMED position
    from mxnet_tpu import checkpoint as _ckpt
    state, manifest = CheckpointManager(ckpt_dir).restore_latest()
    assert manifest["nbatch"] == 2 and manifest["epoch"] == 0
    cur = _ckpt.cursor_from_state(state)
    assert cur is not None and cur["seed"] == 11

    monkeypatch.setenv("MXNET_RESUME_DIR", ckpt_dir)
    resumed, n_resumed, seeks = _stream_fit(recs, 2, ckpt_dir=ckpt_dir)
    # seek, not replay: exactly the 6 remaining batches were delivered
    # (batch-skip replay would have pulled 2 throwaway batches first)
    assert seeks == 1
    assert n_resumed == 8 - 2

    assert sorted(baseline) == sorted(resumed)
    for k in baseline:
        np.testing.assert_array_equal(
            baseline[k], resumed[k],
            err_msg="param %r diverged across cursor resume" % k)


def test_fit_resume_batch_skip_fallback_mid_epoch_bitwise(tmp_path,
                                                          monkeypatch):
    """The cursorless fallback stays: an NDArrayIter (no get_cursor/seek)
    killed mid-epoch resumes through the O(steps) batch-skip replay and
    is ALSO bitwise."""
    from tests.dist_train_common import make_net, full_data, fixed_params
    monkeypatch.delenv("MXNET_CHECKPOINT_DIR", raising=False)
    monkeypatch.delenv("MXNET_RESUME_DIR", raising=False)

    def fit_once(num_epoch, crash_at_nbatch=None):
        mx.random.seed(99)
        X, Y = full_data(1)
        it = mx.io.NDArrayIter(X, Y, batch_size=8,
                               label_name="softmax_label")
        assert not hasattr(it, "get_cursor")  # exercises the skip path
        cb = None
        if crash_at_nbatch is not None:
            def cb(param):
                if param.epoch == 0 and param.nbatch == crash_at_nbatch:
                    raise _Boom()
        sym = make_net()
        mod = mx.mod.Module(sym)
        mod.fit(it, num_epoch=num_epoch, kvstore="local", optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                                  "rescale_grad": 1.0 / 8},
                arg_params=fixed_params(sym), initializer=None,
                eval_metric=None, batch_end_callback=cb)
        args, _ = mod.get_params()
        return {k: v.asnumpy() for k, v in args.items()}

    baseline = fit_once(2)
    ckpt_dir = str(tmp_path / "ckpt")
    monkeypatch.setenv("MXNET_CHECKPOINT_DIR", ckpt_dir)
    with pytest.raises(_Boom):
        fit_once(2, crash_at_nbatch=2)
    monkeypatch.setenv("MXNET_RESUME_DIR", ckpt_dir)
    resumed = fit_once(2)
    for k in baseline:
        np.testing.assert_array_equal(
            baseline[k], resumed[k],
            err_msg="param %r diverged across batch-skip resume" % k)


# ---------------------------------------------------- layout-manifest reshard

def _save_world(root, world, state_fn, step=7, sharded=None, meta=None):
    """Commit one step across ``world`` per-rank managers, the way a
    real N-rank run does (each rank writes its own slice)."""
    from mxnet_tpu.parallel.layout import LayoutManifest, shard_state
    full = state_fn()
    meta = dict(meta or {})
    man = None
    if sharded:
        shapes = {k: list(np.shape(v)) for k, v in full.items()
                  if not isinstance(v, (bytes, bytearray))}
        man = LayoutManifest.build(shapes, world, sharded_axes=sharded)
        meta["layout"] = man.to_dict()
    for r in range(world):
        cm = CheckpointManager(str(root), rank=r, world=world,
                               async_save=False)
        st = shard_state(full, man, r) if man is not None else dict(full)
        cm.save(st, step, meta=meta, blocking=True)
    return full


def _demo_state(seed=3):
    rng = np.random.RandomState(seed)
    return {
        "embed.weight": rng.randn(11, 4).astype(np.float32),
        "dense.weight": rng.randn(4, 2).astype(np.float32),
        "__opt__": b"opt-blob",
        "__rng__": b"rng-blob",
    }


@pytest.mark.parametrize("new_world", [3, 5])
def test_restore_resharded_across_world_sizes(tmp_path, new_world):
    """Save at world 4, restore at N-k and N+k: every rank of the new
    world sees exactly its manifest slice, blobs ride along."""
    root = tmp_path / "ckpt"
    full = _save_world(root, 4, _demo_state,
                       sharded={"embed.weight": 0})
    from mxnet_tpu.parallel.layout import LayoutManifest
    gathered = {}
    for r in range(new_world):
        cm = CheckpointManager(str(root), rank=r, world=new_world,
                               async_save=False)
        state, manifest = cm.restore_resharded()
        assert state is not None
        assert manifest["world"] == new_world
        assert manifest["meta"]["resharded_from"] == {"world": 4,
                                                      "step": 7}
        man = LayoutManifest.from_dict(manifest["meta"]["layout"])
        start, stop = man.part_for("embed.weight", r)
        np.testing.assert_array_equal(state["embed.weight"],
                                      full["embed.weight"][start:stop])
        np.testing.assert_array_equal(state["dense.weight"],
                                      full["dense.weight"])
        assert state["__opt__"] == b"opt-blob"
        gathered[r] = state
    # the union of the new shards is the old global state, bitwise
    from mxnet_tpu.parallel.layout import gather_state
    back = gather_state(gathered, man)
    np.testing.assert_array_equal(back["embed.weight"],
                                  full["embed.weight"])


def test_restore_resharded_same_world_is_plain_restore(tmp_path):
    root = tmp_path / "ckpt"
    full = _save_world(root, 2, _demo_state)
    cm = CheckpointManager(str(root), rank=1, world=2, async_save=False)
    state, manifest = cm.restore_resharded()
    assert "resharded_from" not in (manifest.get("meta") or {})
    np.testing.assert_array_equal(state["dense.weight"],
                                  full["dense.weight"])


def test_restore_resharded_corrupt_layout_falls_back(tmp_path, caplog):
    """A snapshot whose layout record is garbage still reshards: the
    inferred all-replicated (DDP) layout is the fallback."""
    import json as _json
    root = tmp_path / "ckpt"
    full = _save_world(root, 2, _demo_state)    # replicated layout
    for r in range(2):
        mpath = root / ("rank_%d" % r) / "ckpt-7.json"
        man = _json.loads(mpath.read_text())
        man["meta"]["layout"] = {"format": "mxtpu-layout",
                                 "world": "NaN-garbage"}
        mpath.write_text(_json.dumps(man))
    cm = CheckpointManager(str(root), rank=0, world=3, async_save=False)
    with caplog.at_level(logging.WARNING):
        state, manifest = cm.restore_resharded()
    assert state is not None
    np.testing.assert_array_equal(state["embed.weight"],
                                  full["embed.weight"])
    assert any("layout" in r.message for r in caplog.records)


def test_reshard_checkpoint_writes_sibling_root(tmp_path):
    root = tmp_path / "ckpt"
    full = _save_world(root, 4, _demo_state,
                       sharded={"embed.weight": 0})
    from mxnet_tpu.checkpoint import reshard_checkpoint
    report = reshard_checkpoint(str(root), 3)
    assert report["old_world"] == 4
    assert report["new_world"] == 3
    assert report["dst"] == str(root) + "-w3"
    assert report["step"] == 7
    # the destination restores natively at world 3
    for r in range(3):
        cm = CheckpointManager(report["dst"], rank=r, world=3,
                               async_save=False)
        state, manifest = cm.restore()
        assert state is not None
        assert manifest["world"] == 3
    # and the source root is untouched (still 4 rank dirs)
    from mxnet_tpu.checkpoint import _rank_dirs
    assert sorted(_rank_dirs(str(root))) == [0, 1, 2, 3]


def test_reshard_checkpoint_refuses_empty_root(tmp_path):
    from mxnet_tpu.checkpoint import reshard_checkpoint
    with pytest.raises(ValueError):
        reshard_checkpoint(str(tmp_path / "nope"), 2)


def test_kill_resume_bitwise_after_reshard(tmp_path):
    """The elastic-resume contract end to end, in-process: a 4-rank
    'run' checkpoints mid-training, the resume happens on 3 ranks via
    manifest resharding, and the final params are bitwise-identical to
    an uninterrupted reference run at the new world. The trainer is a
    deterministic numpy loop with a row-sharded embedding (each rank
    updates only its manifest slice) and a replicated dense layer
    (DDP-style identical updates everywhere)."""
    from mxnet_tpu.parallel.layout import (LayoutManifest, gather_state,
                                           shard_state)

    def init_full():
        rng = np.random.RandomState(0)
        return {
            "embed.weight": rng.randn(10, 3).astype(np.float32),
            "dense.weight": rng.randn(3, 3).astype(np.float32),
        }

    def manifest_for(full, world):
        shapes = {k: list(v.shape) for k, v in full.items()}
        return LayoutManifest.build(shapes, world,
                                    sharded_axes={"embed.weight": 0})

    def train_steps(shards, man, steps, start_step):
        """Per-rank updates, deterministic in (step, global row id) —
        world-size invariant by construction, like a fixed global
        batch."""
        for k in range(start_step, start_step + steps):
            for r, st in shards.items():
                lo, _hi = man.part_for("embed.weight", r)
                emb = st["embed.weight"]
                rows = np.arange(emb.shape[0], dtype=np.float32)
                grad = np.outer(np.sin(rows + lo + k),
                                np.ones(emb.shape[1],
                                        dtype=np.float32))
                st["embed.weight"] = emb - 0.01 * grad.astype(np.float32)
                st["dense.weight"] = (st["dense.weight"]
                                      - 0.01 * np.float32(np.cos(k)))
        return shards

    TOTAL, KILL = 6, 3

    # reference: uninterrupted run at the NEW world (3 ranks)
    full = init_full()
    man3 = manifest_for(full, 3)
    ref = {r: shard_state(full, man3, r) for r in range(3)}
    train_steps(ref, man3, TOTAL, 0)
    ref_full = gather_state(ref, man3)

    # interrupted run: 4 ranks, killed after KILL steps (checkpoint
    # committed), resumed at 3 ranks via restore_resharded
    full = init_full()
    man4 = manifest_for(full, 4)
    shards = {r: shard_state(full, man4, r) for r in range(4)}
    train_steps(shards, man4, KILL, 0)
    root = tmp_path / "ckpt4"
    for r in range(4):
        cm = CheckpointManager(str(root), rank=r, world=4,
                               async_save=False)
        cm.save(shards[r], KILL,
                meta={"layout": man4.to_dict()}, blocking=True)
    # ranks die here; a 3-rank incarnation resumes
    resumed = {}
    for r in range(3):
        cm = CheckpointManager(str(root), rank=r, world=3,
                               async_save=False)
        state, manifest = cm.restore_resharded()
        assert manifest["meta"]["resharded_from"]["world"] == 4
        resumed[r] = state
    man3b = LayoutManifest.from_dict(
        manifest["meta"]["layout"])
    train_steps(resumed, man3b, TOTAL - KILL, KILL)
    resumed_full = gather_state(resumed, man3b)

    for k in ref_full:
        np.testing.assert_array_equal(
            ref_full[k], resumed_full[k],
            err_msg="param %r diverged across the 4->3 elastic "
                    "resume" % k)
