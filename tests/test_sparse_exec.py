"""Sparse EXECUTION (round-3): ops that must run without materializing the
dense logical shape — csr dot, retain, row-sparse reduce, lazy optimizer
updates, kvstore row_sparse paths. Reference: src/operator/tensor/dot-inl.h,
src/operator/optimizer_op-inl.h, kvstore_dist_server.h:517-716."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse as sp
from mxnet_tpu import optimizer as opt


def _rand_rsp(shape, rows, seed=0, scale=1.0):
    rng = np.random.RandomState(seed)
    vals = rng.randn(len(rows), *shape[1:]).astype(np.float32) * scale
    dense = np.zeros(shape, np.float32)
    dense[list(rows)] = vals
    rsp = sp.row_sparse_array((vals, np.array(rows, np.int64)), shape=shape)
    return rsp, dense


def test_csr_dot_and_transpose():
    rng = np.random.RandomState(0)
    dense = rng.randn(6, 5).astype(np.float32)
    dense[dense < 0.3] = 0  # sparsify
    csr = sp.csr_matrix(dense)
    rhs = mx.nd.array(rng.randn(5, 4).astype(np.float32))
    out = sp.dot(csr, rhs)
    np.testing.assert_allclose(out.asnumpy(), dense @ rhs.asnumpy(),
                               rtol=1e-5)
    rhs2 = mx.nd.array(rng.randn(6, 3).astype(np.float32))
    out_t = sp.dot(csr, rhs2, transpose_a=True)
    np.testing.assert_allclose(out_t.asnumpy(), dense.T @ rhs2.asnumpy(),
                               rtol=1e-5)


def test_retain_sparse_no_densify():
    rsp, dense = _rand_rsp((100, 3), [2, 50, 97])
    kept = sp.retain(rsp, mx.nd.array(np.array([2, 7, 97], np.int64)))
    assert kept.stype == "row_sparse"
    expected = np.zeros((100, 3), np.float32)
    expected[[2, 97]] = dense[[2, 97]]
    np.testing.assert_allclose(kept.asnumpy(), expected, rtol=1e-6)


def test_rsp_add_unions_rows():
    a, da = _rand_rsp((50, 4), [1, 10, 30], seed=1)
    b, db = _rand_rsp((50, 4), [10, 44], seed=2)
    s = sp.add(a, b)
    assert s.stype == "row_sparse"
    assert sorted(np.asarray(s.indices.asnumpy()).tolist()) == [1, 10, 30, 44]
    np.testing.assert_allclose(s.asnumpy(), da + db, rtol=1e-6)


@pytest.mark.parametrize("name,kw", [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
    ("adagrad", {"learning_rate": 0.1}),
])
def test_lazy_update_touches_only_grad_rows(name, kw):
    shape = (40, 3)
    rows = [3, 17, 25]
    o = opt.create(name, wd=0.01, rescale_grad=0.5, **kw)
    w = mx.nd.array(np.random.RandomState(0).randn(*shape)
                    .astype(np.float32))
    w0 = w.asnumpy().copy()
    grad, gdense = _rand_rsp(shape, rows, seed=3)
    state = o.create_state(0, w)
    o.update(0, w, grad, state)
    w1 = w.asnumpy()
    untouched = np.setdiff1d(np.arange(shape[0]), rows)
    # untouched rows: IDENTICAL (no wd decay — lazy semantics)
    np.testing.assert_array_equal(w1[untouched], w0[untouched])
    assert np.abs(w1[rows] - w0[rows]).max() > 0

    # touched rows match the dense-math update restricted to those rows
    o2 = opt.create(name, wd=0.01, rescale_grad=0.5, **kw)
    wd_ = mx.nd.array(w0.copy())
    st2 = o2.create_state(0, wd_)
    o2.update(0, wd_, mx.nd.array(gdense), st2)
    np.testing.assert_allclose(w1[rows], wd_.asnumpy()[rows],
                               rtol=1e-5, atol=1e-6)


def test_kvstore_rsp_push_stays_sparse():
    kv = mx.kv.create("local")
    kv.init("emb", sp.zeros("row_sparse", (30, 4)))
    g1, d1 = _rand_rsp((30, 4), [0, 5], seed=4)
    g2, d2 = _rand_rsp((30, 4), [5, 12], seed=5)
    kv.push("emb", [g1, g2])
    assert isinstance(kv._store["emb"], sp.RowSparseNDArray)
    out = mx.nd.zeros((30, 4))
    kv.pull("emb", out=out, ignore_sparse=False)
    np.testing.assert_allclose(out.asnumpy(), d1 + d2, rtol=1e-6)


def test_kvstore_pull_ignores_sparse_by_default():
    kv = mx.kv.create("local")
    kv.init("emb", sp.zeros("row_sparse", (10, 2)))
    out = mx.nd.ones((10, 2))
    kv.pull("emb", out=out)  # ignore_sparse=True: skipped
    np.testing.assert_array_equal(out.asnumpy(), np.ones((10, 2)))
    kv.pull("emb", out=out, ignore_sparse=False)
    np.testing.assert_array_equal(out.asnumpy(), np.zeros((10, 2)))


def test_kvstore_row_sparse_pull():
    kv = mx.kv.create("local")
    rsp, dense = _rand_rsp((20, 3), [2, 9, 15], seed=6)
    kv.init("w", rsp)
    out = sp.zeros("row_sparse", (20, 3))
    kv.row_sparse_pull("w", out=out, row_ids=mx.nd.array(
        np.array([2, 9], np.int64)))
    expected = np.zeros((20, 3), np.float32)
    expected[[2, 9]] = dense[[2, 9]]
    np.testing.assert_allclose(out.asnumpy(), expected, rtol=1e-6)


def test_sparse_embedding_training_pattern():
    """The canonical row-sparse consumer: embedding-style rows updated
    lazily across steps; cold rows never move."""
    vocab, dim = 200, 8
    table = mx.nd.array(np.random.RandomState(0)
                        .randn(vocab, dim).astype(np.float32) * 0.1)
    t0 = table.asnumpy().copy()
    o = opt.create("adagrad", learning_rate=0.5, rescale_grad=1.0)
    state = o.create_state(0, table)
    hot = set()
    for step in range(5):
        rows = [(step * 7) % vocab, (step * 13 + 1) % vocab]
        hot.update(rows)
        g, _ = _rand_rsp((vocab, dim), sorted(set(rows)), seed=step)
        o.update(0, table, g, state)
    t1 = table.asnumpy()
    cold = np.setdiff1d(np.arange(vocab), sorted(hot))
    np.testing.assert_array_equal(t1[cold], t0[cold])
    assert np.abs(t1[sorted(hot)] - t0[sorted(hot)]).max() > 0


def test_sparse_weight_lazy_update():
    """Row-sparse WEIGHT (the dist-server rsp table) updated in place,
    staying sparse (round-3 review: the lazy branch crashed on sparse
    weights)."""
    shape = (60, 4)
    w = sp.row_sparse_array(
        (np.ones((2, 4), np.float32), np.array([5, 20], np.int64)),
        shape=shape)
    o = opt.create("sgd", learning_rate=1.0, rescale_grad=1.0)
    g, gd = _rand_rsp(shape, [5, 33], seed=9)
    o.update(0, w, g, o.create_state(0, w))
    assert w.stype == "row_sparse"
    assert sorted(np.asarray(w.indices.asnumpy()).tolist()) == [5, 20, 33]
    dense = w.asnumpy()
    np.testing.assert_allclose(dense[5], 1.0 - gd[5], rtol=1e-5)
    np.testing.assert_allclose(dense[20], 1.0)   # untouched row kept
    np.testing.assert_allclose(dense[33], -gd[33], rtol=1e-5)


def test_adam_lazy_update_flag_respected():
    shape = (20, 2)
    w0 = np.random.RandomState(0).randn(*shape).astype(np.float32)
    g, _ = _rand_rsp(shape, [3], seed=1)
    # lazy (default): untouched rows frozen
    o1 = opt.create("adam", learning_rate=0.1, wd=0.1)
    w1 = mx.nd.array(w0.copy())
    o1.update(0, w1, g, o1.create_state(0, w1))
    np.testing.assert_array_equal(w1.asnumpy()[0], w0[0])
    # lazy_update=False: dense semantics, wd decays every row
    o2 = opt.create("adam", learning_rate=0.1, wd=0.1, lazy_update=False)
    w2 = mx.nd.array(w0.copy())
    o2.update(0, w2, g, o2.create_state(0, w2))
    assert (w2.asnumpy()[0] != w0[0]).any()
