"""Tensor parallelism beyond one matmul (VERDICT r3 #8): the Megatron
sharding pattern (parallel/spmd.py megatron_tp_rule) on a 2-layer MLP and
a full attention block, with tp=2 numerics checked against tp=1 on the
8-virtual-device CPU mesh.

What the pattern claims (Megatron-LM; reference has no TP — group2ctx
model parallelism is refused loudly and replaced by this): column-split
the first matmul / QKV projection, row-split the second / output
projection, one psum per pair inserted by GSPMD.
"""
import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu.parallel import SPMDTrainStep, make_mesh, megatron_tp_rule

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs the 8-virtual-device mesh")


def _mlp_sym():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="ffn1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=24, name="ffn2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="head")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _attn_sym(embed=16, heads=4, seq=8):
    """QKV (column-parallel over heads) -> FlashAttention -> out proj
    (row-parallel) -> pooled classifier."""
    data = mx.sym.Variable("data")               # (B, T, C)
    qkv = mx.sym.FullyConnected(data, num_hidden=3 * embed, flatten=False,
                                name="attn_qkv")     # (B, T, 3C)
    # HEAD-major feature layout: a contiguous tp row-split of the fused
    # weight is then a whole-head partition (see megatron_tp_rule note)
    qkv = mx.sym.reshape(qkv, shape=(0, 0, heads, 3, embed // heads))
    qkv = mx.sym.transpose(qkv, axes=(3, 0, 2, 1, 4))  # (3, B, H, T, D)
    q = mx.sym.squeeze(mx.sym.slice_axis(qkv, axis=0, begin=0, end=1), axis=0)
    k = mx.sym.squeeze(mx.sym.slice_axis(qkv, axis=0, begin=1, end=2), axis=0)
    v = mx.sym.squeeze(mx.sym.slice_axis(qkv, axis=0, begin=2, end=3), axis=0)
    o = mx.sym.contrib.FlashAttention(q, k, v, causal=True)  # (B, H, T, D)
    o = mx.sym.transpose(o, axes=(0, 2, 1, 3))               # (B, T, H, D)
    o = mx.sym.reshape(o, shape=(0, 0, -3))                  # (B, T, C)
    o = mx.sym.FullyConnected(o, num_hidden=embed, flatten=False,
                              name="attn_out")
    o = mx.sym.mean(o, axis=1)                               # (B, C)
    o = mx.sym.FullyConnected(o, num_hidden=4, name="head")
    return mx.sym.SoftmaxOutput(o, name="softmax")


def _train(sym, data_shape, tp, steps=3, seed=0, rule=None, batch=8):
    """Run `steps` SPMD train steps on a dp x tp mesh; return params."""
    n_tp = tp
    n_dp = 1
    devices = jax.devices()[: n_dp * n_tp]
    mesh = make_mesh({"dp": n_dp, "tp": n_tp}, devices=devices)
    shapes = dict(data=(batch,) + data_shape)
    arg_shapes, _, aux_shapes = sym.infer_shape(**shapes)
    names = sym.list_arguments()
    param_shapes = {n: tuple(s) for n, s in zip(names, arg_shapes)
                    if n not in ("data", "softmax_label")}
    aux_d = {n: tuple(s) for n, s in
             zip(sym.list_auxiliary_states(), aux_shapes)}
    step = SPMDTrainStep(sym, mesh, dp_axis="dp", tp_axis="tp",
                         tp_rule=rule, lr=0.1, momentum=0.9)
    step.compile(param_shapes, aux_d, {"data": shapes["data"]},
                 {"softmax_label": (batch,)})
    params, aux, opt = step.init(param_shapes, aux_d, seed=seed)

    rng = np.random.RandomState(42)
    key = jax.random.PRNGKey(0)
    for i in range(steps):
        data = {"data": jax.device_put(
            rng.randn(*shapes["data"]).astype(np.float32),
            NamedSharding(mesh, P("dp")))}
        label = {"softmax_label": jax.device_put(
            rng.randint(0, 4, (batch,)).astype(np.float32),
            NamedSharding(mesh, P("dp")))}
        params, aux, opt, outs = step(params, aux, opt, data, label, key)
    return {k: np.asarray(jax.device_get(v)) for k, v in params.items()}


def test_mlp_tp2_matches_tp1():
    rule = megatron_tp_rule(column_parallel=["ffn1"], row_parallel=["ffn2"])
    p1 = _train(_mlp_sym(), (16,), tp=1, rule=rule)
    p2 = _train(_mlp_sym(), (16,), tp=2, rule=rule)
    assert set(p1) == set(p2)
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], rtol=2e-4, atol=2e-5,
                                   err_msg=k)
    # and training actually moved the sharded weights
    p0 = _train(_mlp_sym(), (16,), tp=2, rule=rule, steps=0)
    assert any(not np.allclose(p2[k], p0[k]) for k in p2)


def test_mlp_tp4_matches_tp1():
    rule = megatron_tp_rule(column_parallel=["ffn1"], row_parallel=["ffn2"])
    p1 = _train(_mlp_sym(), (16,), tp=1, rule=rule)
    p4 = _train(_mlp_sym(), (16,), tp=4, rule=rule)
    for k in p1:
        np.testing.assert_allclose(p1[k], p4[k], rtol=2e-4, atol=2e-5,
                                   err_msg=k)


def test_attention_block_tp2_matches_tp1():
    rule = megatron_tp_rule(column_parallel=["attn_qkv"],
                            row_parallel=["attn_out"])
    p1 = _train(_attn_sym(), (8, 16), tp=1, rule=rule)
    p2 = _train(_attn_sym(), (8, 16), tp=2, rule=rule)
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], rtol=5e-4, atol=5e-5,
                                   err_msg=k)


def test_sharding_actually_splits_weights():
    """Not just numerics: the tp=2 run must PLACE ffn1_weight split across
    the tp axis (no silent replication)."""
    rule = megatron_tp_rule(column_parallel=["ffn1"], row_parallel=["ffn2"])
    devices = jax.devices()[:2]
    mesh = make_mesh({"dp": 1, "tp": 2}, devices=devices)
    sym = _mlp_sym()
    batch = 8
    arg_shapes, _, _ = sym.infer_shape(data=(batch, 16))
    names = sym.list_arguments()
    param_shapes = {n: tuple(s) for n, s in zip(names, arg_shapes)
                    if n not in ("data", "softmax_label")}
    step = SPMDTrainStep(sym, mesh, dp_axis="dp", tp_axis="tp",
                         tp_rule=rule)
    step.compile(param_shapes, {}, {"data": (batch, 16)},
                 {"softmax_label": (batch,)})
    params, aux, opt = step.init(param_shapes, {})
    w = params["ffn1_weight"]
    shard_shapes = {s.data.shape for s in w.addressable_shards}
    full = param_shapes["ffn1_weight"]
    assert shard_shapes == {(full[0] // 2, full[1])}, shard_shapes
    w2 = params["ffn2_weight"]
    shard_shapes2 = {s.data.shape for s in w2.addressable_shards}
    full2 = param_shapes["ffn2_weight"]
    assert shard_shapes2 == {(full2[0], full2[1] // 2)}, shard_shapes2
