"""PR-15 sharded embedding subsystem: the chip-free fleet gates.

What is pinned here (ISSUE.md acceptance):

* the mesh all-to-all lookup is BITWISE-equal to the 1-rank dense
  ``take`` — forward AND gradient (the stable-sort / position-ordered
  send-buffer discipline of embed/table.py);
* out-of-range ids CLIP identically on every dispatch path (Pallas
  scalar-prefetch kernel, jnp.take fallback, ops/nn.py
  sparse_embedding, kernels/take.py gather_pages), fwd and grad;
* the sparse DDP bucket kind exchanges coalesced contributions that
  reduce BITWISE-equal to the densified oracle, at >= 10x fewer bytes;
* the two-tower fleet drill: a table whose LOGICAL size exceeds the
  configured host budget trains through cache+spill, and the final
  parameters are bitwise-equal across shardings (1 rank vs 2x2 mesh)
  and across cache capacities;
* the recommend serving leg: format_version-6 round trip, engine
  scores == the numpy oracle, ONE d2h per response batch, MXL511
  clean, gather-unit admission cap, and ``/v1/recommend`` end-to-end
  through the fleet router's least-loaded pick.
"""
import json
import os
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.base import MXNetError
from mxnet_tpu.embed import (HotRowCache, ShardedEmbedding, SpillStore,
                             row_init)

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs the 8-virtual-device mesh")


def _mesh22():
    from mxnet_tpu.parallel import make_mesh
    return make_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])


# ---------------------------------------------------------------- row init

def test_row_init_is_per_row_and_order_independent():
    a = row_init(7, [3, 11, 5], 16)
    b = row_init(7, [5, 3], 16)
    assert np.array_equal(a[2], b[0]) and np.array_equal(a[0], b[1])
    # different seed, different bits
    assert not np.array_equal(row_init(8, [3], 16)[0], a[0])


# ------------------------------------------------------- lookup bitwise

@needs_mesh
def test_sharded_lookup_bitwise_vs_dense_fwd_and_grad():
    """2x2-mesh all-to-all lookup == 1-rank dense take, bit for bit —
    forward and table gradient. rows=37 exercises stripe padding; the
    id batch includes out-of-range ids (the clip contract) and heavy
    duplication (the scatter-add fold order)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    rows, dim, batch = 37, 8, 16
    mesh = _mesh22()
    emb = ShardedEmbedding(rows, dim, mesh=mesh, axis_names=("dp", "tp"))
    dense = ShardedEmbedding(rows, dim)     # 1-rank layout
    assert emb.padded_rows % emb.num_shards == 0
    table = emb.init(0)                     # (padded_rows, dim) host
    tab_dense = dense.init(0)
    assert np.array_equal(table[:rows], tab_dense[:rows])

    rng = np.random.RandomState(0)
    ids = rng.randint(0, rows, size=(batch,)).astype(np.int64)
    ids[3] = rows + 9           # OOB high -> clips to rows-1
    ids[5] = ids[7] = ids[1]    # duplicates -> grad contributions fold
    targets = rng.randn(batch, dim).astype(np.float32)

    # forward
    got = np.asarray(emb.make_lookup()(emb.device_put(table), ids))
    want = np.asarray(dense.make_lookup()(tab_dense, ids))
    assert np.array_equal(got, want)

    # gradient: grad of the LOCAL partial loss — every rank's
    # contribution reaches the owner stripe through the all-to-all
    # transpose; a psum inside the grad would scale cotangents by the
    # axis size (see examples/train_twotower.py)
    def local_loss(tab, ids_l, tgt_l):
        v = emb.lookup(tab, ids_l)
        return ((v - tgt_l) ** 2).sum()

    g_fn = shard_map(
        lambda t, i, y: jax.grad(local_loss)(t, i, y),
        mesh=mesh,
        in_specs=(emb.table_spec, P(emb.axis_name), P(emb.axis_name)),
        out_specs=emb.table_spec, check_rep=False)
    g_mesh = np.asarray(jax.jit(g_fn)(emb.device_put(table), ids,
                                      targets))

    def dense_loss(tab):
        v = jnp.take(tab, jnp.clip(ids.astype(np.int32), 0, rows - 1),
                     axis=0)
        return ((v - targets) ** 2).sum()

    g_dense = np.asarray(jax.grad(dense_loss)(tab_dense))
    assert np.array_equal(g_mesh[:rows], g_dense[:rows])
    # padded stripe rows are unreachable: zero grad
    assert not g_mesh[rows:].any()


# ------------------------------------------------------------ OOB parity

def test_oob_clip_parity_across_dispatch_paths():
    """ids beyond the vocab (and negative) must clip identically on the
    Pallas kernel, the jnp.take fallback, sparse_embedding, and
    gather_pages — fwd and grad (tier-independent numerics)."""
    from mxnet_tpu.kernels import take as ktake
    from mxnet_tpu.ops import nn as opsnn

    V, D = 12, 128   # D lane-aligned so the kernel guard admits it
    rng = np.random.RandomState(1)
    w = rng.randn(V, D).astype(np.float32)
    ids = np.array([0, 3, V - 1, V + 7, -2, 3], np.int64)
    ref = np.asarray(jnp.take(w, jnp.clip(ids.astype(np.int32), 0,
                                          V - 1), axis=0))

    assert ktake.eligible(w.shape, w.dtype, ids.shape, ids.dtype) is None
    out_k = np.asarray(ktake.take_rows(jnp.asarray(w), jnp.asarray(ids),
                                       interpret=True))
    out_g = np.asarray(ktake.gather_pages(jnp.asarray(w),
                                          jnp.asarray(ids)))
    out_e = np.asarray(opsnn.sparse_embedding(jnp.asarray(ids),
                                              jnp.asarray(w)))
    assert np.array_equal(out_k, ref)
    assert np.array_equal(out_g, ref)
    assert np.array_equal(out_e, ref)

    # grad parity: the kernel's custom_vjp recomputes through jnp.take,
    # so the scatter-add over clipped (duplicated) ids is the same fold
    cot = rng.randn(len(ids), D).astype(np.float32)

    def via(fn):
        return np.asarray(jax.grad(
            lambda t: (fn(t) * cot).sum())(jnp.asarray(w)))

    g_ref = via(lambda t: jnp.take(
        t, jnp.clip(ids.astype(np.int32), 0, V - 1), axis=0))
    g_k = via(lambda t: ktake.take_rows(t, jnp.asarray(ids),
                                        interpret=True))
    g_e = via(lambda t: opsnn.sparse_embedding(jnp.asarray(ids), t))
    assert np.array_equal(g_k, g_ref)
    assert np.array_equal(g_e, g_ref)


# ------------------------------------------------------------- sparse DDP

@needs_mesh
def test_sparse_ddp_bitwise_and_10x_compression():
    """The sparse bucket kind: contributions all-gathered and coalesced
    in sorted-id order reduce BITWISE-equal to the densified psum oracle
    — at >= 10x fewer exchanged bytes for a realistically tall table."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel import ddp, make_mesh

    rows, dim, per_rank, ranks = 4096, 16, 8, 4
    mesh = make_mesh({"dp": ranks}, devices=jax.devices()[:ranks])
    rng = np.random.RandomState(2)
    ids = rng.randint(0, rows, size=(ranks * per_rank,)).astype(np.int64)
    ids[1] = ids[9] = ids[17]   # cross-rank duplicates must coalesce
    vals = rng.randn(ranks * per_rank, dim).astype(np.float32)

    sb = ddp.SparseBucket("emb", per_rank, dim, rows)
    red = ddp.GradReducer([("w", (4, 4), "float32")], axis_name="dp",
                          axis_size=ranks, sparse=[sb])
    assert red.sparse_densified_bytes >= 10 * red.sparse_comm_bytes
    assert red.stats()["sparse_compression"] >= 10

    w_grad = rng.randn(ranks, 4, 4).astype(np.float32)

    def body(i_l, v_l, w_l):
        out = red.reduce({"emb": (i_l, v_l), "w": w_l[0]})
        return out["emb"], out["w"]

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P("dp"), P("dp"), P("dp")),
                   out_specs=(P(), P()), check_rep=False)
    dense_emb, dense_w = jax.jit(fn)(
        ids.reshape(ranks, per_rank), vals.reshape(ranks, per_rank, dim),
        w_grad)

    # 1-rank oracle: the same sorted-id scatter-add over the GLOBAL batch
    oracle = np.asarray(ddp.coalesce_sparse_grad(
        jnp.asarray(ids), jnp.asarray(vals), rows))
    assert np.array_equal(np.asarray(dense_emb), oracle)
    assert np.array_equal(np.asarray(dense_w), w_grad.sum(0))


# -------------------------------------------------------- cache + spill

def test_spill_store_budget_gate():
    store = SpillStore(64, 8, seed=0, budget_bytes=10 * 8 * 4)
    assert store.logical_bytes > store.budget_bytes  # table > host budget
    store.put(np.arange(10), np.zeros((10, 8), np.float32))
    with pytest.raises(MXNetError, match="host spill store exceeded"):
        store.put(np.arange(10, 14), np.zeros((4, 8), np.float32))


@needs_mesh
def test_twotower_fleet_bitwise_across_shardings_and_capacities():
    """The chip-free fleet drill: the same two-tower run converges to
    BITWISE-identical tables on (a) the 1-rank dense step, (b) the 2x2
    mesh all-to-all step, and (c) the hot-row cache + host-spill step at
    two different capacities — with the user table's LOGICAL bytes above
    the configured host budget for (c)."""
    from functools import partial
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    U, I, D, B, steps = 96, 32, 8, 8, 6
    lr = np.float32(0.5)
    rng = np.random.RandomState(3)
    u_ids = rng.randint(0, U, size=(steps, B)).astype(np.int64)
    i_ids = rng.randint(0, I, size=(steps, B)).astype(np.int64)
    ratings = rng.randn(steps, B).astype(np.float32)

    # (a) dense 1-rank reference
    @partial(jax.jit, donate_argnums=(0, 1))
    def dense_step(u_tab, i_tab, us, isl, r):
        uv = jnp.take(u_tab, us.astype(jnp.int32), axis=0)
        iv = jnp.take(i_tab, isl.astype(jnp.int32), axis=0)
        err = (uv * iv).sum(-1) - r
        d = (2.0 / B) * err
        gu = jnp.zeros_like(u_tab).at[us].add(d[:, None] * iv)
        gi = jnp.zeros_like(i_tab).at[isl].add(d[:, None] * uv)
        return u_tab - lr * gu, i_tab - lr * gi

    u_ref = jnp.asarray(row_init(1, np.arange(U), D))
    i_ref = jnp.asarray(row_init(2, np.arange(I), D))
    for s in range(steps):
        u_ref, i_ref = dense_step(u_ref, i_ref, u_ids[s], i_ids[s],
                                  ratings[s])
    u_ref, i_ref = np.asarray(u_ref), np.asarray(i_ref)

    # (b) 2x2 mesh: all-to-all lookup, grad of the LOCAL partial loss
    mesh = _mesh22()
    emb_u = ShardedEmbedding(U, D, mesh=mesh, axis_names=("dp", "tp"),
                             seed=1)
    emb_i = ShardedEmbedding(I, D, mesh=mesh, axis_names=("dp", "tp"),
                             seed=2)
    ax = emb_u.axis_name

    def local_loss(u_tab, i_tab, u, i, r):
        uv = emb_u.lookup(u_tab, u)
        iv = emb_i.lookup(i_tab, i)
        return (((uv * iv).sum(-1) - r) ** 2).sum() / B

    def mesh_step(u_tab, i_tab, u, i, r):
        gu, gi = jax.grad(local_loss, argnums=(0, 1))(u_tab, i_tab,
                                                      u, i, r)
        return u_tab - lr * gu, i_tab - lr * gi

    step_fn = jax.jit(shard_map(
        mesh_step, mesh=mesh,
        in_specs=(emb_u.table_spec, emb_i.table_spec, P(ax), P(ax),
                  P(ax)),
        out_specs=(emb_u.table_spec, emb_i.table_spec),
        check_rep=False), donate_argnums=(0, 1))
    u_tab = emb_u.device_put(emb_u.init())
    i_tab = emb_i.device_put(emb_i.init())
    for s in range(steps):
        u_tab, i_tab = step_fn(u_tab, i_tab, u_ids[s], i_ids[s],
                               ratings[s])
    assert np.array_equal(np.asarray(u_tab)[:U], u_ref)
    assert np.array_equal(np.asarray(i_tab)[:I], i_ref)

    # (c) cache + spill, two capacities; host budget < logical table
    def run_cached(cap):
        budget = (U - 8) * D * 4   # resident host rows must stay below
        store_u = SpillStore(U, D, seed=1, budget_bytes=budget)
        assert store_u.logical_bytes > budget
        store_i = SpillStore(I, D, seed=2)
        cu, ci = HotRowCache(store_u, cap), HotRowCache(store_i, I)

        @partial(jax.jit, donate_argnums=(0, 1))
        def cache_step(u_buf, i_buf, us, isl, r):
            uv, iv = u_buf[us], i_buf[isl]
            err = (uv * iv).sum(-1) - r
            d = (2.0 / B) * err
            # coalesce per row FIRST, then ONE update per row — the
            # fold that keeps this bitwise-equal to the dense step
            gu = jnp.zeros_like(u_buf).at[us].add(d[:, None] * iv)
            gi = jnp.zeros_like(i_buf).at[isl].add(d[:, None] * uv)
            return u_buf - lr * gu, i_buf - lr * gi

        for s in range(steps):
            us, isl = cu.ensure(u_ids[s]), ci.ensure(i_ids[s])
            cu.buf, ci.buf = cache_step(cu.buf, ci.buf, us, isl,
                                        jnp.asarray(ratings[s]))
            cu.note_updated(u_ids[s])
            ci.note_updated(i_ids[s])
        cu.flush(), ci.flush()
        assert cu.stats()["spill_bytes"] > 0   # the cache really spilled
        return (store_u.peek(np.arange(U)), store_i.peek(np.arange(I)))

    for cap in (24, 48):
        u_c, i_c = run_cached(cap)
        assert np.array_equal(u_c, u_ref), "capacity %d diverged" % cap
        assert np.array_equal(i_c, i_ref)


# ------------------------------------------------------ recommend serving

@pytest.fixture(scope="module")
def reco_artifact(tmp_path_factory):
    from mxnet_tpu.embed.serve import export_recommend
    path = str(tmp_path_factory.mktemp("reco") / "twotower.mxtpu")
    U, I, D = 64, 24, 8
    export_recommend(row_init(1, np.arange(U), D),
                     row_init(2, np.arange(I), D), path,
                     max_ids=8, k=5)
    return path


def test_recommend_roundtrip_oracle_one_d2h_and_mxl511(reco_artifact):
    from mxnet_tpu import profiler
    from mxnet_tpu.serving import load_artifact

    model = load_artifact(reco_artifact)
    assert model.meta["format_version"] == 6
    eng = model.engine(capacity=16, buckets=(4,))
    id_lists = [[3, 9, 9, 60], [0], [5, 1, 2]]
    profiler.reset_sync_counters()
    scores, items = eng.recommend_batch(id_lists)
    # ONE d2h for the whole batch (cold cache: no dirty spills yet)
    assert profiler.sync_counters()["d2h"] == 1

    user, corpus = model.user_table, model.item_table
    for j, ids in enumerate(id_lists):
        vec = user[np.asarray(ids)].mean(0)
        want = np.argsort(-(corpus @ vec), kind="stable")[:5]
        assert list(items[j]) == list(want)
        np.testing.assert_allclose(scores[j], (corpus @ vec)[want],
                                   rtol=1e-6)
    assert eng.stats()["gathers"] == sum(len(x) for x in id_lists)
    assert eng.check_discipline() == []     # MXL511 clean


def test_recommend_admission_cap_bills_gather_units(reco_artifact):
    from mxnet_tpu.config import override
    from mxnet_tpu.serve import Server
    from mxnet_tpu.serve.admission import ServerBusy

    with override(serve_max_gathers=4):
        srv = Server(reco_artifact, auto_start=False)
        try:
            req = srv.submit_recommend([1, 2, 3])
            assert req.units == 3           # billed per-request gathers
            with pytest.raises(ServerBusy, match="cost cap"):
                srv.submit_recommend([4, 5, 6])
            srv.start()
            scores, items = req.result(timeout=30)
            assert len(items) == 5
            assert srv.load_status()["load"]["load_s"] >= 0.0
        finally:
            srv.close(drain=False)


def test_recommend_e2e_through_router_least_loaded(reco_artifact):
    """Two recommend replicas behind the fleet router: /v1/recommend
    proxies through the least-loaded pick (gather-derived load_s), both
    replicas take traffic, bad bodies 400."""
    from mxnet_tpu.fleet.router import Router, RouterHTTPFrontEnd
    from mxnet_tpu.serve import Server
    from mxnet_tpu.serve.http import HttpFrontEnd

    servers, fronts = [], []
    router = Router()
    rfe = None
    try:
        for rid in ("r0", "r1"):
            srv = Server(reco_artifact)
            fe = HttpFrontEnd(srv, port=0).start()
            servers.append(srv)
            fronts.append(fe)
            router.registry.register(
                {"id": rid, "url": fe.address, "model": "twotower",
                 "version": "1", "mode": "recommend", "ready": True})
        rfe = RouterHTTPFrontEnd(router, port=0).start()

        used = set()
        for n in range(8):
            body = json.dumps(
                {"ids": [int(x) for x in
                         np.random.RandomState(n).randint(0, 64, 3)],
                 "model": "twotower"}).encode()
            req = urllib.request.Request(
                rfe.address + "/v1/recommend", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200
                out = json.loads(resp.read())
            used.add(out["replica"])
            assert len(out["items"]) == len(out["scores"]) == 5
            assert out["gathers"] == 3
        # cold fleet: served-count tie-break round-robins both replicas
        assert used == {"r0", "r1"}

        bad = urllib.request.Request(
            rfe.address + "/v1/recommend",
            data=json.dumps({"ids": "nope"}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad, timeout=30)
        assert ei.value.code == 400
    finally:
        if rfe is not None:
            rfe.stop()
        for fe in fronts:
            fe.stop(drain=False)
        for srv in servers:
            srv.close(drain=False)
