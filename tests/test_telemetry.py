"""Run-wide telemetry tests: registry semantics and thread-safety,
Prometheus exposition conformance (round-trip through the strict
parser), the exporters (HTTP listener, JSONL stream), the flight
recorder's ring bounds and postmortem dumps, and the chip-timing
recalibration path (timings log -> LinearCostModel.fit -> ranking
agreement, plus the autotune --recalibrate CLI). All chip-free."""
import json
import math
import os
import sys
import threading
import urllib.request

import pytest

from mxnet_tpu import config as _config
from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import prom
from mxnet_tpu.telemetry.recorder import FlightRecorder
from mxnet_tpu.telemetry.registry import Registry

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))
import autotune as autotune_cli  # noqa: E402

sys.path.pop(0)


# ---------------------------------------------------------------- registry

class TestRegistry:
    def test_counter_inc_value_and_labels(self):
        reg = Registry()
        c = reg.counter("kernel/dispatch_total")
        c.inc()
        c.inc(2, op="bn_act")
        c.inc(3, op="bn_act")
        assert c.value() == 1
        assert c.value(op="bn_act") == 5
        assert c.value(op="other") == 0
        assert sorted((lb.get("op", ""), v) for lb, v in c.samples()) \
            == [("", 1.0), ("bn_act", 5.0)]

    def test_counter_cannot_decrease(self):
        with pytest.raises(ValueError):
            Registry().counter("x").inc(-1)

    def test_gauge_set_and_add(self):
        g = Registry().gauge("train/engine_depth")
        assert g.value() is None
        g.set(3)
        g.add(-1)
        assert g.value() == 2.0

    def test_histogram_cumulative_buckets(self):
        h = Registry().histogram("serve/latency_ms", buckets=(1, 10, 100))
        for v in (0.5, 5, 5, 50, 5000):
            h.observe(v)
        ((labels, s),) = h.samples()
        assert labels == {}
        assert s["buckets"] == {1.0: 1, 10.0: 3, 100.0: 4, math.inf: 5}
        assert s["count"] == 5 and s["sum"] == pytest.approx(5060.5)

    def test_get_or_create_and_kind_clash(self):
        reg = Registry()
        a = reg.counter("a/b", "first help wins")
        assert reg.counter("a/b") is a
        assert a.help == "first help wins"
        with pytest.raises(TypeError):
            reg.gauge("a/b")
        assert reg.get("a/b") is a and reg.get("nope") is None

    def test_snapshot_is_json_able(self):
        reg = Registry()
        reg.counter("c").inc(2, op="x")
        reg.gauge("g").set(1.5)
        reg.histogram("h", buckets=(1.0,)).observe(3)
        snap = json.loads(json.dumps(reg.snapshot()))
        assert snap["c"]["type"] == "counter"
        assert snap["h"]["samples"][0]["buckets"] == {"1.0": 0, "+Inf": 1}

    def test_run_info_merge_skips_none(self):
        reg = Registry()
        reg.set_run_info(flops_per_step=1e9, device_kind=None)
        reg.set_run_info(batch_size=128)
        assert reg.run_info() == {"flops_per_step": 1e9, "batch_size": 128}

    def test_concurrent_publishers_lose_nothing(self):
        """The exactness contract: N threads x M increments == N*M, with
        scrapes running concurrently (collect must not deadlock or tear)."""
        reg = Registry()
        c = reg.counter("stress/total")
        h = reg.histogram("stress/lat", buckets=(1, 10))
        N, M = 8, 500
        stop = threading.Event()

        def publish(tid):
            for i in range(M):
                c.inc()
                c.inc(1, worker=str(tid))
                h.observe(i % 20)

        def scrape():
            while not stop.is_set():
                prom.parse_exposition(prom.exposition(reg))

        scraper = threading.Thread(target=scrape, daemon=True)
        scraper.start()
        threads = [threading.Thread(target=publish, args=(t,))
                   for t in range(N)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        scraper.join(5)
        assert c.value() == N * M
        assert sum(c.value(worker=str(t)) for t in range(N)) == N * M
        ((_, s),) = h.samples()
        assert s["count"] == N * M


# ------------------------------------------------------------- prometheus

class TestPrometheusExposition:
    def _reg(self):
        reg = Registry()
        reg.counter("kernel/dispatch_total", "dispatches").inc(
            4, op="bn_act")
        reg.gauge("train/step_time_ms", "per-step ms").set(12.25)
        reg.histogram("serve/latency_ms", buckets=(1, 10)).observe(3)
        return reg

    def test_round_trip_and_naming(self):
        text = prom.exposition(self._reg())
        fams = prom.parse_exposition(text)
        # counters grow _total exactly once; slashes sanitize to _
        assert "mxtpu_kernel_dispatch_total" in fams
        assert fams["mxtpu_kernel_dispatch_total"]["type"] == "counter"
        assert fams["mxtpu_kernel_dispatch_total"]["samples"] \
            == [({"op": "bn_act"}, 4.0)]
        assert fams["mxtpu_train_step_time_ms"]["samples"] == [({}, 12.25)]

    def test_histogram_children_key_under_parent(self):
        fams = prom.parse_exposition(prom.exposition(self._reg()))
        f = fams["mxtpu_serve_latency_ms"]
        assert f["type"] == "histogram"
        by_le = {lb.get("le"): v for lb, v in f["samples"] if "le" in lb}
        assert by_le == {"1": 0.0, "10": 1.0, "+Inf": 1.0}
        # _sum and _count folded in too: 2 extra label-free samples
        assert len(f["samples"]) == 5

    def test_label_escaping_survives_round_trip(self):
        reg = Registry()
        reg.counter("c").inc(1, path='a"b\\c\nd')
        fams = prom.parse_exposition(prom.exposition(reg))
        ((labels, v),) = fams["mxtpu_c_total"]["samples"]
        assert labels == {"path": 'a"b\\c\nd'} and v == 1.0

    def test_special_values(self):
        reg = Registry()
        reg.gauge("g").set(math.inf)
        reg.gauge("g").set(math.nan, kind="n")
        fams = prom.parse_exposition(prom.exposition(reg))
        vals = {tuple(lb.items()): v for lb, v in
                fams["mxtpu_g"]["samples"]}
        assert vals[()] == math.inf
        assert math.isnan(vals[(("kind", "n"),)])

    def test_parser_is_strict(self):
        for bad in ("metric 1 2 3 junk\n", "1bad_name 2\n",
                    'm{no_quote=3} 1\n', "m nope\n"):
            with pytest.raises(ValueError):
                prom.parse_exposition(bad)

    def test_sanitize_name(self):
        assert prom.sanitize_name("train/step_time_ms") \
            == "mxtpu_train_step_time_ms"
        assert prom.sanitize_name("0weird-name") == "mxtpu__0weird_name"


# -------------------------------------------------------------- exporters

class TestExporters:
    def test_http_listener_on_ephemeral_port(self):
        telemetry.gauge("exporters_test/alive").set(1)
        srv = telemetry.exporters.TelemetryHTTPServer(
            host="127.0.0.1", port=0).start()
        try:
            assert srv.port > 0
            with urllib.request.urlopen(srv.address + "/metrics",
                                        timeout=10) as r:
                assert r.headers["Content-Type"] == prom.CONTENT_TYPE
                fams = prom.parse_exposition(r.read().decode())
            assert "mxtpu_exporters_test_alive" in fams
            with urllib.request.urlopen(srv.address + "/metrics.json",
                                        timeout=10) as r:
                snap = json.loads(r.read().decode())
            assert "exporters_test/alive" in snap
            with urllib.request.urlopen(srv.address + "/healthz",
                                        timeout=10) as r:
                assert json.loads(r.read().decode())["status"] == "ok"
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(srv.address + "/nope", timeout=10)
        finally:
            srv.stop()

    def test_jsonl_writer_appends(self, tmp_path):
        path = str(tmp_path / "sub" / "telemetry.jsonl")
        w = telemetry.exporters.JsonlWriter(path)
        assert w.write({"global_step": 1})
        assert w.write({"global_step": 2})
        lines = [json.loads(ln) for ln in open(path)]
        assert [ln["global_step"] for ln in lines] == [1, 2]

    def test_jsonl_path_resolution(self, tmp_path):
        with _config.override(telemetry_jsonl="", telemetry_dir=""):
            assert telemetry.exporters.jsonl_path() is None
        with _config.override(telemetry_dir=str(tmp_path)):
            assert telemetry.exporters.jsonl_path() \
                == os.path.join(str(tmp_path), "telemetry.jsonl")
        with _config.override(telemetry_jsonl="/x/y.jsonl",
                              telemetry_dir=str(tmp_path)):
            assert telemetry.exporters.jsonl_path() == "/x/y.jsonl"


# --------------------------------------------------------- publish_window

class TestPublishWindow:
    def test_populates_series_and_returns_record(self):
        rec = telemetry.publish_window(steps=16, window_s=0.8,
                                       examples=512, engine_depth=2,
                                       global_step=160)
        assert rec["step_ms"] == pytest.approx(50.0)
        reg = telemetry.default_registry()
        assert reg.get("train/step_time_ms").value() \
            == pytest.approx(50.0)
        assert reg.get("train/examples_per_s").value() \
            == pytest.approx(512 / 0.8)
        assert reg.get("train/engine_depth").value() == 2
        assert reg.get("train/global_step").value() == 160
        assert reg.get("host_sync/d2h") is not None

    def test_adds_zero_host_syncs(self):
        """The tentpole invariant: publishing a window touches no device
        array, so the profiler's sync census does not move."""
        from mxnet_tpu import profiler
        before = profiler.sync_counters()
        for i in range(5):
            telemetry.publish_window(steps=4, window_s=0.1, examples=16,
                                     engine_depth=1, global_step=i)
        assert profiler.sync_counters() == before

    def test_live_mfu_from_run_info(self):
        reg = telemetry.default_registry()
        reg.set_run_info(flops_per_step=1e12, device_kind=None)
        try:
            telemetry.publish_window(steps=10, window_s=1.0)
            mfu = reg.get("train/mfu").value()
            assert mfu is not None and 0 < mfu
        finally:
            reg._run_info.pop("flops_per_step", None)

    def test_mirrors_label_free_series_into_trace(self, tmp_path):
        import mxnet_tpu as mx
        prof = str(tmp_path / "telemetry_prof.json")
        mx.profiler.set_config(filename=prof)
        mx.profiler.set_state("run")
        try:
            telemetry.gauge("mirror_test/depth").set(7)
        finally:
            mx.profiler.set_state("stop")
        mx.profiler.dump()
        with open(prof) as f:
            events = json.load(f)["traceEvents"]
        tracks = [e for e in events if e.get("ph") == "C"
                  and e.get("name") == "mirror_test/depth"]
        assert tracks and tracks[-1]["args"]["mirror_test/depth"] == 7.0


# --------------------------------------------------------- flight recorder

class TestFlightRecorder:
    def test_ring_bounds(self):
        rec = FlightRecorder(maxlen=4)
        for i in range(10):
            rec.record_step({"global_step": i})
        pm = rec.postmortem("test")
        assert [s["global_step"] for s in pm["steps"]] == [6, 7, 8, 9]

    def test_postmortem_payload(self):
        rec = FlightRecorder(maxlen=8)
        rec.record_step({"global_step": 1})
        rec.record_event("ckpt", step=1)
        rec.note_snapshot({"some": "registry"})
        pm = rec.postmortem("why not")
        assert pm["reason"] == "why not"
        assert pm["pid"] == os.getpid()
        assert pm["events"][0]["kind"] == "ckpt"
        assert pm["snapshots"][0]["registry"] == {"some": "registry"}
        assert "registry" in pm and "sync_counters" in pm
        json.dumps(pm, default=str)   # JSON-able end to end

    def test_dump_is_noop_without_dir(self):
        with _config.override(telemetry_dir=""):
            assert FlightRecorder(maxlen=2).dump("no dir") is None

    def test_dump_writes_once_unless_forced(self, tmp_path):
        rec = FlightRecorder(maxlen=2)
        rec.record_step({"global_step": 3})
        with _config.override(telemetry_dir=str(tmp_path)):
            path = rec.dump("first")
            assert path and os.path.dirname(path) == str(tmp_path)
            with open(path) as f:
                post = json.load(f)
            assert post["reason"] == "first"
            assert post["steps"][0]["global_step"] == 3
            assert rec.dump("second") is None          # once per process
            assert rec.dump("third", force=True) == path
            with open(path) as f:
                assert json.load(f)["reason"] == "third"


# ----------------------------------------------------- recalibration path

def _synthetic_rows(n_tasks=3, n_cfg=8, seed=7):
    """Timing rows from a perturbed linear ground truth: a fresh OLS fit
    must rank them (near-)perfectly, the shipped weights imperfectly."""
    import random
    from mxnet_tpu.tune import cost_model as cm
    rng = random.Random(seed)
    true_w = {"hbm_time_us": 1.7, "flop_time_us": 0.4,
              "grid_overhead_us": 3.0, "misalign": 120.0,
              "waste": 5.0, "vmem_frac": 0.5, "vpu_time_us": 0.9,
              "dma_steps": 0.01, "tile_waste": 8.0}
    rows = []
    for t in range(n_tasks):
        for _ in range(n_cfg):
            feat = {k: rng.random() * 10 for k in cm.FEATURE_NAMES}
            rows.append({
                "op": "bn_act", "key": "bn_act|task%d|bfloat16" % t,
                "shapes": [[8192, 4096]], "dtype": "bfloat16",
                "config": {"block_r": 8 * (t + 1)},
                "features": feat,
                "time_us": sum(true_w[k] * feat[k]
                               for k in cm.FEATURE_NAMES),
            })
    return rows


class TestRecalibration:
    def test_record_rows_writes_only_measured(self, tmp_path):
        from mxnet_tpu.tune import cost_model as cm, timings
        path = str(tmp_path / "kt.jsonl")
        rows = [
            {"config": {"block_r": 8}, "source": "measured",
             "features": {k: 1.0 for k in cm.FEATURE_NAMES},
             "score_us": 10.0},
            {"config": {"block_r": 16}, "source": "model",
             "features": {}, "score_us": 5.0},
        ]
        n = timings.record_rows("bn_act", ((8192, 4096),), "bfloat16",
                                "TPU v5 lite", rows, path=path)
        assert n == 1
        loaded, skipped = timings.load(path)
        assert len(loaded) == 1 and skipped == 0
        assert loaded[0]["time_us"] == 10.0
        assert loaded[0]["key"].startswith("bn_act|")

    def test_record_rows_disabled_without_path(self, tmp_path):
        from mxnet_tpu.tune import timings
        with _config.override(kernel_timings="", telemetry_dir=""):
            assert timings.timings_path() is None
            assert timings.record_rows("bn_act", ((8, 8),), "f32",
                                       "cpu", [{"source": "measured",
                                                "config": {},
                                                "features": {},
                                                "score_us": 1.0}]) == 0

    def test_load_skips_torn_lines(self, tmp_path):
        from mxnet_tpu.tune import timings
        path = tmp_path / "torn.jsonl"
        good = _synthetic_rows(1, 2)
        path.write_text(json.dumps(good[0]) + "\n"
                        + "{\"torn\": tru\n"
                        + json.dumps(good[1]) + "\n"
                        + json.dumps({"op": "x"}) + "\n")
        rows, skipped = timings.load(str(path))
        assert len(rows) == 2 and skipped == 2

    def test_recalibrate_improves_ranking_agreement(self):
        from mxnet_tpu.tune import timings
        rows = _synthetic_rows()
        fitted, report = timings.recalibrate(rows)
        assert report["rows"] == len(rows) and report["tasks"] == 3
        assert report["after"]["pairwise"] >= report["before"]["pairwise"]
        assert report["after"]["pairwise"] == pytest.approx(1.0)
        assert report["after"]["top1"] == 1.0
        # the fit recovered the ground-truth misalign >> waste ordering
        assert fitted.weights["misalign"] > fitted.weights["waste"]
        with pytest.raises(ValueError):
            timings.recalibrate([])

    def test_saved_weights_round_trip_into_default_model(self, tmp_path):
        from mxnet_tpu.tune import cost_model as cm, timings
        fitted, _ = timings.recalibrate(_synthetic_rows())
        path = str(tmp_path / "weights.json")
        assert cm.save_weights(fitted, path) == path
        with _config.override(kernel_cost_model=path):
            loaded = cm.default_model()
            assert loaded.weights == pytest.approx(fitted.weights)
        with _config.override(kernel_cost_model=""):
            assert cm.default_model().weights == cm.LinearCostModel.\
                DEFAULT_WEIGHTS

    def test_autotune_recalibrate_cli(self, tmp_path, capsys):
        path = str(tmp_path / "kt.jsonl")
        with open(path, "w") as f:
            for row in _synthetic_rows():
                f.write(json.dumps(row) + "\n")
        model_out = str(tmp_path / "model.json")
        rc = autotune_cli.main(["--recalibrate", "--timings", path,
                                "--save-model", model_out])
        out = capsys.readouterr().out
        assert rc == 0
        assert "ranking agreement" in out and "pairwise" in out
        assert "->" in out            # before -> after rendering
        doc = json.load(open(model_out))
        from mxnet_tpu.tune import cost_model as cm
        assert doc["version"] == cm.WEIGHTS_VERSION and "weights" in doc
        assert set(doc["features"]) == set(cm.FEATURE_NAMES)

    def test_autotune_recalibrate_no_log_is_rc2(self, tmp_path, capsys):
        rc = autotune_cli.main(["--recalibrate", "--timings",
                                str(tmp_path / "missing.jsonl")])
        assert rc == 2
        assert "no timing log" in capsys.readouterr().err
