"""im2rec tool end-to-end (parity: reference tools/im2rec.py): folder of
images -> .lst -> .rec/.idx -> ImageRecordIter batches."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
IM2REC = os.path.join(ROOT, "tools", "im2rec.py")
cv2 = pytest.importorskip("cv2")


def _make_images(base):
    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        d = os.path.join(base, cls)
        os.makedirs(d)
        for i in range(4):
            img = rng.randint(0, 255, (40, 50, 3), dtype=np.uint8)
            cv2.imwrite(os.path.join(d, "%s_%d.jpg" % (cls, i)), img)


def _run(args):
    r = subprocess.run([sys.executable, IM2REC] + args, capture_output=True,
                       text=True, timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr
    return r


def test_im2rec_list_and_pack(tmp_path):
    imgdir = str(tmp_path / "images")
    _make_images(imgdir)
    prefix = str(tmp_path / "data")

    _run(["--list", "--recursive", prefix, imgdir])
    lst = prefix + ".lst"
    assert os.path.exists(lst)
    lines = open(lst).read().strip().splitlines()
    assert len(lines) == 8
    labels = {float(l.split("\t")[1]) for l in lines}
    assert labels == {0.0, 1.0}  # one label per leaf dir

    _run(["--resize", "32", "--num-thread", "2", prefix, imgdir])
    assert os.path.exists(prefix + ".rec") and os.path.exists(prefix + ".idx")

    it = mx.io.ImageRecordIter(prefix + ".rec", data_shape=(3, 28, 28),
                               batch_size=4, rand_crop=True,
                               preprocess_threads=2, seed=7)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (4, 3, 28, 28)
    seen_labels = np.concatenate([b.label[0].asnumpy() for b in batches])
    assert set(seen_labels.tolist()) == {0.0, 1.0}


def test_im2rec_train_val_split(tmp_path):
    imgdir = str(tmp_path / "images")
    _make_images(imgdir)
    prefix = str(tmp_path / "split")
    _run(["--list", "--recursive", "--train-ratio", "0.5", prefix, imgdir])
    train = open(prefix + "_train.lst").read().strip().splitlines()
    val = open(prefix + "_val.lst").read().strip().splitlines()
    assert len(train) == 4 and len(val) == 4


def test_getnnz():
    # csr: STORED-value count — the explicit zero counts (reference
    # contrib/nnz.cc semantics)
    csr = mx.nd.sparse.csr_matrix(
        (np.array([1.0, 0.0, 3.0], np.float32),
         np.array([0, 2, 1], np.int64), np.array([0, 2, 3], np.int64)),
        shape=(2, 3))
    n_stored = mx.nd.contrib.getnnz(csr)
    assert int(n_stored.asnumpy()) == 3
    # dense fallback counts nonzeros
    n = mx.nd.contrib.getnnz(mx.nd.array(np.array([[1, 0], [2, 3]],
                                                  np.float32)))
    assert int(n.asnumpy()) == 3
