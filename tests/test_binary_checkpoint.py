"""Reference binary .params format tests (wire layout of
src/ndarray/ndarray.cc:1583-1795)."""
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import serialization as ser


def test_roundtrip_dense_dict(tmp_path):
    p = str(tmp_path / "x.params")
    d = {"w": mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3)),
         "b": mx.nd.array(np.array([1, 2, 3], np.int32))}
    mx.nd.save(p, d)
    out = mx.nd.load(p)
    assert set(out) == {"w", "b"}
    np.testing.assert_array_equal(out["w"].asnumpy(), d["w"].asnumpy())
    assert out["b"].asnumpy().dtype == np.int32


def test_roundtrip_list_and_dtypes(tmp_path):
    p = str(tmp_path / "l.params")
    arrays = [mx.nd.array(np.random.rand(4, 5).astype(dt))
              for dt in (np.float32, np.float16, np.float64)]
    mx.nd.save(p, arrays)
    out = mx.nd.load(p)
    assert isinstance(out, list) and len(out) == 3
    for a, b in zip(arrays, out):
        np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())
        assert a.asnumpy().dtype == b.asnumpy().dtype


def test_roundtrip_sparse(tmp_path):
    p = str(tmp_path / "s.params")
    rsp = mx.nd.sparse.row_sparse_array(
        (np.array([[1., 2.], [3., 4.]], np.float32),
         np.array([1, 3], np.int64)), shape=(5, 2))
    csr = mx.nd.sparse.csr_matrix(
        (np.array([7., 8.], np.float32), np.array([1, 0], np.int64),
         np.array([0, 1, 2], np.int64)), shape=(2, 3))
    mx.nd.save(p, {"rsp": rsp, "csr": csr})
    out = mx.nd.load(p)
    assert out["rsp"].stype == "row_sparse"
    assert out["csr"].stype == "csr"
    np.testing.assert_array_equal(out["rsp"].asnumpy(), rsp.asnumpy())
    np.testing.assert_array_equal(out["csr"].asnumpy(), csr.asnumpy())


def test_wire_layout_golden():
    """Byte-level check of the V2 record against the reference layout."""
    out = bytearray()
    ser.save_array(out, np.array([[1.0, 2.0]], np.float32))
    expect = (struct.pack("<I", 0xF993FAC9)      # V2 magic
              + struct.pack("<i", 1)             # kDefaultStorage
              + struct.pack("<I", 2)             # ndim
              + struct.pack("<qq", 1, 2)         # int64 dims
              + struct.pack("<ii", 1, 0)         # Context cpu:0
              + struct.pack("<i", 0)             # kFloat32
              + struct.pack("<ff", 1.0, 2.0))    # raw data
    assert bytes(out) == expect


def test_list_container_golden(tmp_path):
    p = str(tmp_path / "g.params")
    mx.nd.save(p, {"a": mx.nd.array([1.0], dtype="float32")})
    raw = open(p, "rb").read()
    magic, reserved, count = struct.unpack_from("<QQQ", raw)
    assert magic == 0x112 and reserved == 0 and count == 1
    # names vector at the tail: count, len, bytes
    assert raw.endswith(struct.pack("<Q", 1) + struct.pack("<Q", 1) + b"a")


def test_legacy_v1_and_v0_records_load():
    data = np.array([[5.0, 6.0]], np.float32)
    # V1: magic + shape + ctx + flag + raw
    v1 = (struct.pack("<I", 0xF993FAC8) + struct.pack("<I", 2)
          + struct.pack("<qq", 1, 2) + struct.pack("<ii", 1, 0)
          + struct.pack("<i", 0) + data.tobytes())
    # V0: uint32 ndim as 'magic', uint32 dims
    v0 = (struct.pack("<I", 2) + struct.pack("<II", 1, 2)
          + struct.pack("<ii", 1, 0) + struct.pack("<i", 0)
          + data.tobytes())
    import io as _io
    for raw in (v1, v0):
        arr = ser.load_array(_io.BytesIO(raw))
        np.testing.assert_array_equal(arr, data)


def test_npz_legacy_container_still_loads(tmp_path):
    p = str(tmp_path / "old.params")
    with open(p, "wb") as f:
        f.write(b"MXTPU001")
        np.savez(f, __keys__=np.asarray(["k"]),
                 **{"data_k": np.array([1.0, 2.0], np.float32)})
    out = mx.nd.load(p)
    np.testing.assert_array_equal(out["k"].asnumpy(), [1.0, 2.0])


def test_module_checkpoint_uses_binary_format(tmp_path):
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                              name="fc"), name="softmax")
    mod = mx.mod.Module(net)
    mod.bind([("data", (4, 3))], [("softmax_label", (4,))])
    mod.init_params(mx.initializer.Xavier())
    prefix = str(tmp_path / "ckpt")
    mod.save_checkpoint(prefix, 0)
    raw = open(prefix + "-0000.params", "rb").read()
    assert struct.unpack_from("<Q", raw)[0] == 0x112
    sym, arg, aux = mx.model.load_checkpoint(prefix, 0)
    w0 = mod.get_params()[0]["fc_weight"].asnumpy()
    np.testing.assert_array_equal(arg["fc_weight"].asnumpy(), w0)


def test_scalar_array_roundtrips_as_shape_1(tmp_path):
    """0-d arrays project to (1,) — the reference wire format's ndim-0
    record means 'none' and carries no payload (regression: scalar save
    corrupted the stream for every following record)."""
    p = str(tmp_path / "sc.params")
    ser.save_file(p, [np.array(3.5, np.float32),
                      np.array([1.0, 2.0], np.float32)], [])
    arrays, _ = ser.load_file(p)
    np.testing.assert_array_equal(arrays[0], [3.5])
    np.testing.assert_array_equal(arrays[1], [1.0, 2.0])
