"""Gluon core tests (model: reference tests/python/unittest/test_gluon.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier")
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)
    assert p.list_ctx() == [mx.current_context()]


def test_parameter_invalid_access():
    p = gluon.Parameter("weight", shape=(10, 10))
    with pytest.raises(RuntimeError):
        p.data()


def test_constant():
    class Test(gluon.HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.value = np.asarray([[1, 2], [3, 4]], dtype="float32")
            self.const = self.params.get_constant("const", self.value)

        def hybrid_forward(self, F, x, const):
            return x + const

    test = Test()
    test.initialize()
    trainer = gluon.Trainer(test.collect_params(), "sgd",
                            {"learning_rate": 1.0, "momentum": 0.5})
    with autograd.record():
        x = mx.nd.ones((2, 2))
        x.attach_grad()
        y = test(x)
        y.backward()
    trainer.step(1)
    assert (test.const.data().asnumpy() == test.value).all()
    assert (x.grad.asnumpy() == 1).all()


def test_paramdict_get_shared():
    shared = gluon.ParameterDict("net_")
    d1 = gluon.ParameterDict("net_", shared)
    p0 = shared.get("w", shape=(2, 2))
    p1 = d1.get("w")
    assert p0 is p1


def test_dense_forward_value():
    layer = nn.Dense(3, in_units=4, use_bias=True)
    layer.initialize(mx.init.One())
    x = mx.nd.array(np.arange(8).reshape(2, 4).astype("float32"))
    out = layer(x)
    # per-param init wins over default_init: bias_initializer='zeros' holds
    expect = np.arange(8).reshape(2, 4).sum(1, keepdims=True)
    assert_almost_equal(out.asnumpy(), np.tile(expect, (1, 3)))


def test_dense_deferred_init():
    layer = nn.Dense(7)
    layer.initialize()
    x = mx.nd.ones((4, 5))
    out = layer(x)
    assert out.shape == (4, 7)
    assert layer.weight.shape == (7, 5)


def test_dense_no_flatten():
    layer = nn.Dense(5, flatten=False)
    layer.initialize()
    out = layer(mx.nd.ones((2, 3, 4)))
    assert out.shape == (2, 3, 5)


def test_sequential_and_indexing():
    net = nn.Sequential()
    net.add(nn.Dense(4), nn.Dense(3), nn.Dense(2))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)
    net.initialize()
    assert net(mx.nd.ones((1, 6))).shape == (1, 2)


def test_hybrid_matches_eager():
    np.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"),
                nn.LayerNorm(),
                nn.Dense(8))
    net.initialize()
    x = mx.nd.array(np.random.randn(4, 16).astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert_almost_equal(eager, hybrid, rtol=1e-5, atol=1e-5)


def test_hybrid_gradients_match_eager():
    np.random.seed(1)
    def build():
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16, activation="tanh"), nn.Dense(1))
        net.initialize(mx.init.Xavier())
        return net

    import tempfile, os
    net_e = build()
    x = mx.nd.array(np.random.randn(5, 8).astype("float32"))
    net_e(x)  # trigger deferred init
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "p.params")
        net_e.save_parameters(fname)
        net_h = build()
        net_h(x)
        net_h.load_parameters(fname)
    net_h.hybridize()
    grads = []
    for net in (net_e, net_h):
        with autograd.record():
            y = net(x)
            loss = (y * y).sum()
        loss.backward()
        grads.append({k: p.grad().asnumpy()
                      for k, p in net.collect_params().items()})
    keys_e = sorted(grads[0])
    keys_h = sorted(grads[1])
    for ke, kh in zip(keys_e, keys_h):
        assert_almost_equal(grads[0][ke], grads[1][kh], rtol=1e-4, atol=1e-5)


def test_batchnorm_running_stats_update():
    bn = nn.BatchNorm(in_channels=3)
    bn.initialize()
    x = mx.nd.array(np.random.randn(8, 3, 4, 4).astype("float32") * 2 + 5)
    with autograd.record():
        bn(x)
    rm = bn.running_mean.data().asnumpy()
    assert not np.allclose(rm, 0)  # moving mean moved toward batch mean
    # inference mode uses running stats, no update
    rm_before = rm.copy()
    bn(x)
    assert_almost_equal(bn.running_mean.data().asnumpy(), rm_before)


def test_batchnorm_hybrid_updates_stats():
    bn = nn.BatchNorm(in_channels=2)
    bn.initialize()
    bn.hybridize()
    x = mx.nd.array(np.random.randn(4, 2, 3, 3).astype("float32") + 3)
    with autograd.record():
        bn(x)
    assert not np.allclose(bn.running_mean.data().asnumpy(), 0)


def test_conv2d_shapes():
    layer = nn.Conv2D(16, (3, 3), padding=(1, 1))
    layer.initialize()
    out = layer(mx.nd.ones((2, 4, 8, 8)))
    assert out.shape == (2, 16, 8, 8)
    assert layer.weight.shape == (16, 4, 3, 3)


def test_conv1d_conv3d():
    l1 = nn.Conv1D(4, 3)
    l1.initialize()
    assert l1(mx.nd.ones((2, 3, 10))).shape == (2, 4, 8)
    l3 = nn.Conv3D(4, (2, 2, 2))
    l3.initialize()
    assert l3(mx.nd.ones((2, 3, 5, 5, 5))).shape == (2, 4, 4, 4, 4)


def test_conv2d_transpose():
    layer = nn.Conv2DTranspose(8, (3, 3), strides=(2, 2))
    layer.initialize()
    out = layer(mx.nd.ones((1, 4, 7, 7)))
    assert out.shape[0:2] == (1, 8)


def test_pooling_layers():
    x = mx.nd.ones((2, 3, 8, 8))
    assert nn.MaxPool2D()(x).shape == (2, 3, 4, 4)
    assert nn.AvgPool2D((2, 2), strides=2)(x).shape == (2, 3, 4, 4)
    assert nn.GlobalAvgPool2D()(x).shape == (2, 3, 1, 1)
    assert nn.GlobalMaxPool2D()(x).shape == (2, 3, 1, 1)


def test_activations_layers():
    x = mx.nd.array(np.array([-1.0, 0.0, 2.0], dtype="float32"))
    assert_almost_equal(nn.Activation("relu")(x).asnumpy(),
                        np.array([0, 0, 2], dtype="float32"))
    out = nn.LeakyReLU(0.1)(x).asnumpy()
    assert_almost_equal(out, np.array([-0.1, 0, 2], dtype="float32"))
    for layer in [nn.ELU(), nn.SELU(), nn.Swish(), nn.GELU()]:
        y = layer(x)
        assert y.shape == x.shape
    pr = nn.PReLU()
    pr.initialize()
    assert pr(x).shape == x.shape


def test_embedding():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = mx.nd.array(np.array([1, 2, 3], dtype="float32"))
    out = emb(idx)
    assert out.shape == (3, 4)
    with autograd.record():
        loss = emb(idx).sum()
    loss.backward()
    g = emb.weight.grad().asnumpy()
    assert g[1].sum() != 0 and g[0].sum() == 0


def test_trainer_sgd_matches_manual():
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize(mx.init.One())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    x = mx.nd.array([[1.0, 2.0]])
    with autograd.record():
        y = net(x)
    y.backward()
    trainer.step(1)
    # w <- w - 0.5 * grad; grad = x
    assert_almost_equal(net.weight.data().asnumpy(),
                        np.array([[0.5, 0.0]], dtype="float32"))


def test_trainer_save_load_states(tmp_path):
    net = nn.Dense(2, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    x = mx.nd.ones((1, 2))
    with autograd.record():
        net(x).sum().backward()
    trainer.step(1)
    fname = str(tmp_path / "trainer.states")
    trainer.save_states(fname)
    trainer.load_states(fname)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4), nn.Dense(2))
    net.initialize()
    x = mx.nd.ones((1, 3))
    y0 = net(x).asnumpy()
    fname = str(tmp_path / "net.params")
    net.save_parameters(fname)

    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(4), nn.Dense(2))
    net2.load_parameters(fname)
    assert_almost_equal(net2(x).asnumpy(), y0)


def test_losses_values():
    pred = mx.nd.array(np.array([[1.0, 2.0], [0.5, 0.5]], dtype="float32"))
    label = mx.nd.array(np.array([[0.0, 1.0], [1.0, 0.0]], dtype="float32"))
    l2 = gluon.loss.L2Loss()(pred, label).asnumpy()
    expect = ((np.array([[1, 1], [-0.5, 0.5]]) ** 2) / 2).mean(1)
    assert_almost_equal(l2, expect.astype("float32"), rtol=1e-5)

    l1 = gluon.loss.L1Loss()(pred, label).asnumpy()
    assert_almost_equal(l1, np.abs(
        np.array([[1, 1], [-0.5, 0.5]])).mean(1).astype("float32"), rtol=1e-5)

    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    sparse_label = mx.nd.array(np.array([1, 0], dtype="float32"))
    out = sce(pred, sparse_label).asnumpy()
    p = np.exp([[1, 2], [0.5, 0.5]])
    p = p / p.sum(1, keepdims=True)
    expect = -np.log(np.array([p[0, 1], p[1, 0]]))
    assert_almost_equal(out, expect.astype("float32"), rtol=1e-5)


def test_loss_shapes():
    pred = mx.nd.ones((4, 3))
    lab = mx.nd.ones((4, 3))
    for L in [gluon.loss.SigmoidBCELoss(), gluon.loss.KLDivLoss(),
              gluon.loss.HuberLoss(), gluon.loss.HingeLoss(),
              gluon.loss.SquaredHingeLoss(), gluon.loss.LogisticLoss()]:
        out = L(pred, lab)
        assert out.shape == (4,), (type(L).__name__, out.shape)
    tl = gluon.loss.TripletLoss()
    assert tl(pred, lab, 0 * lab).shape == (4,)


def test_split_and_load():
    data = mx.nd.array(np.arange(12).reshape(6, 2).astype("float32"))
    parts = gluon.utils.split_data(data, 3)
    assert [p.shape for p in parts] == [(2, 2)] * 3
    loaded = gluon.utils.split_and_load(data, [mx.cpu(), mx.cpu()])
    assert len(loaded) == 2
    with pytest.raises(ValueError):
        gluon.utils.split_data(data, 5)


def test_clip_global_norm():
    arrays = [mx.nd.ones((2, 2)) * 3, mx.nd.ones((2,)) * 4]
    total = gluon.utils.clip_global_norm(arrays, 1.0)
    new_norm = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert abs(new_norm - 1.0) < 1e-4
    assert total > 1.0


def test_block_naming_and_scopes():
    class Model(gluon.Block):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                self.dense0 = nn.Dense(5)
                self.dense1 = nn.Dense(5)

        def forward(self, x):
            return self.dense1(self.dense0(x))

    model = Model(prefix="model_")
    assert model.prefix == "model_"
    assert model.dense0.prefix.startswith("model_dense")
    names = list(model.collect_params().keys())
    assert all(n.startswith("model_") for n in names)


def test_collect_params_select():
    net = nn.HybridSequential(prefix="net_")
    with net.name_scope():
        net.add(nn.Dense(4, prefix="fc1_"), nn.Dense(4, prefix="fc2_"))
    sel = net.collect_params("net_fc1_.*")
    assert all("fc1" in k for k in sel.keys())
    assert len(sel) == 2


def test_forward_hooks():
    calls = []
    net = nn.Dense(2, in_units=2)
    net.initialize()
    net.register_forward_pre_hook(lambda blk, ins: calls.append("pre"))
    net.register_forward_hook(lambda blk, ins, outs: calls.append("post"))
    net(mx.nd.ones((1, 2)))
    assert calls == ["pre", "post"]


def test_symbol_block_and_export(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize()
    x = mx.nd.ones((2, 4))
    y0 = net(x).asnumpy()
    path = str(tmp_path / "model")
    net.export(path)
    imported = gluon.SymbolBlock.imports(path + "-symbol.json", ["data"],
                                         path + "-0000.params")
    y1 = imported(x).asnumpy()
    assert_almost_equal(y0, y1, rtol=1e-5, atol=1e-6)


def test_lambda_blocks():
    lam = nn.Lambda(lambda x: x * 2)
    assert_almost_equal(lam(mx.nd.ones((2,))).asnumpy(),
                        np.full((2,), 2, dtype="float32"))
    hl = nn.HybridLambda(lambda F, x: F.relu(x))
    assert hl(mx.nd.array(np.array([-1.0, 1.0]))).asnumpy()[0] == 0


def test_hybrid_static_shape_cache():
    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.hybridize()
    net(mx.nd.ones((2, 3)))
    net(mx.nd.ones((5, 3)))  # second signature compiles separately
    assert len(net._cached_graph) == 2


def test_zero_grad_and_grad_req():
    p = gluon.Parameter("w_weight", shape=(2,))
    p.initialize()
    x = p.data()
    with autograd.record():
        (x * 2).sum().backward()
    assert p.grad().asnumpy().sum() != 0
    p.zero_grad()
    assert p.grad().asnumpy().sum() == 0
    p.grad_req = "null"
    with pytest.raises(RuntimeError):
        p.grad()


def test_lr_mult_freezes_param():
    """Review regression: Parameter.lr_mult must reach the optimizer."""
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize(mx.init.One())
    net.weight.lr_mult = 0.0
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1.0})
    with autograd.record():
        net(mx.nd.ones((1, 2))).backward()
    trainer.step(1)
    assert_almost_equal(net.weight.data().asnumpy(),
                        np.ones((1, 2), dtype="float32"))


def test_ctc_loss_lengths_change_result():
    """Review regression: pred_lengths must affect the CTC loss value."""
    np.random.seed(3)
    pred = mx.nd.array(np.random.randn(2, 20, 5).astype("float32"))  # NTC
    label = mx.nd.array(np.array([[1, 2, -1, -1], [2, 3, -1, -1]],
                                 dtype="float32"))  # -1 pad (blank='last')
    L = gluon.loss.CTCLoss()
    full = L(pred, label).asnumpy()
    lens = mx.nd.array(np.array([10, 20], dtype="float32"))
    lab_lens = mx.nd.array(np.array([2, 2], dtype="float32"))
    short = L(pred, label, lens, lab_lens).asnumpy()
    assert not np.allclose(full[0], short[0])  # sample 0 truncated at t=10
    assert np.allclose(full[1], short[1], rtol=1e-4)  # sample 1 full length


def test_trainer_stale_grad_detection():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    with pytest.raises(UserWarning):
        trainer.step(1)  # no backward ran
    trainer.step(1, ignore_stale_grad=True)  # suppressed


def test_export_roundtrip_via_load_parameters(tmp_path):
    """Review regression: load_parameters on an export()-style file must not
    double-prefix names."""
    def build():
        net = nn.HybridSequential(prefix="model_")
        with net.name_scope():
            net.add(nn.Dense(3))
        net.initialize()
        return net

    net = build()
    x = mx.nd.ones((1, 2))
    y0 = net(x).asnumpy()
    fname = str(tmp_path / "full.params")
    net.collect_params().save(fname)  # fully-prefixed names
    net2 = build()
    net2(x)
    net2.collect_params().load(fname, restore_prefix="")
    # and through Block.load_parameters (auto-detect unstripped prefix)
    net3 = build()
    net3(x)
    net3.load_parameters(fname)
    assert_almost_equal(net3(x).asnumpy(), y0)


def test_hybrid_forward_contrib_namespace():
    """F.contrib.* must resolve inside hybrid_forward under BOTH eager and
    hybridized execution (reference hybrid blocks use F.contrib ops)."""
    import numpy as np

    class Net(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            # CamelCase contrib op and a snake_case one
            y = F.contrib.div_sqrt_dim(x)
            q = F.expand_dims(x, axis=1)            # (N, 1, T, D)
            att = F.contrib.FlashAttention(q, q, q, causal=True)
            return y + F.reshape(att, shape=(-3, 0, 0))

    net = Net()
    net.initialize()
    x = mx.nd.random.normal(shape=(2, 4, 9))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-6)


def test_hybridize_compute_dtype_policy_bf16():
    """Session dtype policy (MXNET_COMPUTE_DTYPE=bfloat16) on the CachedOp
    path: compute runs bf16 off a single grouped downcast, BatchNorm
    params/stats are excluded (stay f32), and outputs track the f32 run."""
    from mxnet_tpu import config

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8), nn.BatchNorm(), nn.Activation("relu"),
                nn.Dense(3))
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0).randn(4, 5).astype("f4"))
    y32 = net(x).asnumpy()
    with config.override(compute_dtype="bfloat16"):
        ybf = net(x)
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
    assert ybf.dtype == np.dtype("bfloat16").type or \
        str(ybf.asnumpy().dtype) == "bfloat16"
    assert_almost_equal(ybf.asnumpy().astype("f4"), y32, rtol=0.05,
                        atol=0.05)
    for name, p in net.collect_params().items():
        assert p.data().dtype == np.float32, name  # masters untouched
        if p.grad_req != "null":
            assert np.isfinite(p.grad().asnumpy().astype("f4")).all(), name
    # BatchNorm keeps f32 params/stats even under an explicit low-p cast
    bn = [b for b in net._children.values()
          if isinstance(b, nn.BatchNorm)][0]
    bn.cast("bfloat16")
    assert bn.gamma.dtype == np.float32
    assert bn.running_mean.dtype == np.float32
