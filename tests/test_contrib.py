"""contrib package tests: text, svrg_optimization, io, autograd, tensorboard
(parity models: tests/python/unittest/test_contrib_text.py,
test_contrib_svrg_module.py / test_contrib_svrg_optimizer.py)."""
import collections

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import text as ctext
from mxnet_tpu.contrib.svrg_optimization import (SVRGModule, _SVRGOptimizer,
                                                 _AssignmentOptimizer)


# ---------------------------------------------------------------- text
def _counter():
    return ctext.utils.count_tokens_from_str(
        "life is great ! \n life is good . \n", to_lower=False)


def test_count_tokens_from_str():
    c = ctext.utils.count_tokens_from_str(
        " Life is great ! \n life is good . \n", to_lower=True)
    assert c == collections.Counter(
        {"life": 2, "is": 2, "great": 1, "good": 1, "!": 1, ".": 1})
    c2 = ctext.utils.count_tokens_from_str(
        "*Life*is*great*!*\n*life*is*good*.*\n", token_delim=r"\*",
        to_lower=True)
    assert c2["life"] == 2


def test_vocabulary_indexing():
    v = ctext.Vocabulary(_counter(), most_freq_count=None, min_freq=1,
                         unknown_token="<unk>", reserved_tokens=["<pad>"])
    assert v.token_to_idx["<unk>"] == 0
    assert v.token_to_idx["<pad>"] == 1
    # most frequent first: 'life'/'is' (freq 2) before freq-1 tokens
    assert v.to_indices("is") in (2, 3) and v.to_indices("life") in (2, 3)
    assert v.to_indices("nonexistent") == 0
    assert v.to_tokens(0) == "<unk>"
    assert v.to_tokens(v.to_indices(["great", "good"])) == ["great", "good"]
    with pytest.raises(ValueError):
        v.to_tokens(len(v))
    # thresholds
    v2 = ctext.Vocabulary(_counter(), min_freq=2)
    assert len(v2) == 3  # unk + life + is
    v3 = ctext.Vocabulary(_counter(), most_freq_count=2)
    assert len(v3) == 3


def _write_embedding(path):
    with open(path, "w") as f:
        f.write("a 0.1 0.2 0.3\n")
        f.write("b 1.0 2.0 3.0\n")
        f.write("c -1.0 -2.0 -3.0\n")


def test_custom_embedding(tmp_path):
    p = str(tmp_path / "emb.txt")
    _write_embedding(p)
    emb = ctext.embedding.CustomEmbedding(p)
    assert emb.vec_len == 3
    assert emb.idx_to_vec.shape == (4, 3)  # unk + 3 tokens
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("b").asnumpy(), [1.0, 2.0, 3.0])
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens(["zzz"]).asnumpy(), [[0, 0, 0]])
    # lower_case_backup
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens(["B"], lower_case_backup=True).asnumpy(),
        [[1.0, 2.0, 3.0]])
    emb.update_token_vectors("a", mx.nd.array([[9.0, 9.0, 9.0]]))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("a").asnumpy(), [9.0, 9.0, 9.0])
    with pytest.raises(ValueError):
        emb.update_token_vectors("zzz", mx.nd.array([[1.0, 1.0, 1.0]]))


def test_embedding_with_vocabulary_and_composite(tmp_path):
    p = str(tmp_path / "emb.txt")
    _write_embedding(p)
    counter = collections.Counter(["a", "a", "c", "d"])
    vocab = ctext.Vocabulary(counter)
    emb = ctext.embedding.CustomEmbedding(p, vocabulary=vocab)
    assert len(emb.idx_to_token) == len(vocab)
    # token 'd' not in the file -> unknown vector (zeros)
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("d").asnumpy(), [0, 0, 0])
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("c").asnumpy(), [-1.0, -2.0, -3.0])

    comp = ctext.embedding.CompositeEmbedding(
        vocab, [ctext.embedding.CustomEmbedding(p),
                ctext.embedding.CustomEmbedding(p)])
    assert comp.vec_len == 6
    np.testing.assert_allclose(
        comp.get_vecs_by_tokens("c").asnumpy(),
        [-1.0, -2.0, -3.0, -1.0, -2.0, -3.0])


def test_embedding_registry():
    assert "glove" in ctext.embedding.get_pretrained_file_names()
    assert any("840B" in n for n in
               ctext.embedding.get_pretrained_file_names("glove"))
    with pytest.raises(Exception):
        # zero-egress environment: missing local file must raise, not hang
        ctext.embedding.create("glove",
                               pretrained_file_name="glove.6B.50d.txt")


# ---------------------------------------------------------------- svrg
def _lin_data(n=128, d=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d, 1).astype(np.float32)
    y = (X @ w).ravel() + 0.01 * rng.randn(n).astype(np.float32)
    return X, y


def _lin_sym():
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=1, name="fc")
    return mx.sym.LinearRegressionOutput(out, name="lro")


def test_svrg_module_fit_decreases_loss():
    X, y = _lin_data()
    it = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True,
                           label_name="lro_label")
    mod = SVRGModule(_lin_sym(), label_names=("lro_label",), update_freq=2)
    losses = []

    def cb(param):
        losses.append(param.eval_metric.get()[1])

    mod.fit(it, num_epoch=8, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05}, eval_metric="mse",
            batch_end_callback=cb)
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])


def test_svrg_grad_equals_full_grad_at_snapshot():
    """Right after the snapshot, w == w~ so g(w) - g(w~) + g~ == g~."""
    X, y = _lin_data(64)
    it = mx.io.NDArrayIter(X, y, batch_size=64, label_name="lro_label")
    mod = SVRGModule(_lin_sym(), label_names=("lro_label",), update_freq=1)
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.initializer.Uniform(0.1))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.0})
    mod.update_full_grads(it)
    full = {k: v.asnumpy().copy() for k, v in mod._param_dict.items()}
    it.reset()
    batch = next(it)
    mod.forward_backward(batch)
    mod._update_svrg_gradients()
    for name in mod._param_names:
        g = mod._exec.grad_dict.get(name)
        if g is None:
            continue
        np.testing.assert_allclose(g.asnumpy(), full[name],
                                   rtol=1e-4, atol=1e-5)


def test_svrg_optimizer_routing():
    opt = _SVRGOptimizer(default_optimizer="sgd", learning_rate=1.0,
                         rescale_grad=1.0)
    w = mx.nd.array([1.0, 1.0])
    g = mx.nd.array([0.5, 0.5])
    st = opt.create_state("fc_weight_full", w)
    opt.update("fc_weight_full", w, g, st)
    np.testing.assert_allclose(w.asnumpy(), [0.5, 0.5])  # assigned
    w2 = mx.nd.array([1.0, 1.0])
    st2 = opt.create_state("fc_weight", w2)
    opt.update("fc_weight", w2, g, st2)
    np.testing.assert_allclose(w2.asnumpy(), [0.5, 0.5])  # sgd lr=1: w - g
    assert isinstance(opt.aux_opt, _AssignmentOptimizer)


def test_svrg_update_freq_validation():
    with pytest.raises(ValueError):
        SVRGModule(_lin_sym(), update_freq=0)


# ---------------------------------------------------------------- io
def test_dataloader_iter_with_module():
    from mxnet_tpu.contrib.io import DataLoaderIter
    X, y = _lin_data(70)
    ds = mx.gluon.data.ArrayDataset(X, y)
    loader = mx.gluon.data.DataLoader(ds, batch_size=16)
    it = DataLoaderIter(loader, label_name="lro_label")
    assert it.batch_size == 16
    batches = list(it)
    assert len(batches) == 5  # 4 full + 1 padded
    assert batches[-1].pad == 16 - 70 % 16
    assert batches[-1].data[0].shape == (16, 4)
    it.reset()
    mod = mx.mod.Module(_lin_sym(), label_names=("lro_label",))
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.01}, eval_metric="mse")


# ---------------------------------------------------------------- autograd
def test_contrib_autograd_old_api():
    from mxnet_tpu.contrib import autograd as old_ag
    x = mx.nd.array([1.0, 2.0, 3.0])

    def loss_fn(x):
        return (x * x).sum()

    g_and_l = old_ag.grad_and_loss(loss_fn)
    grads, loss = g_and_l(x)
    np.testing.assert_allclose(grads[0].asnumpy(), [2.0, 4.0, 6.0])
    np.testing.assert_allclose(loss.asnumpy(), 14.0)

    g_fn = old_ag.grad(loss_fn)
    np.testing.assert_allclose(g_fn(x)[0].asnumpy(), [2.0, 4.0, 6.0])

    # train/test sections and compute_gradient
    y = mx.nd.array([2.0, -1.0])
    gy = mx.nd.zeros_like(y)
    old_ag.mark_variables([y], [gy])
    with old_ag.train_section():
        z = (y * y * y).sum()
    old_ag.compute_gradient([z])
    np.testing.assert_allclose(gy.asnumpy(), [12.0, 3.0])


# ---------------------------------------------------------------- tensorboard
def test_tensorboard_callback_graceful():
    from mxnet_tpu.contrib.tensorboard import LogMetricsCallback
    cb = LogMetricsCallback("/tmp/tb_test_logs")
    metric = mx.metric.create("acc")
    metric.update([mx.nd.array([1.0, 0.0])],
                  [mx.nd.array([[0.1, 0.9], [0.8, 0.2]])])
    param = mx.model.BatchEndParam(epoch=0, nbatch=1, eval_metric=metric,
                                   locals=None)
    cb(param)  # must not raise whether or not a writer backend exists


def test_custom_embedding_with_reserved_tokens(tmp_path):
    """Reserved tokens must own matrix rows: indices and vectors stay
    aligned (regression: rows shifted when reserved_tokens was passed)."""
    p = str(tmp_path / "emb.txt")
    _write_embedding(p)
    emb = ctext.embedding.CustomEmbedding(p, reserved_tokens=["<pad>"])
    assert emb.idx_to_vec.shape[0] == len(emb.idx_to_token) == 5
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("a").asnumpy(), [0.1, 0.2, 0.3])
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("c").asnumpy(), [-1.0, -2.0, -3.0])
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("<pad>").asnumpy(), [0, 0, 0])


def test_tensorboard_steps_monotone(tmp_path):
    from mxnet_tpu.contrib.tensorboard import LogMetricsCallback
    cb = LogMetricsCallback(str(tmp_path / "tb"))
    calls = []

    class FakeWriter:
        def add_scalar(self, name, value, global_step=None):
            calls.append(global_step)

    cb.summary_writer = FakeWriter()
    metric = mx.metric.create("acc")
    metric.update([mx.nd.array([1.0])], [mx.nd.array([[0.1, 0.9]])])
    for i in range(3):
        cb(mx.model.BatchEndParam(epoch=0, nbatch=i, eval_metric=metric,
                                  locals=None))
    assert calls == sorted(set(calls)), calls  # strictly increasing
