"""Symbol + Executor tests (parity model: tests/python/unittest/
test_symbol.py + test_executor.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.base import MXNetError


def test_compose_and_arguments():
    x = sym.Variable("data")
    fc = sym.FullyConnected(x, num_hidden=4, name="fc")
    act = sym.Activation(fc, act_type="relu")
    assert act.list_arguments() == ["data", "fc_weight", "fc_bias"]
    outs = act.list_outputs()
    assert len(outs) == 1 and outs[0].startswith("activation_") \
        and outs[0].endswith("_output")


def test_auto_variable_creation():
    net = sym.Convolution(data=sym.Variable("data"), kernel=(3, 3),
                          num_filter=4, name="c")
    assert net.list_arguments() == ["data", "c_weight", "c_bias"]
    net2 = sym.Convolution(data=sym.Variable("data"), kernel=(3, 3),
                           num_filter=4, no_bias=True, name="c2")
    assert net2.list_arguments() == ["data", "c2_weight"]
    loss = sym.SoftmaxOutput(net, name="softmax")
    assert "softmax_label" in loss.list_arguments()


def test_infer_shape_with_weight_inference():
    x = sym.Variable("data")
    net = sym.FullyConnected(x, num_hidden=7, name="fc")
    arg_shapes, out_shapes, _ = net.infer_shape(data=(5, 3))
    assert arg_shapes == [(5, 3), (7, 3), (7,)]
    assert out_shapes == [(5, 7)]


def test_infer_shape_partial():
    x = sym.Variable("a") + sym.Variable("b")
    arg_shapes, out_shapes, _ = x.infer_shape_partial(a=(2, 2))
    assert arg_shapes[0] == (2, 2)


def test_symbol_arithmetic_and_getitem():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = (a + b) * 2 - a / b
    ex = c.bind(mx.cpu(), {"a": mx.nd.array([4.0]), "b": mx.nd.array([2.0])})
    out = ex.forward()[0]
    assert out.asscalar() == pytest.approx((4 + 2) * 2 - 2.0)


def test_group_and_slicing():
    a = sym.Variable("a")
    s1 = a * 2
    s2 = a + 1
    g = sym.Group([s1, s2])
    assert g.num_outputs == 2
    ex = g.bind(mx.cpu(), {"a": mx.nd.array([3.0])})
    o1, o2 = ex.forward()
    assert o1.asscalar() == 6.0 and o2.asscalar() == 4.0
    first = g[0]
    assert first.num_outputs == 1


def test_get_internals():
    x = sym.Variable("data")
    fc = sym.FullyConnected(x, num_hidden=4, name="fc")
    act = sym.Activation(fc, act_type="relu", name="act")
    internals = act.get_internals()
    names = internals.list_outputs()
    assert any("fc" in n for n in names)


def test_json_roundtrip_with_exec():
    x = sym.Variable("data")
    net = sym.FullyConnected(x, num_hidden=3, name="fc")
    net = sym.Activation(net, act_type="tanh")
    js = net.tojson()
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    args = {"data": mx.nd.ones((2, 4)),
            "fc_weight": mx.nd.ones((3, 4)),
            "fc_bias": mx.nd.zeros((3,))}
    o1 = net.bind(mx.cpu(), dict(args)).forward()[0]
    o2 = net2.bind(mx.cpu(), dict(args)).forward()[0]
    np.testing.assert_allclose(o1.asnumpy(), o2.asnumpy())


def test_executor_backward_matches_eager():
    x = sym.Variable("x")
    y = sym.Variable("y")
    z = sym.broadcast_mul(sym.sin(x), y) + sym.square(x)
    xv = np.random.randn(3, 2).astype(np.float32)
    yv = np.random.randn(3, 2).astype(np.float32)
    args = {"x": mx.nd.array(xv), "y": mx.nd.array(yv)}
    grads = {"x": mx.nd.zeros((3, 2)), "y": mx.nd.zeros((3, 2))}
    ex = z.bind(mx.cpu(), args, args_grad=grads)
    ex.forward(is_train=True)
    ex.backward()
    np.testing.assert_allclose(grads["x"].asnumpy(),
                               np.cos(xv) * yv + 2 * xv, rtol=1e-5)
    np.testing.assert_allclose(grads["y"].asnumpy(), np.sin(xv), rtol=1e-5)


def test_executor_explicit_out_grads():
    x = sym.Variable("x")
    z = x * 3.0
    args = {"x": mx.nd.array([1.0, 2.0])}
    grads = {"x": mx.nd.zeros((2,))}
    ex = z.bind(mx.cpu(), args, args_grad=grads)
    ex.forward(is_train=True)
    ex.backward(mx.nd.array([10.0, 100.0]))
    np.testing.assert_allclose(grads["x"].asnumpy(), [30.0, 300.0])


def test_grad_req_add_and_null():
    x = sym.Variable("x")
    y = sym.Variable("y")
    z = x * y
    args = {"x": mx.nd.array([2.0]), "y": mx.nd.array([3.0])}
    grads = {"x": mx.nd.zeros((1,))}
    ex = z.bind(mx.cpu(), args, args_grad=grads,
                grad_req={"x": "add", "y": "null"})
    for _ in range(2):
        ex.forward(is_train=True)
        ex.backward()
    np.testing.assert_allclose(grads["x"].asnumpy(), [6.0])


def test_batchnorm_aux_states():
    d = sym.Variable("data")
    bn = sym.BatchNorm(d, fix_gamma=False, momentum=0.5, name="bn")
    assert bn.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]
    ex = bn.simple_bind(mx.cpu(), data=(8, 3))
    ex.arg_dict["data"][:] = mx.nd.array(
        np.random.randn(8, 3).astype(np.float32) * 2 + 1)
    ex.arg_dict["bn_gamma"][:] = 1.0
    before = ex.aux_dict["bn_moving_mean"].asnumpy().copy()
    ex.forward(is_train=True)
    after = ex.aux_dict["bn_moving_mean"].asnumpy()
    assert not np.allclose(before, after)
    # predict mode does not touch aux
    ex.forward(is_train=False)
    np.testing.assert_allclose(ex.aux_dict["bn_moving_mean"].asnumpy(), after)


def test_dropout_in_graph_fresh_randomness():
    d = sym.Variable("data")
    net = sym.Dropout(d, p=0.5)
    ex = net.bind(mx.cpu(), {"data": mx.nd.ones((100,))})
    a = ex.forward(is_train=True)[0].asnumpy()
    b = ex.forward(is_train=True)[0].asnumpy()
    assert not np.allclose(a, b), "dropout mask must differ across runs"
    c = ex.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(c, np.ones(100))


def test_simple_bind_shape_error():
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=4)
    with pytest.raises(MXNetError):
        net.infer_shape()  # no shapes given


def test_reshape_executor():
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=4, name="fc")
    ex = net.simple_bind(mx.cpu(), data=(2, 8))
    ex2 = ex.reshape(data=(5, 8))
    assert ex2.arg_dict["data"].shape == (5, 8)
    assert ex2.arg_dict["fc_weight"].shape == (4, 8)


def test_name_manager_prefix():
    """mx.name.Prefix scopes auto-generated symbol names
    (reference name.py:93)."""
    with mx.name.Prefix("stage1_"):
        a = sym.FullyConnected(sym.Variable("data"), num_hidden=4)
    assert a.name.startswith("stage1_fullyconnected"), a.name
    # explicit names get the prefix too (reference Prefix.get prepends
    # after passing the user name through)
    with mx.name.Prefix("x_"):
        b = sym.FullyConnected(sym.Variable("data"), num_hidden=4,
                               name="mine")
    assert b.name == "x_mine"
    # variables keep their explicit names (no NameManager in Variable)
    with mx.name.Prefix("y_"):
        v = sym.Variable("data2")
    assert v.name == "data2"


def test_attr_scope():
    """mx.AttrScope attaches attrs to symbols created in scope
    (reference attribute.py:27), nesting and user override included."""
    with mx.AttrScope(lr_mult="0.1"):
        v = sym.Variable("w")
        with mx.AttrScope(wd_mult="0"):
            n = sym.FullyConnected(sym.Variable("data"), num_hidden=2,
                                   name="fc_scoped")
    assert v.attr("lr_mult") == "0.1"
    attrs = n.attr_dict()["fc_scoped"]
    assert attrs["lr_mult"] == "0.1" and attrs["wd_mult"] == "0"
    # user attr wins over scope
    with mx.AttrScope(lr_mult="0.5"):
        u = sym.Variable("u", attr={"lr_mult": "2.0"})
    assert u.attr("lr_mult") == "2.0"
    # scope ends cleanly
    w2 = sym.Variable("w2")
    assert w2.attr("lr_mult") is None


def test_backward_do_mirror_grad_parity_and_remat():
    """MXNET_BACKWARD_DO_MIRROR (reference graph_executor.cc:260-283):
    jax.checkpoint wraps the differentiated graph — gradients must be
    numerically identical, and the backward jaxpr must carry a remat."""
    from mxnet_tpu import config
    from mxnet_tpu.executor import mirror_wrap
    import jax
    import jax.numpy as jnp

    x = sym.Variable("data")
    net = sym.FullyConnected(x, num_hidden=8, name="fc1")
    net = sym.Activation(net, act_type="tanh")
    net = sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = sym.SoftmaxOutput(net, name="softmax")

    rng = np.random.RandomState(0)
    feed = {"data": mx.nd.array(rng.randn(4, 6).astype("float32")),
            "softmax_label": mx.nd.array(
                rng.randint(0, 2, (4,)).astype("float32"))}

    def grads_with(flag):
        with config.override(backward_do_mirror=flag):
            ex = net.simple_bind(mx.cpu(), data=(4, 6))
            ex.forward(is_train=True, **feed)
            ex.backward()
            return {k: v.asnumpy() for k, v in ex.grad_dict.items()
                    if v is not None}

    g0 = grads_with(False)
    g1 = grads_with(True)
    assert set(g0) == set(g1)
    for k in g0:
        np.testing.assert_allclose(g0[k], g1[k], rtol=1e-6, atol=1e-7,
                                   err_msg=k)

    # the wrap really remats (and refuses unknown policies loudly)
    def f(d):
        return jnp.tanh(d["w"]).sum()

    with config.override(backward_do_mirror=True):
        jaxpr = str(jax.make_jaxpr(jax.grad(mirror_wrap(f)))(
            {"w": jnp.ones((3,))}))
        assert "remat" in jaxpr or "checkpoint" in jaxpr
    with config.override(backward_do_mirror=True, mirror_policy="no_such"):
        with pytest.raises(ValueError, match="MXNET_MIRROR_POLICY"):
            mirror_wrap(f)
