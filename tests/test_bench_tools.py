"""The two measurement tools behind BASELINE.json's secondary metrics
(VERDICT r3 #7): kvstore push/pull µs and Gluon LSTM tokens/sec.

Smoke-sized here (tiny shapes, 2 reps); bench.py attaches the real-shape
numbers to the round's JSON line.
"""
import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_bandwidth_probe():
    from tools.bandwidth import measure
    r = measure("local", size_mb=0.1, reps=2)
    assert r["metric"] == "kvstore_push_pull_us"
    assert r["value"] > 0 and r["gbit_per_s"] > 0


def test_bandwidth_probe_compressed():
    from tools.bandwidth import measure
    r = measure("local", size_mb=0.1, reps=2, compression="2bit")
    assert r["compression"] == "2bit" and r["value"] > 0


def test_bandwidth_probe_multi_device_reduce():
    from tools.bandwidth import measure
    r = measure("local", size_mb=0.05, reps=2, ndev=4)
    assert r["ndev"] == 4 and r["value"] > 0


def test_lstm_tokens_per_sec():
    from tools.bench_lstm import measure
    r = measure(batch=4, seq_len=8, hidden=16, vocab=50, layers=1, steps=2)
    assert r["metric"] == "gluon_lstm_tokens_per_sec"
    assert r["value"] > 0 and r["step_ms"] > 0
