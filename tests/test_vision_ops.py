"""Vision ops vs brute-force references: Correlation, Crop v1,
DeformableConvolution, Proposal, SyncBatchNorm (reference
src/operator/correlation.cc, crop.cc, contrib/)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _np_correlation(d1, d2, k, md, s1, s2, pad, is_multiply):
    """Direct transcription of the reference loop nest
    (correlation.cc:33-82)."""
    n, c, h, w = d1.shape
    t1 = np.pad(d1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    t2 = np.pad(d2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    hp, wp = h + 2 * pad, w + 2 * pad
    kr = (k - 1) // 2
    border = md + kr
    top_h = int(np.ceil((hp - 2 * border) / s1))
    top_w = int(np.ceil((wp - 2 * border) / s1))
    ngr = md // s2
    ngw = 2 * ngr + 1
    out = np.zeros((n, ngw * ngw, top_h, top_w), np.float32)
    sumelems = k * k * c
    for b in range(n):
        for i in range(top_h):
            for j in range(top_w):
                y1, x1 = i * s1 + md, j * s1 + md
                for tc in range(ngw * ngw):
                    s2o = (tc % ngw - ngr) * s2
                    s2p = (tc // ngw - ngr) * s2
                    y2, x2 = y1 + s2p, x1 + s2o
                    p1 = t1[b, :, y1:y1 + k, x1:x1 + k]
                    p2 = t2[b, :, y2:y2 + k, x2:x2 + k]
                    v = (p1 * p2).sum() if is_multiply else \
                        np.abs(p1 - p2).sum()
                    out[b, tc, i, j] = v / sumelems
    return out


@pytest.mark.parametrize("k,md,s1,s2,pad,mult", [
    (1, 1, 1, 1, 1, True),
    (3, 2, 1, 2, 2, True),
    (1, 2, 2, 1, 2, False),
])
def test_correlation_matches_reference_loop(k, md, s1, s2, pad, mult):
    rng = np.random.RandomState(0)
    d1 = rng.randn(2, 3, 8, 9).astype(np.float32)
    d2 = rng.randn(2, 3, 8, 9).astype(np.float32)
    out = mx.nd.Correlation(mx.nd.array(d1), mx.nd.array(d2),
                            kernel_size=k, max_displacement=md,
                            stride1=s1, stride2=s2, pad_size=pad,
                            is_multiply=mult)
    expected = _np_correlation(d1, d2, k, md, s1, s2, pad, mult)
    assert out.shape == expected.shape
    np.testing.assert_allclose(out.asnumpy(), expected, rtol=1e-4,
                               atol=1e-5)


def test_correlation_grads():
    rng = np.random.RandomState(1)
    a = mx.nd.array(rng.randn(1, 2, 6, 6).astype(np.float32))
    b = mx.nd.array(rng.randn(1, 2, 6, 6).astype(np.float32))
    a.attach_grad(); b.attach_grad()
    with mx.autograd.record():
        y = mx.nd.Correlation(a, b, kernel_size=1, max_displacement=1,
                              pad_size=1)
        loss = y.sum()
    loss.backward()
    assert np.abs(a.grad.asnumpy()).sum() > 0
    assert np.abs(b.grad.asnumpy()).sum() > 0


def test_crop_v1():
    x = mx.nd.array(np.arange(2 * 3 * 6 * 8, dtype=np.float32)
                    .reshape(2, 3, 6, 8))
    y = mx.nd.Crop(x, h_w=(4, 5), offset=(1, 2))
    np.testing.assert_array_equal(y.asnumpy(),
                                  x.asnumpy()[:, :, 1:5, 2:7])
    ref = mx.nd.zeros((2, 3, 4, 4))
    y2 = mx.nd.Crop(x, ref, center_crop=True, num_args=2)
    np.testing.assert_array_equal(y2.asnumpy(),
                                  x.asnumpy()[:, :, 1:5, 2:6])
    # symbolic
    d = mx.sym.Variable("data")
    s = mx.sym.Crop(d, h_w=(4, 5), offset=(1, 2))
    _, outs, _ = s.infer_shape(data=(2, 3, 6, 8))
    assert outs[0] == (2, 3, 4, 5)


def test_deformable_conv_zero_offset_equals_conv():
    """With zero offsets, DeformableConvolution == Convolution."""
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(2, 4, 7, 7).astype(np.float32))
    wgt = mx.nd.array(rng.randn(6, 4, 3, 3).astype(np.float32))
    bias = mx.nd.array(rng.randn(6).astype(np.float32))
    off = mx.nd.zeros((2, 2 * 3 * 3, 7, 7))
    y = mx.nd._contrib_DeformableConvolution(
        x, off, wgt, bias, kernel=(3, 3), num_filter=6, pad=(1, 1))
    ref = mx.nd.Convolution(x, wgt, bias, kernel=(3, 3), num_filter=6,
                            pad=(1, 1))
    np.testing.assert_allclose(y.asnumpy(), ref.asnumpy(), rtol=1e-4,
                               atol=1e-4)


def test_deformable_conv_integer_offset_shifts():
    """A constant integer offset samples a shifted feature map."""
    rng = np.random.RandomState(1)
    x = rng.randn(1, 2, 9, 9).astype(np.float32)
    wgt = rng.randn(3, 2, 1, 1).astype(np.float32)
    off = np.zeros((1, 2, 9, 9), np.float32)
    off[:, 0] = 1.0  # dy = +1 everywhere
    y = mx.nd._contrib_DeformableConvolution(
        mx.nd.array(x), mx.nd.array(off), mx.nd.array(wgt),
        kernel=(1, 1), num_filter=3, no_bias=True)
    shifted = np.zeros_like(x)
    shifted[:, :, :-1] = x[:, :, 1:]  # sample at y+1
    ref = np.einsum("fc,nchw->nfhw", wgt[:, :, 0, 0], shifted)
    np.testing.assert_allclose(y.asnumpy(), ref, rtol=1e-4, atol=1e-5)


def test_deformable_conv_grads_flow_to_offset():
    rng = np.random.RandomState(2)
    x = mx.nd.array(rng.randn(1, 2, 6, 6).astype(np.float32))
    wgt = mx.nd.array(rng.randn(2, 2, 3, 3).astype(np.float32))
    off = mx.nd.array(0.3 * rng.randn(1, 18, 6, 6).astype(np.float32))
    off.attach_grad()
    with mx.autograd.record():
        y = mx.nd._contrib_DeformableConvolution(
            x, off, wgt, kernel=(3, 3), num_filter=2, pad=(1, 1),
            no_bias=True)
        loss = (y * y).sum()
    loss.backward()
    assert np.abs(off.grad.asnumpy()).sum() > 0


def test_proposal_shapes_and_sanity():
    rng = np.random.RandomState(0)
    n, fh, fw = 1, 6, 8
    A = 3 * 3  # 3 scales x 3 ratios
    cls = mx.nd.array(rng.rand(n, 2 * A, fh, fw).astype(np.float32))
    bbox = mx.nd.array(0.1 * rng.randn(n, 4 * A, fh, fw).astype(np.float32))
    im_info = mx.nd.array(np.array([[fh * 16, fw * 16, 1.0]], np.float32))
    rois = mx.nd._contrib_Proposal(
        cls, bbox, im_info, rpn_pre_nms_top_n=60, rpn_post_nms_top_n=20,
        threshold=0.7, rpn_min_size=4, scales=(4, 8, 16),
        ratios=(0.5, 1, 2), feature_stride=16)
    assert rois.shape == (20, 5)
    r = rois.asnumpy()
    valid = r[r[:, 1] >= 0]
    assert len(valid) > 0
    # batch index 0, boxes inside the image, x2>=x1, y2>=y1
    assert (valid[:, 0] == 0).all()
    assert (valid[:, 1] >= 0).all() and (valid[:, 3] <= fw * 16 - 1).all()
    assert (valid[:, 3] >= valid[:, 1]).all()
    assert (valid[:, 4] >= valid[:, 2]).all()
    # output_score variant
    rois2, scores = mx.nd._contrib_Proposal(
        cls, bbox, im_info, rpn_pre_nms_top_n=60, rpn_post_nms_top_n=20,
        scales=(4, 8, 16), ratios=(0.5, 1, 2), output_score=True)
    assert scores.shape == (20, 1)


def test_sync_batch_norm_matches_bn():
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(4, 3, 5, 5).astype(np.float32))
    gamma, beta = mx.nd.ones((3,)), mx.nd.zeros((3,))
    mmean, mvar = mx.nd.zeros((3,)), mx.nd.ones((3,))
    with mx.autograd.record():  # training mode uses batch stats
        y1 = mx.nd._contrib_SyncBatchNorm(x, gamma, beta, mmean.copy(),
                                          mvar.copy(), ndev=8, key="bn0")
        y2 = mx.nd.BatchNorm(x, gamma, beta, mmean.copy(), mvar.copy())
    np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(), rtol=1e-5)


def test_proposal_pads_when_anchors_fewer_than_post_nms():
    """Anchor count < rpn_post_nms_top_n must still emit the fixed-shape
    output with -1 padding (reference proposal.cc pads unconditionally)."""
    rng = np.random.RandomState(3)
    n, fh, fw = 1, 4, 4
    A = 3 * 3
    cls = mx.nd.array(rng.rand(n, 2 * A, fh, fw).astype(np.float32))
    bbox = mx.nd.array(0.1 * rng.randn(n, 4 * A, fh, fw).astype(np.float32))
    im_info = mx.nd.array(np.array([[fh * 16, fw * 16, 1.0]], np.float32))
    rois = mx.nd._contrib_Proposal(
        cls, bbox, im_info, rpn_pre_nms_top_n=6000, rpn_post_nms_top_n=300,
        threshold=0.7, rpn_min_size=4, scales=(4, 8, 16),
        ratios=(0.5, 1, 2), feature_stride=16)
    assert rois.shape == (300, 5)  # 144 anchors -> padded to 300
    r = rois.asnumpy()
    assert (r[:, 1] >= 0).sum() <= 144
    assert (r[-1] == -1).any()  # tail rows are -1 padding


def _psroi_brute(data, rois, spatial_scale, output_dim, pooled_size,
                 group_size):
    """Direct port of the reference loop nest (psroi_pooling.cc:43-112)."""
    import math
    n_rois = rois.shape[0]
    _, channels, height, width = data.shape
    out = np.zeros((n_rois, output_dim, pooled_size, pooled_size),
                   np.float32)
    for n in range(n_rois):
        b = int(rois[n, 0])
        sw = round(rois[n, 1]) * spatial_scale
        sh = round(rois[n, 2]) * spatial_scale
        ew = (round(rois[n, 3]) + 1.0) * spatial_scale
        eh = (round(rois[n, 4]) + 1.0) * spatial_scale
        rw = max(ew - sw, 0.1)
        rh = max(eh - sh, 0.1)
        bh, bw = rh / pooled_size, rw / pooled_size
        for ctop in range(output_dim):
            for ph in range(pooled_size):
                for pw in range(pooled_size):
                    hstart = min(max(int(math.floor(ph * bh + sh)), 0), height)
                    hend = min(max(int(math.ceil((ph + 1) * bh + sh)), 0), height)
                    wstart = min(max(int(math.floor(pw * bw + sw)), 0), width)
                    wend = min(max(int(math.ceil((pw + 1) * bw + sw)), 0), width)
                    gh = min(max(ph * group_size // pooled_size, 0), group_size - 1)
                    gw = min(max(pw * group_size // pooled_size, 0), group_size - 1)
                    c = (ctop * group_size + gh) * group_size + gw
                    patch = data[b, c, hstart:hend, wstart:wend]
                    area = (hend - hstart) * (wend - wstart)
                    out[n, ctop, ph, pw] = 0.0 if area <= 0 \
                        else patch.sum() / area
    return out


def test_psroi_pooling_matches_brute_force():
    rng = np.random.RandomState(0)
    D, G = 3, 3
    data = rng.randn(2, D * G * G, 14, 14).astype(np.float32)
    rois = np.array([[0, 1, 1, 9, 11], [1, 0, 2, 12, 13],
                     [0, 3, 3, 6, 6]], np.float32)
    out = mx.nd._contrib_PSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), spatial_scale=1.0,
        output_dim=D, pooled_size=G, group_size=G)
    ref = _psroi_brute(data, rois, 1.0, D, G, G)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-4, atol=1e-5)


def test_deformable_psroi_no_trans_close_to_psroi():
    """no_trans deformable PSROI bilinear-samples where plain PSROI
    averages — on a linear ramp image both give the bin centroid value."""
    D, G = 2, 2
    h = w = 12
    ramp = np.arange(h * w, dtype=np.float32).reshape(h, w)
    data = np.broadcast_to(ramp, (1, D * G * G, h, w)).copy()
    rois = np.array([[0, 2, 2, 9, 9]], np.float32)
    out = mx.nd._contrib_DeformablePSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), None, spatial_scale=1.0,
        output_dim=D, group_size=G, pooled_size=G, sample_per_part=4,
        no_trans=True)
    assert out.shape == (1, D, G, G)
    v = out.asnumpy()
    # ramp: values increase with h and w; bins must be ordered
    assert v[0, 0, 0, 0] < v[0, 0, 0, 1] < v[0, 0, 1, 1]


def test_deformable_psroi_trans_shifts_sampling():
    D, G = 1, 1
    h = w = 16
    ramp = np.arange(h * w, dtype=np.float32).reshape(h, w)
    data = ramp[None, None].copy()
    rois = np.array([[0, 4, 4, 11, 11]], np.float32)
    base = mx.nd._contrib_DeformablePSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), None, spatial_scale=1.0,
        output_dim=D, group_size=G, pooled_size=G, sample_per_part=2,
        no_trans=True).asnumpy()
    # positive x-offset -> samples shift right -> larger ramp values
    trans = np.zeros((1, 2, 1, 1), np.float32)
    trans[0, 0] = 1.0  # x offset (normalized); trans_std scales it
    shifted = mx.nd._contrib_DeformablePSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), mx.nd.array(trans),
        spatial_scale=1.0, output_dim=D, group_size=G, pooled_size=G,
        sample_per_part=2, trans_std=0.2, no_trans=False).asnumpy()
    assert shifted[0, 0, 0, 0] > base[0, 0, 0, 0]


def test_quadratic_and_div_sqrt_dim():
    x = mx.nd.array(np.array([[1.0, 2.0], [3.0, -1.0]], np.float32))
    out = mx.nd._contrib_quadratic(x, a=2.0, b=1.0, c=-1.0)
    np.testing.assert_allclose(out.asnumpy(),
                               2 * x.asnumpy() ** 2 + x.asnumpy() - 1)
    d = mx.nd._contrib_div_sqrt_dim(x)
    np.testing.assert_allclose(d.asnumpy(), x.asnumpy() / np.sqrt(2),
                               rtol=1e-6)


def test_multi_proposal_is_batched_proposal():
    rng = np.random.RandomState(5)
    n, fh, fw = 2, 6, 6
    A = 9
    cls = mx.nd.array(rng.rand(n, 2 * A, fh, fw).astype(np.float32))
    bbox = mx.nd.array(0.1 * rng.randn(n, 4 * A, fh, fw).astype(np.float32))
    im_info = mx.nd.array(np.array([[96, 96, 1.0]] * n, np.float32))
    rois = mx.nd._contrib_MultiProposal(
        cls, bbox, im_info, rpn_pre_nms_top_n=60, rpn_post_nms_top_n=20,
        threshold=0.7, rpn_min_size=4, scales=(4, 8, 16),
        ratios=(0.5, 1, 2), feature_stride=16)
    assert rois.shape == (n * 20, 5)
    r = rois.asnumpy()
    valid = r[r[:, 1] >= 0]
    assert set(np.unique(valid[:, 0])) <= {0.0, 1.0}
