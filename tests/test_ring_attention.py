"""Sequence-parallel attention tests: blockwise (flash-pattern) and ring
attention over an 8-virtual-device CPU mesh (the SURVEY.md §4 stand-in for
an 8-chip ICI ring)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.parallel import (attention_reference, blockwise_attention,
                                make_mesh, make_ring_attention)


def _qkv(b=2, h=2, t=64, d=8, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(b, h, t, d).astype(np.float32)),
            jnp.asarray(rng.randn(b, h, t, d).astype(np.float32)),
            jnp.asarray(rng.randn(b, h, t, d).astype(np.float32)))


def test_blockwise_matches_dense():
    q, k, v = _qkv()
    ref = attention_reference(q, k, v)
    out = blockwise_attention(q, k, v, block_size=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_blockwise_causal_matches_dense():
    q, k, v = _qkv(t=48)
    ref = attention_reference(q, k, v, causal=True)
    out = blockwise_attention(q, k, v, block_size=16, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_blockwise_unaligned_block():
    q, k, v = _qkv(t=50)  # 50 % 16 != 0 -> padding path
    ref = attention_reference(q, k, v)
    out = blockwise_attention(q, k, v, block_size=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_ring_attention_matches_dense():
    mesh = make_mesh({"sp": 8})
    q, k, v = _qkv(t=64)
    run = make_ring_attention(mesh, "sp")
    out = run(q, k, v)
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_ring_attention_causal_matches_dense():
    mesh = make_mesh({"sp": 8})
    q, k, v = _qkv(t=64, seed=3)
    run = make_ring_attention(mesh, "sp", causal=True)
    out = run(q, k, v)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_ring_attention_output_stays_sharded():
    mesh = make_mesh({"sp": 8})
    q, k, v = _qkv(t=32)
    run = make_ring_attention(mesh, "sp")
    out = run(q, k, v)
    assert len(out.sharding.device_set) == 8


def test_ring_attention_grads():
    mesh = make_mesh({"sp": 4}, devices=jax.devices("cpu")[:4])
    q, k, v = _qkv(t=32, seed=5)

    from functools import partial
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel.ring_attention import ring_attention
    spec = P(None, None, "sp", None)
    fn = shard_map(partial(ring_attention, axis_name="sp"),
                   mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)

    def loss(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)
