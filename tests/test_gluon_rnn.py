"""Gluon RNN tests (model: reference tests/python/unittest/test_gluon_rnn.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import rnn
from mxnet_tpu.test_utils import assert_almost_equal


def _x(n=5, t=3, c=4, seed=0):
    rng = np.random.RandomState(seed)
    return mx.nd.array(rng.randn(n, t, c).astype("float32"))


def test_rnn_cells_unroll_shapes():
    for cell_cls, n_states in [(rnn.RNNCell, 1), (rnn.LSTMCell, 2),
                               (rnn.GRUCell, 1)]:
        cell = cell_cls(8)
        cell.initialize()
        outs, states = cell.unroll(3, _x(), layout="NTC",
                                   merge_outputs=True)
        assert outs.shape == (5, 3, 8)
        assert len(states) == n_states
        assert all(s.shape == (5, 8) for s in states)


def test_cell_step():
    cell = rnn.LSTMCell(8, input_size=4)
    cell.initialize()
    x = mx.nd.ones((2, 4))
    states = cell.begin_state(2)
    out, new_states = cell(x, states)
    assert out.shape == (2, 8)
    assert len(new_states) == 2


def test_fused_layers_shapes():
    x = _x()
    for Layer, n_states in [(rnn.LSTM, 2), (rnn.GRU, 1), (rnn.RNN, 1)]:
        layer = Layer(8, num_layers=2, layout="NTC")
        layer.initialize()
        assert layer(x).shape == (5, 3, 8)
        out, states = layer(x, layer.begin_state(5))
        assert out.shape == (5, 3, 8)
        assert len(states) == n_states


def test_fused_tnc_layout():
    layer = rnn.LSTM(8)
    layer.initialize()
    x = mx.nd.ones((3, 5, 4))  # TNC
    assert layer(x).shape == (3, 5, 8)


def test_bidirectional_fused():
    layer = rnn.LSTM(8, bidirectional=True, layout="NTC")
    layer.initialize()
    assert layer(_x()).shape == (5, 3, 16)


def test_cell_vs_fused_parity():
    """The fused scan and the unrolled cell must agree on shared weights."""
    fused = rnn.LSTM(8, layout="NTC", input_size=4)
    fused.initialize()
    cell = rnn.LSTMCell(8, input_size=4)
    cell.initialize()
    cell.i2h_weight.set_data(fused.l0_i2h_weight.data())
    cell.h2h_weight.set_data(fused.l0_h2h_weight.data())
    cell.i2h_bias.set_data(fused.l0_i2h_bias.data())
    cell.h2h_bias.set_data(fused.l0_h2h_bias.data())
    x = _x()
    of = fused(x).asnumpy()
    oc, _ = cell.unroll(3, x, layout="NTC", merge_outputs=True)
    assert_almost_equal(of, oc.asnumpy(), rtol=1e-4, atol=1e-6)


def test_gru_cell_vs_fused_parity():
    fused = rnn.GRU(6, layout="NTC", input_size=4)
    fused.initialize()
    cell = rnn.GRUCell(6, input_size=4)
    cell.initialize()
    for name in ["i2h_weight", "h2h_weight", "i2h_bias", "h2h_bias"]:
        getattr(cell, name).set_data(
            getattr(fused, "l0_" + name).data())
    x = _x()
    assert_almost_equal(
        fused(x).asnumpy(),
        cell.unroll(3, x, layout="NTC", merge_outputs=True)[0].asnumpy(),
        rtol=1e-4, atol=1e-6)


def test_fused_gradients():
    layer = rnn.LSTM(8, num_layers=2, bidirectional=True, layout="NTC")
    layer.initialize()
    with autograd.record():
        loss = (layer(_x()) ** 2).sum()
    loss.backward()
    for name, p in layer.collect_params().items():
        assert np.abs(p.grad().asnumpy()).sum() > 0, name


def test_fused_hybridize():
    layer = rnn.GRU(8, layout="NTC")
    layer.initialize()
    x = _x()
    eager = layer(x).asnumpy()
    layer.hybridize()
    assert_almost_equal(layer(x).asnumpy(), eager, rtol=1e-5, atol=1e-6)


def test_bidirectional_cell():
    cell = rnn.BidirectionalCell(rnn.GRUCell(4), rnn.GRUCell(4))
    cell.initialize()
    outs, states = cell.unroll(3, _x(), layout="NTC", merge_outputs=True)
    assert outs.shape == (5, 3, 8)
    with pytest.raises(NotImplementedError):
        cell(mx.nd.ones((2, 4)), cell.begin_state(2))


def test_sequential_stack_and_modifiers():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(4))
    stack.add(rnn.ResidualCell(rnn.LSTMCell(4)))
    stack.add(rnn.DropoutCell(0.3))
    stack.initialize()
    outs, states = stack.unroll(3, _x(), layout="NTC", merge_outputs=True)
    assert outs.shape == (5, 3, 4)
    assert len(states) == 4
    assert len(stack) == 3


def test_zoneout_cell():
    cell = rnn.ZoneoutCell(rnn.RNNCell(4), zoneout_outputs=0.5,
                           zoneout_states=0.5)
    cell.initialize()
    with autograd.record():  # training mode -> zoneout active
        outs, states = cell.unroll(3, _x(), layout="NTC",
                                   merge_outputs=True)
    assert outs.shape == (5, 3, 4)


def test_residual_cell_value():
    base = rnn.RNNCell(4, input_size=4)
    cell = rnn.ResidualCell(base)
    cell.initialize()
    x = _x(c=4)
    outs, _ = cell.unroll(3, x, layout="NTC", merge_outputs=True)
    base._modified = False
    inner, _ = base.unroll(3, x, layout="NTC", merge_outputs=True)
    base._modified = True
    assert_almost_equal(outs.asnumpy(), (inner + x.transpose((0, 1, 2))
                                         ).asnumpy(), rtol=1e-5)


def test_unfuse():
    layer = rnn.LSTM(8, num_layers=2, layout="NTC", input_size=4,
                     dropout=0.2)
    stack = layer._unfuse()
    stack.initialize()
    outs, states = stack.unroll(3, _x(), layout="NTC", merge_outputs=True)
    assert outs.shape == (5, 3, 8)


def test_rnn_layer_begin_state_shapes():
    layer = rnn.LSTM(8, num_layers=3, bidirectional=True)
    st = layer.state_info(5)
    assert st[0]["shape"] == (6, 5, 8)
    layer.initialize()
    states = layer.begin_state(5)
    assert states[0].shape == (6, 5, 8)
    assert states[1].shape == (6, 5, 8)


def test_variable_length_unroll():
    cell = rnn.LSTMCell(4)
    cell.initialize()
    x = _x(n=3, t=4, c=5)
    valid = mx.nd.array(np.array([2, 3, 4], dtype="float32"))
    outs, states = cell.unroll(4, x, layout="NTC", merge_outputs=True,
                               valid_length=valid)
    o = outs.asnumpy()
    # steps beyond valid_length must be masked to zero
    assert np.allclose(o[0, 2:], 0)
    assert np.allclose(o[1, 3:], 0)
    assert not np.allclose(o[2, 3], 0)


def test_hybridized_cell_step():
    """Review regression: cells must be hybridizable when stepped with a
    state list."""
    cell = rnn.GRUCell(4, input_size=3)
    cell.initialize()
    x = mx.nd.ones((2, 3))
    states = cell.begin_state(2)
    eager_out, eager_states = cell(x, states)
    cell.hybridize()
    hy_out, hy_states = cell(x, states)
    assert_almost_equal(eager_out.asnumpy(), hy_out.asnumpy(), rtol=1e-5)
    assert len(hy_states) == 1
    # second call reuses the compiled graph
    cell(x, states)
    assert len(cell._cached_graph) == 1


def test_bidirectional_valid_length():
    """Review regression: backward outputs in the valid region must be
    non-zero and match unrolling the truncated sequence."""
    l, r = rnn.GRUCell(4, input_size=5), rnn.GRUCell(4, input_size=5)
    cell = rnn.BidirectionalCell(l, r)
    cell.initialize()
    rng = np.random.RandomState(0)
    full = rng.randn(1, 4, 5).astype("float32")
    full[0, 2:] = 99.0  # garbage padding
    x = mx.nd.array(full)
    valid = mx.nd.array(np.array([2], dtype="float32"))
    outs, _ = cell.unroll(4, x, layout="NTC", merge_outputs=True,
                          valid_length=valid)
    o = outs.asnumpy()
    assert np.allclose(o[0, 2:], 0)          # masked padding
    assert not np.allclose(o[0, :2, 4:], 0)  # backward half non-zero

    # parity with unrolling only the valid prefix
    cell2 = rnn.BidirectionalCell(l, r)  # shares params via same cells? no —
    outs2, _ = cell.unroll(2, mx.nd.array(full[:, :2]), layout="NTC",
                           merge_outputs=True)
    assert_almost_equal(o[0, :2], outs2.asnumpy()[0], rtol=1e-4, atol=1e-5)


def test_ctc_label_lengths_without_pred_lengths():
    """Review regression: label_lengths alone must not shift into the
    data_lengths slot."""
    np.random.seed(5)
    pred = mx.nd.array(np.random.randn(1, 8, 5).astype("float32"))
    label = mx.nd.array(np.array([[1, 0, 2, 2]], dtype="float32"))
    L = gluon.loss.CTCLoss()
    with_len = L(pred, label, None,
                 mx.nd.array(np.array([2], dtype="float32"))).asnumpy()
    without = L(pred, label).asnumpy()
    assert not np.allclose(with_len, without)


def test_clip_global_norm_async_path():
    arrays = [mx.nd.ones((2, 2)) * 3, mx.nd.ones((2,)) * 4]
    total = gluon.utils.clip_global_norm(arrays, 1.0, check_isfinite=False)
    assert hasattr(total, "asnumpy")  # NDArray, not a synced float
    new_norm = np.sqrt(sum((a.asnumpy() ** 2).sum() for a in arrays))
    assert abs(new_norm - 1.0) < 1e-4


def test_unroll_list_inputs():
    """Review regression: list-of-steps input must infer batch from axis 0."""
    cell = rnn.GRUCell(6, input_size=4)
    cell.initialize()
    steps = [mx.nd.ones((5, 4)) for _ in range(3)]
    outs, states = cell.unroll(3, steps, layout="TNC")
    assert len(outs) == 3 and outs[0].shape == (5, 6)
    assert states[0].shape == (5, 6)


def test_zoneout_hybridize_no_tracer_leak():
    """Review regression: stepping a hybridized ZoneoutCell across batch
    sizes must not leak tracers between traces."""
    cell = rnn.ZoneoutCell(rnn.RNNCell(4, input_size=3),
                           zoneout_outputs=0.5)
    cell.initialize()
    cell.hybridize()
    with autograd.record():
        for bs in (2, 3, 2):
            out, _ = cell(mx.nd.ones((bs, 3)), cell.begin_state(bs))
            assert out.shape == (bs, 4)
