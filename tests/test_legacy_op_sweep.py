"""Numeric tests for legacy v1 ops with no prior direct coverage:
Sequence{Mask,Last,Reverse}, UpSampling, LRN, L2Normalization,
SoftmaxActivation, SliceChannel, SwapAxis, BlockGrad, Cast, the
regression output heads, SVMOutput, and the STN trio
GridGenerator/BilinearSampler/SpatialTransformer (reference
tests/python/unittest/test_operator.py cases re-expressed)."""
import numpy as np
import pytest

import mxnet_tpu as mx

RNG = np.random.RandomState(5)


def _inv(name, arrs, **kw):
    out = mx.nd.invoke(name, [mx.nd.array(a) for a in arrs], kw)
    if isinstance(out, (list, tuple)):
        out = out[0]
    return out.asnumpy()


# ---------------------------------------------------------------------------
# sequence ops (time-major (T, B, ...), per-batch lengths)
# ---------------------------------------------------------------------------

def test_sequence_mask_lengths_and_value():
    x = RNG.randn(4, 3, 2).astype("f4")
    lens = np.array([2, 4, 1], "f4")
    got = _inv("SequenceMask", [x, lens], use_sequence_length=True,
               value=-7.0)
    want = x.copy()
    for b, L in enumerate(lens.astype(int)):
        want[L:, b] = -7.0
    np.testing.assert_allclose(got, want)
    # without lengths: identity
    np.testing.assert_allclose(_inv("SequenceMask", [x]), x)


def test_sequence_last_lengths():
    x = RNG.randn(5, 3, 2).astype("f4")
    lens = np.array([1, 5, 3], "f4")
    got = _inv("SequenceLast", [x, lens], use_sequence_length=True)
    want = np.stack([x[0, 0], x[4, 1], x[2, 2]])
    np.testing.assert_allclose(got, want)
    np.testing.assert_allclose(_inv("SequenceLast", [x]), x[-1])


def test_sequence_reverse_lengths():
    x = RNG.randn(4, 2, 3).astype("f4")
    lens = np.array([3, 4], "f4")
    got = _inv("SequenceReverse", [x, lens], use_sequence_length=True)
    want = x.copy()
    for b, L in enumerate(lens.astype(int)):
        want[:L, b] = x[:L, b][::-1]
    np.testing.assert_allclose(got, want)
    np.testing.assert_allclose(_inv("SequenceReverse", [x]), x[::-1])


# ---------------------------------------------------------------------------
# spatial/shape ops
# ---------------------------------------------------------------------------

def test_upsampling_nearest():
    x = RNG.randn(2, 3, 4, 4).astype("f4")
    got = _inv("UpSampling", [x], scale=2, sample_type="nearest")
    want = np.repeat(np.repeat(x, 2, axis=2), 2, axis=3)
    np.testing.assert_allclose(got, want)


def test_lrn_vs_torch():
    torch = pytest.importorskip("torch")
    x = RNG.randn(2, 8, 5, 5).astype("f4")
    nsize, alpha, beta, k = 5, 1e-3, 0.75, 2.0
    got = _inv("LRN", [x], nsize=nsize, alpha=alpha, beta=beta, knorm=k)
    want = torch.nn.functional.local_response_norm(
        torch.from_numpy(x), size=nsize, alpha=alpha, beta=beta,
        k=k).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_l2_normalization_modes():
    x = RNG.randn(2, 3, 4).astype("f4")
    got = _inv("L2Normalization", [x], mode="instance")
    want = x / np.sqrt((x ** 2).sum(axis=(1, 2), keepdims=True) + 1e-10)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    got = _inv("L2Normalization", [x], mode="channel")
    want = x / np.sqrt((x ** 2).sum(axis=1, keepdims=True) + 1e-10)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    got = _inv("L2Normalization", [x], mode="spatial")
    want = x / np.sqrt((x ** 2).sum(axis=2, keepdims=True) + 1e-10)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_softmax_activation_channel_mode():
    x = RNG.randn(2, 4, 3, 3).astype("f4")
    got = _inv("SoftmaxActivation", [x], mode="channel")
    e = np.exp(x - x.max(axis=1, keepdims=True))
    np.testing.assert_allclose(got, e / e.sum(axis=1, keepdims=True),
                               rtol=1e-5)
    # instance mode flattens trailing dims
    x2 = RNG.randn(3, 6).astype("f4")
    got2 = _inv("SoftmaxActivation", [x2])
    e2 = np.exp(x2 - x2.max(axis=1, keepdims=True))
    np.testing.assert_allclose(got2, e2 / e2.sum(axis=1, keepdims=True),
                               rtol=1e-5)


def test_slice_channel_and_squeeze():
    x = RNG.randn(2, 6, 3).astype("f4")
    outs = mx.nd.invoke("SliceChannel", [mx.nd.array(x)],
                        {"num_outputs": 3, "axis": 1})
    assert len(outs) == 3
    for i, o in enumerate(outs):
        np.testing.assert_allclose(o.asnumpy(), x[:, 2 * i:2 * i + 2, :])
    outs = mx.nd.invoke("SliceChannel", [mx.nd.array(x)],
                        {"num_outputs": 6, "axis": 1,
                         "squeeze_axis": True})
    assert outs[0].shape == (2, 3)
    np.testing.assert_allclose(outs[4].asnumpy(), x[:, 4, :])


def test_swapaxis_and_cast():
    x = RNG.randn(2, 3, 4).astype("f4")
    np.testing.assert_allclose(_inv("SwapAxis", [x], dim1=0, dim2=2),
                               np.swapaxes(x, 0, 2))
    got = mx.nd.invoke("Cast", [mx.nd.array(x)], {"dtype": "int32"})
    assert got.dtype == np.int32
    np.testing.assert_allclose(got.asnumpy(), x.astype("i4"))


def test_block_grad_stops_gradient():
    x = mx.nd.array(np.full((3,), 2.0, "f4"))
    x.attach_grad()
    with mx.autograd.record():
        y = (mx.nd.invoke("BlockGrad", [x], {}) * x * x).sum()
    y.backward()
    # d/dx [bg(x) * x^2] = 2 * bg(x) * x = 8 (the bg(x)=x factor is held)
    np.testing.assert_allclose(x.grad.asnumpy(), np.full((3,), 8.0))


# ---------------------------------------------------------------------------
# output heads: forward + injected gradients
# ---------------------------------------------------------------------------

def _head_grad(name, data, label, **kw):
    d = mx.nd.array(data)
    d.attach_grad()
    with mx.autograd.record():
        out = mx.nd.invoke(name, [d, mx.nd.array(label)], kw)
    out.backward()
    return out.asnumpy(), d.grad.asnumpy()


def test_linear_regression_output_grad():
    data = RNG.randn(4, 3).astype("f4")
    label = RNG.randn(4, 3).astype("f4")
    out, grad = _head_grad("LinearRegressionOutput", data, label)
    np.testing.assert_allclose(out, data)
    np.testing.assert_allclose(grad, (data - label) / 3, rtol=1e-5)


def test_mae_regression_output_grad():
    data = RNG.randn(4, 3).astype("f4")
    label = RNG.randn(4, 3).astype("f4")
    out, grad = _head_grad("MAERegressionOutput", data, label)
    np.testing.assert_allclose(out, data)
    np.testing.assert_allclose(grad, np.sign(data - label) / 3)


def test_logistic_regression_output_grad():
    data = RNG.randn(4, 1).astype("f4")
    label = RNG.randint(0, 2, (4, 1)).astype("f4")
    out, grad = _head_grad("LogisticRegressionOutput", data, label)
    sig = 1 / (1 + np.exp(-data))
    np.testing.assert_allclose(out, sig, rtol=1e-5)
    np.testing.assert_allclose(grad, sig - label, rtol=1e-5, atol=1e-6)


def test_svm_output_hinge_grad():
    data = np.array([[2.0, -2.0], [0.2, -0.2]], "f4")  # row0 satisfied
    label = np.array([0, 0], "f4")
    out, grad = _head_grad("SVMOutput", data, label, margin=1.0,
                           use_linear=True)
    np.testing.assert_allclose(out, data)
    np.testing.assert_allclose(grad[0], [0, 0])          # margin met
    np.testing.assert_allclose(grad[1], [-1.0, 1.0])     # violations


# ---------------------------------------------------------------------------
# STN trio
# ---------------------------------------------------------------------------

def test_grid_generator_affine_identity():
    theta = np.tile(np.array([1, 0, 0, 0, 1, 0], "f4"), (2, 1))
    grid = _inv("GridGenerator", [theta], transform_type="affine",
                target_shape=(3, 5))
    assert grid.shape == (2, 2, 3, 5)
    np.testing.assert_allclose(grid[0, 0, 0], np.linspace(-1, 1, 5),
                               rtol=1e-5)
    np.testing.assert_allclose(grid[0, 1, :, 0], np.linspace(-1, 1, 3),
                               rtol=1e-5)


def test_spatial_transformer_identity_and_torch():
    torch = pytest.importorskip("torch")
    x = RNG.randn(2, 3, 6, 6).astype("f4")
    ident = np.tile(np.array([1, 0, 0, 0, 1, 0], "f4"), (2, 1))
    got = _inv("SpatialTransformer", [x, ident], target_shape=(6, 6),
               transform_type="affine", sampler_type="bilinear")
    np.testing.assert_allclose(got, x, rtol=1e-4, atol=1e-5)
    # a real affine vs torch grid_sample(align_corners=True)
    theta = np.tile(np.array([0.8, 0.1, 0.05, -0.1, 0.9, -0.05], "f4"),
                    (2, 1))
    got = _inv("SpatialTransformer", [x, theta], target_shape=(5, 4),
               transform_type="affine", sampler_type="bilinear")
    tg = torch.nn.functional.affine_grid(
        torch.from_numpy(theta.reshape(2, 2, 3)), (2, 3, 5, 4),
        align_corners=True)
    want = torch.nn.functional.grid_sample(
        torch.from_numpy(x), tg, mode="bilinear", padding_mode="zeros",
        align_corners=True).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
