"""Numeric tests for contrib ops with no prior direct coverage: fft/ifft,
count_sketch, index_copy, quadratic, boolean_mask, getnnz, box_iou,
box_nms, div_sqrt_dim, AdaptiveAvgPooling2D, BilinearResize2D (reference
tests/python/unittest/test_contrib_operator.py / test_operator.py cases
re-expressed)."""
import numpy as np
import pytest

import mxnet_tpu as mx

RNG = np.random.RandomState(11)


def _inv(name, arrs, **kw):
    out = mx.nd.invoke(name, [mx.nd.array(a) for a in arrs], kw)
    if isinstance(out, (list, tuple)):
        out = out[0]
    return out.asnumpy()


def test_fft_ifft_roundtrip_and_values():
    x = RNG.randn(3, 8).astype("f4")
    packed = _inv("_contrib_fft", [x])
    assert packed.shape == (3, 16)
    want = np.fft.fft(x, axis=-1)
    np.testing.assert_allclose(packed[:, 0::2], want.real, rtol=1e-4,
                               atol=1e-4)
    np.testing.assert_allclose(packed[:, 1::2], want.imag, rtol=1e-4,
                               atol=1e-4)
    # reference ifft scales by n (contrib/fft-inl.h backward convention)
    back = _inv("_contrib_ifft", [packed])
    np.testing.assert_allclose(back, x * 8, rtol=1e-4, atol=1e-4)


def test_count_sketch_unbiased_dot_product():
    """Count-sketch preserves dot products in expectation; with a single
    (h, s) draw we check the defining identity: sketch(x) . sketch(y)
    computed with the same hashes equals sum_j s_j^2 x_j y_j grouped by
    buckets — verified against a direct numpy sketch."""
    in_dim, out_dim = 32, 16
    x = RNG.randn(2, in_dim).astype("f4")
    h = RNG.randint(0, out_dim, (1, in_dim)).astype("f4")
    s = np.sign(RNG.randn(1, in_dim)).astype("f4")
    got = mx.nd.invoke("_contrib_count_sketch",
                       [mx.nd.array(x), mx.nd.array(h), mx.nd.array(s)],
                       {"out_dim": out_dim}).asnumpy()
    want = np.zeros((2, out_dim), "f4")
    for j in range(in_dim):
        want[:, int(h[0, j])] += s[0, j] * x[:, j]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_index_copy():
    old = np.zeros((5, 3), "f4")
    new = RNG.randn(2, 3).astype("f4")
    idx = np.array([3, 0], "f4")
    got = _inv("_contrib_index_copy", [old, idx, new])
    want = old.copy()
    want[3] = new[0]
    want[0] = new[1]
    np.testing.assert_allclose(got, want)


def test_quadratic_and_grad():
    x = mx.nd.array(RNG.randn(4).astype("f4"))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.invoke("_contrib_quadratic", [x],
                         {"a": 2.0, "b": -1.0, "c": 0.5}).sum()
    y.backward()
    xn = x.asnumpy()
    np.testing.assert_allclose(x.grad.asnumpy(), 4 * xn - 1, rtol=1e-5)


def test_div_sqrt_dim():
    x = RNG.randn(2, 9).astype("f4")
    np.testing.assert_allclose(_inv("_contrib_div_sqrt_dim", [x]),
                               x / 3.0, rtol=1e-6)


def test_boolean_mask_compacts_kept_rows():
    data = np.arange(12, dtype="f4").reshape(4, 3)
    mask = np.array([1, 0, 1, 0], "f4")
    got = _inv("_contrib_boolean_mask", [data, mask])
    # static-shape contract: kept rows first, zero padding after
    np.testing.assert_allclose(got[:2], data[[0, 2]])
    np.testing.assert_allclose(got[2:], 0)


def test_getnnz_dense():
    x = np.array([[0, 1, 2], [0, 0, 3]], "f4")
    assert _inv("_contrib_getnnz", [x]).item() == 3


def test_box_iou_matches_manual():
    a = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], "f4")
    b = np.array([[0, 0, 2, 2], [2, 2, 4, 4]], "f4")
    got = _inv("_contrib_box_iou", [a, b])
    assert got.shape == (2, 2)
    np.testing.assert_allclose(got[0, 0], 1.0, rtol=1e-6)
    np.testing.assert_allclose(got[0, 1], 0.0, atol=1e-7)
    # boxes [1,1,3,3] vs [2,2,4,4]: inter 1, union 7
    np.testing.assert_allclose(got[1, 1], 1 / 7, rtol=1e-5)


def test_box_nms_suppresses_overlaps():
    # rows: [class_id, score, x1, y1, x2, y2]
    boxes = np.array([
        [0, 0.9, 0, 0, 2, 2],
        [0, 0.8, 0.1, 0.1, 2.1, 2.1],   # heavy overlap with row 0
        [0, 0.7, 5, 5, 7, 7],           # far away
    ], "f4")
    out = _inv("_contrib_box_nms", [boxes],
               overlap_thresh=0.5, coord_start=2, score_index=1,
               id_index=0)
    scores = out[:, 1]
    assert (scores == 0.9).any() and (scores == 0.7).any()
    assert not (scores == 0.8).any()      # suppressed -> -1 row
    assert (out == -1).any()


def test_adaptive_avg_pooling():
    x = RNG.randn(1, 2, 4, 4).astype("f4")
    got = _inv("_contrib_AdaptiveAvgPooling2D", [x], output_size=(2, 2))
    want = x.reshape(1, 2, 2, 2, 2, 2).mean(axis=(3, 5))
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # global: output_size 1 == full mean
    got1 = _inv("_contrib_AdaptiveAvgPooling2D", [x], output_size=(1, 1))
    np.testing.assert_allclose(got1[..., 0, 0], x.mean(axis=(2, 3)),
                               rtol=1e-5)


def test_adaptive_avg_pooling_non_divisible_vs_torch():
    torch = pytest.importorskip("torch")
    x = RNG.randn(2, 3, 7, 5).astype("f4")
    got = _inv("_contrib_AdaptiveAvgPooling2D", [x], output_size=(3, 2))
    want = torch.nn.functional.adaptive_avg_pool2d(
        torch.from_numpy(x), (3, 2)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_bilinear_resize_corners_and_torch():
    torch = pytest.importorskip("torch")
    x = RNG.randn(1, 1, 5, 5).astype("f4")
    got = _inv("_contrib_BilinearResize2D", [x], height=9, width=9)
    want = torch.nn.functional.interpolate(
        torch.from_numpy(x), size=(9, 9), mode="bilinear",
        align_corners=True).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
