"""Fused Module train step (module/fused.py): numeric parity with the eager
per-parameter update path, through the public Module.fit API.

The reference semantics being matched: update_on_kvstore=False training
(python/mxnet/model.py:123-170) where fwd/bwd run, grads are reduced, and
the optimizer op applies per parameter — here all inside one XLA program
when kvstore='tpu_sync'.
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _make_net(with_bn=True):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    if with_bn:
        net = mx.sym.BatchNorm(net, name="bn1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _data(n=64, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 8).astype("float32")
    Y = rng.randint(0, 4, (n,)).astype("float32")
    return X, Y


def _fixed_params(sym, seed=3):
    rng = np.random.RandomState(seed)
    shapes, _, _ = sym.infer_shape(data=(16, 8))
    out = {}
    for name, shp in zip(sym.list_arguments(), shapes):
        if name in ("data", "softmax_label"):
            continue
        out[name] = mx.nd.array(rng.uniform(-0.1, 0.1, shp).astype("float32"))
    return out


def _fit(kvstore, optimizer, optimizer_params, ctx=None, num_epoch=3,
         with_bn=True, n=64):
    sym = _make_net(with_bn)
    X, Y = _data(n)
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    mod = mx.mod.Module(sym, context=ctx)
    mod.fit(it, num_epoch=num_epoch, kvstore=kvstore, optimizer=optimizer,
            optimizer_params=optimizer_params,
            arg_params={k: v.copy() for k, v in _fixed_params(sym).items()},
            initializer=None, allow_missing=False)
    return mod


def _assert_params_close(mod_a, mod_b, rtol=2e-5, atol=2e-6):
    args_a, aux_a = mod_a.get_params()
    args_b, aux_b = mod_b.get_params()
    assert set(args_a) == set(args_b)
    for k in args_a:
        np.testing.assert_allclose(args_a[k].asnumpy(), args_b[k].asnumpy(),
                                   rtol=rtol, atol=atol, err_msg=k)
    for k in aux_a:
        np.testing.assert_allclose(aux_a[k].asnumpy(), aux_b[k].asnumpy(),
                                   rtol=rtol, atol=atol, err_msg=k)


@pytest.mark.parametrize("opt,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("sgd", {"learning_rate": 0.1}),
    ("adam", {"learning_rate": 0.01}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("adagrad", {"learning_rate": 0.05}),
    ("ftrl", {"learning_rate": 0.05}),
    ("signum", {"learning_rate": 0.01, "momentum": 0.9}),
])
def test_fused_matches_eager_one_step(opt, opt_params):
    """Single-step parity, tight tolerance: one batch, one update. (Multi-
    step comparison of two different XLA programs diverges chaotically for
    normalizing optimizers — sign(g)/sqrt(v) amplifies last-ulp rounding —
    so the strict multi-step check below is limited to the linear ones.)"""
    eager = _fit("local", opt, opt_params, num_epoch=1, n=16)
    assert eager._fused is None  # cpu ctx + local kv -> eager path
    fused = _fit("tpu_sync", opt, opt_params, num_epoch=1, n=16)
    assert fused._fused is not None, "tpu_sync must engage the fused step"
    _assert_params_close(eager, fused, rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("opt,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
])
def test_fused_matches_eager_multi_step(opt, opt_params):
    eager = _fit("local", opt, opt_params)
    fused = _fit("tpu_sync", opt, opt_params)
    assert fused._fused is not None
    _assert_params_close(eager, fused)


def test_fused_with_lr_scheduler():
    sched = mx.lr_scheduler.FactorScheduler(step=4, factor=0.5)
    eager = _fit("local", "sgd",
                 {"learning_rate": 0.2, "momentum": 0.9,
                  "lr_scheduler": sched})
    sched2 = mx.lr_scheduler.FactorScheduler(step=4, factor=0.5)
    fused = _fit("tpu_sync", "sgd",
                 {"learning_rate": 0.2, "momentum": 0.9,
                  "lr_scheduler": sched2})
    assert fused._fused is not None
    _assert_params_close(eager, fused)
    # schedule actually advanced identically
    assert eager._optimizer.num_update == fused._optimizer.num_update


def test_fused_spmd_matches_single_device():
    ctxs = [mx.Context("cpu", i) for i in range(4)]
    single = _fit("tpu_sync", "sgd", {"learning_rate": 0.1, "momentum": 0.9})
    spmd = _fit("tpu_sync", "sgd", {"learning_rate": 0.1, "momentum": 0.9},
                ctx=ctxs)
    assert spmd._fused is not None
    _assert_params_close(single, spmd)


def test_fused_optimizer_states_roundtrip(tmp_path):
    fused = _fit("tpu_sync", "adam", {"learning_rate": 0.01}, num_epoch=2)
    assert fused._fused is not None
    f = str(tmp_path / "opt.states")
    fused.save_optimizer_states(f)

    # an eager module can load what the fused path saved
    sym = _make_net()
    X, Y = _data()
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    eager = mx.mod.Module(sym)
    eager.bind(it.provide_data, it.provide_label)
    eager.init_params(arg_params=_fixed_params(sym), aux_params={},
                      allow_missing=True)
    eager.init_optimizer(kvstore="local", optimizer="adam",
                         optimizer_params={"learning_rate": 0.01})
    eager.load_optimizer_states(f)
    # fused module reloads its own states
    fused.load_optimizer_states(f)
    st = fused._fused_opt_state
    names = fused._fused.param_names
    for k in names:
        idx = fused._fused._name2idx[k]
        es = eager._updater.states[idx]
        es = es if isinstance(es, tuple) else (es,)
        for a, b in zip(st[k], es):
            np.testing.assert_allclose(np.asarray(a), b.asnumpy(), rtol=1e-6)


def test_fit_step_donates_buffers():
    """The atomic fit-loop step donates param/aux/opt buffers to XLA:
    after one _fit_step, the PREVIOUS device buffers must be deleted
    (in-place update, no HBM double-buffering) — while data/label inputs
    survive for reuse across steps."""
    sym = _make_net(with_bn=True)
    X, Y = _data(16)
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    batch = next(iter(it))
    mod = mx.mod.Module(sym)
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(arg_params=_fixed_params(sym), aux_params={},
                    allow_missing=True)
    mod.init_optimizer(kvstore="tpu_sync", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    assert mod._fused is not None
    ex = mod._exec
    pnames = mod._fused.param_names
    old_params = {k: ex.arg_dict[k]._data for k in pnames}
    old_opt = {k: mod._fused_opt_state[k] for k in pnames}
    old_aux = {k: v._data for k, v in ex.aux_dict.items()}
    # copy=True: on CPU np.asarray(jax_array) is a zero-copy view whose
    # external reference would (correctly) block donation of that buffer
    w_before = {k: np.array(v, copy=True) for k, v in old_params.items()}

    mod._fit_step(batch)
    data_val = batch.data[0]._data

    for k in pnames:
        assert old_params[k].is_deleted(), "param %s was copied, not donated" % k
        assert not ex.arg_dict[k]._data.is_deleted()
    for k, st in old_opt.items():
        for s in st:
            assert s.is_deleted(), "opt state of %s not donated" % k
    for k, a in old_aux.items():
        assert a.is_deleted(), "aux %s not donated" % k
    assert not data_val.is_deleted(), "data input must NOT be donated"
    # and the step actually trained
    w_after = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    assert any((w_before[k] != w_after[k]).any() for k in w_before)
    # a second step with the same (surviving) batch works
    mod._fit_step(batch)


def test_fused_flag_disables():
    from mxnet_tpu import config
    with config.override(module_fused_step=False):
        mod = _fit("tpu_sync", "sgd", {"learning_rate": 0.1})
    assert mod._fused is None


def test_fit_without_metric():
    sym = _make_net(with_bn=False)
    X, Y = _data()
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    mod = mx.mod.Module(sym)
    mod.fit(it, num_epoch=1, eval_metric=None, kvstore="tpu_sync",
            arg_params=_fixed_params(sym), initializer=None)
    assert mod._fused is not None


def test_unfusable_optimizer_falls_back():
    mod = _fit("tpu_sync", "nadam", {"learning_rate": 0.01}, num_epoch=1)
    assert mod._fused is None  # Nadam updates via NDArray math on host


# ------------------------------------------- steps_per_dispatch (run_k/scan)
def _fit_grouped(k, opt="sgd", opt_params=None, n=64, num_epoch=3,
                 eval_metric="acc", record_cb=False):
    sym = _make_net()
    X, Y = _data(n)
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    mod = mx.mod.Module(sym)
    calls = []
    cb = (lambda p: calls.append(p.nbatch)) if record_cb else None
    mod.fit(it, num_epoch=num_epoch, kvstore="tpu_sync", optimizer=opt,
            optimizer_params=opt_params or {"learning_rate": 0.1,
                                            "momentum": 0.9},
            arg_params={k_: v.copy() for k_, v in _fixed_params(sym).items()},
            initializer=None, eval_metric=eval_metric,
            steps_per_dispatch=k, batch_end_callback=cb)
    return mod, calls


def test_grouped_dispatch_matches_per_step():
    """K=4 divides the 4 batches/epoch exactly: the whole epoch is one
    scan dispatch. Params+aux (BN stats ride the scan carry) must match
    the per-step fused path."""
    per = _fit("tpu_sync", "sgd", {"learning_rate": 0.1, "momentum": 0.9})
    grp, _ = _fit_grouped(4)
    assert grp._fused is not None
    _assert_params_close(per, grp)


def test_grouped_dispatch_tail_metric_callbacks():
    """n=80 -> 5 batches/epoch, K=2 -> two groups + a 1-batch tail (which
    takes the per-step program rather than tracing a second scan variant
    for the odd size). Callbacks fire once per batch; the metric
    accumulates per sub-batch, equal to per-step."""
    m_grp = mx.metric.create("acc")
    grp, calls = _fit_grouped(2, n=80, num_epoch=2, eval_metric=m_grp,
                              record_cb=True)
    assert calls == list(range(5)) * 2
    m_per = mx.metric.create("acc")
    sym = _make_net()
    X, Y = _data(80)
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    per = mx.mod.Module(sym)
    per.fit(it, num_epoch=2, kvstore="tpu_sync", optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            arg_params={k: v.copy() for k, v in _fixed_params(sym).items()},
            initializer=None, eval_metric=m_per)
    _assert_params_close(per, grp)
    np.testing.assert_allclose(m_per.get()[1], m_grp.get()[1], atol=1e-6)


def test_grouped_adam_update_count_advances_in_scan():
    """Adam's bias correction depends on t: if the in-scan update count
    failed to advance, step 2..K would reuse t=1 and diverge fast."""
    per = _fit("tpu_sync", "adam", {"learning_rate": 0.01}, num_epoch=1)
    grp, _ = _fit_grouped(4, opt="adam",
                          opt_params={"learning_rate": 0.01}, num_epoch=1)
    _assert_params_close(per, grp, rtol=2e-4, atol=2e-6)


def test_grouped_dispatch_spmd_matches_single_device():
    """run_k's mesh branch: stacked feeds re-committed to P(None, 'dp'),
    params/opt replicated — numerics equal to the single-device run."""
    sym = _make_net()
    X, Y = _data(64)
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    mod = mx.mod.Module(sym, context=[mx.Context("cpu", i) for i in range(4)])
    mod.fit(it, num_epoch=3, kvstore="tpu_sync", optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            arg_params={k: v.copy() for k, v in _fixed_params(sym).items()},
            initializer=None, steps_per_dispatch=4)
    assert mod._fused is not None
    single = _fit("tpu_sync", "sgd", {"learning_rate": 0.1, "momentum": 0.9})
    _assert_params_close(single, mod)


def test_grouped_accepts_numpy_feeds():
    """set_inputs accepts raw numpy feeds; the grouped path must too
    (it routes every value through Executor.prepare_input)."""
    from mxnet_tpu.io import DataBatch, DataDesc
    sym = _make_net()
    X, Y = _data(64)
    batches = [DataBatch(data=[X[i * 16:(i + 1) * 16]],
                         label=[Y[i * 16:(i + 1) * 16]]) for i in range(4)]

    class It:
        provide_data = [DataDesc("data", (16, 8))]
        provide_label = [DataDesc("softmax_label", (16,))]
        batch_size = 16

        def __iter__(self):
            return iter(batches)

        def reset(self):
            pass

    mod = mx.mod.Module(sym)
    mod.fit(It(), num_epoch=1, eval_metric=None, kvstore="tpu_sync",
            optimizer="sgd", arg_params=_fixed_params(sym),
            initializer=None, steps_per_dispatch=2)
    assert mod._fused is not None


def test_fused_with_backward_mirror_matches():
    """Gradient mirroring under the fused step: jax.checkpoint recompute
    must not change the numerics (same program, residuals recomputed)."""
    from mxnet_tpu import config
    base = _fit("tpu_sync", "sgd", {"learning_rate": 0.1, "momentum": 0.9},
                num_epoch=1, n=16)
    with config.override(backward_do_mirror=True):
        mirrored = _fit("tpu_sync", "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9},
                        num_epoch=1, n=16)
    _assert_params_close(base, mirrored, rtol=1e-5, atol=1e-7)


def test_grouped_rejects_bad_k():
    sym = _make_net()
    X, Y = _data(16)
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    mod = mx.mod.Module(sym)
    with pytest.raises(ValueError, match="steps_per_dispatch"):
        mod.fit(it, num_epoch=1, steps_per_dispatch=0)


def test_grouped_rejects_monitor():
    sym = _make_net()
    X, Y = _data(16)
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    mod = mx.mod.Module(sym)
    mon = mx.monitor.Monitor(1)
    with pytest.raises(ValueError, match="steps_per_dispatch"):
        mod.fit(it, num_epoch=1, kvstore="tpu_sync",
                steps_per_dispatch=2, monitor=mon)
    # the raise fired before bind/install_monitor/init_optimizer: a retry
    # without the monitor must still engage the fused path
    it.reset()
    mod.fit(it, num_epoch=1, kvstore="tpu_sync", steps_per_dispatch=2,
            arg_params=_fixed_params(_make_net()), initializer=None)
    assert mod._fused is not None


# --------------------------------------------------------------- gluon side
def _gluon_train(fused, opt="sgd", opt_params=None, steps=6):
    from mxnet_tpu import gluon, autograd, config
    opt_params = dict(opt_params or {"learning_rate": 0.1, "momentum": 0.9})
    rng = np.random.RandomState(0)
    X = mx.nd.array(rng.randn(32, 8).astype("float32"))
    Y = mx.nd.array(rng.randn(32, 1).astype("float32"))
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(1))
    net.initialize(mx.initializer.Xavier(rnd_type="gaussian"),
                   force_reinit=True)
    mx.random.seed(7)
    # deterministic init: overwrite with fixed values
    net(X)  # shape inference
    r2 = np.random.RandomState(5)
    for p in net.collect_params().values():
        p.set_data(mx.nd.array(
            r2.uniform(-0.1, 0.1, p.shape).astype("float32")))
    trainer = gluon.Trainer(net.collect_params(), opt, opt_params)
    loss_fn = gluon.loss.L2Loss()
    with config.override(trainer_fused_update=fused):
        for _ in range(steps):
            with autograd.record():
                loss = loss_fn(net(X), Y)
            loss.backward()
            trainer.step(32)
    # positional keys: gluon name counters advance globally between runs
    return [p.data().asnumpy() for p in net.collect_params().values()], \
        trainer


def test_trainer_fused_matches_eager():
    eager, tr_e = _gluon_train(False)
    fused, tr_f = _gluon_train(True)
    assert tr_f._fused_jit is not None, "fused trainer update did not engage"
    for a, b in zip(eager, fused):
        np.testing.assert_allclose(a, b, rtol=2e-6, atol=1e-7)


def test_gluon_hybridize_mirror_matches():
    """Mirroring on the CachedOp backward (hybridize path): identical
    training trajectory with remat on."""
    from mxnet_tpu import config
    base, _ = _gluon_train(True)
    with config.override(backward_do_mirror=True):
        mirrored, _ = _gluon_train(True)
    for a, b in zip(base, mirrored):
        np.testing.assert_allclose(a, b, rtol=2e-6, atol=1e-7)


def test_trainer_fused_adam_matches_eager():
    eager, _ = _gluon_train(False, "adam", {"learning_rate": 0.01}, steps=1)
    fused, tr = _gluon_train(True, "adam", {"learning_rate": 0.01}, steps=1)
    assert tr._fused_jit is not None
    for a, b in zip(eager, fused):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_trainer_fused_states_roundtrip(tmp_path):
    _, tr = _gluon_train(True, "adam", {"learning_rate": 0.01}, steps=3)
    f = str(tmp_path / "trainer.states")
    tr.save_states(f)
    tr.load_states(f)
    assert tr._fused_jit is None  # caches dropped on load


def test_custom_loop_keeps_eager_semantics():
    """Bare forward()/backward()/update() must behave exactly like the
    reference even when the fused step is configured: weights move only at
    update(), grad_dict is populated, and a skipped update() leaves weights
    and the LR schedule untouched."""
    sym = _make_net(with_bn=False)
    X, Y = _data(16)
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    batch = next(iter(it))
    mod = mx.mod.Module(sym)
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(arg_params=_fixed_params(sym), aux_params={},
                    allow_missing=True)
    mod.init_optimizer(kvstore="tpu_sync", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    assert mod._fused is not None
    before = {k: v.asnumpy().copy() for k, v in mod.get_params()[0].items()}

    # eager-style loop: weights untouched until update()
    mod.forward(batch, is_train=True)
    mod.backward()
    assert any(g is not None for g in mod._exec.grad_dict.values())
    mid = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    for k in before:
        np.testing.assert_array_equal(before[k], mid[k])
    mod.update()
    after = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    assert any((before[k] != after[k]).any() for k in before)

    # fused fit-style step with update() SKIPPED: no weight/schedule motion
    n_before = mod._optimizer.num_update
    w_before = {k: v.asnumpy().copy() for k, v in mod.get_params()[0].items()}
    mod.forward_backward(batch)  # launches the fused program
    assert mod._fused_ran
    w_mid = {k: v.asnumpy() for k, v in mod.get_params()[0].items()}
    for k in w_before:
        np.testing.assert_array_equal(w_before[k], w_mid[k])
    assert mod._optimizer.num_update == n_before  # schedule not advanced
    mod.update()
    assert mod._optimizer.num_update == n_before + 1


def test_eval_metric_none_with_eval_data_raises():
    sym = _make_net(with_bn=False)
    X, Y = _data()
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    it2 = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    mod = mx.mod.Module(sym)
    with pytest.raises(ValueError):
        mod.fit(it, eval_data=it2, eval_metric=None, num_epoch=1)


def test_bucketing_fused_matches_eager_across_buckets():
    """BucketingModule engages the fused step per bucket with ONE
    optimizer accumulator per weight across buckets (mirrored through
    the shared Updater on switches) — numerics must match the all-eager
    path over an alternating-bucket schedule."""
    from mxnet_tpu import config
    from mxnet_tpu.io import DataBatch, DataDesc

    def sym_gen(L):
        data = mx.sym.Variable("data")
        net = mx.sym.mean(data, axis=1)             # (B, 4) for any L
        net = mx.sym.FullyConnected(net, num_hidden=8, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
        return (mx.sym.SoftmaxOutput(net, name="softmax"),
                ("data",), ("softmax_label",))

    rng = np.random.RandomState(0)
    buckets = [3, 5]
    batches = []
    for i in range(8):
        L = buckets[i % 2]
        b = DataBatch(
            data=[mx.nd.array(rng.randn(4, L, 4).astype("f4"))],
            label=[mx.nd.array(rng.randint(0, 4, (4,)).astype("f4"))],
            provide_data=[DataDesc("data", (4, L, 4))],
            provide_label=[DataDesc("softmax_label", (4,))])
        b.bucket_key = L
        batches.append(b)

    def train(fused):
        with config.override(module_fused_step=fused):
            mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=5)
            mod.bind([DataDesc("data", (4, 5, 4))],
                     [DataDesc("softmax_label", (4,))])
            prng = np.random.RandomState(3)
            sym5 = sym_gen(5)[0]
            shapes, _, _ = sym5.infer_shape(data=(4, 5, 4))
            fixed = {n: mx.nd.array(
                prng.uniform(-0.1, 0.1, s).astype("f4"))
                for n, s in zip(sym5.list_arguments(), shapes)
                if n not in ("data", "softmax_label")}
            mod.init_params(arg_params=fixed, aux_params={},
                            allow_missing=True)
            mod.init_optimizer(kvstore="tpu_sync", optimizer="sgd",
                               optimizer_params={"learning_rate": 0.1,
                                                 "momentum": 0.9})
            if fused:
                assert mod._curr_module._fused is not None
            for b in batches:
                mod._fit_step(b)
            return {k: v.asnumpy() for k, v in mod.get_params()[0].items()}

    p_f = train(True)
    p_e = train(False)
    for k in p_e:
        np.testing.assert_allclose(p_f[k], p_e[k], rtol=2e-5, atol=2e-6,
                                    err_msg=k)


def test_bucketing_checkpoint_saves_active_bucket_momentum(tmp_path):
    """save_checkpoint(save_optimizer_states=True) while a NON-default
    bucket is active must capture that bucket's fused momentum (not the
    default bucket's stale snapshot)."""
    from mxnet_tpu import config
    from mxnet_tpu.io import DataBatch, DataDesc

    def sym_gen(L):
        data = mx.sym.Variable("data")
        net = mx.sym.mean(data, axis=1)
        net = mx.sym.FullyConnected(net, num_hidden=4, name="fc")
        return (mx.sym.SoftmaxOutput(net, name="softmax"),
                ("data",), ("softmax_label",))

    rng = np.random.RandomState(0)

    def batch(L):
        b = DataBatch(
            data=[mx.nd.array(rng.randn(4, L, 4).astype("f4"))],
            label=[mx.nd.array(rng.randint(0, 4, (4,)).astype("f4"))],
            provide_data=[DataDesc("data", (4, L, 4))],
            provide_label=[DataDesc("softmax_label", (4,))])
        b.bucket_key = L
        return b

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=5)
    mod.bind([DataDesc("data", (4, 5, 4))], [DataDesc("softmax_label", (4,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(kvstore="tpu_sync", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    assert mod._curr_module._fused is not None
    # switch to bucket 3 and train ONLY there: all momentum lives in
    # bucket 3's fused state
    for _ in range(4):
        mod._fit_step(batch(3))
    prefix = str(tmp_path / "bk")
    mod.save_checkpoint(prefix, 1, save_optimizer_states=True)

    states = open(prefix + "-0001.states", "rb").read()
    eager = mx.mod.Module(sym_gen(5)[0])
    eager.bind([DataDesc("data", (4, 5, 4))],
               [DataDesc("softmax_label", (4,))])
    eager.init_params(initializer=mx.initializer.Xavier())
    eager.init_optimizer(kvstore="local", optimizer="sgd",
                         optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.9})
    eager._updater.set_states(states)
    moms = [s.asnumpy() if hasattr(s, "asnumpy") else np.asarray(s)
            for s in eager._updater.states.values() if s is not None]
    assert any(np.abs(m).max() > 0 for m in moms), \
        "saved momentum is all-zero: active bucket's state was lost"


# ---- bf16-native BatchNorm: parity with the f32 reference ------------------
# The bf16 path computes stats as f32-widened dot_general reductions over
# the bf16 activations and normalizes in bf16 (ops/nn.py batch_norm); these
# tests pin it against the unchanged f32 path on bit-identical input values.

def _bn_run(x, gamma, beta, rmean, rvar, training=True, fix_gamma=False):
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.nn import batch_norm

    kw = dict(eps=1e-3, momentum=0.9, fix_gamma=fix_gamma, axis=1,
              _training=training)
    # random target: with a plain sum, dgamma = sum(xhat) ~ 0, and with a
    # pure sum-of-squares, dx cancels analytically (dy lies in the span BN's
    # backward projects out) — either would make the comparison vacuous
    tgt = jnp.asarray(np.random.RandomState(7).randn(*x.shape)
                      .astype("f4"))

    def loss(xx, g, b):
        out = batch_norm(xx, g, b, rmean, rvar, **kw)[0]
        return jnp.sum((out.astype(jnp.float32) - tgt) ** 2)

    grads = jax.grad(loss, argnums=(0, 1, 2))(x, gamma, beta)
    outs = batch_norm(x, gamma, beta, rmean, rvar, **kw)
    return outs, grads


def _rel_err(a, b):
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    return np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-6)


def test_batchnorm_bf16_training_parity():
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    xbf = jnp.asarray(rng.randn(8, 5, 6, 7).astype("f4") * 2 + 1,
                      jnp.bfloat16)
    x32 = xbf.astype(jnp.float32)  # identical values, f32 reference path
    gamma = jnp.asarray(rng.uniform(0.5, 1.5, (5,)).astype("f4"))
    beta = jnp.asarray(rng.randn(5).astype("f4"))
    rmean = jnp.zeros((5,), jnp.float32)
    rvar = jnp.ones((5,), jnp.float32)

    (o_bf, m_bf, v_bf, nm_bf, nv_bf), g_bf = _bn_run(xbf, gamma, beta,
                                                     rmean, rvar)
    (o_32, m_32, v_32, nm_32, nv_32), g_32 = _bn_run(x32, gamma, beta,
                                                     rmean, rvar)
    # output stays in the activation dtype — no hidden upcast
    assert o_bf.dtype == jnp.bfloat16 and o_32.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(o_bf, np.float32),
                               np.asarray(o_32), rtol=0.05, atol=0.05)
    # batch stats and running-stat updates are f32 on both paths and the
    # widened reductions are exact f32 sums of the same values: tight
    for a, b, tol in ((m_bf, m_32, 1e-5), (v_bf, v_32, 1e-4),
                      (nm_bf, nm_32, 1e-5), (nv_bf, nv_32, 1e-4)):
        assert a.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=tol, atol=tol)
    dx_bf, dg_bf, db_bf = g_bf
    dx_32, dg_32, db_32 = g_32
    assert dx_bf.dtype == jnp.bfloat16  # cotangent stays bf16 (no convert)
    assert _rel_err(dx_bf, dx_32) < 0.03
    assert _rel_err(dg_bf, dg_32) < 0.03
    assert _rel_err(db_bf, db_32) < 0.03


def test_batchnorm_bf16_inference_parity():
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    xbf = jnp.asarray(rng.randn(4, 3, 5, 5).astype("f4"), jnp.bfloat16)
    x32 = xbf.astype(jnp.float32)
    gamma = jnp.asarray(rng.uniform(0.5, 1.5, (3,)).astype("f4"))
    beta = jnp.asarray(rng.randn(3).astype("f4"))
    rmean = jnp.asarray(rng.randn(3).astype("f4"))
    rvar = jnp.asarray(rng.uniform(0.5, 2.0, (3,)).astype("f4"))

    (o_bf, _, _, nm_bf, nv_bf), _ = _bn_run(xbf, gamma, beta, rmean, rvar,
                                            training=False)
    (o_32, _, _, _, _), _ = _bn_run(x32, gamma, beta, rmean, rvar,
                                    training=False)
    assert o_bf.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(o_bf, np.float32),
                               np.asarray(o_32), rtol=0.05, atol=0.05)
    # inference must not touch the running stats
    np.testing.assert_array_equal(np.asarray(nm_bf), np.asarray(rmean))
    np.testing.assert_array_equal(np.asarray(nv_bf), np.asarray(rvar))


def test_batchnorm_bf16_fix_gamma_zero_grad():
    import jax.numpy as jnp

    rng = np.random.RandomState(2)
    xbf = jnp.asarray(rng.randn(4, 3, 6).astype("f4"), jnp.bfloat16)
    gamma = jnp.asarray(rng.uniform(0.5, 1.5, (3,)).astype("f4"))
    beta = jnp.zeros((3,), jnp.float32)
    _, (dx, dg, db) = _bn_run(xbf, gamma, beta, jnp.zeros((3,)),
                              jnp.ones((3,)), fix_gamma=True)
    np.testing.assert_array_equal(np.asarray(dg), np.zeros((3,), "f4"))
    assert np.abs(np.asarray(db)).max() > 0  # beta still trains


def test_fused_module_bf16_policy_trains_and_matches_f32():
    """End to end through the fused Module step under the session dtype
    policy (MXNET_COMPUTE_DTYPE=bfloat16): params stay f32 masters, BN
    running stats move, and 2 epochs stay close to the f32 run."""
    from mxnet_tpu import config

    p_32 = _fit("tpu_sync", "sgd", {"learning_rate": 0.05, "momentum": 0.9,
                                    "multi_precision": True}, num_epoch=2)
    with config.override(compute_dtype="bfloat16"):
        p_bf = _fit("tpu_sync", "sgd", {"learning_rate": 0.05,
                                        "momentum": 0.9,
                                        "multi_precision": True},
                    num_epoch=2)
    args_bf, aux_bf = p_bf.get_params()
    args_32, aux_32 = p_32.get_params()
    for k in args_32:
        a = args_bf[k].asnumpy()
        assert np.isfinite(a).all(), k
        assert a.dtype == np.float32, k  # master copies stay f32
        assert _rel_err(a, args_32[k].asnumpy()) < 0.05, k
    # BN running stats updated (and in f32) on the bf16 path
    rm = aux_bf["bn1_moving_mean"].asnumpy()
    assert rm.dtype == np.float32 and not np.allclose(rm, 0)
    rv = aux_bf["bn1_moving_var"].asnumpy()
    assert _rel_err(rv, aux_32["bn1_moving_var"].asnumpy()) < 0.05
