"""Module API tests (parity model: tests/python/unittest/test_module.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def _toy_data(n=256, d=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, classes).astype(np.float32)
    y = (X @ W).argmax(axis=1).astype(np.float32)
    return X, y


def _mlp(classes=4):
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def test_module_fit_and_score():
    X, y = _toy_data()
    train = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.initializer.Xavier())
    train.reset()
    score = dict(mod.score(train, "acc"))
    assert score["accuracy"] > 0.9, score


def test_module_predict():
    X, y = _toy_data(64)
    train = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(train.provide_data, train.provide_label, for_training=False)
    mod.init_params(mx.initializer.Xavier())
    out = mod.predict(train)
    assert out.shape == (64, 4)
    np.testing.assert_allclose(out.asnumpy().sum(axis=1), np.ones(64),
                               rtol=1e-5)


def test_module_checkpoint_roundtrip(tmp_path):
    X, y = _toy_data(64)
    train = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(train.provide_data, train.provide_label)
    mod.init_params(mx.initializer.Xavier())
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 1)
    mod2 = mx.mod.Module.load(prefix, 1, context=mx.cpu())
    mod2.bind(train.provide_data, train.provide_label, for_training=False)
    mod2.init_params(arg_params=mod2._arg_params, aux_params=mod2._aux_params)
    p1 = mod.predict(train).asnumpy()
    train.reset()
    p2 = mod2.predict(train).asnumpy()
    np.testing.assert_allclose(p1, p2, rtol=1e-6)


def test_module_get_set_params():
    X, y = _toy_data(64)
    train = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(train.provide_data, train.provide_label)
    mod.init_params(mx.initializer.Xavier())
    args, auxs = mod.get_params()
    assert "fc1_weight" in args
    args2 = {k: v * 0 for k, v in args.items()}
    mod.set_params(args2, auxs)
    new_args, _ = mod.get_params()
    assert new_args["fc1_weight"].asnumpy().sum() == 0


def test_module_input_grads():
    X, y = _toy_data(32)
    train = mx.io.NDArrayIter(X, y, batch_size=32)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(train.provide_data, train.provide_label, for_training=True,
             inputs_need_grad=True)
    mod.init_params(mx.initializer.Xavier())
    batch = next(iter(train))
    mod.forward(batch, is_train=True)
    mod.backward()
    g = mod.get_input_grads()[0]
    assert np.abs(g.asnumpy()).sum() > 0


def test_module_optimizer_states_roundtrip(tmp_path):
    X, y = _toy_data(64)
    train = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(train.provide_data, train.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    batch = next(iter(train))
    mod.forward_backward(batch)
    mod.update()
    fname = str(tmp_path / "opt.states")
    mod.save_optimizer_states(fname)
    mod.load_optimizer_states(fname)


def test_bucketing_module():
    def sym_gen(seq_len):
        # weights must be bucket-invariant (RNN-unroll pattern): reduce the
        # variable-length axis before the shared FC layers
        data = sym.Variable("data")
        pooled = sym.mean(data, axis=1, keepdims=True)
        net = sym.FullyConnected(pooled, num_hidden=8, name="fc1")
        net = sym.FullyConnected(net, num_hidden=2, name="fc2")
        net = sym.SoftmaxOutput(net, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10,
                                 context=mx.cpu())
    from mxnet_tpu.io import DataDesc, DataBatch
    mod.bind([DataDesc("data", (4, 10))], [DataDesc("softmax_label", (4,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd")
    for key in (10, 5, 10):
        batch = DataBatch(
            data=[mx.nd.ones((4, key))],
            label=[mx.nd.zeros((4,))],
            bucket_key=key,
            provide_data=[DataDesc("data", (4, key))],
            provide_label=[DataDesc("softmax_label", (4,))])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    assert len(mod._buckets) == 2


def test_bucketing_fit_metric_with_multiple_live_buckets():
    """Regression: fit must update the metric BEFORE prepare() switches
    the bucketing module to the next batch's bucket (reference
    base_module.py:528-545 ordering) — with two live buckets the old
    order read a freshly-bound executor with no outputs."""
    rng = np.random.RandomState(0)

    def sym_gen(seq_len):
        # parameters must be bucket-independent (shared across buckets)
        data = sym.Variable("data")
        net = sym.Embedding(data, input_dim=16, output_dim=8, name="embed")
        net = sym.mean(net, axis=1)
        net = sym.FullyConnected(net, num_hidden=2, name="fc")
        return (sym.SoftmaxOutput(net, name="softmax"),
                ("data",), ("softmax_label",))

    class TwoBucketIter(mx.io.DataIter):
        def __init__(self):
            super().__init__(8)
            self.default_bucket_key = 16
            self.provide_data = [("data", (8, 16))]
            self.provide_label = [("softmax_label", (8,))]
            self._i = 0

        def reset(self):
            self._i = 0

        def __iter__(self):
            return self

        def __next__(self):
            if self._i >= 4:
                raise StopIteration
            key = 16 if self._i % 2 == 0 else 10
            self._i += 1
            X = rng.randint(0, 16, (8, key)).astype(np.float32)
            y = (X[:, 0] > 8).astype(np.float32)
            return mx.io.DataBatch(
                data=[mx.nd.array(X)], label=[mx.nd.array(y)],
                bucket_key=key,
                provide_data=[("data", (8, key))],
                provide_label=[("softmax_label", (8,))])

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=16)
    metric_values = []

    def cb(param):
        metric_values.append(param.eval_metric.get()[1])

    mod.fit(TwoBucketIter(), num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1}, eval_metric="acc",
            batch_end_callback=cb)
    assert metric_values and all(0.0 <= v <= 1.0 for v in metric_values)
