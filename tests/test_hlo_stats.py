"""Unit tests for mxnet_tpu.hlo_stats (the chip-free HLO counters shared by
tools/diagnose_step_hlo.py and the convert-budget regression test)."""
import jax
import jax.numpy as jnp
import numpy as np

from mxnet_tpu import hlo_stats as hs

_SYNTHETIC = """
module @jit_f {
  func.func public @main(%arg0: tensor<4x8xf32>) -> tensor<4x8xf32> {
    %0 = stablehlo.convert %arg0 : (tensor<4x8xf32>) -> tensor<4x8xbf16>
    %1 = stablehlo.transpose %0, dims = [1, 0] : (tensor<4x8xbf16>) -> tensor<8x4xbf16>
    %2 = stablehlo.dot_general %0, %1, contracting_dims = [1] x [0] : (tensor<4x8xbf16>, tensor<8x4xbf16>) -> tensor<4x4xbf16>
    %3 = stablehlo.convert %2 : (tensor<4x4xbf16>) -> tensor<4x4xf32>
    %4 = stablehlo.convert %arg0 : (tensor<4x8xf32>) -> tensor<4x8xbf16>
    %5 = stablehlo.add %3, %3 : tensor<4x4xf32>
    return %5 : tensor<4x4xf32>
  }
}
"""


def test_analyze_synthetic_counts():
    st = hs.analyze_stablehlo(_SYNTHETIC)
    assert st["convert_count"] == 3
    assert st["convert_pairs"] == {"f32->bf16": 2, "bf16->f32": 1}
    assert st["transpose_count"] == 1
    assert st["dot_general"] == {"bf16": 1}
    assert st["top_ops"]["add"] == 1
    # element traffic: 2 * 32 f32->bf16, 16 bf16->f32 (in Gelem)
    assert abs(st["convert_gelems"]["f32->bf16"] - 64 / 1e9) < 1e-12


def test_convert_between_helpers():
    st = hs.analyze_stablehlo(_SYNTHETIC)
    assert hs.convert_count_between(st, "f32", "bf16") == 3
    assert hs.convert_count_between(st, "bf16", "f32") == 3  # symmetric
    assert hs.convert_count_between(st, "f32", "f16") == 0
    assert hs.convert_gelems_between(st, "f32", "bf16") > 0


def test_analyze_real_lowering():
    """The counters agree with an actual jax lowering, not just the
    synthetic grammar."""

    def f(x, w):
        return jnp.dot(x.astype(jnp.bfloat16),
                       w.astype(jnp.bfloat16)).astype(jnp.float32)

    text = jax.jit(f).lower(jnp.zeros((4, 8), jnp.float32),
                            jnp.zeros((8, 2), jnp.float32)).as_text()
    st = hs.analyze_stablehlo(text)
    assert hs.convert_count_between(st, "f32", "bf16") == 3
    assert st["dot_general"] == {"bf16": 1}
    assert st["total_ops"] >= 4


_TUPLE_CUSTOM_CALL = """
module @jit_g {
  func.func public @main(%arg0: tensor<4xf32> {tf.aliasing_output = 0 : i32}, %arg1: tensor<2x2xf32>, %arg2: !stablehlo.token) -> tensor<4xf32> {
    %0:2 = stablehlo.custom_call @xla_python_cpu_callback(%arg0) {api_version = 2 : i32} : (tensor<4xf32>) -> (tensor<4xf32>, tensor<4xi32>)
    %1 = stablehlo.custom_call @Sharding(%0#0) : (tensor<4xf32>) -> tensor<4xf32>
    return %1 : tensor<4xf32>
  }
}
"""


def test_entry_params_zero_entry_module():
    """A module with no entry computation returns [] instead of raising
    (found while generalizing hlo_stats into mxlint Layer 2)."""
    assert hs.entry_params("") == []
    assert hs.entry_params("module @jit_empty {\n}\n") == []
    # truncated signature (unbalanced parens) degrades to [] too
    assert hs.entry_params("func.func public @main(%arg0: tensor<") == []


def test_entry_params_parses_donation_and_bytes():
    params = hs.entry_params(_TUPLE_CUSTOM_CALL)
    assert [p["name"] for p in params] == ["%arg0", "%arg1", "%arg2"]
    assert params[0]["donated"] and params[0]["bytes"] == 16
    assert not params[1]["donated"] and params[1]["bytes"] == 16
    # non-tensor (token) params are included but carry no bytes
    assert params[2]["elems"] == 0


def test_custom_call_targets_tuple_returning():
    """Tuple-returning custom calls (``%0:2 = ...``) must not confuse the
    target census."""
    targets = hs.custom_call_targets(_TUPLE_CUSTOM_CALL)
    assert targets == {"xla_python_cpu_callback": 1, "Sharding": 1}
    assert hs.custom_call_targets("") == {}


def test_analyze_stablehlo_empty_module():
    st = hs.analyze_stablehlo("")
    assert st["convert_count"] == 0 and st["total_ops"] == 0


def test_entry_params_real_lowering():
    def step(w, g):
        return w - 0.1 * g

    z = jnp.zeros((16, 16), jnp.float32)
    text = jax.jit(step, donate_argnums=(0,)).lower(z, z).as_text()
    params = hs.entry_params(text)
    assert len(params) == 2
    assert params[0]["donated"] and not params[1]["donated"]
    assert params[0]["bytes"] == 16 * 16 * 4


def test_tool_reexports_shared_impl():
    """tools/diagnose_step_hlo.py must consume the same counters the
    regression test does."""
    import importlib
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        tool = importlib.import_module("diagnose_step_hlo")
    finally:
        sys.path.pop(0)
    assert tool.analyze_stablehlo is hs.analyze_stablehlo
