"""Custom Python operators (mx.operator.CustomOp) — the reference's
custom-op surface (python/mxnet/operator.py, tests/python/unittest/
test_operator.py::test_custom_op), executed eagerly, in the symbolic
executor, hybridized (jit via pure_callback), and with gradients."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import operator as mxop


@mxop.register("sqr")
class SqrProp(mxop.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, shapes, dtypes):
        return Sqr()


class Sqr(mxop.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], in_data[0] * in_data[0])

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0], 2 * in_data[0] * out_grad[0])


@mxop.register("twin")
class TwinProp(mxop.CustomOpProp):
    """Two inputs, two outputs, second output a different shape."""

    def list_arguments(self):
        return ["a", "b"]

    def list_outputs(self):
        return ["sum", "total"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0], [1]], []

    def create_operator(self, ctx, shapes, dtypes):
        return Twin()


class Twin(mxop.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        s = in_data[0] + in_data[1]
        self.assign(out_data[0], req[0], s)
        self.assign(out_data[1], req[1], s.sum().reshape((1,)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        g = out_grad[0] + out_grad[1].reshape(())  # broadcast scalar
        self.assign(in_grad[0], req[0], g)
        self.assign(in_grad[1], req[1], g)


def test_eager_forward_backward():
    x = mx.nd.array(np.array([[1., 2.], [3., 4.]], np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.Custom(x, op_type="sqr")
        loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy() ** 2)
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_eager_multi_io():
    a = mx.nd.ones((2, 3))
    b = mx.nd.ones((2, 3)) * 2
    a.attach_grad()
    with mx.autograd.record():
        s, tot = mx.nd.Custom(a, b, op_type="twin")
        loss = s.sum() + tot.sum()
    loss.backward()
    np.testing.assert_allclose(s.asnumpy(), 3 * np.ones((2, 3)))
    np.testing.assert_allclose(tot.asnumpy(), [18.0])
    np.testing.assert_allclose(a.grad.asnumpy(), 2 * np.ones((2, 3)))


def test_symbolic_executor():
    data = mx.sym.Variable("data")
    y = mx.sym.Custom(data, op_type="sqr", name="sq")
    # shape inference runs the Prop's infer_shape, not the python body
    args, outs, _ = y.infer_shape(data=(4, 5))
    assert outs[0] == (4, 5)
    from mxnet_tpu.executor import simple_bind
    ex = simple_bind(y, mx.cpu(), data=(4, 5))
    x = np.random.RandomState(0).randn(4, 5).astype(np.float32)
    ex.arg_dict["data"][:] = x
    out = ex.forward(is_train=True)[0]
    np.testing.assert_allclose(out.asnumpy(), x ** 2, rtol=1e-6)
    ex.backward(out_grads=mx.nd.ones((4, 5)))
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(), 2 * x,
                               rtol=1e-6)


def test_custom_in_module_fit():
    """Custom op inside a full compiled training step (fused program +
    pure_callback escape)."""
    data = mx.sym.Variable("data")
    net = mx.sym.Custom(data, op_type="sqr")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(0)
    X = rng.randn(32, 6).astype(np.float32)
    Y = rng.randint(0, 4, (32,)).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=8, label_name="softmax_label")
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=2, kvstore="tpu_sync", optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    assert mod._fused is not None  # ran inside the one-program step


def test_hybridized_gluon_block():
    from mxnet_tpu import gluon

    class Net(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.Custom(x, op_type="sqr")

    net = Net()
    net.hybridize()
    x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = net(x)
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy() ** 2)


def test_stateful_custom_op():
    """Forward stashes state; backward uses it (reference per-executor
    operator instance semantics)."""
    @mxop.register("stateful_scale")
    class StatefulProp(mxop.CustomOpProp):
        def create_operator(self, ctx, shapes, dtypes):
            return Stateful()

    class Stateful(mxop.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self._saved = in_data[0].asnumpy().copy()
            self.assign(out_data[0], req[0], in_data[0] * 3)

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            assert hasattr(self, "_saved")  # same instance as forward
            self.assign(in_grad[0], req[0], out_grad[0] * 3)

    x = mx.nd.ones((2, 2))
    x.attach_grad()
    with mx.autograd.record():
        y = mx.nd.Custom(x, op_type="stateful_scale")
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 3 * np.ones((2, 2)))


def test_unregistered_op_type_raises():
    with pytest.raises(KeyError):
        mx.nd.Custom(mx.nd.ones((2,)), op_type="never_registered")


def test_interleaved_stateful_instances():
    """Two same-shape forwards before their backwards must NOT share one
    operator instance (round-3 review finding: a shared cache corrupted
    stashed state)."""
    @mxop.register("stash_mul")
    class StashProp(mxop.CustomOpProp):
        def create_operator(self, ctx, shapes, dtypes):
            return StashMul()

    class StashMul(mxop.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self._x = in_data[0].asnumpy().copy()
            self.assign(out_data[0], req[0], in_data[0])

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            # grad = stashed forward input (detects cross-talk)
            self.assign(in_grad[0], req[0], mx.nd.array(self._x))

    x1 = mx.nd.array(np.full((2,), 2.0, np.float32)); x1.attach_grad()
    x2 = mx.nd.array(np.full((2,), 5.0, np.float32)); x2.attach_grad()
    with mx.autograd.record():
        y1 = mx.nd.Custom(x1, op_type="stash_mul")
        y2 = mx.nd.Custom(x2, op_type="stash_mul")  # same shape, later fwd
    y1.backward()
    y2.backward()
    np.testing.assert_allclose(x1.grad.asnumpy(), [2.0, 2.0])
    np.testing.assert_allclose(x2.grad.asnumpy(), [5.0, 5.0])


def test_unhashable_kwargs():
    @mxop.register("kw_shape")
    class KwProp(mxop.CustomOpProp):
        def __init__(self, shape="(1,)"):
            super().__init__()
            self._shape = eval(shape)
        def infer_shape(self, in_shape):
            return in_shape, [list(self._shape)], []
        def create_operator(self, ctx, shapes, dtypes):
            return KwOp(self._shape)

    class KwOp(mxop.CustomOp):
        def __init__(self, shape):
            self._shape = shape
        def forward(self, is_train, req, in_data, out_data, aux):
            self.assign(out_data[0], req[0],
                        mx.nd.ones(self._shape) * in_data[0].sum())

    y = mx.nd.Custom(mx.nd.ones((3,)), op_type="kw_shape", shape=[2, 2])
    assert y.shape == (2, 2)
    np.testing.assert_allclose(y.asnumpy(), 3 * np.ones((2, 2)))


def test_custom_op_exception_propagates_to_sync_point():
    """A Python error inside a custom op must reach the CALLER as an
    exception, not hang or corrupt state (reference test_exc_handling.py
    semantics: async worker errors rethrow at sync points). Also: the
    session stays usable afterwards."""

    class Exploding(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            raise RuntimeError("boom from custom op")

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            pass

    @mx.operator.register("_test_exploding")
    class ExplodingProp(mx.operator.CustomOpProp):
        def list_arguments(self):
            return ["data"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            return Exploding()

    x = mx.nd.ones((2, 2))
    with pytest.raises(Exception) as ei:
        out = mx.nd.Custom(x, op_type="_test_exploding")
        out.asnumpy()          # sync point at the latest
    assert "boom" in str(ei.value)
    # engine/session still healthy after the failure
    np.testing.assert_allclose((x + 1).asnumpy(), 2.0)
