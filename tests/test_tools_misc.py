"""Tests for the small reference-parity utility tools (reference tools/:
parse_log.py, rec2idx.py, flakiness_checker.py, diagnose.py) and the
MXNET_TEST_SEED replay contract of test_utils.with_seed."""
import os
import subprocess
import sys

import numpy as np

TOP = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(TOP, "tools")
sys.path.insert(0, TOOLS)


def test_parse_log_markdown(tmp_path):
    log = tmp_path / "train.log"
    log.write_text(
        "INFO:root:Epoch[0] Train-accuracy=0.500000\n"
        "INFO:root:Epoch[0] Time cost=12.000\n"
        "INFO:root:Epoch[0] Validation-accuracy=0.550000\n"
        "INFO:root:Epoch[1] Train-accuracy=0.700000\n"
        "INFO:root:Epoch[1] Time cost=10.000\n"
        "INFO:root:Epoch[1] Validation-accuracy=0.650000\n")
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "parse_log.py"), str(log)],
        capture_output=True, text=True, check=True).stdout
    lines = out.strip().splitlines()
    assert lines[0].startswith("| epoch | train-accuracy | val-accuracy")
    assert "| 0.500000 | 0.550000 | 12.0 |" in lines[2]
    assert "| 0.700000 | 0.650000 | 10.0 |" in lines[3]


def test_rec2idx_roundtrip(tmp_path):
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "t.rec")
    idx_path = str(tmp_path / "t.idx")
    w = recordio.MXRecordIO(rec_path, "w")
    payloads = [b"a" * 10, b"bb" * 20, b"ccc" * 30]
    for p in payloads:
        w.write(p)
    w.close()

    subprocess.run([sys.executable, os.path.join(TOOLS, "rec2idx.py"),
                    rec_path, idx_path], capture_output=True, check=True)

    r = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    assert sorted(r.keys) == [0, 1, 2]
    for i, p in enumerate(payloads):
        assert r.read_idx(i) == p
    r.close()


def test_flakiness_checker_spec_parsing():
    import flakiness_checker as fc
    path, name = fc.parse_spec("tests/test_tools_misc.py::test_parse_log")
    assert path.endswith("test_tools_misc.py") and name == "test_parse_log"
    path, name = fc.parse_spec("test_tools_misc.test_rec2idx_roundtrip")
    assert path.endswith("test_tools_misc.py")
    assert name == "test_rec2idx_roundtrip"


def test_diagnose_runs():
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "diagnose.py"),
         "--device", "0", "--hardware", "0", "--network", "0"],
        capture_output=True, text=True, timeout=180,
        env=dict(os.environ, JAX_PLATFORMS="cpu")).stdout
    assert "Python Info" in out
    assert "mxnet_tpu Info" in out
    assert "Version" in out


def test_with_seed_env_replay():
    from mxnet_tpu import test_utils
    import mxnet_tpu as mx

    @test_utils.with_seed()
    def draw():
        return mx.nd.random.uniform(shape=(4,)).asnumpy()

    os.environ["MXNET_TEST_SEED"] = "12345"
    try:
        a, b = draw(), draw()
        np.testing.assert_array_equal(a, b)  # pinned seed -> same stream
    finally:
        del os.environ["MXNET_TEST_SEED"]
    # explicit seed argument still wins
    @test_utils.with_seed(7)
    def draw7():
        return mx.nd.random.uniform(shape=(4,)).asnumpy()
    c, d = draw7(), draw7()
    np.testing.assert_array_equal(c, d)


def test_check_symbolic_forward_backward_harness():
    # the reference-parity symbolic checkers drive bind/forward/backward
    import mxnet_tpu as mx
    from mxnet_tpu import test_utils

    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = a * b + a
    x = np.array([[1.0, 2.0], [3.0, 4.0]], "f4")
    y = np.array([[5.0, 6.0], [7.0, 8.0]], "f4")
    test_utils.check_symbolic_forward(out, [x, y], [x * y + x])
    og = np.ones((2, 2), "f4")
    test_utils.check_symbolic_backward(out, [x, y], [og],
                                       {"a": y + 1, "b": x})
    # list-form expected and None skips
    test_utils.check_symbolic_backward(out, [x, y], [og], [y + 1, None])
    # mismatched grads must raise
    import pytest as _pytest
    with _pytest.raises(AssertionError):
        test_utils.check_symbolic_backward(out, [x, y], [og], {"a": y})
