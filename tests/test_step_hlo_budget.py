"""Chip-free HLO regression budget for the benched fused ResNet-50 step.

The round-5 diagnosis found 766 bf16<->f32 converts (~2.75 Gelem per
direction) in the lowered train step — one f32 round-trip of every BN
activation, fwd and bwd. The bf16-native BatchNorm (ops/nn.py) plus the
grouped parameter downcast (module/fused.py) eliminate them at the trace
level, so the pre-optimization StableHLO — deterministic on CPU — is the
regression surface: if a change reintroduces per-tensor round-trips, the
convert count jumps by hundreds and this test fails without ever needing
the chip.

Budget: <= 120 bf16<->f32 converts (measured 111 at time of writing:
109 f32->bf16 one-per-parameter-ish small casts + 2 from the grouped
downcast pair), versus 766 before.
"""
import numpy as np
import pytest

BUDGET = 120


@pytest.fixture(scope="module")
def step_stats(resnet_step_text):
    # the lowering itself is the session-scoped `resnet_step_text`
    # fixture (tests/conftest.py), shared with the MXL505 fusion-bytes
    # ratchet in test_lint_clean.py
    from mxnet_tpu import hlo_stats as hs
    return hs.analyze_stablehlo(resnet_step_text)


def test_convert_budget(step_stats):
    from mxnet_tpu import hlo_stats as hs
    n = hs.convert_count_between(step_stats, "f32", "bf16")
    assert n <= BUDGET, (
        "bf16<->f32 converts regressed: %d > budget %d (was 766 before "
        "the bf16-native BatchNorm; pairs=%r). A jump by ~100s means "
        "some path is round-tripping activations through f32 again."
        % (n, BUDGET, step_stats["convert_pairs"]))
    # and the traffic through them stays negligible (< 0.2 Gelem total
    # vs ~5.5 Gelem before)
    assert hs.convert_gelems_between(step_stats, "f32", "bf16") < 0.2


def test_convolutions_stay_bf16(step_stats):
    """Every convolution (fwd + both bwd passes) must hit the MXU in
    bf16 — an f32 conv means the dtype policy broke upstream of it."""
    assert set(step_stats["convolution"]) == {"bf16"}
    assert step_stats["convolution"]["bf16"] >= 150  # 53 convs x 3 passes


def test_no_layout_transposes(step_stats):
    """NCHW stays native: no transpose blowup from the policy change."""
    assert step_stats["transpose_count"] <= 6
