"""mxlint unit tests: every rule catches a seeded bug and passes on the
corrected version; the baseline ratchet only tightens; the CLI exit codes
hold. All chip-free — Layer 1 never imports jax, Layer 2 lowers under the
CPU platform the suite already pins."""
import json
import os
import sys

import pytest

from mxnet_tpu.analysis import baseline as baseline_mod
from mxnet_tpu.analysis import lint_sources
from mxnet_tpu.analysis import hlo_passes
from mxnet_tpu.analysis.runner import lint_paths

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))
import mxlint as mxlint_cli  # noqa: E402

sys.path.pop(0)


def _rules(src, path="fix.py"):
    return sorted({d.rule for d in lint_sources({path: src})})


def _diags(src, path="fix.py"):
    return lint_sources({path: src})


# ---------------------------------------------------------------- layer 1

class TestHostSyncRules:
    def test_asnumpy_in_jitted_body_fires(self):
        bad = (
            "import jax\n"
            "def step(params, batch):\n"
            "    h = batch.asnumpy()\n"
            "    return params\n"
            "train = jax.jit(step)\n")
        assert "MXL101" in _rules(bad)

    def test_device_get_in_scanned_body_fires(self):
        bad = (
            "import jax\n"
            "from jax import lax\n"
            "def body(carry, x):\n"
            "    v = jax.device_get(x)\n"
            "    return carry, v\n"
            "def run(xs):\n"
            "    return lax.scan(body, 0, xs)\n")
        assert "MXL101" in _rules(bad)

    def test_np_asarray_in_fused_decorated_fires(self):
        bad = (
            "import numpy as np\n"
            "def fused(f):\n"
            "    return f\n"
            "@fused\n"
            "def step(x):\n"
            "    return np.asarray(x)\n")
        assert "MXL101" in _rules(bad)

    def test_float_coercion_fires_and_corrected_passes(self):
        bad = (
            "import jax\n"
            "def step(x):\n"
            "    return float(x) * 2\n"
            "f = jax.jit(step)\n")
        good = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def step(x):\n"
            "    return x.astype(jnp.float32) * 2\n"
            "f = jax.jit(step)\n")
        assert "MXL102" in _rules(bad)
        assert _rules(good) == []

    def test_asnumpy_outside_traced_body_is_fine(self):
        good = (
            "def evaluate(out):\n"
            "    return out.asnumpy().sum()\n")
        assert _rules(good) == []

    def test_unbatched_loop_fetch_fires_and_batched_passes(self):
        bad = (
            "import jax\n"
            "def loop(batches, f):\n"
            "    for b in batches:\n"
            "        out = f(b)\n"
            "        x = out[0].asnumpy()\n"
            "        y = out[1].asnumpy()\n")
        good = (
            "import jax\n"
            "def loop(batches, f):\n"
            "    for b in batches:\n"
            "        out = f(b)\n"
            "        x, y = jax.device_get((out[0], out[1]))\n")
        assert "MXL103" in _rules(bad)
        assert _rules(good) == []


class TestRetraceRules:
    def test_python_branch_on_traced_fires(self):
        bad = (
            "import jax\n"
            "def step(x):\n"
            "    if x > 0:\n"
            "        return x\n"
            "    return -x\n"
            "f = jax.jit(step)\n")
        assert "MXL201" in _rules(bad)

    def test_branch_on_tainted_local_fires(self):
        bad = (
            "import jax\n"
            "def step(batch):\n"
            "    x = batch['data'] * 2\n"
            "    if x.sum() > 0:\n"
            "        x = -x\n"
            "    return x\n"
            "f = jax.jit(step)\n")
        assert "MXL201" in _rules(bad)

    def test_branch_on_shape_or_none_passes(self):
        good = (
            "import jax\n"
            "def step(x, state):\n"
            "    if x.shape[0] > 4:\n"
            "        x = x[:4]\n"
            "    if state is not None and x.ndim == 2:\n"
            "        x = x + state\n"
            "    return x\n"
            "f = jax.jit(step)\n")
        assert _rules(good) == []

    def test_branch_on_dict_key_comprehension_passes(self):
        # dict keys are static pytree structure under jit — the fused
        # Module's per-group downcast filter must stay clean
        good = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def step(d):\n"
            "    cast = [k for k, v in d.items()\n"
            "            if v.dtype == jnp.float32 and v.size > 0]\n"
            "    if cast:\n"
            "        pass\n"
            "    return d\n"
            "f = jax.jit(step)\n")
        assert _rules(good) == []

    def test_branch_on_traced_dict_value_in_comprehension_fires(self):
        bad = (
            "import jax\n"
            "def step(d):\n"
            "    pos = [v for k, v in d.items() if v > 0]\n"
            "    return d\n"
            "f = jax.jit(step)\n")
        assert "MXL201" not in _rules(bad)  # comprehension itself is fine
        bad2 = (
            "import jax\n"
            "def step(d):\n"
            "    total = sum(v.sum() for k, v in d.items())\n"
            "    if total > 0:\n"
            "        pass\n"
            "    return d\n"
            "f = jax.jit(step)\n")
        assert "MXL201" in _rules(bad2)

    def test_fstring_of_traced_value_fires_and_shape_passes(self):
        bad = (
            "import jax\n"
            "def step(x):\n"
            "    name = f'val={x}'\n"
            "    return x\n"
            "f = jax.jit(step)\n")
        good = (
            "import jax\n"
            "def step(x):\n"
            "    name = f'shape={x.shape}'\n"
            "    return x\n"
            "f = jax.jit(step)\n")
        assert "MXL202" in _rules(bad)
        assert _rules(good) == []

    def test_unhashable_static_arg_fires_and_tuple_passes(self):
        bad = (
            "import jax\n"
            "def step(x, dims):\n"
            "    return x\n"
            "f = jax.jit(step, static_argnums=(1,))\n"
            "def run(x):\n"
            "    return f(x, [1, 2])\n")
        good = bad.replace("[1, 2]", "(1, 2)")
        assert "MXL203" in _rules(bad)
        assert _rules(good) == []

    def test_unhashable_static_argname_fires(self):
        bad = (
            "import jax\n"
            "def step(x, dims=None):\n"
            "    return x\n"
            "f = jax.jit(step, static_argnames=('dims',))\n"
            "def run(x):\n"
            "    return f(x, dims={'a': 1})\n")
        assert "MXL203" in _rules(bad)


class TestDonationRule:
    BAD = (
        "import jax\n"
        "def step(params, grads):\n"
        "    return params\n"
        "train = jax.jit(step, donate_argnums=(0,))\n"
        "def loop(params, grads):\n"
        "    out = train(params, grads)\n"
        "    norm = params.sum()\n"      # use-after-donation
        "    return out, norm\n")
    GOOD = (
        "import jax\n"
        "def step(params, grads):\n"
        "    return params\n"
        "train = jax.jit(step, donate_argnums=(0,))\n"
        "def loop(params, grads):\n"
        "    params = train(params, grads)\n"   # rebind: buffer is new
        "    norm = params.sum()\n"
        "    return params, norm\n")

    def test_use_after_donation_fires(self):
        assert "MXL301" in _rules(self.BAD)

    def test_rebind_after_donation_passes(self):
        assert _rules(self.GOOD) == []

    def test_method_style_wrapper_tracked(self):
        bad = (
            "import jax\n"
            "class T:\n"
            "    def __init__(self, step):\n"
            "        self._jitted = jax.jit(step, donate_argnums=(0,))\n"
            "    def run(self, params, batch):\n"
            "        out = self._jitted(params, batch)\n"
            "        stale = params\n"
            "        return out, stale\n")
        assert "MXL301" in _rules(bad)


class TestLockRules:
    def test_blocking_queue_put_under_lock_fires(self):
        bad = (
            "import threading, queue\n"
            "_lock = threading.Lock()\n"
            "_q = queue.Queue()\n"
            "def produce(x):\n"
            "    with _lock:\n"
            "        _q.put(x)\n")
        assert "MXL401" in _rules(bad)

    def test_device_get_under_lock_fires_and_outside_passes(self):
        bad = (
            "import jax, threading\n"
            "class Cache:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def fetch(self, arr):\n"
            "        with self._lock:\n"
            "            return jax.device_get(arr)\n")
        good = (
            "import jax, threading\n"
            "class Cache:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def fetch(self, arr):\n"
            "        host = jax.device_get(arr)\n"
            "        with self._lock:\n"
            "            self.last = host\n"
            "        return host\n")
        assert "MXL401" in _rules(bad)
        assert _rules(good) == []

    def test_condition_wait_is_not_blocking(self):
        # Condition.wait releases the lock while sleeping — the
        # admission-queue pattern must stay clean
        good = (
            "import threading\n"
            "class Q:\n"
            "    def __init__(self):\n"
            "        self._cond = threading.Condition()\n"
            "    def take(self):\n"
            "        with self._cond:\n"
            "            while not self.items:\n"
            "                self._cond.wait(0.1)\n"
            "            return self.items.pop()\n")
        assert _rules(good) == []

    def test_nonblocking_put_passes(self):
        good = (
            "import threading, queue\n"
            "_lock = threading.Lock()\n"
            "_q = queue.Queue()\n"
            "def produce(x):\n"
            "    with _lock:\n"
            "        _q.put(x, block=False)\n")
        assert _rules(good) == []

    def test_inconsistent_lock_order_across_files_fires(self):
        a = (
            "import threading\n"
            "a_lock = threading.Lock()\n"
            "b_lock = threading.Lock()\n"
            "def f():\n"
            "    with a_lock:\n"
            "        with b_lock:\n"
            "            pass\n")
        b = (
            "from mod_a import a_lock, b_lock\n"
            "def g():\n"
            "    with b_lock:\n"
            "        with a_lock:\n"
            "            pass\n")
        diags = lint_sources({"mod_a.py": a, "mod_b.py": b})
        assert {d.rule for d in diags} == {"MXL402"}
        assert {d.path for d in diags} == {"mod_a.py", "mod_b.py"}

    def test_consistent_lock_order_passes(self):
        a = (
            "import threading\n"
            "a_lock = threading.Lock()\n"
            "b_lock = threading.Lock()\n"
            "def f():\n"
            "    with a_lock:\n"
            "        with b_lock:\n"
            "            pass\n"
            "def g():\n"
            "    with a_lock:\n"
            "        with b_lock:\n"
            "            pass\n")
        assert _rules(a) == []


class TestTelemetryDiscipline:
    def test_raw_profiler_counter_fires(self):
        bad = (
            "from mxnet_tpu import profiler\n"
            "def publish(depth):\n"
            "    profiler.record_counter('serve/queue_depth', depth)\n")
        assert "MXL506" in _rules(bad)

    def test_registry_path_and_slash_free_names_pass(self):
        # the registry's own trace mirror is the sanctioned caller, and
        # slash-free names are not registry-owned series
        mirror = (
            "from mxnet_tpu import profiler\n"
            "def _mirror_to_trace(name, value):\n"
            "    profiler.record_counter(name, value)\n")
        assert "MXL506" not in _rules(
            mirror, path="mxnet_tpu/telemetry/registry.py")
        plain = (
            "from mxnet_tpu import profiler\n"
            "def publish(n):\n"
            "    profiler.record_counter('lintdebt', n)\n")
        assert "MXL506" not in _rules(plain)

    def test_registry_publish_passes(self):
        good = (
            "from mxnet_tpu import telemetry\n"
            "def publish(depth):\n"
            "    telemetry.gauge('serve/queue_depth').set(depth)\n")
        assert _rules(good) == []


class TestStagedFeedRule:
    def test_device_put_in_step_loop_fires(self):
        bad = (
            "import jax\n"
            "def train(batches, step):\n"
            "    for b in batches:\n"
            "        x = jax.device_put(b)\n"
            "        step(x)\n")
        assert "MXL513" in _rules(bad)

    def test_nd_array_feed_in_fit_loop_fires(self):
        bad = (
            "from mxnet_tpu.ndarray import ndarray as _nd\n"
            "def train(mod, arrays):\n"
            "    for a in arrays:\n"
            "        batch = _nd.array(a)\n"
            "        mod._fit_step(batch)\n")
        assert "MXL513" in _rules(bad)

    def test_feed_without_step_dispatch_passes(self):
        # fused.stack_feeds' shape: per-name device_put in a loop with no
        # step dispatch is staging, not a hand-rolled train loop
        good = (
            "import jax\n"
            "def stage(feeds):\n"
            "    out = {}\n"
            "    for name in feeds:\n"
            "        out[name] = jax.device_put(feeds[name])\n"
            "    return out\n")
        assert "MXL513" not in _rules(good)

    def test_staged_loop_passes(self):
        # consuming pre-staged windows: no per-batch feed in the loop
        good = (
            "def train(feed, mod):\n"
            "    while True:\n"
            "        win = feed.next_window()\n"
            "        mod._fit_step(win)\n")
        assert "MXL513" not in _rules(good)


# ---------------------------------------------------------------- layer 3

class TestUnguardedSharedWrite:
    """MXL601: attribute shared across thread contexts, mixed lock
    discipline."""

    BAD = (
        "import threading\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.pending = []\n"
        "        self._t = threading.Thread(target=self._loop)\n"
        "        self._t.start()\n"
        "    def _loop(self):\n"
        "        while True:\n"
        "            with self._lock:\n"
        "                self.pending.append(1)\n"
        "    def drain(self):\n"
        "        out = list(self.pending)\n"
        "        self.pending = []\n"
        "        return out\n")

    def test_unlocked_caller_access_fires(self):
        diags = [d for d in _diags(self.BAD) if d.rule == "MXL601"]
        assert len(diags) == 1
        assert diags[0].symbol == "Box.pending"

    def test_locked_everywhere_passes(self):
        good = self.BAD.replace(
            "    def drain(self):\n"
            "        out = list(self.pending)\n"
            "        self.pending = []\n"
            "        return out\n",
            "    def drain(self):\n"
            "        with self._lock:\n"
            "            out = list(self.pending)\n"
            "            self.pending = []\n"
            "        return out\n")
        assert "MXL601" not in _rules(good)

    def test_single_owner_convention_passes(self):
        # never-locked loop state driven from one thread: not a race
        src = (
            "import threading\n"
            "class Loop:\n"
            "    def __init__(self):\n"
            "        self.steps = 0\n"
            "        self._t = threading.Thread(target=self.run_loop)\n"
            "    def run_loop(self):\n"
            "        self.steps += 1\n")
        assert "MXL601" not in _rules(src)


class TestBlockingUnderFleetLock:
    """MXL602: fsync / journal append / socket / sleep inside a
    critical section."""

    def test_fsync_under_lock_fires(self):
        bad = (
            "import os, threading\n"
            "class Journal:\n"
            "    def __init__(self, fh):\n"
            "        self._lock = threading.Lock()\n"
            "        self._fh = fh\n"
            "    def append(self, rec):\n"
            "        with self._lock:\n"
            "            self._fh.write(rec)\n"
            "            os.fsync(self._fh.fileno())\n")
        assert "MXL602" in _rules(bad)

    def test_fsync_outside_lock_passes(self):
        good = (
            "import os, threading\n"
            "class Journal:\n"
            "    def __init__(self, fh):\n"
            "        self._lock = threading.Lock()\n"
            "        self._fh = fh\n"
            "    def append(self, rec):\n"
            "        with self._lock:\n"
            "            self._fh.write(rec)\n"
            "        os.fsync(self._fh.fileno())\n")
        assert "MXL602" not in _rules(good)

    def test_journal_append_under_lock_fires(self):
        bad = (
            "class Router:\n"
            "    def set_split(self, model, split):\n"
            "        with self._lock:\n"
            "            self._journal_append('split', {'m': model})\n"
            "            self.table = split\n")
        assert "MXL602" in _rules(bad)

    def test_set_split_pattern_passes(self):
        # journal first (outside the lock), then mutate under it
        good = (
            "class Router:\n"
            "    def set_split(self, model, split):\n"
            "        self._journal_append('split', {'m': model})\n"
            "        with self._lock:\n"
            "            self.table = split\n")
        assert "MXL602" not in _rules(good)

    def test_sleep_under_lock_fires(self):
        bad = (
            "import threading, time\n"
            "_lock = threading.Lock()\n"
            "def poke():\n"
            "    with _lock:\n"
            "        time.sleep(0.1)\n")
        assert "MXL602" in _rules(bad)


class TestWallClockLiveness:
    """MXL603: time.time() feeding liveness/lease/backoff deadlines."""

    def test_wall_clock_deadline_fires(self):
        bad = (
            "import time\n"
            "def lease():\n"
            "    deadline = time.time() + 5.0\n"
            "    return deadline\n")
        assert "MXL603" in _rules(bad)

    def test_monotonic_deadline_passes(self):
        good = (
            "import time\n"
            "def lease():\n"
            "    deadline = time.monotonic() + 5.0\n"
            "    return deadline\n")
        assert "MXL603" not in _rules(good)

    def test_wall_clock_lease_compare_fires(self):
        bad = (
            "import time\n"
            "class Registry:\n"
            "    def check(self, rec):\n"
            "        return time.time() < rec.lease_expiry\n")
        assert "MXL603" in _rules(bad)

    def test_wall_clock_in_liveness_fn_fires(self):
        bad = (
            "import time\n"
            "def sweep_dead(registry):\n"
            "    now = time.time()\n"
            "    return [r for r in registry if r.t < now]\n")
        assert "MXL603" in _rules(bad)

    def test_wall_clock_log_stamp_passes(self):
        # wall clock is fine for log timestamps
        good = (
            "import time\n"
            "def log_stamp():\n"
            "    return time.time()\n")
        assert "MXL603" not in _rules(good)


class TestJournalFirst:
    """MXL604: control-route mutations must journal first, required."""

    HANDLER = (
        "class Handler:\n"
        "    def do_POST(self):\n"
        "        payload = self._read_json()\n"
        "        if self.path.startswith('/fleet/split'):\n"
        "            self.router.set_split(payload['m'], payload['s'])\n")

    def test_mutate_before_append_fires(self):
        bad = (
            "class Router:\n"
            "    def _journal_append(self, kind, rec, required=False):\n"
            "        self._journal.append((kind, rec))\n"
            "    def set_split(self, model, split):\n"
            "        self.splits[model] = split\n"
            "        self._journal_append('split', {'m': model},\n"
            "                             required=True)\n"
            + self.HANDLER)
        diags = [d for d in _diags(bad) if d.rule == "MXL604"]
        assert diags and "mutated before" in diags[0].message

    def test_append_without_required_fires(self):
        bad = (
            "class Router:\n"
            "    def _journal_append(self, kind, rec, required=False):\n"
            "        self._journal.append((kind, rec))\n"
            "    def set_split(self, model, split):\n"
            "        self._journal_append('split', {'m': model})\n"
            "        self.splits[model] = split\n"
            + self.HANDLER)
        diags = [d for d in _diags(bad) if d.rule == "MXL604"]
        assert diags and "required=True" in diags[0].message

    def test_journal_first_required_passes(self):
        good = (
            "class Router:\n"
            "    def _journal_append(self, kind, rec, required=False):\n"
            "        self._journal.append((kind, rec))\n"
            "    def set_split(self, model, split):\n"
            "        self._journal_append('split', {'m': model},\n"
            "                             required=True)\n"
            "        with self._lock:\n"
            "            self.splits[model] = split\n"
            + self.HANDLER)
        assert "MXL604" not in _rules(good)


class TestEpochFencing:
    """MXL605: state-mutating control routes must check the fence."""

    ROUTES = (
        "        if self.path.startswith('/fleet/split'):\n"
        "            self.router.set_split(payload)\n"
        "        elif self.path.startswith('/admin/drain'):\n"
        "            self.router.drain()\n")

    def test_unfenced_routes_fire(self):
        bad = (
            "class Handler:\n"
            "    def do_POST(self):\n"
            "        payload = self._read_json()\n"
            + self.ROUTES)
        diags = [d for d in _diags(bad) if d.rule == "MXL605"]
        assert len(diags) == 2

    def test_preamble_fence_covers_every_route(self):
        good = (
            "class Handler:\n"
            "    def do_POST(self):\n"
            "        payload = self._read_json()\n"
            "        if self.path.startswith(('/fleet/', '/admin/')) \\\n"
            "                and not self._fence(payload):\n"
            "            return\n"
            + self.ROUTES)
        assert "MXL605" not in _rules(good)

    def test_in_branch_fence_passes(self):
        good = (
            "class Handler:\n"
            "    def do_POST(self):\n"
            "        payload = self._read_json()\n"
            "        if self.path.startswith('/fleet/split'):\n"
            "            if not self._fence(payload):\n"
            "                return\n"
            "            self.router.set_split(payload)\n")
        assert "MXL605" not in _rules(good)


class TestPayloadDeterminism:
    """MXL606: journaled/dispatched payloads must replay bitwise."""

    def test_set_and_wall_clock_payload_fires(self):
        bad = (
            "import time\n"
            "class Router:\n"
            "    def record(self, replicas):\n"
            "        rec = {'replicas': {r for r in replicas},\n"
            "               'ts': time.time()}\n"
            "        self._journal_append('epoch', rec, required=True)\n")
        diags = [d for d in _diags(bad) if d.rule == "MXL606"]
        assert len(diags) == 2

    def test_sorted_payload_passes(self):
        good = (
            "class Router:\n"
            "    def record(self, replicas, stamp):\n"
            "        rec = {'replicas': sorted(replicas),\n"
            "               'stamp': stamp}\n"
            "        self._journal_append('epoch', rec, required=True)\n")
        assert "MXL606" not in _rules(good)

    def test_rng_draw_in_dispatch_fires(self):
        bad = (
            "import random\n"
            "def dispatch(rng, payload):\n"
            "    dispatch_payload({'jitter': rng.uniform(0, 1)})\n")
        assert "MXL606" in _rules(bad)


def test_parse_error_is_a_diagnostic_not_a_crash():
    diags = _diags("def broken(:\n")
    assert [d.rule for d in diags] == ["MXL001"]


# ------------------------------------------------------------ diagnostics

def test_baseline_key_is_line_number_free():
    """Inserting code above a violation must not churn its baseline key."""
    bad = (
        "import jax\n"
        "def step(x):\n"
        "    return float(x)\n"
        "f = jax.jit(step)\n")
    shifted = "import os\n\n\n" + bad
    k1 = [d.key() for d in _diags(bad)]
    k2 = [d.key() for d in _diags(shifted)]
    assert k1 == k2 and len(k1) == 1
    assert "::step#0" in k1[0]


def test_diagnostic_payload_fields():
    d = _diags("import jax\n"
               "def step(x):\n"
               "    return float(x)\n"
               "f = jax.jit(step)\n")[0]
    payload = d.to_dict()
    for field in ("rule", "path", "line", "col", "severity", "symbol",
                  "message", "hint", "key"):
        assert field in payload
    assert payload["line"] == 3
    assert "float" in d.format()


# ---------------------------------------------------------------- layer 2

@pytest.fixture(scope="module")
def lowerings():
    import jax
    import jax.numpy as jnp
    import numpy as np

    w = np.zeros((256, 256), np.float32)
    g = np.zeros((256, 256), np.float32)

    def sgd(w, g):
        # two outputs so BOTH donated inputs have a buffer to alias
        return w - 0.1 * g, g * 0.9

    def sgd_bf16_detour(w, g):
        return (w - (0.1 * g.astype(jnp.bfloat16)).astype(jnp.float32),
                g * 0.9)

    def with_callback(w, g):
        jax.debug.callback(lambda v: None, g.sum())
        return w - 0.1 * g, g * 0.9

    return {
        "donated": jax.jit(sgd, donate_argnums=(0, 1)).lower(w, g).as_text(),
        "undonated": jax.jit(sgd).lower(w, g).as_text(),
        "bf16_detour": jax.jit(sgd_bf16_detour).lower(w, g).as_text(),
        "callback": jax.jit(with_callback).lower(w, g).as_text(),
    }


class TestHloPasses:
    def test_convert_budget_catches_and_passes(self, lowerings):
        bad = hlo_passes.convert_budget_pass(
            lowerings["bf16_detour"], "step", budget=0)
        assert len(bad) == 1 and bad[0].rule == "MXL501"
        assert hlo_passes.convert_budget_pass(
            lowerings["donated"], "step", budget=0) == []

    def test_donation_coverage_catches_and_passes(self, lowerings):
        bad = hlo_passes.donation_coverage_pass(
            lowerings["undonated"], "step", min_coverage=0.5,
            large_bytes=1024)
        assert len(bad) == 1 and bad[0].rule == "MXL502"
        assert hlo_passes.donation_coverage_pass(
            lowerings["donated"], "step", min_coverage=0.99,
            large_bytes=1024) == []

    def test_donation_coverage_no_large_params_is_clean(self):
        # zero large params -> nothing worth donating -> coverage 1.0
        assert hlo_passes.donation_coverage("", large_bytes=1)[2] == 1.0

    def test_d2h_catches_callback_and_passes_clean(self, lowerings):
        bad = hlo_passes.d2h_transfer_pass(
            lowerings["callback"], "step", budget=0)
        assert len(bad) == 1 and bad[0].rule == "MXL503"
        assert hlo_passes.d2h_transfer_pass(
            lowerings["donated"], "step", budget=0) == []

    def test_fusion_bytes_catches_and_passes(self, lowerings):
        # the sgd program writes a few elementwise results (256x256 f32
        # each): a zero budget must flag it, a generous one must not
        bad = hlo_passes.fusion_bytes_pass(
            lowerings["donated"], "step", budget_gib=0.0)
        assert len(bad) == 1 and bad[0].rule == "MXL505"
        assert "GiB" in bad[0].message
        assert hlo_passes.fusion_bytes_pass(
            lowerings["donated"], "step", budget_gib=64.0) == []

    # MXL507 fixtures: hand-written StableHLO with known dataflow. The
    # chained module reduces THROUGH the only compute chain (dot ->
    # all_reduce -> dot): nothing can overlap. The overlapped module has
    # an independent dot the scheduler can slide under the collective.
    _DDP_BAD = (
        'func.func public @main(%arg0: tensor<4x4xf32>) {\n'
        '  %0 = stablehlo.dot_general %arg0, %arg0 : tensor<4x4xf32>\n'
        '  %1 = "stablehlo.all_reduce"(%0) <{replica_groups = '
        'dense<[[0,1]]>}> ({\n'
        '  ^bb0(%arg1: tensor<f32>, %arg2: tensor<f32>):\n'
        '    %4 = stablehlo.add %arg1, %arg2 : tensor<f32>\n'
        '    stablehlo.return %4 : tensor<f32>\n'
        '  }) : tensor<4x4xf32>\n'
        '  %2 = stablehlo.dot_general %1, %1 : tensor<4x4xf32>\n'
        '  return %2 : tensor<4x4xf32>\n'
        '}\n')
    _DDP_GOOD = (
        'func.func public @main(%arg0: tensor<4x4xf32>) {\n'
        '  %0 = stablehlo.dot_general %arg0, %arg0 : tensor<4x4xf32>\n'
        '  %1 = "stablehlo.all_reduce"(%0) <{replica_groups = '
        'dense<[[0,1]]>}> ({\n'
        '  ^bb0(%arg1: tensor<f32>, %arg2: tensor<f32>):\n'
        '    %4 = stablehlo.add %arg1, %arg2 : tensor<f32>\n'
        '    stablehlo.return %4 : tensor<f32>\n'
        '  }) : tensor<4x4xf32>\n'
        '  %2 = stablehlo.dot_general %arg0, %arg0 : tensor<4x4xf32>\n'
        '  %3 = stablehlo.add %1, %2 : tensor<4x4xf32>\n'
        '  return %3 : tensor<4x4xf32>\n'
        '}\n')

    def test_collective_interleave_catches_and_passes(self):
        bad = hlo_passes.collective_interleave_pass(
            self._DDP_BAD, "ddp/step", max_collectives=1)
        assert len(bad) == 1 and bad[0].rule == "MXL507"
        assert "critical path" in bad[0].message
        assert hlo_passes.collective_interleave_pass(
            self._DDP_GOOD, "ddp/step", max_collectives=1) == []

    def test_collective_interleave_budget_and_absence(self):
        over = hlo_passes.collective_interleave_pass(
            self._DDP_GOOD, "ddp/step", max_collectives=0)
        assert len(over) == 1 and "bucket plan" in over[0].message
        none = hlo_passes.collective_interleave_pass(
            "func.func public @main() {\n  return\n}\n", "ddp/step")
        assert len(none) == 1 and "not being reduced" in none[0].message

    def test_decode_cache_discipline_catches_and_passes(self, lowerings):
        # donated in-place update over the "cache" params: clean
        assert hlo_passes.decode_cache_discipline_pass(
            lowerings["donated"], "decode", cache_params=(0, 1)) == []
        # same program without donation: the KV buffers round-trip
        bad = hlo_passes.decode_cache_discipline_pass(
            lowerings["undonated"], "decode", cache_params=(0, 1))
        assert len(bad) == 1 and bad[0].rule == "MXL508"
        assert "not donated" in bad[0].message
        # host callback inside the step: a d2h per token
        leak = hlo_passes.decode_cache_discipline_pass(
            lowerings["callback"], "decode", cache_params=())
        assert len(leak) == 1 and leak[0].rule == "MXL508"
        assert "host-transfer" in leak[0].message

    def test_speculative_dispatch_catches_and_passes(self, lowerings):
        # MXL510 fixture pair rides the same programs as MXL508: what
        # changes is the contract — ALL cache params (verifier + draft
        # pairs) donated, zero host transfers in the FUSED program.
        # fused + donated: clean
        assert hlo_passes.speculative_dispatch_pass(
            lowerings["donated"], "draft_verify",
            cache_params=(0, 1)) == []
        # undonated draft/verifier KV: the page stores copy every window
        bad = hlo_passes.speculative_dispatch_pass(
            lowerings["undonated"], "draft_verify", cache_params=(0, 1))
        assert len(bad) == 1 and bad[0].rule == "MXL510"
        assert "not donated" in bad[0].message
        # a host callback inside the step: the tell of a draft
        # dispatched separately from its verifier (extra d2h per window)
        leak = hlo_passes.speculative_dispatch_pass(
            lowerings["callback"], "draft_verify", cache_params=())
        assert len(leak) == 1 and leak[0].rule == "MXL510"
        assert "not fused with its verifier" in leak[0].message

    def test_embedding_lookup_discipline_catches_and_passes(
            self, lowerings):
        # MXL511 fixture pair rides the same programs as MXL508: the
        # "cache" param here plays the hot-row embedding buffer the
        # RecommendEngine donates (argnum 0).
        assert hlo_passes.embedding_lookup_discipline_pass(
            lowerings["donated"], "recommend", cache_params=(0, 1)) == []
        # undonated hot-row buffer: the resident rows copy per batch
        bad = hlo_passes.embedding_lookup_discipline_pass(
            lowerings["undonated"], "recommend", cache_params=(0, 1))
        assert len(bad) == 1 and bad[0].rule == "MXL511"
        assert "not donated" in bad[0].message
        # a host callback inside the served lookup: hit/miss accounting
        # must stay host-held (HotRowCache counters), zero extra d2h
        leak = hlo_passes.embedding_lookup_discipline_pass(
            lowerings["callback"], "recommend", cache_params=())
        assert len(leak) == 1 and leak[0].rule == "MXL511"
        assert "host-transfer" in leak[0].message

    # MXL512 fixtures: hand-written StableHLO around the pass's tell.
    # BAD materializes the (seq, ctx) score softmax — an exponential
    # whose f32 result spans the full context width in its last dim.
    # GOOD is the flash kernel's footprint: exps over kernel tiles
    # (last dim < ctx) plus the sampler's log-of-uniform Gumbel trick,
    # neither of which may fire the rule.
    _ATTN_BAD = (
        'func.func public @main(%arg0: tensor<8x4x48xf32>) {\n'
        '  %0 = stablehlo.exponential %arg0 : tensor<8x4x48xf32>\n'
        '  %1 = stablehlo.exponential %arg0 : tensor<8x4x48xf32>\n'
        '  return %1 : tensor<8x4x48xf32>\n'
        '}\n')
    _ATTN_GOOD = (
        'func.func public @main(%arg0: tensor<16x16xf32>, '
        '%arg1: tensor<8x4xf32>) {\n'
        '  %0 = stablehlo.exponential %arg0 : tensor<16x16xf32>\n'
        '  %1 = stablehlo.log %arg1 : tensor<8x4xf32>\n'
        '  return %0 : tensor<16x16xf32>\n'
        '}\n')

    def test_attention_fusion_catches_and_passes(self):
        # decode geometry: ctx = page_size * max_pages_per_slot = 48
        bad = hlo_passes.attention_fusion_pass(
            self._ATTN_BAD, "decode_step", ctx=48)
        assert len(bad) == 1 and bad[0].rule == "MXL512"
        assert "softmax exponential" in bad[0].message
        assert "8x4x48xf32" in bad[0].message
        # tile-width exps (16 < 48) and the Gumbel log: clean
        assert hlo_passes.attention_fusion_pass(
            self._ATTN_GOOD, "decode_step", ctx=48) == []
        # the same tile exp IS the score block when ctx shrinks to it
        tight = hlo_passes.attention_fusion_pass(
            self._ATTN_GOOD, "decode_step", ctx=16)
        assert len(tight) == 1 and tight[0].rule == "MXL512"

    def test_attention_fusion_holds_sync_budget(self, lowerings):
        # a host callback inside the step: fusing attention must not
        # add device syncs (the MXL508 one-fetch contract still holds)
        leak = hlo_passes.attention_fusion_pass(
            lowerings["callback"], "decode_step", ctx=48)
        assert len(leak) == 1 and leak[0].rule == "MXL512"
        assert "must not add device syncs" in leak[0].message
        assert hlo_passes.attention_fusion_pass(
            lowerings["donated"], "decode_step", ctx=10 ** 6) == []

    # MXL509 fixtures: hand-written StableHLO in the shape the quantized
    # serving ops lower to. GOOD: f32 activations quantize (f32->i8), an
    # int8 dot accumulates in i32, and the only upcast is the i32
    # accumulator entering the dequant epilogue. BAD: the int8 weight is
    # upcast i8->f32 and the dot runs in f32 — the artifact shrank but
    # the compute did not quantize.
    _QUANT_GOOD = (
        'func.func public @main(%arg0: tensor<4x256xf32>) {\n'
        '  %c = stablehlo.constant dense<1> : tensor<8x256xi8>\n'
        '  %0 = stablehlo.convert %arg0 : (tensor<4x256xf32>) -> '
        'tensor<4x256xi8>\n'
        '  %1 = stablehlo.dot_general %0, %c, contracting_dims = [1] x '
        '[1] : (tensor<4x256xi8>, tensor<8x256xi8>) -> tensor<4x8xi32>\n'
        '  %2 = stablehlo.convert %1 : (tensor<4x8xi32>) -> '
        'tensor<4x8xf32>\n'
        '  return %2 : tensor<4x8xf32>\n'
        '}\n')
    _QUANT_BAD = (
        'func.func public @main(%arg0: tensor<4x256xf32>) {\n'
        '  %c = stablehlo.constant dense<1> : tensor<8x256xi8>\n'
        '  %0 = stablehlo.convert %c : (tensor<8x256xi8>) -> '
        'tensor<8x256xf32>\n'
        '  %1 = stablehlo.dot_general %arg0, %0, contracting_dims = [1] '
        'x [1] : (tensor<4x256xf32>, tensor<8x256xf32>) -> '
        'tensor<4x8xf32>\n'
        '  return %1 : tensor<4x8xf32>\n'
        '}\n')

    def test_quant_dequant_budget_catches_and_passes(self):
        assert hlo_passes.quant_dequant_budget_pass(
            self._QUANT_GOOD, "int8/predict", min_int8_ops=1) == []
        bad = hlo_passes.quant_dequant_budget_pass(
            self._QUANT_BAD, "int8/predict", min_int8_ops=1)
        # both failure modes: no int8 compute AND a weight upcast
        assert len(bad) == 2
        assert all(d.rule == "MXL509" for d in bad)
        assert "i8->f32" in bad[1].message

    def test_quant_dequant_upcast_budget_is_a_ratchet(self):
        # a module with valid int8 compute plus ONE stray i8->f32: the
        # budget tolerates it at 1 (MXL501 idiom) and flags it at 0
        mixed = self._QUANT_GOOD.replace(
            '  return %2 : tensor<4x8xf32>\n',
            '  %3 = stablehlo.convert %c : (tensor<8x256xi8>) -> '
            'tensor<8x256xf32>\n'
            '  return %2 : tensor<4x8xf32>\n')
        assert hlo_passes.quant_dequant_budget_pass(
            mixed, "int8/predict", upcast_budget=1) == []
        over = hlo_passes.quant_dequant_budget_pass(
            mixed, "int8/predict", upcast_budget=0)
        assert len(over) == 1 and over[0].rule == "MXL509"

    def test_collective_overlap_report_is_per_func(self):
        # SSA names restart per func.func: a %0 in a second function must
        # not alias the first function's dataflow
        two = self._DDP_BAD + self._DDP_GOOD.replace("@main", "@shmap_body")
        rep = hlo_passes.collective_overlap_report(two)
        assert rep["collectives"] == 2
        assert rep["overlappable"] == 1

    def test_metrics_from_text(self, lowerings):
        m = hlo_passes.metrics_from_text(lowerings["donated"],
                                         large_bytes=1024)
        assert m["donation_coverage"] == 1.0
        assert m["d2h_count"] == 0
        assert m["elementwise_gib"] >= 0.0
        assert m["pallas_kernels"] == 0
        m2 = hlo_passes.metrics_from_text(lowerings["bf16_detour"],
                                          large_bytes=1024)
        assert m2["convert_f32_bf16"] >= 2


class TestRecompileFingerprint:
    def test_shape_churn_flagged(self):
        import numpy as np
        fp = hlo_passes.RecompileFingerprint("predict", max_variants=2)
        for n in (1, 2, 3, 4):
            fp.observe(np.zeros((n, 8), np.float32))
        diags = fp.diagnostics()
        assert len(diags) == 1 and diags[0].rule == "MXL504"
        assert fp.variants == 4

    def test_bucketed_shapes_pass(self):
        import numpy as np
        fp = hlo_passes.RecompileFingerprint("predict", max_variants=2)
        for n in (1, 3, 2, 4):
            bucket = 4    # serve/engine_cache-style padding
            fp.observe(np.zeros((bucket, 8), np.float32))
        assert fp.diagnostics() == [] and fp.variants == 1

    def test_static_value_churn_flagged(self):
        fp = hlo_passes.RecompileFingerprint("step", max_variants=2)
        for lr in (0.1, 0.2, 0.3):
            fp.observe(lr=lr)
        assert fp.diagnostics() and fp.variants == 3


# ------------------------------------------------------------ the ratchet

BAD_SRC = (
    "import jax\n"
    "def step(x):\n"
    "    return float(x)\n"
    "f = jax.jit(step)\n")


class TestBaselineRatchet:
    def _write(self, tmp_path, name, src):
        p = tmp_path / name
        p.write_text(src)
        return str(p)

    def test_new_violation_fails_baselined_passes(self, tmp_path):
        f = self._write(tmp_path, "mod.py", BAD_SRC)
        bl = str(tmp_path / "baseline.json")
        diags = lint_paths([f], root=str(tmp_path))
        assert diags
        # not baselined -> new
        new, baselined, stale = baseline_mod.partition(
            diags, baseline_mod.load(bl))
        assert new and not baselined
        # baselined -> passes
        baseline_mod.update(bl, diags, allow_growth=True)
        new, baselined, stale = baseline_mod.partition(
            diags, baseline_mod.load(bl))
        assert not new and baselined and not stale

    def test_update_shrinks_but_never_grows(self, tmp_path):
        f = self._write(tmp_path, "mod.py", BAD_SRC)
        bl = str(tmp_path / "baseline.json")
        diags = lint_paths([f], root=str(tmp_path))
        baseline_mod.update(bl, diags, allow_growth=True)
        assert len(baseline_mod.load(bl)) == 1

        # violation fixed -> shrink happens without any flag
        self._write(tmp_path, "mod.py",
                    "def step(x):\n    return x\n")
        diags = lint_paths([str(tmp_path / "mod.py")], root=str(tmp_path))
        baseline_mod.update(bl, diags)
        assert baseline_mod.load(bl) == {}

        # new violation -> growth refused without allow_growth
        self._write(tmp_path, "mod.py", BAD_SRC)
        diags = lint_paths([str(tmp_path / "mod.py")], root=str(tmp_path))
        with pytest.raises(baseline_mod.BaselineGrowthError):
            baseline_mod.update(bl, diags)
        assert baseline_mod.load(bl) == {}    # refused update wrote nothing
        baseline_mod.update(bl, diags, allow_growth=True)
        assert len(baseline_mod.load(bl)) == 1

    def test_layer3_growth_refused(self, tmp_path):
        """New MXL6xx findings ride the same one-way ratchet."""
        f = self._write(tmp_path, "mod.py", (
            "import time\n"
            "def lease():\n"
            "    deadline = time.time() + 5.0\n"
            "    return deadline\n"))
        bl = str(tmp_path / "baseline.json")
        baseline_mod.update(bl, [])            # seed an empty baseline
        diags = lint_paths([f], root=str(tmp_path))
        assert {d.rule for d in diags} == {"MXL603"}
        with pytest.raises(baseline_mod.BaselineGrowthError):
            baseline_mod.update(bl, diags)
        assert baseline_mod.load(bl) == {}     # refusal wrote nothing

    def test_unsupported_baseline_format_raises(self, tmp_path):
        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps({"version": 99, "entries": {}}))
        with pytest.raises(ValueError):
            baseline_mod.load(str(bl))


# ------------------------------------------------------------------- CLI

class TestCli:
    def test_exit_codes_and_json(self, tmp_path, capsys):
        mod = tmp_path / "mod.py"
        mod.write_text(BAD_SRC)
        bl = str(tmp_path / "bl.json")

        rc = mxlint_cli.main([str(mod), "--no-baseline", "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1 and out["new"] == 1
        assert out["diagnostics"][0]["rule"] == "MXL102"

        # clean file -> 0
        clean = tmp_path / "ok.py"
        clean.write_text("def f(x):\n    return x\n")
        assert mxlint_cli.main([str(clean), "--no-baseline"]) == 0

    def test_rule_filter_and_unknown_rule(self, tmp_path, capsys):
        mod = tmp_path / "mod.py"
        mod.write_text(BAD_SRC)
        rc = mxlint_cli.main([str(mod), "--no-baseline", "--rule",
                              "MXL401"])
        capsys.readouterr()
        assert rc == 0          # only lock rules requested; none fire
        assert mxlint_cli.main(["--rule", "MXL999"]) == 2

    def test_list_rules(self, capsys):
        assert mxlint_cli.main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rid in ("MXL101", "MXL201", "MXL301", "MXL401", "MXL501",
                    "MXL502", "MXL503", "MXL504"):
            assert rid in out

    def test_baseline_update_guard_needs_full_scope(self, tmp_path,
                                                    capsys):
        rc = mxlint_cli.main(["--baseline-update", "--rule", "MXL101"])
        capsys.readouterr()
        assert rc == 2
        rc = mxlint_cli.main(["--baseline-update", "--concurrency"])
        capsys.readouterr()
        assert rc == 2

    def test_concurrency_scope_filters_layer1(self, tmp_path, capsys):
        mod = tmp_path / "mod.py"
        mod.write_text(BAD_SRC +
                       "import time\n"
                       "def lease():\n"
                       "    deadline = time.time() + 5.0\n"
                       "    return deadline\n")
        rc = mxlint_cli.main([str(mod), "--no-baseline", "--json",
                              "--concurrency"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 1
        assert {d["rule"] for d in out["diagnostics"]} == {"MXL603"}
