"""ONNX import/export round-trip tests (parity model:
tests/python-pytest/onnx/).  No `onnx` package exists in this image, so
interop is proven by round-tripping through the wire format itself:
export writes real protobuf bytes, import parses them back, and the
reconstructed graph must be numerically identical."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import onnx as onnx_mxnet
from mxnet_tpu.contrib.onnx import _proto as P


def _forward(sym, params, data, label_names=()):
    mod = mx.mod.Module(sym, label_names=list(label_names))
    mod.bind([("data", data.shape)], for_training=False)
    mod.init_params(arg_params=params[0], aux_params=params[1],
                    allow_missing=False)
    mod.forward(mx.io.DataBatch(data=[mx.nd.array(data)]), is_train=False)
    return mod.get_outputs()[0].asnumpy()


def _roundtrip(sym, arg_params, aux_params, data, tmp_path,
               label_names=("softmax_label",)):
    path = str(tmp_path / "model.onnx")
    onnx_mxnet.export_model(sym, {**arg_params, **aux_params},
                            [data.shape], np.float32, path)
    sym2, arg2, aux2 = onnx_mxnet.import_model(path)
    y1 = _forward(sym, (arg_params, aux_params), data,
                  label_names=label_names)
    y2 = _forward(sym2, (arg2, aux2), data, label_names=())
    np.testing.assert_allclose(y1, y2, rtol=1e-5, atol=1e-6)
    return sym2


def _init_params(sym, data_shape, seed=0):
    mod = mx.mod.Module(sym)
    mod.bind([("data", data_shape)], for_training=False)
    mx.random.seed(seed)
    mod.init_params(mx.initializer.Xavier())
    return mod.get_params()


def test_proto_codec_roundtrip():
    """The hand-rolled protobuf codec must round-trip a nested model."""
    t = P.TensorProto(name="w", dims=[2, 3], data_type=P.TensorProto.FLOAT,
                      raw_data=np.arange(6, dtype=np.float32).tobytes())
    node = P.NodeProto(op_type="Conv", input=["x", "w"], output=["y"],
                       name="conv0",
                       attribute=[P.AttributeProto(
                           name="kernel_shape", ints=[3, 3],
                           type=P.AttributeProto.INTS)])
    g = P.GraphProto(node=[node], name="g", initializer=[t])
    m = P.ModelProto(ir_version=4, producer_name="test", graph=g,
                     opset_import=[P.OperatorSetIdProto(version=9)])
    m2 = P.ModelProto.decode(m.encode())
    assert m2.producer_name == "test"
    assert m2.opset_import[0].version == 9
    assert m2.graph.node[0].op_type == "Conv"
    assert tuple(m2.graph.node[0].attribute[0].ints) == (3, 3)
    assert m2.graph.initializer[0].dims == [2, 3]
    w = np.frombuffer(m2.graph.initializer[0].raw_data, np.float32)
    np.testing.assert_array_equal(w, np.arange(6, dtype=np.float32))


def test_proto_negative_int_and_skip_unknown():
    a = P.AttributeProto(name="axis", i=-1, type=P.AttributeProto.INT)
    a2 = P.AttributeProto.decode(a.encode())
    assert a2.i == -1
    # unknown fields are skipped: decode NodeProto bytes as AttributeProto
    # must not crash (field numbers overlap but kinds differ benignly)
    raw = P.NodeProto(op_type="X", doc_string="d").encode()
    P.AttributeProto.decode(raw)


def test_onnx_roundtrip_mlp(tmp_path):
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=16,
                                name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    data = np.random.RandomState(0).randn(8, 10).astype(np.float32)
    arg, aux = _init_params(net, data.shape)
    _roundtrip(net, arg, aux, data, tmp_path)


def test_onnx_roundtrip_convnet(tmp_path):
    net = mx.sym.Convolution(mx.sym.Variable("data"), kernel=(3, 3),
                             num_filter=8, pad=(1, 1), name="conv1")
    net = mx.sym.BatchNorm(net, name="bn1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max", name="pool1")
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=4, name="conv2")
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg",
                         kernel=(1, 1), name="gap")
    net = mx.sym.Flatten(net, name="flat")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    data = np.random.RandomState(1).randn(2, 3, 16, 16).astype(np.float32)
    arg, aux = _init_params(net, data.shape)
    _roundtrip(net, arg, aux, data, tmp_path)


def test_onnx_roundtrip_elemwise_and_reduce(tmp_path):
    d = mx.sym.Variable("data")
    net = (d * 2.0 + 1.0)
    net = mx.sym.exp(mx.sym.clip(net, a_min=-2.0, a_max=2.0))
    net = mx.sym.mean(net, axis=1, keepdims=True)
    net = mx.sym.broadcast_mul(net, mx.sym.sqrt(mx.sym.abs(d) + 1.0))
    data = np.random.RandomState(2).randn(4, 5).astype(np.float32)
    path = str(tmp_path / "ew.onnx")
    onnx_mxnet.export_model(net, {}, [data.shape], np.float32, path)
    sym2, arg2, aux2 = onnx_mxnet.import_model(path)
    y1 = _forward(net, ({}, {}), data)
    y2 = _forward(sym2, (arg2, aux2), data)
    np.testing.assert_allclose(y1, y2, rtol=1e-5, atol=1e-6)


def test_onnx_metadata(tmp_path):
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                                name="fc")
    data_shape = (8, 10)
    arg, aux = _init_params(net, data_shape)
    path = str(tmp_path / "meta.onnx")
    onnx_mxnet.export_model(net, dict(arg), [data_shape], np.float32, path)
    meta = onnx_mxnet.get_model_metadata(path)
    assert meta["input_tensor_data"] == [("data", (8, 10))]
    assert meta["output_tensor_data"][0][1] == (8, 4)


def test_onnx_import_unsupported_op_is_loud(tmp_path):
    node = P.NodeProto(op_type="NonexistentOp", input=["data"],
                       output=["y"], name="bad")
    g = P.GraphProto(node=[node],
                     input=[P.ValueInfoProto(name="data")],
                     output=[P.ValueInfoProto(name="y")])
    m = P.ModelProto(ir_version=4, graph=g,
                     opset_import=[P.OperatorSetIdProto(version=9)])
    path = str(tmp_path / "bad.onnx")
    with open(path, "wb") as f:
        f.write(m.encode())
    with pytest.raises(Exception, match="NonexistentOp"):
        onnx_mxnet.import_model(path)


def test_onnx_fix_gamma_exports_ones(tmp_path):
    """fix_gamma=True (the BatchNorm default) computes with gamma=1 —
    the export must match that, whatever the stored gamma says."""
    net = mx.sym.BatchNorm(mx.sym.Variable("data"), name="bn",
                           fix_gamma=True)
    data = np.random.RandomState(3).randn(2, 4, 5, 5).astype(np.float32)
    arg, aux = _init_params(net, data.shape)
    arg["bn_gamma"][:] = 5.0  # would poison the export if not fixed
    _roundtrip(net, arg, aux, data, tmp_path, label_names=())


def test_onnx_squeeze_all_and_one_sided_clip(tmp_path):
    d = mx.sym.Variable("data")
    net = mx.sym.squeeze(mx.sym.clip(d, a_min=-3.4028234663852886e38,
                                     a_max=6.0))
    data = np.random.RandomState(4).rand(1, 3, 1, 2).astype(np.float32) * 10
    path = str(tmp_path / "sq.onnx")
    onnx_mxnet.export_model(net, {}, [data.shape], np.float32, path)
    sym2, arg2, aux2 = onnx_mxnet.import_model(path)
    y1 = _forward(net, ({}, {}), data)
    y2 = _forward(sym2, (arg2, aux2), data)
    assert y1.shape == y2.shape == (3, 2)
    np.testing.assert_allclose(y1, y2, rtol=1e-6)


def test_onnx_fp16_int32_data_bitcast():
    from mxnet_tpu.contrib.onnx.onnx2mx import tensor_to_numpy
    t = P.TensorProto(name="h", dims=[2], data_type=P.TensorProto.FLOAT16,
                      int32_data=[15360, 16384])  # bits of 1.0, 2.0
    np.testing.assert_array_equal(tensor_to_numpy(t),
                                  np.array([1.0, 2.0], np.float16))


def test_reshape_special_codes_refuse_export(tmp_path):
    x = mx.sym.Variable("data")
    net = mx.sym.reshape(x, shape=(0, -3))   # -3: merge dims, no ONNX form
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    with pytest.raises(mx.base.MXNetError):
        onnx_mxnet.export_model(net, {}, [(2, 3, 4)],
                                onnx_file_path=str(tmp_path / "bad.onnx"))


def _roundtrip_expr(net, data, tmp_path, data_name="data"):
    path = str(tmp_path / "expr.onnx")
    onnx_mxnet.export_model(net, {}, [data.shape], np.float32, path)
    sym2, arg2, aux2 = onnx_mxnet.import_model(path)
    y1 = _forward(net, ({}, {}), data)
    y2 = _forward(sym2, (arg2, aux2), data)
    np.testing.assert_allclose(y1, y2, rtol=1e-4, atol=1e-5)
    return y1


def test_onnx_roundtrip_math_tail(tmp_path):
    """The exporter ops added for full reference-table parity round-trip
    through the wire format (export -> import -> identical numerics)."""
    d = mx.sym.Variable("data")
    net = mx.sym.square(mx.sym.cos(d)) + mx.sym.ceil(d) - mx.sym.floor(d)
    net = net + mx.sym.reciprocal(d + 3.0) + mx.sym.arctan(d)
    net = mx.sym.maximum(net, mx.sym.minimum(d, net))
    data = np.random.RandomState(5).rand(3, 4).astype(np.float32) + 0.5
    _roundtrip_expr(net, data, tmp_path)


def test_onnx_roundtrip_reduce_and_index_tail(tmp_path):
    d = mx.sym.Variable("data")
    net = mx.sym.broadcast_add(
        mx.sym.prod(d, axis=1, keepdims=True),
        mx.sym.argmax(d, axis=1, keepdims=True))
    data = np.random.RandomState(6).rand(3, 4).astype(np.float32) + 0.5
    _roundtrip_expr(net, data, tmp_path)


def test_onnx_roundtrip_structure_tail(tmp_path):
    d = mx.sym.Variable("data")
    parts = mx.sym.SliceChannel(d, num_outputs=2, axis=1)
    net = mx.sym.add_n(parts[0], parts[1])
    net = mx.sym.pad(net, mode="constant", pad_width=(0, 0, 0, 0, 1, 1,
                                                      1, 1),
                     constant_value=0.5)
    net = mx.sym.slice_axis(net, axis=2, begin=1, end=None)
    data = np.random.RandomState(7).randn(2, 4, 5, 5).astype(np.float32)
    _roundtrip_expr(net, data, tmp_path)


def test_onnx_roundtrip_nn_tail(tmp_path):
    d = mx.sym.Variable("data")
    net = mx.sym.LRN(d, nsize=3, alpha=1e-3, beta=0.7, knorm=1.5)
    net = mx.sym.hard_sigmoid(net, alpha=0.3, beta=0.4)
    net = mx.sym.space_to_depth(mx.sym.depth_to_space(net, block_size=2),
                                block_size=2)
    data = np.random.RandomState(8).rand(1, 4, 6, 6).astype(np.float32)
    _roundtrip_expr(net, data, tmp_path)


def test_onnx_export_table_covers_reference(tmp_path):
    """Name-by-name diff against the reference exporter's @mx_op.register
    table (minus 'null', which is the variable passthrough)."""
    from mxnet_tpu.contrib.onnx.mx2onnx import _TRANSLATIONS
    reference_table = [
        "Activation", "BatchNorm", "Cast", "Concat", "Convolution",
        "Dropout", "Flatten", "FullyConnected", "L2Normalization", "LRN",
        "LeakyReLU", "Pad", "Pooling", "Reshape", "SliceChannel",
        "SoftmaxOutput", "_copy", "_div_scalar", "_linalg_gemm2",
        "_maximum", "_minimum", "_minus_scalar", "_mul_scalar",
        "_plus_scalar", "_power", "abs", "add_n", "arccos", "arcsin",
        "arctan", "argmax", "argmin", "broadcast_add", "broadcast_div",
        "broadcast_equal", "broadcast_greater", "broadcast_lesser",
        "broadcast_mul", "broadcast_power", "broadcast_sub", "cast",
        "ceil", "clip", "cos", "depth_to_space", "dot", "elemwise_add",
        "elemwise_div", "elemwise_mul", "elemwise_sub", "exp", "floor",
        "log", "max", "mean", "min", "negative", "prod", "reciprocal",
        "relu", "sigmoid", "sin", "slice_axis", "softmax",
        "space_to_depth", "sqrt", "square", "squeeze", "sum", "tan",
        "tanh", "transpose",
    ]
    missing = [op for op in reference_table if op not in _TRANSLATIONS]
    assert not missing, "exporter lacks reference table ops: %r" % missing


def test_onnx_export_l2normalization_roundtrips(tmp_path):
    net = mx.sym.L2Normalization(mx.sym.Variable("data"), mode="channel")
    data = np.random.RandomState(9).rand(2, 3, 4).astype(np.float32)
    path = str(tmp_path / "l2.onnx")
    onnx_mxnet.export_model(net, {}, [data.shape], np.float32, path)
    model = P.ModelProto.decode(open(path, "rb").read())
    assert [n.op_type for n in model.graph.node] == ["LpNormalization"]
    sym2, a2, x2 = onnx_mxnet.import_model(path)
    y1 = _forward(net, ({}, {}), data)
    y2 = _forward(sym2, (a2, x2), data)
    np.testing.assert_allclose(y1, y2, rtol=1e-5, atol=1e-6)


def test_onnx_export_l2normalization_instance_mode_refuses(tmp_path):
    """mode='instance' (the MXNet default) normalizes over ALL non-batch
    axes — LpNormalization axis=1 would silently change numerics, so the
    export must refuse (reference exporter behavior)."""
    net = mx.sym.L2Normalization(mx.sym.Variable("data"))
    with pytest.raises(mx.base.MXNetError, match="channel"):
        onnx_mxnet.export_model(net, {}, [(2, 3, 4)],
                                onnx_file_path=str(tmp_path / "bad.onnx"))


def test_onnx_full_resnet18_roundtrip(tmp_path):
    """Flagship interop: the zoo's symbolic ResNet-18 exports to ONNX and
    reimports with byte-identical inference — the reference's model-zoo
    export workflow end to end."""
    from mxnet_tpu import models
    sym = models.resnet_symbol(num_classes=10, num_layers=18)
    rng = np.random.RandomState(0)
    shapes, _, aux_shapes = sym.infer_shape(data=(2, 3, 32, 32))
    args = {n: mx.nd.array(rng.uniform(-0.1, 0.1, s).astype("f4"))
            for n, s in zip(sym.list_arguments(), shapes)
            if n not in ("data", "softmax_label")}
    aux = {n: mx.nd.array(np.abs(rng.uniform(0.5, 1.0, s)).astype("f4"))
           for n, s in zip(sym.list_auxiliary_states(), aux_shapes)}
    path = str(tmp_path / "resnet18.onnx")
    onnx_mxnet.export_model(sym, {**args, **aux}, [(2, 3, 32, 32)],
                            np.float32, path)
    sym2, a2, x2 = onnx_mxnet.import_model(path)
    data = rng.randn(2, 3, 32, 32).astype(np.float32)
    y1 = _forward(sym, (args, aux), data,
                  label_names=("softmax_label",))
    y2 = _forward(sym2, (a2, x2), data, label_names=())
    np.testing.assert_allclose(y1, y2, rtol=1e-5, atol=1e-5)


def test_import_model_for_training_keeps_bn_batch_stats(tmp_path):
    data = mx.sym.Variable("data")
    net = mx.sym.BatchNorm(mx.sym.FullyConnected(data, num_hidden=4,
                                                 name="fc"), name="bn")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(0)
    args = {"fc_weight": mx.nd.array(rng.randn(4, 3).astype("f4")),
            "fc_bias": mx.nd.zeros((4,)),
            "bn_gamma": mx.nd.ones((4,)), "bn_beta": mx.nd.zeros((4,))}
    aux = {"bn_moving_mean": mx.nd.zeros((4,)),
           "bn_moving_var": mx.nd.ones((4,))}
    f = str(tmp_path / "m.onnx")
    onnx_mxnet.export_model(net, {**args, **aux}, [(2, 3)],
                            onnx_file_path=f)
    sym_inf, _, _ = onnx_mxnet.import_model(f)
    sym_tr, _, _ = onnx_mxnet.import_model(f, for_training=True)
    bn_inf = [n for n in sym_inf._topo() if n.op and n.op.name == "BatchNorm"][0]
    bn_tr = [n for n in sym_tr._topo() if n.op and n.op.name == "BatchNorm"][0]
    assert bn_inf.params["use_global_stats"] is True
    assert bn_tr.params["use_global_stats"] is False
