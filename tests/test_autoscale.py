"""Demand-driven autoscaling (mxnet_tpu.fleet.autoscale) — chip-free.

Acceptance properties: (1) the floor launches immediately, ungated by
cooldown or break-even, and a warming replica counts as capacity so a
slow warmup never triggers a launch storm; (2) scale-up needs a
sustained high-watermark breach AND a break-even win; (3) scale-down
drains (never kills) the least-loaded owned replica and reaps it only
once idle; (4) cooldown suppresses actions and is journaled as
``held:cooldown``; (5) every decision round-trips through the fleet
WAL — ``FleetState`` folds them, a promoted router restores them, and
a fresh ``Autoscaler`` inherits its owned set; (6) the router refuses
a traffic split across mixed layout fingerprints.
"""
import pytest

from mxnet_tpu.base import MXNetError
from mxnet_tpu.fleet import (AutoscalePolicy, Autoscaler, FleetJournal,
                             ReplicaRegistry, Router, fencing)
from mxnet_tpu.fleet.journal import FleetState, replay


@pytest.fixture(autouse=True)
def _fresh_epoch():
    fencing.reset()
    yield
    fencing.reset()


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


class FakeSupervisor:
    """Records launch/stop calls; never spawns a process."""

    def __init__(self):
        self.added = []
        self.stopped = []

    def add(self, spec, start=True):
        self.added.append(spec.replica_id
                          if hasattr(spec, "replica_id") else spec)

    def stop(self, replica_id=None, **kw):
        self.stopped.append(replica_id)


def _policy(**kw):
    base = dict(min_replicas=1, max_replicas=3, high_watermark_s=1.0,
                low_watermark_s=0.1, breach_rounds=2, cooldown_s=10.0,
                startup_cost_s=0.5, interval_s=0.5)
    base.update(kw)
    return AutoscalePolicy(**base)


def _register(registry, rid, *, model="m", ready=True, load=None,
              layout=None, mode="predict"):
    return registry.register({
        "id": rid, "url": "http://%s.invalid" % rid, "model": model,
        "version": "0", "mode": mode, "ready": ready,
        "load": load or {}, "layout": layout})


def _scaler(tmp_path=None, policy=None, clock=None, journal=False,
            model="m"):
    reg = ReplicaRegistry(heartbeat_timeout_s=3600.0,
                          clock=clock or FakeClock())
    router = Router(registry=reg)
    if journal:
        router.attach_journal(FleetJournal(str(tmp_path / "j"),
                                           sync_every=1))
    router.announce("http://127.0.0.1:0")
    sup = FakeSupervisor()

    def factory(rid):
        from mxnet_tpu.fleet import ReplicaSpec
        return ReplicaSpec(rid, ["true"])

    sc = Autoscaler(router, sup, factory, model,
                    policy=policy or _policy(),
                    clock=clock or FakeClock())
    return sc, router, sup


# ---------------------------------------------------------------------------
# policy
# ---------------------------------------------------------------------------

def test_policy_defaults_come_from_flags():
    from mxnet_tpu.config import flags
    pol = AutoscalePolicy()
    assert pol.min_replicas == flags.autoscale_min_replicas
    assert pol.max_replicas == flags.autoscale_max_replicas
    assert pol.cooldown_s == flags.autoscale_cooldown_s
    d = pol.to_dict()
    assert d["high_watermark_s"] == flags.autoscale_high_watermark_s


def test_policy_rejects_inverted_bounds():
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=3, max_replicas=1)


# ---------------------------------------------------------------------------
# floor + warming capacity
# ---------------------------------------------------------------------------

def test_floor_launch_is_immediate_and_ungated():
    clock = FakeClock()
    sc, router, sup = _scaler(clock=clock)
    d = sc.step()
    assert d["action"] == "scale_up"
    assert d["reason"] == "below min_replicas"
    assert sup.added == ["m-as1"]
    assert "m-as1" in sc.owned


def test_pending_launch_counts_as_capacity():
    clock = FakeClock()
    sc, router, sup = _scaler(clock=clock)
    sc.step()
    # launch in flight: the floor must NOT double-launch
    for _ in range(5):
        clock.advance(0.5)
        d = sc.step()
        assert d["action"] == "steady", d
    assert sup.added == ["m-as1"]


def test_warming_replica_counts_as_capacity():
    """A registered, ready=False replica is capacity-being-born; the
    floor check must not storm launches through its warmup window."""
    clock = FakeClock()
    sc, router, sup = _scaler(clock=clock)
    sc.step()
    _register(router.registry, "m-as1", ready=False)   # warming
    for _ in range(5):
        clock.advance(0.5)
        d = sc.step()
        assert d["action"] == "steady", d
    assert sup.added == ["m-as1"]


def test_expired_launch_is_retried():
    clock = FakeClock()
    sc, router, sup = _scaler(clock=clock)
    sc.step()
    clock.advance(sc.policy.launch_timeout_s + 1.0)    # never registered
    d = sc.step()
    assert d["action"] == "scale_up"
    assert sup.added == ["m-as1", "m-as2"]
    assert "m-as1" not in sc.owned


# ---------------------------------------------------------------------------
# scale-up: hysteresis + break-even
# ---------------------------------------------------------------------------

def _pressurize(router, rid="m-as1", load_s=5.0):
    _register(router.registry, rid, ready=True,
              load={"load_s": load_s, "queue_depth": 9, "unit_s": 0.1})


def test_scale_up_needs_sustained_breach():
    clock = FakeClock()
    sc, router, sup = _scaler(clock=clock,
                              policy=_policy(cooldown_s=0.0))
    sc.step()
    _pressurize(router)
    d = sc.step(clock.advance(0.5))            # breach round 1
    assert d["action"] == "steady"
    d = sc.step(clock.advance(0.5))            # breach round 2 -> act
    assert d["action"] == "scale_up"
    assert "beats startup" in d["reason"]
    assert sup.added == ["m-as1", "m-as2"]


def test_break_even_holds_marginal_gains():
    clock = FakeClock()
    sc, router, sup = _scaler(
        clock=clock,
        policy=_policy(cooldown_s=0.0, startup_cost_s=100.0))
    sc.step()
    _pressurize(router, load_s=5.0)    # gain 5/1 - 5/2 = 2.5s < 100s
    sc.step(clock.advance(0.5))
    d = sc.step(clock.advance(0.5))
    assert d["action"] == "held:break_even"
    assert d["wanted"] == "scale_up"
    assert sup.added == ["m-as1"]


def test_cooldown_suppresses_and_journals():
    clock = FakeClock()
    sc, router, sup = _scaler(clock=clock)    # cooldown 10s
    sc.step()                                  # floor launch (action t)
    _pressurize(router)
    sc.step(clock.advance(0.5))
    d = sc.step(clock.advance(0.5))
    assert d["action"] == "held:cooldown"
    assert d["wanted"] == "scale_up"
    # cooldown elapsed: the sustained breach may now act
    d = sc.step(clock.advance(sc.policy.cooldown_s + 1.0))
    assert d["action"] == "scale_up"


def test_max_replicas_caps_scale_up():
    clock = FakeClock()
    sc, router, sup = _scaler(
        clock=clock, policy=_policy(max_replicas=1, cooldown_s=0.0))
    sc.step()
    _pressurize(router)
    for _ in range(4):
        d = sc.step(clock.advance(0.5))
        assert d["action"] == "steady", d
    assert sup.added == ["m-as1"]


# ---------------------------------------------------------------------------
# residual signals: KV page occupancy + p99-vs-deadline
# ---------------------------------------------------------------------------

def test_kv_page_occupancy_gates_scale_out_bypassing_break_even():
    clock = FakeClock()
    sc, router, sup = _scaler(
        clock=clock,
        policy=_policy(cooldown_s=0.0, startup_cost_s=100.0))
    sc.step()                                  # floor launch m-as1
    # queue-seconds calm, but the decode KV pool is nearly exhausted:
    # waiting cannot free pages, so break-even must not hold this
    _register(router.registry, "m-as1", ready=True,
              load={"load_s": 0.0, "queue_depth": 0, "unit_s": 0.1,
                    "kv_page_occupancy": 0.97})
    d = sc.step(clock.advance(0.5))            # breach round 1
    assert d["action"] == "steady"
    d = sc.step(clock.advance(0.5))            # breach round 2 -> act
    assert d["action"] == "scale_up"
    assert "kv page occupancy" in d["reason"]
    assert sup.added == ["m-as1", "m-as2"]


def test_p99_vs_deadline_gates_scale_out():
    clock = FakeClock()
    sc, router, sup = _scaler(
        clock=clock,
        policy=_policy(cooldown_s=0.0, startup_cost_s=100.0))
    sc.step()
    # tail latency is past the request deadline while the mean load
    # looks fine: requests are about to expire, add capacity
    _register(router.registry, "m-as1", ready=True,
              load={"load_s": 0.0, "queue_depth": 0, "unit_s": 0.1,
                    "p99_ms": 600.0, "deadline_ms": 500.0})
    sc.step(clock.advance(0.5))
    d = sc.step(clock.advance(0.5))
    assert d["action"] == "scale_up"
    assert "p99/deadline" in d["reason"]
    assert sup.added == ["m-as1", "m-as2"]


def test_hot_fleet_never_scales_down():
    clock = FakeClock()
    sc, router, sup = _scaler(clock=clock,
                              policy=_policy(cooldown_s=0.0,
                                             max_replicas=2))
    sc.step()
    _register(router.registry, "m-as1", ready=True,
              load={"load_s": 0.0, "queue_depth": 0})
    sc.owned.add("m-as2")
    # idle by queue-seconds, but one replica's KV pool is nearly full:
    # the hot signal routes to the high branch, so the low-watermark
    # breach never accumulates
    _register(router.registry, "m-as2", ready=True,
              load={"load_s": 0.0, "queue_depth": 0,
                    "kv_page_occupancy": 0.95})
    for _ in range(4):
        d = sc.step(clock.advance(0.5))
        assert d["action"] == "steady", d
    assert sup.stopped == []
    assert not router.registry.get("m-as2").draining
    # occupancy recedes: the idle fleet may drain again
    _register(router.registry, "m-as2", ready=True,
              load={"load_s": 0.0, "queue_depth": 0,
                    "kv_page_occupancy": 0.2})
    sc.step(clock.advance(0.5))
    d = sc.step(clock.advance(0.5))
    assert d["action"] == "scale_down"


# ---------------------------------------------------------------------------
# scale-down: drain, then reap once idle
# ---------------------------------------------------------------------------

def _two_replica_fleet(clock):
    sc, router, sup = _scaler(clock=clock,
                              policy=_policy(cooldown_s=0.0))
    sc.step()
    _register(router.registry, "m-as1", ready=True,
              load={"load_s": 0.0, "queue_depth": 0})
    sc.owned.add("m-as2")
    _register(router.registry, "m-as2", ready=True,
              load={"load_s": 0.0, "queue_depth": 0})
    return sc, router, sup


def test_scale_down_drains_least_loaded_then_reaps():
    clock = FakeClock()
    sc, router, sup = _two_replica_fleet(clock)
    router.registry.heartbeat("m-as1", load={"load_s": 0.01,
                                             "queue_depth": 1})
    sc.step(clock.advance(0.5))                # low breach 1
    d = sc.step(clock.advance(0.5))            # low breach 2 -> drain
    assert d["action"] == "scale_down"
    assert d["replica"] == "m-as2"             # the idle one
    rep = router.registry.get("m-as2")
    assert rep.draining
    assert sup.stopped == []                   # drained, NOT killed
    # still busy: one in-flight request defers the reap
    router.registry.note_inflight("m-as2", +1)
    sc.step(clock.advance(0.5))
    assert sup.stopped == []
    # idle now: reaped, ownership released
    router.registry.note_inflight("m-as2", -1)
    sc.step(clock.advance(0.5))
    assert sup.stopped == ["m-as2"]
    assert "m-as2" not in sc.owned


def test_warming_replica_is_never_the_drain_victim():
    """The launch/drain-storm regression: a freshly launched replica
    reports no load (score 0) while warming, which made it the
    least-loaded drain victim — the scaler killed every replica it
    launched before it ever turned ready. Low-pressure readings from
    an unsettled fleet must neither count toward the breach nor drain
    a not-ready replica."""
    clock = FakeClock()
    sc, router, sup = _scaler(clock=clock,
                              policy=_policy(cooldown_s=0.0))
    sc.step()
    _register(router.registry, "m-as1", ready=True,
              load={"load_s": 0.0, "queue_depth": 0})
    sc.owned.add("m-as2")
    _register(router.registry, "m-as2", ready=False)   # warming
    for _ in range(6):
        d = sc.step(clock.advance(0.5))
        assert d["action"] == "steady", d
    assert not router.registry.get("m-as2").draining
    assert sup.stopped == []
    # once it settles, a sustained low breach may drain normally
    router.registry.heartbeat("m-as2", ready=True,
                              load={"load_s": 0.0, "queue_depth": 0})
    d = sc.step(clock.advance(0.5))           # settled: breach 1 of 2
    assert d["action"] == "steady"
    d = sc.step(clock.advance(0.5))           # breach 2 -> drain
    assert d["action"] == "scale_down"


def test_scale_down_never_drops_below_min():
    clock = FakeClock()
    sc, router, sup = _scaler(clock=clock,
                              policy=_policy(cooldown_s=0.0))
    sc.step()
    _register(router.registry, "m-as1", ready=True,
              load={"load_s": 0.0, "queue_depth": 0})
    for _ in range(5):
        d = sc.step(clock.advance(0.5))
        assert d["action"] == "steady", d
    assert not router.registry.get("m-as1").draining


def test_scale_down_only_touches_owned_replicas():
    clock = FakeClock()
    sc, router, sup = _scaler(clock=clock,
                              policy=_policy(cooldown_s=0.0))
    sc.step()
    _register(router.registry, "m-as1", ready=True,
              load={"load_s": 0.0, "queue_depth": 0})
    # a second replica this scaler did NOT launch (operator-started)
    _register(router.registry, "operator-1", ready=True,
              load={"load_s": 0.0, "queue_depth": 0})
    for _ in range(4):
        sc.step(clock.advance(0.5))
    # capacity > min and pressure is low, but the only candidates are
    # owned — m-as1 (dropping it goes below min is fine: want_down
    # checks capacity) — operator-1 must never be drained
    assert not router.registry.get("operator-1").draining


# ---------------------------------------------------------------------------
# durability: WAL round-trip, restore, snapshot
# ---------------------------------------------------------------------------

def test_decisions_replay_through_the_wal(tmp_path):
    clock = FakeClock()
    sc, router, sup = _scaler(tmp_path, clock=clock, journal=True)
    sc.step()                                  # scale_up journaled
    st = replay(str(tmp_path / "j"))[0] if isinstance(
        replay(str(tmp_path / "j")), tuple) else replay(
            str(tmp_path / "j"))
    # router-side reducer state matches the journal's
    assert "m" in router.autoscale_state
    rec = router.autoscale_state["m"]
    assert rec["owned"] == ["m-as1"]
    assert rec["last"]["action"] == "scale_up"


def test_fleet_state_folds_autoscale_records():
    st = FleetState()
    st.apply(1, "autoscale", {"scaler": "m", "model": "m",
                              "action": "scale_up", "seq": 1,
                              "owned": ["m-as1"], "replica": "m-as1"})
    st.apply(2, "autoscale", {"scaler": "m", "model": "m",
                              "action": "held:cooldown", "seq": 2,
                              "owned": ["m-as1"]})
    assert st.autoscale["m"]["owned"] == ["m-as1"]
    assert st.autoscale["m"]["last"]["action"] == "held:cooldown"
    d = st.to_dict()
    back = FleetState.from_dict(d)
    assert back.autoscale == st.autoscale
    # unknown kinds stay ignored (backward-safe journals)
    back.apply(3, "a_future_kind", {"x": 1})


def test_promoted_router_restores_scaler_state(tmp_path):
    clock = FakeClock()
    sc, router, sup = _scaler(tmp_path, clock=clock, journal=True)
    sc.step()
    router.journal.close()
    promoted = Router.from_journal(str(tmp_path / "j"))
    assert promoted.autoscale_state["m"]["owned"] == ["m-as1"]
    snap = promoted.fleet_snapshot()
    assert snap["autoscale"]["m"]["last"]["action"] == "scale_up"
    # a fresh Autoscaler against the promoted router inherits its
    # owned set (it may drain those replicas) and its sequence
    sup2 = FakeSupervisor()
    sc2 = Autoscaler(promoted, sup2, sc.spec_factory, "m",
                     policy=_policy(), clock=clock)
    assert sc2.owned == {"m-as1"}
    assert sc2._seq >= 1


def test_snapshot_shape():
    clock = FakeClock()
    sc, router, sup = _scaler(clock=clock)
    sc.step()
    snap = sc.snapshot()
    assert snap["scaler"] == "m"
    assert snap["owned"] == ["m-as1"]
    assert snap["pending"] == ["m-as1"]
    assert snap["policy"]["min_replicas"] == 1


# ---------------------------------------------------------------------------
# mixed-layout refusal
# ---------------------------------------------------------------------------

def _layout(fp):
    return {"fingerprint": fp, "mesh": {"max_slots": 4}}


def test_set_split_refuses_mixed_layouts():
    reg = ReplicaRegistry(heartbeat_timeout_s=3600.0)
    router = Router(registry=reg)
    router.announce("http://127.0.0.1:0")
    _register(reg, "a", model="g", mode="generate",
              layout=_layout("aaaaaaaaaaaa"))
    _register(reg, "b", model="g", mode="generate",
              layout=_layout("bbbbbbbbbbbb"))
    with pytest.raises(MXNetError, match="mixed parameter layouts"):
        router.set_split("g", {"0": 1.0})


def test_set_split_allows_agreeing_and_unknown_layouts():
    reg = ReplicaRegistry(heartbeat_timeout_s=3600.0)
    router = Router(registry=reg)
    router.announce("http://127.0.0.1:0")
    _register(reg, "a", model="g", mode="generate",
              layout=_layout("aaaaaaaaaaaa"))
    _register(reg, "b", model="g", mode="generate",
              layout=_layout("aaaaaaaaaaaa"))
    _register(reg, "c", model="g", mode="generate", layout=None)
    router.set_split("g", {"0": 1.0})          # no raise
    assert router.splits["g"] == {"0": 1.0}


def test_start_canary_refuses_mixed_layouts():
    reg = ReplicaRegistry(heartbeat_timeout_s=3600.0)
    router = Router(registry=reg)
    router.announce("http://127.0.0.1:0")
    _register(reg, "a", model="g", mode="generate",
              layout=_layout("aaaaaaaaaaaa"))
    rep = reg.register({
        "id": "b", "url": "http://b.invalid", "model": "g",
        "version": "1", "mode": "generate", "ready": True,
        "layout": _layout("bbbbbbbbbbbb")})
    assert rep is not None
    with pytest.raises(MXNetError, match="mixed parameter layouts"):
        router.start_canary("g", "1", split=0.2)
