"""Test configuration.

Forces 8 virtual CPU devices so multi-chip sharding tests (mesh/pjit/
shard_map) run without TPU hardware — the strategy SURVEY.md §4 prescribes
as the analog of the reference's N-local-process dist tests
(ci/docker/runtime_functions.sh:901-930).

The suite is pinned to the CPU platform (fast, hermetic, independent of the
axon TPU tunnel); real-chip verification happens via bench.py and the verify
skill. Set MXNET_TEST_PLATFORM=tpu to run the same suite against the chip
(the reference's test_operator_gpu.py pattern).
"""
import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ.setdefault("MXNET_TEST_DEVICE", "cpu")

import jax  # noqa: E402
from mxnet_tpu.config import flags  # noqa: E402  (no jax side effects)

if flags.test_platform == "cpu":
    jax.config.update("jax_platforms", "cpu")
    # Custom-op tests escape to host via jax.pure_callback; with async CPU
    # dispatch the main thread races ahead and the callback's nested jax
    # work can starve the client's thread pool (a hard deadlock on
    # single-core CI boxes). Inline dispatch is deterministic and must be
    # set before the CPU client is created.
    jax.config.update("jax_cpu_enable_async_dispatch", False)

import pytest  # noqa: E402


def pytest_configure(config):
    # tier-1 CI runs `-m 'not slow'`; multi-process kill/restart drills
    # (minutes of wall clock) opt out of it with this marker
    config.addinivalue_line(
        "markers", "slow: multi-process / long-running test, excluded "
        "from the tier-1 fast suite")


@pytest.fixture
def ctx():
    from mxnet_tpu import test_utils
    return test_utils.default_context()


RESNET_STEP_BATCH = 128


@pytest.fixture(scope="session")
def resnet_step_text():
    """Pre-optimization StableHLO of the benched ResNet-50 fused step.

    One session-scoped lowering (a few seconds) shared by every chip-free
    HLO budget: the convert/transpose ratchets (test_step_hlo_budget) and
    the MXL505 fusion-bytes ratchet (test_lint_clean). Lowered at the
    bench batch with the default kernel tier — the committed budgets
    describe the program users get without opting in to anything."""
    if jax.devices()[0].platform != "cpu":
        pytest.skip("lowering analysis is defined for the CPU backend")
    import sys
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    sys.path.insert(0, tools)
    try:
        from diagnose_step_hlo import build_fused, lower_step
    finally:
        sys.path.pop(0)
    mod = build_fused(RESNET_STEP_BATCH)
    return lower_step(mod).as_text()
