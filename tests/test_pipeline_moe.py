"""Pipeline (pp) and expert (ep) parallelism on the 8-virtual-device
mesh: the remaining two axes of the dp/tp/pp/sp/ep matrix.

Correctness bar: the parallel result must equal the plain sequential
computation of the same parameters, forward AND backward.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mxnet_tpu.parallel import (make_pipeline, stack_stage_params,
                                moe_layer, init_moe_params,
                                shard_moe_params, make_mesh)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs the 8-virtual-device mesh")


def _stage_fn(params, x):
    return jax.nn.relu(x @ params["w"] + params["b"])


def _stage_params(n_stage, d, seed=0):
    rng = np.random.RandomState(seed)
    return [{"w": jnp.asarray(rng.randn(d, d).astype("f4") / np.sqrt(d)),
             "b": jnp.asarray(rng.randn(d).astype("f4") * 0.1)}
            for _ in range(n_stage)]


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


@pytest.mark.parametrize("pp,n_micro", [(2, 4), (4, 4), (4, 8)])
def test_pipeline_matches_sequential(pp, n_micro):
    d, batch = 16, 16
    mesh = make_mesh({"pp": pp}, devices=jax.devices()[:pp])
    stages = _stage_params(pp, d)
    stacked = stack_stage_params(stages, mesh, "pp")
    pipe = make_pipeline(_stage_fn, mesh, "pp", n_microbatch=n_micro)
    x = jnp.asarray(np.random.RandomState(1).randn(batch, d).astype("f4"))
    out = jax.jit(pipe)(stacked, x)
    ref = _sequential(stages, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_pipeline_gradients_match_sequential():
    pp, d, batch = 4, 8, 8
    mesh = make_mesh({"pp": pp}, devices=jax.devices()[:pp])
    stages = _stage_params(pp, d, seed=3)
    stacked = stack_stage_params(stages, mesh, "pp")
    pipe = make_pipeline(_stage_fn, mesh, "pp", n_microbatch=4)
    x = jnp.asarray(np.random.RandomState(2).randn(batch, d).astype("f4"))

    def loss_pipe(p):
        return jnp.sum(pipe(p, x) ** 2)

    def loss_seq(plist):
        return jnp.sum(_sequential(plist, x) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    g_seq = jax.grad(loss_seq)(stages)
    for i in range(pp):
        np.testing.assert_allclose(np.asarray(g_pipe["w"][i]),
                                   np.asarray(g_seq[i]["w"]),
                                   rtol=5e-4, atol=5e-5)
        np.testing.assert_allclose(np.asarray(g_pipe["b"][i]),
                                   np.asarray(g_seq[i]["b"]),
                                   rtol=5e-4, atol=5e-5)


def _moe_reference(params, x, capacity_factor=2.0):
    """Token-by-token loop over the same routing rules."""
    import math
    n, d = x.shape
    e = params["gate"].shape[1]
    c = max(1, int(math.ceil(n / e * capacity_factor)))
    logits = np.asarray(x @ params["gate"])
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    expert = probs.argmax(-1)
    used = np.zeros(e, int)
    y = np.array(x, copy=True)
    for i in range(n):
        ex = int(expert[i])
        if used[ex] >= c:
            continue   # dropped: residual only
        used[ex] += 1
        h = np.maximum(np.asarray(x[i]) @ np.asarray(params["w1"][ex]), 0)
        out = h @ np.asarray(params["w2"][ex])
        y[i] = np.asarray(x[i]) + probs[i, ex] * out
    return y


@pytest.mark.parametrize("ep", [1, 2, 4])
def test_moe_matches_reference_loop(ep):
    d, h, e, n = 8, 16, 4, 32
    params = init_moe_params(0, d, h, e)
    x = jnp.asarray(np.random.RandomState(5).randn(n, d).astype("f4"))
    ref = _moe_reference(params, x)
    if ep == 1:
        out = jax.jit(moe_layer)(params, x)
    else:
        mesh = make_mesh({"ep": ep}, devices=jax.devices()[:ep])
        sharded = shard_moe_params(params, mesh, "ep")
        out = jax.jit(moe_layer)(sharded, x)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-5)


def test_moe_expert_weights_actually_sharded():
    mesh = make_mesh({"ep": 4}, devices=jax.devices()[:4])
    params = shard_moe_params(init_moe_params(0, 8, 16, 8), mesh, "ep")
    shard_shapes = {s.data.shape for s in params["w1"].addressable_shards}
    assert shard_shapes == {(2, 8, 16)}   # 8 experts / 4 devices


def test_moe_trains():
    """ep=2 end-to-end: gradient descent reduces a regression loss."""
    d, h, e, n = 8, 16, 4, 64
    mesh = make_mesh({"ep": 2}, devices=jax.devices()[:2])
    params = shard_moe_params(init_moe_params(1, d, h, e), mesh, "ep")
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(n, d).astype("f4"))
    target = jnp.asarray(rng.randn(n, d).astype("f4") * 0.1)

    @jax.jit
    def step(p):
        def loss(p):
            return jnp.mean((moe_layer(p, x) - x - target) ** 2)
        l, g = jax.value_and_grad(loss)(p)
        return l, jax.tree.map(lambda a, b: a - 0.5 * b, p, g)

    l0, params = step(params)
    for _ in range(30):
        l, params = step(params)
    assert float(l) < float(l0) * 0.7, (float(l0), float(l))


def test_aux_load_balance_loss():
    from mxnet_tpu.parallel import aux_load_balance_loss
    d, e = 8, 4
    params = init_moe_params(0, d, 16, e)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, d).astype("f4"))
    l = float(aux_load_balance_loss(params, x))
    assert l > 0
    # a perfectly-balanced uniform router scores E^2 * E * (1/E * 1/E) = 1
    params_uniform = dict(params, gate=jnp.zeros((d, e), jnp.float32))
    lu = float(aux_load_balance_loss(params_uniform, x))
    np.testing.assert_allclose(lu, 1.0, rtol=0.2)
