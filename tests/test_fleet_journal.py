"""Router HA (mxnet_tpu.fleet.journal + fencing): write-ahead fleet
journal, cursor-durable sessions, epoch-fenced failover — chip-free.

The acceptance properties: (1) replay is idempotent and tolerates a
torn/corrupt segment tail without losing the durable prefix; (2)
snapshot+tail compaction replays to exactly the state the pure log
replays to; (3) an in-process promotion (`Router.from_journal`)
restores the replica table, bumps the fencing epoch, and resumes an
orphaned generate session from its journaled hop cursor with ZERO new
device syncs; (4) stale-epoch writes are 409'd and a stale router is
refused by the announcer; (5) the registry's liveness clock is
injectable and NTP-proof.
"""
import json
import os
import threading
import time

import pytest

from mxnet_tpu import profiler
from mxnet_tpu.fleet import (FleetJournal, JournalTailer, ReplicaRegistry,
                             Router, fencing)
from mxnet_tpu.fleet.journal import (LeaseMonitor, lease_holder_alive,
                                     read_segment, release_lease, replay,
                                     write_lease, _segments)


@pytest.fixture(autouse=True)
def _fresh_epoch():
    fencing.reset()
    yield
    fencing.reset()


def _register(registry, rid, *, model="m", version="0", mode="predict",
              ready=True, load=None, spec=None):
    return registry.register({
        "id": rid, "url": "http://%s.invalid" % rid, "model": model,
        "version": version, "mode": mode, "ready": ready,
        "load": load or {}, "spec": spec})


def _journaled_router(tmp_path, **kw):
    reg = ReplicaRegistry(heartbeat_timeout_s=60.0)
    router = Router(registry=reg, **kw)
    router.attach_journal(FleetJournal(str(tmp_path / "j"),
                                       sync_every=2))
    router.announce("http://127.0.0.1:0")
    return router


# ---------------------------------------------------------------------------
# journal: round trip, idempotence, torn tails, corruption, compaction
# ---------------------------------------------------------------------------

def test_journal_round_trip_and_idempotent_replay(tmp_path):
    router = _journaled_router(tmp_path)
    _register(router.registry, "a", mode="generate",
              spec={"vocab": 61, "max_prompt_len": 8, "max_context": 32})
    router.registry.heartbeat("a", ready=True)
    router.set_split("m", {"0": 1.0})
    router.journal.sync()

    st1, stats1 = replay(str(tmp_path / "j"))
    st2, stats2 = replay(str(tmp_path / "j"))     # double replay
    assert st1.to_dict() == st2.to_dict()
    assert stats1["records"] == stats2["records"]
    assert stats1["torn_segments"] == 0
    assert list(st1.replicas) == ["a"]
    assert st1.replicas["a"]["spec"]["max_context"] == 32
    assert st1.splits == {"m": {"0": 1.0}}
    assert st1.epoch == 1
    # seq <= applied_seq is a no-op (idempotence at the record level)
    seq_before = st1.applied_seq
    assert not st1.apply(seq_before, "split", {"model": "x",
                                               "weights": {"0": 1.0}})
    assert "x" not in st1.splits


def test_journal_truncated_tail_keeps_prefix(tmp_path):
    j = FleetJournal(str(tmp_path), sync_every=1)
    j.append("epoch", {"epoch": 3, "address": "http://x"})
    j.append("register", {"id": "a", "url": "u", "model": "m",
                          "version": "0", "mode": "predict"})
    j.close()
    seg = _segments(str(tmp_path))[-1][1]
    with open(seg, "ab") as f:
        f.write(b"\x40\x00\x00\x00\x99\x99")     # torn frame header+junk
    records, _, clean = read_segment(seg)
    assert not clean and len(records) == 2       # prefix intact
    st, stats = replay(str(tmp_path))
    assert stats["torn_segments"] == 1
    assert st.epoch == 3 and list(st.replicas) == ["a"]


def test_journal_crc_mismatch_rejected_without_losing_prefix(tmp_path):
    j = FleetJournal(str(tmp_path), sync_every=1)
    j.append("epoch", {"epoch": 5, "address": None})
    j.append("deregister", {"id": "ghost"})
    j.close()
    seg = _segments(str(tmp_path))[-1][1]
    blob = bytearray(open(seg, "rb").read())
    blob[-3] ^= 0xFF                 # flip a payload byte of record 2
    open(seg, "wb").write(bytes(blob))
    records, _, clean = read_segment(seg)
    assert not clean and [r[1] for r in records] == ["epoch"]
    st, stats = replay(str(tmp_path))
    assert st.epoch == 5 and stats["torn_segments"] == 1


def test_journal_reopen_rotates_past_torn_tail(tmp_path):
    # crash with a torn tail, reopen, append: the new record must land
    # in a FRESH segment, never appended through the garbage
    j = FleetJournal(str(tmp_path), sync_every=1)
    j.append("epoch", {"epoch": 1, "address": None})
    j.close()
    seg1 = _segments(str(tmp_path))[-1][1]
    with open(seg1, "ab") as f:
        f.write(b"\x10\x00")
    j2 = FleetJournal(str(tmp_path), start_seq=1, sync_every=1)
    j2.append("epoch", {"epoch": 2, "address": "http://y"})
    j2.close()
    segs = _segments(str(tmp_path))
    assert len(segs) == 2 and segs[-1][1] != seg1
    st, _ = replay(str(tmp_path))
    assert st.epoch == 2 and st.address == "http://y"


def test_compaction_equivalence_and_segment_truncation(tmp_path):
    router = _journaled_router(tmp_path)
    _register(router.registry, "a")
    _register(router.registry, "b", mode="generate")
    router.registry.set_draining("b", True)
    jdir = str(tmp_path / "j")
    pure_log_state, _ = replay(jdir)

    router.journal.compact(router.export_state())
    # snapshot replaced the log; post-compact mutations form the tail
    _register(router.registry, "c")
    router.registry.deregister("a")
    router.journal.sync()
    st, stats = replay(jdir)
    assert stats["snapshot_seq"] == pure_log_state.applied_seq
    assert sorted(st.replicas) == ["b", "c"]
    assert st.replicas["b"]["draining"] is True
    # compaction equivalence: snapshot state == what the pure log held
    snap = json.load(open(os.path.join(
        jdir, sorted(n for n in os.listdir(jdir)
                     if n.startswith("snap-"))[-1])))
    assert snap == pure_log_state.to_dict()
    # old segments are gone; replay cost is O(snapshot + tail)
    assert len(_segments(jdir)) == 1


# ---------------------------------------------------------------------------
# tailer + lease: what the warm standby runs
# ---------------------------------------------------------------------------

def test_journal_tailer_follows_appends_and_snapshots(tmp_path):
    router = _journaled_router(tmp_path)
    jdir = str(tmp_path / "j")
    tailer = JournalTailer(jdir)
    _register(router.registry, "a")
    router.journal.sync()
    tailer.poll()
    assert list(tailer.state.replicas) == ["a"]
    router.journal.compact(router.export_state())
    _register(router.registry, "b")
    router.journal.sync()
    tailer.poll()
    assert sorted(tailer.state.replicas) == ["a", "b"]
    assert tailer.state.epoch == 1


def test_lease_monitor_measures_content_change_not_wall_clock(tmp_path):
    d = str(tmp_path)
    write_lease(d, {"epoch": 1, "beat": 0})
    mon = LeaseMonitor(d)
    assert not mon.expired(10.0)
    time.sleep(0.15)
    assert mon.expired(0.1)           # content stopped changing
    write_lease(d, {"epoch": 1, "beat": 1})
    assert not mon.expired(0.1)       # a beat resets the age
    # startup guard: a live writer is detected, a silent one is not
    assert not lease_holder_alive(d, wait_s=0.1)
    stop = threading.Event()

    def beat():
        n = 2
        while not stop.is_set():
            write_lease(d, {"epoch": 1, "beat": n})
            n += 1
            time.sleep(0.02)

    t = threading.Thread(target=beat, daemon=True)
    t.start()
    try:
        assert lease_holder_alive(d, wait_s=0.2)
    finally:
        stop.set()
        t.join(2.0)
    release_lease(d)
    assert mon.expired(3600.0) or mon.age_s() >= 0.0


def test_segment_rotation_at_size_cap(tmp_path):
    # satellite: segments rotate once the live one crosses the cap,
    # not only at open/compaction — bounding the replication unit
    j = FleetJournal(str(tmp_path), sync_every=1, segment_bytes=256)
    for i in range(30):
        j.append("state", {"id": "r%d" % i, "pad": "x" * 32})
    j.close()
    segs = _segments(str(tmp_path))
    assert len(segs) > 1, "no size-based rotation happened"
    # every sealed segment respects the cap (only the newest may be
    # mid-fill); all records survive rotation, in order
    for _, p in segs[:-1]:
        assert os.path.getsize(p) >= 256
    st, stats = replay(str(tmp_path))
    assert st.applied_seq == 30
    assert stats["records"] == 30 and stats["torn_segments"] == 0
    # rotation disabled: one segment no matter the volume
    j2 = FleetJournal(str(tmp_path / "flat"), sync_every=1,
                      segment_bytes=0)
    for i in range(30):
        j2.append("state", {"id": "r%d" % i, "pad": "x" * 32})
    j2.close()
    assert len(_segments(str(tmp_path / "flat"))) == 1


def test_tailer_idle_backoff_and_catchup_burst(tmp_path):
    # satellite: no busy-polling — empty polls back off exponentially
    # toward the cap, any progress snaps the delay back to zero
    import random
    j = FleetJournal(str(tmp_path), sync_every=1)
    tailer = JournalTailer(str(tmp_path), idle_base_s=0.01,
                           idle_cap_s=0.5)
    rng = random.Random(3)
    assert tailer.next_delay_s(rng=rng) == 0.0     # never slept yet
    delays = []
    for _ in range(10):
        assert tailer.poll() == 0
        delays.append(tailer.next_delay_s(rng=rng))
    assert all(0.0 < d <= 0.5 for d in delays)
    assert delays[-1] > delays[0]                  # grew toward the cap
    assert max(delays) <= 0.5 + 1e-9               # capped
    j.append("epoch", {"epoch": 1, "address": None})
    assert tailer.poll() == 1
    assert tailer.next_delay_s(rng=rng) == 0.0     # catch-up burst
    j.close()


def test_announcer_retries_transient_conn_failures(monkeypatch):
    # satellite: conn-refused/reset while a router restarts is retried
    # on the shared backoff schedule — the replica rejoins on its own
    from mxnet_tpu.fleet import registry as registry_mod
    from mxnet_tpu.fleet.registry import ReplicaAnnouncer
    calls = []

    def flaky_post(url, payload, timeout_s=None):
        calls.append(url)
        if len(calls) <= 2:
            raise ConnectionRefusedError("router is between incarnations")
        return {"registered": payload.get("id"), "epoch": 1}

    monkeypatch.setattr(registry_mod, "_post_json", flaky_post)
    ann = ReplicaAnnouncer("http://router:1", {"id": "r0", "url": "u",
                                               "model": "m",
                                               "version": "0",
                                               "mode": "predict"},
                           lambda: {"ready": True, "reason": None,
                                    "load": {}}, interval_s=0.2)
    ann.start()
    try:
        assert ann.registered.wait(10.0), \
            "announcer never recovered from transient conn failures"
    finally:
        ann.stop(deregister=False)
    assert len(calls) >= 3                 # 2 failures + the success
    assert ann.conn_failures == 0          # reset on success
    assert ann.stale_router_rejections == 0


def test_announcer_backoff_schedule_is_shared(monkeypatch):
    # the retry delays come from supervisor.backoff_delay (capped at
    # the heartbeat interval), not an ad-hoc sleep
    from mxnet_tpu.fleet import registry as registry_mod
    from mxnet_tpu.fleet import supervisor as supervisor_mod
    from mxnet_tpu.fleet.registry import ReplicaAnnouncer
    waits = []
    real_backoff = supervisor_mod.backoff_delay

    def spy_backoff(attempt, **kw):
        d = real_backoff(attempt, **kw)
        waits.append((attempt, kw.get("base"), kw.get("cap"), d))
        return d

    def always_refused(url, payload, timeout_s=None):
        raise ConnectionRefusedError("down")

    monkeypatch.setattr(supervisor_mod, "backoff_delay", spy_backoff)
    monkeypatch.setattr(registry_mod, "_post_json", always_refused)
    ann = ReplicaAnnouncer("http://router:1", {"id": "r0", "url": "u",
                                               "model": "m",
                                               "version": "0",
                                               "mode": "predict"},
                           lambda: {"ready": True, "reason": None,
                                    "load": {}}, interval_s=0.05)
    ann.start()
    try:
        deadline = time.monotonic() + 10.0
        while len(waits) < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        ann.stop(deregister=False)
    assert len(waits) >= 3
    attempts = [w[0] for w in waits[:3]]
    assert attempts == [0, 1, 2]           # consecutive-failure schedule
    for _, base, cap, d in waits:
        assert cap == pytest.approx(0.05)  # capped at the interval
        # the schedule's jitter is ±50% around min(cap, base * 2^n)
        assert 0.0 < d <= 0.05 * 1.5 + 1e-9
    assert ann.conn_failures >= 3


def test_tailer_adopts_snapshot_when_compaction_races_mid_poll(
        tmp_path, monkeypatch):
    # the exact race the randomized property test samples, forced
    # deterministically: a compaction lands BETWEEN the tailer's
    # snapshot check and its segment scan, so the scan sees only the
    # fresh post-compaction segment (seq jumps past the records that
    # were folded into the snapshot). Without gap detection the tailer
    # applies across the jump and silently loses the folded records —
    # the snapshot is behind applied_seq forever after.
    from mxnet_tpu.fleet import journal as journal_mod
    jdir = str(tmp_path)
    j = FleetJournal(jdir, sync_every=1)
    tailer = JournalTailer(jdir)
    j.append("register", {"id": "early", "url": "u", "model": "m",
                          "version": "0", "mode": "predict"})
    assert tailer.poll() == 1
    # a record the tailer has NOT yet seen, about to be compacted away
    j.append("register", {"id": "mid", "url": "u", "model": "m",
                          "version": "0", "mode": "predict"})

    real_segments = journal_mod._segments
    armed = [None]

    def racing_segments(d):
        fn, armed[0] = armed[0], None
        if fn is not None:
            fn()        # fires between _snapshots() and _segments()
        return real_segments(d)

    def inject():
        st, _ = replay(jdir)
        j.compact(st)          # "mid" now lives only in the snapshot
        j.append("register", {"id": "late", "url": "u", "model": "m",
                              "version": "0", "mode": "predict"})

    monkeypatch.setattr(journal_mod, "_segments", racing_segments)
    armed[0] = inject
    tailer.poll()
    assert tailer.state.applied_seq == j.seq
    assert "mid" in tailer.state.replicas, \
        "compaction race lost records: tailer jumped the seq gap " \
        "instead of adopting the covering snapshot"
    assert "late" in tailer.state.replicas


def test_replay_never_gaps_or_doubles_under_compaction_race(
        tmp_path, monkeypatch):
    # satellite property test: a tailer polling WHILE the writer
    # appends and compacts never applies a record out of contiguous
    # seq order (gap = silently lost records, double-apply = corrupt
    # reducer state) and converges to exactly what a clean replay says.
    import random
    from mxnet_tpu.fleet import journal as journal_mod

    incarnations = []

    class RecordingState(journal_mod.FleetState):
        def __init__(self):
            super().__init__()
            self.seen = []               # (applied_seq_before, seq)
            incarnations.append(self)

        def apply(self, seq, kind, data):
            before = self.applied_seq
            ok = super().apply(seq, kind, data)
            if ok:
                self.seen.append((before, seq))
            return ok

    monkeypatch.setattr(journal_mod, "FleetState", RecordingState)

    rng = random.Random(1234)
    jdir = str(tmp_path)
    j = FleetJournal(jdir, sync_every=1, segment_bytes=512)
    tailer = JournalTailer(jdir, idle_base_s=1e-4, idle_cap_s=1e-3)
    stop = threading.Event()
    poll_error = []

    def chase():
        try:
            while not stop.is_set():
                tailer.poll()
        except Exception as e:            # pragma: no cover - surfaced
            poll_error.append(e)

    t = threading.Thread(target=chase, daemon=True)
    t.start()
    state = journal_mod.FleetState.__mro__[1]()   # plain shadow state
    total = 0
    try:
        for round_ in range(40):
            for _ in range(rng.randint(1, 6)):
                rec = {"id": "r%d" % rng.randint(0, 9), "url": "u",
                       "model": "m", "version": "0", "mode": "predict",
                       "pad": "x" * rng.randint(0, 40)}
                seq = j.append("register", rec)
                state.apply(seq, "register", rec)
                total += 1
            if rng.random() < 0.5:
                # compact mid-chase: segments vanish under the tailer
                j.compact(dict(state.to_dict(), applied_seq=j.seq))
    finally:
        j.sync()
        deadline = time.monotonic() + 10.0
        while (tailer.state.applied_seq < j.seq
               and time.monotonic() < deadline):
            time.sleep(0.005)
        stop.set()
        t.join(5.0)
        j.close()
    assert not poll_error, poll_error
    # (1) contiguity within every state incarnation: each applied seq
    # extends the previous by exactly one (no gap, no double)
    for st in incarnations:
        for before, seq in st.seen:
            assert seq == before + 1, \
                "seq gap/double under compaction race: %d -> %d" \
                % (before, seq)
    # (2) convergence: the raced tailer ends bitwise at clean replay
    final, _ = replay(jdir)
    assert tailer.state.applied_seq == j.seq
    assert tailer.state.to_dict() == final.to_dict()


# ---------------------------------------------------------------------------
# registry liveness: injectable clock (NTP-proof sweeps)
# ---------------------------------------------------------------------------

def test_registry_sweep_uses_injected_monotonic_clock(monkeypatch):
    fake = [100.0]
    reg = ReplicaRegistry(heartbeat_timeout_s=5.0, clock=lambda: fake[0])
    _register(reg, "a")
    # a wall-clock step must be invisible: the sweep only reads the
    # injected (monotonic) clock
    real_time = time.time
    monkeypatch.setattr(time, "time", lambda: real_time() + 1e6)
    assert reg.sweep() == []
    assert reg.is_routable("a")
    fake[0] += 5.1                    # monotonic time actually passes
    assert reg.sweep() == ["a"]
    assert reg.get("a").dead


# ---------------------------------------------------------------------------
# fencing: epochs are monotonic, stale writes are refused everywhere
# ---------------------------------------------------------------------------

def test_fencing_observe_is_monotonic():
    assert fencing.observe(None)      # unfenced pre-HA traffic passes
    assert fencing.observe(3)
    assert fencing.current() == 3
    assert fencing.observe(3)         # current epoch is fine
    assert not fencing.observe(2)     # stale
    assert fencing.observe(7) and fencing.current() == 7


def test_http_handler_fences_stale_epoch():
    from mxnet_tpu.serve.http import _Handler
    replies = []

    class Stub:
        _fence = _Handler._fence
        _reply = lambda self, code, payload, headers=None: \
            replies.append((code, payload))

    stub = Stub()
    fencing.observe(4)
    assert stub._fence({"prompt": [1], "fleet_epoch": 4})
    assert stub._fence({"prompt": [1]})          # unstamped passes
    assert not stub._fence({"prompt": [1], "fleet_epoch": 3})
    assert replies and replies[0][0] == 409
    assert "stale fleet epoch" in replies[0][1]["error"]
    payload = {"prompt": [1], "fleet_epoch": 4}
    stub._fence(payload)
    assert "fleet_epoch" not in payload          # stamp is stripped


def test_announcer_refuses_stale_epoch_router(monkeypatch):
    from mxnet_tpu.fleet import registry as registry_mod
    from mxnet_tpu.fleet.registry import ReplicaAnnouncer
    fencing.observe(9)                # the promoted router's epoch
    posts = []

    def fake_post(url, payload, timeout_s=None):
        posts.append(url)
        if url.endswith("/fleet/heartbeat"):
            # a revived stale primary: doesn't know us, old epoch
            return {"known": False, "epoch": 2}
        return {"registered": payload.get("id"), "epoch": 2}

    monkeypatch.setattr(registry_mod, "_post_json", fake_post)
    ann = ReplicaAnnouncer("http://stale:1", {"id": "r0", "url": "u",
                                              "model": "m",
                                              "version": "0",
                                              "mode": "predict"},
                           lambda: {"ready": True, "reason": None,
                                    "load": {}}, interval_s=60.0)
    ann.registered.set()              # pretend a prior registration
    ann._beat_once()
    # "unknown id" would normally re-register — but the epoch is stale,
    # so the announcer refuses the zombie
    assert ann.stale_router_rejections == 1
    assert not any(u.endswith("/fleet/register") for u in posts)
    assert fencing.current() == 9


# ---------------------------------------------------------------------------
# the tier-1 promotion smoke: journal -> from_journal -> resumed session
# (in-process, no subprocesses; the full kill drill is
#  tools/fault_drill.py --router-ha)
# ---------------------------------------------------------------------------

def test_promote_restores_fleet_and_resumes_session_zero_syncs(
        tmp_path, monkeypatch):
    jdir = str(tmp_path / "j")
    router1 = Router(registry=ReplicaRegistry(heartbeat_timeout_s=60.0),
                     hop_tokens=4)
    router1.attach_journal(FleetJournal(jdir, sync_every=1))
    router1.announce("http://127.0.0.1:0")
    _register(router1.registry, "g0", mode="generate",
              load={"load_s": 0.0, "unit_s": 0.0})
    _register(router1.registry, "g1", mode="generate",
              load={"load_s": 9.0, "unit_s": 0.0})

    # hop 1 succeeds (cursor journaled), then the PRIMARY "crashes":
    # the exception aborts route_generate mid-session, exactly like the
    # process dying between hops — the session is never finished
    payload = {"prompt": [1, 2, 3], "max_new_tokens": 10,
               "temperature": 0.7, "seed": 5}
    hops1 = []

    def call_then_crash(url, body, timeout_s):
        n = body["max_new_tokens"]
        base = len(body["prompt"])
        hops1.append(body)
        if len(hops1) >= 2:
            raise KeyboardInterrupt("primary dies mid-session")
        return 200, {"tokens": list(range(base, base + n)),
                     "finish_reason": "length", "ttft_ms": 1.0}, {}

    monkeypatch.setattr(router1, "_call", call_then_crash)
    with pytest.raises(KeyboardInterrupt):
        router1.route_generate(dict(payload))
    assert router1._sessions                 # cursor journaled, not done
    sid = Router._session_id(payload)
    assert sid in router1._sessions

    # --- failover: replay into a fresh router (the warm standby) -----
    profiler.reset_sync_counters()
    router2 = Router.from_journal(
        jdir, registry=ReplicaRegistry(heartbeat_timeout_s=60.0),
        hop_tokens=4)
    assert router2.epoch == router1.epoch + 1
    assert sorted(router2.registry.snapshot()["replicas"],
                  key=lambda r: r["id"])[0]["id"] == "g0"
    assert router2._sessions[sid]["orphan"]
    assert router2.replay_stats["resumed_sessions"] == 1
    assert router2.replay_stats["replay_ms"] >= 0.0

    # replica-side fakes are deterministic from the resume prompt, so
    # the retried request's stitched tail is bitwise what an
    # uninterrupted run produces
    def call_ok(url, body, timeout_s):
        n = body["max_new_tokens"]
        base = len(body["prompt"])
        assert body.get("fleet_epoch") == router2.epoch  # fenced hops
        return 200, {"tokens": list(range(base, base + n)),
                     "finish_reason": "length", "ttft_ms": 1.0}, {}

    monkeypatch.setattr(router2, "_call", call_ok)
    code, out, _ = router2.route_generate(dict(payload))
    assert code == 200
    assert out["tokens"] == list(range(3, 13))   # == uninterrupted run
    assert sid not in router2._sessions          # finished + journaled
    # control-plane failover must not touch a device
    sync = profiler.sync_counters()
    assert sync["total"] == 0, sync
    # the journal now carries the epoch bump + session_done durably
    router2.journal.sync()
    st, _ = replay(jdir)
    assert st.epoch == router2.epoch and not st.sessions
    snap = router2.fleet_snapshot()
    assert snap["epoch"] == router2.epoch
    assert snap["journal"]["seq"] == st.applied_seq
    assert snap["replay"]["resumed_sessions"] == 1


# ---------------------------------------------------------------------------
# client side: the load generator rides a failover with backoff
# ---------------------------------------------------------------------------

def test_loadgen_rides_connection_failover():
    import socket
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from tools import serve_loadgen

    class _OkHandler(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            body = json.dumps({"outputs": [[1.0]], "latency_ms": 0.1,
                               "bucket": 1, "replica": "r1"}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    url = "http://127.0.0.1:%d" % port
    httpd = [None]

    def promote_later():
        # nothing listens for ~0.4s — every early request gets
        # connection-refused, exactly a router between incarnations
        time.sleep(0.4)
        httpd[0] = ThreadingHTTPServer(("127.0.0.1", port), _OkHandler)
        httpd[0].serve_forever()

    t = threading.Thread(target=promote_later, daemon=True)
    t.start()
    try:
        res = serve_loadgen.measure(url, concurrency=2, requests=4,
                                    conn_retries=8, shape=(1, 2))
    finally:
        if httpd[0] is not None:
            httpd[0].shutdown()
            httpd[0].server_close()
    assert res["completed"] == 4, res
    assert res["failovers_ridden"] >= 1
    # without a conn budget the same outage is a hard error
    res0 = serve_loadgen.measure(
        "http://127.0.0.1:1", concurrency=1, requests=1,
        conn_retries=0, shape=(1, 2))
    assert res0["errors"] == 1 and res0["failovers_ridden"] == 0
