"""Numeric tests for the _image_* operator family (reference
src/operator/image/image_random-inl.h; upstream tested in
test_gluon_data_vision.py). HWC uint8/float conventions, flips,
normalize, crop/resize, and statistical behavior of the random jitters."""
import numpy as np
import pytest

import mxnet_tpu as mx

RNG = np.random.RandomState(3)


def _inv(name, arrs, **kw):
    out = mx.nd.invoke(name, [mx.nd.array(a) for a in arrs], kw)
    if isinstance(out, (list, tuple)):
        out = out[0]
    return out.asnumpy()


def _img(h=6, w=5):
    return RNG.randint(0, 255, (h, w, 3)).astype("uint8")


def test_to_tensor_scales_and_transposes():
    x = _img()
    got = _inv("_image_to_tensor", [x])
    assert got.shape == (3, 6, 5)
    np.testing.assert_allclose(got, x.transpose(2, 0, 1) / 255.0,
                               rtol=1e-6)


def test_normalize_per_channel():
    x = RNG.rand(3, 4, 4).astype("f4")
    got = _inv("_image_normalize", [x], mean=(0.5, 0.4, 0.3),
               std=(0.2, 0.25, 0.3))
    want = (x - np.array([0.5, 0.4, 0.3]).reshape(3, 1, 1)) \
        / np.array([0.2, 0.25, 0.3]).reshape(3, 1, 1)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_flips_hwc():
    x = _img()
    np.testing.assert_allclose(_inv("_image_flip_left_right", [x]),
                               x[:, ::-1])
    np.testing.assert_allclose(_inv("_image_flip_top_bottom", [x]),
                               x[::-1])


def test_random_flip_is_identity_or_flip():
    mx.random.seed(7)
    x = _img()
    seen = set()
    for _ in range(32):
        got = _inv("_image_random_flip_left_right", [x])
        if np.array_equal(got, x):
            seen.add("id")
        elif np.array_equal(got, x[:, ::-1]):
            seen.add("flip")
        else:
            raise AssertionError("output is neither identity nor flip")
    assert seen == {"id", "flip"}      # both outcomes occur


def test_crop_and_resize():
    x = _img(8, 8)
    got = _inv("_image_crop", [x], x=2, y=1, width=4, height=5)
    np.testing.assert_allclose(got, x[1:6, 2:6])
    got = _inv("_image_resize", [x.astype("f4")], size=(4, 4))
    assert got.shape == (4, 4, 3)
    # constant image stays constant under any interpolation
    const = np.full((8, 8, 3), 77.0, "f4")
    np.testing.assert_allclose(_inv("_image_resize", [const],
                                    size=(5, 3)), 77.0, rtol=1e-5)


def test_random_brightness_bounds():
    x = np.full((4, 4, 3), 100.0, "f4")
    mx.random.seed(0)
    for _ in range(8):
        got = _inv("_image_random_brightness", [x], min_factor=0.5,
                   max_factor=1.5)
        f = got.mean() / 100.0
        assert 0.5 - 1e-5 <= f <= 1.5 + 1e-5
        # brightness is a pure scale: image stays constant
        assert np.allclose(got, got.flat[0])


def test_random_contrast_preserves_constant_gray():
    # contrast blends toward the gray mean; a constant gray image is a
    # fixed point for any factor
    x = np.full((4, 4, 3), 90.0, "f4")
    mx.random.seed(1)
    got = _inv("_image_random_contrast", [x], min_factor=0.3,
               max_factor=1.7)
    np.testing.assert_allclose(got, x, rtol=1e-4)


def test_random_saturation_preserves_gray():
    # saturation blends toward per-pixel gray; already-gray pixels are
    # fixed points
    x = np.repeat(RNG.rand(4, 4, 1).astype("f4") * 200, 3, axis=2)
    mx.random.seed(2)
    got = _inv("_image_random_saturation", [x], min_factor=0.2,
               max_factor=1.8)
    np.testing.assert_allclose(got, x, rtol=1e-4, atol=1e-3)


def test_random_hue_preserves_gray():
    x = np.repeat(RNG.rand(4, 4, 1).astype("f4"), 3, axis=2)
    mx.random.seed(3)
    got = _inv("_image_random_hue", [x], min_factor=0.7, max_factor=1.3)
    np.testing.assert_allclose(got, x, rtol=1e-3, atol=1e-3)


def test_random_lighting_zero_std_is_identity():
    x = RNG.rand(5, 5, 3).astype("f4")
    got = _inv("_image_random_lighting", [x], alpha_std=0.0)
    np.testing.assert_allclose(got, x, rtol=1e-6)


def test_random_color_jitter_zero_is_identity():
    x = RNG.rand(5, 5, 3).astype("f4") * 255
    got = _inv("_image_random_color_jitter", [x], brightness=0.0,
               contrast=0.0, saturation=0.0, hue=0.0)
    np.testing.assert_allclose(got, x, rtol=1e-5)


def test_gluon_vision_transforms_compose():
    # the user-facing composition: ToTensor + Normalize through gluon
    from mxnet_tpu.gluon.data.vision import transforms
    t = transforms.Compose([transforms.ToTensor(),
                            transforms.Normalize(0.5, 0.25)])
    x = mx.nd.array(_img())
    out = t(x).asnumpy()
    assert out.shape == (3, 6, 5)
    want = (_to_chw_float(x.asnumpy()) - 0.5) / 0.25
    np.testing.assert_allclose(out, want, rtol=1e-5)


def _to_chw_float(img):
    return img.transpose(2, 0, 1).astype("f4") / 255.0
