"""Gluon data + recordio + image tests
(model: reference tests/python/unittest/test_gluon_data.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, image, recordio
from mxnet_tpu.gluon import data as gdata
from mxnet_tpu.gluon.data.vision import transforms
from mxnet_tpu.test_utils import assert_almost_equal


def test_array_dataset():
    X = np.random.randn(10, 3).astype("float32")
    Y = np.arange(10).astype("float32")
    ds = gdata.ArrayDataset(X, Y)
    assert len(ds) == 10
    x, y = ds[3]
    assert x.shape == (3,) and y == 3.0
    with pytest.raises(AssertionError):
        gdata.ArrayDataset(X, Y[:5])


def test_simple_dataset_ops():
    ds = gdata.SimpleDataset(list(range(10)))
    assert len(ds.take(4)) == 4
    assert list(ds.filter(lambda x: x % 2 == 0)) == [0, 2, 4, 6, 8]
    t = ds.transform(lambda x: x * 2)
    assert t[3] == 6
    s = ds.sample(gdata.SequentialSampler(5))
    assert len(s) == 5


def test_samplers():
    assert list(gdata.SequentialSampler(5)) == [0, 1, 2, 3, 4]
    assert sorted(gdata.RandomSampler(5)) == [0, 1, 2, 3, 4]
    bs = gdata.BatchSampler(gdata.SequentialSampler(10), 3, "keep")
    assert [len(b) for b in bs] == [3, 3, 3, 1]
    bs = gdata.BatchSampler(gdata.SequentialSampler(10), 3, "discard")
    assert [len(b) for b in bs] == [3, 3, 3]
    assert len(bs) == 3
    bs = gdata.BatchSampler(gdata.SequentialSampler(10), 3, "rollover")
    assert [len(b) for b in list(bs)] == [3, 3, 3]
    assert [len(b) for b in list(bs)] == [3, 3, 3]  # rolled-over 1 + 10 -> 3x3+2


def test_dataloader_basic():
    X = np.random.randn(20, 4).astype("float32")
    Y = np.arange(20).astype("float32")
    loader = gdata.DataLoader(gdata.ArrayDataset(X, Y), batch_size=6)
    batches = list(loader)
    assert len(batches) == 4
    assert batches[0][0].shape == (6, 4)
    assert batches[-1][0].shape == (2, 4)
    assert len(loader) == 4
    # shuffle covers all samples
    loader = gdata.DataLoader(gdata.ArrayDataset(X, Y), batch_size=5,
                              shuffle=True)
    seen = np.concatenate([b[1].asnumpy() for b in loader])
    assert sorted(seen.tolist()) == list(range(20))


def test_dataloader_multiworker():
    X = np.random.randn(12, 2).astype("float32")
    Y = np.arange(12).astype("float32")
    loader = gdata.DataLoader(gdata.ArrayDataset(X, Y), batch_size=4,
                              num_workers=2)
    batches = list(loader)
    assert len(batches) == 3
    seen = np.concatenate([b[1].asnumpy() for b in batches])
    assert sorted(seen.tolist()) == list(range(12))


def test_recordio_roundtrip(tmp_path):
    rec = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(rec, "w")
    for i in range(5):
        w.write(b"record-%d" % i)
    w.close()
    r = recordio.MXRecordIO(rec, "r")
    for i in range(5):
        assert r.read() == b"record-%d" % i
    assert r.read() is None


def test_indexed_recordio(tmp_path):
    rec = str(tmp_path / "t.rec")
    idx = str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(5):
        w.write_idx(i, b"payload-%d" % (i * 7))
    w.close()
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert r.read_idx(3) == b"payload-21"
    assert r.read_idx(0) == b"payload-0"
    assert r.keys == [0, 1, 2, 3, 4]


def test_irheader_pack_unpack():
    hdr = recordio.IRHeader(0, 3.5, 7, 0)
    s = recordio.pack(hdr, b"imagedata")
    hdr2, data = recordio.unpack(s)
    assert hdr2.label == 3.5 and hdr2.id == 7 and data == b"imagedata"
    # multi-label
    hdr = recordio.IRHeader(0, [1.0, 2.0, 3.0], 7, 0)
    s = recordio.pack(hdr, b"x")
    hdr2, data = recordio.unpack(s)
    assert list(hdr2.label) == [1.0, 2.0, 3.0] and data == b"x"


def test_image_record_dataset(tmp_path):
    rec = str(tmp_path / "imgs.rec")
    idx = str(tmp_path / "imgs.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(4):
        img = (np.random.rand(8, 8, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img))
    w.close()
    ds = gdata.vision.ImageRecordDataset(rec)
    assert len(ds) == 4
    img, label = ds[2]
    assert img.shape == (8, 8, 3) and label == 2.0


def test_image_folder_dataset(tmp_path):
    import cv2
    for cls in ["cat", "dog"]:
        os.makedirs(str(tmp_path / cls))
        for i in range(2):
            cv2.imwrite(str(tmp_path / cls / ("%d.jpg" % i)),
                        (np.random.rand(6, 6, 3) * 255).astype(np.uint8))
    ds = gdata.vision.ImageFolderDataset(str(tmp_path))
    assert len(ds) == 4
    assert ds.synsets == ["cat", "dog"]
    img, label = ds[0]
    assert img.shape == (6, 6, 3) and label == 0


def test_transforms_to_tensor_normalize():
    img = mx.nd.array((np.arange(48).reshape(4, 4, 3) % 256)
                      .astype(np.uint8), dtype=np.uint8)
    t = transforms.ToTensor()(img)
    assert t.shape == (3, 4, 4)
    assert abs(float(t.asnumpy().max()) - 47 / 255) < 1e-6
    n = transforms.Normalize([0.5, 0.5, 0.5], [2, 2, 2])(t)
    assert_almost_equal(n.asnumpy(), (t.asnumpy() - 0.5) / 2, rtol=1e-5)


def test_transforms_geometric():
    img = mx.nd.array((np.random.rand(10, 8, 3) * 255).astype(np.uint8))
    assert transforms.Resize(16)(img).shape == (16, 16, 3)
    assert transforms.Resize((6, 4))(img).shape == (4, 6, 3)
    assert transforms.CenterCrop(4)(img).shape == (4, 4, 3)
    assert transforms.RandomResizedCrop(5)(img).shape == (5, 5, 3)
    f = transforms.RandomFlipLeftRight()(img)
    assert f.shape == img.shape


def test_transforms_color():
    img = mx.nd.array((np.random.rand(6, 6, 3) * 255).astype(np.uint8))
    for t in [transforms.RandomBrightness(0.3),
              transforms.RandomContrast(0.3),
              transforms.RandomSaturation(0.3),
              transforms.RandomHue(0.1),
              transforms.RandomColorJitter(0.2, 0.2, 0.2, 0.1),
              transforms.RandomLighting(0.1)]:
        out = t(img.astype("float32"))
        assert out.shape == img.shape


def test_transform_compose_in_loader():
    imgs = [(np.random.rand(8, 8, 3) * 255).astype(np.uint8)
            for _ in range(6)]
    ds = gdata.SimpleDataset([(im, float(i)) for i, im in enumerate(imgs)])
    tfn = transforms.Compose([transforms.ToTensor()])
    tds = ds.transform_first(lambda x: tfn(mx.nd.array(x, dtype=np.uint8)))
    loader = gdata.DataLoader(tds, batch_size=3)
    b = next(iter(loader))
    assert b[0].shape == (3, 3, 8, 8)


def test_image_module():
    import cv2
    img = (np.random.rand(12, 10, 3) * 255).astype(np.uint8)
    ok, buf = cv2.imencode(".jpg", img)
    decoded = image.imdecode(buf.tobytes())
    assert decoded.shape == (12, 10, 3)
    r = image.imresize(decoded, 5, 6)
    assert r.shape == (6, 5, 3)
    rs = image.resize_short(decoded, 6)
    assert min(rs.shape[:2]) == 6
    c, rect = image.center_crop(decoded, (4, 4))
    assert c.shape == (4, 4, 3)
    c2, _ = image.random_crop(decoded, (4, 4))
    assert c2.shape == (4, 4, 3)
    augs = image.CreateAugmenter((3, 6, 6), rand_crop=True, rand_mirror=True,
                                 brightness=0.1, mean=True, std=True)
    out = decoded
    for a in augs:
        out = a(out)
    assert out.shape == (6, 6, 3) and out.dtype == np.float32


def test_image_iter(tmp_path):
    rec = str(tmp_path / "ii.rec")
    idx = str(tmp_path / "ii.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(7):
        img = (np.random.rand(8, 8, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), img))
    w.close()
    it = image.ImageIter(batch_size=3, data_shape=(3, 8, 8),
                         path_imgrec=rec, path_imgidx=idx)
    batch = it.next()
    assert batch.data[0].shape == (3, 3, 8, 8)
    assert batch.label[0].shape == (3,)
    n = 1 + sum(1 for _ in it)
    assert n >= 2


def test_pack_numpy_scalar_label():
    """Review regression: numpy scalar labels must pack as plain labels."""
    hdr = recordio.IRHeader(0, np.float32(3.0), 1, 0)
    h2, data = recordio.unpack(recordio.pack(hdr, b"z"))
    assert h2.label == 3.0 and h2.flag == 0
    # 2-D label flattens to element count, not row count
    hdr = recordio.IRHeader(0, np.ones((2, 3), np.float32), 1, 0)
    h2, _ = recordio.unpack(recordio.pack(hdr, b"z"))
    assert h2.flag == 6 and len(h2.label) == 6
