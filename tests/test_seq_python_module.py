"""SequentialModule / PythonModule tests (parity model:
tests/python/unittest/test_module.py test_module_layout + python module
examples)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def _toy_data(n=256, d=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    W = rng.randn(d, classes).astype(np.float32)
    y = (X @ W).argmax(axis=1).astype(np.float32)
    return X, y


def _stage1():
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=32, name="fc1")
    return sym.Activation(net, act_type="relu", name="relu1")


def _stage2(classes=4):
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=classes,
                             name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def test_sequential_module_fit():
    X, y = _toy_data()
    train = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True)
    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(_stage1(), label_names=None, context=mx.cpu())) \
       .add(mx.mod.Module(_stage2(), context=mx.cpu()),
            take_labels=True, auto_wiring=True)
    seq.fit(train, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.initializer.Xavier())
    train.reset()
    score = dict(seq.score(train, "acc"))
    assert score["accuracy"] > 0.9, score

    # params from both stages are visible through the container
    arg_params, _ = seq.get_params()
    assert "fc1_weight" in arg_params and "fc2_weight" in arg_params


def test_sequential_module_matches_single_module():
    """A 2-stage chain must train identically to the same net in one Module."""
    X, y = _toy_data(128)
    classes = 4

    def fused_sym():
        net = sym.FullyConnected(sym.Variable("data"), num_hidden=32,
                                 name="fc1")
        net = sym.Activation(net, act_type="relu", name="relu1")
        net = sym.FullyConnected(net, num_hidden=classes, name="fc2")
        return sym.SoftmaxOutput(net, name="softmax")

    init = mx.initializer.Xavier(rnd_type="gaussian", magnitude=2.0)
    batch = 32
    train1 = mx.io.NDArrayIter(X, y, batch_size=batch)
    train2 = mx.io.NDArrayIter(X, y, batch_size=batch)

    single = mx.mod.Module(fused_sym(), context=mx.cpu())
    single.bind(train1.provide_data, train1.provide_label)
    mx.random.seed(7)
    single.init_params(init)

    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(_stage1(), label_names=None, context=mx.cpu())) \
       .add(mx.mod.Module(_stage2(classes), context=mx.cpu()),
            take_labels=True, auto_wiring=True)
    seq.bind(train2.provide_data, train2.provide_label)
    arg_params, aux_params = single.get_params()
    seq.init_params(init, arg_params=arg_params, aux_params=aux_params,
                    force_init=True)

    for m in (single, seq):
        m.init_optimizer(optimizer="sgd",
                         optimizer_params={"learning_rate": 0.1})
    for _ in range(3):
        train1.reset(); train2.reset()
        for b1, b2 in zip(train1, train2):
            single.forward_backward(b1); single.update()
            seq.forward_backward(b2); seq.update()

    a1, _ = single.get_params()
    a2, _ = seq.get_params()
    for k in a1:
        np.testing.assert_allclose(a1[k].asnumpy(), a2[k].asnumpy(),
                                   rtol=2e-4, atol=2e-5)


def test_sequential_module_rejects_unknown_meta_and_dup_params():
    seq = mx.mod.SequentialModule()
    with pytest.raises(ValueError):
        seq.add(mx.mod.Module(_stage1(), label_names=None), bogus_meta=True)

    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(_stage1(), label_names=None, context=mx.cpu())) \
       .add(mx.mod.Module(_stage1(), label_names=None, context=mx.cpu()),
            auto_wiring=True)
    seq.bind([("data", (8, 8))])
    with pytest.raises(ValueError, match="duplicate parameter"):
        seq.init_params()


def _softmax_ce_grad(scores, labels):
    s = scores.asnumpy()
    e = np.exp(s - s.max(axis=1, keepdims=True))
    p = e / e.sum(axis=1, keepdims=True)
    lab = labels.asnumpy().astype(np.int64)
    p[np.arange(len(lab)), lab] -= 1.0  # SoftmaxOutput grad semantics (no batch normalization)
    return p


def test_python_loss_module_chain():
    """net Module + PythonLossModule(grad_func) trains like SoftmaxOutput."""
    X, y = _toy_data()
    train = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True)

    net = sym.FullyConnected(sym.Variable("data"), num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu", name="relu1")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")

    seq = mx.mod.SequentialModule()
    seq.add(mx.mod.Module(net, label_names=None, context=mx.cpu())) \
       .add(mx.mod.PythonLossModule(grad_func=_softmax_ce_grad),
            take_labels=True, auto_wiring=True)
    seq.fit(train, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            initializer=mx.initializer.Xavier(), eval_metric=None)

    # score by argmax of the raw scores the loss module passes through
    train.reset()
    correct = total = 0
    for batch in train:
        seq.forward(batch, is_train=False)
        pred = seq.get_outputs()[0].asnumpy().argmax(axis=1)
        lab = batch.label[0].asnumpy()
        correct += (pred == lab).sum(); total += len(lab)
    assert correct / total > 0.9


def test_python_module_shapes_and_metric():
    mod = mx.mod.PythonLossModule()
    mod.bind([("data", (16, 4))], [("softmax_label", (16,))])
    assert mod.output_shapes == [("pyloss_output", (16, 4))]
    assert mod.get_params() == ({}, {})
    batch = mx.io.DataBatch(data=[mx.nd.array(np.random.rand(16, 4))],
                            label=[mx.nd.array(np.zeros(16))])
    mod.forward(batch, is_train=True)
    assert mod.get_outputs()[0].shape == (16, 4)
