"""Host-sync budget guardrail for the async fit loop (chip-free).

The async-loop contract (docs/perf.md "Async fit loop"): the benched
ResNet-50 ``Module.fit`` inner loop, with a supported metric folded into
the device step, performs at most ONE involuntary device->host transfer
per K-step dispatch window — the metric publish at the epoch/display
boundary. Every other read stays on device; the profiler's sync counters
(``profiler.record_host_sync``) are the evidence.

The second half asserts the OTHER side of the bargain: going async must
not change the answer. The same 16 steps replayed fully synchronously —
engine_depth=1 (lockstep dispatch) and device metrics OFF, so every batch
pays a host metric update with its own d2h — from the same initial params
must produce bitwise-identical metric values at the epoch boundary:
engine depth changes only WHEN the host waits, never what the device
computes, and the host metric consumes the same output bits the device
carry consumed. (Dispatch granularity — scan vs per-step programs — is a
separate pre-existing dimension with its own allclose-level parity tests
in test_module_fused.py; it is held fixed here.)

Runs on CPU (tier-1): resnet_symbol is shape-agnostic until bind
(global_pool), so a 64x64 bind keeps the 50-layer program CPU-feasible
while exercising the exact graph bench.py measures.
"""
import logging

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler
from mxnet_tpu import telemetry
from mxnet_tpu import config as _config
from mxnet_tpu.config import flags
from mxnet_tpu.io import DataBatch, DataDesc

BATCH = 4
SIDE = 64
K = flags.steps_per_dispatch  # default 16; the budget window (>= 10)
N_CLASSES = 100

_logger = logging.getLogger("sync_budget_test")
_logger.addHandler(logging.NullHandler())
_logger.propagate = False


class _OneBatchIter:
    """bench.py's --benchmark 1 iterator: one device-resident batch
    repeated, zero input-pipeline cost (and zero h2d after warmup)."""

    def __init__(self, batch, steps, provide_data, provide_label):
        self._batch = batch
        self._steps = steps
        self.provide_data = provide_data
        self.provide_label = provide_label
        self.batch_size = provide_data[0].shape[0]
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._i >= self._steps:
            raise StopIteration
        self._i += 1
        return self._batch

    def reset(self):
        self._i = 0


def _make_iter():
    rng = np.random.RandomState(7)
    data = mx.nd.array(rng.randn(BATCH, 3, SIDE, SIDE).astype(np.float32))
    label = mx.nd.array(
        rng.randint(0, N_CLASSES, (BATCH,)).astype(np.float32))
    return _OneBatchIter(DataBatch(data=[data], label=[label]), K,
                         [DataDesc("data", (BATCH, 3, SIDE, SIDE))],
                         [DataDesc("softmax_label", (BATCH,))])


def _make_module(it, arg_params=None, aux_params=None):
    from mxnet_tpu import models
    sym = models.resnet_symbol(num_classes=N_CLASSES, num_layers=50)
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.logger = _logger
    mod.bind(it.provide_data, it.provide_label, for_training=True)
    np.random.seed(11)  # Initializer draws from the global numpy RNG
    mod.init_params(mx.initializer.Xavier(factor_type="in", magnitude=2.0),
                    arg_params=arg_params, aux_params=aux_params)
    return mod


def _fit(mod, it, metric, **kw):
    mod.fit(it, num_epoch=1, eval_metric=metric, kvstore="tpu_sync",
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            **kw)


@pytest.mark.skipif(K < 10, reason="budget window needs K >= 10")
def test_resnet50_fit_syncs_at_most_once_per_k_steps():
    it = _make_iter()
    mod = _make_module(it)
    # host-side snapshot of the starting point for the baseline run
    # (before the counters arm — this read is test scaffolding, not loop)
    arg0, aux0 = mod.get_params()
    arg0 = {k: mx.nd.array(v.asnumpy()) for k, v in arg0.items()}
    aux0 = {k: mx.nd.array(v.asnumpy()) for k, v in aux0.items()}

    # the epoch has exactly K batches, so fit's default (auto) dispatch
    # runs them as ONE K-step scan; counters cover the whole fit inner
    # loop including the epoch-end metric read
    m_async = mx.metric.create("acc")
    profiler.reset_sync_counters()
    _fit(mod, it, m_async)
    counters = profiler.sync_counters()

    assert mod._fused is not None, "fused step must engage (tpu_sync)"
    assert mod._device_plan is not None, \
        "accuracy must fold into the device step"
    # the budget: <= 1 involuntary d2h for the whole K-step window. The
    # single allowed transfer is the epoch-end metric publish (a few
    # bytes); compile/dispatch/feed never move device data to host.
    # Telemetry is ON (registry default-enabled, no flag) for this run,
    # so these bounds also pin the tentpole claim: window sampling adds
    # ZERO device->host transfers on top of the metric publish.
    assert counters["d2h"] <= 1, counters
    assert counters["d2h_bytes"] <= 64, counters

    # ...and the windows really were published from host-held values:
    # the K-batch epoch is one dispatch window, so every train/ series
    # carries the whole epoch
    reg = telemetry.default_registry()
    assert reg.get("train/step_time_ms").value() > 0
    assert reg.get("train/window_steps").value() == K
    assert reg.get("train/examples_per_s").value() > 0
    assert reg.get("train/engine_depth").value() is not None
    assert reg.get("train/global_step").value() >= K
    assert reg.get("train/steps_total").value() >= K
    # the host_sync/* gauges republish the same census sampled ABOVE at
    # the last window boundary — they can only lag counters, never add
    assert reg.get("host_sync/d2h").value() <= counters["d2h"]

    # the epoch-end publish wrote the device carry into the wrapped
    # host metric, so the caller's own metric object reads normally
    acc_async = dict(m_async.get_name_value())

    # ---- per-step-sync baseline: same dispatch granularity (one K-step
    # scan), but lockstep depth and the reference host metric path — the
    # K stacked outputs are replayed through EvalMetric.update_dict one
    # sub-batch at a time, each paying its own d2h ----
    it.reset()
    base = _make_module(it, arg_params=arg0, aux_params=aux0)
    m_sync = mx.metric.create("acc")
    with _config.override(engine_depth=1, device_metrics=False):
        profiler.reset_sync_counters()
        _fit(base, it, m_sync, steps_per_dispatch=K)
        sync_counters = profiler.sync_counters()

    assert base._device_plan is None  # host path, as intended
    # the host path really did sync per batch (what the budget loop saves)
    assert sync_counters["d2h"] >= K, sync_counters
    acc_sync = dict(m_sync.get_name_value())

    # same initial params, same batches, same program granularity: the
    # epoch accuracy must agree bitwise (integer hit-counts over 64
    # samples; depth and metric residency change no device math)
    assert acc_async == acc_sync, (acc_async, acc_sync)


def test_ddp_window_stats_add_no_d2h():
    """The DDP telemetry contract: ``ddp/comm_bytes``/``buckets``/
    ``overlap_ms`` come from the GradReducer's STATIC bucket plan — host
    memory decided at compile time — so sampling them at a window
    boundary performs ZERO device->host transfers."""
    import jax
    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-virtual-device mesh")
    rng = np.random.RandomState(5)
    X = rng.randn(32, 8).astype(np.float32)
    Y = rng.randint(0, 4, (32,)).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(sym)
    mod.logger = _logger
    with _config.override(ddp=True):
        mod.fit(it, num_epoch=1, kvstore="dist_sync", optimizer="sgd",
                optimizer_params={"learning_rate": 0.1})
    assert mod._ddp and mod._fused is not None

    profiler.reset_sync_counters()
    stats = mod._ddp_stats(K)
    telemetry.publish_window(steps=K, window_s=0.1, examples=16 * K,
                             global_step=K, ddp=stats)
    counters = profiler.sync_counters()
    assert counters["d2h"] == 0 and counters["d2h_bytes"] == 0, counters

    assert stats["buckets"] >= 1 and stats["comm_bytes"] > 0
    reg = telemetry.default_registry()
    assert reg.get("ddp/buckets").value() == stats["buckets"]
    assert reg.get("ddp/comm_bytes").value() >= stats["comm_bytes"]
    assert reg.get("ddp/overlap_ms").value() == stats["overlap_ms"]


def test_embed_window_stats_add_no_d2h():
    """The embedding telemetry contract (PR 15): ``embed/cache_hit_rate``
    and ``embed/spill_bytes`` come from the HotRowCache's HOST-HELD
    counters (embed/cache.py never reads the device to account), and
    ``ddp/sparse_comm_bytes`` from the SparseBucket STATIC plan — so a
    window publish carrying all three performs ZERO device->host
    transfers beyond what training itself already paid."""
    from mxnet_tpu.embed import HotRowCache, SpillStore
    from mxnet_tpu.parallel.ddp import SparseBucket

    store = SpillStore(64, 8, seed=3)
    cache = HotRowCache(store, 16)
    # touch enough distinct rows to force dirty evictions -> spill d2h,
    # all PAID here, before the window boundary being measured
    for lo in (0, 12, 24, 36):
        ids = np.arange(lo, lo + 12, dtype=np.int64)
        cache.ensure(ids)
        cache.note_updated(ids)
    assert cache.stats()["spill_bytes"] > 0

    sb = SparseBucket("emb_user", 32, 8, 64)
    spill_before = 0  # window delta: first window since cache creation
    profiler.reset_sync_counters()
    stats = cache.stats()
    telemetry.publish_window(
        steps=K, window_s=0.1, examples=16 * K, global_step=K,
        ddp={"buckets": 1, "comm_bytes": 0, "overlap_ms": 0.0,
             "sparse_comm_bytes": sb.comm_bytes(4)},
        embed={"hit_rate": stats["hit_rate"],
               "spill_bytes": stats["spill_bytes"] - spill_before})
    counters = profiler.sync_counters()
    assert counters["d2h"] == 0 and counters["d2h_bytes"] == 0, counters

    reg = telemetry.default_registry()
    assert reg.get("embed/cache_hit_rate").value() == stats["hit_rate"]
    assert reg.get("embed/spill_bytes").value() >= stats["spill_bytes"]
    assert reg.get("ddp/sparse_comm_bytes").value() >= sb.comm_bytes(4)


def test_counters_shape():
    profiler.reset_sync_counters()
    c = profiler.sync_counters()
    assert c["d2h"] == 0 and c["wait"] == 0 and c["total"] == 0
    profiler.record_host_sync("d2h", 128)
    profiler.record_host_sync("wait")
    profiler.record_host_sync("depth_wait")
    c = profiler.sync_counters()
    assert c["d2h"] == 1 and c["d2h_bytes"] == 128
    assert c["wait"] == 1 and c["depth_wait"] == 1
    # depth_wait is expected back-pressure, not a budget violation
    assert c["total"] == 2


def _pack_resnet_records(tmp_path, n):
    """n raw-tensor (3,SIDE,SIDE) f32 records + class labels, sharded."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        from make_recordio import write_shards
    finally:
        sys.path.pop(0)
    rng = np.random.RandomState(7)
    X = rng.randn(n, 3, SIDE, SIDE).astype(np.float32)
    Y = rng.randint(0, N_CLASSES, (n,)).astype(np.float32)
    return write_shards(((float(Y[i]), X[i].tobytes()) for i in range(n)),
                        str(tmp_path / "rset"), 2)


def _stream_iter(recs):
    from mxnet_tpu.data import (RawTensorDecoder, ShardedRecordStream,
                                StreamingDataIter)
    return StreamingDataIter(ShardedRecordStream(recs, seed=13),
                             RawTensorDecoder((3, SIDE, SIDE)),
                             batch_size=BATCH)


@pytest.mark.skipif(K < 10, reason="budget window needs K >= 10")
def test_streaming_fit_same_budget_and_bitwise_vs_in_memory(tmp_path):
    """The tentpole contract end to end: the benched ResNet-50 fit fed by
    the STREAMING tier (sharded stream -> parallel decode -> StagedKFeed
    pre-stacking each K-window off-thread) keeps the <=1-d2h-per-window
    budget AND lands bitwise-identical params + metric to the same fit
    fed from memory (NDArrayIter over the same rows in the same order) —
    the staging machinery moves work off the critical path without
    touching a single bit of the math."""
    recs = _pack_resnet_records(tmp_path, K * BATCH)

    # twin iterator captures the epoch-0 delivered order for the
    # in-memory baseline (same seed => same shuffle plan)
    twin = _stream_iter(recs)
    try:
        caps = [(b.data[0].asnumpy().copy(), b.label[0].asnumpy().copy())
                for b in twin]
    finally:
        twin.close()
    assert len(caps) == K
    X = np.concatenate([d for d, _ in caps])
    Y = np.concatenate([l for _, l in caps])

    it = _stream_iter(recs)
    try:
        mod = _make_module(it)
        arg0, aux0 = mod.get_params()
        arg0 = {k: mx.nd.array(v.asnumpy()) for k, v in arg0.items()}
        aux0 = {k: mx.nd.array(v.asnumpy()) for k, v in aux0.items()}

        assert flags.data_staged_feed  # default-on staged K-step feed
        m_stream = mx.metric.create("acc")
        profiler.reset_sync_counters()
        _fit(mod, it, m_stream)
        counters = profiler.sync_counters()
    finally:
        it.close()

    assert mod._fused is not None and mod._device_plan is not None
    # same budget as the one-batch loop: streaming feed + cursor capture
    # + data/* window telemetry add ZERO device->host transfers
    assert counters["d2h"] <= 1, counters
    assert counters["d2h_bytes"] <= 64, counters

    # the window telemetry actually reported the data plane (host-held)
    reg = telemetry.default_registry()
    assert reg.get("data/input_stall_ms").value() >= 0
    assert reg.get("data/h2d_bytes").value() \
        >= X.nbytes + Y.nbytes
    assert reg.get("data/examples_per_s").value() > 0

    # ---- in-memory baseline: same rows, same order, same init ----
    base_it = mx.io.NDArrayIter(X, Y, batch_size=BATCH,
                                label_name="softmax_label")
    base = _make_module(base_it, arg_params=arg0, aux_params=aux0)
    m_base = mx.metric.create("acc")
    _fit(base, base_it, m_base, steps_per_dispatch=K)

    assert dict(m_stream.get_name_value()) == dict(m_base.get_name_value())
    arg_s, aux_s = mod.get_params()
    arg_b, aux_b = base.get_params()
    for name in arg_b:
        np.testing.assert_array_equal(
            arg_s[name].asnumpy(), arg_b[name].asnumpy(),
            err_msg="param %r diverged under the streaming feed" % name)
    for name in aux_b:
        np.testing.assert_array_equal(
            aux_s[name].asnumpy(), aux_b[name].asnumpy(),
            err_msg="aux %r diverged under the streaming feed" % name)


def test_data_window_stats_add_no_d2h():
    """The data-plane telemetry contract: ``data/input_stall_ms``,
    ``data/h2d_bytes``, ``data/queue_depth`` etc. come from host-held
    timers and shape arithmetic — publishing them moves ZERO device
    data to host."""
    profiler.reset_sync_counters()
    telemetry.publish_window(
        steps=K, window_s=0.5, examples=BATCH * K, global_step=K,
        data={"input_stall_ms": 12.5, "h2d_bytes": 4096,
              "queue_depth": 2})
    counters = profiler.sync_counters()
    assert counters["d2h"] == 0 and counters["d2h_bytes"] == 0, counters

    reg = telemetry.default_registry()
    assert reg.get("data/input_stall_ms").value() == 12.5
    assert reg.get("data/h2d_bytes").value() >= 4096
    assert reg.get("data/queue_depth").value() == 2
    assert reg.get("data/examples_per_s").value() == BATCH * K / 0.5
    assert reg.get("data/stall_frac").value() == pytest.approx(0.025)
    # 2.5% stall, no flops figure -> 10% threshold -> compute-bound
    assert reg.get("data/input_bound").value() == 0.0
