"""Smoke tests: every example script must run end to end on CPU."""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, args):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # examples don't need the 8-device mesh
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", script)] + args
        + ["--device", "cpu"],
        capture_output=True, text=True, timeout=480, env=env, cwd=ROOT)
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
    return r


def test_train_mnist_example():
    r = _run("train_mnist.py", ["--num-epochs", "2"])
    assert "final validation" in r.stdout


def test_long_context_attention_example():
    r = _run("long_context_attention.py",
             ["--devices", "4", "--seq-len", "512"])
    assert "LONG-CONTEXT OK" in r.stdout


def test_transformer_lm_example():
    # a 1-layer model must SOLVE the lag-9 copy task — only possible by
    # attending 9 steps back through the causal flash kernel
    r = _run("train_transformer_lm.py",
             ["--steps", "300", "--seq-len", "32", "--lag", "9",
              "--dim", "32", "--num-layers", "1", "--batch-size", "32",
              "--lr", "5e-3"])
    assert "loss first->last" in r.stdout


def test_nce_word2vec_example():
    # short run: assert the mechanics (zipfian negatives, NCE head,
    # manual SGD on a shared embedding) improve the loss; the full
    # embedding-geometry check runs at the script's own defaults
    r = _run("nce_word2vec.py", ["--steps", "60", "--vocab", "128",
                                 "--num-neg", "7", "--batch-size", "128"])
    assert "partner-nearest-neighbour" in r.stdout


def test_train_cifar10_example():
    r = _run("train_cifar10.py", ["--num-epochs", "1", "--batch-size", "64",
                                  "--num-layers", "20"])
    assert "final accuracy" in r.stdout


def test_gluon_cnn_example():
    r = _run("gluon_cnn.py", ["--num-epochs", "1"])
    assert "epoch 0" in r.stdout


def test_char_lstm_example():
    r = _run("char_lstm.py", ["--num-epochs", "1"])
    assert "final" in r.stdout


def test_word_language_model_example():
    r = _run("word_language_model.py",
             ["--epochs", "2", "--synthetic-tokens", "16000"])
    assert "LM training OK" in r.stdout


def test_super_resolution_example():
    r = _run("super_resolution.py", ["--epochs", "4"])
    assert "super-resolution OK" in r.stdout


def test_dcgan_example():
    r = _run("train_dcgan.py", ["--epochs", "3", "--num-samples", "64",
                                "--batch-size", "16"])
    assert "dcgan OK" in r.stdout


def test_vae_example():
    r = _run("train_vae.py", ["--epochs", "3", "--num-samples", "128"])
    assert "vae OK" in r.stdout


def test_sparse_linear_classification_example():
    r = _run("sparse_linear_classification.py", ["--epochs", "5"])
    assert "sparse linear classification OK" in r.stdout


def test_matrix_factorization_example():
    r = _run("matrix_factorization.py", ["--epochs", "6"])
    assert "matrix factorization OK" in r.stdout


def test_train_imagenet_benchmark_mode():
    r = _run("train_imagenet.py",
             ["--benchmark", "1", "--benchmark-steps", "2",
              "--network", "resnet", "--num-layers", "18",
              "--image-shape", "3,32,32", "--num-classes", "10",
              "--batch-size", "8"])
    assert "benchmark:" in r.stdout and "img/s" in r.stdout


def test_train_rcnn_example():
    r = _run("train_rcnn.py", ["--epochs", "3"])
    assert "Faster R-CNN training OK" in r.stdout


def test_train_twotower_example():
    # small run of the PR-15 fleet drill: dense vs 2x2-mesh vs
    # cache+spill must agree BITWISE (the script asserts it; the
    # "user=True item=True" lines are the receipts)
    r = _run("train_twotower.py",
             ["--users", "128", "--items", "48", "--dim", "8",
              "--batch-size", "16", "--steps", "12", "--capacity", "40",
              "--window", "6"])
    assert "bitwise cache-vs-mesh: user=True item=True" in r.stdout
    assert "two-tower OK" in r.stdout
