"""Model zoo tests (model: reference tests/python/unittest/test_gluon_model_zoo.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo import vision


def _fwd(name, shape=(1, 3, 224, 224), classes=10):
    net = vision.get_model(name, classes=classes)
    net.initialize()
    out = net(mx.nd.array(np.random.randn(*shape).astype("float32")))
    assert out.shape == (shape[0], classes), (name, out.shape)
    return net


def test_resnet_family_forward():
    _fwd("resnet18_v1")
    _fwd("resnet18_v2")


def test_squeezenet_forward():
    _fwd("squeezenet1.0")
    _fwd("squeezenet1.1")


def test_mobilenet_forward():
    _fwd("mobilenet0.25")
    _fwd("mobilenetv2_0.25")


def test_alexnet_forward():
    _fwd("alexnet")


def test_inception_forward():
    _fwd("inceptionv3", shape=(1, 3, 299, 299))


def test_all_models_construct():
    names = ["resnet34_v1", "resnet50_v1", "resnet101_v1", "resnet152_v1",
             "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
             "vgg11", "vgg13", "vgg16", "vgg19", "vgg11_bn", "vgg16_bn",
             "densenet121", "densenet161", "densenet169", "densenet201",
             "mobilenet1.0", "mobilenet0.5", "mobilenetv2_1.0",
             "mobilenetv2_0.5"]
    for name in names:
        net = vision.get_model(name, classes=7)
        assert len(net.collect_params()) > 0, name


def test_get_model_unknown():
    with pytest.raises(ValueError):
        vision.get_model("resnet999_v9")


def test_resnet_train_step():
    net = vision.get_model("resnet18_v1", classes=4)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.nd.array(np.random.randn(2, 3, 32, 32).astype("float32"))
    y = mx.nd.array(np.array([0, 1], dtype="float32"))
    with autograd.record():
        L = loss_fn(net(x), y).mean()
    L.backward()
    trainer.step(2)
    # at least one conv weight moved
    p = net.features[0].weight
    assert np.abs(p.grad().asnumpy()).sum() > 0


def test_resnet_hybridize_matches_eager():
    net = vision.get_model("resnet18_v2", classes=5)
    net.initialize()
    x = mx.nd.array(np.random.randn(1, 3, 32, 32).astype("float32"))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-4, atol=1e-5)
