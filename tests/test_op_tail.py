"""Operator-tail parity tests (VERDICT r3 #6): add_n/ElementWiseSum,
reshape_like, batch_take, _slice_assign[_scalar], bipartite_matching,
group_adagrad_update, SparseEmbedding, quantized_pooling/concat, LibSVMIter.

Cases mirror the reference's unit tests
(tests/python/unittest/test_operator.py, test_contrib_operator.py,
test_io.py) re-expressed against this package's API.
"""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx


def test_add_n():
    rng = np.random.RandomState(0)
    arrs = [mx.nd.array(rng.randn(4, 5).astype("f4")) for _ in range(5)]
    out = mx.nd.add_n(*arrs)
    np.testing.assert_allclose(
        out.asnumpy(), sum(a.asnumpy() for a in arrs), rtol=1e-6)
    out2 = mx.nd.ElementWiseSum(*arrs)
    np.testing.assert_allclose(out.asnumpy(), out2.asnumpy())


def test_add_n_symbolic_grad():
    xs = [mx.sym.Variable("x%d" % i) for i in range(3)]
    y = mx.sym.add_n(*xs)
    ex = y.bind(mx.cpu(), {("x%d" % i): mx.nd.ones((2, 2)) * i
                           for i in range(3)},
                args_grad={("x%d" % i): mx.nd.zeros((2, 2))
                           for i in range(3)})
    ex.forward(is_train=True)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(),
                               np.full((2, 2), 3.0))
    ex.backward([mx.nd.ones((2, 2))])
    for g in ex.grad_dict.values():
        np.testing.assert_allclose(g.asnumpy(), np.ones((2, 2)))


def test_reshape_like():
    a = mx.nd.array(np.arange(6, dtype="f4"))
    b = mx.nd.zeros((3, 2))
    out = mx.nd.reshape_like(a, b)
    assert out.shape == (3, 2)
    np.testing.assert_allclose(out.asnumpy().ravel(), np.arange(6))


def test_batch_take():
    # reference docstring example (indexing_op.cc:748)
    x = mx.nd.array([[1., 2.], [3., 4.], [5., 6.]])
    out = mx.nd.batch_take(x, mx.nd.array([0, 1, 0]))
    np.testing.assert_allclose(out.asnumpy(), [1., 4., 5.])


def test_slice_assign_ops():
    x = mx.nd.zeros((3, 4))
    rhs = mx.nd.ones((2, 2))
    out = mx.nd.invoke("_slice_assign", [x, rhs],
                       {"begin": (0, 1), "end": (2, 3)})
    exp = np.zeros((3, 4), "f4")
    exp[0:2, 1:3] = 1.0
    np.testing.assert_allclose(out.asnumpy(), exp)
    out2 = mx.nd.invoke("_slice_assign_scalar", [x],
                        {"scalar": 5.0, "begin": (1,), "end": (3,)})
    exp2 = np.zeros((3, 4), "f4")
    exp2[1:3] = 5.0
    np.testing.assert_allclose(out2.asnumpy(), exp2)


def test_setitem_routes_slice_assign():
    x = mx.nd.zeros((3, 4))
    x[0:2, 1:3] = 7.0
    exp = np.zeros((3, 4), "f4")
    exp[0:2, 1:3] = 7.0
    np.testing.assert_allclose(x.asnumpy(), exp)
    x[1] = mx.nd.array(np.arange(4, dtype="f4"))
    exp[1] = np.arange(4)
    np.testing.assert_allclose(x.asnumpy(), exp)
    x[:, ::2] = -1.0
    exp[:, ::2] = -1.0
    np.testing.assert_allclose(x.asnumpy(), exp)


def test_bipartite_matching():
    # both cases from the reference test_contrib_operator.py:235-245
    inp = mx.nd.array([[0.5, 0.6], [0.1, 0.2], [0.3, 0.4]])
    a, b = mx.nd.invoke("_contrib_bipartite_matching", [inp],
                        {"threshold": 1e-12, "is_ascend": False})
    np.testing.assert_array_equal(a.asnumpy().astype("i8"), [1, -1, 0])
    np.testing.assert_array_equal(b.asnumpy().astype("i8"), [2, 0])
    a, b = mx.nd.invoke("_contrib_bipartite_matching", [inp],
                        {"threshold": 100, "is_ascend": True})
    np.testing.assert_array_equal(a.asnumpy().astype("i8"), [-1, 0, 1])
    np.testing.assert_array_equal(b.asnumpy().astype("i8"), [1, 2])


def test_bipartite_matching_batched_topk():
    rng = np.random.RandomState(7)
    s = rng.rand(2, 4, 5).astype("f4")
    a, b = mx.nd.invoke("_contrib_bipartite_matching", [mx.nd.array(s)],
                        {"threshold": 1e-12, "topk": 2})
    a, b = a.asnumpy(), b.asnumpy()
    assert a.shape == (2, 4) and b.shape == (2, 5)
    for i in range(2):
        # every match is mutual and scores decrease along the greedy order
        for r, c in enumerate(a[i]):
            if c >= 0:
                assert b[i, int(c)] == r


def test_group_adagrad_update_matches_formula():
    rng = np.random.RandomState(1)
    w = rng.randn(6, 3).astype("f4")
    g = rng.randn(6, 3).astype("f4")
    h = np.zeros((6, 1), "f4")
    nw, nh = mx.nd.invoke("group_adagrad_update",
                          [mx.nd.array(w), mx.nd.array(g), mx.nd.array(h)],
                          {"lr": 0.1, "epsilon": 1e-5})
    exp_h = h + np.mean(np.square(g), axis=1, keepdims=True)
    exp_w = w - 0.1 * g / np.sqrt(exp_h + 1e-5)
    np.testing.assert_allclose(nh.asnumpy(), exp_h, rtol=1e-5)
    np.testing.assert_allclose(nw.asnumpy(), exp_w, rtol=1e-5)


def test_group_adagrad_optimizer_dense_and_fused():
    opt = mx.optimizer.create("groupadagrad", learning_rate=0.1, wd=0.0)
    assert opt.fused_ops() is not None
    w = mx.nd.array(np.ones((4, 2), "f4"))
    g = mx.nd.array(np.full((4, 2), 0.5, "f4"))
    st = opt.create_state(0, w)
    assert st.shape == (4, 1)
    opt.update(0, w, g, st)
    exp_h = 0.25
    exp_w = 1.0 - 0.1 * 0.5 / np.sqrt(exp_h + 1e-5)
    np.testing.assert_allclose(w.asnumpy(), np.full((4, 2), exp_w),
                               rtol=1e-5)


def test_sparse_embedding_forward():
    w = mx.nd.array(np.arange(12, dtype="f4").reshape(4, 3))
    d = mx.nd.array([2, 0])
    out = mx.nd.invoke("_contrib_SparseEmbedding", [d, w],
                       {"input_dim": 4, "output_dim": 3})
    np.testing.assert_allclose(out.asnumpy(),
                               [[6., 7., 8.], [0., 1., 2.]])


def test_quantized_pooling():
    d = mx.nd.array(np.array([[[[10, 20], [30, 40]]]], "u1"), dtype="uint8")
    out, lo, hi = mx.nd.invoke(
        "_contrib_quantized_pooling",
        [d, mx.nd.array([0.]), mx.nd.array([6.])],
        {"kernel": (2, 2), "pool_type": "max"})
    assert out.asnumpy()[0, 0, 0, 0] == 40
    assert float(lo.asscalar()) == 0. and float(hi.asscalar()) == 6.
    out, _, _ = mx.nd.invoke(
        "_contrib_quantized_pooling",
        [d, mx.nd.array([0.]), mx.nd.array([6.])],
        {"kernel": (2, 2), "pool_type": "avg"})
    assert out.asnumpy()[0, 0, 0, 0] == 25


def test_quantized_concat_rescales():
    a = mx.nd.array(np.array([[127, -127]], "i1"), dtype="int8")   # [-1, 1]
    b = mx.nd.array(np.array([[127, 64]], "i1"), dtype="int8")     # [-2, 2]
    out, lo, hi = mx.nd.invoke(
        "_contrib_quantized_concat",
        [a, b, mx.nd.array([-1.]), mx.nd.array([1.]),
         mx.nd.array([-2.]), mx.nd.array([2.])], {"dim": 1})
    assert float(lo.asscalar()) == -2. and float(hi.asscalar()) == 2.
    # first input's codes are halved into the union range
    np.testing.assert_array_equal(out.asnumpy()[0, :2], [64, -64])
    np.testing.assert_array_equal(out.asnumpy()[0, 2:], [127, 64])


def _write_libsvm(lines):
    f = tempfile.NamedTemporaryFile("w", suffix=".libsvm", delete=False)
    f.write("\n".join(lines) + "\n")
    f.close()
    return f.name


def test_libsvm_iter_basic():
    path = _write_libsvm(["1 0:0.5 3:1.2", "0 1:2.0", "1 2:-1.0 3:0.1",
                          "0 0:4.0", "1 1:1.0"])
    try:
        it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(4,),
                              batch_size=2)
        batches = list(it)
        assert len(batches) == 3
        d0 = batches[0].data[0]
        assert type(d0).__name__ == "CSRNDArray"
        np.testing.assert_allclose(
            d0.asnumpy(), [[0.5, 0, 0, 1.2], [0, 2.0, 0, 0]])
        np.testing.assert_allclose(batches[0].label[0].asnumpy(), [1., 0.])
        assert batches[-1].pad == 1  # last batch wrapped one row
    finally:
        os.unlink(path)


def test_libsvm_iter_sharding_and_label_file():
    data = _write_libsvm(["1 0:1", "2 1:1", "3 2:1", "4 0:2"])
    lab = _write_libsvm(["0:1 1:1", "1:1", "2:1", "0:5"])
    try:
        parts = []
        for pi in range(2):
            it = mx.io.LibSVMIter(data_libsvm=data, data_shape=(3,),
                                  label_libsvm=lab, label_shape=(3,),
                                  batch_size=2, num_parts=2, part_index=pi)
            for b in it:
                parts.append((b.data[0].asnumpy(), b.label[0].asnumpy()))
        # two parts of two rows each; labels come from the label file (CSR)
        assert len(parts) == 2
        np.testing.assert_allclose(parts[0][1],
                                   [[1., 1., 0.], [0., 1., 0.]])
        np.testing.assert_allclose(parts[1][0],
                                   [[0., 0., 1.], [2., 0., 0.]])
    finally:
        os.unlink(data)
        os.unlink(lab)


def test_libsvm_iter_smaller_than_batch():
    path = _write_libsvm(["1 0:1", "0 1:2", "1 2:3"])
    try:
        it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(3,),
                              batch_size=8)
        b = next(iter(it))
        assert b.data[0].shape == (8, 3)  # wrapped modulo the 3 rows
        np.testing.assert_allclose(b.data[0].asnumpy()[3],
                                   b.data[0].asnumpy()[0])
        assert b.label[0].shape == it.provide_label[0].shape[:1]
    finally:
        os.unlink(path)


def test_libsvm_iter_validates():
    path = _write_libsvm(["1 0:1"])
    try:
        with pytest.raises(ValueError):
            mx.io.LibSVMIter(data_libsvm=path, data_shape=(2, 2),
                             batch_size=1)
        with pytest.raises(ValueError):
            mx.io.LibSVMIter(data_libsvm=path, data_shape=(4,),
                             label_shape=(3,), batch_size=1)
    finally:
        os.unlink(path)


def test_reshape_like_ranges():
    """Range-limited reshape_like (reference test_operator.py:2206 table:
    replace lhs dims [lhs_begin, lhs_end) with rhs dims
    [rhs_begin, rhs_end))."""
    cases = [
        ((30,), (15, 2, 4), 0, None, 0, 2, (15, 2)),
        ((30,), (15, 2, 4), None, 1, None, 2, (15, 2)),
        ((30, 7), (15, 2, 4), 0, 1, 0, 2, (15, 2, 7)),
        ((3, 5), (1, 15, 4), 0, 2, 1, 2, (15,)),
        ((3, 5), (1, 15, 4), 0, None, 1, -1, (15,)),
        ((30, 12), (4, 2, 2, 3), -1, None, 1, None, (30, 2, 2, 3)),
        ((1, 1, 7, 3, 1, 1), (81, 1, 1, 21), 1, -1, 1, None,
         (1, 1, 1, 21, 1)),
    ]
    for lshape, rshape, lb, le, rb, re, want in cases:
        lhs = np.arange(int(np.prod(lshape)), dtype="f4").reshape(lshape)
        out = mx.nd.reshape_like(
            mx.nd.array(lhs), mx.nd.zeros(rshape), lhs_begin=lb,
            lhs_end=le, rhs_begin=rb, rhs_end=re)
        assert out.shape == want, (lshape, rshape, out.shape, want)
        np.testing.assert_allclose(out.asnumpy(), lhs.reshape(want))
    # old api unchanged
    out = mx.nd.reshape_like(mx.nd.zeros((40, 30)), mx.nd.zeros((30, 20, 2)))
    assert out.shape == (30, 20, 2)


def test_reshape_like_invalid_range_raises():
    with pytest.raises(Exception, match="invalid lhs range"):
        mx.nd.reshape_like(mx.nd.zeros((1, 6)), mx.nd.ones((1, 3)),
                           lhs_begin=1, lhs_end=0, rhs_begin=0, rhs_end=1)
    # fluent method routes through the operator, ranges included
    out = mx.nd.zeros((30, 7)).reshape_like(
        mx.nd.zeros((15, 2, 4)), lhs_begin=0, lhs_end=1, rhs_begin=0,
        rhs_end=2)
    assert out.shape == (15, 2, 7)
