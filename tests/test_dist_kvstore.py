"""Multi-process dist_sync kvstore: N real processes over jax.distributed
(Gloo on CPU), launched through tools/launch.py — the CI analog of the
reference's nightly dist test (tests/nightly/dist_sync_kvstore.py) per its
runtime_functions.sh local-N-process recipe (ci/docker/runtime_functions.sh
:901-930).
"""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(ROOT, "tools", "launch.py")
WORKER = os.path.join(ROOT, "tests", "dist_worker.py")


@pytest.mark.parametrize("n", [2, 3])
def test_dist_sync_invariants(n):
    env = dict(os.environ)
    # workers pin CPU themselves; drop the suite's forced device count to
    # keep per-process startup light
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, LAUNCH, "-n", str(n), sys.executable, WORKER],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-2000:]
    for rank in range(n):
        assert "rank %d/%d: all dist_sync invariants OK" % (rank, n) \
            in r.stdout, r.stdout[-4000:]


def test_launcher_propagates_failure():
    # --max-restarts 0: the failure is deterministic, retries would only
    # slow the test down (supervised-restart behavior has its own tests
    # in test_fault.py)
    r = subprocess.run(
        [sys.executable, LAUNCH, "-n", "2", "--max-restarts", "0",
         sys.executable, "-c",
         "import sys, os; sys.exit(3 if os.environ['MXNET_WORKER_RANK'] "
         "== '1' else 0)"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 3


def test_single_process_dist_degrades_to_local():
    import mxnet_tpu as mx
    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == 1 and kv.rank == 0
    kv.init("a", mx.nd.ones((2, 2)))
    kv.push("a", mx.nd.ones((2, 2)) * 3)
    out = mx.nd.zeros((2, 2))
    kv.pull("a", out=out)
    assert (out.asnumpy() == 3).all()


def test_dist_training_matches_single_process(tmp_path):
    """2-process data-parallel Module.fit(dist_sync) == single-process
    full-batch training (no BN, so the math is exactly equivalent)."""
    import numpy as np
    n = 2
    dump = str(tmp_path / "dist_params.npz")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["DIST_TRAIN_DUMP"] = dump
    r = subprocess.run(
        [sys.executable, LAUNCH, "-n", str(n), sys.executable,
         os.path.join(ROOT, "tests", "dist_train_worker.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert r.returncode == 0, r.stdout[-4000:] + r.stderr[-2000:]

    # single-process equivalent: full batch = n shards concatenated,
    # same rescale -> identical aggregated gradient per step
    from tests.dist_train_common import (make_net, full_data, fixed_params,
                                         PER_WORKER_BATCH, EPOCHS)
    import mxnet_tpu as mx
    X, Y = full_data(n)
    order = np.concatenate([  # interleave shards the way N workers step
        np.arange(len(X)).reshape(n, -1, PER_WORKER_BATCH)
        .transpose(1, 0, 2).reshape(-1)])
    it = mx.io.NDArrayIter(X[order], Y[order],
                           batch_size=PER_WORKER_BATCH * n,
                           label_name="softmax_label")
    sym = make_net()
    mod = mx.mod.Module(sym)
    mod.fit(it, num_epoch=EPOCHS, kvstore="local", optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1.0 / (PER_WORKER_BATCH * n)},
            arg_params=fixed_params(sym), initializer=None)
    args, _ = mod.get_params()
    dist_params = np.load(dump)
    for name in dist_params.files:
        np.testing.assert_allclose(args[name].asnumpy(), dist_params[name],
                                   rtol=2e-5, atol=2e-6, err_msg=name)
