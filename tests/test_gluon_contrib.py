"""gluon.contrib (reference python/mxnet/gluon/contrib/): Concurrent
containers, SparseEmbedding, SyncBatchNorm, variational dropout, LSTMP,
and the conv recurrent cell family."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, autograd

cnn = gluon.contrib.nn
crnn = gluon.contrib.rnn


def test_concurrent_and_identity():
    for cls, hybrid in [(cnn.Concurrent, False),
                        (cnn.HybridConcurrent, True)]:
        net = cls(axis=1)
        net.add(gluon.nn.Dense(4), gluon.nn.Dense(3), cnn.Identity())
        net.initialize(mx.initializer.Xavier())
        if hybrid:
            net.hybridize()
        x = mx.nd.array(np.random.RandomState(0).rand(2, 5).astype("f4"))
        out = net(x)
        assert out.shape == (2, 12)
        np.testing.assert_allclose(out.asnumpy()[:, 7:], x.asnumpy(),
                                   rtol=1e-6)


def test_sparse_embedding_trains_lazy_rows():
    """The Trainer routes sparse_grad params through the optimizers'
    LAZY row_sparse branch: with weight decay, untouched rows must NOT
    decay (a dense update would shrink every row)."""
    emb = cnn.SparseEmbedding(30, 6)
    emb.initialize(mx.initializer.Normal(0.1))
    tr = gluon.Trainer(emb.collect_params(), "sgd",
                       {"learning_rate": 0.5, "wd": 0.1})
    ids = mx.nd.array([1, 5, 5, 9])
    w0 = emb.weight.data().asnumpy().copy()
    with autograd.record():
        loss = (emb(ids) ** 2).sum()
    loss.backward()
    tr.step(4)
    w1 = emb.weight.data().asnumpy()
    touched = [1, 5, 9]
    untouched = [i for i in range(30) if i not in touched]
    assert not np.allclose(w1[touched], w0[touched])
    np.testing.assert_array_equal(w1[untouched], w0[untouched])


def test_sync_batchnorm_matches_batchnorm():
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(4, 3, 5, 5).astype("f4"))
    a = cnn.SyncBatchNorm(in_channels=3, num_devices=8)
    b = gluon.nn.BatchNorm(axis=1, in_channels=3)
    a.initialize()
    b.initialize()
    with autograd.record():
        ya = a(x)
    with autograd.record():
        yb = b(x)
    np.testing.assert_allclose(ya.asnumpy(), yb.asnumpy(), rtol=1e-5,
                               atol=1e-6)


def test_variational_dropout_mask_constant_across_time():
    vd = crnn.VariationalDropoutCell(gluon.rnn.RNNCell(8),
                                     drop_outputs=0.5)
    vd.base_cell.initialize(mx.initializer.Uniform(1.0))
    mx.random.seed(11)
    # dropout only fires in training mode: record() like a real step
    with autograd.record():
        outs, _ = vd.unroll(4, mx.nd.ones((2, 4, 8)),
                            merge_outputs=False)
    masks = [(o.asnumpy() == 0) for o in outs]
    assert masks[0].any(), "no dropout applied - test would be vacuous"
    for m in masks[1:]:
        np.testing.assert_array_equal(masks[0], m)
    # reset() draws fresh masks (statistically certain to differ)
    vd.reset()
    with autograd.record():
        outs2, _ = vd.unroll(4, mx.nd.ones((2, 4, 8)),
                             merge_outputs=False)
    assert ((outs2[0].asnumpy() == 0) != masks[0]).any()


def test_variational_dropout_hybridized():
    """Masks cached across steps must not leak tracers across jit
    traces (the ZoneoutCell trace-id guard)."""
    vd = crnn.VariationalDropoutCell(gluon.rnn.RNNCell(8),
                                     drop_inputs=0.4)
    vd.base_cell.initialize()
    vd.hybridize()
    for _ in range(2):   # two separate traces
        with autograd.record():
            outs, _ = vd.unroll(3, mx.nd.ones((2, 3, 8)),
                                merge_outputs=True)
        outs.backward()
        vd.reset()


def test_lstmp_cell_shapes_and_grads():
    cell = crnn.LSTMPCell(16, projection_size=8)
    cell.initialize(mx.initializer.Xavier())
    x = mx.nd.array(np.random.RandomState(1).rand(2, 4, 5).astype("f4"))
    with autograd.record():
        out, states = cell.unroll(4, x, merge_outputs=True)
        loss = (out ** 2).sum()
    loss.backward()
    assert out.shape == (2, 4, 8)          # projected size
    assert states[0].shape == (2, 8) and states[1].shape == (2, 16)
    g = cell.params.get("h2r_weight").grad()
    assert float(np.abs(g.asnumpy()).sum()) > 0


def test_lstmp_reduces_to_manual_math():
    """One step vs hand-computed LSTMP equations."""
    cell = crnn.LSTMPCell(4, projection_size=3, input_size=2)
    cell.initialize(mx.initializer.Uniform(0.5))
    x = mx.nd.array(np.random.RandomState(2).rand(1, 2).astype("f4"))
    states = cell.begin_state(1)
    out, _ = cell(x, states)
    names = {k.split("_", 1)[1]: v.data().asnumpy()
             for k, v in cell.params._params.items()}
    i2h = x.asnumpy() @ names["i2h_weight"].T + names["i2h_bias"]
    h2h = np.zeros_like(i2h) + names["h2h_bias"]
    gates = (i2h + h2h).reshape(4, 4)

    def sig(v):
        return 1 / (1 + np.exp(-v))
    i, f, g, o = sig(gates[0]), sig(gates[1]), np.tanh(gates[2]), \
        sig(gates[3])
    c = f * 0 + i * g
    h = o * np.tanh(c)
    r = h @ names["h2r_weight"].T
    np.testing.assert_allclose(out.asnumpy()[0], r, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("cls,ndim,n_states", [
    (crnn.Conv1DRNNCell, 1, 1), (crnn.Conv2DRNNCell, 2, 1),
    (crnn.Conv3DRNNCell, 3, 1), (crnn.Conv1DLSTMCell, 1, 2),
    (crnn.Conv2DLSTMCell, 2, 2), (crnn.Conv3DLSTMCell, 3, 2),
    (crnn.Conv1DGRUCell, 1, 1), (crnn.Conv2DGRUCell, 2, 1),
    (crnn.Conv3DGRUCell, 3, 1),
])
def test_conv_cells_unroll_and_grads(cls, ndim, n_states):
    spatial = (6,) * ndim
    cell = cls(input_shape=(3,) + spatial, hidden_channels=4,
               i2h_kernel=3, h2h_kernel=3)
    cell.initialize(mx.initializer.Xavier())
    rng = np.random.RandomState(0)
    seq = mx.nd.array(rng.rand(2, 3, 3, *spatial).astype("f4"))
    with autograd.record():
        out, states = cell.unroll(3, seq, merge_outputs=True)
        loss = (out ** 2).sum()
    loss.backward()
    assert out.shape == (2, 3, 4) + spatial
    assert len(states) == n_states
    for s in states:
        assert s.shape == (2, 4) + spatial
    g = cell.params.get("h2h_weight").grad()
    assert float(np.abs(g.asnumpy()).sum()) > 0


def test_conv_cell_rejects_even_h2h_kernel():
    with pytest.raises(ValueError):
        crnn.Conv2DLSTMCell(input_shape=(3, 6, 6), hidden_channels=4,
                            i2h_kernel=3, h2h_kernel=2)


def test_interval_sampler():
    assert list(gluon.contrib.data.IntervalSampler(10, 3)) == \
        [0, 3, 6, 9, 1, 4, 7, 2, 5, 8]
    s = gluon.contrib.data.IntervalSampler(10, 3, rollover=False)
    assert list(s) == [0, 3, 6, 9] and len(s) == 4
    with pytest.raises(ValueError):
        gluon.contrib.data.IntervalSampler(3, 5)


def test_sparse_embedding_lazy_rows_update_on_kvstore():
    """Same lazy contract when the update runs ON the kvstore (the dist
    path): the pushed gradient must be row_sparse so the store's updater
    hits the lazy branch too."""
    emb = cnn.SparseEmbedding(30, 6)
    emb.initialize(mx.initializer.Normal(0.1))
    tr = gluon.Trainer(emb.collect_params(), "sgd",
                       {"learning_rate": 0.5, "wd": 0.1},
                       kvstore="local", update_on_kvstore=True)
    ids = mx.nd.array([2, 7])
    w0 = emb.weight.data().asnumpy().copy()
    with autograd.record():
        loss = (emb(ids) ** 2).sum()
    loss.backward()
    tr.step(2)
    w1 = emb.weight.data().asnumpy()
    untouched = [i for i in range(30) if i not in (2, 7)]
    assert not np.allclose(w1[[2, 7]], w0[[2, 7]])
    np.testing.assert_array_equal(w1[untouched], w0[untouched])
