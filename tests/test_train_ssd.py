"""End-to-end SSD detector training smoke (VERDICT r3 #9): the full
example — synthetic detection .rec -> ImageDetIter -> multibox target ->
fused Module.fit — must run and the loss must decrease."""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

cv2 = pytest.importorskip("cv2")


def test_train_ssd_loss_decreases(tmp_path):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "examples", "train_ssd.py"),
         "--device", "cpu", "--epochs", "3", "--batch-size", "8",
         "--prefix", str(tmp_path / "ssd")],
        capture_output=True, text=True, timeout=420, env=env,
        cwd=os.path.join(ROOT, "examples"))
    assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-2000:]
    assert "SSD training OK" in r.stdout
    assert os.path.exists(str(tmp_path / "ssd-symbol.json"))
    assert os.path.exists(str(tmp_path / "ssd-0003.params"))
