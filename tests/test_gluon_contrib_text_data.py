"""WikiText datasets (parity: python/mxnet/gluon/contrib/data/text.py)
on a synthetic corpus in the reference's file layout."""
import os
import zipfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.contrib.data import WikiText2, WikiText103

CORPUS = """\
 the quick brown fox jumps over the lazy dog

 the dog sleeps all day long
 a fox is quick and brown
"""


def _write_corpus(root, segment="train"):
    os.makedirs(root, exist_ok=True)
    fname = {"train": "wiki.train.tokens", "validation": "wiki.valid.tokens",
             "test": "wiki.test.tokens"}[segment]
    with open(os.path.join(root, fname), "w", encoding="utf8") as f:
        f.write(CORPUS)


def test_wikitext2_reads_reference_layout(tmp_path):
    root = str(tmp_path)
    _write_corpus(root)
    ds = WikiText2(root=root, segment="train", seq_len=5)
    # 3 non-empty lines: 9 + 6 + 6 tokens + 3 <eos> = 24 tokens; the
    # shifted stream has 23 entries -> 4 full samples of 5
    assert len(ds) == 4
    data, label = ds[0]
    assert data.shape == (5,) and label.shape == (5,)
    # label is data shifted by one position in the flat stream
    d_all = np.concatenate([ds[i][0].asnumpy() for i in range(len(ds))])
    l_all = np.concatenate([ds[i][1].asnumpy() for i in range(len(ds))])
    np.testing.assert_array_equal(d_all[1:], l_all[:-1])


def test_wikitext_vocab_eos_and_roundtrip(tmp_path):
    root = str(tmp_path)
    _write_corpus(root)
    ds = WikiText2(root=root, seq_len=5)
    vocab = ds.vocabulary
    assert vocab.to_indices("<eos>") > 0          # reserved, indexed
    assert ds.frequencies["the"] == 3
    toks = vocab.to_tokens([int(i) for i in ds[0][0].asnumpy()])
    assert toks[0] == "the"                        # corpus order preserved


def test_wikitext_shared_vocab_across_segments(tmp_path):
    root = str(tmp_path)
    _write_corpus(root, "train")
    _write_corpus(root, "test")
    train = WikiText2(root=root, segment="train", seq_len=5)
    test = WikiText2(root=root, segment="test", seq_len=5,
                     vocab=train.vocabulary)
    assert test.vocabulary is train.vocabulary
    np.testing.assert_array_equal(test[0][0].asnumpy(),
                                  train[0][0].asnumpy())


def test_wikitext103_extracts_local_archive(tmp_path):
    root = str(tmp_path)
    os.makedirs(root, exist_ok=True)
    with zipfile.ZipFile(os.path.join(root, "wikitext-103-v1.zip"),
                         "w") as zf:
        zf.writestr("wikitext-103/wiki.train.tokens", CORPUS)
    ds = WikiText103(root=root, seq_len=7)
    assert len(ds) >= 3
    assert os.path.exists(os.path.join(root, "wiki.train.tokens"))


def test_wikitext_missing_corpus_is_loud(tmp_path):
    with pytest.raises(RuntimeError, match="wiki.valid.tokens"):
        WikiText2(root=str(tmp_path), segment="validation")


def test_wikitext_feeds_dataloader():
    """End-to-end: dataset -> DataLoader -> LSTM-shaped batches."""
    import tempfile
    root = tempfile.mkdtemp()
    _write_corpus(root)
    ds = WikiText2(root=root, seq_len=5)
    loader = mx.gluon.data.DataLoader(ds, batch_size=2)
    data, label = next(iter(loader))
    assert data.shape == (2, 5) and label.shape == (2, 5)
    assert data.dtype == np.int32
