"""Online serving runtime (mxnet_tpu.serve): dynamic micro-batching,
shape-bucketed executable cache, admission control — all chip-free.

The acceptance property: >= 8 concurrent single requests coalesce into
ONE bucketed device batch whose per-request outputs are BITWISE equal
to individual CompiledModel calls through the same bucket engine, with
the metrics snapshot reporting per-bucket latency percentiles and the
padding-waste ratio for the run.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.serve import (DeadlineExceeded, Server, ServerBusy,
                             ServerClosed, serve_http)


@pytest.fixture(scope="module")
def art(tmp_path_factory):
    """A dynamic-batch artifact of a small conv+BN net, plus the raw
    (sym, args, aux) for live-executor parity checks."""
    tmp = tmp_path_factory.mktemp("serve")
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4,
                             pad=(1, 1), name="c1")
    net = mx.sym.BatchNorm(net, name="bn1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(0)
    shapes, _, aux_shapes = net.infer_shape(data=(2, 1, 8, 8))
    args = {n: mx.nd.array(rng.uniform(-0.3, 0.3, s).astype("f4"))
            for n, s in zip(net.list_arguments(), shapes)
            if n not in ("data", "softmax_label")}
    aux = {n: mx.nd.array(np.ones(s, "f4") if "var" in n
                          else np.zeros(s, "f4"))
           for n, s in zip(net.list_auxiliary_states(), aux_shapes)}
    path = str(tmp / "m.mxtpu")
    meta = mx.serving.export_compiled(net, args, {k: v for k, v in
                                                  aux.items()},
                                      {"data": (None, 1, 8, 8)}, path)
    assert meta["dynamic_batch"] is True
    return {"path": path, "sym": net, "args": args, "aux": aux}


@pytest.fixture(scope="module")
def qart(art, tmp_path_factory):
    """The int8-quantized sibling of ``art`` (format_version 4)."""
    from mxnet_tpu import quant
    path = str(tmp_path_factory.mktemp("serve_q") / "m.int8.mxtpu")
    rng = np.random.RandomState(20)
    calib = [{"data": rng.randn(4, 1, 8, 8).astype("f4")}
             for _ in range(3)]
    meta = quant.export_quantized(art["sym"], art["args"], art["aux"],
                                  calib, {"data": (None, 1, 8, 8)}, path)
    assert meta["format_version"] == 4
    return path


def _x(rng, n=1):
    return rng.randn(n, 1, 8, 8).astype("f4")


# ---------------------------------------------------------------------------
# acceptance: coalescing + bitwise parity + metrics
# ---------------------------------------------------------------------------

def test_coalesces_eight_concurrent_requests_into_one_batch_bitwise(art):
    srv = Server(art["path"], buckets=(8,), auto_start=False,
                 batch_timeout_ms=0)
    cm_ref = mx.serving.CompiledModel.load(art["path"], buckets=(8,))
    rng = np.random.RandomState(1)
    xs = [_x(rng) for _ in range(8)]
    results = [None] * 8
    errors = []
    barrier = threading.Barrier(8)

    def caller(i):
        try:
            barrier.wait(5)
            req = srv.submit(data=xs[i], timeout_ms=30000)
            results[i] = req.result(timeout=30)
        except Exception as e:   # pragma: no cover - diagnostic
            errors.append((i, e))

    threads = [threading.Thread(target=caller, args=(i,), daemon=True)
               for i in range(8)]
    for t in threads:
        t.start()
    # wait until ALL 8 are queued, then run ONE batcher round
    t_end = time.monotonic() + 10
    while srv._queue.pending_count() < 8:
        assert time.monotonic() < t_end, "submissions did not arrive"
        time.sleep(0.002)
    taken = srv.run_once(block=False)
    assert taken == 8
    for t in threads:
        t.join(30)
    assert not errors, errors

    # bitwise equality vs individual CompiledModel calls (same bucket)
    for i in range(8):
        ref = np.asarray(cm_ref.predict(data=xs[i])[0])
        assert (results[i][0] == ref).all(), "row %d not bitwise equal" % i

    snap = srv.metrics()
    b8 = snap["buckets"]["8"]
    assert b8["batches"] == 1            # ONE device batch for all 8
    assert b8["rows"] == 8
    assert b8["padded_rows"] == 0
    assert b8["occupancy"] == 1.0
    assert b8["padding_waste"] == 0.0
    lat = b8["latency_ms"]
    assert lat["count"] == 8
    for p in ("p50", "p95", "p99"):
        assert lat[p] is not None and lat[p] > 0
    assert snap["requests"]["completed"] == 8
    assert snap["requests"]["rejected"] == 0
    srv.close(drain=True)


def test_padded_rows_never_leak_and_waste_is_reported(art):
    srv = Server(art["path"], buckets=(8,), auto_start=False,
                 batch_timeout_ms=0)
    cm_ref = mx.serving.CompiledModel.load(art["path"], buckets=(8,))
    rng = np.random.RandomState(2)
    xs = [_x(rng) for _ in range(5)]
    reqs = [srv.submit(data=x, timeout_ms=30000) for x in xs]
    assert srv.run_once(block=False) == 5
    for x, r in zip(xs, reqs):
        out = r.result(5)
        assert out[0].shape == (1, 3)            # real rows only
        assert (out[0] == np.asarray(cm_ref.predict(data=x)[0])).all()
    b8 = srv.metrics()["buckets"]["8"]
    assert b8["rows"] == 5 and b8["padded_rows"] == 3
    assert b8["padding_waste"] == round(3 / 8, 4)
    assert b8["occupancy"] == round(5 / 8, 4)
    srv.close(drain=True)


def test_multi_row_requests_coalesce_to_the_right_bucket(art):
    srv = Server(art["path"], buckets=(1, 2, 4, 8), auto_start=False,
                 batch_timeout_ms=0)
    rng = np.random.RandomState(3)
    r1 = srv.submit(data=_x(rng, 2), timeout_ms=30000)
    r2 = srv.submit(data=_x(rng, 3), timeout_ms=30000)
    assert srv.run_once(block=False) == 2
    assert r1.result(5)[0].shape == (2, 3)
    assert r2.result(5)[0].shape == (3, 3)
    assert r1.bucket == r2.bucket == 8           # 5 rows -> bucket 8
    srv.close(drain=True)


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def test_deadline_expiry_skips_dispatch(art):
    srv = Server(art["path"], buckets=(8,), auto_start=False,
                 batch_timeout_ms=0)
    rng = np.random.RandomState(4)
    req = srv.submit(data=_x(rng), timeout_ms=5)
    time.sleep(0.05)
    srv.run_once(block=False)
    with pytest.raises(DeadlineExceeded):
        req.result(1)
    snap = srv.metrics()
    assert snap["requests"]["expired"] == 1
    assert snap["buckets"] == {}                 # nothing was dispatched
    srv.close(drain=True)


def test_backpressure_rejects_with_retry_after(art):
    srv = Server(art["path"], buckets=(8,), auto_start=False,
                 queue_depth=2, batch_timeout_ms=0)
    rng = np.random.RandomState(5)
    srv.submit(data=_x(rng), timeout_ms=30000)
    srv.submit(data=_x(rng), timeout_ms=30000)
    with pytest.raises(ServerBusy) as ei:
        srv.submit(data=_x(rng), timeout_ms=30000)
    assert ei.value.retry_after > 0
    assert srv.metrics()["requests"]["rejected"] == 1
    srv.run_once(block=False)                    # free the queue
    srv.close(drain=True)


def test_request_larger_than_biggest_bucket_is_rejected(art):
    srv = Server(art["path"], buckets=(8,), auto_start=False)
    with pytest.raises(mx.base.MXNetError) as ei:
        srv.submit(data=np.zeros((9, 1, 8, 8), "f4"))
    assert "exceeds the largest bucket" in str(ei.value)
    srv.close(drain=True)


def test_drain_on_shutdown_completes_everything(art):
    srv = Server(art["path"], buckets=(1, 8), batch_timeout_ms=2)
    rng = np.random.RandomState(6)
    reqs = [srv.submit(data=_x(rng), timeout_ms=30000)
            for _ in range(12)]
    srv.close(drain=True)                        # graceful
    for r in reqs:
        assert r.result(1)[0].shape == (1, 3)
    snap = srv.metrics()
    assert snap["requests"]["completed"] == 12
    assert snap["requests"]["dropped"] == 0
    assert snap["status"] == "closed"
    with pytest.raises(ServerClosed):
        srv.submit(data=_x(rng))


def test_close_without_drain_fails_pending_as_dropped(art):
    srv = Server(art["path"], buckets=(8,), auto_start=False,
                 batch_timeout_ms=0)
    rng = np.random.RandomState(7)
    reqs = [srv.submit(data=_x(rng), timeout_ms=30000) for _ in range(3)]
    srv.close(drain=False)
    for r in reqs:
        with pytest.raises(ServerClosed):
            r.result(1)
    assert srv.metrics()["requests"]["dropped"] == 3


# ---------------------------------------------------------------------------
# parity + engine cache + observability
# ---------------------------------------------------------------------------

def test_server_predict_parity_vs_live_module(art):
    """export -> load -> batched Server.predict matches the live
    executor (Module forward) on the same params."""
    rng = np.random.RandomState(8)
    x = _x(rng, 4)
    srv = Server(art["path"], buckets=(1, 4, 8), batch_timeout_ms=0)
    out = srv.predict(data=x, timeout_ms=30000)[0]
    srv.close(drain=True)

    m = mx.mod.Module(art["sym"])
    m.bind([("data", (4, 1, 8, 8))], [("softmax_label", (4,))],
           for_training=False)
    m.set_params(art["args"], art["aux"])
    from mxnet_tpu.io import DataBatch
    m.forward(DataBatch(data=[mx.nd.array(x)]), is_train=False)
    live = m.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(out, live, rtol=1e-5, atol=1e-6)


def test_engine_cache_lru_eviction(art):
    srv = Server(art["path"], buckets=(1, 2), auto_start=False,
                 cache_engines=1, batch_timeout_ms=0)
    rng = np.random.RandomState(9)
    r = srv.submit(data=_x(rng, 1), timeout_ms=30000)
    srv.run_once(block=False)
    r.result(5)
    r = srv.submit(data=_x(rng, 2), timeout_ms=30000)
    srv.run_once(block=False)
    r.result(5)
    eng = srv.metrics()["engines"]
    assert eng["builds"] == 2
    assert eng["evictions"] == 1
    assert list(eng["engines"]) == ["2"]         # only the LRU survivor
    srv.close(drain=True)


def test_fixed_batch_artifact_serves_at_frozen_bucket(art, tmp_path):
    fixed = str(tmp_path / "fixed.mxtpu")
    mx.serving.export_compiled(art["sym"], art["args"], art["aux"],
                               {"data": (4, 1, 8, 8)}, fixed)
    srv = Server(fixed, auto_start=False, batch_timeout_ms=0)
    assert srv.buckets == (4,)                   # frozen batch IS the bucket
    rng = np.random.RandomState(10)
    xs = [_x(rng) for _ in range(2)]
    reqs = [srv.submit(data=x, timeout_ms=30000) for x in xs]
    srv.run_once(block=False)
    cm_ref = mx.serving.CompiledModel.load(fixed, buckets=(4,))
    for x, r in zip(xs, reqs):
        assert (r.result(5)[0] == np.asarray(
            cm_ref.predict(data=x)[0])).all()
    assert srv.metrics()["buckets"]["4"]["padded_rows"] == 2
    srv.close(drain=True)


def test_profiler_sees_serve_events(art, tmp_path):
    prof = str(tmp_path / "serve_prof.json")
    mx.profiler.set_config(filename=prof)
    mx.profiler.set_state("run")
    try:
        srv = Server(art["path"], buckets=(8,), auto_start=False,
                     batch_timeout_ms=0)
        rng = np.random.RandomState(11)
        req = srv.submit(data=_x(rng), timeout_ms=30000)
        srv.run_once(block=False)
        req.result(5)
        srv.close(drain=True)
    finally:
        mx.profiler.set_state("stop")
    mx.profiler.dump()
    with open(prof) as f:
        events = json.load(f)["traceEvents"]
    names = [e.get("name") for e in events]
    assert "serve/bucket8" in names              # duration event
    assert "serve/queue_depth" in names          # counter track


def test_quantized_engines_serve_side_by_side_with_dtype_metrics(art,
                                                                 qart):
    """One server, one bucket, BOTH precisions: f32 and int8 requests
    coalesce into their own device batches through the dtype-routed
    engine cache, each request's output is bitwise equal to the matching
    CompiledModel through the same bucket, and the metrics snapshot
    tags every per-bucket series with its dtype."""
    # max bucket 4 => ONE coalescing window admits all 4 requests; the
    # per-dtype split then lands each pair in its own bucket-2 batch
    srv = Server(art["path"], quantized=qart, buckets=(2, 4),
                 auto_start=False, batch_timeout_ms=0)
    assert srv.model.engine_cache.dtypes == ("f32", "int8")
    rng = np.random.RandomState(21)
    xs = [_x(rng) for _ in range(4)]
    f32_reqs = [srv.submit(data=xs[i], timeout_ms=30000)
                for i in range(2)]
    int8_reqs = [srv.submit(data=xs[2 + i], timeout_ms=30000,
                            dtype="int8") for i in range(2)]
    assert srv.run_once(block=False) == 4        # ONE coalescing round...

    cm_f32 = mx.serving.CompiledModel.load(art["path"], buckets=(2,))
    cm_int8 = mx.serving.CompiledModel.load(qart, buckets=(2,))
    for i, r in enumerate(f32_reqs):
        ref = np.asarray(cm_f32.predict(data=xs[i])[0])
        assert (r.result(30)[0] == ref).all()
    for i, r in enumerate(int8_reqs):
        ref = np.asarray(cm_int8.predict(data=xs[2 + i])[0])
        assert (r.result(30)[0] == ref).all()
    # ...but one device batch PER dtype (precisions never mix in a batch)
    snap = srv.metrics()
    assert snap["buckets"]["2"]["batches"] == 2  # merged (historical key)
    by_dtype = snap["buckets_by_dtype"]
    assert by_dtype["f32"]["2"]["batches"] == 1
    assert by_dtype["f32"]["2"]["rows"] == 2
    assert by_dtype["int8"]["2"]["batches"] == 1
    assert by_dtype["int8"]["2"]["rows"] == 2
    for d in ("f32", "int8"):
        lat = by_dtype[d]["2"]["latency_ms"]
        assert lat["count"] == 2
        assert lat["p50"] is not None and lat["p99"] is not None

    eng = snap["engines"]
    assert eng["dtypes"] == ["f32", "int8"]
    assert sorted(eng["engines"]) == ["2", "int8:2"]
    assert eng["engines"]["int8:2"]["dtype"] == "int8"

    # unknown dtypes are rejected at admission, not at dispatch
    with pytest.raises(mx.base.MXNetError) as ei:
        srv.submit(data=_x(rng), dtype="bf16")
    assert "bf16" in str(ei.value)
    srv.close(drain=True)


def test_quantized_attach_requires_v4_artifact(art, tmp_path):
    """quantized= refuses a plain f32 artifact: the int8 route must not
    silently serve f32 weights as 'int8'."""
    with pytest.raises(mx.base.MXNetError) as ei:
        Server(art["path"], quantized=art["path"], auto_start=False)
    assert "quantize_model" in str(ei.value)


def test_loadgen_routes_dtype_to_quantized_engines(art, qart):
    from tools.serve_loadgen import measure
    srv = Server(art["path"], quantized=qart, buckets=(1, 8),
                 batch_timeout_ms=1)
    res = measure(srv, concurrency=4, requests=12, timeout_ms=30000,
                  dtype="int8")
    snap = srv.metrics()
    srv.close(drain=True)
    assert res["errors"] == 0 and res["completed"] == 12
    int8_rows = sum(b["rows"]
                    for b in snap["buckets_by_dtype"]["int8"].values())
    assert int8_rows == 12                       # every request went int8
    assert "f32" not in snap["buckets_by_dtype"]


def test_loadgen_inprocess_accounting(art):
    from tools.serve_loadgen import measure
    srv = Server(art["path"], buckets=(1, 8), batch_timeout_ms=1)
    res = measure(srv, concurrency=4, requests=16, timeout_ms=30000)
    srv.close(drain=True)
    assert (res["completed"] + res["rejected"] + res["expired"]
            + res["errors"]) == res["attempted"] == 16
    assert res["errors"] == 0
    assert res["completed"] > 0
    assert res["latency_ms"]["p50"] is not None
    assert sum(res["histogram"]["counts"]) == res["completed"]
    assert res["goodput_qps"] > 0


# ---------------------------------------------------------------------------
# HTTP front end
# ---------------------------------------------------------------------------

def test_http_front_end_round_trip(art):
    srv = Server(art["path"], buckets=(1, 8), batch_timeout_ms=1)
    front = serve_http(srv, host="127.0.0.1", port=0)
    try:
        url = front.address
        rng = np.random.RandomState(12)
        x = _x(rng)
        body = json.dumps({"inputs": {"data": x.tolist()}}).encode()
        req = urllib.request.Request(
            url + "/v1/predict", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            payload = json.loads(r.read().decode())
        cm = mx.serving.CompiledModel.load(art["path"], buckets=(1, 8))
        ref = np.asarray(cm.predict(data=x)[0])
        np.testing.assert_allclose(
            np.asarray(payload["outputs"][0], "f4"), ref,
            rtol=1e-6, atol=1e-7)
        assert payload["bucket"] == 1
        with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
            snap = json.loads(r.read().decode())
        assert snap["requests"]["completed"] >= 1
        with urllib.request.urlopen(url + "/healthz", timeout=10) as r:
            assert json.loads(r.read().decode())["status"] == "ok"
        # malformed input -> 400 naming the input, not a 500
        bad = json.dumps({"inputs": {"data": [[0.0] * 3]}}).encode()
        breq = urllib.request.Request(
            url + "/v1/predict", data=bad,
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(breq, timeout=10)
            assert False, "expected HTTP 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "data" in json.loads(e.read().decode())["error"]
    finally:
        front.stop(drain=True)
    assert srv.closed


def test_http_metrics_speaks_prometheus_on_request(art):
    """Content negotiation on /metrics: JSON snapshot by default (the
    back-compat path asserted above), Prometheus text exposition for a
    scraper's Accept header or ?format=prometheus."""
    from mxnet_tpu.telemetry import prom
    srv = Server(art["path"], buckets=(1,), batch_timeout_ms=1)
    front = serve_http(srv, host="127.0.0.1", port=0)
    try:
        url = front.address
        rng = np.random.RandomState(13)
        srv.submit(data=_x(rng), timeout_ms=30000).result(30)
        req = urllib.request.Request(url + "/metrics",
                                     headers={"Accept": "text/plain"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.headers["Content-Type"] == prom.CONTENT_TYPE
            fams = prom.parse_exposition(r.read().decode())
        assert fams["mxtpu_serve_completed_total"]["samples"][0][1] >= 1
        assert "mxtpu_serve_latency_ms" in fams
        with urllib.request.urlopen(url + "/metrics?format=prometheus",
                                    timeout=10) as r:
            prom.parse_exposition(r.read().decode())
    finally:
        front.stop(drain=True)


# ---------------------------------------------------------------------------
# soak: graceful restart drops nothing (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soak_graceful_restart_drops_no_inflight_requests(art):
    """Closed-loop load against server A; mid-run A is gracefully
    drained and replaced by server B. Every admitted request must
    complete (zero dropped); rejected submits retry onto B."""
    from tools.serve_loadgen import measure

    servers = [Server(art["path"], buckets=(1, 8), batch_timeout_ms=1,
                      queue_depth=64)]
    swapped = threading.Event()

    def current():
        return servers[-1]

    result = {}

    def drive():
        result.update(measure(current, concurrency=8, requests=300,
                              timeout_ms=30000, retries=20))

    t = threading.Thread(target=drive, daemon=True)
    t.start()
    time.sleep(0.5)                    # mid-flight...
    old = servers[-1]
    servers.append(Server(art["path"], buckets=(1, 8), batch_timeout_ms=1,
                          queue_depth=64))
    swapped.set()
    old.close(drain=True)              # graceful: finish every admitted req
    t.join(120)
    assert not t.is_alive(), "loadgen did not finish"
    new = servers[-1]
    new.close(drain=True)

    assert result["errors"] == 0
    assert result["expired"] == 0
    assert result["rejected"] == 0     # retries rerouted every reject
    assert result["completed"] == result["attempted"] == 300
    for s in (old, new):
        snap = s.metrics()
        assert snap["requests"]["dropped"] == 0
        # every request ADMITTED by this server got a response
        assert (snap["requests"]["completed"] + snap["requests"]["expired"]
                ) == snap["requests"]["submitted"]
    total = (old.metrics()["requests"]["completed"]
             + new.metrics()["requests"]["completed"])
    assert total == 300
