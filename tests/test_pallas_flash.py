"""Pallas flash-attention kernel vs the dense reference (interpreter mode
on CPU; the same kernel lowers via Mosaic on TPU)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.parallel import attention_reference, flash_attention


def _qkv(b=2, h=3, tq=256, tk=256, d=64, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda t: jnp.asarray(
        (rng.randn(b, h, t, d) / np.sqrt(d)).astype(dtype))
    return mk(tq), mk(tk), mk(tk)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, 128, 128, causal)
    ref = attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_uneven_lengths_and_blocks():
    # T not a multiple of the block sizes: padding paths on both axes
    q, k, v = _qkv(tq=200, tk=328, d=32)
    out = flash_attention(q, k, v, 128, 128, False)
    ref = attention_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_causal_cross_length():
    # decode-style: fewer queries than keys, diagonal offset tk - tq
    q, k, v = _qkv(tq=64, tk=256)
    out = flash_attention(q, k, v, 64, 128, True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_bf16_accumulates_f32():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, 128, 128, False)
    assert out.dtype == jnp.bfloat16
    ref = attention_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients(causal):
    q, k, v = _qkv(tq=128, tk=128, d=32)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, 64, 64, causal) ** 2).sum()

    def loss_ref(q, k, v):
        return (attention_reference(q, k, v, causal=causal) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_jits():
    q, k, v = _qkv(tq=128, tk=128)
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, 64, 64, True))
    out1 = f(q, k, v)
    out2 = f(q, k, v)  # cached trace
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2))


def test_flash_registered_op_eager():
    import mxnet_tpu as mx
    q, k, v = _qkv(tq=64, tk=64, d=32)
    out = mx.nd._contrib_FlashAttention(
        mx.nd.array(np.asarray(q)), mx.nd.array(np.asarray(k)),
        mx.nd.array(np.asarray(v)), causal=True)
    ref = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out.asnumpy(), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_causal_more_queries_than_keys_matches_blockwise():
    """seq_q > seq_k causal: fully-masked leading rows are ZERO (the
    flash/blockwise convention, documented on flash_attention) and the
    visible region matches blockwise numerics."""
    from mxnet_tpu.parallel import blockwise_attention
    q, k, v = _qkv(tq=128, tk=64, d=32)
    out = np.asarray(flash_attention(q, k, v, 64, 64, True))
    blk = np.asarray(blockwise_attention(q, k, v, block_size=64,
                                         causal=True))
    np.testing.assert_allclose(out, blk, rtol=2e-5, atol=2e-5)
    assert np.all(out[:, :, :63] == 0)  # rows before the first visible key
