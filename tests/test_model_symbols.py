"""Symbolic model builders (reference example/image-classification/symbols):
resnet (covered elsewhere), inception-v3, alexnet — shape-inferred and
executed forward through the bound executor."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import models


def test_inception_v3_shapes():
    s = models.inception_v3_symbol(num_classes=1000)
    _, outs, _ = s.infer_shape(data=(4, 3, 299, 299))
    assert outs == [(4, 1000)]
    # the documented minimum input also resolves
    _, outs, _ = s.infer_shape(data=(1, 3, 139, 139))
    assert outs == [(1, 1000)]


def test_inception_v3_forward():
    s = models.inception_v3_symbol(num_classes=7, dropout=0.0)
    ex = s.simple_bind(mx.cpu(), data=(1, 3, 139, 139), grad_req="null")
    for k, v in ex.arg_dict.items():
        if k not in ("data", "softmax_label"):
            v[:] = mx.nd.array(
                np.random.RandomState(0).uniform(-0.05, 0.05, v.shape)
                .astype("f4"))
    for k, v in ex.aux_dict.items():
        v[:] = mx.nd.ones(v.shape) if k.endswith("var") \
            else mx.nd.zeros(v.shape)
    ex.forward(is_train=False,
               data=mx.nd.array(np.random.rand(1, 3, 139, 139)
                                .astype("f4")))
    out = ex.outputs[0].asnumpy()
    assert out.shape == (1, 7)
    np.testing.assert_allclose(out.sum(), 1.0, rtol=1e-4)


def test_alexnet_forward():
    s = models.alexnet_symbol(num_classes=5)
    ex = s.simple_bind(mx.cpu(), data=(2, 3, 224, 224), grad_req="null")
    for k, v in ex.arg_dict.items():
        if k not in ("data", "softmax_label"):
            v[:] = mx.nd.array(
                np.random.RandomState(1).uniform(-0.02, 0.02, v.shape)
                .astype("f4"))
    ex.forward(is_train=False,
               data=mx.nd.array(np.random.rand(2, 3, 224, 224)
                                .astype("f4")))
    out = ex.outputs[0].asnumpy()
    assert out.shape == (2, 5)
    np.testing.assert_allclose(out.sum(axis=1), [1.0, 1.0], rtol=1e-4)
