"""Bucketed, backward-overlapped gradient all-reduce (parallel/ddp.py).

In-process half: bucket partitioning edges, GradReducer numerics on the
8-virtual-device mesh, the SPMDTrainStep ``ddp_bucketed`` mode against
the GSPMD reference (dp-only and dp x tp), Module.fit's DDP path vs the
kvstore path, and MXL507 over the really-lowered step.

Fleet half: N real processes through ``tools/launch.py --ddp`` (2 and 4
ranks) running tests/ddp_train_worker.py — bitwise parity across bucket
sizes incl. optimizer state, cross-rank equality, and (slow) an injected
kill survived by supervised restart with MXNET_DDP on.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import config
from mxnet_tpu.parallel import ddp

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCH = os.path.join(ROOT, "tools", "launch.py")
WORKER = os.path.join(ROOT, "tests", "ddp_train_worker.py")

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs the 8-virtual-device mesh")


# ------------------------------------------------------------ bucket plan

def test_partition_buckets_reverse_order_and_size_bound():
    entries = [("a", (8,), np.float32), ("b", (8,), np.float32),
               ("c", (8,), np.float32)]
    buckets = ddp.partition_buckets(entries, bucket_bytes=64)
    # reverse production order: the LAST param leads bucket 0
    assert buckets[0].keys == ("c", "b")
    assert buckets[1].keys == ("a",)
    assert all(b.nbytes <= 64 for b in buckets)


def test_partition_buckets_oversized_param_gets_own_bucket():
    entries = [("small", (4,), np.float32), ("big", (1024,), np.float32),
               ("tail", (4,), np.float32)]
    buckets = ddp.partition_buckets(entries, bucket_bytes=64)
    big = [b for b in buckets if "big" in b.keys]
    assert len(big) == 1 and big[0].keys == ("big",)


def test_partition_buckets_dtype_change_closes_bucket():
    entries = [("f1", (4,), np.float32), ("h1", (4,), np.float16),
               ("h2", (4,), np.float16)]
    buckets = ddp.partition_buckets(entries, bucket_bytes=1 << 20,
                                    reverse=False)
    assert [b.dtype for b in buckets] == [np.dtype(np.float32),
                                          np.dtype(np.float16)]
    assert buckets[1].keys == ("h1", "h2")


def test_choose_bucket_bytes_override_and_model():
    with config.override(ddp_bucket_mb=2.0):
        assert ddp.choose_bucket_bytes() == 2 << 20
    with config.override(ddp_bucket_mb=0.0):
        b = ddp.choose_bucket_bytes("TPU v5p")
        assert (1 << 20) <= b <= (64 << 20)


def test_choose_bucket_bytes_tracks_interconnect_table():
    """The auto-sized bucket is the ICI-table formula, clamped — pinned
    per device kind so a table edit shows up as a policy change here."""
    from mxnet_tpu import perfmodel
    with config.override(ddp_bucket_mb=0.0):
        for kind in ("TPU v5p", "TPU v4", "TPU v3", "TPU v2", "weird"):
            bw = perfmodel.interconnect_bytes_per_s(kind)
            want = int(min(max(bw * 20e-6 / 0.05, 1 << 20), 64 << 20))
            assert ddp.choose_bucket_bytes(kind) == want
        # fast ICI saturates the 64 MiB overlap ceiling; v2/v3 land
        # mid-range where the launch-amortization formula is live
        assert ddp.choose_bucket_bytes("TPU v5p") == 64 << 20
        assert ddp.choose_bucket_bytes("TPU v3") == 32_800_000
        assert ddp.choose_bucket_bytes("TPU v2") == 24_800_000


def test_grad_reducer_stats_model_vs_plan():
    """stats() must report both the ICI-table policy value (model) and
    what this reducer actually used (plan), so dashboards can spot a
    plan that drifted from policy."""
    entries = [("w", (256, 256), np.float32), ("b", (256,), np.float32)]
    with config.override(ddp_bucket_mb=0.0):
        auto = ddp.GradReducer(entries, axis_name="dp",
                               device_kind="TPU v3")
        st = auto.stats()
        assert st["bucket_bytes_model"] == ddp.choose_bucket_bytes("TPU v3")
        assert st["bucket_bytes_plan"] == st["bucket_bytes_model"]
        # an explicit bucket_bytes is the plan; the model stays on-table
        pinned = ddp.GradReducer(entries, axis_name="dp",
                                 bucket_bytes=4 << 20,
                                 device_kind="TPU v3")
        st = pinned.stats()
        assert st["bucket_bytes_plan"] == 4 << 20
        assert st["bucket_bytes_model"] == ddp.choose_bucket_bytes("TPU v3")
    # MXNET_DDP_BUCKET_MB is an operator decision: it IS the policy,
    # so model and plan agree under the override
    with config.override(ddp_bucket_mb=2.0):
        st = ddp.GradReducer(entries, axis_name="dp",
                             device_kind="TPU v3").stats()
        assert st["bucket_bytes_model"] == 2 << 20
        assert st["bucket_bytes_plan"] == 2 << 20


def test_estimate_overlap_excludes_last_bucket():
    assert ddp.estimate_overlap_ms([100, 100], 1) == 0.0       # no dp
    assert ddp.estimate_overlap_ms([100], 4) == 0.0            # one bucket
    two = ddp.estimate_overlap_ms([100, 100], 4, "TPU v4")
    three = ddp.estimate_overlap_ms([100, 100, 100], 4, "TPU v4")
    assert three == pytest.approx(2 * two)                     # last free


# -------------------------------------------------------- traced reducer

@needs_mesh
def test_grad_reducer_psum_matches_sum():
    from jax.experimental.shard_map import shard_map
    mesh = ddp.process_mesh()
    n = mesh.size
    entries = [("w", (3, 4), np.float32), ("b", (4,), np.float32)]
    red = ddp.GradReducer(entries, axis_name=mesh.axis_names[0],
                          bucket_bytes=8, axis_size=n)
    grads = {"w": np.arange(12, np.float32).reshape(3, 4)
             if False else np.arange(12, dtype=np.float32).reshape(3, 4),
             "b": np.ones((4,), np.float32)}

    def body(g):
        return red.reduce(g)

    fn = shard_map(body, mesh=mesh, in_specs=(P(),), out_specs=P(),
                   check_rep=False)
    out = jax.jit(fn)(grads)
    np.testing.assert_allclose(np.asarray(out["w"]), grads["w"] * n)
    np.testing.assert_allclose(np.asarray(out["b"]), grads["b"] * n)
    assert red.stats()["comm_bytes"] == 64


# ------------------------------------------------- SPMD ddp_bucketed mode

def _mlp_sym():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="ffn1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=24, name="ffn2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=8, name="head")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _spmd_train(dp, tp, ddp_bucketed, bucket_bytes=None, steps=3,
                rule=None, batch=8):
    from mxnet_tpu.parallel import SPMDTrainStep, make_mesh
    sym = _mlp_sym()
    mesh = make_mesh({"dp": dp, "tp": tp}, devices=jax.devices()[:dp * tp])
    arg_shapes, _, _ = sym.infer_shape(data=(batch, 16))
    pshapes = {n: tuple(s)
               for n, s in zip(sym.list_arguments(), arg_shapes)
               if n not in ("data", "softmax_label")}
    st = SPMDTrainStep(sym, mesh, dp_axis="dp", tp_axis="tp", tp_rule=rule,
                       lr=0.1, momentum=0.9, ddp_bucketed=ddp_bucketed,
                       bucket_bytes=bucket_bytes)
    st.compile(pshapes, {}, {"data": (batch, 16)},
               {"softmax_label": (batch,)})
    params, aux, opt = st.init(pshapes, {}, seed=0)
    rng = np.random.RandomState(42)
    key = jax.random.PRNGKey(0)
    for _ in range(steps):
        data = {"data": jax.device_put(
            rng.randn(batch, 16).astype(np.float32),
            NamedSharding(mesh, P("dp")))}
        label = {"softmax_label": jax.device_put(
            rng.randint(0, 8, (batch,)).astype(np.float32),
            NamedSharding(mesh, P("dp")))}
        params, aux, opt, _ = st(params, aux, opt, data, label, key)
    st.quiesce()
    return ({k: np.asarray(jax.device_get(v)) for k, v in params.items()},
            st)


@needs_mesh
def test_spmd_ddp_bucketed_matches_gspmd():
    ref, _ = _spmd_train(8, 1, False)
    got, st = _spmd_train(8, 1, True, bucket_bytes=256)
    stats = st.ddp_stats()
    assert stats["buckets"] >= 2, stats
    for k in ref:
        np.testing.assert_allclose(ref[k], got[k], rtol=2e-4, atol=2e-5,
                                   err_msg=k)


@needs_mesh
def test_spmd_ddp_bucket_size_is_bitwise_neutral():
    tiny, st1 = _spmd_train(8, 1, True, bucket_bytes=256)
    huge, st2 = _spmd_train(8, 1, True, bucket_bytes=64 << 20)
    assert st1.ddp_stats()["buckets"] > st2.ddp_stats()["buckets"] == 1
    for k in tiny:
        np.testing.assert_array_equal(tiny[k], huge[k], err_msg=k)


@needs_mesh
def test_spmd_ddp_composes_with_tp():
    from mxnet_tpu.parallel import megatron_tp_rule
    rule = megatron_tp_rule(column_parallel=["ffn1"],
                            row_parallel=["ffn2"])
    ref, _ = _spmd_train(4, 2, False, rule=rule)
    got, st = _spmd_train(4, 2, True, bucket_bytes=256, rule=rule)
    # tp-sharded params reduce per-param, outside the flat buckets
    assert "ffn1_weight" in st._ddp_tp_names
    for k in ref:
        np.testing.assert_allclose(ref[k], got[k], rtol=5e-4, atol=5e-5,
                                   err_msg=k)


@needs_mesh
def test_mxl507_on_lowered_ddp_step():
    """The lint rule against the REAL lowered step: collective count ==
    bucket count, every one schedulable off the backward's critical
    path with several buckets, zero-overlap flagged with one."""
    from mxnet_tpu.analysis import hlo_passes
    from mxnet_tpu.parallel import SPMDTrainStep, make_mesh

    def lower(bucket_bytes):
        sym = _mlp_sym()
        mesh = make_mesh({"dp": 8}, devices=jax.devices()[:8])
        arg_shapes, _, _ = sym.infer_shape(data=(8, 16))
        pshapes = {n: tuple(s)
                   for n, s in zip(sym.list_arguments(), arg_shapes)
                   if n not in ("data", "softmax_label")}
        st = SPMDTrainStep(sym, mesh, dp_axis="dp", ddp_bucketed=True,
                           bucket_bytes=bucket_bytes)
        jitted = st.compile(pshapes, {}, {"data": (8, 16)},
                            {"softmax_label": (8,)})
        sds = lambda s: jax.ShapeDtypeStruct(s, np.float32)  # noqa: E731
        text = jitted.lower(
            {k: sds(v) for k, v in pshapes.items()}, {},
            {k: sds(v) for k, v in pshapes.items()},
            {"data": sds((8, 16))}, {"softmax_label": sds((8,))},
            jax.ShapeDtypeStruct((2,), np.uint32)).as_text()
        return text, st.ddp_stats()

    text, stats = lower(256)
    rep = hlo_passes.collective_overlap_report(text)
    assert rep["collectives"] == stats["buckets"] >= 2, (rep, stats)
    assert rep["overlappable"] == rep["collectives"], rep
    assert hlo_passes.collective_interleave_pass(
        text, "ddp/step", max_collectives=stats["buckets"]) == []
    # budget violation: pretend the plan allowed fewer collectives
    over = hlo_passes.collective_interleave_pass(
        text, "ddp/step", max_collectives=stats["buckets"] - 1)
    assert len(over) == 1 and over[0].rule == "MXL507"
    # a single fused bucket cannot overlap anything — MXL507 says so
    text1, stats1 = lower(64 << 20)
    diags = hlo_passes.collective_interleave_pass(
        text1, "ddp/step", max_collectives=1)
    assert stats1["buckets"] == 1
    assert len(diags) == 1 and "critical path" in diags[0].message
    assert hlo_passes.metrics_from_text(text)["collective_count"] == \
        stats["buckets"]


def test_mxl507_flags_missing_collectives():
    from mxnet_tpu.analysis import hlo_passes
    text = ('func.func public @main(%arg0: tensor<4xf32>) {\n'
            '  %0 = stablehlo.add %arg0, %arg0 : tensor<4xf32>\n'
            '  return %0 : tensor<4xf32>\n}\n')
    diags = hlo_passes.collective_interleave_pass(text, "ddp/step")
    assert len(diags) == 1 and "not being reduced" in diags[0].message


# -------------------------------------------------- Module.fit DDP path

def _fit_module(kv_type, n_samples=64, batch=32, epochs=2,
                bucket_mb=None, ddp_on=False):
    rng = np.random.RandomState(11)
    X = rng.randn(n_samples, 8).astype(np.float32)
    Y = rng.randint(0, 4, (n_samples,)).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=batch,
                           label_name="softmax_label")
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")
    shapes, _, _ = sym.infer_shape(data=(batch, 8))
    arg_params = {name: mx.nd.array(
        np.random.RandomState(3).uniform(-0.1, 0.1, shp).astype(np.float32))
        for name, shp in zip(sym.list_arguments(), shapes)
        if name not in ("data", "softmax_label")}
    mod = mx.mod.Module(sym)
    over = {"ddp": ddp_on}
    if bucket_mb is not None:
        over["ddp_bucket_mb"] = bucket_mb
    with config.override(**over):
        mod.fit(it, num_epoch=epochs, kvstore=kv_type, optimizer="sgd",
                optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                                  "rescale_grad": 1.0 / batch},
                arg_params=arg_params, initializer=None)
    args, _ = mod.get_params()
    return {k: v.asnumpy() for k, v in args.items()}, mod


@needs_mesh
def test_module_ddp_in_process_matches_kvstore_path():
    """Single process, 8 virtual devices as dp ranks: the DDP fused step
    must match the kvstore-path params (allclose: the batch is split 8
    ways, so partial-sum order differs) and be bitwise-stable across
    bucket sizes."""
    ref, rmod = _fit_module("dist_sync", ddp_on=False)
    assert not rmod._ddp
    tiny, tmod = _fit_module("dist_sync", bucket_mb=0.0003, ddp_on=True)
    huge, hmod = _fit_module("dist_sync", bucket_mb=64.0, ddp_on=True)
    assert tmod._ddp and hmod._ddp
    ts, hs = tmod._ddp_stats(1), hmod._ddp_stats(1)
    assert ts["buckets"] >= 2 and hs["buckets"] == 1, (ts, hs)
    for k in ref:
        np.testing.assert_array_equal(tiny[k], huge[k], err_msg=k)
        np.testing.assert_allclose(ref[k], tiny[k], rtol=2e-5, atol=1e-6,
                                   err_msg=k)


@needs_mesh
def test_module_ddp_indivisible_batch_falls_back():
    """batch % mesh.size != 0 cannot shard evenly: DDP must decline and
    the kvstore path still trains."""
    params, mod = _fit_module("dist_sync", n_samples=42, batch=21,
                              ddp_on=True)
    assert not mod._ddp
    assert all(np.isfinite(v).all() for v in params.values())


@needs_mesh
def test_module_ddp_refuses_device_metric():
    """Per-rank device metric accumulation under check_rep=False would be
    silently wrong — the fused step must refuse it loudly."""
    _, mod = _fit_module("dist_sync", ddp_on=True)
    assert mod._fused is not None
    with pytest.raises(ValueError, match="MXNET_DDP"):
        mod._fused.attach_metric(lambda outs, label: outs[0].sum())


# ------------------------------------------------------------- telemetry

def test_publish_window_carries_ddp_stats():
    from mxnet_tpu import telemetry
    rec = telemetry.publish_window(
        steps=4, window_s=0.1, examples=128, global_step=40,
        ddp={"buckets": 3, "comm_bytes": 4096, "overlap_ms": 0.25})
    assert rec["ddp"] == {"buckets": 3, "comm_bytes": 4096,
                          "overlap_ms": 0.25}
    snap = telemetry.snapshot()
    assert snap["ddp/buckets"]["samples"][0]["value"] == 3
    assert snap["ddp/overlap_ms"]["samples"][0]["value"] == 0.25
    assert snap["ddp/comm_bytes"]["samples"][0]["value"] >= 4096


def test_publish_window_gauges_bucket_bytes_model():
    from mxnet_tpu import telemetry
    entries = [("w", (64, 64), np.float32)]
    with config.override(ddp_bucket_mb=0.0):
        st = ddp.GradReducer(entries, axis_name="dp",
                             device_kind="TPU v3").stats()
    rec = telemetry.publish_window(
        steps=4, window_s=0.1, examples=128, global_step=41, ddp=st)
    assert rec["ddp"]["bucket_bytes_model"] == \
        ddp.choose_bucket_bytes("TPU v3")
    snap = telemetry.snapshot()
    assert snap["ddp/bucket_bytes_model"]["samples"][-1]["value"] == \
        st["bucket_bytes_model"]


# ------------------------------------------------------------ fleet runs

def _run_fleet(n, tmp_path, extra_args=(), extra_env=None):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("MXNET_FAULT_INJECT", None)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, LAUNCH, "--ddp", "-n", str(n)]
        + list(extra_args) + [sys.executable, WORKER],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)


@pytest.mark.parametrize("n", [2, 4])
def test_ddp_fleet_bitwise_parity(n, tmp_path):
    """N real processes (tools/launch.py --ddp): bucketed vs unbucketed
    bitwise parity incl. optimizer state, plus cross-rank equality."""
    dump = str(tmp_path / "ddp_params.npz")
    r = _run_fleet(n, tmp_path, extra_env={"DDP_TRAIN_DUMP": dump})
    assert r.returncode == 0, r.stdout[-6000:] + r.stderr[-3000:]
    for rank in range(n):
        assert ("rank %d/%d: ddp bucketed training bitwise-stable"
                % (rank, n)) in r.stdout, r.stdout[-6000:]
    assert os.path.exists(dump)


def test_ddp_fleet_matches_kvstore_fleet(tmp_path):
    """Same 2-process fleet through the kvstore dist_sync path: the DDP
    params must agree to float tolerance (the per-rank partial-gradient
    sums associate differently, so bitwise is not the contract here)."""
    ddp_dump = str(tmp_path / "ddp.npz")
    r = _run_fleet(2, tmp_path, extra_env={"DDP_TRAIN_DUMP": ddp_dump})
    assert r.returncode == 0, r.stdout[-6000:] + r.stderr[-3000:]
    kv_dump = str(tmp_path / "kv.npz")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["DIST_TRAIN_DUMP"] = kv_dump
    r = subprocess.run(
        [sys.executable, LAUNCH, "-n", "2", sys.executable,
         os.path.join(ROOT, "tests", "dist_train_worker.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    assert r.returncode == 0, r.stdout[-6000:] + r.stderr[-3000:]
    with np.load(ddp_dump) as a, np.load(kv_dump) as b:
        assert sorted(a.files) == sorted(b.files)
        for k in a.files:
            np.testing.assert_allclose(a[k], b[k], rtol=2e-5, atol=1e-6,
                                       err_msg=k)


@pytest.mark.slow
def test_ddp_elastic_kill_resume(tmp_path):
    """MXNET_FAULT_INJECT kills rank 0 mid-DDP-training; the supervised
    restart resumes from checkpoint and the final params match an
    uninterrupted DDP run bitwise (same as the kvstore-path elastic test
    in test_fault.py, with the bucketed all-reduce on)."""
    resume_worker = os.path.join(ROOT, "tests", "fault_resume_worker.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("MXNET_FAULT_INJECT", None)

    def run(dump, extra):
        e = dict(env)
        e["FAULT_TRAIN_DUMP"] = dump
        return subprocess.run(
            [sys.executable, LAUNCH, "--ddp", "-n", "2",
             "--restart-backoff", "0.2"] + extra
            + [sys.executable, resume_worker],
            capture_output=True, text=True, timeout=600, env=e, cwd=ROOT)

    base = str(tmp_path / "base.npz")
    r = run(base, ["--max-restarts", "0"])
    assert r.returncode == 0, r.stdout[-6000:] + r.stderr[-3000:]
    killed = str(tmp_path / "killed.npz")
    r = run(killed, ["--max-restarts", "3",
                     "--checkpoint-dir", str(tmp_path / "ckpt"),
                     "--env", "MXNET_FAULT_INJECT=kill@step=3:rank=0"])
    assert r.returncode == 0, r.stdout[-6000:] + r.stderr[-3000:]
    assert "launch.py: restarting the group" in r.stderr, r.stderr[-3000:]
    assert "resumed from checkpoint step" in r.stdout, r.stdout[-6000:]
    with np.load(base) as b, np.load(killed) as k:
        assert sorted(b.files) == sorted(k.files)
        for name in b.files:
            np.testing.assert_array_equal(
                b[name], k[name],
                err_msg="param %r diverged after kill+resume" % name)
