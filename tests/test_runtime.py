"""Native C++ runtime tests (model: reference
tests/cpp/engine/threaded_engine_test.cc semantics, storage_test.cc)."""
import os
import random
import threading
import time

import numpy as np
import pytest

from mxnet_tpu import recordio, runtime

pytestmark = pytest.mark.skipif(not runtime.available(),
                                reason="native runtime not built")


def test_engine_write_serialization():
    eng = runtime.NativeEngine(4)
    v = eng.new_variable()
    log = []
    lock = threading.Lock()

    def op(name, delay=0.0):
        def fn():
            if delay:
                time.sleep(delay)
            with lock:
                log.append(name)
        return fn

    eng.push(op("w1", 0.05), mutable_vars=[v])
    eng.push(op("w2"), mutable_vars=[v])
    eng.push(op("r1"), const_vars=[v])
    eng.wait_for_var(v)
    assert log.index("w1") < log.index("w2") < log.index("r1")
    eng.close()


def test_engine_concurrent_reads_block_write():
    eng = runtime.NativeEngine(4)
    v = eng.new_variable()
    log = []
    lock = threading.Lock()

    def op(name, delay=0.0):
        def fn():
            if delay:
                time.sleep(delay)
            with lock:
                log.append(name)
        return fn

    eng.push(op("rA", 0.05), const_vars=[v])
    eng.push(op("rB", 0.05), const_vars=[v])
    eng.push(op("wX"), mutable_vars=[v])
    eng.wait_all()
    assert log.index("wX") == 2 and set(log[:2]) == {"rA", "rB"}
    eng.close()


def test_engine_stress_random_deps():
    """Port of threaded_engine_test.cc:114-320 semantics: random read/write
    workloads stay serializable per variable."""
    eng = runtime.NativeEngine(8)
    vars_ = [eng.new_variable() for _ in range(16)]
    counters = {v: 0 for v in vars_}
    expected = {v: 0 for v in vars_}

    def inc(var):
        def fn():
            # unsynchronized increment is safe iff writes on var serialize
            counters[var] += 1
        return fn

    rng = random.Random(0)
    for _ in range(500):
        v = rng.choice(vars_)
        expected[v] += 1
        eng.push(inc(v), mutable_vars=[v])
    eng.wait_all()
    assert counters == expected
    assert eng.pending() == 0
    eng.close()


def test_engine_cross_var_dependency():
    eng = runtime.NativeEngine(4)
    a, b = eng.new_variable(), eng.new_variable()
    state = {}

    def writer():
        time.sleep(0.05)
        state["x"] = 42

    def reader():
        state["seen"] = state.get("x")

    eng.push(writer, mutable_vars=[a])
    eng.push(reader, const_vars=[a], mutable_vars=[b])
    eng.wait_for_var(b)
    assert state["seen"] == 42
    eng.close()


def test_storage_pool_reuse():
    pool = runtime.NativeStoragePool()
    p1 = pool.alloc(1000)
    pool.free(p1)
    assert pool.pooled_bytes == 1024
    p2 = pool.alloc(900)  # same 1024 size-class -> pooled block reused
    assert p1 == p2
    assert pool.pooled_bytes == 0 and pool.used_bytes == 1024
    pool.direct_free(p2)
    assert pool.used_bytes == 0
    pool.close()


def test_storage_pool_reserve_limit():
    pool = runtime.NativeStoragePool(reserve_limit=2048)
    ptrs = [pool.alloc(1024) for _ in range(4)]
    for p in ptrs:
        pool.free(p)
    assert pool.pooled_bytes <= 2048  # excess released to the OS
    pool.release_all()
    assert pool.pooled_bytes == 0
    pool.close()


def test_native_record_reader_parity(tmp_path):
    rec = str(tmp_path / "x.rec")
    w = recordio.MXRecordIO(rec, "w")
    payloads = [os.urandom(np.random.randint(1, 300)) for _ in range(25)]
    for p in payloads:
        w.write(p)
    w.close()
    r = runtime.NativeRecordReader(rec)
    assert len(r) == 25
    for i in range(25):
        assert r[i] == payloads[i]
    r.close()


def test_record_file_dataset_uses_native(tmp_path):
    from mxnet_tpu.gluon import data as gdata
    rec = str(tmp_path / "d.rec")
    idx = str(tmp_path / "d.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(6):
        w.write_idx(i, b"payload-%d" % i)
    w.close()
    ds = gdata.RecordFileDataset(rec)
    assert ds._native is not None
    assert len(ds) == 6
    assert ds[4] == b"payload-4"


def test_record_file_dataset_shuffled_idx_falls_back(tmp_path):
    """Review regression: a shuffled .idx must not use the native
    file-order scanner."""
    from mxnet_tpu.gluon import data as gdata
    rec = str(tmp_path / "s.rec")
    idx = str(tmp_path / "s.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(5):
        w.write_idx(i, b"item-%d" % i)
    w.close()
    # shuffle the idx lines
    lines = open(idx).read().strip().splitlines()
    lines = [lines[2], lines[0], lines[4], lines[1], lines[3]]
    open(idx, "w").write("\n".join(lines) + "\n")
    ds = gdata.RecordFileDataset(rec)
    assert ds._native is None  # fell back to the idx-driven reader
    assert ds[0] == b"item-2"
    assert ds[-1] == b"item-3"


def test_engine_many_pushes_keepalive_bounded():
    eng = runtime.NativeEngine(4)
    v = eng.new_variable()
    for _ in range(200):
        eng.push(lambda: None, mutable_vars=[v])
    eng.wait_all()
    assert len(eng._keepalive) == 0  # closures retired after the barrier
    eng.close()


def test_storage_double_free_is_noop():
    pool = runtime.NativeStoragePool()
    p = pool.alloc(100)
    pool.free(p)
    pooled = pool.pooled_bytes
    pool.free(p)  # double free: detected, no-op
    assert pool.pooled_bytes == pooled
    pool.direct_free(p)  # already pooled: no-op, no crash
    pool.close()


def test_engine_duplicate_vars_no_deadlock():
    """A var listed twice (in mutable, or in both const and mutable) must
    not deadlock the var queue (advisor finding: the second queue entry
    could never be granted)."""
    eng = runtime.NativeEngine(2)
    v = eng.new_variable()
    w = eng.new_variable()
    ran = []
    eng.push(lambda: ran.append("dup-mut"), mutable_vars=[v, v])
    eng.push(lambda: ran.append("const+mut"), const_vars=[v, w],
             mutable_vars=[v])
    eng.push(lambda: ran.append("dup-const"), const_vars=[w, w])
    done = threading.Event()

    def waiter():
        eng.wait_all()
        done.set()

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    assert done.wait(timeout=10.0), "engine deadlocked on duplicate vars"
    assert sorted(ran) == ["const+mut", "dup-const", "dup-mut"]
    eng.close()


def test_recordio_multipart_write_roundtrip(tmp_path):
    """Payloads over the 29-bit length field go out as multi-part records
    (cflag 1/2/3) and read back whole. Uses a tiny patched part size so the
    test doesn't need a 512MB payload."""
    path = str(tmp_path / "multi.rec")
    w = recordio.MXRecordIO(path, "w")
    orig = recordio.MXRecordIO._MAX_PART
    recordio.MXRecordIO._MAX_PART = 16
    try:
        payload = bytes(range(256)) * 3  # 768 bytes -> 48 parts
        w.write(b"small")
        w.write(payload)
        w.write(b"after")
    finally:
        recordio.MXRecordIO._MAX_PART = orig
        w.close()
    r = recordio.MXRecordIO(path, "r")
    assert r.read() == b"small"
    assert r.read() == payload
    assert r.read() == b"after"
    assert r.read() is None
    r.close()
