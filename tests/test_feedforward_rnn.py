"""Legacy FeedForward trainer + mx.rnn symbolic package (reference
python/mxnet/model.py:536, python/mxnet/rnn/) — the v0.x user surface."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _net():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_feedforward_fit_predict_score_save_load(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.randn(64, 8).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32)
    model = mx.model.FeedForward(
        _net(), num_epoch=20, learning_rate=0.5, numpy_batch_size=16)
    model.fit(X, Y)  # plain numpy in, like the v0.x examples
    acc = model.score(mx.io.NDArrayIter(X, Y, batch_size=16,
                                        label_name="softmax_label"))
    assert acc > 0.8, acc
    probs = model.predict(X)
    assert probs.shape == (64, 2)
    prefix = str(tmp_path / "ff")
    model.save(prefix)
    loaded = mx.model.FeedForward.load(prefix, 20)
    probs2 = loaded.predict(X)
    np.testing.assert_allclose(probs, probs2, rtol=1e-5)


def test_feedforward_create():
    rng = np.random.RandomState(1)
    X = rng.randn(32, 8).astype(np.float32)
    Y = (X[:, 1] > 0).astype(np.float32)
    model = mx.model.FeedForward.create(
        _net(), X, Y, num_epoch=2, learning_rate=0.1, numpy_batch_size=16)
    assert model.arg_params is not None


@pytest.mark.parametrize("cell_cls,n_states", [
    (lambda: mx.rnn.RNNCell(8), 1),
    (lambda: mx.rnn.LSTMCell(8), 2),
    (lambda: mx.rnn.GRUCell(8), 1),
])
def test_rnn_cell_unroll_shapes(cell_cls, n_states):
    cell = cell_cls()
    data = mx.sym.Variable("data")
    outputs, states = cell.unroll(5, data, layout="NTC",
                                  merge_outputs=True)
    assert len(states) == n_states
    kw = {"data": (4, 5, 6)}
    for name in outputs.list_arguments():
        if "begin_state" in name:
            kw[name] = (4, 8)
    _, out_shapes, _ = outputs.infer_shape(**kw)
    assert out_shapes[0] == (4, 5, 8)


def test_rnn_sequential_bidirectional():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(8, prefix="l0_"))
    stack.add(mx.rnn.DropoutCell(0.0))
    stack.add(mx.rnn.GRUCell(8, prefix="l1_"))
    data = mx.sym.Variable("data")
    outputs, states = stack.unroll(4, data, merge_outputs=True)
    assert len(states) == 3  # 2 (lstm) + 0 (dropout) + 1 (gru)

    bi = mx.rnn.BidirectionalCell(mx.rnn.LSTMCell(5, prefix="f_"),
                                  mx.rnn.LSTMCell(5, prefix="b_"))
    outs, st = bi.unroll(4, mx.sym.Variable("data"), merge_outputs=True)
    kw = {"data": (2, 4, 3)}
    for name in outs.list_arguments():
        if "begin_state" in name:
            kw[name] = (2, 5)
    _, out_shapes, _ = outs.infer_shape(**kw)
    assert out_shapes[0] == (2, 4, 10)  # fwd/bwd concat


def test_rnn_lstm_trains_via_module():
    """The canonical v0.x pattern: unrolled LSTM -> Module.fit (e.g.
    example/rnn/lstm_bucketing.py shape)."""
    T, B, C, H = 6, 8, 4, 16
    cell = mx.rnn.LSTMCell(H, prefix="lstm_")
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    outputs, _ = cell.unroll(T, data, layout="NTC", merge_outputs=True)
    pred = mx.sym.Reshape(outputs, shape=(-1, H))
    pred = mx.sym.FullyConnected(pred, num_hidden=3, name="pred")
    net = mx.sym.SoftmaxOutput(pred, mx.sym.Reshape(label, shape=(-1,)),
                               name="softmax")
    rng = np.random.RandomState(0)
    X = rng.randn(32, T, C).astype(np.float32)
    Y = rng.randint(0, 3, (32, T)).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=B, label_name="softmax_label")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=2, optimizer="adam", eval_metric=None,
            optimizer_params={"learning_rate": 0.01})


def test_fused_rnn_cell_unroll():
    cell = mx.rnn.FusedRNNCell(8, num_layers=2, mode="lstm",
                               get_next_state=True)
    data = mx.sym.Variable("data")
    output, states = cell.unroll(5, data, layout="NTC")
    assert len(states) == 2
    kw = {"data": (4, 5, 6)}
    for name in output.list_arguments():
        if "begin_state" in name:
            kw[name] = (2, 4, 8)
    arg_shapes, out_shapes, _ = output.infer_shape(**kw)
    assert out_shapes[0] == (4, 5, 8)
    # packed parameter vector got a concrete inferred shape
    d = dict(zip(output.list_arguments(), arg_shapes))
    assert np.prod(d["lstm_parameters"]) > 0


def test_bucket_sentence_iter():
    rng = np.random.RandomState(0)
    sentences = [list(rng.randint(1, 50, rng.randint(2, 12)))
                 for _ in range(200)]
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=8,
                                   buckets=[5, 10, 15], invalid_label=-1)
    seen_keys = set()
    n = 0
    for batch in it:
        assert batch.data[0].shape == (8, batch.bucket_key)
        assert batch.label[0].shape == (8, batch.bucket_key)
        # label is the next-token shift of data
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        np.testing.assert_array_equal(l[:, :-1], d[:, 1:])
        seen_keys.add(batch.bucket_key)
        n += 1
    assert n > 0 and len(seen_keys) >= 2
    it.reset()
    assert sum(1 for _ in it) == n


def test_bucket_iter_empty_bucket_ok():
    """A bucket with zero sentences must not crash construction (round-3
    review finding)."""
    it = mx.rnn.BucketSentenceIter([[1, 2, 3]] * 20, batch_size=8,
                                   buckets=[5, 10])
    n = sum(1 for _ in it)
    assert n > 0


def test_lstm_forget_bias_applied():
    cell = mx.rnn.LSTMCell(4, forget_bias=2.0, prefix="fb_")
    data = mx.sym.Variable("data")
    outputs, _ = cell.unroll(2, data, merge_outputs=True)
    from mxnet_tpu.executor import simple_bind
    import mxnet_tpu.initializer as init
    ex = simple_bind(outputs, mx.cpu(), data=(2, 2, 3))
    mod_init = init.Uniform(0.01)
    for name in ex.arg_dict:
        if name != "data":
            from mxnet_tpu.initializer import InitDesc
            # replicate Module.init_params attr routing
            attrs = {}
            for node in outputs._topo():
                if node.is_variable and node.name == name:
                    attrs = dict(node.attrs)
            mod_init(InitDesc(name, attrs), ex.arg_dict[name])
    b = ex.arg_dict["fb_h2h_bias"].asnumpy()
    np.testing.assert_allclose(b[4:8], 2.0)  # forget gate rows
    assert np.abs(b[:4]).max() < 0.1


def test_feedforward_predict_return_data():
    rng = np.random.RandomState(0)
    X = rng.randn(32, 8).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32)
    model = mx.model.FeedForward(_net(), num_epoch=1, learning_rate=0.1,
                                 numpy_batch_size=16)
    model.fit(X, Y)
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    outs, datas, labels = model.predict(it, return_data=True)
    assert outs.shape == (32, 2) and datas.shape == (32, 8)
    assert labels.shape == (32,)


def test_feedforward_predict_return_data_with_pad():
    """Outputs/data/labels must stay row-aligned when the last batch pads
    (reference model.py:677 trims all three by pad)."""
    rng = np.random.RandomState(1)
    X = rng.randn(70, 8).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32)
    model = mx.model.FeedForward(_net(), num_epoch=1, learning_rate=0.1,
                                 numpy_batch_size=16)
    model.fit(X, Y)
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    outs, datas, labels = model.predict(it, return_data=True)
    assert outs.shape[0] == datas.shape[0] == labels.shape[0] == 70
    np.testing.assert_allclose(datas, X, rtol=1e-6)
    np.testing.assert_allclose(labels, Y, rtol=1e-6)


def test_feedforward_epoch_size_streaming():
    """epoch_size bounds an epoch for streaming iterators (model.py:536)."""
    rng = np.random.RandomState(2)
    X = rng.randn(64, 8).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=16, label_name="softmax_label")
    seen = []

    def batch_cb(param):
        seen.append((param.epoch, param.nbatch))

    model = mx.model.FeedForward(_net(), num_epoch=3, epoch_size=2,
                                 learning_rate=0.1)
    model.fit(it, batch_end_callback=batch_cb)
    # 3 epochs x 2 batches each, not 3 x 4
    per_epoch = {}
    for ep, _ in seen:
        per_epoch[ep] = per_epoch.get(ep, 0) + 1
    assert per_epoch == {0: 2, 1: 2, 2: 2}, per_epoch


def test_bucket_iter_reports_discards():
    sents = [[1, 2, 3]] * 8 + [[1] * 50] * 3  # 3 sentences exceed max bucket
    it = mx.rnn.BucketSentenceIter(sents, batch_size=4, buckets=[5, 10])
    assert it.ndiscard == 3


def test_feedforward_score_numpy():
    rng = np.random.RandomState(4)
    X = rng.randn(48, 8).astype(np.float32)
    Y = (X[:, 0] > 0).astype(np.float32)
    model = mx.model.FeedForward(_net(), num_epoch=1, learning_rate=0.1)
    model.fit(X, Y)
    acc = model.score(X)  # scored against zero labels, reference semantics
    assert 0.0 <= acc <= 1.0


def test_fused_unroll_default_placeholders():
    out, _ = mx.rnn.FusedRNNCell(8, prefix="lstm_").unroll(3)
    args = out.list_arguments()
    assert "t0_data" in args and "t2_data" in args, args
    l = mx.rnn.LSTMCell(4, prefix="l_")
    r = mx.rnn.LSTMCell(4, prefix="r_")
    outs, _ = mx.rnn.BidirectionalCell(l, r).unroll(3)
    args = outs[0].list_arguments()
    assert "t0_data" in args, args


def test_fused_cell_default_init_and_weight_packing():
    """Module.init_params on a FusedRNNCell model works with ANY global
    initializer (the packed vector carries a FusedRNN __init__ attr,
    reference rnn_cell.py:578-580 / initializer.py:689), the forget-gate
    bias initializes to forget_bias, and unpack/pack round-trips."""
    import numpy as np
    T, H, V = 5, 8, 12
    data = mx.sym.Variable("data")
    emb = mx.sym.Embedding(data, input_dim=V, output_dim=H, name="emb")
    emb_t = mx.sym.swapaxes(emb, dim1=0, dim2=1)
    cell = mx.rnn.FusedRNNCell(H, num_layers=2, mode="lstm", prefix="l_",
                               forget_bias=2.0)
    out, _ = cell.unroll(T, emb_t, layout="TNC", merge_outputs=True)
    logits = mx.sym.FullyConnected(
        mx.sym.Reshape(mx.sym.swapaxes(out, dim1=0, dim2=1),
                       shape=(-1, H)), num_hidden=V, name="fc")
    loss = mx.sym.SoftmaxOutput(
        logits, mx.sym.Reshape(mx.sym.Variable("softmax_label"),
                               shape=(-1,)), name="softmax")
    mod = mx.mod.Module(loss, context=mx.cpu())
    mod.bind([mx.io.DataDesc("data", (4, T))],
             [mx.io.DataDesc("softmax_label", (4, T))])
    # a PLAIN global initializer: routed through the FusedRNN attr
    mod.init_params(mx.initializer.Xavier())
    params = mod.get_params()[0]

    unpacked = cell.unpack_weights({"l_parameters": params["l_parameters"]})
    # naming contract: direction 'l', per-layer per-gate i2h/h2h pieces
    assert "l_l0_i2h_i_weight" in unpacked
    assert "l_l1_h2h_o_bias" in unpacked
    assert unpacked["l_l0_i2h_c_weight"].shape == (H, H)   # layer0: in=H
    assert unpacked["l_l1_i2h_c_weight"].shape == (H, H)
    np.testing.assert_allclose(unpacked["l_l0_i2h_f_bias"].asnumpy(), 2.0)
    np.testing.assert_allclose(unpacked["l_l1_i2h_f_bias"].asnumpy(), 2.0)
    # Xavier actually ran on the weight pieces (nonzero, bounded)
    w = unpacked["l_l0_i2h_i_weight"].asnumpy()
    assert np.abs(w).max() > 0 and np.abs(w).max() < 2.0

    repacked = cell.pack_weights(unpacked)
    np.testing.assert_allclose(repacked["l_parameters"].asnumpy(),
                               params["l_parameters"].asnumpy(), rtol=1e-6)
