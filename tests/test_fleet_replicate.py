"""Journal replication + storage fault model (mxnet_tpu.fleet.replicate
+ router degraded mode) — chip-free.

The acceptance properties: (1) a standby's JournalReplicator streams
the primary's journal over the router's own HTTP front end into a
local directory that ``Router.from_journal`` promotes from — snapshot
bootstrap, offset-resumed fetches, receiver-side CRC re-verification
(an in-transit bit flip is truncated and re-fetched, never applied),
seq-gap auto re-sync, and an epoch fence so a demoted primary can
never feed a promoted standby; (2) the storage fault model
(``enospc``/``torn_write``/``slow_fsync`` at ``@journal`` points)
drives the router into degraded mode where control-plane mutations
503 with Retry-After while predict/generate traffic keeps flowing,
and a recovered disk exits degraded mode with NO restart.
"""
import glob
import json
import os
import time
import urllib.error
import urllib.request

import pytest

from mxnet_tpu import telemetry
from mxnet_tpu.fleet import (FleetJournal, JournalDegraded,
                             JournalReplicator, ReplicaRegistry, Router,
                             StaleSourceError, fencing, route_http)
from mxnet_tpu.fleet.journal import replay
from mxnet_tpu.fleet.replicate import read_journal_file
from mxnet_tpu.parallel import faultinject


@pytest.fixture(autouse=True)
def _clean_slate(monkeypatch):
    fencing.reset()
    monkeypatch.delenv("MXNET_FAULT_INJECT", raising=False)
    faultinject.reset()
    yield
    fencing.reset()
    faultinject.reset()


def _register(registry, rid, *, model="m", version="0", mode="predict",
              ready=True, load=None, spec=None):
    return registry.register({
        "id": rid, "url": "http://%s.invalid" % rid, "model": model,
        "version": version, "mode": mode, "ready": ready,
        "load": load or {}, "spec": spec})


def _primary(tmp_path, name="pj", **jkw):
    """A journaled router serving its journal over a real HTTP front."""
    jkw.setdefault("sync_every", 1)
    router = Router(registry=ReplicaRegistry(heartbeat_timeout_s=60.0))
    router.attach_journal(FleetJournal(str(tmp_path / name), **jkw))
    front = route_http(router, "127.0.0.1", 0)
    router.announce(front.address)
    return router, front


def _post(url, payload, timeout=30.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read().decode() or "{}"), \
                dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode() or "{}"), \
            dict(e.headers)


def _get_json(url, timeout=30.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode() or "{}")


def _gauge_value(prom_text, name):
    for line in prom_text.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            return float(line.rsplit(None, 1)[-1])
    return None


# ---------------------------------------------------------------------------
# primary side: manifest + bounded reads
# ---------------------------------------------------------------------------

def test_manifest_and_bounded_reads(tmp_path):
    router, front = _primary(tmp_path)
    try:
        _register(router.registry, "a")
        man = router.journal_manifest()
        assert man["epoch"] == 1
        assert man["seq"] == router.journal.seq >= 2
        assert man["degraded"] is False
        assert [s["name"] for s in man["segments"]] == ["wal-00000001.log"]
        size = man["segments"][0]["size"]
        blob = router.journal_read("wal-00000001.log")
        assert len(blob) == size
        # offset-resumed read returns only the tail
        tail = router.journal_read("wal-00000001.log", offset=size - 4)
        assert tail == blob[-4:]
        # name validation: traversal / non-journal files are KeyError,
        # never opened
        for bad in ("../secret", "lease.json", "/etc/passwd",
                    "wal-1.log", "snap-x.json", ""):
            with pytest.raises(KeyError):
                read_journal_file(router.journal.dir, bad)
        with pytest.raises(KeyError):
            router.journal_read("wal-00000099.log")   # absent file
    finally:
        front.stop()


# ---------------------------------------------------------------------------
# replicator: bootstrap, incremental follow, restart resume, promotion
# ---------------------------------------------------------------------------

def test_replicator_bootstraps_then_follows_incrementally(tmp_path):
    router, front = _primary(tmp_path)
    rdir = str(tmp_path / "replica")
    try:
        for rid in ("a", "b", "c"):
            _register(router.registry, rid)
        router.set_split("m", {"0": 1.0})
        repl = JournalReplicator(front.address, rdir, poll_s=0.05)
        n = repl.poll()
        assert n == router.journal.seq     # epoch + 3 registers + split
        assert repl.state.to_dict() == replay(router.journal.dir)[0].to_dict()
        assert repl.max_epoch == 1         # epoch learned from the wire
        assert repl.stats()["lag_records"] == 0
        assert repl.next_delay_s() == 0.0  # catch-up burst after progress

        # incremental: only the new records cross the wire
        _register(router.registry, "d")
        router.set_split("m", {"0": 0.5, "1": 0.5})
        assert repl.poll() == 2
        assert repl.state.splits["m"] == {"0": 0.5, "1": 0.5}
        assert repl.poll() == 0            # nothing new
        assert repl.next_delay_s() == pytest.approx(0.05)  # idle pace
    finally:
        front.stop()


def test_replicator_resumes_offsets_across_restart_and_rotation(tmp_path):
    # tiny segments force rotation mid-stream: the replica mirrors the
    # multi-segment layout and a restarted replicator re-verifies its
    # local files instead of re-fetching history
    router, front = _primary(tmp_path, segment_bytes=256)
    rdir = str(tmp_path / "replica")
    try:
        for i in range(12):
            router.journal.append("noop", {"pad": "x" * 40, "i": i})
        repl = JournalReplicator(front.address, rdir, poll_s=0.05)
        repl.poll()
        assert len(glob.glob(os.path.join(rdir, "wal-*.log"))) > 1
        assert repl.state.applied_seq == router.journal.seq

        repl2 = JournalReplicator(front.address, rdir, poll_s=0.05)
        # local re-verification alone restores the state (no network)
        assert repl2.state.applied_seq == repl.state.applied_seq
        assert repl2.poll() == 0
        assert repl2._offsets == repl._offsets

        # the replica directory IS the promotion path
        front.stop()
        promoted = Router.from_journal(
            rdir, registry=ReplicaRegistry(heartbeat_timeout_s=60.0))
        assert promoted.epoch == router.epoch + 1
        promoted.journal.close()
    finally:
        front.stop()


def test_snapshot_bootstrap_skips_compacted_history(tmp_path):
    router, front = _primary(tmp_path)
    rdir = str(tmp_path / "replica")
    try:
        for rid in ("a", "b"):
            _register(router.registry, rid)
        router.set_split("m", {"0": 1.0})
        router.journal.compact(router.export_state())
        _register(router.registry, "late")
        repl = JournalReplicator(front.address, rdir, poll_s=0.05)
        repl.poll()
        assert repl.state.applied_seq == router.journal.seq
        assert set(repl.state.replicas) == {"a", "b", "late"}
        assert glob.glob(os.path.join(rdir, "snap-*.json"))
        # post-compaction segments only: the pre-snapshot history never
        # crossed the wire
        local_segs = sorted(os.path.basename(p) for p in
                            glob.glob(os.path.join(rdir, "wal-*.log")))
        remote_segs = sorted(s["name"] for s in
                             router.journal_manifest()["segments"])
        assert local_segs == remote_segs
    finally:
        front.stop()


def test_seq_gap_on_cold_replica_triggers_resync_not_partial_state(
        tmp_path, monkeypatch):
    # a cold replica whose snapshot fetch fails must NOT start applying
    # mid-history segments (silent prefix loss): the seq gap forces a
    # re-sync, and the second pass adopts the snapshot
    router, front = _primary(tmp_path)
    rdir = str(tmp_path / "replica")
    try:
        for rid in ("a", "b"):
            _register(router.registry, rid)
        router.journal.compact(router.export_state())
        _register(router.registry, "late")
        repl = JournalReplicator(front.address, rdir, poll_s=0.05)
        orig = repl._adopt_snapshot
        failed = []

        def flaky(snap):
            if not failed:
                failed.append(1)
                raise OSError("half-written on the source")
            return orig(snap)

        monkeypatch.setattr(repl, "_adopt_snapshot", flaky)
        repl.poll()
        assert failed                       # the failure path ran
        assert repl.state.applied_seq == router.journal.seq
        assert set(repl.state.replicas) == {"a", "b", "late"}
        assert repl.state.to_dict() == replay(rdir)[0].to_dict()
    finally:
        front.stop()


def test_history_regression_wipes_and_resyncs(tmp_path):
    # the source restarted with a FRESH journal (seq behind the
    # replica): record-by-record patching cannot reconverge, so the
    # replica wipes itself and re-bootstraps
    router, front = _primary(tmp_path)
    rdir = str(tmp_path / "replica")
    try:
        for i in range(6):
            router.journal.append("noop", {"i": i})
        repl = JournalReplicator(front.address, rdir, poll_s=0.05)
        repl.poll()
        assert repl.state.applied_seq == router.journal.seq > 3

        fresh = FleetJournal(str(tmp_path / "fresh"), sync_every=1)
        router.journal.close()
        router.attach_journal(fresh)
        router.announce(front.address)      # re-journal the epoch claim
        repl.poll()
        assert repl.state.applied_seq == fresh.seq < 6
        assert repl.state.to_dict() == replay(rdir)[0].to_dict()
    finally:
        front.stop()


# ---------------------------------------------------------------------------
# receiver-side CRC: an in-transit bit flip is refetched, never applied
# ---------------------------------------------------------------------------

def test_bit_flipped_segment_is_refetched_not_applied(tmp_path,
                                                      monkeypatch):
    router, front = _primary(tmp_path)
    rdir = str(tmp_path / "replica")
    try:
        for rid in ("a", "b", "c"):
            _register(router.registry, rid)
        repl = JournalReplicator(front.address, rdir, poll_s=0.05)
        orig = repl._fetch_file
        flipped = []

        def corrupt_once(kind, name, offset=0):
            data = orig(kind, name, offset)
            if kind == "segment" and not flipped and len(data) > 20:
                flipped.append(name)
                buf = bytearray(data)
                buf[len(buf) // 2] ^= 0xFF
                data = bytes(buf)
            return data

        monkeypatch.setattr(repl, "_fetch_file", corrupt_once)
        repl.poll()
        assert flipped
        # the flip landed mid-stream: everything from the corrupt record
        # on was truncated off, nothing garbage was applied
        truth, _ = replay(router.journal.dir)
        assert repl.state.applied_seq < truth.applied_seq
        seg = os.path.join(rdir, flipped[0])
        assert os.path.getsize(seg) < \
            router.journal_manifest()["segments"][0]["size"]
        for rec in repl.state.replicas.values():
            assert rec["id"] in ("a", "b", "c")

        # next poll re-fetches from the verified offset and converges
        repl.poll()
        assert repl.state.to_dict() == truth.to_dict()
        assert repl.state.applied_seq == router.journal.seq
    finally:
        front.stop()


# ---------------------------------------------------------------------------
# epoch fence: a demoted primary can never feed a promoted standby
# ---------------------------------------------------------------------------

def test_stale_primary_is_refused_by_promoted_standby(tmp_path):
    router, front = _primary(tmp_path)    # serves epoch 1
    rdir = str(tmp_path / "replica")
    try:
        _register(router.registry, "a")
        repl = JournalReplicator(front.address, rdir, poll_s=0.05)
        # the standby was promoted meanwhile: it has observed epoch 5
        repl.max_epoch = 5
        assert repl.poll() == 0
        assert repl.state.applied_seq == 0          # nothing applied
        assert repl.conn_failures == 0              # not a conn failure
        assert repl.max_epoch == 5                  # never lowered
        assert not glob.glob(os.path.join(rdir, "wal-*"))
        with pytest.raises(StaleSourceError):
            repl._check_epoch(4)
        # a stale source never refreshes the liveness clock either: the
        # standby's own promotion timer keeps running
        time.sleep(0.05)
        assert repl.age_s() > 0.04
    finally:
        front.stop()


# ---------------------------------------------------------------------------
# storage fault model: enospc -> degraded control plane, flowing data
# plane, restartless recovery (the ISSUE's pinned acceptance tests)
# ---------------------------------------------------------------------------

def test_enospc_degrades_control_plane_not_data_plane(tmp_path,
                                                      monkeypatch):
    router = Router(registry=ReplicaRegistry(heartbeat_timeout_s=60.0))
    router.attach_journal(FleetJournal(str(tmp_path / "j"),
                                       sync_every=1))
    router.announce("http://127.0.0.1:0")
    _register(router.registry, "p", load={"load_s": 0.0, "unit_s": 0.01})
    _register(router.registry, "g", mode="generate",
              spec={"vocab": 61, "max_prompt_len": 8, "max_context": 32})
    router.set_split("m", {"0": 1.0})               # acked pre-fault

    def fake_call(url, payload, timeout_s):
        if "prompt" in payload:
            base = len(payload["prompt"])
            n = payload["max_new_tokens"]
            return 200, {"tokens": list(range(base, base + n)),
                         "finish_reason": "length", "ttft_ms": 1.0}, {}
        return 200, {"outputs": [[1.0]]}, {}

    monkeypatch.setattr(router, "_call", fake_call)

    monkeypatch.setenv("MXNET_FAULT_INJECT", "enospc@journal=append")
    faultinject.reset()
    # control-plane mutation: refused, NOT acked, NOT applied
    with pytest.raises(JournalDegraded) as ei:
        router.set_split("m", {"0": 0.5, "1": 0.5})
    assert ei.value.retry_after_s > 0
    assert router.journal_degraded is True
    assert router.splits["m"] == {"0": 1.0}         # journal-first: no
    snap = router.fleet_snapshot()                  # half-applied split
    assert snap["journal_degraded"] is True
    assert "ENOSPC" in snap["journal_degraded_reason"]
    assert _gauge_value(telemetry.prometheus_text(),
                        "mxtpu_fleet_journal_degraded") == 1.0

    # data plane keeps flowing: predict AND generate (whose session
    # cursors journal best-effort) both succeed while degraded
    code, out, _ = router.route_predict({"inputs": {"data": [[0.0]]}})
    assert code == 200 and out["replica"] == "p"
    code, out, _ = router.route_generate({"prompt": [5, 9, 13],
                                          "max_new_tokens": 4})
    assert code == 200 and len(out["tokens"]) == 4
    # registry liveness unaffected
    router.registry.heartbeat("p", ready=True)
    assert router.journal_degraded is True          # still degraded

    # disk recovers: the next control attempt probes, compacts the
    # missed mutations into a snapshot, and exits degraded mode with
    # NO restart
    monkeypatch.delenv("MXNET_FAULT_INJECT")
    faultinject.reset()
    out = router.set_split("m", {"0": 0.25, "1": 0.75})
    assert out == {"0": 0.25, "1": 0.75}
    assert router.journal_degraded is False
    assert router.degraded_reason is None
    assert _gauge_value(telemetry.prometheus_text(),
                        "mxtpu_fleet_journal_degraded") == 0.0
    # everything the journal missed while unwritable was recaptured:
    # replay sees the recovery-compaction snapshot + the new split
    router.journal.sync()
    st, _ = replay(router.journal.dir)
    assert st.splits["m"] == {"0": 0.25, "1": 0.75}
    assert set(st.replicas) == {"p", "g"}
    router.journal.close()


def test_enospc_is_503_with_retry_after_over_http(tmp_path, monkeypatch):
    router, front = _primary(tmp_path)
    url = front.address
    try:
        code, _, _ = _post(url + "/fleet/register",
                           {"id": "a", "url": "http://a.invalid",
                            "model": "m", "version": "0",
                            "mode": "predict", "ready": True})
        assert code == 200
        code, out, _ = _post(url + "/admin/split",
                             {"model": "m", "weights": {"0": 1.0}})
        assert code == 200

        monkeypatch.setenv("MXNET_FAULT_INJECT", "enospc@journal=append")
        faultinject.reset()
        code, out, headers = _post(url + "/admin/split",
                                   {"model": "m", "weights": {"0": 2.0}})
        assert code == 503
        assert "journal" in out["error"]
        assert int(headers["Retry-After"]) >= 1
        code, _, headers = _post(url + "/admin/drain", {"id": "a"})
        assert code == 503 and "Retry-After" in headers
        # reads and the data-plane/registry legs still answer
        code, snap = _get_json(url + "/fleet")
        assert code == 200 and snap["journal_degraded"] is True
        code, out, _ = _post(url + "/fleet/heartbeat",
                             {"id": "a", "ready": True})
        assert code == 200 and out["known"] is True

        monkeypatch.delenv("MXNET_FAULT_INJECT")
        faultinject.reset()
        code, out, _ = _post(url + "/admin/split",
                             {"model": "m", "weights": {"0": 2.0}})
        assert code == 200                  # recovered, no restart
        code, snap = _get_json(url + "/fleet")
        assert snap["journal_degraded"] is False
    finally:
        front.stop()


def test_torn_write_is_repaired_before_the_next_append(tmp_path,
                                                       monkeypatch):
    j = FleetJournal(str(tmp_path / "j"), sync_every=1)
    j.append("noop", {"i": 1})
    j.append("noop", {"i": 2})
    size_before = os.path.getsize(j._seg_path)

    monkeypatch.setenv("MXNET_FAULT_INJECT", "torn_write@journal=append")
    faultinject.reset()
    with pytest.raises(OSError):
        j.append("noop", {"i": 3})
    # power-loss semantics: a frame prefix reached the disk
    assert os.path.getsize(j._seg_path) > size_before
    st, stats = replay(str(tmp_path / "j"))
    assert st.applied_seq == 2 and stats["torn_segments"] == 1

    monkeypatch.delenv("MXNET_FAULT_INJECT")
    faultinject.reset()
    # the writer truncates its dirty tail before appending through it,
    # and the failed append never burned a seq (no replication gap)
    seq = j.append("noop", {"i": 3})
    assert seq == 3
    st, stats = replay(str(tmp_path / "j"))
    assert st.applied_seq == 3 and stats["torn_segments"] == 0
    j.close()


def test_slow_fsync_injects_group_commit_latency(tmp_path, monkeypatch):
    j = FleetJournal(str(tmp_path / "j"), sync_every=1)
    t0 = time.monotonic()
    j.append("noop", {"i": 1})
    fast = time.monotonic() - t0
    monkeypatch.setenv("MXNET_FAULT_INJECT",
                       "slow_fsync@journal=fsync:secs=0.15")
    faultinject.reset()
    t0 = time.monotonic()
    j.append("noop", {"i": 2})
    slow = time.monotonic() - t0
    assert slow >= 0.14 > fast
    j.close()
