"""Metric + IO tests (parity model: tests/python/unittest/test_metric.py +
test_io.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import metric


def test_accuracy():
    m = metric.Accuracy()
    pred = mx.nd.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    label = mx.nd.array([1.0, 0.0, 0.0])
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(2.0 / 3.0)


def test_topk():
    m = metric.TopKAccuracy(top_k=2)
    pred = mx.nd.array([[0.1, 0.5, 0.4], [0.6, 0.3, 0.1]])
    label = mx.nd.array([2.0, 2.0])
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(0.5)


def test_mse_mae_rmse():
    pred = mx.nd.array([1.0, 2.0, 3.0])
    label = mx.nd.array([1.5, 2.0, 2.5])
    for name, expect in [("mse", ((0.25 + 0 + 0.25) / 3)),
                         ("mae", (0.5 + 0 + 0.5) / 3),
                         ("rmse", np.sqrt((0.25 + 0 + 0.25) / 3))]:
        m = metric.create(name)
        m.update([label], [pred])
        assert m.get()[1] == pytest.approx(expect, rel=1e-5)


def test_perplexity_and_ce():
    pred = mx.nd.array([[0.25, 0.75], [0.9, 0.1]])
    label = mx.nd.array([1.0, 0.0])
    ce = metric.create("ce")
    ce.update([label], [pred])
    expect = -(np.log(0.75) + np.log(0.9)) / 2
    assert ce.get()[1] == pytest.approx(expect, rel=1e-5)
    pp = metric.Perplexity(ignore_label=None)
    pp.update([label], [pred])
    assert pp.get()[1] == pytest.approx(np.exp(expect), rel=1e-5)


def test_composite_and_custom():
    comp = metric.create(["acc", "mse"])
    names, values = comp.get()
    assert len(names) == 2
    cm = metric.np(lambda l, p: float((l == p.argmax(1)).mean()))
    pred = mx.nd.array([[0.1, 0.9]])
    cm.update([mx.nd.array([1.0])], [pred])
    assert cm.get()[1] == 1.0


def test_update_dict_preds_keep_asnumpy():
    """User metric subclasses written against the reference call
    .asnumpy() on what update() receives (examples/train_ssd.py,
    examples/train_rcnn.py do); the batched one-sync fetch in
    update_dict must hand them asnumpy()-compatible arrays."""
    seen = {}

    class UserMetric(metric.EvalMetric):
        def update(self, labels, preds):
            seen["pred"] = preds[0].asnumpy()
            seen["label"] = labels[0].asnumpy()
            self.sum_metric += float(seen["pred"].sum())
            self.num_inst += 1

    m = UserMetric("user")
    m.update_dict({"softmax_label": mx.nd.array([1.0, 0.0])},
                  {"softmax_output": mx.nd.array([[0.1, 0.9], [0.8, 0.2]])})
    assert seen["pred"].shape == (2, 2)
    np.testing.assert_allclose(seen["label"], [1.0, 0.0])
    assert m.get()[1] == pytest.approx(2.0)


def test_f1():
    m = metric.F1()
    pred = mx.nd.array([[0.3, 0.7], [0.8, 0.2], [0.4, 0.6]])
    label = mx.nd.array([1.0, 0.0, 1.0])
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(1.0)


def test_ndarray_iter():
    X = np.arange(40).reshape(10, 4).astype(np.float32)
    y = np.arange(10).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=4, shuffle=False,
                           last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 4)
    assert batches[2].pad == 2
    it.reset()
    b0 = next(it)
    np.testing.assert_allclose(b0.data[0].asnumpy(), X[:4])
    # discard mode
    it2 = mx.io.NDArrayIter(X, y, batch_size=4, last_batch_handle="discard")
    assert len(list(it2)) == 2


def test_ndarray_iter_dict_and_provide():
    X = {"a": np.zeros((8, 2), np.float32), "b": np.ones((8, 3), np.float32)}
    it = mx.io.NDArrayIter(X, None, batch_size=4)
    descs = it.provide_data
    assert {d.name for d in descs} == {"a", "b"}
    batch = next(it)
    assert len(batch.data) == 2


def test_resize_iter():
    X = np.zeros((8, 2), np.float32)
    it = mx.io.ResizeIter(mx.io.NDArrayIter(X, batch_size=4), size=5)
    assert len(list(it)) == 5


def test_prefetching_iter():
    X = np.arange(32).reshape(8, 4).astype(np.float32)
    base = mx.io.NDArrayIter(X, batch_size=4)
    it = mx.io.PrefetchingIter(base)
    batches = [b for b in iter(it.next, None) if b]  # drain via next()
    # simpler: pull twice then StopIteration
    it.reset()
    n = 0
    while True:
        try:
            it.next()
            n += 1
        except StopIteration:
            break
    assert n == 2


def test_speedometer_runs():
    from mxnet_tpu.callback import Speedometer
    from mxnet_tpu.model import BatchEndParam
    s = Speedometer(batch_size=4, frequent=1)
    m = metric.Accuracy()
    for i in range(3):
        s(BatchEndParam(epoch=0, nbatch=i, eval_metric=m, locals=None))
