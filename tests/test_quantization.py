"""int8 quantization graph pass (contrib/quantization.py — reference
quantize_graph_pass.cc + calibration from quantization.py): quantize
islands around FC/conv, int8-domain fusion through pooling/flatten/
concat, naive and entropy calibration, numeric closeness to the float
model."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.contrib import quantization as Q


def _convnet():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, name="c1")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max", name="p1")
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=8, name="c2")
    net = mx.sym.Flatten(net, name="fl")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _setup(seed=0, n=64):
    rng = np.random.RandomState(seed)
    sym = _convnet()
    shapes, _, _ = sym.infer_shape(data=(2, 3, 16, 16))
    args = {nm: mx.nd.array(rng.uniform(-0.2, 0.2, s).astype("f4"))
            for nm, s in zip(sym.list_arguments(), shapes)
            if nm not in ("data", "softmax_label")}
    X = rng.rand(n, 3, 16, 16).astype("f4")
    return sym, args, X


def _forward(sym, args, X):
    ex = sym.bind(mx.cpu(), {**args, "data": mx.nd.array(X),
                             "softmax_label": mx.nd.zeros((len(X),))})
    ex.forward()
    return ex.outputs[0].asnumpy()


@pytest.mark.parametrize("calib_mode", ["naive", "entropy"])
def test_quantize_model_close_to_float(calib_mode):
    sym, args, X = _setup()
    it = mx.io.NDArrayIter(X, np.zeros(len(X), "f4"), batch_size=16,
                           label_name="softmax_label")
    qsym, qargs, qaux = Q.quantize_model(
        sym, args, {}, calib_data=it, calib_mode=calib_mode,
        num_calib_examples=32)
    ref = _forward(sym, args, X[:4])
    out = _forward(qsym, qargs, X[:4])
    assert np.abs(out - ref).max() < 0.1


def test_pooling_flatten_stay_int8():
    """The whole conv->pool->conv->flatten->fc chain runs in the int8
    domain: no dequantize between quantized islands (reference
    quantize_graph_pass keeps pooling/flatten/concat quantized)."""
    sym, args, X = _setup()
    it = mx.io.NDArrayIter(X, np.zeros(len(X), "f4"), batch_size=16,
                           label_name="softmax_label")
    qsym, _, _ = Q.quantize_model(sym, args, {}, calib_data=it,
                                  calib_mode="naive",
                                  num_calib_examples=32)
    ops = [n.op.name for n in qsym._topo() if not n.is_variable]
    assert "_contrib_quantized_pooling" in ops
    assert "_contrib_quantized_flatten" in ops
    # exactly ONE dequantize: at the island's exit before softmax
    assert ops.count("_contrib_dequantize") == 1


def test_concat_stays_int8():
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(1, 1), num_filter=4, name="c1")
    c2 = mx.sym.Convolution(data, kernel=(1, 1), num_filter=4, name="c2")
    net = mx.sym.Concat(c1, c2, dim=1, name="cat")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc")
    sym = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(1)
    shapes, _, _ = sym.infer_shape(data=(2, 3, 8, 8))
    args = {nm: mx.nd.array(rng.uniform(-0.2, 0.2, s).astype("f4"))
            for nm, s in zip(sym.list_arguments(), shapes)
            if nm not in ("data", "softmax_label")}
    X = rng.rand(32, 3, 8, 8).astype("f4")
    it = mx.io.NDArrayIter(X, np.zeros(32, "f4"), batch_size=16,
                           label_name="softmax_label")
    qsym, qargs, _ = Q.quantize_model(sym, args, {}, calib_data=it,
                                      calib_mode="naive",
                                      num_calib_examples=32)
    ops = [n.op.name for n in qsym._topo() if not n.is_variable]
    assert "_contrib_quantized_concat" in ops
    ref = _forward(sym, args, X[:4])
    out = _forward(qsym, qargs, X[:4])
    assert np.abs(out - ref).max() < 0.1


def test_excluded_layer_stays_float():
    sym, args, X = _setup()
    it = mx.io.NDArrayIter(X, np.zeros(len(X), "f4"), batch_size=16,
                           label_name="softmax_label")
    qsym, _, _ = Q.quantize_model(sym, args, {}, calib_data=it,
                                  calib_mode="naive",
                                  excluded_sym_names=["fc"],
                                  num_calib_examples=16)
    names = [n.name for n in qsym._topo() if not n.is_variable]
    assert "fc" in names and "fc_quantized" not in names
