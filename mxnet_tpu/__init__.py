"""mxnet_tpu — a TPU-native deep learning framework.

API-parity target: Apache MXNet 1.4.x (the reference at /root/reference);
architecture: JAX/XLA/Pallas-first (see ARCHITECTURE.md). Import as::

    import mxnet_tpu as mx
    x = mx.nd.ones((2, 3), ctx=mx.tpu())
"""
from __future__ import annotations

from . import config
# float32/int32 by default (mshadow default_real_t); float64/int64 are
# opt-in via MXNET_ENABLE_X64=1 because x64 doubles every index array and
# pushes XLA onto f64 paths the MXU doesn't have.
if config.flags.enable_x64:
    import jax as _jax
    _jax.config.update("jax_enable_x64", True)

import os as _os

# Re-assert a user-pinned CPU platform into jax config. A site-installed
# PJRT plugin (e.g. a TPU-proxy sitecustomize) may call
# jax.config.update("jax_platforms", ...) during registration, silently
# overriding the env var — and a forced remote platform HANGS every
# jax.devices() call when its link is down, hermetic CPU runs included.
# Only cpu-leading values are re-asserted: for accelerator values the
# plugin's own selection (typically "<plat>,cpu") is already right.
# Pure config, no backend init, so import hygiene holds. Runs BEFORE the
# cache block below, which keys off the resolved platform.
if _os.environ.get("JAX_PLATFORMS", "").split(",")[0] == "cpu":
    import jax as _jax_plat
    _jax_plat.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

# Persistent XLA compilation cache (the operator_tune replacement — see
# the flag's docstring). Pure config: no device/backend work happens here,
# so import hygiene is preserved. CPU-pinned processes skip the default
# cache: XLA:CPU persists AOT machine code whose feature stamps
# (+prefer-no-scatter etc.) fail host verification on reload and can
# SIGILL/segfault — and CPU compiles are cheap anyway; the cache's job is
# the TPU's multi-minute fused-step compiles. An explicit
# MXNET_COMPILE_CACHE_DIR is always honored.
if config.flags.compile_cache_dir:
    import jax as _jax_cc
    # default-on only when an accelerator platform is explicitly selected
    # (unset/auto and cpu-pinned processes both resolve to XLA:CPU)
    _lead = (_jax_cc.config.jax_platforms or "").split(",")[0]
    _accel = _lead not in ("", "cpu")
    if _os.environ.get("MXNET_COMPILE_CACHE_DIR") or _accel:
        _jax_cc.config.update("jax_compilation_cache_dir",
                              config.flags.compile_cache_dir)
        _jax_cc.config.update("jax_persistent_cache_min_compile_time_secs",
                              config.flags.compile_cache_min_compile_secs)

# Under a launcher (tools/launch.py sets MXNET_COORDINATOR_ADDRESS /
# DMLC_PS_ROOT_URI), join the process group NOW — jax.distributed must
# initialize before any JAX call touches a backend, and user scripts touch
# arrays long before they create a kvstore. No-op outside a launcher.
if _os.environ.get("MXNET_COORDINATOR_ADDRESS") \
        or _os.environ.get("DMLC_PS_ROOT_URI"):
    from .parallel import dist as _dist
    _dist.init(strict=False)

# ps-lite launcher compatibility: server/scheduler-role processes run the
# (no-op) server module and exit at import, exactly like the reference
# (python/mxnet/kvstore_server.py:85) — they must not fall through and
# execute the training script as stray singleton workers
import os as _os_role
if _os_role.environ.get("DMLC_ROLE", "") in ("server", "scheduler"):
    from . import kvstore_server as _kvs
    _kvs._init_kvstore_server_module()

from .base import MXNetError
from .attribute import AttrScope
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from . import engine
from . import random
from . import autograd
from . import ndarray
from . import ndarray as nd

from .ndarray import NDArray

__version__ = "0.1.0"


def waitall():
    engine.waitall()


# submodules loaded lazily to keep import light and avoid cycles
def __getattr__(name):
    import importlib
    lazy = {
        "sym": ".symbol", "symbol": ".symbol",
        "gluon": ".gluon",
        "mod": ".module", "module": ".module",
        "optimizer": ".optimizer",
        "metric": ".metric",
        "initializer": ".initializer",
        "init": ".initializer",
        "lr_scheduler": ".lr_scheduler",
        "callback": ".callback",
        "io": ".io",
        "recordio": ".recordio",
        "image": ".image",
        "kvstore": ".kvstore",
        "kv": ".kvstore",
        "monitor": ".monitor",
        "operator": ".operator",
        "name": ".name",
        "attribute": ".attribute",
        "util": ".util",
        "log": ".log",
        "libinfo": ".libinfo",
        "rtc": ".rtc",
        "registry": ".registry",
        "kvstore_server": ".kvstore_server",
        "executor_manager": ".executor_manager",
        "rnn": ".rnn",
        "model": ".model",
        "checkpoint": ".checkpoint",
        "subgraph": ".subgraph",
        "parallel": ".parallel",
        "profiler": ".profiler",
        "test_utils": ".test_utils",
        "executor": ".executor",
        "visualization": ".visualization",
        "viz": ".visualization",
        "serving": ".serving",
        "serve": ".serve",
        "contrib": ".contrib",
    }
    if name in lazy:
        m = importlib.import_module(lazy[name], __name__)
        globals()[name] = m
        return m
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
