"""Network visualization (parity: python/mxnet/visualization.py —
print_summary over a Symbol, plot_network via graphviz when available)."""
from __future__ import annotations

import json

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a per-node table of a Symbol graph with params + output shapes
    (reference visualization.py print_summary)."""
    if positions is None:
        positions = [0.44, 0.64, 0.74, 1.0]
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    heads = {h[0] for h in conf.get("heads", [])}
    shape_dict = {}
    if shape is not None:
        internals = symbol.get_internals()
        arg_shapes, out_shapes, aux_shapes = \
            internals.infer_shape_partial(**shape)
        for name, s in zip(symbol.list_arguments(), arg_shapes):
            shape_dict[name] = s
        for name, s in zip(internals.list_outputs(), out_shapes):
            shape_dict[name] = s
    positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #",
                  "Previous Layer"]

    lines = ["_" * line_length, _row(to_display, positions),
             "=" * line_length]
    total_params = 0

    input_names = set(shape or {})

    def param_count(node):
        name = node["name"]
        if node["op"] != "null" or name in input_names \
                or name.endswith("_label"):
            return 0  # data/label inputs are not parameters
        s = shape_dict.get(name)
        if s is None:
            return 0
        n = 1
        for d in s:
            n *= d
        return n

    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null":
            continue  # params are accounted to their consumer
        n_params = 0
        prevs = []
        for in_idx in node.get("inputs", []):
            prev = nodes[in_idx[0]]
            if prev["op"] == "null":
                n_params += param_count(prev)
                continue
            prevs.append(prev["name"])
        total_params += n_params
        out_shape = shape_dict.get(name + "_output",
                                   shape_dict.get(name, ""))
        lines.append(_row(["%s (%s)" % (name, op), str(out_shape),
                           str(n_params), ",".join(prevs)], positions))
    lines.append("=" * line_length)
    lines.append("Total params: %d" % total_params)
    lines.append("_" * line_length)
    text = "\n".join(lines)
    print(text)
    return text


def _row(fields, positions):
    line = ""
    for field, pos in zip(fields, positions):
        line += str(field)
        line = line[:pos - 1]
        line += " " * (pos - len(line))
    return line


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Return a graphviz Digraph of the symbol graph. Falls back to a text
    edge list object when graphviz is unavailable (this image has no
    graphviz python package by default)."""
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    edges = []
    for i, node in enumerate(nodes):
        for in_idx in node.get("inputs", []):
            src = nodes[in_idx[0]]
            if hide_weights and src["op"] == "null" and \
                    src["name"] != "data":
                continue
            edges.append((src["name"], node["name"]))
    try:
        from graphviz import Digraph
    except ImportError:
        class _TextGraph:
            def __init__(self, edges, nodes):
                self.edges = edges
                self.nodes = [n["name"] for n in nodes]

            def render(self, *a, **k):
                raise RuntimeError("graphviz not installed")

            def __repr__(self):
                return "digraph {\n" + "\n".join(
                    '  "%s" -> "%s";' % e for e in self.edges) + "\n}"
        return _TextGraph(edges, nodes)
    dot = Digraph(name=title)
    seen = set()
    for node in nodes:
        if hide_weights and node["op"] == "null" and \
                node["name"] != "data":
            continue
        label = node["name"] if node["op"] == "null" else \
            "%s\n%s" % (node["op"], node["name"])
        dot.node(node["name"], label=label)
        seen.add(node["name"])
    for src, dst in edges:
        if src in seen and dst in seen:
            dot.edge(src, dst)
    return dot
